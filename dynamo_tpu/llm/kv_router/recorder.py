"""KV-event recorder/replayer: capture router event streams to JSONL and
replay them for offline analysis or index reconstruction.

Capability parity: reference `lib/llm/src/kv_router/recorder.rs` +
`recorder.rs:667` (JSONL record/replay) — powers router debugging and the
route-quality analysis workflow without a live cluster.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Callable, Iterator

from dynamo_tpu.llm.kv_router.protocols import KvCacheEvent, RouterEvent


def _event_to_json(event: RouterEvent) -> dict:
    return {
        "w": event.worker_id,
        "i": event.event_id,
        "op": event.event.op,
        "h": list(event.event.block_hashes),
        "p": event.event.parent_hash,
    }


def _event_from_json(d: dict) -> RouterEvent:
    return RouterEvent(
        worker_id=d["w"],
        event_id=d["i"],
        event=KvCacheEvent(op=d["op"], block_hashes=tuple(d["h"]), parent_hash=d["p"]),
    )


class KvEventRecorder:
    def __init__(self, path: str | Path):
        self.path = Path(path)
        self._fh = None
        self.recorded = 0

    def __enter__(self) -> "KvEventRecorder":
        self._fh = open(self.path, "a")
        return self

    def __exit__(self, *exc) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None

    def record(self, event: RouterEvent, ts: float | None = None) -> None:
        assert self._fh is not None, "use as a context manager"
        line = {"ts": ts if ts is not None else time.time(), "event": _event_to_json(event)}
        self._fh.write(json.dumps(line) + "\n")
        self.recorded += 1

    def attach(self, indexer) -> Callable[[RouterEvent], None]:
        """Tap: returns a callback that records then forwards to the
        indexer's tree."""

        def tap(event: RouterEvent) -> None:
            self.record(event)
            indexer.tree.apply_event(event)

        return tap


def replay_events(path: str | Path) -> Iterator[tuple[float, RouterEvent]]:
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            yield obj["ts"], _event_from_json(obj["event"])


def replay_into(path: str | Path, tree) -> int:
    """Rebuild an index from a recording; returns events applied."""
    n = 0
    for _, event in replay_events(path):
        tree.apply_event(event)
        n += 1
    return n
