"""Router replica synchronization: N frontends, one coherent view.

Two routers serving the same component would otherwise route blind to
each other's in-flight load (the radix trees converge automatically —
worker KV events broadcast to every subscriber — but ActiveSequences is
router-local state). Parity: reference ActiveSequencesMultiWorker +
replica-sync subjects (`lib/llm/src/kv_router/sequence.rs:225`,
`kv_router.rs:58-65`) and the late-joiner radix bootstrap
(`indexer.rs:445` dump_tree_as_events).

Mechanics, all over the store's pub/sub plane (msgpack payloads):

- **Active-sequence deltas**: every routing decision / prefill-done /
  free publishes a delta tagged with the origin router id; replicas apply
  deltas whose origin is not their own.
- **Bootstrap**: a starting router publishes a state request with a
  unique reply subject; any established replica answers with its radix
  dump (per-worker stored events) plus an active-sequence snapshot.
  Radix events are idempotent, so multiple replies are safe.
"""

from __future__ import annotations

import asyncio
import logging
import uuid
from typing import TYPE_CHECKING

import msgpack

from dynamo_tpu.runtime.tasks import spawn_logged

if TYPE_CHECKING:  # pragma: no cover
    from dynamo_tpu.llm.kv_router.router import KvRouter

log = logging.getLogger("dynamo_tpu.kv_router.sync")


def sync_subject(namespace: str, component: str) -> str:
    return f"kv_router_sync:{namespace}:{component}"


def bootstrap_subject(namespace: str, component: str) -> str:
    return f"kv_router_bootstrap:{namespace}:{component}"


class ReplicaSync:
    def __init__(self, store, namespace: str, component: str, router: "KvRouter"):
        self.store = store
        self.router = router
        self.router_id = uuid.uuid4().hex
        self._delta_subject = sync_subject(namespace, component)
        self._boot_subject = bootstrap_subject(namespace, component)
        self._tasks: list[asyncio.Task] = []
        self._subs: list = []

    # -- lifecycle ---------------------------------------------------------

    async def start(self, bootstrap_timeout: float = 0.5) -> None:
        delta_sub = await self.store.subscribe(self._delta_subject)
        boot_sub = await self.store.subscribe(self._boot_subject)
        self._subs = [delta_sub, boot_sub]
        # spawn_logged: if a loop dies on an unexpected message shape the
        # failure is logged immediately, not discovered via a frozen
        # replica view (the handles still go in _tasks for stop()).
        self._tasks = [
            spawn_logged(self._delta_loop(delta_sub),
                         name="replica-sync-delta-loop", logger=log),
            spawn_logged(self._bootstrap_serve_loop(boot_sub),
                         name="replica-sync-bootstrap-loop", logger=log),
        ]
        await self._bootstrap(bootstrap_timeout)

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for s in self._subs:
            try:
                await s.unsubscribe()
            except Exception:  # noqa: BLE001 — store may already be gone
                log.debug("unsubscribe failed during stop", exc_info=True)

    # -- delta publication (called by KvRouter on every decision) ----------

    def publish_add(
        self, request_id: str, worker_id: int, prompt_tokens: int, overlap_blocks: int
    ) -> None:
        self._publish(
            {
                "op": "add",
                "rid": request_id,
                "w": worker_id,
                "n": prompt_tokens,
                "o": overlap_blocks,
            }
        )

    def publish_prefill_done(self, request_id: str) -> None:
        self._publish({"op": "prefill_done", "rid": request_id})

    def publish_free(self, request_id: str) -> None:
        self._publish({"op": "free", "rid": request_id})

    def _publish(self, delta: dict) -> None:
        delta["origin"] = self.router_id
        payload = msgpack.packb(delta, use_bin_type=True)

        async def _send() -> None:
            try:
                await self.store.publish(self._delta_subject, payload)
            except Exception:  # noqa: BLE001 — sync is best-effort
                log.warning("replica-sync publish failed", exc_info=True)

        spawn_logged(_send(), name="replica-sync-publish", logger=log)

    # -- delta application -------------------------------------------------

    async def _delta_loop(self, sub) -> None:
        async for msg in sub:
            try:
                d = msgpack.unpackb(msg["p"], raw=False)
            except (TypeError, ValueError, msgpack.UnpackException):
                log.warning("dropping malformed replica-sync delta")
                continue
            if not isinstance(d, dict):
                log.warning("dropping non-dict replica-sync delta %r", d)
                continue
            if d.get("origin") == self.router_id:
                continue
            try:
                self._apply(d)
            except Exception:  # noqa: BLE001 — one bad delta must not kill sync
                log.warning("dropping unapplicable replica-sync delta %r",
                            d, exc_info=True)

    def _apply(self, d: dict) -> None:
        active = self.router.active
        op = d.get("op")
        if op == "add":
            active.add_request(d["rid"], d["w"], d["n"], d["o"])
        elif op == "prefill_done":
            active.mark_prefill_done(d["rid"])
        elif op == "free":
            active.free(d["rid"])

    # -- bootstrap ---------------------------------------------------------

    def _snapshot(self) -> bytes:
        """Radix dump + active sequences, for a late-joining replica."""
        tree = self.router.indexer_tree()
        radix = []
        if tree is not None:
            for w in self.router.known_workers():
                for ev in tree.dump_as_events(w):
                    radix.append(ev.to_wire())
        active = [
            {
                "rid": rid,
                "w": seq.worker_id,
                "pf": seq.prefill_tokens,
                "db": seq.decode_blocks,
            }
            for rid, seq in self.router.active.items()
        ]
        return msgpack.packb({"radix": radix, "active": active}, use_bin_type=True)

    async def _bootstrap_serve_loop(self, sub) -> None:
        async for msg in sub:
            try:
                req = msgpack.unpackb(msg["p"], raw=False)
            except (TypeError, ValueError, msgpack.UnpackException):
                log.warning("dropping malformed bootstrap request")
                continue
            if not isinstance(req, dict):
                log.warning("dropping non-dict bootstrap request %r", req)
                continue
            if req.get("origin") == self.router_id:
                continue
            try:
                await self.store.publish(req["reply"], self._snapshot())
            except Exception:  # noqa: BLE001
                log.warning("bootstrap reply failed", exc_info=True)

    async def _bootstrap(self, timeout: float) -> None:
        from dynamo_tpu.llm.kv_router.protocols import RouterEvent

        reply = f"kv_router_bootstrap_rep:{self.router_id}"
        rep_sub = await self.store.subscribe(reply)
        try:
            await self.store.publish(
                self._boot_subject,
                msgpack.packb(
                    {"origin": self.router_id, "reply": reply}, use_bin_type=True
                ),
            )
            try:
                msg = await rep_sub.get(timeout=timeout)
            except (asyncio.TimeoutError, TimeoutError):
                return  # first replica up: nothing to inherit
            snap = msgpack.unpackb(msg["p"], raw=False)
            for raw in snap.get("radix", []):
                self.router.apply_radix_event(RouterEvent.from_wire(raw))
            for e in snap.get("active", []):
                self.router.active.add_raw(e["rid"], e["w"], e["pf"], e["db"])
            log.info(
                "replica bootstrap: %d radix events, %d active sequences",
                len(snap.get("radix", [])),
                len(snap.get("active", [])),
            )
        finally:
            await rep_sub.unsubscribe()
