"""The KV router: prefix-overlap-aware request dispatch.

``KvRouter`` owns the index (event-driven or approximate), the active
sequence bookkeeping, and the selector. ``KvPushRouter`` binds it to an
endpoint client: every request is hashed into blocks, scored, dispatched
``direct`` to the chosen worker, and its bookkeeping freed when the stream
ends — including the worker-death path, which also drops the dead worker
from the index.

Capability parity: reference `lib/llm/src/kv_router.rs:158` (KvRouter),
`:342` (KvPushRouter); per-request overrides `:79`.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, AsyncIterator

from dynamo_tpu import tracing
from dynamo_tpu.llm.kv_router.indexer import ApproxKvIndexer, KvIndexer
from dynamo_tpu.llm.kv_router.protocols import (
    RouterConfig,
    kv_events_subject,
    kv_resync_subject,
)
from dynamo_tpu.llm.kv_router.scheduler import DefaultWorkerSelector, SelectionResult
from dynamo_tpu.llm.kv_router.sequence import ActiveSequences
from dynamo_tpu.runtime.component import EndpointClient, NoInstancesError
from dynamo_tpu.tokens import compute_seq_hashes

log = logging.getLogger("dynamo_tpu.kv_router")


def best_peer_hint(overlaps: dict[int, int]) -> tuple[int, int]:
    """The peer worth pulling a cached prefix from: most overlap blocks,
    ties broken DETERMINISTICALLY by lowest worker_id. A bare
    ``max(..., key=value)`` breaks ties by dict insertion order, which
    varies with KV-event arrival — routing traces and chaos replays must
    reproduce, so the tie-break is pinned (test_kv_router)."""
    return max(overlaps.items(), key=lambda kv: (kv[1], -kv[0]))


class KvRouter:
    def __init__(
        self,
        store,
        namespace: str,
        component: str,
        config: RouterConfig | None = None,
    ):
        config = config or RouterConfig()
        if config.block_size is None:
            # Default on a copy — never mutate the caller's config object.
            config = dataclasses.replace(config, block_size=32)
        self.config = config
        self.active = ActiveSequences(block_size=self.config.block_size)
        # Network-aware scoring (NetKV, ISSUE 14): measured transfer cost
        # + queue depth extend the overlap cost. The netcost model's
        # fleet view is wired by KvPushRouter from its WorkerMonitor.
        self.netcost = None
        if self.config.network_aware:
            from dynamo_tpu.llm.kv_router.netcost import (
                NetCostModel,
                NetworkAwareSelector,
            )

            self.netcost = NetCostModel(
                recompute_ms_per_block=self.config.recompute_ms_per_block
            )
            self.selector: DefaultWorkerSelector = NetworkAwareSelector(
                self.netcost
            )
        else:
            self.selector = DefaultWorkerSelector()
        if self.config.use_kv_events:
            self.indexer: KvIndexer | ApproxKvIndexer = KvIndexer(
                store,
                kv_events_subject(namespace, component),
                resync_subject=kv_resync_subject(namespace, component),
            )
        else:
            self.indexer = ApproxKvIndexer()
        self.sync = None
        if self.config.replica_sync:
            from dynamo_tpu.llm.kv_router.replica_sync import ReplicaSync

            self.sync = ReplicaSync(store, namespace, component, self)

    async def start(self) -> None:
        if isinstance(self.indexer, KvIndexer):
            await self.indexer.start()
        if self.sync is not None:
            await self.sync.start()

    async def stop(self) -> None:
        if self.sync is not None:
            await self.sync.stop()
        if isinstance(self.indexer, KvIndexer):
            await self.indexer.stop()

    # -- replica-sync introspection ---------------------------------------

    def indexer_tree(self):
        return self.indexer.tree if isinstance(self.indexer, KvIndexer) else None

    def known_workers(self) -> set[int]:
        return (
            set(self.indexer.known_workers)
            if isinstance(self.indexer, KvIndexer)
            else set()
        )

    def apply_radix_event(self, event) -> None:
        """Feed a bootstrap radix event through the indexer's one apply
        path (KvIndexer.apply); a no-op for the approx indexer."""
        if isinstance(self.indexer, KvIndexer):
            self.indexer.apply(event)

    def find_best_match(
        self,
        request_id: str,
        token_ids: list[int],
        workers: list[int],
        config_override: RouterConfig | None = None,
    ) -> SelectionResult:
        config = config_override or self.config
        seq_hashes = compute_seq_hashes(token_ids, self.config.block_size)
        overlaps = self.indexer.find_matches(seq_hashes)
        result = self.selector.select_worker(
            workers, overlaps, len(token_ids), self.active, config
        )
        result.overlaps = dict(overlaps)
        self.active.add_request(
            request_id, result.worker_id, len(token_ids), result.overlap_blocks
        )
        if self.sync is not None:
            self.sync.publish_add(
                request_id, result.worker_id, len(token_ids), result.overlap_blocks
            )
        if isinstance(self.indexer, ApproxKvIndexer):
            self.indexer.process_routing_decision(result.worker_id, seq_hashes)
        return result

    def note_pinned(self, request_id: str, worker_id: int, prompt_tokens: int) -> None:
        """Bookkeeping for a caller-pinned worker (no selection ran)."""
        self.active.add_request(request_id, worker_id, prompt_tokens, 0)
        if self.sync is not None:
            self.sync.publish_add(request_id, worker_id, prompt_tokens, 0)

    def mark_prefill_done(self, request_id: str) -> None:
        self.active.mark_prefill_done(request_id)
        if self.sync is not None:
            self.sync.publish_prefill_done(request_id)

    def free(self, request_id: str) -> None:
        self.active.free(request_id)
        if self.sync is not None:
            self.sync.publish_free(request_id)

    def remove_worker(self, worker_id: int) -> list[str]:
        self.indexer.remove_worker(worker_id)
        return self.active.remove_worker(worker_id)

    def peer_hint(self, selection: SelectionResult) -> tuple[int, int] | None:
        """The peer-prefix pull hint for a selection: network-aware mode
        uses the selector's cost-decided source (None when no pull beats
        recomputing — a slow peer is left alone even if it overlaps
        best); overlap-only mode keeps the historical most-blocks hint."""
        if self.config.network_aware:
            return selection.pull_hint
        if not selection.overlaps:
            return None
        peer, blocks = best_peer_hint(selection.overlaps)
        if peer != selection.worker_id and blocks > selection.overlap_blocks:
            return peer, blocks
        return None


class KvPushRouter:
    """EndpointClient + KvRouter glued into one `generate` surface."""

    def __init__(self, client: EndpointClient, router: KvRouter, monitor=None):
        self.client = client
        self.router = router
        # Optional WorkerMonitor (runtime/worker_monitor.py): busy-aware
        # routing when the config sets a busy_threshold; its aggregator
        # also feeds ProcessedEndpoints snapshots to observers.
        self.monitor = monitor
        if monitor is not None and getattr(router, "netcost", None) is not None:
            # The netcost model reads queue depths and every worker's
            # measured per-peer pull costs from the monitor's
            # ForwardPassMetrics view (one subscription, shared).
            router.netcost.fleet_view = lambda: monitor.metrics
        self._tracer = tracing.get_tracer("router")
        client.on_instance_removed.append(self._on_worker_gone)

    def _on_worker_gone(self, worker_id: int) -> None:
        if self.monitor is not None:
            self.monitor.remove_worker(worker_id)
        orphans = self.router.remove_worker(worker_id)
        if orphans:
            log.info("worker %d died with %d in-flight requests", worker_id, len(orphans))

    async def generate(
        self,
        payload: dict,
        request_id: str,
        token_ids: list[int],
        headers: dict[str, str] | None = None,
        router_overrides: dict[str, Any] | None = None,
        exclude: set[int] | None = None,
    ) -> AsyncIterator[Any]:
        overrides = router_overrides or {}
        # Route-decision span: closed before dispatch, so the routing cost
        # never overlaps the worker's prefill phase in the waterfall.
        with self._tracer.span(
            "route", headers=headers, attrs={"request_id": request_id}
        ) as route_span:
            workers = self.client.instance_ids()
            if exclude:
                # Migration retries must not re-dial a worker that just failed —
                # its cached prefix makes it the router's top pick otherwise.
                workers = [w for w in workers if w not in exclude] or workers
            if self.monitor is not None:
                # Busy/saturation-aware routing: the monitor marks
                # workers busy on KV pressure (busy_threshold) or queue
                # saturation (queue_threshold / worker-exported queue
                # limit); an all-busy fleet falls back to the full set.
                workers = self.monitor.eligible(workers)
            if not workers:
                raise NoInstancesError(self.client.endpoint.path)
            pinned = overrides.get("backend_instance_id")
            if pinned is not None:
                selection = SelectionResult(
                    worker_id=pinned, overlap_blocks=0, required_prefill_tokens=len(token_ids), costs={}
                )
                self.router.note_pinned(request_id, pinned, len(token_ids))
            else:
                config = self.router.config
                if "overlap_weight" in overrides or "router_temperature" in overrides:
                    # replace() keeps every other knob (network_aware,
                    # queue_weight, thresholds) at the router's values.
                    config = dataclasses.replace(
                        config,
                        overlap_weight=overrides.get(
                            "overlap_weight", config.overlap_weight
                        ),
                        temperature=overrides.get(
                            "router_temperature", config.temperature
                        ),
                    )
                selection = self.router.find_best_match(request_id, token_ids, workers, config)
                if route_span.recording and selection.score_end_s > selection.score_start_s:
                    # The selector has no trace context; it stamps the
                    # scoring-pass bounds and we file them here, parented
                    # to the route span.
                    self._tracer.record(
                        "overlap_score",
                        selection.score_start_s,
                        selection.score_end_s,
                        parent=route_span,
                        attrs={"workers": len(workers)},
                    )
            route_span.set("worker_id", selection.worker_id)
            route_span.set("overlap_blocks", selection.overlap_blocks)
            route_span.set("required_prefill_tokens", selection.required_prefill_tokens)
            if selection.costs:
                route_span.set("cost", selection.costs.get(selection.worker_id))
        payload = dict(payload)
        payload.setdefault("meta", {})["overlap_blocks"] = selection.overlap_blocks
        # Cross-worker prefix pull (reference KVBM-distributed semantics,
        # block_manager/distributed/leader.rs:64): when routing lands on
        # a worker with LESS of this prompt cached than some peer —
        # busy-avoidance, temperature sampling, migration exclusion — the
        # hint lets the chosen worker pull the peer's blocks (device or
        # offload tiers) over the data plane instead of recomputing. In
        # network-aware mode the hint is cost-decided: a slow/loaded peer
        # is skipped even when it overlaps best (router.peer_hint).
        hint = self.router.peer_hint(selection)
        if hint is not None:
            peer, blocks = hint
            payload["kv_transfer_params"] = dict(
                payload.get("kv_transfer_params") or {},
                peer_prefix={"worker_id": peer, "blocks": blocks},
            )

        first = True
        stream = None
        done = False
        try:
            try:
                stream = await self.client.direct(
                    selection.worker_id, payload, headers
                )
            except (ConnectionError, NoInstancesError) as e:
                # Dial-time failure: tag the instance so migration
                # excludes it on replay (a dead worker's cached prefix
                # would otherwise make it the router's top pick again).
                e.worker_id = selection.worker_id  # type: ignore[attr-defined]
                raise
            while True:
                try:
                    item = await stream.__anext__()
                except StopAsyncIteration:
                    done = True
                    break
                except (ConnectionError, NoInstancesError) as e:
                    done = True  # the worker side is already gone
                    # Tag the failure with the worker so migration can
                    # exclude it on replay.
                    e.worker_id = selection.worker_id  # type: ignore[attr-defined]
                    raise
                except Exception:
                    done = True  # stream-delivered error: server closed it
                    raise
                # CancelledError/GeneratorExit (consumer vanished while
                # awaiting a frame) fall through with done=False — the
                # finally forwards the kill.
                if first:
                    first = False
                    self.router.mark_prefill_done(request_id)
                # The one suspension the CONSUMER can abandon us at
                # (client disconnect -> GeneratorExit/CancelledError
                # thrown here): `done` stays False and the finally
                # forwards the kill.
                yield item
        finally:
            self.router.free(request_id)
            if stream is not None and not done:
                # Consumer vanished mid-stream: forward the kill so the
                # worker drops the request — queued or running — instead
                # of serving a ghost. Fire-and-forget: this finally may
                # be unwinding a cancellation and must not await.
                from dynamo_tpu.runtime.tasks import spawn_logged

                spawn_logged(
                    stream.kill_quietly(),
                    name=f"router-kill-{request_id}",
                    logger=log,
                )

    @property
    def worker_ids(self) -> list[int]:
        return self.client.instance_ids()
