"""Worker selection: the KV-aware cost function + softmax sampling.

For each candidate worker the cost is

    cost(w) = overlap_weight * potential_prefill_blocks(w)
              + potential_decode_blocks(w)

where ``potential_prefill_blocks`` is the prefill still required *after*
prefix-cache reuse on that worker, and ``potential_decode_blocks`` the
worker's block occupancy if the request lands there. Lower is better; a
softmax over negative normalized costs (temperature ``t``) picks the
worker — ``t == 0`` degenerates to argmin with deterministic tie-break.

Capability parity: reference `lib/llm/src/kv_router/scheduler.rs:361,
417-418` (DefaultWorkerSelector, formula) and `:288` (softmax_sample).
"""

from __future__ import annotations

import math
import random
import time
from dataclasses import dataclass
from typing import Protocol

from dynamo_tpu.llm.kv_router.protocols import RouterConfig
from dynamo_tpu.llm.kv_router.sequence import ActiveSequences


@dataclass
class SelectionResult:
    worker_id: int
    overlap_blocks: int
    required_prefill_tokens: int
    costs: dict[int, float]
    # Per-worker cached-prefix overlap (blocks) as the indexer saw it at
    # selection time — lets the caller detect a better-overlapping PEER
    # than the chosen worker (cross-worker prefix pull).
    overlaps: dict[int, int] = None  # type: ignore[assignment]
    # Wall-clock bounds of the scoring pass (cost function + softmax),
    # stamped by the selector. The router files these as an
    # ``overlap_score`` child span of its route span — the selector
    # itself has no trace context, so the span is recorded upstream.
    score_start_s: float = 0.0
    score_end_s: float = 0.0
    # Network-aware routing (netcost.NetworkAwareSelector): the chosen
    # worker's cost-decided pull source — (source_worker_id, blocks held
    # there) — already discounted by measured transfer cost. None = no
    # pull beats recomputing (or the selector is overlap-only).
    pull_hint: tuple[int, int] | None = None


class WorkerSelector(Protocol):
    def select_worker(
        self,
        workers: list[int],
        overlaps: dict[int, int],
        prompt_tokens: int,
        active: ActiveSequences,
        config: RouterConfig,
    ) -> SelectionResult: ...


def softmax_sample(
    costs: dict[int, float], temperature: float, rng: random.Random | None = None
) -> int:
    """Sample a key with probability decreasing in cost; t=0 → argmin."""
    if not costs:
        raise ValueError("no candidates")
    if temperature <= 0.0:
        return min(sorted(costs), key=lambda k: costs[k])
    vals = list(costs.values())
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span == 0.0:
        keys = sorted(costs)
        return (rng or random).choice(keys)
    logits = {k: -(v - lo) / span / temperature for k, v in costs.items()}
    mx = max(logits.values())
    weights = {k: math.exp(v - mx) for k, v in logits.items()}
    total = sum(weights.values())
    r = (rng.random() if rng else random.random()) * total
    acc = 0.0
    for k in sorted(weights):
        acc += weights[k]
        if r <= acc:
            return k
    return max(sorted(weights), key=lambda k: weights[k])


class DefaultWorkerSelector:
    def __init__(self, rng: random.Random | None = None):
        self._rng = rng or random.Random()

    def _score(
        self,
        worker_id: int,
        overlap: int,
        prefill_blocks: float,
        decode_blocks: float,
        overlaps: dict[int, int],
        prompt_blocks: int,
        config: RouterConfig,
    ) -> tuple[float, object]:
        """One candidate's cost, plus an opaque note handed to
        :meth:`_annotate` if this candidate wins. Subclasses extend the
        scoring HERE (NetworkAwareSelector) so the candidate loop itself
        exists once and the two routing modes cannot silently diverge."""
        return config.overlap_weight * prefill_blocks + decode_blocks, None

    def _annotate(self, result: SelectionResult, note: object) -> SelectionResult:
        """Post-selection hook: the winning candidate's note from
        :meth:`_score`."""
        return result

    def select_worker(
        self,
        workers: list[int],
        overlaps: dict[int, int],
        prompt_tokens: int,
        active: ActiveSequences,
        config: RouterConfig,
    ) -> SelectionResult:
        if not workers:
            raise ValueError("no live workers")
        t_score = time.time()
        block_size = active.block_size
        prompt_blocks = math.ceil(prompt_tokens / block_size) if prompt_tokens else 0
        costs: dict[int, float] = {}
        notes: dict[int, object] = {}
        for w in workers:
            overlap = min(overlaps.get(w, 0), prompt_blocks)
            decode_blocks, prefill_tokens = active.potential_blocks_and_tokens(
                w, prompt_tokens, overlap
            )
            prefill_blocks = prefill_tokens / block_size
            costs[w], notes[w] = self._score(
                w, overlap, prefill_blocks, decode_blocks, overlaps,
                prompt_blocks, config,
            )
        chosen = softmax_sample(costs, config.temperature, self._rng)
        overlap = min(overlaps.get(chosen, 0), prompt_blocks)
        result = SelectionResult(
            worker_id=chosen,
            overlap_blocks=overlap,
            required_prefill_tokens=max(0, prompt_tokens - overlap * block_size),
            costs=costs,
            score_start_s=t_score,
            score_end_s=time.time(),
        )
        return self._annotate(result, notes[chosen])
