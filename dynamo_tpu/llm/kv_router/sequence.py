"""Router-side bookkeeping of in-flight work per worker.

The *load* term of the scheduling cost: for every request the router has
dispatched but not seen complete, track how many prefill tokens are still
owed and how many KV blocks the sequence occupies as it decodes. Freed on
stream completion or worker death.

Capability parity: reference `lib/llm/src/kv_router/sequence.rs:48-225`
(ActiveSequences / ActiveSequencesMultiWorker) + `prefill_counter.rs:70`.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass


@dataclass
class _ActiveSeq:
    worker_id: int
    prefill_tokens: int     # tokens that still need prefill on the worker
    decode_blocks: int      # blocks currently held by this sequence
    started: float


class ActiveSequences:
    def __init__(self, block_size: int = 32):
        self.block_size = block_size
        self._seqs: dict[str, _ActiveSeq] = {}
        self._worker_prefill_tokens: dict[int, int] = {}
        self._worker_decode_blocks: dict[int, int] = {}

    def add_request(
        self,
        request_id: str,
        worker_id: int,
        prompt_tokens: int,
        overlap_blocks: int,
    ) -> None:
        if request_id in self._seqs:
            # Duplicate adds happen legitimately (migration retries reuse
            # the request id; a replica-sync delta can arrive after the
            # bootstrap snapshot already installed the request) — counting
            # twice would skew this worker's load permanently.
            return
        new_prefill = max(0, prompt_tokens - overlap_blocks * self.block_size)
        blocks = math.ceil(prompt_tokens / self.block_size)
        self._seqs[request_id] = _ActiveSeq(
            worker_id=worker_id,
            prefill_tokens=new_prefill,
            decode_blocks=blocks,
            started=time.monotonic(),
        )
        self._worker_prefill_tokens[worker_id] = (
            self._worker_prefill_tokens.get(worker_id, 0) + new_prefill
        )
        self._worker_decode_blocks[worker_id] = (
            self._worker_decode_blocks.get(worker_id, 0) + blocks
        )

    def mark_prefill_done(self, request_id: str) -> None:
        seq = self._seqs.get(request_id)
        if seq is None or seq.prefill_tokens == 0:
            return
        self._worker_prefill_tokens[seq.worker_id] -= seq.prefill_tokens
        seq.prefill_tokens = 0

    def add_decode_block(self, request_id: str) -> None:
        seq = self._seqs.get(request_id)
        if seq is None:
            return
        seq.decode_blocks += 1
        self._worker_decode_blocks[seq.worker_id] += 1

    def free(self, request_id: str) -> None:
        seq = self._seqs.pop(request_id, None)
        if seq is None:
            return
        self._worker_prefill_tokens[seq.worker_id] = (
            self._worker_prefill_tokens.get(seq.worker_id, 0) - seq.prefill_tokens
        )
        self._worker_decode_blocks[seq.worker_id] = (
            self._worker_decode_blocks.get(seq.worker_id, 0) - seq.decode_blocks
        )

    def add_raw(
        self, request_id: str, worker_id: int, prefill_tokens: int, decode_blocks: int
    ) -> None:
        """Install a replica-sync snapshot entry verbatim (already-derived
        prefill/block counts, no recomputation)."""
        if request_id in self._seqs:
            return
        self._seqs[request_id] = _ActiveSeq(
            worker_id=worker_id,
            prefill_tokens=prefill_tokens,
            decode_blocks=decode_blocks,
            started=time.monotonic(),
        )
        self._worker_prefill_tokens[worker_id] = (
            self._worker_prefill_tokens.get(worker_id, 0) + prefill_tokens
        )
        self._worker_decode_blocks[worker_id] = (
            self._worker_decode_blocks.get(worker_id, 0) + decode_blocks
        )

    def items(self):
        """(request_id, entry) view for replica-sync snapshots."""
        return self._seqs.items()

    def remove_worker(self, worker_id: int) -> list[str]:
        """Drops all state for a dead worker; returns orphaned request ids
        (candidates for migration)."""
        orphans = [rid for rid, s in self._seqs.items() if s.worker_id == worker_id]
        for rid in orphans:
            del self._seqs[rid]
        self._worker_prefill_tokens.pop(worker_id, None)
        self._worker_decode_blocks.pop(worker_id, None)
        return orphans

    # -- load queries ------------------------------------------------------

    def potential_blocks_and_tokens(
        self, worker_id: int, prompt_tokens: int, overlap_blocks: int
    ) -> tuple[int, int]:
        """(decode blocks, prefill tokens) on `worker_id` *if* this request
        were routed there."""
        new_prefill = max(0, prompt_tokens - overlap_blocks * self.block_size)
        blocks = math.ceil(prompt_tokens / self.block_size)
        return (
            self._worker_decode_blocks.get(worker_id, 0) + blocks,
            self._worker_prefill_tokens.get(worker_id, 0) + new_prefill,
        )

    def decode_blocks(self, worker_id: int) -> int:
        return self._worker_decode_blocks.get(worker_id, 0)

    def prefill_tokens(self, worker_id: int) -> int:
        return self._worker_prefill_tokens.get(worker_id, 0)

    def active_requests(self, worker_id: int | None = None) -> int:
        if worker_id is None:
            return len(self._seqs)
        return sum(1 for s in self._seqs.values() if s.worker_id == worker_id)
