"""Request migration: mid-stream worker-failure recovery by token replay.

If the worker serving a stream dies, the accumulated output tokens are
appended to the request's prompt and the request is re-issued to another
worker — the client sees one uninterrupted stream. Token replay is
engine-agnostic: with prefix caching the new worker re-prefills cheaply.
Bounded by the model card's ``migration_limit``.

Structured as pipeline-graph nodes (runtime/pipeline.py):
:class:`MigrationOperator` is the canonical full Operator — it must carry
state from the backward path (tokens already streamed) into the forward
path (the replayed request), exactly the property the reference built its
PipelineOperator trait for — and :class:`RouterEgress` is the terminal
backend that routes one attempt to a worker over the data plane.
:class:`Migration` assembles the two into a ServicePipeline (the same
composition `build_routed_pipeline` does on model-add, reference
`lib/llm/src/entrypoint/input/common.rs:216`).

Capability parity: reference `lib/llm/src/migration.rs:26,74-89`
(RetryManager) + `docs/architecture/request_migration.md`.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from dataclasses import replace
from typing import AsyncIterator

from dynamo_tpu import tracing
from dynamo_tpu.llm.kv_router.router import KvPushRouter
from dynamo_tpu.llm.protocols.common import LLMEngineOutput, PreprocessedRequest
from dynamo_tpu.runtime.component import EndpointClient, NoInstancesError
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.pipeline import NextFn, PipelineBuilder, ServicePipeline
from dynamo_tpu.runtime.store.client import reconnect_delay

log = logging.getLogger("dynamo_tpu.migration")


class RouterEgress:
    """Terminal pipeline backend: route ONE attempt of a preprocessed
    request to a worker instance and stream its wire chunks back. Routing
    hints ride the context: ``exclude_instances`` (workers the migration
    operator has seen die) and the caller's headers."""

    def __init__(
        self,
        client: EndpointClient,
        push_router: KvPushRouter | None,
        mode: str = "kv",
    ):
        self.client = client
        self.push_router = push_router
        self.mode = mode

    async def generate(
        self, pre: PreprocessedRequest, context: Context
    ) -> AsyncIterator[LLMEngineOutput]:
        payload = pre.to_wire()
        exclude = context.meta.get("exclude_instances", set())
        headers = context.headers or None
        if self.push_router is not None:
            stream = self.push_router.generate(
                payload,
                request_id=pre.request_id or "anon",
                token_ids=pre.token_ids,
                headers=headers,
                router_overrides=pre.router,
                exclude=exclude,
            )
            async for item in stream:
                yield LLMEngineOutput.from_wire(item)
        else:
            worker_id = self.client.pick_instance(self.mode, exclude)
            stream = None
            done = False
            try:
                try:
                    stream = await self.client.direct(worker_id, payload, headers)
                except (ConnectionError, NoInstancesError) as e:
                    # Dial-time failure: tag the instance for exclusion.
                    e.worker_id = worker_id  # type: ignore[attr-defined]
                    raise
                while True:
                    try:
                        item = await stream.__anext__()
                    except StopAsyncIteration:
                        done = True
                        break
                    except (ConnectionError, NoInstancesError) as e:
                        done = True  # the worker side is already gone
                        e.worker_id = worker_id  # type: ignore[attr-defined]
                        raise
                    except Exception:
                        done = True  # stream-delivered error: server closed it
                        raise
                    # Consumer abandonment (client disconnect) surfaces
                    # as CancelledError/GeneratorExit — at the await
                    # above or thrown in at this yield — and leaves
                    # `done` False, so the finally forwards the kill.
                    yield LLMEngineOutput.from_wire(item)
            finally:
                if stream is not None and not done:
                    # Consumer vanished mid-stream: forward the kill so
                    # the worker drops the request (queued or running)
                    # instead of serving a ghost. Fire-and-forget — this
                    # finally may be unwinding a cancellation.
                    from dynamo_tpu.runtime.tasks import spawn_logged

                    spawn_logged(
                        stream.kill_quietly(),
                        name=f"egress-kill-{pre.request_id}",
                        logger=log,
                    )


class MigrationOperator:
    """Retry-with-token-replay around the downstream egress. Forward path:
    rewrites the request with already-generated tokens appended and the
    stop budget shrunk; backward path: accumulates streamed tokens (the
    state the next forward rewrite needs) and closes the stream exactly
    once a finish reason passes."""

    def __init__(self, limit: int = 3, rng: random.Random | None = None):
        self.limit = limit
        self._tracer = tracing.get_tracer("migration")
        # Retry pacing: full-jitter exponential backoff on the store
        # client's reconnect schedule (same ceilings, same rationale — a
        # worker crash fails every stream it carried at the same instant,
        # and a fixed wait would re-dial the survivors in one synchronized
        # wave). `rng`/`_sleep` are injectable for deterministic tests.
        self._rng = rng or random.Random()
        self._sleep = asyncio.sleep

    async def generate(
        self, pre: PreprocessedRequest, context: Context, next: NextFn
    ) -> AsyncIterator[LLMEngineOutput]:
        attempts = 0
        generated: list[int] = []
        failed_workers: set[int] = set()
        current = pre

        def trace_attempt(start_s: float, outcome: str) -> None:
            # Per-attempt spans only once a migration actually happened:
            # the unmigrated fast path records nothing (span names stay a
            # small fixed set; the attempt index is an attribute).
            if attempts == 0 and outcome != "failed":
                return
            self._tracer.record(
                "migration_attempt", start_s, time.time(),
                headers=context.headers,
                attrs={
                    "request_id": pre.request_id,
                    "attempt": attempts,
                    "replayed_tokens": len(current.token_ids) - len(pre.token_ids),
                    "outcome": outcome,
                },
            )

        while True:
            attempt_ctx = context.child()
            attempt_ctx.meta["exclude_instances"] = failed_workers
            t_attempt = time.time()
            try:
                async for out in next(current, attempt_ctx):
                    generated.extend(out.token_ids)
                    if attempts and out.finish_reason is not None:
                        # Usage fix-up after a replay: the final attempt's
                        # engine counts the replayed tokens as PROMPT and
                        # only its own output as completion. The client
                        # billed the original prompt and streamed
                        # len(generated) tokens total — report exactly
                        # that, charging each replayed token once.
                        out = replace(
                            out,
                            prompt_tokens=len(pre.token_ids),
                            completion_tokens=len(generated),
                        )
                    yield out
                    if out.finish_reason is not None:
                        trace_attempt(t_attempt, "completed")
                        return
                trace_attempt(t_attempt, "completed")
                return
            except (ConnectionError, NoInstancesError) as e:
                trace_attempt(t_attempt, "failed")
                attempts += 1
                failed = getattr(e, "worker_id", None)
                if failed is not None:
                    failed_workers.add(failed)
                if attempts > self.limit:
                    log.warning(
                        "request %s exhausted %d migrations", pre.request_id, self.limit
                    )
                    raise
                # Replay: generated tokens become prompt suffix; budget
                # and minimum shrink by what the client already has.
                new_stop = pre.stop.after_replay(len(generated))
                if new_stop.max_tokens is not None and new_stop.max_tokens <= 0:
                    # Budget exhausted exactly at failure: close the
                    # stream with an explicit length finish.
                    yield LLMEngineOutput(
                        token_ids=[],
                        finish_reason="length",
                        prompt_tokens=len(pre.token_ids),
                        completion_tokens=len(generated),
                    )
                    return
                current = replace(
                    current,
                    token_ids=list(pre.token_ids) + generated,
                    stop=new_stop,
                    replayed_tokens=len(generated),
                )
                log.info(
                    "migrating request %s (attempt %d/%d, %d tokens replayed): %s",
                    pre.request_id, attempts, self.limit, len(generated), e,
                )
                await self._sleep(reconnect_delay(attempts - 1, self._rng))


class Migration:
    """The assembled routed pipeline segment: MigrationOperator →
    RouterEgress. Kept as a class so callers (ModelManager, disagg
    router) hold one object with the historical ``generate(pre,
    headers)`` surface; internally it IS a ServicePipeline and further
    operators can be linked in front via ``build_pipeline``."""

    def __init__(
        self,
        client: EndpointClient,
        push_router: KvPushRouter | None,
        mode: str = "kv",
        limit: int = 3,
    ):
        self.client = client
        self.push_router = push_router
        self.mode = mode
        self.limit = limit
        self.pipeline: ServicePipeline = self.build_pipeline()

    def build_pipeline(self, *front_operators) -> ServicePipeline:
        """Assemble ``front_operators → MigrationOperator → RouterEgress``."""
        builder = PipelineBuilder()
        for op in front_operators:
            builder.link(op)
        return builder.link(MigrationOperator(self.limit)).backend(
            RouterEgress(self.client, self.push_router, self.mode)
        )

    async def generate(
        self, pre: PreprocessedRequest, headers: dict[str, str] | None = None
    ) -> AsyncIterator[LLMEngineOutput]:
        ctx = Context(request_id=pre.request_id, headers=headers)
        async for out in self.pipeline.generate(pre, ctx):
            yield out
