"""Request migration: mid-stream worker-failure recovery by token replay.

If the worker serving a stream dies, the accumulated output tokens are
appended to the request's prompt and the request is re-issued to another
worker — the client sees one uninterrupted stream. Token replay is
engine-agnostic: with prefix caching the new worker re-prefills cheaply.
Bounded by the model card's ``migration_limit``.

Capability parity: reference `lib/llm/src/migration.rs:26,74-89`
(RetryManager) + `docs/architecture/request_migration.md`.
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import replace
from typing import AsyncIterator

from dynamo_tpu.llm.kv_router.router import KvPushRouter
from dynamo_tpu.llm.protocols.common import LLMEngineOutput, PreprocessedRequest
from dynamo_tpu.runtime.component import EndpointClient, NoInstancesError

log = logging.getLogger("dynamo_tpu.migration")

_RETRY_WAIT_S = 0.2


class Migration:
    def __init__(
        self,
        client: EndpointClient,
        push_router: KvPushRouter | None,
        mode: str = "kv",
        limit: int = 3,
    ):
        self.client = client
        self.push_router = push_router
        self.mode = mode
        self.limit = limit

    async def _dispatch(
        self, pre: PreprocessedRequest, headers: dict[str, str] | None
    ) -> AsyncIterator[LLMEngineOutput]:
        payload = pre.to_wire()
        if self.push_router is not None:
            stream = self.push_router.generate(
                payload,
                request_id=pre.request_id or "anon",
                token_ids=pre.token_ids,
                headers=headers,
                router_overrides=pre.router,
            )
            async for item in stream:
                yield LLMEngineOutput.from_wire(item)
        else:
            pick = self.client.random if self.mode == "random" else self.client.round_robin
            stream = await pick(payload, headers)
            async for item in stream:
                yield LLMEngineOutput.from_wire(item)

    async def generate(
        self, pre: PreprocessedRequest, headers: dict[str, str] | None = None
    ) -> AsyncIterator[LLMEngineOutput]:
        attempts = 0
        generated: list[int] = []
        current = pre
        while True:
            try:
                async for out in self._dispatch(current, headers):
                    generated.extend(out.token_ids)
                    yield out
                    if out.finish_reason is not None:
                        return
                return
            except (ConnectionError, NoInstancesError) as e:
                attempts += 1
                if attempts > self.limit:
                    log.warning(
                        "request %s exhausted %d migrations", pre.request_id, self.limit
                    )
                    raise
                # Replay: generated tokens become prompt suffix; budget shrinks.
                new_stop = replace(current.stop)
                if new_stop.max_tokens is not None:
                    remaining = (pre.stop.max_tokens or 0) - len(generated)
                    if remaining <= 0:
                        # Budget exhausted exactly at failure: close the
                        # stream with an explicit length finish.
                        yield LLMEngineOutput(
                            token_ids=[],
                            finish_reason="length",
                            prompt_tokens=len(pre.token_ids),
                            completion_tokens=len(generated),
                        )
                        return
                    new_stop.max_tokens = remaining
                current = replace(
                    current,
                    token_ids=list(pre.token_ids) + generated,
                    stop=new_stop,
                )
                log.info(
                    "migrating request %s (attempt %d/%d, %d tokens replayed): %s",
                    pre.request_id, attempts, self.limit, len(generated), e,
                )
                await asyncio.sleep(_RETRY_WAIT_S)
