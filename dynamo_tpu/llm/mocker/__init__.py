from dynamo_tpu.llm.mocker.engine import MockEngineArgs, MockTpuEngine
from dynamo_tpu.llm.mocker.kv_manager import MockKvManager

__all__ = ["MockEngineArgs", "MockKvManager", "MockTpuEngine"]
