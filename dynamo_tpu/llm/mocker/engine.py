"""The mock TPU engine: a timing-faithful fake worker.

Simulates a paged-attention continuous-batching engine — watermark
admission, chunked prefill, prefix-cache reuse, per-iteration cost model,
LRU eviction — while emitting *real* KV events and load metrics. It is the
linchpin of cluster-free testing (SURVEY.md §4): router, disaggregation,
migration, and planner e2e tests all run against fleets of these.

Capability parity: reference `lib/llm/src/mocker/engine.rs:60`
(MockVllmEngine), `scheduler.rs:54` (watermark/chunked-prefill
SchedulerState), `protocols.rs:79` (MockEngineArgs, speedup_ratio).
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Any, AsyncIterator

from dynamo_tpu import knobs, tracing
from dynamo_tpu.engine.fair_queue import FairQueue
from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics, KvStats, WorkerStats
from dynamo_tpu.llm.mocker.kv_manager import InsufficientBlocksError, MockKvManager
from dynamo_tpu.llm.protocols.common import (
    LLMEngineOutput,
    PreprocessedRequest,
    StopConditions,
)
from dynamo_tpu.runtime import chaos
from dynamo_tpu.runtime.engine import Context, EngineOverloadedError
from dynamo_tpu.spec import SpecConfig, SpecStats, resolve_spec_config
from dynamo_tpu.tokens import TokenBlockSequence, compute_seq_hashes

log = logging.getLogger("dynamo_tpu.mocker")


@dataclass
class MockEngineArgs:
    num_kv_blocks: int = 8192
    block_size: int = 32
    max_num_seqs: int = 256
    max_num_batched_tokens: int = 8192
    watermark: float = 0.01
    enable_prefix_caching: bool = True
    enable_chunked_prefill: bool = True
    # Step scheduler, mirroring EngineConfig.scheduling: "chunked" mixes
    # prefill chunks with decode rows under max_num_batched_tokens (the
    # mocker's historical shape); "waves" runs monolithic prefill
    # iterations strictly before decode — in-flight decodes stall while
    # any prompt prefills, like the real engine's wave scheduler.
    scheduling: str = "chunked"
    # Chunk cap for streaming one prompt per mixed step; 0 = budget-bound
    # only (mirrors EngineConfig.prefill_chunk).
    prefill_chunk: int = 0
    speedup_ratio: float = 1.0
    # Cost model (pre-speedup): base_iter_us is the fixed per-dispatch
    # HOST overhead (plan assembly, sampled-token fetch, bookkeeping,
    # detokenization); the token/seq terms are DEVICE compute.
    #   async_exec off: iteration = host + device  (they serialize)
    #   async_exec on:  iteration = max(host, device)  (one-step-ahead
    #     pipelining hides the smaller term under the larger — the
    #     virtual-clock twin of EngineCore's plan/dispatch/commit split;
    #     token VALUES are unchanged, the stream stays bit-identical)
    base_iter_us: float = 500.0
    prefill_us_per_token: float = 10.0
    decode_us_per_seq: float = 100.0
    async_exec: bool = False
    # Speculative decoding (mirrors EngineConfig.spec_decode/spec_k): with
    # "ngram", every decode row becomes a verify row that emits
    # 1 + accepted tokens per iteration, where accepted is simulated by
    # spec_acceptance_rate (per-draft-token Bernoulli, stop at first
    # miss — the geometric acceptance profile real drafters show). Draft
    # tokens are priced like prefill tokens and count against
    # max_num_batched_tokens, so frontend/router/bench A/Bs exercise the
    # scheduling + timing consequences CPU-only. Token VALUES are
    # unchanged — the stream stays bit-identical to spec off.
    spec_decode: str = "off"
    spec_k: int = 4
    spec_acceptance_rate: float = 0.6
    # On-device n-gram drafting (mirrors EngineConfig.spec_device_draft,
    # ISSUE 18): with megastep_k >= 2, a device-drafting lane's inner
    # iterations become draft->verify->accept ROUNDS riding the same
    # dispatch — round 0 emits one token, every later round drafts up to
    # spec_k fresh tokens from the (simulated) history ring and emits
    # accepted + 1. Drafted tokens price like prefill tokens (each is an
    # extra target forward in the verify-shaped row) and every round
    # adds DYN_SPEC_DRAFT_ROUND_US of match/gather cost to the clock.
    # Token VALUES are unchanged — the stream stays bit-identical to
    # spec off; only the chunking and the virtual clock move.
    spec_device_draft: bool = False
    # UNIVERSAL megastep (mirrors EngineConfig.megastep_k, ISSUE 12):
    # every iteration with decode work fuses k device steps under ONE
    # per-dispatch host overhead (base_iter_us) — decode lanes run up to
    # k inner iterations, spec verify lanes resolve accept/reject inside
    # the fused iteration and emit (1 + accepted) + (k - 1) tokens, and
    # prefill chunks ride the same priced dispatch (mixed traffic no
    # longer forces k=1). The device term prices k lane-iterations per
    # lane — lanes that stop early still pay the masked no-op
    # iterations, like the real scan. Token VALUES are unchanged — the
    # stream is bit-identical to k=1.
    megastep_k: int = 1
    # Quantized KV cache (mirrors EngineConfig.kv_dtype): decode
    # attention is DMA-latency-bound (PERF.md), so the cost model prices
    # per-lane-iteration KV traffic as resident_blocks x
    # kv_read_us_per_block x the dtype's byte ratio (engine/kv_quant.py:
    # 1.0 for bf16, ~0.516 for int8 at head_dim 128, scales included).
    # kv_read_us_per_block=0 (default) keeps every existing timing
    # bit-identical; bench.py run_kvquant_ab sets it for the A/B. Token
    # VALUES never change — only the virtual clock and capacity move.
    kv_dtype: str = "bf16"
    kv_read_us_per_block: float = 0.0
    # Cluster KV pool (ISSUE 11): virtual-clock price of pulling ONE
    # bf16-equivalent KV block from a peer over the dataplane, scaled by
    # the kv_dtype's byte ratio (int8 pulls move ~0.52x the bytes — the
    # packed wire buffer IS the transfer format). 0 = pulls are free on
    # the clock (legacy timing untouched); bench run_peer_pool_ab sets it
    # for the shared-prefix fleet A/B.
    kv_pull_us_per_block: float = 0.0
    # Overload robustness (mirrors EngineConfig, ISSUE 10): per-tenant
    # DRR fair admission (off = exact FIFO; single tenant is FIFO either
    # way, so streams stay bit-identical), the DRR quantum (0 = token
    # budget), and the bounded admission queue (0 = unbounded; at the
    # ceiling submits raise the typed retryable EngineOverloadedError).
    fair_scheduling: bool = False
    fair_quantum: int = 0
    max_waiting: int = 0
    # Pipeline parallelism (mirrors EngineCore's pp_mesh, ISSUE 20): the
    # virtual clock prices every decode dispatch's stage traffic as
    # (k * pp + pp - 1) hops at DYN_PP_HOP_US each — k wavefront
    # iterations over pp stages plus the pipe fill/drain bubble. With
    # megastep_k=1 that is the host-rollback pp baseline (one priced
    # dispatch + bubble PER TOKEN); with megastep_k=k the same bubble
    # amortizes over k tokens under ONE base_iter_us — exactly the fused
    # pp megastep A/B bench.py run_pp_megastep_ab asserts. Token VALUES
    # are unchanged — pp streams stay bit-identical to pp=1.
    pp: int = 1


@dataclass
class _Seq:
    request_id: str
    prompt: list[int]
    max_tokens: int
    out: asyncio.Queue
    seq: TokenBlockSequence
    prompt_hashes: list[int]
    cached_blocks: int = 0
    pinned: list[int] = field(default_factory=list)
    partials_held: int = 0
    prefilled: int = 0
    generated: int = 0
    cancelled: bool = False
    stop: StopConditions = field(default_factory=StopConditions)
    # Speculation draft length for this request (0 = off); resolved at
    # submit from the engine default + the request's spec_decode dict.
    spec_k: int = 0
    # Drafts on device between megastep inner iterations (ISSUE 18);
    # resolved like spec_k (engine flag AND the request's choice).
    spec_device: bool = False
    # Tokens a previous attempt already streamed to the client
    # (migration replay): offsets the synthetic token function so a
    # replayed stream continues bit-identically where the dead worker
    # stopped, the way a real model conditioning on the grown prompt
    # would.
    replay_base: int = 0
    # Overload metadata (ISSUE 10), mirroring engine/core.Sequence:
    # fairness identity, within-tenant ordering, absolute deadline (in
    # the engine's clock domain — injectable for virtual-clock tests).
    tenant_id: str = ""
    priority: int = 0
    deadline_epoch: float | None = None
    # do_remote_decode request (disagg prefill side): advertise chunk
    # commits through the engine's on_chunk_commit hook and tag the
    # final output with kv_transfer_params for the reply contract.
    notify_chunks: bool = False
    # Phase timestamps for the tracer (0.0 = not reached yet). The spans
    # are emitted retroactively when the stream closes so the sim loop's
    # hot path only ever stamps a float.
    t_submit: float = 0.0
    t_first_sched: float = 0.0   # first prefill chunk entered a step
    t_prefill_done: float = 0.0
    t_last_token: float = 0.0

    @property
    def prefill_done(self) -> bool:
        return self.prefilled >= len(self.prompt)


class MockTpuEngine:
    """AsyncEngine over PreprocessedRequest wire dicts."""

    _FINISHED = object()

    def __init__(
        self,
        args: MockEngineArgs | None = None,
        kv_manager: MockKvManager | None = None,
        eos_token_ids: tuple[int, ...] = (),
    ):
        self.args = args or MockEngineArgs()
        if self.args.scheduling not in ("waves", "chunked"):
            raise ValueError(
                f"unknown scheduling policy {self.args.scheduling!r} "
                "(expected 'waves' or 'chunked')"
            )
        if self.args.spec_decode not in ("off", "ngram"):
            raise ValueError(
                f"unknown spec_decode {self.args.spec_decode!r} "
                "(expected 'off' or 'ngram')"
            )
        if self.args.megastep_k < 1:
            raise ValueError(
                f"megastep_k must be >= 1, got {self.args.megastep_k}"
            )
        if self.args.pp < 1:
            raise ValueError(f"pp must be >= 1, got {self.args.pp}")
        from dynamo_tpu.engine.kv_quant import KV_DTYPES, kv_byte_ratio

        if self.args.kv_dtype not in KV_DTYPES:
            raise ValueError(
                f"unknown kv_dtype {self.args.kv_dtype!r} "
                f"(expected one of {KV_DTYPES})"
            )
        # Bytes moved per resident KV block relative to bf16 (int8 pages
        # + f32 scales ~0.516x at the nominal head_dim 128).
        self._kv_byte_ratio = kv_byte_ratio(self.args.kv_dtype)
        self._last_kv_blocks_read = 0
        self._last_device_rounds = 0
        self._last_pp_rounds = 0
        # Cluster-pool peer-pull accounting (kv_pool_* gauges; same
        # counter shape as the jax worker's PeerKvClient).
        from dynamo_tpu.llm.kv_pool import PeerPullStats

        self.peer_stats = PeerPullStats()
        # Streaming disagg mirror (ISSUE 17), same contract as
        # EngineCore.on_chunk_commit: fired as a do_remote_decode
        # sequence commits prefill chunks (done=True at finish). The sim
        # loop runs ON the event loop, so the callback may touch
        # loop-affine state directly — no thread hop needed.
        self.on_chunk_commit = None
        self._spec_default = (
            SpecConfig(
                k=self.args.spec_k, device=self.args.spec_device_draft
            )
            if self.args.spec_decode != "off"
            else None
        )
        # Acceptance simulation: deterministic per engine instance so
        # virtual-clock A/Bs reproduce exactly.
        import random as _random

        self._spec_rng = _random.Random(0x5bec)
        self.spec_stats = SpecStats()
        self.eos_token_ids = set(eos_token_ids)
        self.kv = kv_manager or MockKvManager(
            num_blocks=self.args.num_kv_blocks,
            block_size=self.args.block_size,
            enable_prefix_caching=self.args.enable_prefix_caching,
        )
        # Admission queue: per-tenant DRR over prompt-token cost,
        # mirroring EngineCore.waiting (fair off = exact FIFO, keeping
        # every historical stream bit-identical).
        self._waiting: FairQueue = FairQueue(
            quantum=self.args.fair_quantum or self.args.max_num_batched_tokens,
            fair=self.args.fair_scheduling,
            cost_fn=lambda s: len(s.prompt),
        )
        self._running: list[_Seq] = []
        # Deadline clock — injectable so virtual-clock drivers (bench
        # run_overload_ab, fairness tests) expire queued requests on the
        # simulated timeline instead of the wall.
        self.clock = time.time
        self._wakeup = asyncio.Event()
        self._loop_task: asyncio.Task | None = None
        self._iterations = 0
        # Chaos: the engine.step injection point fires once per sim
        # iteration, targeted by this tag (run_mocker sets it to the
        # worker id). A `kill` action leaves the loop dead — in-flight
        # streams stop producing, which is exactly the wedged-worker
        # shape the client-side stall deadline exists to catch.
        self.chaos_tag = ""
        self._dead = False
        # Crash/stall flight recorder (ISSUE 13): one record per sim
        # iteration with decode/prefill work — step shape, lane cursors,
        # timestamps — dumped to a redacted artifact on chaos kill /
        # stall / drain. run_mocker renames it to the worker id.
        from dynamo_tpu.obs.flight_recorder import FlightRecorder

        self.flight = FlightRecorder(f"mock-{id(self) & 0xFFFF:04x}")
        self._tracer = tracing.get_tracer("engine")
        # Queue-wait stat spans under their own service (the waterfall
        # sched_admit twin in _trace_phases is service "engine"; sharing
        # the key would double-observe the histogram — same split as
        # EngineCore._mark_first_sched).
        self._sched_tracer = tracing.get_tracer("sched")
        # Scheduler gauges, mirroring EngineCore.sched_stats (the status
        # server exports the same series for real and mock workers).
        # The mocker never truly preempts (release + re-queue) — a decode
        # blocked on allocation just stalls one iteration — so stalls are
        # counted separately, not as preemptions.
        # Admission-time prefix-cache accounting, mirroring
        # EngineCore._admit (kv_prefix_cache_admitted_* gauges).
        self._admit_prefix_queries = 0
        self._admit_prefix_hits = 0
        self.sched_stats = {
            "preemptions": 0,
            "decode_stalls": 0,
            "mixed_steps": 0,
            "last_step_batched_tokens": 0,
            "last_step_budget_utilization": 0.0,
            "chunked_prefills_in_flight": 0,
            # Megastep observability, mirroring EngineCore.exec_stats:
            # iterations that fused k > 1 decode steps under one dispatch
            # overhead vs everything else, plus emitted tokens (the
            # dispatches_per_token gauge divides these).
            "dispatches": 0,
            "megastep_dispatches": 0,
            "single_step_dispatches": 0,
            "committed_tokens": 0,
            # Universal megastep (ISSUE 12), mirroring EngineCore:
            # dispatches that fused mixed/verify work, and (real-engine
            # only — the mocker never truncates a watch) batches forced
            # to k=1 by the device stop-watch overflow.
            "fused_mixed_dispatches": 0,
            "megastep_forced_single": 0,
            # Pipeline parallelism (ISSUE 20), mirroring EngineCore:
            # decode dispatches that fused k > 1 wavefront iterations
            # across the pipe vs the single-iteration (bubble-per-token)
            # fallback. Both 0 when pp == 1.
            "pp_fused_dispatches": 0,
            "pp_forced_single": 0,
            # Overload counters (ISSUE 10), mirroring EngineCore.
            "shed_total": 0,
            "deadline_expired_total": 0,
        }

    # -- public engine surface --------------------------------------------

    async def generate(self, request: dict, context: Context) -> AsyncIterator[dict]:
        """Handler-compatible: wire dict in, wire dicts out."""
        if request.get("clear_kv_blocks"):
            # Admin clear: unpinned cache only; the kv manager's
            # on_removed callback carries the router events.
            cleared = self.kv.clear_unpinned()
            yield {"cleared_blocks": len(cleared), "finish_reason": "stop"}
            return
        if request.get("embed"):
            # Deterministic synthetic embedding (seeded by content) so
            # /v1/embeddings works against mocker fleets in tests, like
            # every other surface (reference mocker philosophy).
            import numpy as _np

            token_ids = list(request["token_ids"])
            rng = _np.random.RandomState(abs(hash(tuple(token_ids))) % (2**31))
            vec = rng.randn(64).astype(float)
            yield {
                "embedding": [float(x) for x in vec],
                "prompt_tokens": len(token_ids),
                "finish_reason": "stop",
            }
            return
        pre = PreprocessedRequest.from_wire(request)
        limit = self.args.max_waiting
        if limit and len(self._waiting) >= limit:
            # Bounded admission queue (backpressure): the typed shed
            # error serializes as a retry-elsewhere err frame, exactly
            # like EngineCore's — migration moves the request to a
            # less-loaded worker.
            self.sched_stats["shed_total"] += 1
            self.flight.record_event(
                "shed_queue_full", rid=pre.request_id or context.id,
                waiting=len(self._waiting), limit=limit,
            )
            raise EngineOverloadedError(
                f"scheduler queue full ({limit} requests waiting); "
                f"retry on another instance"
            )
        max_tokens = pre.stop.max_tokens or 16
        seq = _Seq(
            request_id=pre.request_id or context.id,
            prompt=list(pre.token_ids),
            max_tokens=max_tokens,
            out=asyncio.Queue(),
            seq=TokenBlockSequence(pre.token_ids, self.args.block_size),
            prompt_hashes=compute_seq_hashes(pre.token_ids, self.args.block_size),
            stop=pre.stop,
            replay_base=pre.replayed_tokens,
            tenant_id=pre.tenant_id or "",
            priority=pre.priority or 0,
            notify_chunks=bool(
                (pre.kv_transfer_params or {}).get("do_remote_decode")
            ),
        )
        if pre.deadline_epoch is not None:
            seq.deadline_epoch = pre.deadline_epoch
        elif pre.deadline_ms is not None and pre.deadline_ms > 0:
            seq.deadline_epoch = self.clock() + pre.deadline_ms / 1000.0
        spec = resolve_spec_config(
            self._spec_default, pre.spec_decode, self.args.spec_k
        )
        seq.spec_k = spec.k if spec is not None else 0
        seq.spec_device = spec.device if spec is not None else False
        seq.t_submit = time.time()
        self._waiting.append(seq)
        self._ensure_loop()
        self._wakeup.set()
        try:
            while True:
                # Engine-local queue; a chaos-killed loop parks this
                # deliberately (the client stall deadline catches it).
                # dynalint: unbounded-ok — engine-local queue
                item = await seq.out.get()
                if item is self._FINISHED:
                    return
                shed = item.get("meta", {}).get("shed") if isinstance(item, dict) else None
                if shed == "deadline":
                    # Expired while queued: typed, clean, never a
                    # half-stream (mirrors TpuEngine.generate).
                    from dynamo_tpu.runtime.engine import DeadlineExceededError

                    raise DeadlineExceededError(
                        item["meta"].get("detail", "deadline exceeded in queue")
                    )
                yield item
                if context.is_stopped:
                    seq.cancelled = True
                    return
        finally:
            seq.cancelled = True
            self._trace_phases(seq, context)

    def _trace_phases(self, seq: _Seq, context: Context) -> None:
        """Emit the request's prefill/decode spans from the timestamps the
        sim loop stamped; parented through the dataplane headers so they
        stitch under the frontend's root span."""
        headers = context.headers
        if seq.t_first_sched:
            # Queue-wait attribution (admit -> first chunk), mirroring the
            # real engine's sched_admit span.
            self._tracer.record(
                "sched_admit", seq.t_submit, seq.t_first_sched, headers=headers,
                attrs={
                    "request_id": seq.request_id,
                    "prompt_tokens": len(seq.prompt),
                    "tenant": seq.tenant_id or "default",
                },
            )
        if seq.t_prefill_done:
            self._tracer.record(
                "prefill", seq.t_submit, seq.t_prefill_done, headers=headers,
                attrs={
                    "request_id": seq.request_id,
                    "prompt_tokens": len(seq.prompt),
                    "cached_tokens": seq.cached_blocks * self.args.block_size,
                    "tenant": seq.tenant_id or "default",
                },
            )
        if seq.generated and seq.t_last_token and seq.t_prefill_done:
            self._tracer.record(
                "decode", seq.t_prefill_done, seq.t_last_token, headers=headers,
                attrs={
                    "request_id": seq.request_id,
                    "tokens": seq.generated,
                    "tenant": seq.tenant_id or "default",
                },
            )

    def scheduler_stats(self) -> dict:
        """Point-in-time scheduler gauges (status-server /metrics export);
        same keys as EngineCore.scheduler_stats."""
        st = dict(self.sched_stats)
        st["waiting"] = len(self._waiting)
        st["running"] = len(self._running)
        st["chunked_scheduling"] = 1 if self.args.scheduling == "chunked" else 0
        st["token_budget"] = self.args.max_num_batched_tokens
        st["async_exec"] = 1 if self.args.async_exec else 0
        st["queue_limit"] = self.args.max_waiting
        st["fair_enabled"] = 1 if self.args.fair_scheduling else 0
        st["megastep_k"] = self.args.megastep_k
        # Pipe occupancy, mirroring EngineCore.scheduler_stats: k*M
        # wavefront work items over k*M + pp - 1 rounds (M = pp
        # microbatch groups); 1.0 when pp is off.
        st["pp_stages"] = self.args.pp
        km = max(1, self.args.megastep_k) * self.args.pp
        st["pp_pipe_occupancy"] = km / (km + self.args.pp - 1)
        toks = self.sched_stats["committed_tokens"]
        st["dispatches_per_token"] = (
            self.sched_stats["dispatches"] / toks if toks else 0.0
        )
        return st

    def spec_decode_stats(self) -> dict:
        """Speculation gauges, same keys as EngineCore.spec_decode_stats
        (the status server exports identical series for real and mock
        workers)."""
        st = self.spec_stats.as_dict()
        st["enabled"] = 1 if self._spec_default is not None else 0
        return st

    def kv_cache_stats(self) -> dict:
        """Prefix-cache gauges, same keys as EngineCore.kv_cache_stats:
        ``prefix_*`` are match_prefix probe counters, ``admitted_*`` count
        admitted sequences whose prefix was served from cache.
        bytes_per_block uses the mocker's nominal llama3-8b geometry
        (L=32, n_kv=8, d=128) so the dtype capacity delta is observable
        on /metrics just like a real worker's."""
        from dynamo_tpu.engine.kv_quant import kv_page_bytes

        st = self.kv.stats
        return {
            "kv_dtype": self.args.kv_dtype,
            "kv_dtype_int8": 1 if self.args.kv_dtype == "int8" else 0,
            "bytes_per_block": kv_page_bytes(
                32, self.args.block_size, 8, 128, self.args.kv_dtype
            ),
            "capacity_blocks": self.kv.capacity,
            "resident_blocks": self.kv.used_blocks,
            "prefix_queries": st.prefix_queries,
            "prefix_hits": st.prefix_hits,
            "prefix_hit_rate": (
                st.prefix_hits / st.prefix_queries if st.prefix_queries else 0.0
            ),
            "admitted_queries": self._admit_prefix_queries,
            "admitted_hits": self._admit_prefix_hits,
            "admitted_hit_rate": (
                self._admit_prefix_hits / self._admit_prefix_queries
                if self._admit_prefix_queries
                else 0.0
            ),
        }

    def fair_queue_stats(self) -> dict[str, dict[str, float]]:
        """Per-tenant queue depth + DRR deficit snapshot, same shape as
        EngineCore.fair_queue_stats (status-server tenant gauges)."""
        return self._waiting.stats()

    # -- cluster KV pool mirror (ISSUE 11) ---------------------------------

    def import_peer_blocks(
        self, hashes: list[int], parents: list[int | None]
    ) -> tuple[int, float]:
        """Register peer-pulled block hashes as locally cached and price
        the transfer: returns (blocks imported, virtual-clock seconds the
        pull costs). The cost models the dataplane copy of the canonical
        packed buffer — per-block microseconds x the kv_dtype byte ratio
        (int8 ≈ 0.52x) — so shared-prefix TTFT A/Bs carry the transfer
        price, not just the win. Token values never change: an imported
        prefix only turns recompute into a prefix-cache hit."""
        from dynamo_tpu.engine.kv_quant import kv_page_bytes

        imported = 0
        for h, parent in zip(hashes, parents):
            if self.kv.import_block(h, parent):
                imported += 1
        cost_s = (
            imported
            * self.args.kv_pull_us_per_block
            * self._kv_byte_ratio
            / 1e6
            / self.args.speedup_ratio
        )
        self.peer_stats.blocks_pulled += imported
        self.peer_stats.bytes_pulled += imported * kv_page_bytes(
            32, self.args.block_size, 8, 128, self.args.kv_dtype
        )
        return imported, cost_s

    def kv_pool_stats(self) -> dict:
        """kv_pool_* gauge payload, same keys as the jax worker's
        PeerKvClient.pool_stats() + KvEventPublisher.stats() merge (the
        publisher half is merged in by run_mocker, which owns it)."""
        return self.peer_stats.as_dict()

    def metrics(self) -> ForwardPassMetrics:
        return ForwardPassMetrics(
            worker=WorkerStats(
                request_active_slots=len(self._running),
                request_total_slots=self.args.max_num_seqs,
                num_requests_waiting=len(self._waiting),
                queue_limit=self.args.max_waiting,
                requests_shed_total=(
                    self.sched_stats["shed_total"]
                    + self.sched_stats["deadline_expired_total"]
                ),
                budget_utilization=self.sched_stats[
                    "last_step_budget_utilization"
                ],
            ),
            kv=KvStats(
                kv_active_blocks=self.kv.used_blocks,
                kv_total_blocks=self.kv.capacity,
                gpu_cache_usage_perc=self.kv.usage_perc,
                gpu_prefix_cache_hit_rate=(
                    self.kv.stats.prefix_hits / self.kv.stats.prefix_queries
                    if self.kv.stats.prefix_queries
                    else 0.0
                ),
            ),
            spec_decode=(
                self.spec_decode_stats()
                if self._spec_default is not None or self.spec_stats.verify_rows
                else None
            ),
            # Measured per-peer pull cost (NetKV): routers read this to
            # weigh decode placement / peer hints by real transfer cost.
            net=self.peer_stats.net_dict() or None,
        )

    # -- simulation loop ---------------------------------------------------

    def _ensure_loop(self) -> None:
        if self._dead:
            return  # chaos-killed: stays dead until the process restarts
        if self._loop_task is None or self._loop_task.done():
            self._loop_task = asyncio.create_task(self._sim_loop())

    def iter_time_s(
        self, prefill_tokens: int, decode_seqs: int, kv_blocks_read: int = 0,
        device_rounds: int = 0, pp_rounds: int = 0,
    ) -> float:
        """Virtual-clock cost of one iteration under the overlap model:
        with async execution, the fixed host overhead runs one step ahead
        and hides under device compute (bounded by the larger term). The
        uncovered remainder is recorded as the ``host_gap`` stat. NOTE on
        semantics: the mocker's span is the model's DEVICE-IDLE time per
        iteration (it knows the split exactly), while the real engine's
        ``host_gap`` is the wall-clock gap between consecutive dispatch
        enqueues (it cannot see device occupancy) — same name, related
        but not identical quantities; compare trends, not absolutes.

        ``kv_blocks_read`` prices the DMA-bound decode KV traffic
        (resident blocks read per lane-iteration), scaled by the
        configured kv_dtype's byte ratio — int8 halves this term, which
        is exactly the int8-page win bench.py run_kvquant_ab measures."""
        host_s = self.args.base_iter_us / 1e6
        device_s = (
            prefill_tokens * self.args.prefill_us_per_token
            + decode_seqs * self.args.decode_us_per_seq
            + kv_blocks_read
            * self.args.kv_read_us_per_block
            * self._kv_byte_ratio
            # On-device draft rounds: ring match + gather between inner
            # iterations (ISSUE 18) — device-side work, so it hides
            # nothing and overlaps with nothing extra.
            + device_rounds * knobs.get_float("DYN_SPEC_DRAFT_ROUND_US")
            # Pipeline stage hops (ISSUE 20): each ppermute boundary
            # crossing a decode dispatch paid this iteration, bubble
            # included — device-side collective time, same overlap
            # behaviour as the draft rounds above.
            + pp_rounds * knobs.get_float("DYN_PP_HOP_US")
        ) / 1e6
        if self.args.async_exec:
            total = max(host_s, device_s)
            gap = max(0.0, host_s - device_s)
        else:
            total = host_s + device_s
            gap = host_s
        now = time.time()
        self._tracer.record(
            "host_gap", now - gap, now,
            attrs={"overlapped": self.args.async_exec}, stat=True,
        )
        return total / self.args.speedup_ratio

    async def _sim_loop(self) -> None:
        while True:
            if not self._waiting and not self._running:
                self._wakeup.clear()
                await self._wakeup.wait()
            if chaos.active():
                try:
                    # stall: wedged loop, streams freeze, socket stays up;
                    # kill: the loop dies for good (worker-crash twin).
                    await chaos.inject("engine.step", self.chaos_tag)
                except chaos.ChaosKill:
                    log.warning(
                        "chaos: engine loop killed (tag=%r, %d in flight)",
                        self.chaos_tag, len(self._running),
                    )
                    self._dead = True
                    # Post-mortem (ISSUE 13): the victim's final steps
                    # dump to a redacted artifact before the loop dies —
                    # chaos tests reconstruct the killed worker's last
                    # megasteps from it.
                    from dynamo_tpu.obs import flight_recorder

                    flight_recorder.dump_all("chaos_kill", self.chaos_tag)
                    return
            self._admit()
            prefill_tokens, decode_seqs = self._step()
            self._iterations += 1
            await asyncio.sleep(
                self.iter_time_s(
                    prefill_tokens, decode_seqs, self._last_kv_blocks_read,
                    self._last_device_rounds, self._last_pp_rounds,
                )
            )

    def _sweep_queue(self) -> None:
        """Queue hygiene ahead of admission, mirroring EngineCore:
        cancelled requests leave from ANY queue position; queued
        requests past their deadline get the typed shed frame (the
        generate loop raises it as DeadlineExceededError). Queued
        sequences hold no pins or partials, so removal is the whole
        cleanup."""
        now = self.clock()

        def dead(s: _Seq) -> bool:
            # ONE combined pass per iteration (cancel + expiry),
            # mirroring EngineCore._sweep_queue.
            return s.cancelled or (
                s.deadline_epoch is not None
                and now > s.deadline_epoch
                and s.generated == 0
            )

        swept = self._waiting.sweep(dead)
        for seq in swept:
            if seq.cancelled:
                self._finish(seq, emit=False)
        expired = [s for s in swept if not s.cancelled]
        for seq in expired:
            self.sched_stats["deadline_expired_total"] += 1
            self.flight.record_event(
                "deadline_expired", rid=seq.request_id,
                tenant=seq.tenant_id or "default",
            )
            waited_ms = (now - seq.t_submit) * 1e3 if seq.t_submit else 0.0
            out = LLMEngineOutput(
                token_ids=[], finish_reason="error",
                prompt_tokens=len(seq.prompt), completion_tokens=0,
            )
            out.meta = {
                "shed": "deadline",
                "detail": (
                    f"request {seq.request_id} expired after "
                    f"{waited_ms:.0f} ms in the scheduler queue"
                ),
            }
            seq.out.put_nowait(out.to_wire())
            self._finish(seq, emit=False)

    def _admit(self) -> None:
        self._sweep_queue()
        watermark_blocks = self.args.watermark * self.kv.capacity
        while self._waiting and len(self._running) < self.args.max_num_seqs:
            # DRR head (FIFO with fairness off / one tenant); pop() on
            # successful admission charges the tenant's deficit.
            seq = self._waiting.head()
            cached = self.kv.acquire_cached(seq.prompt_hashes)
            to_commit = len(seq.prompt_hashes) - cached
            trailing = 1 if len(seq.prompt) % self.args.block_size else 0
            need = to_commit + trailing
            if self.kv.free_blocks - need < watermark_blocks and self._running:
                # Not enough headroom; un-pin and retry next iteration.
                self.kv.release(seq.prompt_hashes[:cached])
                return
            try:
                self.kv.allocate_partial(need) if need else None
            except InsufficientBlocksError:
                self.kv.release(seq.prompt_hashes[:cached])
                return
            self._waiting.pop()
            # Admission-time prefix accounting (one query per ADMITTED
            # sequence), mirroring EngineCore._admit — DEDICATED counters,
            # never the kv manager's match_prefix probe counters.
            self._admit_prefix_queries += 1
            if cached:
                self._admit_prefix_hits += 1
            seq.cached_blocks = cached
            seq.pinned = list(seq.prompt_hashes[:cached])
            seq.partials_held = need
            seq.prefilled = cached * self.args.block_size
            if seq.prefill_done:  # fully prefix-cached: no prefill phase
                self._mark_first_sched(seq)
                seq.t_prefill_done = seq.t_first_sched
            self._running.append(seq)

    def _mark_first_sched(self, seq: _Seq) -> None:
        """Close the admit→first-schedule window as a sched_admit stat
        span (cache hits included — the queue-wait histogram must cover
        the fast cohort too, mirroring EngineCore._mark_first_sched)."""
        if seq.t_first_sched:
            return
        seq.t_first_sched = time.time()
        self._sched_tracer.record(
            "sched_admit", seq.t_submit, seq.t_first_sched,
            attrs={
                "request_id": seq.request_id,
                "prompt_tokens": len(seq.prompt),
            },
            stat=True,
        )

    def _step(self) -> tuple[int, int]:
        """One engine iteration; returns (prefill tokens, decoding seqs).

        scheduling='chunked': prefill chunks (capped at prefill_chunk) and
        decode rows share the max_num_batched_tokens budget in the same
        iteration. scheduling='waves': while ANY prompt is prefilling,
        the iteration is prefill-only (monolithic, budget-bound) and every
        in-flight decode stalls — the real engine's wave scheduler."""
        budget = self.args.max_num_batched_tokens
        chunk_cap = self.args.prefill_chunk or budget
        any_prefill = any(
            not s.prefill_done and not s.cancelled for s in self._running
        )
        prefill_only = self.args.scheduling == "waves" and any_prefill
        # UNIVERSAL megastep (ISSUE 12, mirroring the real engine):
        # every iteration with decode work fuses — prefill chunks ride
        # the same priced dispatch and spec verify lanes resolve
        # accept/reject inside it, so mixed traffic no longer forces
        # k=1. k caps at the batch's largest remaining budget, like
        # EngineCore._chain_length. (waves scheduling still stalls
        # decodes during a wave via prefill_only — nothing to fuse.)
        k_mega = 1
        if self.args.megastep_k > 1 and not prefill_only:
            remaining = [
                max(1, s.max_tokens - s.generated)
                for s in self._running
                if s.prefill_done and not s.cancelled
            ]
            if remaining:
                k_mega = min(self.args.megastep_k, max(remaining))
        mega_lanes = 0
        mega_verify_lanes = 0
        mega_device_lanes = 0
        device_draft_tokens = 0  # priced like prefill tokens, not budgeted
        device_rounds_step = 0   # DYN_SPEC_DRAFT_ROUND_US each on the clock
        chunk_rows = 0
        tokens_emitted = 0
        prefill_tokens = 0
        decode_seqs = 0
        kv_blocks_read = 0  # resident blocks read by decode lane-iterations
        # Simulated verify accounting: drafted tokens are priced like
        # prefill tokens (each is one extra target forward in the verify
        # row) and count against the shared step budget.
        spec_tokens = 0
        spec_rows = spec_drafted = spec_accepted = spec_emitted = 0
        finished: list[_Seq] = []
        # Flight-recorder lane cursors for this iteration (counts only —
        # the dump artifact is redacted by contract, never token values).
        lane_records: list[dict] = []

        for seq in self._running:
            if seq.cancelled:
                finished.append(seq)
                continue
            if not seq.prefill_done:
                if not self.args.enable_chunked_prefill and prefill_tokens:
                    continue  # one prefill at a time without chunking
                chunk = min(
                    len(seq.prompt) - seq.prefilled,
                    budget - prefill_tokens - spec_tokens,
                )
                if not prefill_only:
                    chunk = min(chunk, chunk_cap)  # chunked: stream the prompt
                if chunk <= 0:
                    continue
                self._mark_first_sched(seq)
                chunk_rows += 1
                start_block = seq.prefilled // self.args.block_size
                seq.prefilled += chunk
                prefill_tokens += chunk
                lane_records.append(
                    {
                        "rid": seq.request_id, "kind": "chunk",
                        "chunk": chunk, "prefilled": seq.prefilled,
                        "prompt": len(seq.prompt),
                    }
                )
                end_block = seq.prefilled // self.args.block_size
                for i in range(max(start_block, seq.cached_blocks), end_block):
                    h = seq.prompt_hashes[i]
                    parent = seq.prompt_hashes[i - 1] if i else None
                    self.kv.commit_block(h, parent)
                    seq.partials_held -= 1
                    seq.pinned.append(h)
                if (
                    seq.notify_chunks
                    and self.on_chunk_commit is not None
                    and end_block > max(start_block, seq.cached_blocks)
                ):
                    # Absolute cursor: blocks [0, end_block) are all in
                    # cache now (cached prefix included). done rides
                    # _finish, mirroring EngineCore.
                    self.on_chunk_commit(seq.request_id, end_block, False)
                if seq.prefill_done:
                    seq.t_prefill_done = time.time()
                continue
            if prefill_only:
                continue  # waves: decodes stall for the whole wave

            # Decode: one token per iteration — or a UNIVERSAL MEGASTEP
            # of up to k_mega fused inner iterations under one dispatch
            # overhead. A speculating lane's verify row resolves inside
            # the fused iteration: it emits (1 + accepted) tokens for
            # iteration 0 plus one per remaining inner iteration,
            # mirroring the real engine's on-device accept/reject +
            # scanned continuation. Token VALUES are unchanged in every
            # mode: the stream is bit-identical, only the chunking and
            # the virtual clock move.
            inner = k_mega
            decode_seqs += inner  # lane-iterations: device term prices
            #                       masked no-ops too, like the real scan
            # KV traffic term: each lane-iteration's attention reads the
            # lane's whole resident context (DMA-bound decode).
            lane_blocks = inner * (
                -(-(seq.prefilled + seq.generated) // self.args.block_size)
            )
            kv_blocks_read += lane_blocks
            dev_lane = bool(seq.spec_k and seq.spec_device and inner > 1)
            if inner > 1:
                mega_lanes += 1
                if dev_lane:
                    mega_device_lanes += 1
                elif seq.spec_k:
                    mega_verify_lanes += 1
            if dev_lane:
                # ON-DEVICE DRAFTING (ISSUE 18): round 0 emits one token;
                # each later inner iteration drafts up to spec_k fresh
                # tokens from the history ring (clamped by the remaining
                # generation budget, like the device kc clamp) and emits
                # accepted + 1 — accepted depth compounds INSIDE the one
                # priced dispatch. Drafted tokens price like prefill
                # tokens but do NOT consume max_num_batched_tokens (the
                # ring lives on device; the plan charges one base token,
                # like the real engine).
                emitted = []
                finish = None
                stalled = False
                lane_rounds = lane_hits = 0
                lane_drafted = lane_accepted = 0
                for r in range(inner):
                    if r == 0:
                        n_emit = 1
                    else:
                        d_j = min(
                            seq.spec_k,
                            max(0, seq.max_tokens - seq.generated - 1),
                        )
                        a_j = 0
                        for _ in range(d_j):
                            if (
                                self._spec_rng.random()
                                >= self.args.spec_acceptance_rate
                            ):
                                break
                            a_j += 1
                        n_emit = a_j + 1
                        lane_rounds += 1
                        if d_j:
                            lane_hits += 1
                            lane_drafted += d_j
                            lane_accepted += a_j
                            self.spec_stats.observe_row(d_j, a_j)
                    for _ in range(n_emit):
                        token = 97 + ((seq.replay_base + seq.generated) % 26)
                        if len(self.seq_tail(seq)) == 0:
                            try:
                                self.kv.allocate_partial(1)
                                seq.partials_held += 1
                            except InsufficientBlocksError:
                                stalled = not emitted
                                break
                        completed = seq.seq.append(token)
                        if completed is not None:
                            self.kv.commit_block(
                                completed.block_hash, completed.parent_hash
                            )
                            seq.partials_held -= 1
                            seq.pinned.append(completed.block_hash)
                        seq.generated += 1
                        emitted.append(token)
                        finish = self._check_stop(seq, token)
                        if finish is not None:
                            break
                    if stalled or finish is not None:
                        break
                if stalled:
                    decode_seqs -= inner
                    kv_blocks_read -= lane_blocks
                    mega_lanes -= 1
                    mega_device_lanes -= 1
                    self.sched_stats["decode_stalls"] += 1
                    continue
                tokens_emitted += len(emitted)
                lane_records.append(
                    {
                        "rid": seq.request_id, "kind": "device",
                        "emitted": len(emitted), "generated": seq.generated,
                        "inner": inner, "rounds": lane_rounds,
                        "finish": finish or "",
                    }
                )
                device_draft_tokens += lane_drafted
                device_rounds_step += lane_rounds
                self.spec_stats.device_rounds += lane_rounds
                self.spec_stats.device_hits += lane_hits
                spec_rows += 1
                spec_drafted += lane_drafted
                spec_accepted += lane_accepted
                spec_emitted += len(emitted)
                out = LLMEngineOutput(token_ids=emitted)
                if seq.generated == len(emitted):
                    out.meta = {
                        "cached_tokens": (
                            seq.cached_blocks * self.args.block_size
                        ),
                        "iteration": self._iterations,
                    }
                seq.t_last_token = time.time()
                if finish is not None:
                    out.finish_reason = finish
                    out.prompt_tokens = len(seq.prompt)
                    out.completion_tokens = seq.generated
                    if seq.notify_chunks:
                        out.kv_transfer_params = {
                            "request_id": seq.request_id
                        }
                    seq.out.put_nowait(out.to_wire())
                    finished.append(seq)
                else:
                    seq.out.put_nowait(out.to_wire())
                continue
            drafted = min(
                seq.spec_k, max(0, budget - prefill_tokens - spec_tokens)
            )
            accepted = 0
            for _ in range(drafted):
                if self._spec_rng.random() >= self.args.spec_acceptance_rate:
                    break
                accepted += 1
            emitted: list[int] = []
            finish = None
            stalled = False
            for _ in range((1 + accepted) + (inner - 1) if seq.spec_k else inner):
                # 'a'..'z' cycle (ByteTokenizer); replay_base keeps a
                # migrated continuation on the original cycle position.
                token = 97 + ((seq.replay_base + seq.generated) % 26)
                if len(self.seq_tail(seq)) == 0:
                    # Starting a fresh block mid-decode needs a new partial.
                    try:
                        self.kv.allocate_partial(1)
                        seq.partials_held += 1
                    except InsufficientBlocksError:
                        stalled = not emitted
                        break  # stalled: emit what we have (maybe nothing)
                completed = seq.seq.append(token)
                if completed is not None:
                    self.kv.commit_block(completed.block_hash, completed.parent_hash)
                    seq.partials_held -= 1
                    seq.pinned.append(completed.block_hash)
                seq.generated += 1
                emitted.append(token)
                finish = self._check_stop(seq, token)
                if finish is not None:
                    break
            if stalled:
                decode_seqs -= inner
                kv_blocks_read -= lane_blocks
                if inner > 1:
                    mega_lanes -= 1
                    if seq.spec_k:
                        mega_verify_lanes -= 1
                self.sched_stats["decode_stalls"] += 1
                continue  # stalled this iteration (preemption-lite)
            tokens_emitted += len(emitted)
            lane_records.append(
                {
                    "rid": seq.request_id,
                    "kind": "verify" if drafted else "decode",
                    "emitted": len(emitted), "generated": seq.generated,
                    "inner": inner,
                    "finish": finish or "",
                }
            )
            if drafted:
                # Charge + account the verify row only once it actually
                # ran (the real engine drops the draft under block
                # pressure the same way — a stalled lane must not skew
                # the clock or the acceptance gauges).
                spec_tokens += drafted
                self.spec_stats.observe_row(drafted, accepted)
                spec_rows += 1
                spec_drafted += drafted
                spec_accepted += accepted
                spec_emitted += len(emitted)
            out = LLMEngineOutput(token_ids=emitted)
            if seq.generated == len(emitted):
                out.meta = {
                    "cached_tokens": seq.cached_blocks * self.args.block_size,
                    "iteration": self._iterations,
                }
            seq.t_last_token = time.time()
            if finish is not None:
                out.finish_reason = finish
                out.prompt_tokens = len(seq.prompt)
                out.completion_tokens = seq.generated
                if seq.notify_chunks:
                    # Disagg reply contract: the decode side pulls held
                    # blocks keyed by this id (the worker stamps its
                    # worker_id into the same dict before replying).
                    out.kv_transfer_params = {"request_id": seq.request_id}
                seq.out.put_nowait(out.to_wire())
                finished.append(seq)
            else:
                seq.out.put_nowait(out.to_wire())

        for seq in finished:
            self._running.remove(seq)
            self._finish(seq, emit=True)
        if spec_rows:
            # Draft + verify spans mirror the real engine's (the mocker's
            # draft is free, so the spans share one timestamp pair; what
            # matters for /traces consumers is the accepted-token attrs).
            now = time.time()
            self.spec_stats.verify_steps += 1
            self._tracer.record(
                "spec_draft", now, now,
                attrs={"seqs": spec_rows, "drafted": spec_drafted}, stat=True,
            )
            self._tracer.record(
                "spec_verify", now, now,
                attrs={
                    "seqs": spec_rows, "drafted": spec_drafted,
                    "accepted": spec_accepted, "tokens": spec_emitted,
                },
                stat=True,
            )
        st = self.sched_stats
        if prefill_tokens or decode_seqs or spec_rows:
            st["dispatches"] += 1
            if mega_lanes:
                st["megastep_dispatches"] += 1
                if chunk_rows or mega_verify_lanes:
                    # (Pure device-draft dispatches stay plain fused
                    # decode dispatches, like the real engine — the dd
                    # lanes keep their decode row shape.)
                    # A fused MIXED dispatch (ISSUE 12): prefill chunks
                    # and/or verify rows rode the same priced megastep.
                    st["fused_mixed_dispatches"] += 1
                now = time.time()
                # Same span name + attrs as EngineCore's megastep commit
                # (zero-width on the mocker's free host clock) so /traces
                # consumers and the smoke tool see identical series.
                self._tracer.record(
                    "engine_megastep", now, now,
                    attrs={
                        "seqs": mega_lanes, "inner_steps": k_mega,
                        "tokens": tokens_emitted,
                        "draft_rounds": device_rounds_step,
                        "pp_stages": self.args.pp,
                        "fused_shapes": {
                            "decode": (
                                mega_lanes - mega_verify_lanes
                                - mega_device_lanes
                            ),
                            "chunk": chunk_rows,
                            "verify": mega_verify_lanes,
                            "device": mega_device_lanes,
                        },
                    },
                    stat=True,
                )
            else:
                st["single_step_dispatches"] += 1
        st["committed_tokens"] += tokens_emitted
        if prefill_tokens and decode_seqs:
            st["mixed_steps"] += 1
        batched = prefill_tokens + spec_tokens + decode_seqs
        st["last_step_batched_tokens"] = batched
        st["last_step_budget_utilization"] = batched / budget if budget else 0.0
        st["chunked_prefills_in_flight"] = sum(
            1 for s in self._running if not s.prefill_done and s.t_first_sched
        )
        self._last_kv_blocks_read = kv_blocks_read
        self._last_device_rounds = device_rounds_step
        # Pipeline stage traffic this iteration (ISSUE 20): a decode
        # dispatch wavefronts k_mega iterations over pp stages and pays
        # the fill/drain bubble once — k*pp + pp-1 ppermute hops; a
        # prefill-only dispatch crosses the pipe once (pp + pp-1 hops).
        # With megastep_k=1 the SAME formula is the host-rollback
        # baseline: every token pays its own bubble + base_iter_us.
        pp_rounds_step = 0
        if self.args.pp > 1 and (prefill_tokens or decode_seqs):
            k_pp = k_mega if decode_seqs else 1
            pp_rounds_step = k_pp * self.args.pp + self.args.pp - 1
            if decode_seqs:
                key = "pp_fused_dispatches" if k_mega > 1 else "pp_forced_single"
                st[key] += 1
        self._last_pp_rounds = pp_rounds_step
        if self.flight.capacity and lane_records:
            # One flight-recorder record per iteration with work: step
            # shape + lane cursors (the chaos-kill artifact reconstructs
            # the victim's final megasteps from these). One dict append —
            # no work added to the priced step itself.
            self.flight.record_step(
                i=self._iterations,
                k=k_mega,
                shape={
                    "decode": sum(
                        1 for r in lane_records if r["kind"] == "decode"
                    ),
                    "chunk": chunk_rows,
                    "verify": sum(
                        1 for r in lane_records if r["kind"] == "verify"
                    ),
                    "device": sum(
                        1 for r in lane_records if r["kind"] == "device"
                    ),
                },
                batched=batched,
                emitted=tokens_emitted,
                lanes=lane_records[:64],
                lanes_truncated=len(lane_records) > 64,
                shed_total=st["shed_total"],
                deadline_expired_total=st["deadline_expired_total"],
            )
        # Device-drafted tokens ride the returned prefill-equivalent term
        # (each is one extra target forward in the verify-shaped row) but
        # never entered `batched` — they don't consume the host budget.
        return prefill_tokens + spec_tokens + device_draft_tokens, decode_seqs

    def _check_stop(self, seq: _Seq, token: int) -> str | None:
        reason = seq.stop.check_token(token, seq.generated, self.eos_token_ids)
        if reason is None and seq.generated >= seq.max_tokens:
            reason = "length"  # mocker defaults max_tokens when unset
        return reason

    def seq_tail(self, seq: _Seq) -> list[int]:
        return seq.seq.partial_tokens

    def _finish(self, seq: _Seq, emit: bool) -> None:
        if seq.notify_chunks and self.on_chunk_commit is not None:
            # Final cursor: every full prompt block is committed (the
            # mock cache RETAINS committed blocks after release, which
            # is what makes the decode side's window pulls work — no
            # hold/release plumbing needed in the mirror).
            self.on_chunk_commit(
                seq.request_id, len(seq.prompt) // self.args.block_size, True
            )
        self.kv.release(seq.pinned)
        if seq.partials_held:
            self.kv.release_partial(seq.partials_held)
            seq.partials_held = 0
        if emit:
            seq.out.put_nowait(self._FINISHED)
