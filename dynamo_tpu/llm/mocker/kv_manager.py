"""Paged-KV bookkeeping for the mock engine: prefix caching + LRU eviction.

Faithfully models what a paged-attention engine's cache does — refcounted
active blocks, an inactive LRU pool that *stays cached* until capacity
pressure evicts it, prefix reuse by chained block hash — and surfaces
stored/removed transitions so the mocker emits **real KV events**. This is
what makes router e2e tests meaningful without TPUs.

Capability parity: reference `lib/llm/src/mocker/kv_manager.rs:57` +
`evictor.rs` (LRU), and the block lifecycle of `block_manager.md:1-50`.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable


@dataclass
class _Block:
    block_hash: int
    parent_hash: int | None
    refcount: int = 0


class InsufficientBlocksError(RuntimeError):
    pass


@dataclass
class KvManagerStats:
    stored_events: int = 0
    removed_events: int = 0
    prefix_hits: int = 0
    prefix_queries: int = 0


class MockKvManager:
    def __init__(
        self,
        num_blocks: int,
        block_size: int = 32,
        enable_prefix_caching: bool = True,
        on_stored: Callable[[list[int], int | None], None] | None = None,
        on_removed: Callable[[list[int]], None] | None = None,
    ):
        self.capacity = num_blocks
        self.block_size = block_size
        self.enable_prefix_caching = enable_prefix_caching
        self._active: dict[int, _Block] = {}
        self._inactive: OrderedDict[int, _Block] = OrderedDict()  # LRU, oldest first
        self._partial_in_use = 0  # partial (unhashed) blocks held by sequences
        self.on_stored = on_stored or (lambda hashes, parent: None)
        self.on_removed = on_removed or (lambda hashes: None)
        self.stats = KvManagerStats()

    # -- capacity ----------------------------------------------------------

    @property
    def used_blocks(self) -> int:
        return len(self._active) + len(self._inactive) + self._partial_in_use

    @property
    def free_blocks(self) -> int:
        """Blocks allocatable right now (inactive LRU counts as reclaimable)."""
        return self.capacity - len(self._active) - self._partial_in_use

    @property
    def usage_perc(self) -> float:
        return self.used_blocks / self.capacity if self.capacity else 0.0

    # -- prefix cache ------------------------------------------------------

    def match_prefix(self, seq_hashes: list[int]) -> int:
        """Contiguous leading blocks already cached (active or inactive)."""
        self.stats.prefix_queries += 1
        n = 0
        for h in seq_hashes:
            if h in self._active or h in self._inactive:
                n += 1
            else:
                break
        if n:
            self.stats.prefix_hits += 1
        return n

    # -- allocation --------------------------------------------------------

    def _evict_lru(self) -> bool:
        if not self._inactive:
            return False
        h, _ = self._inactive.popitem(last=False)
        self.stats.removed_events += 1
        self.on_removed([h])
        return True

    def _ensure_headroom(self, blocks_needed: int) -> None:
        while self.capacity - self.used_blocks < blocks_needed:
            if not self._evict_lru():
                raise InsufficientBlocksError(
                    f"need {blocks_needed} blocks, "
                    f"{self.capacity - self.used_blocks} available"
                )

    def acquire_cached(self, seq_hashes: list[int]) -> int:
        """Pin the cached prefix of a sequence; returns blocks pinned."""
        if not self.enable_prefix_caching:
            return 0
        n = 0
        for h in seq_hashes:
            block = self._active.get(h)
            if block is None:
                block = self._inactive.pop(h, None)
                if block is not None:
                    self._active[h] = block
            if block is None:
                break
            block.refcount += 1
            n += 1
        return n

    def allocate_partial(self, count: int = 1) -> None:
        """Reserve space for not-yet-complete blocks (no hash yet)."""
        self._ensure_headroom(count)
        self._partial_in_use += count

    def commit_block(self, block_hash: int, parent_hash: int | None) -> None:
        """A partial block filled up: register it under its hash (emits a
        stored event unless it deduplicates onto an existing block)."""
        assert self._partial_in_use > 0
        self._partial_in_use -= 1
        existing = self._active.get(block_hash)
        if existing is not None:
            existing.refcount += 1
            return
        revived = self._inactive.pop(block_hash, None)
        if revived is not None:
            revived.refcount += 1
            self._active[block_hash] = revived
            return
        self._active[block_hash] = _Block(block_hash, parent_hash, refcount=1)
        self.stats.stored_events += 1
        self.on_stored([block_hash], parent_hash)

    def release_partial(self, count: int) -> None:
        self._partial_in_use -= count
        assert self._partial_in_use >= 0

    def release(self, seq_hashes: list[int]) -> None:
        """Unpin a sequence's complete blocks; zero-ref blocks go to the
        inactive LRU (still cached → still 'stored' for the router)."""
        for h in seq_hashes:
            block = self._active.get(h)
            if block is None:
                continue
            block.refcount -= 1
            if block.refcount <= 0:
                del self._active[h]
                if self.enable_prefix_caching:
                    self._inactive[h] = block
                    self._inactive.move_to_end(h)
                else:
                    self.stats.removed_events += 1
                    self.on_removed([h])

    def held_prefix(self, seq_hashes: list[int]) -> list[int]:
        """Contiguous leading hashes this worker can serve (active or
        inactive), WITHOUT touching the prefix-probe counters — the peer
        kv_fetch server's read, mirroring EngineCore.read_cached_pages'
        'longest locally-held prefix' contract."""
        held: list[int] = []
        for h in seq_hashes:
            if h in self._active or h in self._inactive:
                held.append(h)
            else:
                break
        return held

    def import_block(self, block_hash: int, parent_hash: int | None) -> bool:
        """Register peer-pulled content as cached-but-unpinned (inactive
        LRU) — the mocker twin of DeviceBlockAllocator.register_inactive.
        Returns True when the block was actually imported (False: already
        cached, or the pool cannot make headroom)."""
        if block_hash in self._active or block_hash in self._inactive:
            return False
        try:
            self._ensure_headroom(1)
        except InsufficientBlocksError:
            return False
        self._inactive[block_hash] = _Block(block_hash, parent_hash)
        self._inactive.move_to_end(block_hash)
        self.stats.stored_events += 1
        self.on_stored([block_hash], parent_hash)
        return True

    def snapshot(self) -> list[tuple[int, int | None]]:
        """(hash, parent) for every cached block — the mocker's
        anti-entropy resync inventory (single device tier)."""
        out = [(h, b.parent_hash) for h, b in self._active.items()]
        out += [(h, b.parent_hash) for h, b in self._inactive.items()]
        return out

    def clear_unpinned(self) -> list[int]:
        """Drop only the inactive (unpinned) cache — in-flight sequences
        keep their blocks; emits `removed` for the router. The admin
        clear_kv_blocks semantics (same contract as the real engine's
        allocator.clear_cache)."""
        hashes = list(self._inactive)
        self._inactive.clear()
        if hashes:
            self.stats.removed_events += len(hashes)
            self.on_removed(hashes)
        return hashes

    def clear(self) -> list[int]:
        """Drop the whole cache (reset); returns hashes that were cached."""
        hashes = list(self._active) + list(self._inactive)
        self._active.clear()
        self._inactive.clear()
        self._partial_in_use = 0
        return hashes
