"""Model Deployment Card (MDC): everything a frontend needs to serve a model.

Workers build an MDC at registration time; frontends fetch it via the
control plane's object store and use it to construct the preprocessor,
decoder, and router for that model — no worker round-trip on the request
path.

Capability parity: reference `lib/llm/src/model_card.rs:91,147-236`
(ModelDeploymentCard: tokenizer kind, prompt formatter, context length, kv
block size, migration limit, runtime config; stored in NATS object store;
``mdcsum`` checksum).
"""

from __future__ import annotations

import hashlib
from dataclasses import asdict, dataclass, field
from typing import Any

import msgpack

MDC_BUCKET = "mdc"


@dataclass
class ModelRuntimeConfig:
    """Worker-engine facts the router/planner need (parity:
    `local_model/runtime_config.rs` + vllm main.py:227-247)."""

    total_kv_blocks: int | None = None
    max_num_seqs: int | None = None
    max_num_batched_tokens: int | None = None
    extra: dict[str, Any] = field(default_factory=dict)


@dataclass
class ModelDeploymentCard:
    name: str
    tokenizer: str = "byte"            # "byte" | local HF path
    model_path: str | None = None      # weights location for workers
    model_type: str = "chat"           # "chat" | "completions" | "embedding" | "backend"
    context_length: int = 8192
    kv_block_size: int = 32
    migration_limit: int = 3
    runtime_config: ModelRuntimeConfig = field(default_factory=ModelRuntimeConfig)

    def to_wire(self) -> bytes:
        return msgpack.packb(asdict(self))

    @classmethod
    def from_wire(cls, raw: bytes) -> "ModelDeploymentCard":
        d = msgpack.unpackb(raw, raw=False)
        rc = d.pop("runtime_config", {}) or {}
        return cls(**d, runtime_config=ModelRuntimeConfig(**rc))

    def checksum(self) -> str:
        """mdcsum — content address of the card."""
        return hashlib.blake2b(self.to_wire(), digest_size=16).hexdigest()

    async def publish(self, store) -> str:
        """Store under the object bucket; returns the checksum key."""
        key = self.checksum()
        await store.obj_put(MDC_BUCKET, key, self.to_wire())
        return key

    @classmethod
    async def fetch(cls, store, checksum: str) -> "ModelDeploymentCard":
        raw = await store.obj_get(MDC_BUCKET, checksum)
        if raw is None:
            raise KeyError(f"no model card {checksum}")
        return cls.from_wire(raw)
