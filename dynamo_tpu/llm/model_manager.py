"""Per-model serving state on a frontend: preprocessor + routed pipeline.

The :class:`ModelManager` reacts to discovery events: when a model gains
its first worker it builds the preprocessor (tokenizer from the MDC), the
endpoint client, the KV router (in ``kv`` mode), and the routed pipeline
segment ``MigrationOperator → RouterEgress`` (a runtime/pipeline.py
ServicePipeline — further operators compose in front via
``Migration.build_pipeline``); when its last worker leaves, everything is
torn down. Request handlers look models up here.

Capability parity: reference `lib/llm/src/discovery/model_manager.rs` +
`entrypoint/input/common.rs:216` (build_routed_pipeline: the per-model
pipeline SegmentSource→Preprocessor→Backend→Migration→Router assembled on
model-add; the operator-graph machinery is `runtime/src/pipeline/nodes.rs`).
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass
from typing import Any, AsyncIterator

from dynamo_tpu.llm.discovery import ModelEntry, ModelWatcher
from dynamo_tpu.llm.kv_router.protocols import RouterConfig
from dynamo_tpu.llm.kv_router.publisher import MetricsAggregator
from dynamo_tpu.runtime.worker_monitor import WorkerMonitor
from dynamo_tpu.llm.kv_router.router import KvPushRouter, KvRouter
from dynamo_tpu.llm.migration import Migration
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
from dynamo_tpu.llm.protocols.common import LLMEngineOutput, PreprocessedRequest
from dynamo_tpu.runtime import DistributedRuntime
from dynamo_tpu.runtime.component import EndpointClient

log = logging.getLogger("dynamo_tpu.model_manager")


@dataclass
class ServedModel:
    entry: ModelEntry
    mdc: ModelDeploymentCard
    preprocessor: OpenAIPreprocessor
    client: EndpointClient
    kv_router: KvRouter | None
    push_router: KvPushRouter | None
    migration: Migration
    # Live fleet load (ForwardPassMetrics per worker; ProcessedEndpoints
    # snapshots) — busy-aware routing + planner observation source.
    aggregator: MetricsAggregator | None = None

    async def generate(
        self, pre: PreprocessedRequest, headers: dict[str, str] | None = None
    ) -> AsyncIterator[LLMEngineOutput]:
        """Route a preprocessed request and decode wire chunks, with
        mid-stream migration on worker failure."""
        async for out in self.migration.generate(pre, headers):
            yield out


class ModelManager:
    def __init__(
        self,
        runtime: DistributedRuntime,
        router_mode: str = "kv",  # "kv" | "round_robin" | "random"
        router_config: RouterConfig | None = None,
    ):
        self.runtime = runtime
        self.router_mode = router_mode
        self.router_config = router_config
        self.models: dict[str, ServedModel] = {}
        # Degraded-mode wiring (ISSUE 15): a last-instance lease expiry
        # only defers the model teardown while the model's endpoint
        # client still holds routable instances — quarantine keeps those
        # cached exactly when the DATA plane answered, so "the router can
        # still place requests" is the liveness judgment here.
        self.watcher = ModelWatcher(
            runtime.store, data_plane_live=self._data_plane_live
        )
        self.watcher.on_model_added.append(self._on_added)
        self.watcher.on_model_removed.append(self._on_removed)
        self._model_event = asyncio.Event()

    def _data_plane_live(self, name: str) -> bool:
        served = self.models.get(name)
        return bool(served is not None and served.client.instances)

    async def start(self) -> None:
        await self.watcher.start()

    async def stop(self) -> None:
        await self.watcher.stop()
        for served in self.models.values():
            await served.client.stop()
            if served.kv_router:
                await served.kv_router.stop()
            if served.aggregator:
                await served.aggregator.stop()

    async def _on_added(self, entry: ModelEntry, mdc: ModelDeploymentCard) -> None:
        endpoint = (
            self.runtime.namespace(entry.namespace)
            .component(entry.component)
            .endpoint(entry.endpoint)
        )
        client = await endpoint.client()
        kv_router = None
        push_router = None
        aggregator = None
        if self.router_mode == "kv":
            from dataclasses import replace as _replace

            config = (
                _replace(self.router_config) if self.router_config else RouterConfig()
            )
            if config.block_size is None:
                config.block_size = mdc.kv_block_size
            kv_router = KvRouter(
                self.runtime.store, entry.namespace, entry.component, config
            )
            await kv_router.start()
            monitor = WorkerMonitor(
                self.runtime.store,
                entry.namespace,
                entry.component,
                busy_threshold=config.busy_threshold or 0.95,
                queue_threshold=config.queue_threshold,
            )
            await monitor.start()
            aggregator = monitor.aggregator
            push_router = KvPushRouter(client, kv_router, monitor=monitor)
        migration = Migration(
            client=client,
            push_router=push_router,
            mode=self.router_mode,
            limit=mdc.migration_limit,
        )
        self.models[entry.name] = ServedModel(
            entry=entry,
            mdc=mdc,
            preprocessor=OpenAIPreprocessor(mdc),
            client=client,
            kv_router=kv_router,
            push_router=push_router,
            migration=migration,
            aggregator=aggregator,
        )
        self._model_event.set()
        self._model_event = asyncio.Event()
        log.info("model %r ready (router=%s)", entry.name, self.router_mode)

    async def _on_removed(self, name: str) -> None:
        served = self.models.pop(name, None)
        if served:
            await served.client.stop()
            if served.kv_router:
                await served.kv_router.stop()
            if served.aggregator:
                await served.aggregator.stop()
        log.info("model %r removed", name)

    def get(self, name: str) -> ServedModel | None:
        return self.models.get(name)

    def list_models(self) -> list[ServedModel]:
        return list(self.models.values())

    async def wait_for_model(self, name: str, timeout: float = 30.0) -> ServedModel:
        async def _wait() -> ServedModel:
            while name not in self.models:
                await self._model_event.wait()
            return self.models[name]

        return await asyncio.wait_for(_wait(), timeout)
