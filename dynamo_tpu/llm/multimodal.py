"""Multimodal serving: image parts -> placeholder tokens -> patch
embeddings spliced into the prompt.

Reference parity: the encoder->LLM pipeline in
`/root/reference/examples/multimodal/components/{processor,encode_worker,
worker}.py` — a processor splits image refs out of the chat request, a
separate encode worker turns each image into an embedding tensor handed
to the LLM worker by descriptor, and the engine consumes embeddings in
place of the image's prompt positions. TPU-native shape of each piece:

- **Processor** (`split_images`, used by OpenAIPreprocessor): replaces
  each image content-part with MM_PATCHES placeholder tokens whose ids
  are CONTENT-FINGERPRINT pseudo-tokens (sha256 of the image ref folded
  into the vocab). The ids never reach the embedding table — the engine
  overrides those rows — but they make prefix caching, KV routing, and
  migration work unchanged: two prompts with different images hash to
  different block chains, identical images prefix-hit.
- **Encoder** (`patch_embed`): a deterministic patch-embedding
  projection — bytes -> fixed [MM_PATCHES, patch_dim] patch grid -> a
  seeded Gaussian projection to the model's hidden size. This proves the
  pipeline end to end with zero extra dependencies; a real deployment
  replaces this one function with a vision tower (the surrounding
  descriptor flow is already production-shaped).
- **Transport**: data: URLs carry content inline (the zero-egress
  environment's image source); other refs are fingerprinted as opaque
  bytes. The encode worker holds the tensor and serves it by id
  (backends/encoder), mirroring the reference's NIXL descriptor handoff.
"""

from __future__ import annotations

import base64
import functools
import hashlib

import numpy as np

# Placeholder tokens per image: one fixed-size patch grid (static shapes
# under jit — every image costs the same prompt length).
MM_PATCHES = 16
# Flattened pixels per patch fed to the projection.
PATCH_DIM = 256


def image_ref_fingerprint(ref: str) -> bytes:
    """Stable content fingerprint of an image reference. data: URLs are
    content-addressed by construction; other refs fingerprint the ref
    string itself (a stable proxy — the encoder resolves actual bytes)."""
    return hashlib.sha256(ref.encode()).digest()


def pseudo_tokens(ref: str, vocab_size: int) -> list[int]:
    """MM_PATCHES content-derived placeholder ids (never id 0: the
    engine treats 0 as padding in some buffers)."""
    fp = image_ref_fingerprint(ref)
    out = []
    for i in range(MM_PATCHES):
        h = hashlib.sha256(fp + i.to_bytes(2, "little")).digest()
        out.append(1 + int.from_bytes(h[:8], "little") % (vocab_size - 1))
    return out


def image_bytes(ref: str) -> bytes:
    """Resolve an image ref to raw bytes. Supports inline data: URLs
    (any media type; the payload bytes are what the patch grid folds);
    anything else deterministically expands its fingerprint (zero-egress
    environment — a deployment with network plugs an HTTP fetch here)."""
    if ref.startswith("data:"):
        try:
            _, payload = ref.split(",", 1)
            return base64.b64decode(payload + "=" * (-len(payload) % 4))
        except ValueError:  # malformed data URL (binascii.Error included)
            pass
    return image_ref_fingerprint(ref)


def patch_grid(raw: bytes) -> np.ndarray:
    """Fold arbitrary image bytes into a fixed [MM_PATCHES, PATCH_DIM]
    float grid in [-1, 1] (deterministic; length-independent)."""
    need = MM_PATCHES * PATCH_DIM
    buf = np.zeros(need, np.uint8)
    if raw:
        arr = np.frombuffer(raw, np.uint8)
        reps = -(-need // len(arr))
        buf = np.tile(arr, reps)[:need].copy()
        # Mix in position so repeated byte patterns stay distinguishable.
        buf ^= (np.arange(need) * 131).astype(np.uint8)
    return (buf.astype(np.float32) / 127.5 - 1.0).reshape(MM_PATCHES, PATCH_DIM)


@functools.lru_cache(maxsize=8)
def _projection(hidden_size: int, seed: int) -> np.ndarray:
    """The fixed [PATCH_DIM, h]/sqrt(d) Gaussian — depends only on
    (hidden_size, seed), so it is cached, not re-drawn per request."""
    rng = np.random.RandomState(seed)
    w = rng.standard_normal((PATCH_DIM, hidden_size)).astype(np.float32)
    w /= np.sqrt(PATCH_DIM)  # in place: float32 survives NEP-50 promotion
    w.setflags(write=False)  # cached — callers must not mutate
    return w


def patch_embed(raw: bytes, hidden_size: int, seed: int = 0) -> np.ndarray:
    """The stand-in vision tower: project the patch grid to the model's
    hidden size with a fixed seeded Gaussian ([PATCH_DIM, h] / sqrt(d)).
    float32 [MM_PATCHES, hidden_size]."""
    return patch_grid(raw) @ _projection(hidden_size, seed)


def split_images(messages: list[dict]) -> tuple[list[dict], list[str]]:
    """Processor step: strip image parts out of chat messages, returning
    (text-only messages with inline markers, image refs in order). The
    marker ``\x00img{i}\x00`` survives any tokenizer byte-exactly and is
    later replaced by pseudo-token runs (`splice_pseudo_tokens`)."""
    refs: list[str] = []
    out = []
    for m in messages:
        content = m.get("content")
        if not isinstance(content, list):
            out.append(m)
            continue
        pieces = []
        for part in content:
            ptype = part.get("type")
            if ptype == "image_url" or part.get("image_url"):
                url = (part.get("image_url") or {}).get("url", "")
                pieces.append(f"\x00img{len(refs)}\x00")
                refs.append(url)
            elif part.get("text"):
                pieces.append(part["text"])
        out.append(dict(m, content="".join(pieces)))
    return out, refs


def splice_pseudo_tokens(
    token_ids: list[int],
    refs: list[str],
    vocab_size: int,
    encode,
) -> tuple[list[int], list[list[int]]]:
    """Replace each marker's token run with that image's pseudo tokens;
    returns (token_ids, positions) where positions[i] = [start, count]
    for image i. ``encode`` is the tokenizer's encode callable (markers
    are located by exact token-subsequence search)."""
    positions: list[list[int]] = []
    for i, ref in enumerate(refs):
        marker = encode(f"\x00img{i}\x00")
        start = _find_subseq(token_ids, marker)
        if start < 0:
            raise ValueError(f"image marker {i} lost in tokenization")
        pseudo = pseudo_tokens(ref, vocab_size)
        token_ids = token_ids[:start] + pseudo + token_ids[start + len(marker):]
        positions.append([start, len(pseudo)])
    return token_ids, positions


def _find_subseq(haystack: list[int], needle: list[int]) -> int:
    if not needle:
        return -1
    for i in range(len(haystack) - len(needle) + 1):
        if haystack[i : i + len(needle)] == needle:
            return i
    return -1
