"""Tool-call and reasoning parsers (parity: reference lib/parsers)."""

from dynamo_tpu.llm.parsers.reasoning import (
    GptOssChannelParser,
    REASONING_PARSERS,
    ReasoningSplit,
    StreamingThinkParser,
    ThinkTagParser,
    parse_reasoning,
)
from dynamo_tpu.llm.parsers.tool_calls import (
    PARSERS,
    ParsedMessage,
    ToolCall,
    detect_format,
    parse_tool_calls,
)

__all__ = [
    "GptOssChannelParser",
    "PARSERS",
    "ParsedMessage",
    "REASONING_PARSERS",
    "ReasoningSplit",
    "StreamingThinkParser",
    "ThinkTagParser",
    "ToolCall",
    "detect_format",
    "parse_reasoning",
    "parse_tool_calls",
]
