"""Reasoning parsers: split chain-of-thought from the final answer.

Capability parity: reference `lib/parsers/src/reasoning/*` (deepseek-r1
``<think>`` tags, gpt-oss channel markers). The streaming parser carves an
incremental text stream into (reasoning_delta, content_delta) pairs so the
frontend can emit OpenAI ``reasoning_content`` deltas live.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class ReasoningSplit:
    reasoning_content: str | None
    content: str | None


class ThinkTagParser:
    """DeepSeek-R1 family: ``<think> ... </think> answer``.

    Models sometimes omit the opening tag (the template pre-opens it), so
    a stream that hits ``</think>`` without ``<think>`` counts everything
    before it as reasoning.
    """

    OPEN = "<think>"
    CLOSE = "</think>"

    def parse(self, text: str) -> ReasoningSplit:
        close = text.find(self.CLOSE)
        if close < 0:
            if text.lstrip().startswith(self.OPEN):
                body = text.lstrip()[len(self.OPEN):]
                return ReasoningSplit(reasoning_content=body.strip() or None, content=None)
            return ReasoningSplit(reasoning_content=None, content=text.strip() or None)
        head = text[:close]
        open_idx = head.find(self.OPEN)
        reasoning = head[open_idx + len(self.OPEN):] if open_idx >= 0 else head
        content = text[close + len(self.CLOSE):]
        return ReasoningSplit(
            reasoning_content=reasoning.strip() or None,
            content=content.strip() or None,
        )


class GptOssChannelParser:
    """gpt-oss: ``<|channel|>analysis ...<|channel|>final ...`` — analysis
    channels are reasoning, the final channel is the answer."""

    MARK = "<|channel|>"

    def parse(self, text: str) -> ReasoningSplit:
        if self.MARK not in text:
            return ReasoningSplit(reasoning_content=None, content=text.strip() or None)
        reasoning_parts: list[str] = []
        content_parts: list[str] = []
        for segment in text.split(self.MARK):
            if not segment:
                continue
            name, _, body = segment.partition("\n")
            name = name.strip().lower()
            if name.startswith("final"):
                content_parts.append(body)
            else:
                reasoning_parts.append(body)
        return ReasoningSplit(
            reasoning_content="\n".join(p.strip() for p in reasoning_parts) or None,
            content="\n".join(p.strip() for p in content_parts) or None,
        )


class StreamingThinkParser:
    """Incremental ``<think>`` splitter: feed deltas, get
    (reasoning_delta, content_delta) back without waiting for the end."""

    def __init__(self) -> None:
        self._buf = ""
        self._in_reasoning: bool | None = None  # unknown until tags seen
        self._done_reasoning = False

    def feed(self, delta: str) -> tuple[str, str]:
        self._buf += delta
        reasoning_out: list[str] = []
        content_out: list[str] = []
        while self._buf:
            if self._done_reasoning:
                content_out.append(self._buf)
                self._buf = ""
                break
            if self._in_reasoning is None:
                stripped = self._buf.lstrip()
                if ThinkTagParser.OPEN.startswith(stripped[: len(ThinkTagParser.OPEN)]) and len(
                    stripped
                ) < len(ThinkTagParser.OPEN):
                    break  # maybe a partial "<think"
                if stripped.startswith(ThinkTagParser.OPEN):
                    self._in_reasoning = True
                    self._buf = stripped[len(ThinkTagParser.OPEN):]
                    continue
                self._in_reasoning = False
            if self._in_reasoning:
                close = self._buf.find(ThinkTagParser.CLOSE)
                if close >= 0:
                    reasoning_out.append(self._buf[:close])
                    self._buf = self._buf[close + len(ThinkTagParser.CLOSE):]
                    self._done_reasoning = True
                    continue
                # Hold back a possible partial close tag.
                safe = max(0, len(self._buf) - len(ThinkTagParser.CLOSE) + 1)
                reasoning_out.append(self._buf[:safe])
                self._buf = self._buf[safe:]
                break
            content_out.append(self._buf)
            self._buf = ""
        return "".join(reasoning_out), "".join(content_out)

    def flush(self) -> tuple[str, str]:
        buf, self._buf = self._buf, ""
        if self._done_reasoning or self._in_reasoning is False or self._in_reasoning is None:
            return "", buf
        return buf, ""


REASONING_PARSERS = {
    "deepseek_r1": ThinkTagParser,
    "gpt_oss": GptOssChannelParser,
}


def parse_reasoning(text: str, parser: str) -> ReasoningSplit:
    try:
        return REASONING_PARSERS[parser]().parse(text)
    except KeyError:
        raise ValueError(
            f"unknown reasoning parser {parser!r}; have {sorted(REASONING_PARSERS)}"
        )
