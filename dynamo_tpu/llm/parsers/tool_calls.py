"""Tool-call parsers: extract structured function calls from model output.

Capability parity: reference `lib/parsers/src/tool_calling/parsers.rs`
(hermes / mistral / llama3-json / pythonic / nemotron formats behind one
registry). Each parser splits a completed message into plain content plus
zero or more :class:`ToolCall`s; ``detect_format`` sniffs which family a
model's output uses when the model card doesn't say.
"""

from __future__ import annotations

import ast
import json
import re
import uuid
from dataclasses import dataclass, field


@dataclass
class ToolCall:
    name: str
    arguments: dict
    id: str = field(default_factory=lambda: f"call_{uuid.uuid4().hex[:24]}")

    def to_openai(self) -> dict:
        return {
            "id": self.id,
            "type": "function",
            "function": {"name": self.name, "arguments": json.dumps(self.arguments)},
        }


@dataclass
class ParsedMessage:
    content: str | None
    tool_calls: list[ToolCall] = field(default_factory=list)


def _norm_args(obj: dict) -> dict:
    args = obj.get("arguments", obj.get("parameters", {}))
    if isinstance(args, str):
        try:
            args = json.loads(args)
        except json.JSONDecodeError:
            args = {"_raw": args}
    return args if isinstance(args, dict) else {"_value": args}


def _calls_from_json(value) -> list[ToolCall]:
    items = value if isinstance(value, list) else [value]
    out = []
    for it in items:
        if isinstance(it, dict) and "name" in it:
            out.append(ToolCall(name=it["name"], arguments=_norm_args(it)))
    return out


# -- formats ---------------------------------------------------------------

_HERMES_RE = re.compile(r"<tool_call>\s*(.*?)\s*</tool_call>", re.DOTALL)


def parse_hermes(text: str) -> ParsedMessage:
    """``<tool_call>{"name": ..., "arguments": ...}</tool_call>`` blocks."""
    calls: list[ToolCall] = []
    for m in _HERMES_RE.finditer(text):
        try:
            calls.extend(_calls_from_json(json.loads(m.group(1))))
        except json.JSONDecodeError:
            continue
    content = _HERMES_RE.sub("", text).strip()
    return ParsedMessage(content=content or None, tool_calls=calls)


_MISTRAL_TAG = "[TOOL_CALLS]"


def parse_mistral(text: str) -> ParsedMessage:
    """``[TOOL_CALLS][{...}, ...]`` (mistral/mixtral instruct)."""
    idx = text.find(_MISTRAL_TAG)
    if idx < 0:
        return ParsedMessage(content=text.strip() or None)
    payload = text[idx + len(_MISTRAL_TAG):].strip()
    content = text[:idx].strip()
    try:
        calls = _calls_from_json(json.loads(payload))
    except json.JSONDecodeError:
        return ParsedMessage(content=text.strip() or None)
    return ParsedMessage(content=content or None, tool_calls=calls)


_PYTHON_TAG = "<|python_tag|>"


def parse_llama3_json(text: str) -> ParsedMessage:
    """Llama-3 style: optional ``<|python_tag|>`` then a bare JSON object
    ``{"name": ..., "parameters": ...}`` (possibly ``;``-separated)."""
    body = text
    if _PYTHON_TAG in body:
        body = body.split(_PYTHON_TAG, 1)[1]
    body = body.strip()
    calls: list[ToolCall] = []
    for part in body.split(";"):
        part = part.strip()
        if not part.startswith("{"):
            continue
        try:
            calls.extend(_calls_from_json(json.loads(part)))
        except json.JSONDecodeError:
            continue
    if calls:
        return ParsedMessage(content=None, tool_calls=calls)
    return ParsedMessage(content=text.strip() or None)


_PYTHONIC_RE = re.compile(r"^\s*\[(.+)\]\s*$", re.DOTALL)


def parse_pythonic(text: str) -> ParsedMessage:
    """``[get_weather(city="SF"), search(q="x")]`` (llama-4 / pythonic)."""
    m = _PYTHONIC_RE.match(text.strip())
    if not m:
        return ParsedMessage(content=text.strip() or None)
    try:
        tree = ast.parse(text.strip(), mode="eval")
    except SyntaxError:
        return ParsedMessage(content=text.strip() or None)
    if not isinstance(tree.body, ast.List):
        return ParsedMessage(content=text.strip() or None)
    calls: list[ToolCall] = []
    for el in tree.body.elts:
        if not (isinstance(el, ast.Call) and isinstance(el.func, ast.Name)):
            return ParsedMessage(content=text.strip() or None)
        try:
            args = {kw.arg: ast.literal_eval(kw.value) for kw in el.keywords if kw.arg}
        except ValueError:
            return ParsedMessage(content=text.strip() or None)
        calls.append(ToolCall(name=el.func.id, arguments=args))
    return ParsedMessage(content=None, tool_calls=calls)


_NEMOTRON_RE = re.compile(r"<TOOLCALL>\s*(.*?)\s*</TOOLCALL>", re.DOTALL)


def parse_nemotron(text: str) -> ParsedMessage:
    calls: list[ToolCall] = []
    for m in _NEMOTRON_RE.finditer(text):
        try:
            calls.extend(_calls_from_json(json.loads(m.group(1))))
        except json.JSONDecodeError:
            continue
    content = _NEMOTRON_RE.sub("", text).strip()
    return ParsedMessage(content=content or None, tool_calls=calls)


def parse_json(text: str) -> ParsedMessage:
    """The whole message is one JSON tool call (or a list of them)."""
    body = text.strip()
    try:
        calls = _calls_from_json(json.loads(body))
    except json.JSONDecodeError:
        return ParsedMessage(content=body or None)
    if calls:
        return ParsedMessage(content=None, tool_calls=calls)
    return ParsedMessage(content=body or None)


PARSERS = {
    "hermes": parse_hermes,
    "mistral": parse_mistral,
    "llama3_json": parse_llama3_json,
    "pythonic": parse_pythonic,
    "nemotron": parse_nemotron,
    "json": parse_json,
}


def parse_tool_calls(text: str, parser: str) -> ParsedMessage:
    try:
        return PARSERS[parser](text)
    except KeyError:
        raise ValueError(f"unknown tool parser {parser!r}; have {sorted(PARSERS)}")


def detect_format(text: str) -> str | None:
    """Sniff the tool-call format of a completed message, if any."""
    if "<tool_call>" in text:
        return "hermes"
    if _MISTRAL_TAG in text:
        return "mistral"
    if "<TOOLCALL>" in text:
        return "nemotron"
    if _PYTHON_TAG in text:
        return "llama3_json"
    stripped = text.strip()
    if stripped.startswith("{") and '"name"' in stripped:
        return "json"
    if _PYTHONIC_RE.match(stripped) and "(" in stripped:
        return "pythonic"
    return None
