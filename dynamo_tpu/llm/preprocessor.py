"""OpenAI request preprocessing and response postprocessing.

Request path: OpenAI chat/completion request → chat-template render →
tokenize → :class:`PreprocessedRequest` (sampling + stop conditions
extracted, default max_tokens fitted to context length).

Response path: stream of :class:`LLMEngineOutput` chunks → incremental
detokenize + stop engine (:mod:`dynamo_tpu.llm.detokenizer`) → OpenAI SSE
chunk objects with TTFT-correct first-chunk role delta and final usage.

Capability parity: reference `lib/llm/src/preprocessor.rs:92-328`
(OpenAIPreprocessor: preprocess_request + response transform) and
`preprocessor/prompt.rs` (template render).
"""

from __future__ import annotations

import time
from typing import Any, AsyncIterator

from dynamo_tpu.llm.detokenizer import Decoder
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.protocols.common import LLMEngineOutput, PreprocessedRequest
from dynamo_tpu.llm.protocols.openai import (
    ChatChunkChoice,
    ChatCompletionChunk,
    ChatCompletionRequest,
    ChatDelta,
    CompletionChoice,
    CompletionRequest,
    CompletionResponse,
    Usage,
    new_request_id,
)
from dynamo_tpu.llm.tokenizer import Tokenizer, load_tokenizer


class OpenAIPreprocessor:
    def __init__(self, mdc: ModelDeploymentCard, tokenizer: Tokenizer | None = None):
        self.mdc = mdc
        self.tokenizer = tokenizer or load_tokenizer(mdc.tokenizer)

    # -- request side ------------------------------------------------------

    def preprocess_chat(self, request: ChatCompletionRequest) -> PreprocessedRequest:
        from dynamo_tpu.llm.multimodal import split_images, splice_pseudo_tokens

        messages = [m.model_dump(exclude_none=True) for m in request.messages]
        vocab = getattr(self.tokenizer, "vocab_size", 32000)
        messages, image_refs = split_images(messages)
        prompt = self.tokenizer.apply_chat_template(
            messages, add_generation_prompt=True
        )
        token_ids = self.tokenizer.encode(prompt)
        mm = None
        if image_refs:
            token_ids, positions = splice_pseudo_tokens(
                token_ids, image_refs, vocab, self.tokenizer.encode
            )
            mm = {"images": image_refs, "positions": positions}
        pre = self._build(request, token_ids)
        pre.mm = mm
        return pre

    def preprocess_completion(self, request: CompletionRequest) -> PreprocessedRequest:
        prompt = request.prompt
        if isinstance(prompt, list) and prompt and isinstance(prompt[0], int):
            token_ids = list(prompt)  # pre-tokenized
        elif isinstance(prompt, list):
            token_ids = self.tokenizer.encode("".join(prompt))
        else:
            token_ids = self.tokenizer.encode(prompt)
        return self._build(request, token_ids)

    def _build(self, request: Any, token_ids: list[int]) -> PreprocessedRequest:
        budget = max(1, self.mdc.context_length - len(token_ids))
        stop = request.stop_conditions(default_max_tokens=budget)
        if stop.max_tokens is not None:
            stop.max_tokens = min(stop.max_tokens, budget)
        return PreprocessedRequest(
            model=request.model,
            token_ids=token_ids,
            sampling=request.sampling_options(),
            stop=stop,
            output=request.output_options(),
            router=dict(request.dyn.router),
            annotations=list(request.dyn.annotations),
            spec_decode=(
                dict(request.dyn.spec_decode)
                if request.dyn.spec_decode is not None
                else None
            ),
            priority=request.dyn.priority,
            deadline_ms=request.dyn.deadline_ms,
        )

    def make_decoder(self, pre: PreprocessedRequest) -> Decoder:
        return Decoder(
            self.tokenizer,
            prompt_token_ids=pre.token_ids,
            stop=pre.stop.stop,
            stop_token_ids=pre.stop.stop_token_ids,
            ignore_eos=pre.stop.ignore_eos,
            max_tokens=pre.stop.max_tokens,
            min_tokens=pre.stop.min_tokens,
            skip_special_tokens=pre.output.skip_special_tokens,
        )

    # -- logprob formatting ------------------------------------------------

    def _tok_str(self, tid: int) -> str:
        return self.tokenizer.decode([tid], skip_special_tokens=False)

    def _chat_logprobs(self, entries: list[dict]) -> dict:
        """Engine logprob records -> OpenAI chat ``choices[].logprobs``
        (reference protocol shape: protocols/openai, perf/logprobs.rs)."""
        content = []
        for e in entries:
            s = self._tok_str(e["token_id"])
            content.append(
                {
                    "token": s,
                    "logprob": e["logprob"],
                    "bytes": list(s.encode()),
                    "top_logprobs": [
                        {"token": self._tok_str(t), "logprob": lp}
                        for t, lp in e.get("top", [])
                    ],
                }
            )
        return {"content": content}

    def _completion_logprobs(self, entries: list[dict], text_offset: int) -> dict:
        """OpenAI completions ``logprobs`` block (tokens / token_logprobs /
        top_logprobs / text_offset)."""
        tokens, tlps, tops, offs = [], [], [], []
        for e in entries:
            s = self._tok_str(e["token_id"])
            tokens.append(s)
            tlps.append(e["logprob"])
            tops.append({self._tok_str(t): lp for t, lp in e.get("top", [])})
            offs.append(text_offset)
            text_offset += len(s)
        return {
            "tokens": tokens,
            "token_logprobs": tlps,
            "top_logprobs": tops,
            "text_offset": offs,
        }

    # -- response side -----------------------------------------------------

    async def postprocess_chat_stream(
        self,
        pre: PreprocessedRequest,
        engine_stream: AsyncIterator[LLMEngineOutput],
        request_id: str | None = None,
        include_usage: bool = False,
        on_complete=None,  # called with completion_tokens at stream end
    ) -> AsyncIterator[ChatCompletionChunk]:
        """Engine chunks → OpenAI chat chunks. Ends the moment a stop
        condition fires, even if the engine keeps streaming."""
        rid = request_id or new_request_id("chatcmpl")
        created = int(time.time())
        decoder = self.make_decoder(pre)
        sent_role = False
        finish: str | None = None
        completion_tokens = 0
        cached = 0

        def chunk(
            delta: ChatDelta, finish_reason: str | None = None, logprobs: dict | None = None
        ) -> ChatCompletionChunk:
            return ChatCompletionChunk(
                id=rid,
                created=created,
                model=pre.model,
                choices=[
                    ChatChunkChoice(
                        index=0, delta=delta, finish_reason=finish_reason, logprobs=logprobs
                    )
                ],
            )

        async for out in engine_stream:
            if not sent_role:
                sent_role = True
                yield chunk(ChatDelta(role="assistant", content=""))
            completion_tokens += len(out.token_ids)
            cached = out.meta.get("cached_tokens", cached)
            step = decoder.step_many(out.token_ids)
            lp = self._chat_logprobs(out.logprobs) if out.logprobs else None
            if step.text or lp:
                yield chunk(ChatDelta(content=step.text or ""), logprobs=lp)
            finish = step.finish_reason or out.finish_reason
            if step.finish_reason:
                break
        if not sent_role:
            yield chunk(ChatDelta(role="assistant", content=""))

        from dynamo_tpu.llm.protocols.common import FinishReason

        reason = FinishReason(finish).as_openai() if finish else "stop"
        final = chunk(ChatDelta(), finish_reason=reason)
        if include_usage:
            final.usage = Usage(
                prompt_tokens=len(pre.token_ids),
                completion_tokens=completion_tokens,
                total_tokens=len(pre.token_ids) + completion_tokens,
                prompt_tokens_details={"cached_tokens": cached} if cached else None,
            )
        if on_complete is not None:
            on_complete(completion_tokens)
        yield final

    async def postprocess_completion(
        self,
        pre: PreprocessedRequest,
        engine_stream: AsyncIterator[LLMEngineOutput],
        request_id: str | None = None,
        stream: bool = False,
        on_complete=None,  # called with completion_tokens at stream end
    ) -> AsyncIterator[CompletionResponse]:
        """Engine chunks → completion responses (stream chunks or one final)."""
        rid = request_id or new_request_id("cmpl")
        created = int(time.time())
        decoder = self.make_decoder(pre)
        pieces: list[str] = []
        finish: str | None = None
        completion_tokens = 0
        lp_entries: list[dict] = []
        text_len = 0

        async for out in engine_stream:
            completion_tokens += len(out.token_ids)
            step = decoder.step_many(out.token_ids)
            lp = None
            if out.logprobs:
                lp_entries.extend(out.logprobs)
                lp = self._completion_logprobs(out.logprobs, text_len)
            text_len += len(step.text)
            if step.text or lp:
                if stream:
                    yield CompletionResponse(
                        id=rid,
                        created=created,
                        model=pre.model,
                        choices=[CompletionChoice(text=step.text, logprobs=lp)],
                    )
                else:
                    pieces.append(step.text)
            finish = step.finish_reason or out.finish_reason
            if step.finish_reason:
                break

        from dynamo_tpu.llm.protocols.common import FinishReason

        reason = FinishReason(finish).as_openai() if finish else "stop"
        usage = Usage(
            prompt_tokens=len(pre.token_ids),
            completion_tokens=completion_tokens,
            total_tokens=len(pre.token_ids) + completion_tokens,
        )
        if on_complete is not None:
            on_complete(completion_tokens)
        yield CompletionResponse(
            id=rid,
            created=created,
            model=pre.model,
            choices=[
                CompletionChoice(
                    text="" if stream else "".join(pieces),
                    finish_reason=reason,
                    logprobs=(
                        self._completion_logprobs(lp_entries, 0)
                        if lp_entries and not stream
                        else None
                    ),
                )
            ],
            usage=usage,
        )
