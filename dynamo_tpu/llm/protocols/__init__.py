from dynamo_tpu.llm.protocols.common import (
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)

__all__ = [
    "FinishReason",
    "LLMEngineOutput",
    "PreprocessedRequest",
    "SamplingOptions",
    "StopConditions",
]
