"""Internal LLM pipeline types: what flows between preprocessor, router,
workers, and the response path.

Everything here is msgpack-friendly (plain dicts on the wire via
``to_wire``/``from_wire``) because these cross process boundaries on the
data plane.

Capability parity: reference `lib/llm/src/protocols/common/llm_backend.rs`
(PreprocessedRequest / LLMEngineOutput) and `protocols/common/*` (sampling
and stop-condition extraction).
"""

from __future__ import annotations

import enum
from dataclasses import asdict, dataclass, field
from typing import Any


class FinishReason(str, enum.Enum):
    STOP = "stop"           # stop token / stop string hit
    LENGTH = "length"       # max_tokens reached
    EOS = "eos"             # model emitted EOS (maps to "stop" in OpenAI)
    CANCELLED = "cancelled"
    ERROR = "error"

    def as_openai(self) -> str:
        return "stop" if self in (FinishReason.EOS, FinishReason.STOP) else self.value


@dataclass
class SamplingOptions:
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = -1          # -1 = disabled
    seed: int | None = None
    frequency_penalty: float = 0.0
    presence_penalty: float = 0.0
    repetition_penalty: float = 1.0
    n: int = 1

    @property
    def greedy(self) -> bool:
        return self.temperature == 0.0


@dataclass
class StopConditions:
    max_tokens: int | None = None
    min_tokens: int = 0
    stop: list[str] = field(default_factory=list)
    stop_token_ids: list[int] = field(default_factory=list)
    ignore_eos: bool = False

    def check_token(
        self, token: int, n_generated: int, eos_token_ids
    ) -> str | None:
        """Token-level stop trigger: the single source of the eos > stop >
        length precedence used by the engine, the mocker, and the disagg
        first-token check (reference backend.rs:316 StopTrigger). String
        ``stop`` sequences are text-level and live in the detokenizer.
        ``n_generated`` includes ``token``."""
        if token in eos_token_ids and not self.ignore_eos and n_generated >= self.min_tokens:
            return FinishReason.EOS.value
        if token in self.stop_token_ids and n_generated >= self.min_tokens:
            return FinishReason.STOP.value
        if self.max_tokens is not None and n_generated >= self.max_tokens:
            return FinishReason.LENGTH.value
        return None

    def after_replay(self, n_emitted: int) -> "StopConditions":
        """Stop conditions for a token-replay continuation (migration /
        disagg fallback): ``n_emitted`` tokens already reached the client,
        so both the generation budget and the minimum shrink."""
        return StopConditions(
            max_tokens=(
                None if self.max_tokens is None else self.max_tokens - n_emitted
            ),
            min_tokens=max(0, self.min_tokens - n_emitted),
            stop=list(self.stop),
            stop_token_ids=list(self.stop_token_ids),
            ignore_eos=self.ignore_eos,
        )


@dataclass
class OutputOptions:
    logprobs: int | None = None   # top-k logprobs per token, None = off
    echo: bool = False
    skip_special_tokens: bool = True


@dataclass
class PreprocessedRequest:
    """A tokenized request, ready to route to any worker."""

    model: str
    token_ids: list[int]
    sampling: SamplingOptions = field(default_factory=SamplingOptions)
    stop: StopConditions = field(default_factory=StopConditions)
    output: OutputOptions = field(default_factory=OutputOptions)
    # Router hints / overrides (per-request, parity kv_router.rs:79)
    router: dict[str, Any] = field(default_factory=dict)
    # Disaggregation handoff (set by decode worker → prefill worker)
    kv_transfer_params: dict[str, Any] | None = None
    annotations: list[str] = field(default_factory=list)
    request_id: str | None = None
    # Multimodal: {"images": [ref, ...], "positions": [[start, count],
    # ...]} — token_ids carry content-fingerprint pseudo ids at those
    # positions; the worker resolves refs to embeddings (encoder fleet)
    # and the engine splices them over the placeholder rows
    # (llm/multimodal.py; reference examples/multimodal pipeline).
    mm: dict[str, Any] | None = None
    # Per-request speculative-decoding override (dynamo_tpu/spec):
    # {"method": "ngram"|"off", "k": int, "ngram_min": int, "ngram_max":
    # int, "window": int}. None = the worker engine's default policy.
    # Set from the OpenAI dyn.spec_decode extension by the preprocessor
    # and resolved at engine admission.
    spec_decode: dict[str, Any] | None = None
    # Token-replay continuation marker (migration / disagg fallback):
    # the trailing `replayed_tokens` entries of token_ids were GENERATED
    # by a previous attempt and already reached the client. A real model
    # conditions on them naturally (they are prompt now); the mocker uses
    # the count to keep its synthetic token function bit-identical across
    # a replay. 0 on every fresh request.
    replayed_tokens: int = 0
    # -- overload robustness (ISSUE 10) ---------------------------------
    # Fairness identity: the validated x-tenant-id header (frontend) or
    # "" for the default tenant. The scheduler's per-tenant DRR queues
    # key on this; it also labels the per-tenant /metrics gauges.
    tenant_id: str = ""
    # Ordering hint WITHIN a tenant's queue (higher admits first, FIFO
    # among equals). Never a cross-tenant bandwidth grant.
    priority: int = 0
    # Client-requested completion budget in milliseconds (dyn.deadline_ms
    # or x-request-deadline-ms) — observability + the source for
    # deadline_epoch when the frontend did not stamp one.
    deadline_ms: float | None = None
    # Absolute wall-clock deadline (time.time() domain), stamped by the
    # frontend at admission so downstream queue time counts against the
    # budget. A request still queued past this expires with a typed
    # DeadlineExceededError — never a broken stream.
    deadline_epoch: float | None = None

    def to_wire(self) -> dict:
        return asdict(self)

    @classmethod
    def from_wire(cls, d: dict) -> "PreprocessedRequest":
        return cls(
            model=d["model"],
            token_ids=list(d["token_ids"]),
            sampling=SamplingOptions(**d.get("sampling", {})),
            stop=StopConditions(**d.get("stop", {})),
            output=OutputOptions(**d.get("output", {})),
            router=d.get("router", {}),
            kv_transfer_params=d.get("kv_transfer_params"),
            annotations=d.get("annotations", []),
            request_id=d.get("request_id"),
            mm=d.get("mm"),
            spec_decode=d.get("spec_decode"),
            replayed_tokens=d.get("replayed_tokens", 0),
            tenant_id=d.get("tenant_id", ""),
            priority=d.get("priority", 0),
            deadline_ms=d.get("deadline_ms"),
            deadline_epoch=d.get("deadline_epoch"),
        )


@dataclass
class TokenLogProb:
    token_id: int
    logprob: float
    top: dict[int, float] = field(default_factory=dict)


@dataclass
class LLMEngineOutput:
    """One streamed chunk from a worker engine: newly generated tokens."""

    token_ids: list[int] = field(default_factory=list)
    finish_reason: str | None = None  # FinishReason value
    logprobs: list[dict] | None = None
    kv_transfer_params: dict[str, Any] | None = None
    # usage accounting (cumulative, present on final chunk)
    prompt_tokens: int | None = None
    completion_tokens: int | None = None
    # worker-reported metadata (e.g. cached_tokens for prefix-cache hits)
    meta: dict[str, Any] = field(default_factory=dict)

    def to_wire(self) -> dict:
        out: dict[str, Any] = {"token_ids": self.token_ids}
        if self.finish_reason is not None:
            out["finish_reason"] = self.finish_reason
        if self.logprobs is not None:
            out["logprobs"] = self.logprobs
        if self.kv_transfer_params is not None:
            out["kv_transfer_params"] = self.kv_transfer_params
        if self.prompt_tokens is not None:
            out["prompt_tokens"] = self.prompt_tokens
        if self.completion_tokens is not None:
            out["completion_tokens"] = self.completion_tokens
        if self.meta:
            out["meta"] = self.meta
        return out

    @classmethod
    def from_wire(cls, d: dict) -> "LLMEngineOutput":
        return cls(
            token_ids=list(d.get("token_ids", [])),
            finish_reason=d.get("finish_reason"),
            logprobs=d.get("logprobs"),
            kv_transfer_params=d.get("kv_transfer_params"),
            prompt_tokens=d.get("prompt_tokens"),
            completion_tokens=d.get("completion_tokens"),
            meta=d.get("meta", {}),
        )
