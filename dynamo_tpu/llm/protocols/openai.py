"""OpenAI-compatible API types (requests, responses, SSE chunks).

Pydantic models for the HTTP surface: `/v1/chat/completions`,
`/v1/completions`, `/v1/models`. Extension fields beyond the OpenAI schema
live under ``dyn`` (parity with the reference's ``nvext``,
`lib/llm/src/protocols/openai/nvext.rs:247`): ignore_eos, min_tokens,
per-request router overrides, annotations.

Capability parity: reference `lib/llm/src/protocols/openai/*` +
vendored async-openai types.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Literal, Union

from pydantic import BaseModel, ConfigDict, Field

from dynamo_tpu.llm.protocols.common import (
    OutputOptions,
    SamplingOptions,
    StopConditions,
)


class DynExt(BaseModel):
    """dynamo_tpu request extensions (the reference's nvext equivalent)."""

    model_config = ConfigDict(extra="allow")
    ignore_eos: bool = False
    min_tokens: int = 0
    annotations: list[str] = Field(default_factory=list)
    # Router overrides: {"backend_instance_id": int} pins a worker;
    # {"overlap_weight": float, "router_temperature": float} tune scoring.
    router: dict[str, Any] = Field(default_factory=dict)
    # Speculative-decoding override: {"method": "ngram"|"off", "k": int,
    # ...} — rides PreprocessedRequest.spec_decode to the worker engine
    # (greedy output is bit-identical with or without it).
    spec_decode: dict[str, Any] | None = None
    # Overload robustness (ISSUE 10): completion deadline budget in ms
    # (the x-request-deadline-ms header overrides it) — a request still
    # queued past its deadline gets a typed retryable error instead of
    # late tokens. priority orders requests WITHIN the caller's tenant
    # queue (higher first); tenancy itself comes from the validated
    # x-tenant-id header, never the request body.
    deadline_ms: float | None = None
    priority: int = 0


class FunctionCall(BaseModel):
    name: str
    arguments: str


class ToolCall(BaseModel):
    id: str
    type: Literal["function"] = "function"
    function: FunctionCall


class ContentPart(BaseModel):
    model_config = ConfigDict(extra="allow")
    type: str
    text: str | None = None
    image_url: dict | None = None


class ChatMessage(BaseModel):
    model_config = ConfigDict(extra="allow")
    role: str
    content: Union[str, list[ContentPart], None] = None
    name: str | None = None
    tool_calls: list[ToolCall] | None = None
    tool_call_id: str | None = None
    reasoning_content: str | None = None

    def text_content(self) -> str:
        if self.content is None:
            return ""
        if isinstance(self.content, str):
            return self.content
        return "".join(p.text or "" for p in self.content if p.type == "text")


class StreamOptions(BaseModel):
    include_usage: bool = False


class ResponseFormat(BaseModel):
    model_config = ConfigDict(extra="allow")
    type: str = "text"


class _CommonRequest(BaseModel):
    model_config = ConfigDict(extra="allow")
    model: str
    max_tokens: int | None = None
    temperature: float | None = None
    top_p: float | None = None
    top_k: int | None = None
    n: int = 1
    stream: bool = False
    stream_options: StreamOptions | None = None
    stop: Union[str, list[str], None] = None
    seed: int | None = None
    frequency_penalty: float | None = None
    presence_penalty: float | None = None
    repetition_penalty: float | None = None
    logprobs: Any = None
    user: str | None = None
    dyn: DynExt = Field(default_factory=DynExt)

    def sampling_options(self) -> SamplingOptions:
        return SamplingOptions(
            temperature=self.temperature if self.temperature is not None else 1.0,
            top_p=self.top_p if self.top_p is not None else 1.0,
            top_k=self.top_k if self.top_k is not None else -1,
            seed=self.seed,
            frequency_penalty=self.frequency_penalty or 0.0,
            presence_penalty=self.presence_penalty or 0.0,
            repetition_penalty=self.repetition_penalty or 1.0,
            n=self.n,
        )

    def stop_conditions(self, default_max_tokens: int | None = None) -> StopConditions:
        stop = self.stop if isinstance(self.stop, list) else ([self.stop] if self.stop else [])
        return StopConditions(
            max_tokens=self.max_tokens or default_max_tokens,
            min_tokens=self.dyn.min_tokens,
            stop=stop,
            ignore_eos=self.dyn.ignore_eos,
        )


class ChatCompletionRequest(_CommonRequest):
    messages: list[ChatMessage]
    max_completion_tokens: int | None = None
    tools: list[dict] | None = None
    tool_choice: Any = None
    response_format: ResponseFormat | None = None
    top_logprobs: int | None = None

    def stop_conditions(self, default_max_tokens: int | None = None) -> StopConditions:
        sc = super().stop_conditions(default_max_tokens)
        if self.max_completion_tokens is not None:
            sc.max_tokens = self.max_completion_tokens
        return sc

    def output_options(self) -> OutputOptions:
        want = bool(self.logprobs)
        return OutputOptions(logprobs=(self.top_logprobs or 1) if want else None)


class CompletionRequest(_CommonRequest):
    prompt: Union[str, list[str], list[int], list[list[int]]]
    echo: bool = False
    best_of: int | None = None

    def output_options(self) -> OutputOptions:
        k = self.logprobs if isinstance(self.logprobs, int) else None
        return OutputOptions(logprobs=k, echo=self.echo)


class EmbeddingRequest(BaseModel):
    model_config = ConfigDict(extra="allow")
    model: str
    input: Union[str, list[str], list[int], list[list[int]]]
    encoding_format: str = "float"


# -- responses ---------------------------------------------------------------


class Usage(BaseModel):
    prompt_tokens: int = 0
    completion_tokens: int = 0
    total_tokens: int = 0
    prompt_tokens_details: dict | None = None


class ChatDelta(BaseModel):
    role: str | None = None
    content: str | None = None
    reasoning_content: str | None = None
    tool_calls: list[dict] | None = None


class ChatChunkChoice(BaseModel):
    index: int = 0
    delta: ChatDelta
    finish_reason: str | None = None
    logprobs: dict | None = None


class ChatCompletionChunk(BaseModel):
    id: str
    object: Literal["chat.completion.chunk"] = "chat.completion.chunk"
    created: int
    model: str
    choices: list[ChatChunkChoice]
    usage: Usage | None = None


class ChatChoice(BaseModel):
    index: int = 0
    message: ChatMessage
    finish_reason: str | None = None
    logprobs: dict | None = None


class ChatCompletionResponse(BaseModel):
    id: str
    object: Literal["chat.completion"] = "chat.completion"
    created: int
    model: str
    choices: list[ChatChoice]
    usage: Usage | None = None


class CompletionChoice(BaseModel):
    index: int = 0
    text: str
    finish_reason: str | None = None
    logprobs: dict | None = None


class CompletionResponse(BaseModel):
    id: str
    object: Literal["text_completion"] = "text_completion"
    created: int
    model: str
    choices: list[CompletionChoice]
    usage: Usage | None = None


class ModelInfo(BaseModel):
    id: str
    object: Literal["model"] = "model"
    created: int = Field(default_factory=lambda: int(time.time()))
    owned_by: str = "dynamo-tpu"
    max_model_len: int | None = None


class ModelList(BaseModel):
    object: Literal["list"] = "list"
    data: list[ModelInfo] = Field(default_factory=list)


def new_request_id(prefix: str = "cmpl") -> str:
    return f"{prefix}-{uuid.uuid4().hex}"
