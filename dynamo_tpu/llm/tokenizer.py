"""Tokenizer abstraction: HF tokenizers for real models, a dependency-free
byte-level tokenizer for tests/mocker.

Capability parity: reference `lib/llm/src/tokenizers.rs:576` (HF + GGUF
tokenizer wrappers behind one trait). The byte tokenizer replaces the
reference's reliance on downloaded test models — encode/decode are exact
inverses over UTF-8, which incremental detokenization tests exploit.
"""

from __future__ import annotations

import functools
import logging
from typing import Protocol, Sequence, runtime_checkable

log = logging.getLogger("dynamo_tpu.tokenizer")


@runtime_checkable
class Tokenizer(Protocol):
    eos_token_id: int
    vocab_size: int

    def encode(self, text: str) -> list[int]: ...
    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str: ...
    def apply_chat_template(self, messages: list[dict], add_generation_prompt: bool = True) -> str: ...


class ByteTokenizer:
    """Tokens 0..255 are raw UTF-8 bytes; specials sit above.

    Deterministic, zero-asset, and reversible — the workhorse of the test
    suite and the mocker engine.
    """

    BOS = 256
    EOS = 257
    PAD = 258

    def __init__(self) -> None:
        self.eos_token_id = self.EOS
        self.bos_token_id = self.BOS
        self.pad_token_id = self.PAD
        self.vocab_size = 259

    def encode(self, text: str) -> list[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        data = bytes(i for i in ids if i < 256)
        return data.decode("utf-8", errors="replace")

    def apply_chat_template(self, messages: list[dict], add_generation_prompt: bool = True) -> str:
        parts = [f"<|{m['role']}|>{m.get('content') or ''}" for m in messages]
        if add_generation_prompt:
            parts.append("<|assistant|>")
        return "\n".join(parts)


class HFTokenizer:
    """transformers.AutoTokenizer wrapper (local paths only — zero egress)."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.eos_token_id = self._tok.eos_token_id
        self.bos_token_id = getattr(self._tok, "bos_token_id", None)
        self.pad_token_id = getattr(self._tok, "pad_token_id", None)
        self.vocab_size = len(self._tok)

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text, add_special_tokens=False)

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=skip_special_tokens)

    def apply_chat_template(self, messages: list[dict], add_generation_prompt: bool = True) -> str:
        if getattr(self._tok, "chat_template", None):
            return self._tok.apply_chat_template(
                messages, tokenize=False, add_generation_prompt=add_generation_prompt
            )
        # Fallback template for models shipping without one.
        parts = [f"<|{m['role']}|>\n{m.get('content') or ''}" for m in messages]
        if add_generation_prompt:
            parts.append("<|assistant|>\n")
        return "\n".join(parts)


@functools.lru_cache(maxsize=8)
def _load_cached(spec: str) -> Tokenizer:
    if spec.endswith(".gguf"):
        from dynamo_tpu.engine.gguf import GGUFTokenizer, read_gguf

        return GGUFTokenizer.from_gguf(read_gguf(spec))
    return HFTokenizer(spec)


def load_tokenizer(spec: str) -> Tokenizer:
    """``"byte"`` → ByteTokenizer; ``*.gguf`` → the checkpoint's embedded
    tokenizer (engine/gguf.py); anything else is a local HF path. A
    checkpoint directory without tokenizer files serves byte-level with a
    warning instead of killing worker startup (weights-only checkpoints
    are common in tests and conversions). Successful loads are cached per
    spec (eos resolution and the preprocessor would otherwise parse the
    same multi-MB tokenizer.json twice at startup; tokenizers are
    read-only after construction) — the byte-level FALLBACK is not, so a
    tokenizer that appears later is picked up."""
    if spec == "byte":
        return ByteTokenizer()
    try:
        return _load_cached(spec)
    except Exception:  # noqa: BLE001 — see the narrowing below
        from pathlib import Path

        p = Path(spec)
        tok_files = (
            "tokenizer.json", "tokenizer_config.json", "vocab.json",
            "tokenizer.model",
        )
        if p.is_dir() and not any((p / f).exists() for f in tok_files):
            # Weights-only checkpoint directory: degrade, loudly. A
            # mistyped path or a CORRUPT tokenizer still fails fast — only
            # the genuinely-absent case falls back.
            log.warning(
                "checkpoint %r has no tokenizer files; serving byte-level",
                spec,
            )
            return ByteTokenizer()
        raise
