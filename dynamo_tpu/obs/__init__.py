"""Fleet observability plane (ISSUE 13).

Three connected parts:

- :mod:`~dynamo_tpu.obs.snapshot` — workers periodically publish compact
  metric snapshots over the store/event plane (the same subject scheme
  the KV-event and load-metrics publishers use).
- :mod:`~dynamo_tpu.obs.aggregator` — the fleet aggregator composes those
  snapshots into ``/metrics`` series with ``worker_id`` labels plus fleet
  rollups, retires series on lease loss and drain, and feeds the SLA
  planner's :class:`~dynamo_tpu.planner.planner_core.Observation` from
  the aggregate instead of point scrapes.
- :mod:`~dynamo_tpu.obs.slo` — per-request TTFT/TPOT budget attribution
  stitched from the existing tracer spans, exported as ``dynamo_slo_*``
  histograms per tenant and the ``/fleet`` status payload.
- :mod:`~dynamo_tpu.obs.flight_recorder` — a bounded ring of recent
  engine-step records on both backends, dumped to a redacted JSON
  artifact on SIGTERM drain, stall-deadline fire, breaker open, and
  chaos kill.

Capability parity: the reference treats metrics aggregation as a
first-class service over its NATS event plane (``components/metrics``,
PAPER.md §L0/L1); the flight recorder is our post-mortem answer to the
chaos harness (PR 6) killing workers that previously left no artifact.
"""

from dynamo_tpu.obs.flight_recorder import FlightRecorder, dump_all
from dynamo_tpu.obs.snapshot import (
    MetricSnapshot,
    SnapshotPublisher,
    obs_subject,
)
from dynamo_tpu.obs.aggregator import FleetAggregator
from dynamo_tpu.obs.slo import PhaseScanner, SloAttributor, SloTargets

__all__ = [
    "FlightRecorder",
    "FleetAggregator",
    "MetricSnapshot",
    "PhaseScanner",
    "SloAttributor",
    "SloTargets",
    "SnapshotPublisher",
    "dump_all",
    "obs_subject",
]
