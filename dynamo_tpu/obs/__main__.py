"""CLI: ``python -m dynamo_tpu.obs`` — the standalone fleet aggregator."""

from __future__ import annotations

import argparse

from dynamo_tpu.obs.service import run_aggregator
from dynamo_tpu.runtime import DistributedRuntime
from dynamo_tpu.runtime.worker import dynamo_worker


def main() -> None:
    ap = argparse.ArgumentParser(
        description="dynamo-tpu fleet metrics aggregator"
    )
    ap.add_argument("--namespace", default="dynamo")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8082)
    ap.add_argument(
        "--stale-after-s", type=float, default=10.0,
        help="retire a worker's series after this long without a "
             "snapshot (the dead-process backstop; drain and lease loss "
             "retire immediately)",
    )
    args = ap.parse_args()

    @dynamo_worker()
    async def entry(runtime: DistributedRuntime) -> None:
        await run_aggregator(
            runtime,
            namespace=args.namespace,
            host=args.host,
            port=args.port,
            stale_after_s=args.stale_after_s,
        )

    entry()


if __name__ == "__main__":
    main()
