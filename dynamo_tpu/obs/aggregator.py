"""The fleet aggregator: compose worker snapshots into one fleet view.

Subscribes to ``obs_snapshots.{namespace}`` and keeps the latest
:class:`~dynamo_tpu.obs.snapshot.MetricSnapshot` per worker. Exposure:

- **Fleet /metrics** — every worker's gauge families re-exported with a
  ``worker_id`` label (the SAME metric names and keys the per-worker
  status servers export, via the shared gauge tables in
  ``runtime/status_server.py``), plus ``dynamo_fleet_*`` rollups
  (sum / max / p50 / p99 across live workers).
- **Series retirement** — a worker's series are REMOVED (not zeroed) on:
  a ``retired`` snapshot (graceful drain), a discovery instance-removal
  event (lease loss — wire via :meth:`attach_client`), or snapshot
  staleness (no publish for ``stale_after_s``; the backstop for a
  chaos-killed process the watch hasn't caught yet). The PR 11
  inventory-retirement shape, applied to metrics.
- **Tenant cardinality cap** — fleet per-tenant queue gauges cap at
  :data:`MAX_TENANT_GAUGES` series + ``__other__``, with retired
  tenants' series removed (PR 10's rule, applied uniformly here).
- **Planner feed** — :meth:`observation` diffs consecutive aggregate
  states into one adjustment window's planner ``Observation`` (request
  rate / ISL / OSL / TTFT / ITL from frontend snapshots, per-phase means
  from the live workers' cumulative phase totals) — the planner now
  observes the EVENT PLANE, not a point scrape.
- **SLO attribution** — per-request phase records inside the snapshots
  feed a :class:`~dynamo_tpu.obs.slo.SloAttributor` (``dynamo_slo_*``
  histograms + the ``/fleet`` payload).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Callable

from dynamo_tpu.obs.slo import SloAttributor, SloTargets, quantile
from dynamo_tpu.obs.snapshot import MetricSnapshot, obs_subject
from dynamo_tpu.runtime.status_server import (
    KV_CACHE_GAUGES,
    KV_POOL_GAUGES,
    MAX_TENANT_GAUGES,
    SCHEDULER_GAUGES,
    SPEC_GAUGES,
)

log = logging.getLogger("dynamo_tpu.obs.aggregator")

# family name in the snapshot -> (gauge table, service label). The tables
# are the single source of truth for names/docs — per-worker /metrics and
# the fleet view can never drift apart.
FAMILY_TABLES: dict[str, tuple[dict, str]] = {
    "scheduler": (SCHEDULER_GAUGES, "engine"),
    "spec": (SPEC_GAUGES, "engine"),
    "kv_cache": (KV_CACHE_GAUGES, "engine"),
    "kv_pool": (KV_POOL_GAUGES, "kv_pool"),
}

ROLLUP_STATS = ("sum", "max", "p50", "p99")

# The capped-overflow tenant label (shared spelling with PR 10's export).
OTHER = "__other__"


class FleetAggregator:
    """Latest-snapshot fleet state + /metrics exporter + planner feed."""

    def __init__(
        self,
        store,
        namespace: str = "dynamo",
        stale_after_s: float = 10.0,
        slo_targets: SloTargets | None = None,
        max_tenants: int = MAX_TENANT_GAUGES,
    ):
        self._store = store
        self.namespace = namespace
        self.stale_after_s = stale_after_s
        self.max_tenants = max_tenants
        self.latest: dict[int, MetricSnapshot] = {}      # role == "worker"
        self.frontends: dict[int, MetricSnapshot] = {}   # role == "frontend"
        self.slo = SloAttributor(targets=slo_targets, namespace=namespace)
        self.snapshots_received_total = 0
        self.workers_retired_total = 0
        self._sub = None
        self._task: asyncio.Task | None = None
        self._metrics = None  # MetricsRegistry the fleet series land on
        # Closed-loop controller (ISSUE 14): when attached, its decision
        # counters/replica gauges export with the fleet series and the
        # /fleet payload grows a "planner" section.
        self._controller = None
        # Removal bookkeeping: what was exported, so retirement can
        # remove exactly those series (never zero them).
        self._exported_workers: set[int] = set()
        self._exported_tenants: set[str] = set()
        self._exported_rollups: set[tuple[str, str]] = set()  # (fam, key)
        # observation() diff state.
        self._prev_totals: dict[str, float] | None = None
        self._prev_t: float = 0.0
        # Control-plane-dark bookkeeping (ISSUE 15): while the store
        # session is down, NOBODY can publish — snapshot silence is an
        # outage symptom, not worker death, so staleness retirement is
        # suspended; after reconnection every publisher gets one fresh
        # ``stale_after_s`` window to re-appear before retirement resumes.
        self._was_dark = False
        self._dark_grace_until = 0.0
        # Last-seen cumulative typed-shed counter per worker: sheds are
        # diffed per worker (retirement-aware), never on the fleet total.
        self._prev_sheds: dict[int, float] = {}
        self._last_means = (256.0, 128.0)

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        self._sub = await self._store.subscribe(obs_subject(self.namespace))
        self._task = asyncio.create_task(self._loop())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
        if self._sub:
            await self._sub.unsubscribe()

    async def _loop(self) -> None:
        assert self._sub is not None
        async for ev in self._sub:
            try:
                self.ingest(MetricSnapshot.from_wire(ev["p"]))
            except Exception:  # noqa: BLE001 — one bad snapshot must not kill the view
                log.exception("bad snapshot payload")

    # -- ingest + retirement -----------------------------------------------

    def ingest(self, snap: MetricSnapshot) -> None:
        self.snapshots_received_total += 1
        # Staleness is judged against THIS clock (arrival time), never the
        # publisher's wall clock — cross-host skew > stale_after_s must
        # not flap a healthy worker in and out of the fleet view.
        snap.received_at = time.time()
        if snap.retired:
            # Drain retraction: series leave NOW, not at lease expiry.
            self.remove_worker(snap.worker_id)
            return
        side = "frontend" if snap.role == "frontend" else "worker"
        store = self.frontends if side == "frontend" else self.latest
        prev = store.get(snap.worker_id)
        if (
            prev is not None
            and snap.epoch == prev.epoch
            and snap.seq <= prev.seq
        ):
            # Out-of-order redelivery from the SAME publisher incarnation.
            # A different epoch is a restarted process re-using a pinned
            # worker_id: its seq starts over at 1 and must replace the
            # dead incarnation's state immediately.
            return
        store[snap.worker_id] = snap
        if snap.requests:
            self.slo.ingest(snap.requests, side=side)

    def remove_worker(self, worker_id: int) -> None:
        """Retire a worker's fleet series (drain retraction, discovery
        instance removal = lease loss, or staleness)."""
        was = self.latest.pop(worker_id, None) or self.frontends.pop(
            worker_id, None
        )
        if was is not None:
            self.workers_retired_total += 1
        self._remove_series(worker_id)

    def attach_client(self, client) -> None:
        """Retire on lease loss: discovery instance-removal events (the
        same watch the router uses to drop dead workers)."""
        client.on_instance_removed.append(self.remove_worker)

    def attach_controller(self, controller) -> None:
        """Export a PlannerController's decision counters and replica
        gauges on the fleet registry (``planner_*`` series, synced on
        every render like the rest) and surface its status in the
        ``/fleet`` payload — the operator reads what the control loop
        did and why from the same place they read the fleet's load."""
        self._controller = controller

    def live_workers(self) -> list[int]:
        return sorted(self.latest)

    # -- /metrics export ---------------------------------------------------

    def bind(self, metrics, before_render: list) -> None:
        """Export the fleet view on a MetricsRegistry, synced by a
        pre-render hook (the status server's ``before_render`` or the
        HTTP frontend's ``before_metrics``)."""
        self._metrics = metrics
        before_render.append(self.sync)

    def _remove_series(self, worker_id: int) -> None:
        if self._metrics is None or worker_id not in self._exported_workers:
            return
        self._exported_workers.discard(worker_id)
        for _fam, (table, service) in FAMILY_TABLES.items():
            scoped = self._metrics.scoped(
                namespace=self.namespace, service=service,
                worker_id=str(worker_id),
            )
            for _key, (name, _doc) in table.items():
                scoped.remove_gauge(name)

    @property
    def control_plane_dark(self) -> bool:
        """True while this process's store session is down: the event
        plane cannot deliver snapshots, so the fleet view is a frozen
        last-known-good, and "publisher went quiet" means nothing."""
        return not getattr(self._store, "connected", True)

    def sweep_stale(self, now: float | None = None) -> list[int]:
        """Retire workers that stopped publishing (the chaos-kill /
        dead-process backstop when no watch event reached us).

        Suspended while the control plane is dark — a blackout silences
        every publisher at once, and retiring the whole healthy fleet on
        that is the flap ISSUE 15 quarantines. After reconnection the
        fleet gets one fresh ``stale_after_s`` window to republish."""
        now = time.time() if now is None else now
        if self.control_plane_dark:
            self._was_dark = True
            return []
        if self._was_dark:
            self._was_dark = False
            self._dark_grace_until = now + self.stale_after_s
        if now < self._dark_grace_until:
            return []
        stale = [
            w
            for w, s in list(self.latest.items()) + list(self.frontends.items())
            if now - s.received_at > self.stale_after_s
        ]
        for w in stale:
            log.warning("retiring stale worker %d (no snapshot)", w)
            self.remove_worker(w)
        return stale

    def sync(self) -> None:
        """Pre-render: refresh every exported series from the latest
        snapshots. Dead/drained workers' series were already removed at
        retirement; staleness is swept here too so a scrape never shows
        a silently-dead worker as fresh."""
        if self._metrics is None:
            return
        self.sweep_stale()
        self.slo.sweep()
        # Per-worker series, labeled worker_id (+ namespace, so several
        # embedded aggregators sharing one frontend registry can never
        # write — or retire — each other's series).
        for wid, snap in self.latest.items():
            self._exported_workers.add(wid)
            for fam, (table, service) in FAMILY_TABLES.items():
                vals = snap.families.get(fam)
                if not vals:
                    continue
                scoped = self._metrics.scoped(
                    namespace=self.namespace, service=service,
                    worker_id=str(wid),
                )
                for key, (name, doc) in table.items():
                    if key in vals:
                        scoped.gauge(name, doc).set(vals[key])
        # Fleet rollups across live workers. A rollup whose LAST
        # contributing worker retired is removed like any other series
        # (never left frozen at the dead fleet's final values).
        for fam, (table, service) in FAMILY_TABLES.items():
            for key, (name, doc) in table.items():
                series = sorted(
                    s.families[fam][key]
                    for s in self.latest.values()
                    if fam in s.families and key in s.families[fam]
                )
                if not series:
                    if (fam, key) in self._exported_rollups:
                        self._exported_rollups.discard((fam, key))
                        for stat in ROLLUP_STATS:
                            self._metrics.scoped(
                                namespace=self.namespace,
                                service=service, stat=stat,
                            ).remove_gauge(f"fleet_{name}")
                    continue
                self._exported_rollups.add((fam, key))
                rollups = {
                    "sum": float(sum(series)),
                    "max": series[-1],
                    "p50": quantile(series, 0.50),
                    "p99": quantile(series, 0.99),
                }
                for stat in ROLLUP_STATS:
                    self._metrics.scoped(
                        namespace=self.namespace, service=service, stat=stat,
                    ).gauge(
                        f"fleet_{name}",
                        f"Fleet rollup ({'/'.join(ROLLUP_STATS)} across "
                        f"live workers) of {name}: {doc}",
                    ).set(rollups[stat])
        self._sync_tenants()
        self._sync_planner()
        # Aggregator health.
        agg = self._metrics.scoped(namespace=self.namespace, service="obs")
        agg.gauge(
            "obs_live_workers", "Workers with a fresh snapshot in the fleet view"
        ).set(float(len(self.latest)))
        agg.gauge(
            "obs_snapshots_received_total",
            "Metric snapshots ingested from the event plane since start",
        ).set(float(self.snapshots_received_total))
        agg.gauge(
            "obs_workers_retired_total",
            "Workers whose series were retired (drain / lease loss / "
            "staleness) since start",
        ).set(float(self.workers_retired_total))
        agg.gauge(
            "obs_control_plane_dark",
            "1 while the aggregator's store session is down (snapshot "
            "silence is the outage, not worker death; staleness "
            "retirement is suspended)",
        ).set(1.0 if self.control_plane_dark else 0.0)

    def _sync_tenants(self) -> None:
        """Fleet per-tenant queue gauges, cardinality-capped: at most
        ``max_tenants`` tenant series + ``__other__``, retired tenants'
        series REMOVED — the PR 10 rule applied to the aggregator, so a
        churning fleet or adversarial x-tenant-id spray cannot grow the
        aggregator's /metrics unboundedly."""
        fleet: dict[str, dict[str, float]] = {}
        for snap in self.latest.values():
            for tenant, st in snap.tenants.items():
                agg = fleet.setdefault(tenant, {"depth": 0.0, "deficit": 0.0})
                for k in agg:
                    agg[k] += float(st.get(k, 0.0))
        if len(fleet) > self.max_tenants:
            ranked = sorted(fleet.items(), key=lambda kv: -kv[1]["depth"])
            capped = dict(ranked[: self.max_tenants])
            other = {"depth": 0.0, "deficit": 0.0}
            for _t, st in ranked[self.max_tenants:]:
                for k in other:
                    other[k] += st[k]
            capped[OTHER] = other
            fleet = capped
        for tenant in self._exported_tenants - set(fleet):
            scoped = self._metrics.scoped(
                namespace=self.namespace, service="fleet", tenant=tenant
            )
            scoped.remove_gauge("fleet_tenant_queue_depth")
            scoped.remove_gauge("fleet_tenant_deficit_tokens")
        self._exported_tenants.intersection_update(fleet)
        for tenant, st in fleet.items():
            self._exported_tenants.add(tenant)
            scoped = self._metrics.scoped(
                namespace=self.namespace, service="fleet", tenant=tenant
            )
            scoped.gauge(
                "fleet_tenant_queue_depth",
                "Requests waiting in this tenant's admission queues, "
                "summed across live workers",
            ).set(st["depth"])
            scoped.gauge(
                "fleet_tenant_deficit_tokens",
                "The tenant's DRR deficit balance, summed across live workers",
            ).set(st["deficit"])

    def _sync_planner(self) -> None:
        """Planner decision observability (ISSUE 14): decision counters
        by action, per-pool current/desired replica gauges — the
        controller's stats() payload re-exported as fleet series."""
        if self._controller is None or self._metrics is None:
            return
        st = self._controller.stats()
        base = self._metrics.scoped(namespace=self.namespace, service="planner")
        base.gauge(
            "planner_cycles_total",
            "Closed-loop adjustment cycles the controller has run",
        ).set(float(st.get("cycles", 0)))
        for action, n in (st.get("decisions") or {}).items():
            self._metrics.scoped(
                namespace=self.namespace, service="planner", action=action
            ).gauge(
                "planner_decisions_total",
                "Controller decisions by outcome (scale_up / scale_down / "
                "hold / cooldown_hold / hysteresis_hold)",
            ).set(float(n))
        for comp, pool in (st.get("pools") or {}).items():
            scoped = self._metrics.scoped(
                namespace=self.namespace, service="planner", component=comp
            )
            scoped.gauge(
                "planner_current_replicas",
                "Replica count the controller last actuated for this pool",
            ).set(float(pool.get("target", 0)))
            scoped.gauge(
                "planner_target_replicas",
                "This cycle's desired replica count (pre-hysteresis/"
                "cooldown), from the plan math + reactive pressure",
            ).set(float(pool.get("desired", 0)))

    # -- planner feed ------------------------------------------------------

    def _totals(self) -> dict[str, float]:
        """Cumulative fleet totals over LIVE publishers only: frontend
        request/latency counters + per-phase (count, sum) pairs collapsed
        by phase name."""
        totals: dict[str, float] = {}
        for snap in self.frontends.values():
            for k, v in (snap.families.get("frontend") or {}).items():
                totals[k] = totals.get(k, 0.0) + v
        for snap in list(self.latest.values()) + list(self.frontends.values()):
            for key, (count, sec) in snap.phases.items():
                phase = key.rsplit("/", 1)[-1]
                totals[f"phase_count/{phase}"] = (
                    totals.get(f"phase_count/{phase}", 0.0) + count
                )
                totals[f"phase_sum/{phase}"] = (
                    totals.get(f"phase_sum/{phase}", 0.0) + sec
                )
        # Closed-loop signals (ISSUE 14): the SLO attributor's attainment
        # counters, diffed per window by observation(). (Typed sheds are
        # NOT totalled here — observation() diffs them per worker so a
        # retiring worker's cumulative counter leaving the sum cannot
        # clamp the fleet-wide delta to zero.)
        for k, v in self.slo.attainment_counters().items():
            totals[f"slo_{k}"] = v
        return totals

    def observation(self):
        """One adjustment window's planner Observation from the aggregate
        (the event-plane twin of planner/observer.py's point scrape —
        same diff math, fed by snapshots from LIVE workers only)."""
        from dynamo_tpu.planner.planner_core import Observation

        self.sweep_stale()
        # Blind window (ISSUE 15): assembled while the store session was
        # down (or just after — the re-publish grace), so rates/queues in
        # it are phantom zeros. The controller holds on this flag.
        degraded = self.control_plane_dark or (
            self._was_dark or time.time() < self._dark_grace_until
        )
        now = time.monotonic()
        cur = self._totals()
        # Typed sheds: per-worker cumulative counters diffed per worker.
        # A retired worker simply drops out of the dict; a worker id
        # reused by a restarted process restarts near zero and clamps.
        cur_sheds: dict[int, float] = {}
        for wid, snap in self.latest.items():
            sched = snap.families.get("scheduler") or {}
            cur_sheds[wid] = float(sched.get("shed_total", 0) or 0) + float(
                sched.get("deadline_expired_total", 0) or 0
            )
        shed_delta = sum(
            max(0.0, v - self._prev_sheds.get(wid, 0.0))
            for wid, v in cur_sheds.items()
        )
        self._prev_sheds = cur_sheds
        prev, prev_t = self._prev_totals, self._prev_t
        self._prev_totals, self._prev_t = cur, now
        if prev is None:
            return Observation(
                request_rate=0.0,
                mean_isl=self._last_means[0],
                mean_osl=self._last_means[1],
                control_plane_degraded=degraded,
            )
        window = max(now - prev_t, 1e-6)

        def delta(name: str) -> float:
            return max(0.0, cur.get(name, 0.0) - prev.get(name, 0.0))

        def mean(prefix: str, fallback: float) -> float:
            c = delta(f"{prefix}_count")
            return delta(f"{prefix}_sum") / c if c > 0 else fallback

        isl = mean("isl", self._last_means[0])
        osl = mean("osl", self._last_means[1])
        self._last_means = (isl, osl)
        ttft_c = delta("ttft_count")
        itl_c = delta("itl_count")
        phase_means: dict[str, float] = {}
        for key in cur:
            if not key.startswith("phase_count/"):
                continue
            phase = key[len("phase_count/"):]
            c = delta(key)
            if c > 0:
                phase_means[phase] = delta(f"phase_sum/{phase}") / c
        # Closed-loop signals: point-in-time fleet queue depth, windowed
        # typed sheds, windowed SLO attainment (None when nothing
        # finished this window), live worker counts per component.
        queue_depth = 0.0
        queue_by_comp: dict[str, float] = {}
        live: dict[str, int] = {}
        for snap in self.latest.values():
            sched = snap.families.get("scheduler") or {}
            waiting = float(sched.get("waiting", 0) or 0)
            queue_depth += waiting
            queue_by_comp[snap.component] = (
                queue_by_comp.get(snap.component, 0.0) + waiting
            )
            live[snap.component] = live.get(snap.component, 0) + 1
        attainment: dict[str, float] = {}
        ttft_n = delta("slo_ttft_n")
        if ttft_n > 0:
            attainment["ttft"] = delta("slo_ttft_ok") / ttft_n
        tpot_n = delta("slo_tpot_n")
        if tpot_n > 0:
            attainment["tpot"] = delta("slo_tpot_ok") / tpot_n
        return Observation(
            request_rate=delta("requests_total") / window,
            mean_isl=isl,
            mean_osl=osl,
            observed_ttft_s=(delta("ttft_sum") / ttft_c) if ttft_c else None,
            observed_itl_s=(delta("itl_sum") / itl_c) if itl_c else None,
            phase_means=phase_means or None,
            queue_depth=queue_depth,
            queue_depths=queue_by_comp or None,
            shed_delta=shed_delta,
            slo_attainment=attainment or None,
            live_workers=live or None,
            control_plane_degraded=degraded,
        )

    # -- /fleet payload ----------------------------------------------------

    def fleet_payload(self) -> dict:
        """The ``/fleet`` status page: live workers with headline load,
        the per-tenant SLO breakdown, and aggregator health."""
        self.sweep_stale()
        self.slo.sweep()
        now = time.time()

        def worker_row(snap: MetricSnapshot) -> dict:
            sched = snap.families.get("scheduler") or {}
            kv = snap.families.get("kv_cache") or {}
            return {
                "role": snap.role,
                "component": snap.component,
                "seq": snap.seq,
                "age_s": round(max(0.0, now - snap.received_at), 3),
                "waiting": sched.get("waiting", 0),
                "running": sched.get("running", 0),
                "budget_utilization": sched.get(
                    "last_step_budget_utilization", 0.0
                ),
                "kv_resident_blocks": kv.get("resident_blocks", 0),
                "kv_capacity_blocks": kv.get("capacity_blocks", 0),
            }

        return {
            "namespace": self.namespace,
            "live_workers": self.live_workers(),
            "workers": {
                str(w): worker_row(s) for w, s in sorted(self.latest.items())
            },
            "frontends": {
                str(w): worker_row(s)
                for w, s in sorted(self.frontends.items())
            },
            "slo": self.slo.summary(),
            "planner": (
                self._controller.status_payload()
                if self._controller is not None
                else None
            ),
            "snapshots_received": self.snapshots_received_total,
            "workers_retired": self.workers_retired_total,
        }
