"""Crash/stall flight recorder: a bounded ring of engine-step records.

Every engine (real EngineCore and the mocker) appends one compact record
per committed step — step shape, lane cursors, dispatch/commit
timestamps, cumulative shed counters — into a fixed-size ring. The hot
path is ONE dict build + ONE ``deque.append`` (atomic under the GIL, no
host sync, no lock): cheap enough to stay on by default, bounded enough
to never grow.

On a terminal event the ring is dumped to a REDACTED JSON artifact — the
post-mortem the chaos harness (PR 6) could never produce: a killed
worker's final megasteps are reconstructable from the artifact alone.
Dump triggers (each names the artifact's ``reason``):

- ``sigterm_drain``   — graceful drain (DistributedRuntime.drain)
- ``chaos_kill``      — a ChaosKill landed in an engine loop
- ``stall_deadline``  — a response-stream stall deadline fired
  (dataplane ``_note_stall``); in single-process deployments this also
  captures the wedged engine's ring, since a dump flushes EVERY recorder
  registered in the process
- ``breaker_open``    — a dataplane circuit breaker opened

Redaction: artifacts carry counts, cursors, ids, and timestamps — never
token values or prompt/content text. The dump pass strips any key in
:data:`REDACT_KEYS` recursively and truncates long strings, so a record
accidentally carrying payload can not leak it into the artifact.

Knobs (env):

- ``DYN_FLIGHT_STEPS`` — ring capacity in records (default 256; 0
  disables recording entirely — ``record_step`` returns immediately).
- ``DYN_FLIGHT_DIR``   — artifact directory (default
  ``$TMPDIR/dynamo_flight``).
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import tempfile
import time
import weakref
from collections import deque
from typing import Any

from dynamo_tpu import knobs

log = logging.getLogger("dynamo_tpu.obs.flight")

# Keys stripped recursively from every dumped record: the artifact must
# never carry token values or user text, only shapes/cursors/timestamps.
REDACT_KEYS = frozenset(
    {"token_ids", "logprobs", "text", "prompt", "content", "messages"}
)

# Longest string value a dumped record may carry (ids/reasons fit well
# under this; anything longer is suspect payload and is truncated).
_MAX_STR = 256

# Per-reason dump budget: a flapping breaker must not fill the disk with
# artifacts. After this many dumps for one reason, further triggers only
# log. A per-reason cooldown also coalesces bursts.
_MAX_DUMPS_PER_REASON = 8
_DUMP_COOLDOWN_S = 1.0


def _env_capacity() -> int:
    return max(0, knobs.get_int("DYN_FLIGHT_STEPS"))


def artifact_dir() -> str:
    return knobs.get_str("DYN_FLIGHT_DIR") or os.path.join(
        tempfile.gettempdir(), "dynamo_flight"
    )


class FlightRecorder:
    """One engine's bounded step-record ring.

    ``record_step``/``record_event`` are hot-path safe (single append);
    registration is global so a process-wide dump trigger (drain, stall,
    breaker) flushes every live engine's ring at once. Held weakly by
    the registry: an engine garbage-collected between dumps unregisters
    itself.
    """

    def __init__(self, name: str, capacity: int | None = None):
        self.name = name
        self.capacity = _env_capacity() if capacity is None else max(0, capacity)
        self._ring: deque[dict[str, Any]] = deque(maxlen=max(1, self.capacity))
        self.started_at = time.time()
        _register(self)

    def record_step(self, **fields: Any) -> None:
        """One committed engine step. No-op at capacity 0. Fields are
        host-resident scalars/lists only — callers must never pass device
        arrays (this is an append, not a sync point)."""
        if self.capacity == 0:
            return
        fields["t"] = time.time()
        fields.setdefault("kind", "step")
        self._ring.append(fields)

    def record_event(self, event: str, **fields: Any) -> None:
        """A discrete non-step event (shed, deadline expiry, breaker
        trip) interleaved into the same ring in arrival order."""
        if self.capacity == 0:
            return
        fields["t"] = time.time()
        fields["kind"] = "event"
        fields["event"] = event
        self._ring.append(fields)

    def snapshot(self) -> list[dict[str, Any]]:
        # The ring is appended from the engine thread while dump triggers
        # read from the event loop / drain thread; a copy that catches a
        # concurrent append raises RuntimeError — retry, the copy is
        # microseconds and the collision window one append.
        for _ in range(8):
            try:
                return list(self._ring)
            except RuntimeError:
                continue
        return []

    def clear(self) -> None:
        self._ring.clear()


# ---------------------------------------------------------------------------
# Process-global registry + dump triggers
# ---------------------------------------------------------------------------

_ids = itertools.count(1)
_recorders: "weakref.WeakValueDictionary[int, FlightRecorder]" = (
    weakref.WeakValueDictionary()
)
_dumps_by_reason: dict[str, int] = {}
_last_dump_at: dict[str, float] = {}


def _register(rec: FlightRecorder) -> None:
    _recorders[next(_ids)] = rec


def enabled() -> bool:
    """Cheap guard for trigger sites: False when nothing is recording."""
    return len(_recorders) > 0


def redact(obj: Any) -> Any:
    """Strip payload-bearing keys and truncate long strings, recursively.
    The dump's privacy contract: counts/cursors/ids stay, values go."""
    if isinstance(obj, dict):
        return {
            k: redact(v) for k, v in obj.items() if k not in REDACT_KEYS
        }
    if isinstance(obj, (list, tuple)):
        return [redact(v) for v in obj]
    if isinstance(obj, str) and len(obj) > _MAX_STR:
        return obj[:_MAX_STR] + "...[truncated]"
    return obj


def reset_budget() -> None:
    """Test hook: forget per-reason dump budgets/cooldowns."""
    _dumps_by_reason.clear()
    _last_dump_at.clear()


def reset_registry() -> None:
    """Test hook: drop every registered recorder. A process-wide dump
    flushes EVERY live ring by design, so tests that assert on artifact
    counts must first clear recorders leaked by earlier fixtures (the
    registry is weak, but test engines often stay referenced)."""
    _recorders.clear()


def dump_all(reason: str, detail: str = "") -> list[str]:
    """Write every registered recorder's ring to one artifact each;
    returns the paths. Synchronous file I/O — trigger sites are failure
    paths (drain, stall, kill), never the step loop; async callers that
    care hop through ``asyncio.to_thread``. Budgeted per reason so a
    flapping trigger cannot fill the disk."""
    if not enabled():
        return []
    now = time.monotonic()
    if now - _last_dump_at.get(reason, -_DUMP_COOLDOWN_S) < _DUMP_COOLDOWN_S:
        return []
    if _dumps_by_reason.get(reason, 0) >= _MAX_DUMPS_PER_REASON:
        log.warning("flight dump budget exhausted for reason %r", reason)
        return []
    _last_dump_at[reason] = now
    _dumps_by_reason[reason] = _dumps_by_reason.get(reason, 0) + 1
    out_dir = artifact_dir()
    try:
        os.makedirs(out_dir, exist_ok=True)
    except OSError:
        log.exception("flight dump dir %r not writable", out_dir)
        return []
    paths: list[str] = []
    stamp = int(time.time() * 1e3)
    for rec in list(_recorders.values()):
        records = rec.snapshot()
        if not records:
            continue
        payload = {
            "schema": 1,
            "reason": reason,
            "detail": detail,
            "recorder": rec.name,
            "pid": os.getpid(),
            "dumped_at": time.time(),
            "recorder_started_at": rec.started_at,
            "capacity": rec.capacity,
            "records": redact(records),
        }
        fname = f"flight-{os.getpid()}-{rec.name}-{reason}-{stamp}.json"
        path = os.path.join(out_dir, fname)
        try:
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f, separators=(",", ":"))
            os.replace(tmp, path)  # crash-safe like DiskKvPool.put
            paths.append(path)
        except OSError:
            log.exception("flight dump write failed (%s)", path)
    if paths:
        log.warning(
            "flight recorder: dumped %d artifact(s) for %r -> %s",
            len(paths), reason, out_dir,
        )
    return paths
