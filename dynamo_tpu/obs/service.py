"""The aggregator as a service: standalone process or embedded helper.

Standalone (``python -m dynamo_tpu.obs --namespace dynamo``): one process
that subscribes to the namespace's snapshot subject and serves the fleet
``/metrics`` + ``/fleet`` on its own status server — the reference's
``components/metrics`` service shape.

Embedded: the HTTP frontend calls :func:`attach_aggregator` so its own
``/metrics`` carries the fleet series and ``/fleet`` renders without a
second process (the common single-frontend deployment).
"""

from __future__ import annotations

import asyncio
import logging

from aiohttp import web

from dynamo_tpu.obs.aggregator import FleetAggregator
from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.status_server import SystemStatusServer

log = logging.getLogger("dynamo_tpu.obs.service")


async def run_aggregator(
    runtime: DistributedRuntime,
    namespace: str = "dynamo",
    host: str = "0.0.0.0",
    port: int = 8082,
    stale_after_s: float = 10.0,
    ready_event: asyncio.Event | None = None,
    aggregator_out: list | None = None,
    status_out: list | None = None,
) -> None:
    """The standalone aggregator service loop (mirrors run_frontend's
    shape: create, serve, wait for shutdown, tear down)."""
    aggregator = FleetAggregator(
        runtime.store, namespace=namespace, stale_after_s=stale_after_s
    )
    status = SystemStatusServer(host=host, port=port)
    aggregator.bind(status.metrics, status.before_render)
    aggregator.slo.bind_metrics(status.metrics)

    async def fleet(request: web.Request) -> web.Response:
        return web.json_response(aggregator.fleet_payload())

    # Route added before start() — aiohttp freezes the router on setup.
    status.app.router.add_get("/fleet", fleet)
    await status.start()
    await aggregator.start()
    if aggregator_out is not None:
        aggregator_out.append(aggregator)
    if status_out is not None:
        status_out.append(status)
    log.info(
        "fleet aggregator serving namespace %r on http://%s:%d",
        namespace, host, status.port,
    )
    if ready_event is not None:
        ready_event.set()
    try:
        await runtime.wait_for_shutdown()
    finally:
        await aggregator.stop()
        await status.stop()


async def attach_aggregator(
    runtime: DistributedRuntime,
    manager,
    service,
    stale_after_s: float = 10.0,
    out: dict | None = None,
) -> dict[str, FleetAggregator]:
    """Embed a fleet aggregator in a running frontend: one aggregator per
    discovered namespace, bound to the frontend's own metrics registry
    (fleet series appear on the frontend's ``/metrics``; ``/fleet`` is
    served by the HTTP service). Worker retirement wires through each
    served model's discovery watch (lease loss) on top of the
    retired-snapshot and staleness paths.

    Returns the live ``{namespace: aggregator}`` map (it grows as models
    are discovered; pass ``out`` to share the live map with the caller)."""
    aggregators: dict[str, FleetAggregator] = out if out is not None else {}

    async def on_added(entry, mdc) -> None:
        agg = aggregators.get(entry.namespace)
        if agg is None:
            agg = FleetAggregator(
                runtime.store,
                namespace=entry.namespace,
                stale_after_s=stale_after_s,
            )
            agg.bind(service.metrics, service.before_metrics)
            agg.slo.bind_metrics(service.metrics)
            aggregators[entry.namespace] = agg
            await agg.start()
        served = manager.get(entry.name)
        if served is not None:
            # Lease-loss retirement: the same instance watch the router
            # uses to drop dead workers.
            agg.attach_client(served.client)

    # Runs after the manager's own _on_added (registration order), so the
    # ServedModel (and its client watch) already exists.
    manager.watcher.on_model_added.append(on_added)
    # Models discovered BEFORE the attach (workers registered first)
    # never fire the callback — sweep them now.
    for served in manager.list_models():
        await on_added(served.entry, served.mdc)
    service.fleet_fn = lambda: {
        ns: agg.fleet_payload() for ns, agg in aggregators.items()
    }
    return aggregators
