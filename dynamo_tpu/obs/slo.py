"""Per-request SLO attribution: TTFT/TPOT budget breakdown per tenant.

The tracer (PR 2) already records every phase of a request — ``http`` /
``tokenize`` / ``route`` on the frontend, ``sched_admit`` / ``prefill`` /
``decode`` on the worker — as spans sharing one trace and carrying the
request id. This module stitches them into per-request budget records:

    ttft  = tokenize + route + prefill      (prefill span = worker
            submit -> first token, queue time included; ``queue`` is the
            sched_admit sub-window, ``prefill_compute`` the remainder)
    tpot  = decode / (tokens - 1)

Worker-side spans reach the frontend/aggregator inside metric snapshots
(:class:`~dynamo_tpu.obs.snapshot.MetricSnapshot.requests`), scanned off
the process-local ring by :class:`PhaseScanner` — nothing new on the hot
path; the spans were already being recorded.

The :class:`SloAttributor` keys everything by the validated tenant id
(PR 10's fairness identity): per-tenant ``dynamo_slo_*`` histograms on
/metrics, and a ``/fleet`` summary with p50/p99 + attainment against
:class:`SloTargets`. Tenant cardinality is CAPPED (64 + ``__other__``),
like every tenant-labeled export since PR 10 — a rotating x-tenant-id
spray cannot grow the aggregator's /metrics without bound.
"""

from __future__ import annotations

import logging
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field

from dynamo_tpu import knobs

log = logging.getLogger("dynamo_tpu.obs.slo")

# Worker-side request phases (recorded by TpuEngine / MockTpuEngine at
# stream close); the decode span is recorded last, so it completes the
# request's worker-side record.
WORKER_PHASES = frozenset({"sched_admit", "prefill", "decode"})
WORKER_COMPLETE_ON = "decode"

# Frontend-side phases (http root finishes last, in the handler finally).
FRONTEND_PHASES = frozenset({"http", "tokenize", "route"})
FRONTEND_COMPLETE_ON = "http"

# Max distinct tenant label values tracked/exported (PR 10's cap).
MAX_SLO_TENANTS = 64
OTHER_TENANT = "__other__"

# TTFT spans queue + prefill (tens of ms .. many seconds under load);
# TPOT is a per-token mean (sub-ms .. tens of ms). Edges chosen to match
# the measured ranges, like the tuned trace-phase buckets.
SLO_TTFT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.2, 0.35, 0.5, 0.75, 1.0, 1.5,
    2.5, 5.0, 10.0, 30.0, 60.0,
)
SLO_TPOT_BUCKETS = (
    0.0002, 0.0005, 0.001, 0.002, 0.005, 0.01, 0.02, 0.035, 0.05,
    0.075, 0.1, 0.2, 0.5, 1.0,
)


@dataclass(frozen=True)
class SloTargets:
    """Attainment targets (defaults mirror the planner's SlaTargets;
    override via DYN_SLO_TTFT_MS / DYN_SLO_TPOT_MS)."""

    ttft_s: float = knobs.default("DYN_SLO_TTFT_MS") / 1e3
    tpot_s: float = knobs.default("DYN_SLO_TPOT_MS") / 1e3

    @classmethod
    def from_env(cls) -> "SloTargets":
        return cls(
            ttft_s=knobs.get_float("DYN_SLO_TTFT_MS") / 1e3,
            tpot_s=knobs.get_float("DYN_SLO_TPOT_MS") / 1e3,
        )


class PhaseScanner:
    """Incrementally scan a TraceCollector's span ring for finished
    per-request phase spans, grouped by request id.

    Each call to :meth:`scan` returns the request records COMPLETED since
    the previous call: ``{"rid", "tenant", "t", "tokens", "phases":
    {name: seconds}}``. A request completes when its ``complete_on`` span
    lands (decode worker-side, http frontend-side — both are recorded
    last by their emitters). Seen-span tracking and open groups are both
    bounded, so a scanner on a busy collector stays O(ring).
    """

    def __init__(
        self,
        collector,
        names: frozenset[str] = WORKER_PHASES,
        complete_on: str = WORKER_COMPLETE_ON,
        max_pending: int = 1024,
        max_seen: int = 16384,
    ):
        self._collector = collector
        self._names = names
        self._complete_on = complete_on
        self._max_pending = max_pending
        self._pending: "OrderedDict[str, dict]" = OrderedDict()
        self._seen: set[str] = set()
        self._seen_order: deque[str] = deque()
        self._max_seen = max_seen

    def _note_seen(self, span_id: str) -> None:
        self._seen.add(span_id)
        self._seen_order.append(span_id)
        while len(self._seen_order) > self._max_seen:
            self._seen.discard(self._seen_order.popleft())

    def scan(self) -> list[dict]:
        out: list[dict] = []
        for span in self._collector.spans():  # atomic ring copy
            if span.name not in self._names or span.span_id in self._seen:
                continue
            rid = span.attrs.get("request_id")
            if not rid:
                continue
            self._note_seen(span.span_id)
            group = self._pending.get(rid)
            if group is None:
                group = self._pending[rid] = {"phases": {}, "tenant": "", "tokens": 0}
                while len(self._pending) > self._max_pending:
                    self._pending.popitem(last=False)  # drop oldest open group
            group["phases"][span.name] = span.duration_s
            tenant = span.attrs.get("tenant")
            if tenant:
                group["tenant"] = str(tenant)
            if span.name == "decode":
                group["tokens"] = int(span.attrs.get("tokens", 0) or 0)
            if span.name == self._complete_on:
                self._pending.pop(rid, None)
                out.append(
                    {
                        "rid": rid,
                        "tenant": group["tenant"],
                        "t": span.end_s,
                        "tokens": group["tokens"],
                        "phases": group["phases"],
                    }
                )
        return out


def quantile(sorted_vals: list[float], q: float) -> float:
    """Order-statistic quantile on a pre-sorted list (shared by the SLO
    summary and the aggregator's fleet rollups — one definition, so the
    two percentile families can never diverge)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


@dataclass
class _TenantSlo:
    ttft: deque = field(default_factory=lambda: deque(maxlen=512))
    tpot: deque = field(default_factory=lambda: deque(maxlen=512))
    phase_sum: dict = field(default_factory=dict)
    n: int = 0
    ttft_ok: int = 0
    tpot_ok: int = 0
    tpot_n: int = 0


class SloAttributor:
    """Merge frontend- and worker-side request records into per-tenant
    TTFT/TPOT budget breakdowns.

    Worker records are authoritative (they carry queue/prefill/decode and
    the token count); frontend records add tokenize/route. A worker-only
    record finalizes after ``grace_s`` (direct-engine traffic has no
    frontend side); a frontend-only record past grace is dropped (the
    request never reached an instrumented worker — e.g. full shed).
    """

    def __init__(
        self,
        targets: SloTargets | None = None,
        grace_s: float = 5.0,
        max_tenants: int = MAX_SLO_TENANTS,
        metrics=None,
        namespace: str = "dynamo",
    ):
        self.targets = targets or SloTargets.from_env()
        self.grace_s = grace_s
        self.max_tenants = max_tenants
        # Labels every histogram: several namespaces' attributors can
        # share one registry (embedded multi-namespace frontend) without
        # merging their observations.
        self.namespace = namespace
        self._metrics = metrics  # MetricsRegistry | None
        self._pending: "OrderedDict[str, dict]" = OrderedDict()
        self._tenants: dict[str, _TenantSlo] = {}
        # Recently finalized request ids (bounded): duplicate records —
        # snapshot redelivery, or several single-process workers scanning
        # one shared collector — must not double-count a request.
        self._done: set[str] = set()
        self._done_order: deque[str] = deque()
        self.records_total = 0

    def bind_metrics(self, metrics) -> None:
        """Export ``dynamo_slo_*`` per-tenant histograms on this registry
        as records finalize."""
        self._metrics = metrics

    # -- ingest ------------------------------------------------------------

    def ingest(self, records: list[dict], side: str = "worker") -> None:
        now = time.monotonic()
        for rec in records:
            rid = rec.get("rid")
            if not rid or rid in self._done:
                continue
            entry = self._pending.get(rid)
            if entry is None:
                entry = self._pending[rid] = {"t0": now}
                while len(self._pending) > 4096:
                    self._pending.popitem(last=False)
            entry[side] = rec
            if "worker" in entry and "frontend" in entry:
                self._pending.pop(rid, None)
                self._note_done(rid)
                self._finalize(entry)
        self.sweep(now)

    def sweep(self, now: float | None = None) -> None:
        """Finalize worker-only entries past grace; drop frontend-only
        ones (never reached a worker)."""
        now = time.monotonic() if now is None else now
        expired = [
            rid
            for rid, e in self._pending.items()
            if now - e["t0"] > self.grace_s
        ]
        for rid in expired:
            entry = self._pending.pop(rid)
            if "worker" in entry:
                self._note_done(rid)
                self._finalize(entry)

    def _note_done(self, rid: str) -> None:
        self._done.add(rid)
        self._done_order.append(rid)
        while len(self._done_order) > 16384:
            self._done.discard(self._done_order.popleft())

    def _tenant_key(self, tenant: str) -> str:
        tenant = tenant or "default"
        if tenant in self._tenants or len(self._tenants) < self.max_tenants:
            return tenant
        return OTHER_TENANT

    def _finalize(self, entry: dict) -> None:
        worker = entry.get("worker") or {}
        frontend = entry.get("frontend") or {}
        wp = worker.get("phases") or {}
        fp = frontend.get("phases") or {}
        queue = wp.get("sched_admit", 0.0)
        prefill = wp.get("prefill", 0.0)
        decode = wp.get("decode", 0.0)
        tokenize = fp.get("tokenize", 0.0)
        route = fp.get("route", 0.0)
        tokens = int(worker.get("tokens", 0) or 0)
        ttft = tokenize + route + prefill
        tpot = decode / (tokens - 1) if tokens > 1 and decode > 0 else None
        phases = {
            "tokenize": tokenize,
            "route": route,
            "queue": queue,
            "prefill_compute": max(0.0, prefill - queue),
            "decode": decode,
        }
        tenant = self._tenant_key(worker.get("tenant") or frontend.get("tenant") or "")
        st = self._tenants.get(tenant)
        if st is None:
            st = self._tenants[tenant] = _TenantSlo()
        st.n += 1
        self.records_total += 1
        st.ttft.append(ttft)
        if ttft <= self.targets.ttft_s:
            st.ttft_ok += 1
        if tpot is not None:
            st.tpot.append(tpot)
            st.tpot_n += 1
            if tpot <= self.targets.tpot_s:
                st.tpot_ok += 1
        for name, v in phases.items():
            st.phase_sum[name] = st.phase_sum.get(name, 0.0) + v
        if self._metrics is not None:
            scoped = self._metrics.scoped(
                namespace=self.namespace, service="slo", tenant=tenant
            )
            scoped.histogram(
                "slo_ttft_seconds",
                "Per-request TTFT attributed from stitched trace phases "
                "(tokenize + route + worker submit->first-token)",
                buckets=SLO_TTFT_BUCKETS,
            ).observe(ttft)
            if tpot is not None:
                scoped.histogram(
                    "slo_tpot_seconds",
                    "Per-request mean time-per-output-token "
                    "(decode phase / (tokens - 1))",
                    buckets=SLO_TPOT_BUCKETS,
                ).observe(tpot)
            for name, v in phases.items():
                self._metrics.scoped(
                    namespace=self.namespace, service="slo",
                    tenant=tenant, phase=name,
                ).histogram(
                    "slo_phase_seconds",
                    "Per-request TTFT/TPOT budget breakdown by phase",
                    buckets=SLO_TTFT_BUCKETS,
                ).observe(v)

    def attainment_counters(self) -> dict[str, float]:
        """Cumulative fleet-wide attainment counters (all tenants): the
        aggregator diffs consecutive snapshots of these into the planner
        Observation's *windowed* SLO attainment, so the controller reacts
        to the last window's misses rather than the lifetime average."""
        out = {"ttft_ok": 0.0, "ttft_n": 0.0, "tpot_ok": 0.0, "tpot_n": 0.0}
        for st in self._tenants.values():
            out["ttft_ok"] += st.ttft_ok
            out["ttft_n"] += st.n
            out["tpot_ok"] += st.tpot_ok
            out["tpot_n"] += st.tpot_n
        return out

    # -- summary (/fleet + bench) ------------------------------------------

    def summary(self) -> dict:
        tenants = {}
        for tenant, st in sorted(self._tenants.items()):
            ttfts = sorted(st.ttft)
            tpots = sorted(st.tpot)
            tenants[tenant] = {
                "requests": st.n,
                "ttft_p50_ms": round(quantile(ttfts, 0.50) * 1e3, 3),
                "ttft_p99_ms": round(quantile(ttfts, 0.99) * 1e3, 3),
                "tpot_p50_ms": round(quantile(tpots, 0.50) * 1e3, 3),
                "tpot_p99_ms": round(quantile(tpots, 0.99) * 1e3, 3),
                "ttft_attainment": round(st.ttft_ok / st.n, 4) if st.n else 0.0,
                "tpot_attainment": (
                    round(st.tpot_ok / st.tpot_n, 4) if st.tpot_n else 1.0
                ),
                "phase_mean_ms": {
                    name: round(v / st.n * 1e3, 3)
                    for name, v in sorted(st.phase_sum.items())
                },
            }
        return {
            "targets": {
                "ttft_ms": round(self.targets.ttft_s * 1e3, 1),
                "tpot_ms": round(self.targets.tpot_s * 1e3, 1),
            },
            "records": self.records_total,
            "pending": len(self._pending),
            "tenants": tenants,
        }
