"""Metric snapshots over the store/event plane: the wire + the publisher.

Workers (and frontends) publish one compact :class:`MetricSnapshot` per
interval on ``obs_snapshots.{namespace}`` — the same pub/sub plane the KV
events and load metrics already ride (reference ``components/metrics``
over NATS, PAPER.md §L0/L1). The fleet aggregator composes them into
``/metrics`` series with ``worker_id`` labels and rollups.

The publish path is OFF the hot step: a periodic asyncio task reads the
engines' existing stats dicts (the exact callables the status-server
gauges already bind), the tracer's cumulative per-phase totals, and the
finished-request phase records — no host sync, no step-lock hold, no
work added to plan/dispatch. Snapshots ride a bounded loop-affine buffer
(``_snapbuf``) drained by one ordered task, mirroring the KvEventPublisher
shape; overflow drops the OLDEST snapshot visibly (latest-wins — a
snapshot is a point-in-time state, unlike a KV event there is nothing to
resync).
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable

import msgpack

from dynamo_tpu.runtime import wire

log = logging.getLogger("dynamo_tpu.obs.snapshot")


def obs_subject(namespace: str) -> str:
    """One subject per namespace: every component's snapshots land here
    (the snapshot itself carries role + component)."""
    return f"obs_snapshots.{namespace}"


@dataclass
class MetricSnapshot:
    """One publisher's point-in-time metric state.

    ``families`` maps a family name ("scheduler", "kv_cache", "spec",
    "kv_pool", "frontend", ...) to a flat numeric dict — the same keys
    the status-server gauge tables export, so the aggregator re-labels
    without translation. ``phases`` carries CUMULATIVE per-phase
    ``(count, sum_seconds)`` pairs keyed ``service/phase`` (the
    aggregator diffs consecutive snapshots into per-window means).
    ``requests`` carries finished per-request phase records (SLO
    attribution) observed since the previous snapshot. ``retired=True``
    is the drain retraction: the aggregator drops every series for this
    worker immediately instead of waiting for staleness/lease expiry.
    """

    worker_id: int
    role: str = "worker"  # "worker" | "frontend"
    component: str = ""
    seq: int = 0
    t: float = 0.0
    # Publisher incarnation (stamped once per SnapshotPublisher): a
    # restarted process re-using a pinned worker_id starts seq over at 1,
    # and the aggregator must not drop its fresh snapshots as
    # out-of-order against the dead incarnation's higher seq.
    epoch: float = 0.0
    retired: bool = False
    families: dict[str, dict[str, float]] = field(default_factory=dict)
    tenants: dict[str, dict[str, float]] = field(default_factory=dict)
    phases: dict[str, tuple[float, float]] = field(default_factory=dict)
    requests: list[dict] = field(default_factory=list)
    # Aggregator-local arrival stamp (NOT on the wire): staleness is
    # judged against the aggregator's own clock, so cross-host clock skew
    # can never retire a live, publishing worker.
    received_at: float = 0.0

    def to_wire(self) -> bytes:
        d: dict[str, Any] = {
            wire.SNAP_WORKER: self.worker_id,
            wire.SNAP_ROLE: self.role,
            wire.SNAP_COMPONENT: self.component,
            wire.SNAP_SEQ: self.seq,
            wire.SNAP_TIME: self.t,
            wire.SNAP_EPOCH: self.epoch,
            wire.SNAP_FAMILIES: self.families,
            wire.SNAP_TENANTS: self.tenants,
            wire.SNAP_PHASES: {k: [c, s] for k, (c, s) in self.phases.items()},
            wire.SNAP_REQUESTS: self.requests,
        }
        if self.retired:
            d[wire.SNAP_RETIRED] = 1
        return msgpack.packb(d, use_bin_type=True)

    @classmethod
    def from_wire(cls, raw: bytes) -> "MetricSnapshot":
        d = msgpack.unpackb(raw, raw=False)
        return cls(
            worker_id=d[wire.SNAP_WORKER],
            role=d.get(wire.SNAP_ROLE, "worker"),
            component=d.get(wire.SNAP_COMPONENT, ""),
            seq=d.get(wire.SNAP_SEQ, 0),
            t=d.get(wire.SNAP_TIME, 0.0),
            epoch=d.get(wire.SNAP_EPOCH, 0.0),
            retired=bool(d.get(wire.SNAP_RETIRED, 0)),
            families=d.get(wire.SNAP_FAMILIES, {}),
            tenants=d.get(wire.SNAP_TENANTS, {}),
            phases={k: (v[0], v[1]) for k, v in (d.get(wire.SNAP_PHASES) or {}).items()},
            requests=list(d.get(wire.SNAP_REQUESTS) or []),
        )


def numeric_only(d: dict) -> dict[str, float]:
    """Snapshot families carry numbers only (strings like kv_dtype stay
    on the worker's own /metrics as info gauges)."""
    return {k: float(v) for k, v in d.items() if isinstance(v, (int, float))}


# Frontend metric families mirrored into the "frontend" snapshot family:
# prometheus sample name -> snapshot key. Cumulative, like every family —
# the aggregator diffs windows (MetricsObserver's math, event-plane fed).
_FRONTEND_SAMPLES = {
    "dynamo_frontend_requests_total": "requests_total",
    "dynamo_frontend_requests_shed_total": "shed_total",
    "dynamo_frontend_inflight_requests": "inflight",
    "dynamo_frontend_time_to_first_token_seconds_sum": "ttft_sum",
    "dynamo_frontend_time_to_first_token_seconds_count": "ttft_count",
    "dynamo_frontend_inter_token_latency_seconds_sum": "itl_sum",
    "dynamo_frontend_inter_token_latency_seconds_count": "itl_count",
    "dynamo_frontend_input_sequence_tokens_sum": "isl_sum",
    "dynamo_frontend_input_sequence_tokens_count": "isl_count",
    "dynamo_frontend_output_sequence_tokens_sum": "osl_sum",
    "dynamo_frontend_output_sequence_tokens_count": "osl_count",
}


def frontend_totals(metrics) -> dict[str, float]:
    """Sum the frontend's request/latency series (labels collapsed) from
    its live MetricsRegistry — the "frontend" snapshot family that feeds
    the fleet observer's planner Observation."""
    totals: dict[str, float] = {}
    for metric in metrics.registry.collect():
        for sample in metric.samples:
            key = _FRONTEND_SAMPLES.get(sample.name)
            if key is not None:
                totals[key] = totals.get(key, 0.0) + float(sample.value)
    return totals


class SnapshotPublisher:
    """Periodic snapshot publisher for one process.

    ``collectors`` maps family name -> zero-arg callable returning a
    stats dict (the same callables the status-server gauges bind);
    ``tenant_source`` the per-tenant fair-queue stats; ``phase_source``
    the tracer's cumulative per-phase totals; ``request_source`` the
    finished-request phase records since last call (SLO attribution).

    All buffer mutation is loop-affine: the tick task builds + enqueues,
    the single drain task publishes in order (KvEventPublisher's shape).
    """

    def __init__(
        self,
        store,
        namespace: str,
        worker_id: int,
        role: str = "worker",
        component: str = "",
        interval_s: float = 1.0,
        buffer: int = 64,
    ):
        self._store = store
        self._subject = obs_subject(namespace)
        self.worker_id = worker_id
        self.role = role
        self.component = component
        self.interval_s = max(0.01, interval_s)
        self._buffer = max(1, buffer)
        # Incarnation stamp: lets the aggregator tell a restarted
        # publisher (seq reset) from an out-of-order redelivery.
        self.epoch = time.time()
        self._snapbuf: deque[MetricSnapshot] = deque()
        self._wakeup = asyncio.Event()
        self._idle = asyncio.Event()
        self._idle.set()
        self._seq = 0
        self._tick_task: asyncio.Task | None = None
        self._drain_task: asyncio.Task | None = None
        self.collectors: dict[str, Callable[[], dict]] = {}
        self.tenant_source: Callable[[], dict] | None = None
        self.phase_source: Callable[[], dict] | None = None
        self.request_source: Callable[[], list] | None = None
        # Observability of the observability: publish/drop counters.
        self.snapshots_published_total = 0
        self.snapshots_dropped_total = 0
        self.publish_errors_total = 0

    # -- snapshot build (loop-affine, non-blocking) ------------------------

    def build(self, retired: bool = False) -> MetricSnapshot:
        self._seq += 1
        families: dict[str, dict[str, float]] = {}
        for name, collect in self.collectors.items():
            try:
                families[name] = numeric_only(collect())
            except Exception:  # noqa: BLE001 — one bad family must not kill the tick
                log.exception("snapshot collector %r failed", name)
        tenants: dict[str, dict[str, float]] = {}
        if self.tenant_source is not None:
            try:
                tenants = {
                    str(t): numeric_only(st)
                    for t, st in self.tenant_source().items()
                }
            except Exception:  # noqa: BLE001
                log.exception("snapshot tenant source failed")
        phases: dict[str, tuple[float, float]] = {}
        if self.phase_source is not None:
            phases = dict(self.phase_source())
        requests: list = []
        if self.request_source is not None:
            try:
                requests = list(self.request_source())
            except Exception:  # noqa: BLE001
                log.exception("snapshot request source failed")
        return MetricSnapshot(
            worker_id=self.worker_id,
            role=self.role,
            component=self.component,
            seq=self._seq,
            t=time.time(),
            epoch=self.epoch,
            retired=retired,
            families=families,
            tenants=tenants,
            phases=phases,
            requests=requests,
        )

    def publish_nowait(self, retired: bool = False) -> None:
        snap = self.build(retired=retired)
        if len(self._snapbuf) >= self._buffer:
            # Latest-wins: a snapshot is point-in-time state, so the
            # OLDEST is the one to drop — visibly.
            self._snapbuf.popleft()
            self.snapshots_dropped_total += 1
        self._snapbuf.append(snap)
        self._idle.clear()
        self._wakeup.set()
        if self._drain_task is None or self._drain_task.done():
            self._drain_task = asyncio.get_running_loop().create_task(
                self._drain()
            )

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        if self._tick_task is None or self._tick_task.done():
            self._tick_task = asyncio.create_task(self._tick_loop())

    async def stop(self) -> None:
        if self._tick_task:
            self._tick_task.cancel()
        if self._drain_task:
            self._drain_task.cancel()

    async def retire(self, timeout: float = 5.0) -> bool:
        """Drain retraction: publish one final ``retired`` snapshot and
        flush, so the aggregator removes this worker's series NOW rather
        than at staleness/lease expiry (the PR 11 inventory-retirement
        shape). Called from ``runtime.on_drain``."""
        if self._tick_task:
            self._tick_task.cancel()
        self.publish_nowait(retired=True)
        return await self.flush(timeout)

    async def flush(self, timeout: float = 5.0) -> bool:
        if not self._snapbuf and (
            self._drain_task is None or self._drain_task.done()
        ):
            return True
        try:
            await asyncio.wait_for(self._idle.wait(), timeout)
            return True
        except asyncio.TimeoutError:
            log.warning(
                "snapshot publisher %d: flush timed out (%d queued)",
                self.worker_id, len(self._snapbuf),
            )
            return False

    # -- tasks -------------------------------------------------------------

    async def _tick_loop(self) -> None:
        while True:
            self.publish_nowait()
            await asyncio.sleep(self.interval_s)

    async def _drain(self) -> None:
        while True:
            if not self._snapbuf:
                self._idle.set()
                self._wakeup.clear()
                await self._wakeup.wait()
                continue
            snap = self._snapbuf.popleft()
            try:
                await self._store.publish(self._subject, snap.to_wire())
                self.snapshots_published_total += 1
            except ConnectionError:
                self.publish_errors_total += 1
                log.warning("snapshot publish failed (store down?)")
            except Exception:  # noqa: BLE001 — the drain task must survive any
                # one bad publish: dying here strands _idle cleared, so
                # every later flush()/retire() burns its full timeout.
                self.publish_errors_total += 1
                log.exception("snapshot publish failed")

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        return {
            "snapshots_published": self.snapshots_published_total,
            "snapshots_dropped": self.snapshots_dropped_total,
            "publish_errors": self.publish_errors_total,
            "queued": len(self._snapbuf),
        }
