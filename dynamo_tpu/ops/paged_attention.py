"""Paged attention for the decode path: one new query token per sequence
attends over that sequence's KV blocks scattered through the paged cache.

Two implementations with identical semantics:

- :func:`paged_attention_reference` — pure jnp gather + masked softmax.
  Runs anywhere (CPU test mesh included) and is the ground truth.
- :func:`paged_attention_pallas` — Pallas TPU kernel. Grid over the batch;
  per sequence it walks the block table, DMAs each KV page HBM→VMEM, and
  folds it into an online-softmax accumulator (flash-attention style), so
  the full [S] attention row never materializes and HBM traffic is exactly
  the live pages.

The reference framework outsources this op to vLLM's CUDA kernels; on TPU
we own it (SURVEY.md §7 "hard parts"). Cache layout is head-major flat
``[n_kv, total_slots, d]`` with ``slot = block * block_size + offset``:
per-head page DMAs then slice only the untiled leading axes (TPU tiling
constrains the last two dims), and tensor parallelism shards axis 0.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dynamo_tpu import knobs

_NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


def paged_attention_reference(
    q: jax.Array,            # [B, n_q, d]
    k_cache: jax.Array,      # [n_kv, total_slots, d]
    v_cache: jax.Array,      # [n_kv, total_slots, d]
    block_tables: jax.Array, # [B, max_blocks] int32 (padding -> garbage block)
    seq_lens: jax.Array,     # [B] int32, cached tokens (excl. self when given)
    *,
    block_size: int,
    scale: float | None = None,
    k_self: jax.Array | None = None,  # [B, n_kv, d]: the current token's K/V,
    v_self: jax.Array | None = None,  # attended without being in the cache yet
    k_scale: jax.Array | None = None,  # [n_kv, total_slots] f32: int8 caches'
    v_scale: jax.Array | None = None,  # per-slot-per-head dequant scales
) -> jax.Array:              # [B, n_q, d]
    B, n_q, d = q.shape
    n_kv = k_cache.shape[0]
    group = n_q // n_kv
    max_blocks = block_tables.shape[1]
    S = max_blocks * block_size
    scale = scale if scale is not None else d ** -0.5

    offsets = jnp.arange(block_size, dtype=jnp.int32)
    slots = (block_tables[:, :, None] * block_size + offsets[None, None, :]).reshape(B, S)
    k = k_cache[:, slots]  # [n_kv, B, S, d]
    v = v_cache[:, slots]
    if k_scale is not None:
        # int8 cache: dequant fused into the gather (the gather itself
        # moved half the bytes of the bf16 layout).
        from dynamo_tpu.engine.kv_quant import dequantize_kv

        k = dequantize_kv(k, k_scale[:, slots])
        v = dequantize_kv(v, v_scale[:, slots])

    qg = q.reshape(B, n_kv, group, d).astype(jnp.float32)
    kf = k.astype(jnp.float32)
    logits = jnp.einsum("bhgd,hbsd->bhgs", qg, kf) * scale
    mask = jnp.arange(S, dtype=jnp.int32)[None, :] < seq_lens[:, None]  # [B, S]
    logits = jnp.where(mask[:, None, None, :], logits, _NEG_INF)
    vf = v.astype(jnp.float32)
    if k_self is not None:
        # The self position: one extra key/value, always valid. Keeps the
        # cache write out of the layer loop (deferred-scatter decode).
        s_self = jnp.einsum("bhgd,bhd->bhg", qg, k_self.astype(jnp.float32)) * scale
        logits = jnp.concatenate([logits, s_self[..., None]], axis=-1)
        vf = jnp.concatenate(
            [vf, v_self.astype(jnp.float32).transpose(1, 0, 2)[:, :, None, :]], axis=2
        )
    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhgs,hbsd->bhgd", weights, vf)
    return out.reshape(B, n_q, d).astype(q.dtype)


def _paged_attn_kernel(
    # scalar prefetch
    block_tables_ref,  # [B, max_blocks] SMEM
    seq_lens_ref,      # [B] SMEM
    # inputs
    q_ref,             # [1, 1, group, d] VMEM (this sequence, this kv head)
    k_hbm,             # [n_kv, total_slots, d] ANY/HBM
    v_hbm,
    k_scale_hbm,       # [n_kv, n_blocks, block_size] ANY/HBM (int8 only;
    v_scale_hbm,       # dummy otherwise) — page-shaped so the DMA indexes
    #                    a whole page on an untiled axis and never slices
    #                    the minor (lane) dim at non-128 offsets
    k_self_ref,        # [1, 1, 1, d] VMEM — current token's K, this head
    v_self_ref,
    # output
    o_ref,             # [1, 1, group, d] VMEM
    # scratch
    k_page,            # [2, block_size, d] VMEM double buffer
    v_page,
    sem,               # DMA sems [2, 2]
    *quant_scratch,    # with_quant: k_sc, v_sc ([2, block_size] f32), sc_sem
    block_size: int,
    scale: float,
    with_self: bool,
    with_quant: bool,
):
    # One grid instance = one (sequence, kv head): all matmuls are plain 2D
    # (Mosaic's tpu.matmul does not support mismatched batch dims).
    b = pl.program_id(0)
    h = pl.program_id(1)
    seq_len = seq_lens_ref[b]
    num_blocks = jax.lax.div(seq_len + block_size - 1, block_size)
    group, d = q_ref.shape[2], q_ref.shape[3]
    if with_quant:
        k_sc, v_sc, sc_sem = quant_scratch

    q = q_ref[0, 0].astype(jnp.float32) * scale  # [group, d]

    def page_dma(slot, blk_idx):
        page = block_tables_ref[b, blk_idx]
        start = page * block_size
        copies = [
            pltpu.make_async_copy(
                k_hbm.at[h, pl.ds(start, block_size)], k_page.at[slot], sem.at[slot, 0]
            ),
            pltpu.make_async_copy(
                v_hbm.at[h, pl.ds(start, block_size)], v_page.at[slot], sem.at[slot, 1]
            ),
        ]
        if with_quant:
            # int8 pages halve the bulk DMA above; the scale tiles ride
            # alongside (block_size f32 each — noise next to the page).
            # Whole-page rows indexed on the untiled block axis, so no
            # dynamic minor-dim slicing (Mosaic lane alignment).
            copies.append(
                pltpu.make_async_copy(
                    k_scale_hbm.at[h, page], k_sc.at[slot], sc_sem.at[slot, 0]
                )
            )
            copies.append(
                pltpu.make_async_copy(
                    v_scale_hbm.at[h, page], v_sc.at[slot], sc_sem.at[slot, 1]
                )
            )
        return copies

    # Warm up the pipeline with the first page.
    @pl.when(num_blocks > 0)
    def _():
        for c in page_dma(0, 0):
            c.start()

    def body(i, carry):
        m, l, acc = carry  # [group, 1], [group, 1], [group, d]
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < num_blocks)
        def _():
            for c in page_dma(1 - slot, i + 1):
                c.start()

        for c in page_dma(slot, i):
            c.wait()

        k = k_page[slot].astype(jnp.float32)   # [bs, d]
        v = v_page[slot].astype(jnp.float32)
        if with_quant:
            # Dequant in-VMEM, after the halved page copy landed.
            k = k * k_sc[slot][:, None]
            v = v * v_sc[slot][:, None]
        # s[g, t] = q[g, :] . k[t, :]
        s = jax.lax.dot_general(
            q, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [group, bs]
        pos = i * block_size + jax.lax.broadcasted_iota(jnp.int32, (1, block_size), 1)
        s = jnp.where(pos < seq_len, s, _NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                            # [group, bs]
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [group, d]
        acc_new = acc * alpha + pv
        return m_new, l_new, acc_new

    m0 = jnp.full((group, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((group, 1), jnp.float32)
    acc0 = jnp.zeros((group, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_blocks, body, (m0, l0, acc0))
    if with_self:
        # Fold in the current token (not yet in the cache): one extra
        # always-valid position, so deferred-scatter decode stays exact.
        ks = k_self_ref[0, 0, 0].astype(jnp.float32)   # [d]
        vs = v_self_ref[0, 0, 0].astype(jnp.float32)
        s_self = jnp.sum(q * ks[None, :], axis=-1, keepdims=True)  # [group, 1]
        m_new = jnp.maximum(m, s_self)
        p = jnp.exp(s_self - m_new)
        alpha = jnp.exp(m - m_new)
        l = alpha * l + p
        acc = acc * alpha + p * vs[None, :]
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def paged_attention_pallas(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    block_tables: jax.Array,
    seq_lens: jax.Array,
    *,
    block_size: int,
    scale: float | None = None,
    k_self: jax.Array | None = None,
    v_self: jax.Array | None = None,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
    interpret: bool = False,
) -> jax.Array:
    B, n_q, d = q.shape
    max_blocks = block_tables.shape[1]
    n_kv = k_cache.shape[0]
    scale = scale if scale is not None else d ** -0.5

    group = n_q // n_kv
    qg = q.reshape(B, n_kv, group, d)
    with_self = k_self is not None
    with_quant = k_scale is not None
    self_dtype = jnp.float32 if with_quant else k_cache.dtype
    if not with_self:
        k_self = jnp.zeros((B, n_kv, d), self_dtype)
        v_self = jnp.zeros((B, n_kv, d), self_dtype)
    if with_quant:
        # Page-shaped scale layout for the kernel: the DMA then indexes
        # [head, page] and copies a whole block_size row — no dynamic
        # slicing of the minor (lane) dimension, which f32 tiling would
        # reject at non-128-aligned offsets.
        k_scale = k_scale.reshape(n_kv, -1, block_size)
        v_scale = v_scale.reshape(n_kv, -1, block_size)
    else:
        # Tiny dummies (never DMA'd — with_quant is static).
        k_scale = jnp.zeros((n_kv, 1, 1), jnp.float32)
        v_scale = jnp.zeros((n_kv, 1, 1), jnp.float32)
    # 4D so the tiled trailing dims are (1, d) == the array dims — the
    # head index stays on an untiled axis (Mosaic alignment rules).
    k_self4 = k_self.reshape(B, n_kv, 1, d)
    v_self4 = v_self.reshape(B, n_kv, 1, d)

    kernel = functools.partial(
        _paged_attn_kernel,
        block_size=block_size,
        scale=scale,
        with_self=with_self,
        with_quant=with_quant,
    )
    self_spec = pl.BlockSpec(
        (1, 1, 1, d), lambda b, h, *_: (b, h, 0, 0), memory_space=pltpu.VMEM
    )
    scratch = [
        pltpu.VMEM((2, block_size, d), k_cache.dtype),
        pltpu.VMEM((2, block_size, d), v_cache.dtype),
        pltpu.SemaphoreType.DMA((2, 2)),
    ]
    if with_quant:
        scratch += [
            pltpu.VMEM((2, block_size), jnp.float32),
            pltpu.VMEM((2, block_size), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 2)),
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, n_kv),
        in_specs=[
            pl.BlockSpec(
                (1, 1, group, d), lambda b, h, *_: (b, h, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            self_spec,
            self_spec,
        ],
        out_specs=pl.BlockSpec(
            (1, 1, group, d), lambda b, h, *_: (b, h, 0, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=scratch,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, n_kv, group, d), q.dtype),
        interpret=interpret,
    )(
        block_tables.astype(jnp.int32), seq_lens.astype(jnp.int32),
        qg, k_cache, v_cache, k_scale, v_scale, k_self4, v_self4,
    )
    return out.reshape(B, n_q, d)


def pallas_supported(head_dim: int, block_size: int, dtype) -> bool:
    """TPU tiling constraints on the page DMA: lane dim (head_dim) must be
    a multiple of 128 and the sublane slice (block_size) a multiple of the
    dtype's min tile (int8 pages tile at 32 sublanes)."""
    itemsize = jnp.dtype(dtype).itemsize
    sublane = {1: 32, 2: 16}.get(itemsize, 8)
    return head_dim % 128 == 0 and block_size % sublane == 0


def paged_attention(
    q, k_cache, v_cache, block_tables, seq_lens, *, block_size, scale=None,
    k_self=None, v_self=None, k_scale=None, v_scale=None,
) -> jax.Array:
    """Dispatch: XLA gather path by default — measured faster than the
    current Pallas kernel at serving context lengths (the kernel's
    (batch x head) grid runs serially per TensorCore; its page DMAs are
    latency-bound). ``DYNAMO_TPU_PAGED_ATTN=pallas`` opts into the kernel
    (wins when live context is a small fraction of the table span; also
    the base for the next-round ragged multi-page kernel).

    ``k_scale``/``v_scale`` mark int8 caches: the kernel DMAs the halved
    int8 pages plus their per-slot scale tiles and dequantizes in-VMEM
    after the copy — decode attention is DMA-latency-bound (PERF.md), so
    the halved page copy is exactly where int8 can beat the bf16 path;
    the XLA path fuses the dequant into its gather."""
    if (
        jax.default_backend() == "tpu"
        and knobs.get_str("DYNAMO_TPU_PAGED_ATTN") == "pallas"
        and pallas_supported(q.shape[-1], block_size, k_cache.dtype)
    ):
        return paged_attention_pallas(
            q, k_cache, v_cache, block_tables, seq_lens,
            block_size=block_size, scale=scale, k_self=k_self, v_self=v_self,
            k_scale=k_scale, v_scale=v_scale,
        )
    return paged_attention_reference(
        q, k_cache, v_cache, block_tables, seq_lens,
        block_size=block_size, scale=scale, k_self=k_self, v_self=v_self,
        k_scale=k_scale, v_scale=v_scale,
    )
