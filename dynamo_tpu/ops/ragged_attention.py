"""Ragged paged attention: one attention call for prefill, decode, and
mixed batches over the paged KV cache.

Semantics (vLLM-TPU style; the reference outsources this op to vLLM's CUDA
kernels — on TPU it is first-party, SURVEY.md §7 "hard parts"):

- ``q``: ``[T, n_q_heads, d]`` — every scheduled token this step,
  concatenated across sequences (ragged; no per-sequence padding).
- ``kv_pages``: ``[n_pages, page_size, 2 * n_kv_heads, d]`` — the paged KV
  cache for ONE layer, K/V interleaved on the combined-head axis (K at
  even indices, V at odd). The new tokens' K/V must already be written.
- ``kv_lens[s]``: tokens of sequence ``s`` IN CACHE (including this
  step's chunk).
- ``page_indices``: ``[S, pages_per_seq]`` block table per sequence.
- ``cu_q_lens``: ``[S + 1]`` cumulative query lengths; sequence ``s`` owns
  q rows ``cu[s]:cu[s+1]``. Entries past ``num_seqs`` repeat ``cu[num_seqs]``.
- ``num_seqs``: ``i32[1]`` — valid sequences (dynamic).

Query token ``i`` of sequence ``s`` sits at absolute position
``kv_lens[s] - q_len_s + i`` and attends all cache positions ``<=`` its own
— exactly chunked-prefill causality; a decode step is the ``q_len_s == 1``
special case.

On TPU dispatches to the Pallas kernel
(jax.experimental.pallas.ops.tpu.ragged_paged_attention); elsewhere (CPU
test meshes) runs a vectorized jnp reference with identical semantics.
Under tensor parallelism wrap with :func:`sharded_ragged_attention` —
attention is embarrassingly parallel over heads, so the shard_map has no
collectives.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)

# Decode-shape tuned Pallas grid (measured on v5e, round 4: 8-page DMA
# batches, 8-query blocks — 7.35 ms/step vs 7.99 at q=32 and 8.67 at the
# kernel's own defaults; tools/profile_decode.py + PERF.md). Long-context
# calls use the kernel's tuned table instead. Env-overridable for on-chip
# tuning sweeps; 0 = always use the kernel's defaults.
from dynamo_tpu.jax_compat import shard_map
from dynamo_tpu import knobs as _knobs

_DECODE_KV_PAGES_PER_BLOCK = _knobs.get_int("DYNAMO_TPU_ATTN_PAGES_PER_BLOCK")
_DECODE_QUERIES_PER_BLOCK = _knobs.get_int("DYNAMO_TPU_ATTN_QUERIES_PER_BLOCK")
# Prefill-shaped calls: bound the query block explicitly — the kernel's
# own tuned table can pick whole-wave q blocks that blow the scoped-VMEM
# limit (16 MB on v5e under the axon runtime) at T >= 2048.
_PREFILL_QUERIES_PER_BLOCK = _knobs.get_int(
    "DYNAMO_TPU_ATTN_PREFILL_QUERIES_PER_BLOCK"
)


def ragged_paged_attention_ref(
    q: jax.Array,             # [T, n_q, d]
    kv_pages: jax.Array,      # [n_pages, page_size, 2*n_kv, d]
    kv_lens: jax.Array,       # [S] i32
    page_indices: jax.Array,  # [S, pages_per_seq] i32
    cu_q_lens: jax.Array,     # [S+1] i32
    num_seqs: jax.Array,      # [1] i32
    *,
    sm_scale: float,
    kv_scales: jax.Array | None = None,  # [n_pages, page_size, 2*n_kv] f32
) -> jax.Array:               # [T, n_q, d]
    T, n_q, d = q.shape
    n_pages, page_size, n_comb, _ = kv_pages.shape
    n_kv = n_comb // 2
    group = n_q // n_kv
    S, pages_per_seq = page_indices.shape
    span = pages_per_seq * page_size

    t = jnp.arange(T, dtype=jnp.int32)
    # seq_id[t] = s such that cu[s] <= t < cu[s+1]
    seq_id = jnp.sum(t[:, None] >= cu_q_lens[None, 1:], axis=1).astype(jnp.int32)
    seq_id = jnp.minimum(seq_id, S - 1)
    valid_row = t < cu_q_lens[num_seqs[0]]

    q_len = cu_q_lens[seq_id + 1] - cu_q_lens[seq_id]          # [T]
    abs_pos = kv_lens[seq_id] - q_len + (t - cu_q_lens[seq_id])  # [T]

    tables_t = page_indices[seq_id]                      # [T, pages_per_seq]
    offs = jnp.arange(page_size, dtype=jnp.int32)
    slots = (tables_t[:, :, None] * page_size + offs[None, None, :]).reshape(T, span)
    flat = kv_pages.reshape(n_pages * page_size, n_comb, d)
    kv = flat[slots]                                     # [T, span, 2*n_kv, d]
    if kv_scales is not None:
        # Fused dequant-on-gather (int8 pages): the gather above moved
        # HALF the bytes a bf16 cache would; the dequant multiplies the
        # gathered values by their per-slot-per-head scales in registers.
        from dynamo_tpu.engine.kv_quant import dequantize_kv

        scf = kv_scales.reshape(n_pages * page_size, n_comb)[slots]
        kvf = dequantize_kv(kv, scf)
    else:
        kvf = kv.astype(jnp.float32)
    k = kvf[:, :, 0::2, :]                               # [T, span, n_kv, d]
    v = kvf[:, :, 1::2, :]

    qg = q.reshape(T, n_kv, group, d).astype(jnp.float32)
    s = jnp.einsum("thgd,tshd->thgs", qg, k) * sm_scale  # [T, n_kv, group, span]
    pos = jnp.arange(span, dtype=jnp.int32)
    mask = (pos[None, :] <= abs_pos[:, None]) & (pos[None, :] < kv_lens[seq_id][:, None])
    mask = mask & valid_row[:, None]
    s = jnp.where(mask[:, None, None, :], s, _NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    w = jnp.where(valid_row[:, None, None, None], w, 0.0)
    out = jnp.einsum("thgs,tshd->thgd", w, v)
    return out.reshape(T, n_q, d).astype(q.dtype)


def ragged_paged_attention(
    q, kv_pages, kv_lens, page_indices, cu_q_lens, num_seqs, *,
    sm_scale: float, kv_scales=None,
) -> jax.Array:
    """Backend dispatch: Pallas kernel on TPU, jnp reference elsewhere.

    The kernel wants MXU/VPU-aligned shapes (head_dim % 128, page_size %
    8); models outside that (e.g. the byte-sized test presets) run the
    XLA reference path even on TPU — the kernel's trace-time asserts are
    not a serving error.

    ``kv_scales`` marks an int8 cache (``kv_pages`` int8 + per-slot-per-
    head f32 scales). The reference path fuses dequant into its gather
    (halved gather bytes). The TPU library kernel takes real-valued
    pages, so the int8 serving path dequantizes the REFERENCED pages
    before the call when that is smaller than the whole cache, else the
    whole cache — the honest first-cut fallback (capacity win, no
    traffic win); the traffic win lives in the extended first-party
    decode kernel (ops/paged_attention.py, int8 page DMA + in-VMEM
    dequant), opted into via DYNAMO_TPU_PAGED_ATTN=pallas and measured
    by bench.py run_kvquant_ab."""
    d = q.shape[-1]
    page_size = kv_pages.shape[1]
    if jax.default_backend() == "tpu" and d % 128 == 0 and page_size % 8 == 0:
        if kv_scales is not None:
            from dynamo_tpu.engine.kv_quant import dequantize_kv

            n_pages = kv_pages.shape[0]
            S, pages_per_seq = page_indices.shape
            if S * pages_per_seq < n_pages:
                # Dequant-on-gather: materialize only the pages this
                # batch references, renumbering the tables to match.
                ids = page_indices.reshape(-1)
                kv_pages = dequantize_kv(kv_pages[ids], kv_scales[ids]).astype(
                    q.dtype
                )
                page_indices = jnp.arange(
                    S * pages_per_seq, dtype=jnp.int32
                ).reshape(S, pages_per_seq)
            else:
                # Whole-LAYER dequant (this function sees one layer's
                # pages): transient = n_pages bf16 rows for one layer,
                # ~1/num_layers of a full bf16 cache — bounded, but the
                # read traffic is a capacity-only fallback (see docstring).
                kv_pages = dequantize_kv(kv_pages, kv_scales).astype(q.dtype)
            kv_scales = None
        from jax.experimental.pallas.ops.tpu.ragged_paged_attention import (
            ragged_paged_attention as _kernel,
        )

        kw = {}
        # Always pass an explicit grid (env 0 restores kernel defaults):
        # decode-shaped calls use the measured decode grid; prefill waves
        # cap the query block — the kernel's own tuned table can pick
        # whole-wave q blocks that exceed scoped VMEM (16 MB on v5e under
        # the axon runtime) at large T or long block tables.
        if _DECODE_KV_PAGES_PER_BLOCK > 0:
            qb = (
                _DECODE_QUERIES_PER_BLOCK
                if q.shape[0] <= 64
                else min(_PREFILL_QUERIES_PER_BLOCK, q.shape[0])
            )
            kw = dict(
                num_kv_pages_per_block=min(
                    _DECODE_KV_PAGES_PER_BLOCK, page_indices.shape[1]
                ),
                num_queries_per_block=qb,
            )
        return _kernel(
            q, kv_pages, kv_lens, page_indices, cu_q_lens, num_seqs,
            sm_scale=sm_scale, **kw,
        )
    return ragged_paged_attention_ref(
        q, kv_pages, kv_lens, page_indices, cu_q_lens, num_seqs,
        sm_scale=sm_scale, kv_scales=kv_scales,
    )


def sharded_ragged_attention(
    mesh: Mesh,
    q, kv_pages, kv_lens, page_indices, cu_q_lens, num_seqs, *,
    sm_scale: float, kv_scales=None,
) -> jax.Array:
    """Ragged attention under tensor parallelism: heads split over the
    mesh's ``tp`` axis, zero collectives (each shard owns its q heads and
    the matching combined-KV block; dp replicates). int8 caches shard
    their scale pages on the same combined-head axis as the KV pages."""
    if kv_scales is not None:
        fn = functools.partial(ragged_paged_attention, sm_scale=sm_scale)

        def quant_fn(q, kv_pages, kv_scales, kv_lens, page_indices, cu, ns):
            return fn(
                q, kv_pages, kv_lens, page_indices, cu, ns,
                kv_scales=kv_scales,
            )

        return shard_map(
            quant_fn,
            mesh=mesh,
            in_specs=(
                P(None, "tp", None),          # q: heads sharded
                P(None, None, "tp", None),    # kv_pages: combined heads
                P(None, None, "tp"),          # kv_scales: combined heads
                P(), P(), P(), P(),
            ),
            out_specs=P(None, "tp", None),
            check_vma=False,
        )(q, kv_pages, kv_scales, kv_lens, page_indices, cu_q_lens, num_seqs)
    fn = functools.partial(
        ragged_paged_attention, sm_scale=sm_scale
    )
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=(
            P(None, "tp", None),          # q: heads sharded
            P(None, None, "tp", None),    # kv_pages: combined heads sharded
            P(), P(), P(), P(),
        ),
        out_specs=P(None, "tp", None),
        check_vma=False,
    )(q, kv_pages, kv_lens, page_indices, cu_q_lens, num_seqs)
