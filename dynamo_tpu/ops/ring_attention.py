"""Ring attention: causal self-attention with the sequence sharded over a
mesh axis — the long-context prefill primitive.

The reference has NO sequence/context parallelism anywhere (SURVEY.md
§2.6: "ABSENT"); on TPU it is first-class. Each device holds one sequence
chunk of Q, K, V. K/V chunks rotate around the ring via `ppermute` (ICI
neighbor exchange) while every device folds each visiting chunk into an
online-softmax accumulator — full causal attention materializing only
[T_local, T_local] scores at a time, so context scales linearly with the
ring size. (Blockwise ring attention; see PAPERS.md.)

GQA-aware: q [T, n_q, d], k/v [T, n_kv, d]. Computation is f32.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.jax_compat import shard_map

_NEG = -0.7 * float(jnp.finfo(jnp.float32).max)


def causal_attention_reference(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
    """Single-device full causal attention (ground truth)."""
    T, n_q, d = q.shape
    n_kv = k.shape[1]
    group = n_q // n_kv
    scale = d ** -0.5
    qg = q.reshape(T, n_kv, group, d).astype(jnp.float32) * scale
    s = jnp.einsum("thgd,shd->thgs", qg, k.astype(jnp.float32))
    mask = jnp.arange(T)[:, None] >= jnp.arange(T)[None, :]
    s = jnp.where(mask[:, None, None, :], s, _NEG)
    w = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("thgs,shd->thgd", w, v.astype(jnp.float32))
    return out.reshape(T, n_q, d).astype(q.dtype)


def _ring_attention_local(
    q: jax.Array,   # [T_loc, n_q, d] — this device's query chunk
    k: jax.Array,   # [T_loc, n_kv, d]
    v: jax.Array,
    *,
    axis_name: str,
    axis_size: int,
) -> jax.Array:
    T_loc, n_q, d = q.shape
    n_kv = k.shape[1]
    group = n_q // n_kv
    scale = d ** -0.5
    my = jax.lax.axis_index(axis_name)

    qg = (q.astype(jnp.float32) * scale).reshape(T_loc, n_kv, group, d)
    q_pos = my * T_loc + jnp.arange(T_loc, dtype=jnp.int32)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    m = jnp.full((T_loc, n_kv, group, 1), _NEG, jnp.float32)
    l = jnp.zeros((T_loc, n_kv, group, 1), jnp.float32)
    acc = jnp.zeros((T_loc, n_kv, group, d), jnp.float32)
    k_cur, v_cur = k.astype(jnp.float32), v.astype(jnp.float32)

    for p in range(axis_size):
        # The chunk in hand after p rotations originated on device my - p.
        src = (my - p) % axis_size
        kv_pos = src * T_loc + jnp.arange(T_loc, dtype=jnp.int32)
        s = jnp.einsum("thgd,shd->thgs", qg, k_cur)
        mask = q_pos[:, None] >= kv_pos[None, :]
        s = jnp.where(mask[:, None, None, :], s, _NEG)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        pexp = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = alpha * l + jnp.sum(pexp, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum("thgs,shd->thgd", pexp, v_cur)
        m = m_new

        if p + 1 < axis_size:
            # Neighbor exchange over ICI; overlapping this with the next
            # pass's compute is XLA's latency-hiding scheduler's job.
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)

    out = acc / jnp.maximum(l, 1e-30)
    return out.reshape(T_loc, n_q, d).astype(q.dtype)


def sequence_parallel_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    n = n_devices or len(devices)
    return Mesh(np.asarray(devices[:n]), ("sp",))


def ring_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh, axis_name: str = "sp"
) -> jax.Array:
    """Causal self-attention over a sequence sharded on ``axis_name``.

    q/k/v are full [T, heads, d] arrays (or already sharded); T must be
    divisible by the axis size. Runs as shard_map over the mesh.
    """
    axis_size = mesh.shape[axis_name]
    if q.shape[0] % axis_size:
        raise ValueError(f"sequence {q.shape[0]} not divisible by {axis_size}-way sp")
    spec = P(axis_name, None, None)
    fn = shard_map(
        functools.partial(
            _ring_attention_local, axis_name=axis_name, axis_size=axis_size
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
