"""Multi-host bootstrap and helpers: one global device mesh over many
processes.

The reference serves multi-node models by plumbing engine flags
(`/root/reference/components/backends/sglang/docs/multinode-examples.md:10`
— ``dist-init-addr``, ``nnodes``, ``node-rank``); the engines' NCCL/MPI
stacks do the rest. Here the equivalent is first-party and TPU-native:
``jax.distributed`` forms the multi-controller runtime, the engine's mesh
spans every process's chips (`jax.devices()` is global after init), and
XLA/GSPMD inserts the ICI/DCN collectives. Every process runs the same
jitted programs in the same order (classic JAX SPMD); the worker CLI's
leader/follower step replication (backends/jax/multihost.py) keeps the
host-side schedulers in lockstep.
"""

from __future__ import annotations

import logging

import numpy as np

log = logging.getLogger("dynamo_tpu.multihost")


def init_multihost(
    coordinator: str,
    num_processes: int,
    process_id: int,
    local_cpu_devices: int | None = None,
) -> None:
    """Join the multi-controller runtime. Call BEFORE any other jax use.

    ``local_cpu_devices`` forces the CPU platform with that many virtual
    devices per process — the cluster-free validation mode (a 2-process x
    4-device CPU "pod"); on real TPU hosts leave it None and the local
    chips attach themselves. Mirrors the reference's dist-init-addr /
    nnodes / node-rank worker flags (multinode-examples.md:10).
    """
    import jax

    if local_cpu_devices:
        force_cpu_devices(local_cpu_devices)
    jax.distributed.initialize(
        coordinator, num_processes=num_processes, process_id=process_id
    )
    log.info(
        "multihost runtime up: process %d/%d, %d local / %d global devices",
        process_id, num_processes,
        len(jax.local_devices()), len(jax.devices()),
    )


def force_cpu_devices(n: int) -> None:
    """Virtual-device validation mode: N CPU devices stand in for a
    multi-chip host. The TPU PJRT plugin ignores the JAX_PLATFORMS env
    var; the config update is the authoritative switch. Call BEFORE any
    other jax use."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", int(n))


def fetch_replicated(x) -> np.ndarray:
    """Host value of a program output on a (possibly multi-host) mesh.

    Single-host arrays fetch directly. On a mesh spanning processes the
    array is not fully addressable; a REPLICATED output still has the
    full value in every local shard, which is what the engine's
    scheduler needs — identical on every host. A sharded output would
    silently hand each host a partial view, so that is a hard error."""
    if getattr(x, "is_fully_addressable", True):
        return np.asarray(x)
    shard = x.addressable_shards[0]
    if tuple(shard.data.shape) != tuple(x.shape):
        raise RuntimeError(
            f"multi-host fetch of a non-replicated output: global shape "
            f"{tuple(x.shape)}, local shard {tuple(shard.data.shape)} — "
            "the program must produce replicated host-visible outputs"
        )
    return np.asarray(shard.data)


def start_host_copy(x) -> None:
    """Enqueue an async device->host copy of one array (no-op on arrays
    that don't support it, e.g. plain numpy): the later blocking fetch
    then lands data that has been streaming in the background instead of
    paying the full transfer at the sync point."""
    fn = getattr(x, "copy_to_host_async", None)
    if fn is not None:
        fn()


def fetch_replicated_many(arrays) -> list[np.ndarray]:
    """Batched host fetch: start async D2H copies for EVERY array first,
    then land them in order — the transfers overlap each other (and any
    still-running device work) instead of serializing one blocking fetch
    per array. Used for the sampler's (chosen, top_ids, top_lps) logprob
    tuple, which the engine previously fetched as three serial syncs."""
    arrs = list(arrays)
    for a in arrs:
        start_host_copy(a)
    return [fetch_replicated(a) for a in arrs]
