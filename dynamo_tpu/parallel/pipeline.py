"""Pipeline parallelism: layer-staged GPipe over a ``("pp",)`` device mesh.

The reference never implements pipeline parallelism itself — it plumbs
``pipeline-parallel-size`` flags down to its engines
(`/root/reference/components/backends/sglang/docs/multinode-examples.md:10`,
SURVEY.md §2.6 "engine-delegated"). On TPU the partitioning is
first-party, and it is NOT a port of a GPU schedule: the whole fill/drain
pipeline is ONE jitted ``shard_map`` program in which every stage runs the
same code on its own layer slice and activations rotate between stages via
``lax.ppermute`` over ICI.

Design:

- **Layer-axis sharding.** The params pytree keeps its stacked ``[L, ...]``
  layer arrays; PP shards axis 0 over ``pp`` (``pp_param_specs``), so stage
  ``s`` physically holds layers ``[s*L/pp, (s+1)*L/pp)`` — and the paged KV
  cache ``[L, pages, page_size, 2kv, d]`` shards the same way: each stage
  scatters and reads only its own layers' pages. No resharding, no copies:
  placement IS the stage assignment.
- **Microbatched rounds.** The ragged token batch (same layout as
  :func:`dynamo_tpu.engine.model.forward_tokens` — prefill chunks, decode
  tokens, mixed) splits into ``M`` equal row chunks. Round ``r`` has stage
  ``s`` working microbatch ``r - s``; after each round activations
  ``ppermute`` one stage forward. ``M + pp - 1`` rounds drain the pipe;
  steady-state efficiency is ``M / (M + pp - 1)``.
- **Chunked-prefill causality for free.** Microbatch ``m``'s attention
  reads pages written by microbatches ``< m`` in earlier rounds plus its
  own scatter this round — exactly the chunked-prefill semantics the
  ragged kernel already implements (per-chunk ``kv_lens`` computed by the
  host-side :func:`plan_microbatches`), so sequences may straddle chunk
  boundaries.
- **Replicated exit.** Only the last stage's final-norm rows are real; a
  ``psum`` over ``pp`` replicates each sequence's last-token hidden state
  so the logits matmul (and fused sampling above it) run identically on
  every device — multi-host leaders can fetch outputs from any process
  (same rule as `_replicate_out`, engine/core.py).

Composition: v1 is a pure-``pp`` mesh (tp=1 inside each stage); ``pp×tp``
composes by nesting :func:`sharded_ragged_attention`'s head split inside
each stage and is left until a >8-device single-host target exists.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.jax_compat import shard_map
from dynamo_tpu.engine.model import (
    Params,
    _dot,
    _logits,
    dense_layer,
    rms_norm,
    rope_tables,
)


def make_pp_mesh(pp: int, devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    if pp > len(devices):
        raise ValueError(f"pp={pp} needs {pp} devices, have {len(devices)}")
    return Mesh(np.asarray(devices[:pp]), ("pp",))


def pp_param_specs(cfg: ModelConfig, pp: int) -> dict[str, Any]:
    """PartitionSpecs for `model.init_params` pytrees under PP: stacked
    layer arrays shard axis 0 over ``pp``; embeddings/norms replicate
    (stage 0 embeds, the last stage projects — via the psum exit every
    stage holds both, which is what lets the logits matmul run
    replicated)."""
    if cfg.num_layers % pp:
        raise ValueError(f"pp={pp} must divide num_layers={cfg.num_layers}")
    layers = {
        "attn_norm": P("pp"),
        "mlp_norm": P("pp"),
        "wqkv": P("pp"),
        "wo": P("pp"),
    }
    if cfg.attn_qkv_bias:
        layers["bqkv"] = P("pp")
    if cfg.is_moe:
        layers["w_router"] = P("pp")
        layers["w_gate"] = P("pp")
        layers["w_up"] = P("pp")
        layers["w_down"] = P("pp")
    else:
        layers["wgu"] = P("pp")
        layers["w_down"] = P("pp")
    specs = {
        "embed": P(None, None),
        "layers": layers,
        "final_norm": P(None),
        "fuse_tp": P(),
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, None)
    return specs


def _is_quant_leaf(x) -> bool:
    """An int8 ``{"w", "scale"}`` projection (model.quantize_weight)."""
    return isinstance(x, dict) and set(x) == {"w", "scale"}


def shard_params_pp(params: Params, cfg: ModelConfig, mesh: Mesh) -> Params:
    """Place a params pytree per :func:`pp_param_specs`. int8 params keep
    their ``{"w", "scale"}`` dict leaves: a stacked layer projection is
    ``w [L, ...]`` + ``scale [L, 1, out]`` — BOTH carry the layer axis
    first, so one ``P("pp")`` spec shards the pair onto its stage."""
    specs = pp_param_specs(cfg, int(mesh.shape["pp"]))
    if "fuse_tp" not in params:
        specs.pop("fuse_tp")

    def place(x, spec):
        put = lambda a: jax.device_put(a, NamedSharding(mesh, spec))
        if _is_quant_leaf(x):
            return {k: put(v) for k, v in x.items()}
        return put(x)

    return jax.tree.map(place, params, specs, is_leaf=_is_quant_leaf)


def cache_sharding_pp(mesh: Mesh, quantized: bool = False):
    """[L, pages, page_size, 2kv, d] — layer axis on pp (each stage holds
    only its own layers' KV). Quantized caches are a ``{"kv", "scale"}``
    dict of stacked arrays; the scale pages shard their layer axis the
    same way, so every stage owns matching (kv, scale) page pairs."""
    if quantized:
        return {
            "kv": NamedSharding(mesh, P("pp", None, None, None, None)),
            "scale": NamedSharding(mesh, P("pp", None, None, None)),
        }
    return NamedSharding(mesh, P("pp", None, None, None, None))


@dataclass
class PPPlan:
    """Host-planned microbatch schedule (static shapes: one compile per
    (T, S, n_micro) bucket combo, same rule as the engine's buckets)."""

    n_micro: int
    tokens: np.ndarray       # [M, Tm] i32
    positions: np.ndarray    # [M, Tm] i32
    write_pages: np.ndarray  # [M, Tm] i32 (garbage page on pad rows)
    write_offs: np.ndarray   # [M, Tm] i32
    kv_lens: np.ndarray      # [M, S] i32 — per seq, through this chunk
    cu_q_lens: np.ndarray    # [M, S+1] i32 — chunk-local ragged offsets
    last_local: np.ndarray   # [M, S] i32 — chunk-local row of seq's last token
    last_mask: np.ndarray    # [M, S] bool — last token lands in this chunk


def plan_microbatches(
    tokens: np.ndarray,       # [T] i32 ragged batch (model.forward_tokens layout)
    positions: np.ndarray,    # [T] i32
    write_pages: np.ndarray,  # [T] i32
    write_offs: np.ndarray,   # [T] i32
    kv_lens: np.ndarray,      # [S] i32 — per seq, through the whole batch
    cu_q_lens: np.ndarray,    # [S+1] i32
    num_seqs: int,
    last_rows: np.ndarray,    # [S] i32 global row of each seq's last token
    n_micro: int,
    garbage_block: int,
) -> PPPlan:
    """Split a ragged token batch into ``n_micro`` equal row chunks.
    Sequences may straddle chunks: per-chunk ``kv_lens`` count each
    sequence's tokens only through that chunk, which is exactly the
    chunked-prefill contract of :mod:`dynamo_tpu.ops.ragged_attention`."""
    T = len(tokens)
    S = len(kv_lens)
    M = max(1, int(n_micro))
    Tm = -(-T // M)
    pad = M * Tm - T

    def padded(arr, fill):
        return np.concatenate(
            [np.asarray(arr, np.int32), np.full(pad, fill, np.int32)]
        ).reshape(M, Tm)

    plan = PPPlan(
        n_micro=M,
        tokens=padded(tokens, 0),
        positions=padded(positions, 0),
        write_pages=padded(write_pages, garbage_block),
        write_offs=padded(write_offs, 0),
        kv_lens=np.ones((M, S), np.int32),
        cu_q_lens=np.zeros((M, S + 1), np.int32),
        last_local=np.zeros((M, S), np.int32),
        last_mask=np.zeros((M, S), bool),
    )
    cu = np.asarray(cu_q_lens, np.int64)  # dynalint: sync-ok — host plan arrays, not device arrays
    kv = np.asarray(kv_lens, np.int64)  # dynalint: sync-ok — host plan arrays, not device arrays
    for m in range(M):
        lo_c, hi_c = m * Tm, (m + 1) * Tm
        q_in_chunk = np.maximum(
            0,
            np.minimum(cu[1:], hi_c) - np.maximum(cu[:-1], lo_c),
        )  # [S]
        q_in_chunk[num_seqs:] = 0
        # kv through this chunk = total kv minus this seq's rows in LATER
        # chunks (rows are the seq's trailing tokens, kernel contract).
        after = np.maximum(0, cu[1:] - hi_c)
        # A sequence with no query rows in this chunk would otherwise get
        # a meaningless kv_len (e.g. prior_kv - offset for one that starts
        # in a later chunk). The ragged kernel skips zero-length queries,
        # but pin the value to the benign 1 so it can never be consumed.
        kv_through = np.where(q_in_chunk > 0, np.maximum(1, kv - after), 1)
        plan.kv_lens[m] = kv_through.astype(np.int32)
        plan.cu_q_lens[m, 1:] = np.cumsum(q_in_chunk).astype(np.int32)
        in_chunk = (last_rows >= lo_c) & (last_rows < hi_c)
        in_chunk[num_seqs:] = False
        plan.last_mask[m] = in_chunk
        plan.last_local[m] = np.where(in_chunk, last_rows - lo_c, 0).astype(
            np.int32
        )
    return plan


def _stage_layers(
    x, layers_local, cache_local, positions, write_pages, write_offs,
    kv_lens, block_tables, cu_q_lens, num_seqs, cfg: ModelConfig,
):
    """One stage's layer slice over one microbatch: the SAME
    :func:`model.dense_layer` block as forward_hidden, sliced out of the
    stage-local stacked ``[Lp, ...]`` cache (pp keeps the stacked layout
    — the layer axis IS the stage sharding — and pays the slice
    roundtrip the engine's tuple cache avoids; pp is a capacity mode,
    not the single-chip fast path). A quantized cache is a
    ``{"kv", "scale"}`` dict of stacked arrays: the per-layer slice
    hands dense_layer exactly the per-layer dict it already handles, and
    the write-back updates both members in place."""
    rope_cs = rope_tables(positions, cfg.head_dim, cfg.rope_theta)
    quant = isinstance(cache_local, dict)
    Lp = (cache_local["kv"] if quant else cache_local).shape[0]
    for j in range(Lp):
        lp = jax.tree.map(lambda a: a[j], layers_local)
        cache_j = (
            {k: v[j] for k, v in cache_local.items()} if quant
            else cache_local[j]
        )
        x, cache_j = dense_layer(
            x, lp, cache_j, positions, write_pages, write_offs,
            kv_lens, block_tables, cu_q_lens, num_seqs, cfg,
            rope_cs=rope_cs,
        )
        if quant:
            cache_local = {
                k: cache_local[k].at[j].set(cache_j[k]) for k in cache_local
            }
        else:
            cache_local = cache_local.at[j].set(cache_j)
    return x, cache_local


def _pp_program(
    params, cache, mb_tokens, mb_positions, mb_pages, mb_offs,
    mb_kv_lens, block_tables, mb_cu, num_seqs, mb_last_local, mb_last_mask,
    *, cfg: ModelConfig, engine: EngineConfig, pp: int, n_micro: int,
):
    """The per-device GPipe body (runs under shard_map over ``pp``)."""
    M = n_micro
    S = mb_kv_lens.shape[1]
    Tm = mb_tokens.shape[1]
    s = jax.lax.axis_index("pp")
    fwd_perm = [(i, (i + 1) % pp) for i in range(pp)]

    buf = jnp.zeros((Tm, cfg.hidden_size), cfg.jax_dtype)
    hid = jnp.zeros((S, cfg.hidden_size), jnp.float32)
    for r in range(M + pp - 1):
        mb = r - s
        valid = (mb >= 0) & (mb < M)
        mbc = jnp.clip(mb, 0, M - 1)
        toks = mb_tokens[mbc]
        # Stage 0 injects the embedding; later stages take the rotated
        # activation (the gather is a few KB — cheaper than branching).
        x = jnp.where(s == 0, params["embed"][toks], buf)
        pos = mb_positions[mbc]
        pages = jnp.where(valid, mb_pages[mbc], engine.garbage_block)
        x, cache = _stage_layers(
            x, params["layers"], cache, pos, pages, mb_offs[mbc],
            mb_kv_lens[mbc], block_tables, mb_cu[mbc], num_seqs, cfg,
        )
        # Last stage banks each sequence's last-token hidden state the
        # round its microbatch drains.
        normed = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
        take = normed[mb_last_local[mbc]]  # [S, h]
        emit = valid & (s == pp - 1) & mb_last_mask[mbc]
        hid = hid + jnp.where(emit[:, None], take.astype(jnp.float32), 0.0)
        if r < M + pp - 2:
            buf = jax.lax.ppermute(x, "pp", fwd_perm)
    # Replicate the exit: only stage pp-1 contributed.
    hid = jax.lax.psum(hid, "pp")
    return hid, cache


def _param_specs_tree(params: Params):
    specs = jax.tree.map(lambda _: P(), params)
    specs["layers"] = jax.tree.map(lambda _: P("pp"), params["layers"])
    return specs


def pp_forward_impl(
    params: Params,
    cache: jax.Array,
    mb_tokens, mb_positions, mb_pages, mb_offs,
    mb_kv_lens, block_tables, mb_cu, num_seqs,
    mb_last_local, mb_last_mask,
    *,
    cfg: ModelConfig,
    engine: EngineConfig,
    mesh: Mesh,
    n_micro: int,
):
    """Traceable body of :func:`pp_forward_tokens` (EngineCore jits it
    inside its own fused prefill+sample program)."""
    if cfg.is_moe:
        raise NotImplementedError(
            "pipeline parallelism for MoE presets: compose pp with the EP "
            "dispatch inside each stage (parallel/sharding.py) — not yet built"
        )
    pp = int(mesh.shape["pp"])
    hid, cache = shard_map(
        partial(_pp_program, cfg=cfg, engine=engine, pp=pp, n_micro=n_micro),
        mesh=mesh,
        in_specs=(
            _param_specs_tree(params),
            P("pp"),  # cache
            P(), P(), P(), P(),  # mb token arrays
            P(), P(), P(), P(),  # kv_lens, tables, cu, num_seqs
            P(), P(),            # last_local, last_mask
        ),
        out_specs=(P(), P("pp")),
        check_vma=False,
    )(
        params, cache, mb_tokens, mb_positions, mb_pages, mb_offs,
        mb_kv_lens, block_tables, mb_cu, num_seqs, mb_last_local, mb_last_mask,
    )
    return _logits(hid.astype(cfg.jax_dtype), params, cfg), cache


@partial(
    jax.jit,
    static_argnames=("cfg", "engine", "mesh", "n_micro"),
    donate_argnums=(1,),
)
def pp_forward_tokens(
    params: Params,
    cache: jax.Array,
    mb_tokens, mb_positions, mb_pages, mb_offs,
    mb_kv_lens, block_tables, mb_cu, num_seqs,
    mb_last_local, mb_last_mask,
    *,
    cfg: ModelConfig,
    engine: EngineConfig,
    mesh: Mesh,
    n_micro: int,
):
    """PP analogue of :func:`model.forward_tokens`: same ragged batch (via
    a :class:`PPPlan`), same result — last-token logits ``[S, vocab]`` f32
    plus the updated (layer-sharded) cache."""
    return pp_forward_impl(
        params, cache, mb_tokens, mb_positions, mb_pages, mb_offs,
        mb_kv_lens, block_tables, mb_cu, num_seqs, mb_last_local,
        mb_last_mask, cfg=cfg, engine=engine, mesh=mesh, n_micro=n_micro,
    )


def _pp_decode_round_body(
    params, cache, buf, r, store, tables_g, pos0_g, act_g,
    *, cfg: ModelConfig, engine: EngineConfig, pp: int, n_micro: int,
    n_steps: int,
):
    """One wavefront round (per device, under shard_map): stage ``s``
    advances work item ``idx = r - s`` — decode step ``idx // M`` of lane
    group ``idx % M`` — one stage down the pipe. The lm head is computed
    vocab-sharded over ``pp`` (each stage reads only its ``V/pp`` slice of
    the embedding per round, so per-step embedding traffic matches the
    unpipelined engine when ``M == pp``)."""
    M = n_micro
    s = jax.lax.axis_index("pp")
    bs = engine.block_size
    buf = buf[0]  # [Bm, h] (leading pp axis is the shard axis)
    Bm = buf.shape[0]

    idx = r - s
    valid = (idx >= 0) & (idx < n_steps * M)
    idxc = jnp.maximum(idx, 0)
    g = idxc % M
    t = idxc // M

    toks = store[g]                       # [Bm] this group's current token
    x = jnp.where(s == 0, params["embed"][toks], buf)
    pos = pos0_g[g] + t                   # [Bm]
    act = act_g[g]
    table = tables_g[g]                   # [Bm, pages]
    page = jnp.take_along_axis(table, (pos // bs)[:, None], axis=1)[:, 0]
    write_pages = jnp.where(act & valid, page, engine.garbage_block)
    write_offs = pos % bs
    kv_lens = jnp.where(act, pos + 1, 1).astype(jnp.int32)
    cu = jnp.arange(Bm + 1, dtype=jnp.int32)
    num_seqs = jnp.asarray([Bm], jnp.int32)

    x, cache = _stage_layers(
        x, params["layers"], cache, pos, write_pages, write_offs,
        kv_lens, table, cu, num_seqs, cfg,
    )
    # Exit: the last stage's final-norm rows, replicated; then this
    # stage's V/pp slice of the logits.
    normed = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    exit_h = jax.lax.psum(
        jnp.where((s == pp - 1) & valid, normed.astype(jnp.float32), 0.0),
        "pp",
    ).astype(cfg.jax_dtype)
    V = cfg.vocab_size
    Vp = V // pp
    if cfg.tie_embeddings:
        w = jax.lax.dynamic_slice_in_dim(params["embed"], s * Vp, Vp, axis=0)
        logits = jax.lax.dot_general(
            exit_h, w, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    else:
        lm = params["lm_head"]
        if isinstance(lm, dict):
            wq = jax.lax.dynamic_slice_in_dim(lm["w"], s * Vp, Vp, axis=1)
            sc = jax.lax.dynamic_slice_in_dim(lm["scale"], s * Vp, Vp, axis=1)
            logits = _dot(exit_h, {"w": wq, "scale": sc})
        else:
            w = jax.lax.dynamic_slice_in_dim(lm, s * Vp, Vp, axis=1)
            logits = _dot(exit_h, w)
    buf_next = jax.lax.ppermute(x, "pp", [(i, (i + 1) % pp) for i in range(pp)])
    return buf_next[None], cache, logits


def pp_decode_round(
    params, cache, buf, r, store, tables_g, pos0_g, act_g,
    *, cfg: ModelConfig, engine: EngineConfig, mesh: Mesh, n_micro: int,
    n_steps: int,
):
    """One wavefront decode round over the pp mesh. ``buf`` is the
    rotating activation buffer ``[pp, Bm, h]`` (stage-sharded); returns
    (buf', cache', logits ``[Bm, V]`` vocab-sharded over pp)."""
    pp = int(mesh.shape["pp"])
    return shard_map(
        partial(
            _pp_decode_round_body, cfg=cfg, engine=engine, pp=pp,
            n_micro=n_micro, n_steps=n_steps,
        ),
        mesh=mesh,
        in_specs=(
            _param_specs_tree(params),
            P("pp"),   # cache (layer axis)
            P("pp"),   # buf (stage axis)
            P(), P(), P(), P(), P(),  # r, store, tables, pos0, act
        ),
        out_specs=(P("pp"), P("pp"), P(None, "pp")),
        check_vma=False,
    )(params, cache, buf, r, store, tables_g, pos0_g, act_g)
