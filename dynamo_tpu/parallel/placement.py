"""Memory-placement planning: does a (model, engine, mesh) fit the pod?

The north-star deployment (BASELINE.md) is Llama-3-70B disaggregated P/D
on a v5e-64 (16 hosts x 4 chips, 16 GB HBM each). This module is the
planning math a topology is checked against BEFORE burning a pod on an
OOM: per-chip parameter bytes under the TP sharding
(`parallel/sharding.py` — projections split over tp, embeddings/norms
replicated, dp replicas each hold a full copy), per-chip KV-cache bytes
(the combined [L, pages, bs, 2kv, d] cache splits its head axis over
tp), plus a headroom fraction for activations and XLA scratch.

Shape source of truth: ``jax.eval_shape`` over ``model.init_params`` /
``model.init_cache`` with the very PartitionSpecs the engine serves under
(`param_partition_specs`) — the plan counts exactly the arrays the engine
allocates, not a hand formula that can drift from the code.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

from dynamo_tpu.engine.config import EngineConfig, ModelConfig

# v5e: 16 GiB HBM per chip.
V5E_HBM_BYTES = 16 * 1024**3


@dataclass
class MemoryPlan:
    param_bytes_per_chip: int
    cache_bytes_per_chip: int
    headroom_frac: float

    @property
    def total_per_chip(self) -> int:
        return math.ceil(
            (self.param_bytes_per_chip + self.cache_bytes_per_chip)
            * (1.0 + self.headroom_frac)
        )

    def fits(self, hbm_bytes: int = V5E_HBM_BYTES) -> bool:
        return self.total_per_chip <= hbm_bytes

    def describe(self, hbm_bytes: int = V5E_HBM_BYTES) -> str:
        gib = 1024**3
        return (
            f"params {self.param_bytes_per_chip / gib:.2f} GiB/chip + "
            f"kv {self.cache_bytes_per_chip / gib:.2f} GiB/chip "
            f"(+{self.headroom_frac:.0%} headroom) = "
            f"{self.total_per_chip / gib:.2f} / {hbm_bytes / gib:.0f} GiB"
        )


def memory_plan(
    model: ModelConfig,
    engine: EngineConfig,
    tp: int,
    dp: int = 1,
    quant: str | None = None,
    headroom_frac: float = 0.15,
) -> MemoryPlan:
    """Per-chip memory plan for serving ``model`` on a dp x tp mesh.

    Parameter shapes come from ``jax.eval_shape`` of the real init (no
    device memory is touched); each leaf's per-chip share divides by the
    product of mesh axes its PartitionSpec names. ``quant='int8'`` maps
    each projection leaf to 1 byte/element + one float32 scale per
    output column (matching model.quantize_params). dp never divides —
    every dp replica holds full params and its own cache.
    """
    import jax
    from jax.sharding import PartitionSpec

    from dynamo_tpu.engine.model import init_cache, init_params
    from dynamo_tpu.parallel.sharding import param_partition_specs

    params_shape = jax.eval_shape(
        lambda k: init_params(k, model, tp), jax.random.PRNGKey(0)
    )
    specs = param_partition_specs(model, tp)
    is_spec = lambda x: isinstance(x, PartitionSpec)  # noqa: E731
    spec_of = {
        path: spec
        for path, spec in jax.tree_util.tree_flatten_with_path(
            specs, is_leaf=is_spec
        )[0]
    }

    param_bytes = 0
    for path, sd in jax.tree_util.tree_flatten_with_path(params_shape)[0]:
        spec = spec_of[path]
        on_tp = any(name == "tp" for name in spec)
        div = tp if on_tp else 1
        n = math.prod(sd.shape) if sd.shape else 1
        if quant == "int8" and sd.ndim >= 2 and on_tp:
            # Quantized set = the projections — exactly the tp-annotated
            # matrices (quantize_params leaves embeddings/norms at the
            # model dtype).
            param_bytes += math.ceil(n / div)  # 1 byte / element
            param_bytes += math.ceil(sd.shape[-1] / div) * 4  # f32 scales
        else:
            param_bytes += math.ceil(n / div) * sd.dtype.itemsize

    # Per-layer tuple cache (model.init_cache): combined-head axis over tp.
    cache_shapes = jax.eval_shape(lambda: init_cache(model, engine))
    cache_bytes = sum(
        math.ceil(math.prod(s.shape) / tp) * s.dtype.itemsize
        for s in cache_shapes
    )

    return MemoryPlan(
        param_bytes_per_chip=param_bytes,
        cache_bytes_per_chip=cache_bytes,
        headroom_frac=headroom_frac,
    )
