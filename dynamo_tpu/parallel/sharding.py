"""Device-mesh sharding for the JAX engine: megatron-style TP + DP.

The reference delegates tensor parallelism to its GPU engines and only
plumbs `tp_size` flags (`components/backends/vllm/src/dynamo/vllm/args.py`,
SURVEY.md §2.6); on TPU the partitioning is first-party and rides ICI via
XLA collectives — no NCCL.

Mapping (classic megatron over axes ``("dp", "tp")``):
- fused ``wqkv``: column-parallel — the shard-blocked fuse layout
  (``[q_s | k_s | v_s]`` per shard, model.fuse_qkv) makes a plain
  ``P(None, None, "tp")`` hand each shard its own (q, k, v) block
- attention output / mlp down: row-parallel (XLA inserts the psum)
- fused ``wgu``: column-parallel, same shard-blocked trick
- lm_head: vocab-split (sampling reduces across shards inside jit)
- combined paged KV cache ``[L, n_pages, page_size, 2*n_kv, d]``:
  combined-head axis split across tp (K/V interleaved, so K and V of a
  head land on the same shard)
- decode batch: split across dp; prefill (one sequence) replicated on dp

Requires ``tp`` to divide num_heads, num_kv_heads, and intermediate_size
(llama3 GQA: tp ≤ 8). Larger tp would split head_dim — future work.

IMPORTANT: the fused params must have been built with THIS tp
(``init_params(rng, cfg, tp)`` / ``load_hf_llama(path, tp=...)``) — the
shard-blocked column order depends on it.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.engine.config import ModelConfig


def make_mesh(dp: int = 1, tp: int = 1, devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    if dp * tp > len(devices):
        raise ValueError(f"mesh {dp}x{tp} needs {dp*tp} devices, have {len(devices)}")
    grid = np.asarray(devices[: dp * tp]).reshape(dp, tp)
    return Mesh(grid, ("dp", "tp"))


def param_partition_specs(cfg: ModelConfig, tp: int) -> dict[str, Any]:
    """PartitionSpec pytree matching `model.init_params` structure
    (mesh-free: also used for memory planning of pods larger than the
    local machine, parallel/placement.py)."""
    for what, n in (
        ("num_kv_heads", cfg.num_kv_heads),
        ("num_heads", cfg.num_heads),
        ("intermediate_size", cfg.intermediate_size),
    ):
        if n % tp:
            raise ValueError(f"tp={tp} must divide {what}={n}")

    layers = {
        "attn_norm": P(None, None),
        "mlp_norm": P(None, None),
        "wqkv": P(None, None, "tp"),
        "wo": P(None, "tp", None),
    }
    if cfg.attn_qkv_bias:
        layers["bqkv"] = P(None, "tp")  # fused column order, like wqkv
    if cfg.is_moe:
        # Expert parallelism: the expert axis shards over the model axis;
        # the expert-sum contraction becomes a psum over 'tp'.
        if cfg.num_experts % tp:
            raise ValueError(
                f"tp={tp} must divide num_experts={cfg.num_experts}"
            )
        layers["w_router"] = P(None, None, None)
        layers["w_gate"] = P(None, "tp", None, None)
        layers["w_up"] = P(None, "tp", None, None)
        layers["w_down"] = P(None, "tp", None, None)
    else:
        layers["wgu"] = P(None, None, "tp")
        layers["w_down"] = P(None, "tp", None)
    specs = {
        "embed": P(None, None),
        "layers": layers,
        "final_norm": P(None),
        "fuse_tp": P(),
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, "tp")
    return specs


def param_shardings(cfg: ModelConfig, mesh: Mesh) -> dict[str, Any]:
    """NamedSharding pytree matching `model.init_params` structure."""
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        param_partition_specs(cfg, mesh.shape["tp"]),
        is_leaf=lambda x: isinstance(x, P),
    )


def cache_sharding(mesh: Mesh, quantized: bool = False, num_layers: int = 0):
    """Per-layer cache pages [n_pages, page_size, 2*n_kv, d] —
    combined-head axis on tp. One sharding covers every element of the
    per-layer tuple (model.init_cache) as a pytree prefix.

    ``quantized`` (int8 KV, engine/kv_quant.py): each layer entry is a
    {"kv": 4-D, "scale": 3-D} dict, so the prefix trick no longer fits
    one rank — return the full per-layer tuple (``num_layers`` entries),
    scale pages sharded on the same combined-head axis."""
    if not quantized:
        return NamedSharding(mesh, P(None, None, "tp", None))
    entry = {
        "kv": NamedSharding(mesh, P(None, None, "tp", None)),
        "scale": NamedSharding(mesh, P(None, None, "tp")),
    }
    return tuple(dict(entry) for _ in range(num_layers))


def decode_batch_shardings(mesh: Mesh) -> dict[str, NamedSharding]:
    """Decode-step batch operands: batch axis split across dp."""
    dp = NamedSharding(mesh, P("dp"))
    return {
        "tokens": dp,
        "block_tables": NamedSharding(mesh, P("dp", None)),
        "positions": dp,
        "active": dp,
    }


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def expand_specs_for_params(specs: Any, params: Any) -> Any:
    """Match a PartitionSpec pytree to a possibly int8-quantized params
    pytree: where params holds a quantized weight ``{"w", "scale"}``
    (model.quantize_weight layout) under a single spec leaf, expand to
    per-member specs. ``scale`` is ``w``'s shape with the contraction
    axis collapsed to 1, so any sharded axis that is size-1 in scale
    (row-parallel weights: wo, w_down) replicates instead."""
    def expand(spec, p):
        if isinstance(p, dict) and set(p) == {"w", "scale"}:
            scale_spec = P(*[
                ax if p["scale"].shape[i] != 1 else None
                for i, ax in enumerate(spec)
            ])
            return {"w": spec, "scale": scale_spec}
        if isinstance(p, dict):
            return {k: expand(spec[k], p[k]) for k in p}
        return spec

    return {k: expand(specs[k], params[k]) for k in params}


def shard_params(params: Any, cfg: ModelConfig, mesh: Mesh) -> Any:
    """Place an (unsharded, possibly int8-quantized) params pytree onto
    the mesh."""
    specs = param_partition_specs(cfg, mesh.shape["tp"])
    if "fuse_tp" not in params:  # pytrees predating the layout marker
        specs.pop("fuse_tp")
    specs = expand_specs_for_params(specs, params)
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        params, specs,
        is_leaf=lambda x: isinstance(x, P),
    )
