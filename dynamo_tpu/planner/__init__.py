"""SLA autoscaling planner (parity: reference components/planner)."""

from dynamo_tpu.planner.load_predictor import (
    ARPredictor,
    ConstantPredictor,
    MovingAveragePredictor,
    PREDICTORS,
)
from dynamo_tpu.planner.perf_interpolation import (
    DecodeInterpolator,
    PrefillInterpolator,
    from_profile,
)
from dynamo_tpu.planner.controller import (
    ControllerConfig,
    PlannerController,
)
from dynamo_tpu.planner.planner_core import (
    Connector,
    Observation,
    Plan,
    Planner,
    PlannerConfig,
    RecordingConnector,
    SlaTargets,
)

__all__ = [
    "ARPredictor",
    "ConstantPredictor",
    "Connector",
    "ControllerConfig",
    "DecodeInterpolator",
    "MovingAveragePredictor",
    "Observation",
    "PREDICTORS",
    "Plan",
    "Planner",
    "PlannerConfig",
    "PlannerController",
    "PrefillInterpolator",
    "RecordingConnector",
    "SlaTargets",
    "from_profile",
]
