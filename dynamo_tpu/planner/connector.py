"""Scaling connectors: turn a Plan into actual fleet changes.

The reference scales by patching DynamoGraphDeployment replica counts and
letting the Kubernetes operator reconcile pods
(`components/planner/.../kubernetes_connector.py`, `kube.py`). This
environment has no cluster, so the production-shaped connector here
manages local worker PROCESSES: spawn to scale up, terminate to scale
down; dead children are reaped and respawned on the next adjustment. The
discovery plane reacts exactly as it would under an orchestrator — new
workers register under store leases, terminated ones vanish on lease
expiry, and the frontend's watcher prunes them.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
from typing import Sequence

log = logging.getLogger("dynamo_tpu.planner.connector")


class LocalProcessConnector:
    def __init__(
        self,
        store_address: str,
        worker_argv: dict[str, Sequence[str]],
        env: dict[str, str] | None = None,
    ):
        """``worker_argv`` maps component name ("prefill"/"decode"/...) to
        the argv that starts ONE worker of that kind, e.g.
        ``["-m", "dynamo_tpu.backends.mocker", "--model-name", "m"]``
        (interpreted relative to this interpreter)."""
        self.store_address = store_address
        self.worker_argv = {k: list(v) for k, v in worker_argv.items()}
        self.env = env or {}
        self._procs: dict[str, list[subprocess.Popen]] = {}
        # Scaled-down children pending exit: poll()ed on every reap so they
        # never linger as POSIX zombies for the planner's lifetime.
        self._terminated: list[subprocess.Popen] = []

    def _reap(self, component: str) -> list[subprocess.Popen]:
        self._terminated = [p for p in self._terminated if p.poll() is None]
        procs = self._procs.setdefault(component, [])
        live = [p for p in procs if p.poll() is None]
        dead = len(procs) - len(live)
        if dead:
            log.warning("%d dead %s worker(s) reaped", dead, component)
        self._procs[component] = live
        return live

    def current(self, component: str) -> int:
        return len(self._reap(component))

    async def set_replicas(self, component: str, replicas: int) -> None:
        argv = self.worker_argv.get(component)
        if argv is None:
            log.warning("no worker command for component %r", component)
            return
        procs = self._reap(component)
        while len(procs) < replicas:
            env = dict(os.environ, DYN_STORE_ADDRESS=self.store_address, **self.env)
            p = subprocess.Popen(
                [sys.executable, *argv],
                env=env,
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
            )
            procs.append(p)
            log.info("scaled up %s -> %d (pid %d)", component, len(procs), p.pid)
        while len(procs) > replicas:
            p = procs.pop()
            p.terminate()
            self._terminated.append(p)
            log.info("scaled down %s -> %d (pid %d)", component, len(procs), p.pid)

    def shutdown(self) -> None:
        for procs in self._procs.values():
            for p in procs:
                if p.poll() is None:
                    p.terminate()
        for p in [p for procs in self._procs.values() for p in procs] + self._terminated:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
        self._procs.clear()
        self._terminated.clear()
