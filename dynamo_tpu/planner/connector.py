"""Scaling connectors: turn a Plan into actual fleet changes.

The reference scales by patching DynamoGraphDeployment replica counts and
letting the Kubernetes operator reconcile pods
(`components/planner/.../kubernetes_connector.py`, `kube.py`). This
environment has no cluster, so the production-shaped connector here
manages local worker PROCESSES: spawn to scale up, SIGTERM to scale
down. Scale-down is a *graceful drain*, never a kill: SIGTERM triggers
the worker's PR 6 drain (deregister → refuse new work → finish in-flight
→ revoke lease → exit), and only a worker that overstays the drain
window is escalated to SIGKILL. Exit codes are reaped on every
adjustment cycle so scaled-down children never linger as POSIX zombies
for the planner's lifetime. The discovery plane reacts exactly as it
would under an orchestrator — new workers register under store leases,
drained ones deregister themselves, and the frontend's watcher prunes
them.
"""

from __future__ import annotations

import logging
import os
import signal
import subprocess
import sys
import time
from typing import Sequence

from dynamo_tpu import knobs

log = logging.getLogger("dynamo_tpu.planner.connector")


class LocalProcessConnector:
    def __init__(
        self,
        store_address: str,
        worker_argv: dict[str, Sequence[str]],
        env: dict[str, str] | None = None,
        drain_timeout_s: float | None = None,
    ):
        """``worker_argv`` maps component name ("prefill"/"decode"/...) to
        the argv that starts ONE worker of that kind, e.g.
        ``["-m", "dynamo_tpu.backends.mocker", "--model-name", "m"]``
        (interpreted relative to this interpreter).

        ``drain_timeout_s`` bounds how long a SIGTERM'd worker may spend
        draining before the connector escalates to SIGKILL; defaults to
        the worker-side drain budget (``DYN_WORKER_DRAIN_TIMEOUT_S``,
        30 s) plus slack, so a healthy drain always finishes first."""
        self.store_address = store_address
        self.worker_argv = {k: list(v) for k, v in worker_argv.items()}
        self.env = env or {}
        if drain_timeout_s is None:
            drain_timeout_s = knobs.get_float("DYN_WORKER_DRAIN_TIMEOUT_S") + 5.0
        self.drain_timeout_s = drain_timeout_s
        self._procs: dict[str, list[subprocess.Popen]] = {}
        # Scaled-down children pending exit: (proc, SIGKILL-escalation
        # deadline). poll()ed on every reap so exit codes are collected
        # promptly and overstayers are escalated.
        self._draining: list[tuple[subprocess.Popen, float]] = []
        # (pid, returncode) of every reaped child, in reap order — the
        # audit trail tests and operators read (0/-SIGTERM = clean drain,
        # -SIGKILL = escalated).
        self.exit_codes: list[tuple[int, int]] = []
        self.kills_escalated = 0

    def _reap_draining(self, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        still: list[tuple[subprocess.Popen, float]] = []
        for p, deadline in self._draining:
            rc = p.poll()
            if rc is not None:
                self.exit_codes.append((p.pid, rc))
                log.info("drained worker pid %d exited rc=%d", p.pid, rc)
                continue
            if now >= deadline:
                # Drain window blown: the worker is wedged (or its drain
                # is stuck behind a dead store). SIGKILL and keep polling
                # — the exit code lands on a later reap.
                log.warning(
                    "worker pid %d overstayed the %.1fs drain window; "
                    "escalating to SIGKILL", p.pid, self.drain_timeout_s,
                )
                self.kills_escalated += 1
                p.kill()
                still.append((p, float("inf")))  # never escalate twice
                continue
            still.append((p, deadline))
        self._draining = still

    def _reap(self, component: str) -> list[subprocess.Popen]:
        self._reap_draining()
        procs = self._procs.setdefault(component, [])
        live = []
        for p in procs:
            rc = p.poll()
            if rc is None:
                live.append(p)
            else:
                self.exit_codes.append((p.pid, rc))
        dead = len(procs) - len(live)
        if dead:
            log.warning("%d dead %s worker(s) reaped", dead, component)
        self._procs[component] = live
        return live

    def current(self, component: str) -> int:
        return len(self._reap(component))

    def draining_count(self) -> int:
        """Scaled-down workers still inside their drain window."""
        self._reap_draining()
        return len(self._draining)

    async def set_replicas(self, component: str, replicas: int) -> None:
        argv = self.worker_argv.get(component)
        if argv is None:
            log.warning("no worker command for component %r", component)
            return
        procs = self._reap(component)
        while len(procs) < replicas:
            env = dict(os.environ, DYN_STORE_ADDRESS=self.store_address, **self.env)
            p = subprocess.Popen(
                [sys.executable, *argv],
                env=env,
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
            )
            procs.append(p)
            log.info("scaled up %s -> %d (pid %d)", component, len(procs), p.pid)
        while len(procs) > replicas:
            p = procs.pop()
            # Graceful drain, never a kill: the worker's SIGTERM handler
            # deregisters, finishes in-flight streams, and exits. The
            # signal is non-blocking here; escalation and exit-code
            # collection happen on subsequent reap cycles.
            p.send_signal(signal.SIGTERM)
            self._draining.append(
                (p, time.monotonic() + self.drain_timeout_s)
            )
            log.info(
                "scaled down %s -> %d (pid %d draining, %.1fs window)",
                component, len(procs), p.pid, self.drain_timeout_s,
            )

    def shutdown(self) -> None:
        for procs in self._procs.values():
            for p in procs:
                if p.poll() is None:
                    p.terminate()
        pending = [p for procs in self._procs.values() for p in procs]
        pending += [p for p, _ in self._draining]
        for p in pending:
            try:
                rc = p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
                rc = p.wait()
            self.exit_codes.append((p.pid, rc))
        self._procs.clear()
        self._draining.clear()
