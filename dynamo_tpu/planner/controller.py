"""The closed-loop SLA autoscaling controller (ISSUE 14, ROADMAP item 2).

``Planner`` (planner_core) owns the *math*: predict the rate, interpolate
the profile, size prefill and decode pools against the TTFT/TPOT targets.
``PlannerController`` owns the *loop*: consume event-plane Observations
(``FleetMetricsObserver`` over the PR 13 aggregator — per-phase means,
queue depths, shed counters, SLO attainment), turn the math's desired
replica counts into safe actuations, and drive them through a Connector.

What "safe" means here, and why a bare `set_replicas(plan)` loop is not
enough at fleet scale:

- **Reactive pressure.** The rate predictor is a trend-follower; a burst
  or a chaos blip shows up in the queues and shed counters *before* it
  shows up in the fitted rate. A standing queue beyond
  ``queue_depth_per_replica`` per live replica asks for enough extra
  replicas to amortize the backlog back to that depth; any typed shed
  in the window, or SLO attainment under ``attainment_floor``, raises
  the desired count above the math's answer — TTFT misses push the
  prefill pool, TPOT misses push the decode pool.
- **Hysteresis.** Scale-down needs the desired count to sit below the
  current target for ``down_stable_cycles`` consecutive cycles; a single
  trough sample (or a chaos blip that briefly empties the queues) never
  sheds capacity. Scale-up is deliberately asymmetric: one cycle of
  demand is enough.
- **Cooldowns.** After actuating, the pool holds for
  ``scale_up_cooldown_s`` / ``scale_down_cooldown_s`` before moving the
  same direction again — replica changes take effect with lag (process
  spawn, drain window), and re-deciding from observations that predate
  the actuation flaps the fleet.
- **Bounded steps.** At most ``max_step_up`` / ``max_step_down``
  replicas move per pool per cycle: a pathological observation window
  can never double the fleet or halve it in one decision.
- **Reconciliation.** The per-pool target is re-asserted through the
  connector every cycle, not only when a decision moves it: an actuation
  that failed mid-cycle is retried next interval, and dead children are
  reaped and respawned even while the decision is "hold".
- **Drain-only scale-down.** The controller never kills: the connector
  contract is that removing a replica triggers the PR 6 graceful drain
  (SIGTERM → deregister → finish in-flight → exit), so scale-down during
  active decode completes every stream bit-identically.

Every decision is counted (``planner_decisions_total{action}``), every
pool's current/target replicas are gauged, and each cycle emits a
``planner_cycle`` trace span — exported through the fleet aggregator
(:meth:`~dynamo_tpu.obs.aggregator.FleetAggregator.attach_controller`)
so ``/fleet`` shows what the controller did and why.
"""

from __future__ import annotations

import asyncio
import logging
import math
import time
from dataclasses import dataclass, field
from typing import Awaitable, Callable

from dynamo_tpu import tracing
from dynamo_tpu.planner.planner_core import Observation, Plan, Planner

log = logging.getLogger("dynamo_tpu.planner.controller")

# Decision outcomes, one counter each (planner_decisions_total{action}).
ACTIONS = (
    "scale_up",
    "scale_down",
    "hold",
    "cooldown_hold",
    "hysteresis_hold",
    # Control plane dark (ISSUE 15): the observation window is blind, so
    # the controller neither scales nor re-actuates — targets freeze at
    # last-known-good until the bus returns.
    "degraded_hold",
)

# How a pool maps onto the Plan's replica counts. "max" serves aggregated
# fleets (one pool doing both phases): it takes the larger of the two
# requirements, since the same workers must satisfy both budgets.
PLAN_ATTRS = {
    "prefill": lambda p: p.prefill_replicas,
    "decode": lambda p: p.decode_replicas,
    "max": lambda p: max(p.prefill_replicas, p.decode_replicas),
}


@dataclass
class ControllerConfig:
    interval_s: float = 10.0
    scale_up_cooldown_s: float = 15.0
    scale_down_cooldown_s: float = 60.0
    # Consecutive cycles the desired count must sit below the current
    # target before a scale-down actuates (the flap guard).
    down_stable_cycles: int = 3
    max_step_up: int = 4
    max_step_down: int = 1
    # Reactive pressure: queued requests per live replica beyond which
    # the pool scales up regardless of the fitted rate (0 disables).
    queue_depth_per_replica: float = 8.0
    # Any typed shed in the window forces up-pressure (overload has
    # already started; waiting for the predictor to notice is too late).
    shed_pressure: bool = True
    # SLO-attainment floor: below it, the missing target's pool gets
    # up-pressure (ttft -> prefill, tpot -> decode; both for "max"
    # pools). 0 disables.
    attainment_floor: float = 0.92
    min_replicas: int = 1
    max_replicas: int = 16
    # Per-pool (min, max) overrides — a prefill pool rarely needs the
    # decode pool's ceiling. Pools not listed use the globals above.
    pool_limits: dict[str, tuple[int, int]] = field(default_factory=dict)


@dataclass
class PoolState:
    component: str
    plan_attr: str                      # "prefill" | "decode" | "max"
    target: int = 1                     # last actuated replica count
    desired: int = 1                    # this cycle's pre-clamp desire
    last_scale_up_t: float = float("-inf")
    last_scale_down_t: float = float("-inf")
    below_streak: int = 0               # consecutive cycles desired < target
    last_action: str = "hold"
    last_reason: str = ""


class PlannerController:
    """observe → plan → decide → actuate, with the guard rails above.

    ``pools`` maps component name (the connector's scaling unit) to its
    plan attribute: ``{"prefill": "prefill", "decode": "decode"}`` for a
    disaggregated fleet, ``{"backend": "max"}`` for an aggregated one.
    ``clock`` is injectable so the fleet harness (and tests) run the loop
    on a virtual timeline.
    """

    def __init__(
        self,
        planner: Planner,
        connector,
        pools: dict[str, str] | None = None,
        config: ControllerConfig | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.planner = planner
        self.connector = connector
        self.config = config or ControllerConfig()
        pools = pools or {"prefill": "prefill", "decode": "decode"}
        for attr in pools.values():
            if attr not in PLAN_ATTRS:
                raise ValueError(
                    f"unknown plan attribute {attr!r} "
                    f"(expected one of {sorted(PLAN_ATTRS)})"
                )
        start = max(1, self.config.min_replicas)
        self.pools = {
            comp: PoolState(component=comp, plan_attr=attr, target=start,
                            desired=start)
            for comp, attr in pools.items()
        }
        self.clock = clock
        self.decisions: dict[str, int] = {a: 0 for a in ACTIONS}
        self.cycles = 0
        self.last_plan: Plan | None = None
        self.last_observation: Observation | None = None
        self._tracer = tracing.get_tracer("planner")

    # -- one adjustment cycle ----------------------------------------------

    async def cycle(self, obs: Observation) -> dict[str, str]:
        """Run one closed-loop adjustment from an Observation; returns
        {component: action}. Exceptions from the connector propagate —
        the loop wrapper logs and retries next interval."""
        now = self.clock()
        self.cycles += 1
        self.last_observation = obs
        if obs.control_plane_degraded:
            # Hold EVERYTHING on a blind window: no plan math (the
            # predictor must not ingest phantom-zero rates), no decision
            # movement, no actuation (the connector likely can't reach
            # its substrate mid-outage anyway; the standing targets are
            # re-asserted on the first healthy cycle). Hysteresis streaks
            # freeze too — an outage must not count toward a scale-down.
            actions = {}
            for pool in self.pools.values():
                actions[pool.component] = self._note(
                    pool, "degraded_hold", "control plane dark"
                )
                self.decisions["degraded_hold"] += 1
            log.warning(
                "planner cycle %d held: control plane dark", self.cycles
            )
            return actions
        with self._tracer.span(
            "planner_cycle",
            attrs={
                "cycle": self.cycles,
                "request_rate": round(obs.request_rate, 3),
                "queue_depth": obs.queue_depth,
                "shed_delta": obs.shed_delta,
            },
        ) as span:
            plan = self.planner.compute_plan(obs)
            self.last_plan = plan
            actions: dict[str, str] = {}
            for pool in self.pools.values():
                desired, reason = self._desired(pool, plan, obs)
                pool.desired = desired
                action = self._decide(pool, desired, now, reason)
                actions[pool.component] = action
                self.decisions[action] += 1
            # Reconcile EVERY pool EVERY cycle, not just on scale
            # decisions: set_replicas is idempotent (reap dead children,
            # top up / drain down to the count), so a failed actuation
            # is retried next cycle (``target`` is the standing intent,
            # committed above) and a worker that crashes during steady
            # "hold" load is respawned next interval instead of waiting
            # for the next unrelated scale decision.
            for pool in self.pools.values():
                await self.connector.set_replicas(pool.component, pool.target)
            span.set("predicted_rate", round(plan.predicted_rate, 3))
            for pool in self.pools.values():
                span.set(f"{pool.component}_target", pool.target)
                span.set(f"{pool.component}_action", pool.last_action)
        return actions

    def _desired(
        self, pool: PoolState, plan: Plan, obs: Observation
    ) -> tuple[int, str]:
        """The math's answer for this pool, lifted by reactive pressure."""
        cfg = self.config
        desired = PLAN_ATTRS[pool.plan_attr](plan)
        reason = "rate"
        live = (obs.live_workers or {}).get(pool.component, pool.target)
        pressure = pool.target + cfg.max_step_up  # one full step up
        if cfg.queue_depth_per_replica:
            # Backlog-proportional pressure: enough replicas that the
            # standing queue amortizes to the configured per-replica
            # depth — a deep backlog asks for real catch-up capacity,
            # not a fixed nudge (actuation is still bounded by
            # max_step_up per cycle). When the feed attributes queues to
            # components, this pool only answers for ITS OWN backlog — a
            # prefill-side queue must not scale the decode pool.
            per = cfg.queue_depth_per_replica
            if obs.queue_depths is not None:
                qd = obs.queue_depths.get(pool.component, 0.0)
            else:
                qd = obs.queue_depth
            backlog = qd - per * max(1, live)
            if backlog > 0:
                want = max(1, live) + int(math.ceil(backlog / per))
                if want > desired:
                    desired, reason = want, "queue_depth"
        if cfg.shed_pressure and obs.shed_delta > 0 and desired < pressure:
            desired, reason = pressure, "sheds"
        if cfg.attainment_floor and obs.slo_attainment:
            miss_ttft = (
                obs.slo_attainment.get("ttft", 1.0) < cfg.attainment_floor
            )
            miss_tpot = (
                obs.slo_attainment.get("tpot", 1.0) < cfg.attainment_floor
            )
            relevant = {
                "prefill": miss_ttft,
                "decode": miss_tpot,
                "max": miss_ttft or miss_tpot,
            }[pool.plan_attr]
            if relevant and desired <= pool.target:
                desired, reason = pool.target + 1, "slo_attainment"
        lo, hi = cfg.min_replicas, cfg.max_replicas
        return max(lo, min(hi, desired)), reason

    def _decide(
        self, pool: PoolState, desired: int, now: float, reason: str
    ) -> str:
        cfg = self.config
        if desired > pool.target:
            pool.below_streak = 0
            if now - pool.last_scale_up_t < cfg.scale_up_cooldown_s:
                return self._note(pool, "cooldown_hold", f"up blocked ({reason})")
            new = min(desired, pool.target + cfg.max_step_up)
            pool.last_scale_up_t = now
            log.info(
                "scale up %s: %d -> %d (%s, desired %d)",
                pool.component, pool.target, new, reason, desired,
            )
            pool.target = new
            return self._note(pool, "scale_up", reason)
        if desired < pool.target:
            pool.below_streak += 1
            if pool.below_streak < cfg.down_stable_cycles:
                return self._note(
                    pool, "hysteresis_hold",
                    f"below for {pool.below_streak}/{cfg.down_stable_cycles}",
                )
            if now - pool.last_scale_down_t < cfg.scale_down_cooldown_s:
                return self._note(pool, "cooldown_hold", "down blocked")
            new = max(desired, pool.target - cfg.max_step_down)
            pool.last_scale_down_t = now
            # Streak survives a partial step so a deep trough keeps
            # draining one replica per cooldown without re-proving itself.
            if new == desired:
                pool.below_streak = 0
            log.info(
                "scale down %s: %d -> %d (drain; desired %d)",
                pool.component, pool.target, new, desired,
            )
            pool.target = new
            return self._note(pool, "scale_down", reason)
        pool.below_streak = 0
        return self._note(pool, "hold", reason)

    def _note(self, pool: PoolState, action: str, reason: str) -> str:
        pool.last_action = action
        pool.last_reason = reason
        return action

    # -- the loop ----------------------------------------------------------

    async def run(
        self,
        observe: Callable[[], Awaitable[Observation]],
        stop_event: asyncio.Event | None = None,
    ) -> None:
        """``observe()`` → Observation each ``interval_s`` (the wall-clock
        production loop; the fleet harness calls :meth:`cycle` directly
        on its virtual timeline)."""
        while stop_event is None or not stop_event.is_set():
            try:
                obs = await observe()
                await self.cycle(obs)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — one bad cycle must not kill the loop
                log.exception("planner cycle failed; retrying next interval")
            if stop_event is None:
                await asyncio.sleep(self.config.interval_s)
            else:
                try:
                    await asyncio.wait_for(
                        stop_event.wait(), self.config.interval_s
                    )
                except asyncio.TimeoutError:
                    pass

    # -- observability ------------------------------------------------------

    def stats(self) -> dict:
        """Gauge payload (the aggregator/status-server export shape):
        decision counters by action + per-pool current/desired."""
        return {
            "cycles": self.cycles,
            "decisions": dict(self.decisions),
            "pools": {
                comp: {
                    "target": p.target,
                    "desired": p.desired,
                    "last_action": p.last_action,
                }
                for comp, p in self.pools.items()
            },
        }

    def status_payload(self) -> dict:
        """The ``/fleet`` planner section: what the controller did and
        why, per pool, plus the last plan's math."""
        plan = self.last_plan
        obs = self.last_observation
        return {
            "cycles": self.cycles,
            "decisions": dict(self.decisions),
            "pools": {
                comp: {
                    "target": p.target,
                    "desired": p.desired,
                    "plan_attr": p.plan_attr,
                    "last_action": p.last_action,
                    "last_reason": p.last_reason,
                    "below_streak": p.below_streak,
                }
                for comp, p in self.pools.items()
            },
            "last_plan": (
                {
                    "predicted_rate": round(plan.predicted_rate, 3),
                    "prefill_replicas": plan.prefill_replicas,
                    "decode_replicas": plan.decode_replicas,
                    "correction_prefill": round(plan.correction_prefill, 3),
                    "correction_decode": round(plan.correction_decode, 3),
                }
                if plan
                else None
            ),
            "last_observation": (
                {
                    "request_rate": round(obs.request_rate, 3),
                    "queue_depth": obs.queue_depth,
                    "shed_delta": obs.shed_delta,
                    "slo_attainment": obs.slo_attainment,
                }
                if obs
                else None
            ),
        }
