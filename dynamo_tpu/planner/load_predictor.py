"""Load predictors for the SLA planner.

Capability parity: reference `components/planner/src/dynamo/planner/utils/
load_predictor.py:62,75,115` (constant / ARIMA / Prophet). Prophet and
statsmodels aren't in the image, so the AR predictor is a dependency-free
least-squares AR(p) fit — same role (trend-following forecast), numpy only.
"""

from __future__ import annotations

from collections import deque

import numpy as np


class BasePredictor:
    def __init__(self, window: int = 128):
        self.history: deque[float] = deque(maxlen=window)

    def observe(self, value: float) -> None:
        self.history.append(float(value))

    def predict(self) -> float:
        raise NotImplementedError


class ConstantPredictor(BasePredictor):
    """Next load = last observed load."""

    def predict(self) -> float:
        return self.history[-1] if self.history else 0.0


class MovingAveragePredictor(BasePredictor):
    def __init__(self, window: int = 128, span: int = 8):
        super().__init__(window)
        self.span = span

    def predict(self) -> float:
        if not self.history:
            return 0.0
        tail = list(self.history)[-self.span :]
        return float(np.mean(tail))


class ARPredictor(BasePredictor):
    """AR(p) one-step forecast by ordinary least squares on the window."""

    def __init__(self, window: int = 128, order: int = 4):
        super().__init__(window)
        self.order = order

    def predict(self) -> float:
        h = np.asarray(self.history, dtype=np.float64)
        p = self.order
        if len(h) <= p + 1:
            return float(h[-1]) if len(h) else 0.0
        # Rows: h[t-p:t] -> h[t]
        X = np.stack([h[i : i + p] for i in range(len(h) - p)])
        y = h[p:]
        X1 = np.concatenate([X, np.ones((len(X), 1))], axis=1)
        coef, *_ = np.linalg.lstsq(X1, y, rcond=None)
        pred = float(np.concatenate([h[-p:], [1.0]]) @ coef)
        return max(0.0, pred)


PREDICTORS = {
    "constant": ConstantPredictor,
    "moving_average": MovingAveragePredictor,
    "ar": ARPredictor,
}
