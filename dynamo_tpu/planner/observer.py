"""Live observation source for the SLA planner: the frontend's
Prometheus endpoint.

Closes the observe half of the reference's adjustment loop
(`components/planner/src/dynamo/planner/utils/planner_core.py:180`
`observe_metrics` — it scrapes the frontend's TTFT/ITL histograms and
request counters from Prometheus; here the planner scrapes the frontend
directly, no Prometheus server in between).

Each call to :meth:`observe` diffs the current counter/histogram totals
against the previous scrape, turning cumulative series into one
adjustment window's :class:`Observation`.
"""

from __future__ import annotations

import logging
import re
import time

import aiohttp

from dynamo_tpu.planner.planner_core import Observation

log = logging.getLogger("dynamo_tpu.planner.observer")

# Metric families emitted by llm/http_service.py (dynamo_frontend_*).
_REQS = "dynamo_frontend_requests_total"
_TTFT = "dynamo_frontend_time_to_first_token_seconds"
_ITL = "dynamo_frontend_inter_token_latency_seconds"
_ISL = "dynamo_frontend_input_sequence_tokens"
_OSL = "dynamo_frontend_output_sequence_tokens"
# Per-phase latency histograms from the tracer (dynamo_tpu/tracing):
# the measured TTFT/ITL decomposition (tokenize/route/prefill/decode...).
_PHASE = "dynamo_trace_phase_duration_seconds"
_PHASE_LABEL_RE = re.compile(r'phase="([^"]+)"')


def parse_prometheus(text: str) -> dict[str, float]:
    """Sum every sample of each metric family (labels collapsed) AND keep
    every labeled sample addressable under its full ``name{labels}`` key,
    exactly as written on the wire. The family total is what the rate/ISL
    diff math wants; the labeled keys are what the closed-loop controller
    wants — per-worker (``{...worker_id="7"...}``) and per-tenant
    (``{...tenant="acme"...}``) series read directly, without the
    aggregator's rollups collapsing them. Two spellings of the same
    series sum (a family name never contains ``{``, so labeled keys can
    never collide with family totals).

    The tracer's per-phase histograms additionally keep their historical
    phase-only keys (``{family}_sum{{phase}}``) so
    :meth:`MetricsObserver.observe` can decompose TTFT/ITL by phase."""
    totals: dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        try:
            name_part, value = line.rsplit(None, 1)
        except ValueError:
            continue
        name = name_part.split("{", 1)[0]
        try:
            v = float(value)
        except ValueError:
            continue
        totals[name] = totals.get(name, 0.0) + v
        if "{" in name_part:
            # The labeled sample stays addressable verbatim.
            totals[name_part] = totals.get(name_part, 0.0) + v
        if name.startswith(_PHASE) and name != f"{_PHASE}_bucket":
            m = _PHASE_LABEL_RE.search(name_part)
            if m:
                key = f"{name}{{{m.group(1)}}}"
                totals[key] = totals.get(key, 0.0) + v
    return totals


class MetricsObserver:
    """Scrapes ``{base_url}/metrics`` and produces per-window Observations."""

    def __init__(self, base_url: str):
        self.url = base_url.rstrip("/") + "/metrics"
        self._prev: dict[str, float] | None = None
        self._prev_t: float = 0.0
        self._last_means = (256.0, 128.0)  # (isl, osl) fallback before traffic

    async def _scrape(self) -> dict[str, float]:
        async with aiohttp.ClientSession() as s:
            async with s.get(self.url) as r:
                r.raise_for_status()
                return parse_prometheus(await r.text())

    async def observe(self) -> Observation:
        now = time.monotonic()
        cur = await self._scrape()
        prev, prev_t = self._prev, self._prev_t
        self._prev, self._prev_t = cur, now
        if prev is None:
            return Observation(request_rate=0.0, mean_isl=self._last_means[0],
                               mean_osl=self._last_means[1])

        window = max(now - prev_t, 1e-6)

        def delta(name: str) -> float:
            return max(0.0, cur.get(name, 0.0) - prev.get(name, 0.0))

        n_req = delta(_REQS)
        rate = n_req / window

        def mean(family: str, fallback: float) -> float:
            c = delta(f"{family}_count")
            return delta(f"{family}_sum") / c if c > 0 else fallback

        isl = mean(_ISL, self._last_means[0])
        osl = mean(_OSL, self._last_means[1])
        self._last_means = (isl, osl)
        ttft_c = delta(f"{_TTFT}_count")
        itl_c = delta(f"{_ITL}_count")

        # Measured per-phase decomposition over the window: mean seconds
        # spent in each tracer phase (tokenize/route/prefill/decode/...),
        # from the dynamo_trace_phase_duration_seconds{phase=...} series.
        phase_means: dict[str, float] = {}
        prefix = f"{_PHASE}_count{{"
        for key in cur:
            if not key.startswith(prefix):
                continue
            phase = key[len(prefix):-1]
            c = delta(key)
            if c > 0:
                phase_means[phase] = delta(f"{_PHASE}_sum{{{phase}}}") / c

        return Observation(
            request_rate=rate,
            mean_isl=isl,
            mean_osl=osl,
            observed_ttft_s=(delta(f"{_TTFT}_sum") / ttft_c) if ttft_c else None,
            observed_itl_s=(delta(f"{_ITL}_sum") / itl_c) if itl_c else None,
            phase_means=phase_means or None,
        )


class FleetMetricsObserver:
    """The event-plane observation source (ISSUE 13): the planner reads
    the fleet aggregator's composed state instead of point-scraping one
    frontend's /metrics. Same per-window diff math as
    :class:`MetricsObserver` (it lives in
    ``obs/aggregator.FleetAggregator.observation``), but fed by metric
    snapshots from LIVE workers only — a dead worker's counters leave
    the aggregate the moment its series retire, so the planner never
    plans against ghosts."""

    def __init__(self, aggregator):
        self.aggregator = aggregator

    async def observe(self) -> Observation:
        return self.aggregator.observation()
