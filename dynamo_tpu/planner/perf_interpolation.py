"""Profiled-performance interpolators: what one replica can sustain.

Capability parity: reference `components/planner/src/dynamo/planner/utils/
perf_interpolation.py:21,57` — the SLA profiler sweeps a replica offline
(TTFT vs input length for prefill; ITL vs concurrency for decode at fixed
context) and the planner interpolates those grids at plan time. On TPU the
sweep axis is chips-per-replica instead of TP×GPU, but the math is the
same. Profiles are plain dicts so `benchmarks/profile_sla.py` output and
hand-written fixtures both load.
"""

from __future__ import annotations

import numpy as np


class PrefillInterpolator:
    """TTFT (seconds) and throughput (tokens/s) vs input sequence length."""

    def __init__(self, isl_grid: list[float], ttft_s: list[float]):
        order = np.argsort(isl_grid)
        self.isl = np.asarray(isl_grid, np.float64)[order]
        self.ttft = np.asarray(ttft_s, np.float64)[order]

    def ttft_at(self, isl: float) -> float:
        return float(np.interp(isl, self.isl, self.ttft))

    def throughput_at(self, isl: float) -> float:
        """Prefill tokens/s one replica sustains at this ISL."""
        return isl / max(self.ttft_at(isl), 1e-9)

    def max_isl_within(self, ttft_budget_s: float) -> float:
        """Largest ISL meeting the TTFT SLA (grid-bounded)."""
        ok = self.isl[self.ttft <= ttft_budget_s]
        return float(ok[-1]) if len(ok) else float(self.isl[0])


class DecodeInterpolator:
    """ITL (seconds/token) vs concurrency; per-replica decode capacity."""

    def __init__(self, concurrency_grid: list[float], itl_s: list[float]):
        order = np.argsort(concurrency_grid)
        self.conc = np.asarray(concurrency_grid, np.float64)[order]
        self.itl = np.asarray(itl_s, np.float64)[order]

    def itl_at(self, concurrency: float) -> float:
        return float(np.interp(concurrency, self.conc, self.itl))

    def max_concurrency_within(self, itl_budget_s: float) -> float:
        ok = self.conc[self.itl <= itl_budget_s]
        return float(ok[-1]) if len(ok) else float(self.conc[0])

    def throughput_at(self, concurrency: float) -> float:
        """Decode tokens/s one replica sustains at this concurrency."""
        return concurrency / max(self.itl_at(concurrency), 1e-9)


def from_profile(profile: dict) -> tuple[PrefillInterpolator, DecodeInterpolator]:
    """Load from profiler output: {'prefill': {'isl': [...], 'ttft_s': [...]},
    'decode': {'concurrency': [...], 'itl_s': [...]}}."""
    return (
        PrefillInterpolator(profile["prefill"]["isl"], profile["prefill"]["ttft_s"]),
        DecodeInterpolator(profile["decode"]["concurrency"], profile["decode"]["itl_s"]),
    )
