"""The SLA planner: observe → predict → plan → scale.

Capability parity: reference `components/planner/src/dynamo/planner/utils/
planner_core.py:55-528` (adjustment loop, correction factors,
`_compute_replica_requirements` :246-331) and SURVEY.md §3.5. Scaling goes
through a Connector so tests use an in-memory recorder and production uses
an orchestrator (K8s operator equivalent) without touching the math.
"""

from __future__ import annotations

import asyncio
import logging
import math
from dataclasses import dataclass, field
from typing import Protocol

from dynamo_tpu import knobs
from dynamo_tpu.planner.load_predictor import PREDICTORS, BasePredictor
from dynamo_tpu.planner.perf_interpolation import DecodeInterpolator, PrefillInterpolator

log = logging.getLogger("dynamo_tpu.planner")


@dataclass
class SlaTargets:
    ttft_s: float = knobs.default("DYN_SLO_TTFT_MS") / 1e3
    itl_s: float = knobs.default("DYN_SLO_TPOT_MS") / 1e3

    @classmethod
    def from_env(cls) -> "SlaTargets":
        """The same env knobs the SLO attributor reads
        (``DYN_SLO_TTFT_MS`` / ``DYN_SLO_TPOT_MS``) — one spelling of the
        targets across attribution and autoscaling, so ``/fleet``
        attainment and the controller's scaling pressure can never judge
        against different budgets."""
        return cls(
            ttft_s=knobs.get_float("DYN_SLO_TTFT_MS") / 1e3,
            itl_s=knobs.get_float("DYN_SLO_TPOT_MS") / 1e3,
        )


@dataclass
class PlannerConfig:
    adjustment_interval_s: float = 60.0
    min_replicas: int = 1
    max_replicas: int = 16
    predictor: str = "ar"
    # Headroom so predicted load doesn't plan replicas at 100% utilization.
    utilization_target: float = 0.9


@dataclass
class Observation:
    """One adjustment window's worth of aggregated frontend metrics."""

    request_rate: float      # requests/s
    mean_isl: float          # input tokens/request
    mean_osl: float          # output tokens/request
    observed_ttft_s: float | None = None
    observed_itl_s: float | None = None
    # Measured TTFT/ITL decomposition from the tracer's per-phase
    # histograms ({phase: mean seconds} over the window) — lets the
    # planner tell a routing regression from a prefill regression instead
    # of reasoning from totals alone.
    phase_means: dict[str, float] | None = None
    # Closed-loop signals (ISSUE 14), filled by the fleet aggregator's
    # event-plane feed: point-in-time queue depth summed over live
    # workers, typed sheds (queue-full + deadline) observed in the
    # window, per-target SLO attainment over the attributor's recent
    # records ({"ttft": frac, "tpot": frac}), and live worker counts per
    # component. The rate math above ignores these; the controller reads
    # them as reactive scaling pressure.
    queue_depth: float = 0.0
    # Queue depth keyed by worker component (e.g. prefill/decode/backend)
    # when the feed can attribute it — lets the controller aim backlog
    # pressure at the pool that actually holds the backlog. None = only
    # the fleet-wide total above is known.
    queue_depths: dict[str, float] | None = None
    shed_delta: float = 0.0
    slo_attainment: dict[str, float] | None = None
    live_workers: dict[str, int] | None = None
    # Control-plane outage flag (ISSUE 15): True when the observation was
    # assembled while the store session was down (or the whole event
    # plane went silent at once). The controller HOLDS actuation on such
    # windows — a dark bus reads as "zero arrivals, empty queues", and
    # scaling down a healthy serving fleet on that phantom trough is
    # exactly the flap degraded mode exists to prevent.
    control_plane_degraded: bool = False


@dataclass
class Plan:
    prefill_replicas: int
    decode_replicas: int
    predicted_rate: float
    correction_prefill: float
    correction_decode: float


class Connector(Protocol):
    async def set_replicas(self, component: str, replicas: int) -> None: ...


class RecordingConnector:
    """Test/dry-run connector: records the scaling decisions."""

    def __init__(self):
        self.calls: list[tuple[str, int]] = []

    async def set_replicas(self, component: str, replicas: int) -> None:
        self.calls.append((component, replicas))

    def current(self, component: str, default: int = 1) -> int:
        for c, n in reversed(self.calls):
            if c == component:
                return n
        return default


class Planner:
    def __init__(
        self,
        prefill_interp: PrefillInterpolator,
        decode_interp: DecodeInterpolator,
        connector: Connector,
        sla: SlaTargets | None = None,
        config: PlannerConfig | None = None,
    ):
        self.prefill_interp = prefill_interp
        self.decode_interp = decode_interp
        self.connector = connector
        self.sla = sla or SlaTargets()
        self.config = config or PlannerConfig()
        self.rate_predictor: BasePredictor = PREDICTORS[self.config.predictor]()
        # Correction factors: observed/expected latency ratio — models drift
        # between offline profile and live behavior (planner_core.py:
        # correction factors, sla_planner.md:64-84).
        self.correction_prefill = 1.0
        self.correction_decode = 1.0

    # -- planning math -----------------------------------------------------

    def _update_corrections(self, obs: Observation) -> None:
        # Prefer the tracer's measured prefill-phase mean over total TTFT:
        # totals fold tokenize/route/queue time into the prefill correction,
        # so a routing regression would wrongly scale up prefill replicas.
        ttft_signal = (obs.phase_means or {}).get("prefill") or obs.observed_ttft_s
        if ttft_signal:
            expected = self.prefill_interp.ttft_at(obs.mean_isl)
            if expected > 0:
                self.correction_prefill = max(0.1, min(10.0, ttft_signal / expected))
        if obs.observed_itl_s:
            conc = self.decode_interp.max_concurrency_within(self.sla.itl_s)
            expected = self.decode_interp.itl_at(conc)
            if expected > 0:
                self.correction_decode = max(0.1, min(10.0, obs.observed_itl_s / expected))

    def compute_plan(self, obs: Observation) -> Plan:
        self._update_corrections(obs)
        self.rate_predictor.observe(obs.request_rate)
        rate = self.rate_predictor.predict()
        util = self.config.utilization_target

        # Prefill: demand = rate * isl tokens/s, adjusted by how much worse
        # live TTFT runs than the profile; capacity = one replica's prefill
        # throughput at this ISL while still inside the TTFT budget.
        prefill_demand = rate * obs.mean_isl * self.correction_prefill
        isl_cap = min(
            obs.mean_isl,
            self.prefill_interp.max_isl_within(self.sla.ttft_s),
        )
        prefill_capacity = self.prefill_interp.throughput_at(isl_cap) * util
        prefill = math.ceil(prefill_demand / max(prefill_capacity, 1e-9))

        # Decode: demand = rate * osl tokens/s; capacity = concurrency the
        # ITL budget allows x token rate at that concurrency.
        decode_demand = rate * obs.mean_osl * self.correction_decode
        conc = self.decode_interp.max_concurrency_within(self.sla.itl_s)
        decode_capacity = self.decode_interp.throughput_at(conc) * util
        decode = math.ceil(decode_demand / max(decode_capacity, 1e-9))

        lo, hi = self.config.min_replicas, self.config.max_replicas
        return Plan(
            prefill_replicas=max(lo, min(hi, prefill)),
            decode_replicas=max(lo, min(hi, decode)),
            predicted_rate=rate,
            correction_prefill=self.correction_prefill,
            correction_decode=self.correction_decode,
        )

    # -- loop --------------------------------------------------------------

    async def apply(self, plan: Plan) -> None:
        await self.connector.set_replicas("prefill", plan.prefill_replicas)
        await self.connector.set_replicas("decode", plan.decode_replicas)

    async def run(self, observe, stop_event: asyncio.Event | None = None) -> None:
        """``observe()`` -> Observation each adjustment interval."""
        while stop_event is None or not stop_event.is_set():
            obs = await observe()
            plan = self.compute_plan(obs)
            log.info(
                "plan: rate=%.2f -> prefill=%d decode=%d (corr %.2f/%.2f)",
                plan.predicted_rate, plan.prefill_replicas, plan.decode_replicas,
                plan.correction_prefill, plan.correction_decode,
            )
            await self.apply(plan)
            await asyncio.sleep(self.config.adjustment_interval_s)
