"""dynamo-tpu-run: single-command serving for trials and smoke tests.

Capability parity: reference `launch/dynamo-run` (`in=[http|text|batch]
out=[engine|mocker|echo|dyn://...]`, `src/main.py:27`, `opt.rs:7`) — one
process that embeds the control-plane store, a worker for the chosen
engine, and the chosen input surface:

    python -m dynamo_tpu.run --in http  --out mocker --http-port 8080
    python -m dynamo_tpu.run --in text  --out jax --preset tiny
    python -m dynamo_tpu.run --in batch --out mocker --input prompts.jsonl

``--out dyn://namespace`` skips the embedded worker and serves whatever
workers are registered on an external store (--store-address).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import sys
from pathlib import Path

import aiohttp

from dynamo_tpu.runtime import DistributedRuntime
from dynamo_tpu.runtime.store import StoreServer

log = logging.getLogger("dynamo_tpu.run")


class _EchoEngine:
    """Streams the prompt's own tokens back — the zero-compute engine for
    pipeline smoke tests (parity: reference EchoFull, engines.rs:146)."""

    def __init__(self, delay_s: float = 0.0):
        self.delay_s = delay_s

    async def generate(self, request: dict, context):
        from dynamo_tpu.llm.protocols.common import LLMEngineOutput, PreprocessedRequest

        pre = PreprocessedRequest.from_wire(request)
        limit = pre.stop.max_tokens or len(pre.token_ids)
        toks = pre.token_ids[:limit]
        for i, tok in enumerate(toks):
            out = LLMEngineOutput(token_ids=[tok])
            if i == len(toks) - 1:
                out.finish_reason = "stop"
                out.prompt_tokens = len(pre.token_ids)
                out.completion_tokens = len(toks)
            yield out.to_wire()
            if self.delay_s:
                await asyncio.sleep(self.delay_s)


async def _start_worker(runtime, out_mode: str, args) -> None:
    served = asyncio.Event()
    if out_mode == "mocker":
        from dynamo_tpu.backends.mocker.main import run_mocker
        from dynamo_tpu.llm.mocker import MockEngineArgs

        task = asyncio.create_task(
            run_mocker(
                runtime,
                model_name=args.model_name,
                engine_args=MockEngineArgs(speedup_ratio=args.speedup_ratio),
                served_event=served,
            )
        )
    elif out_mode == "jax":
        from dynamo_tpu.backends.jax.main import run_jax_worker

        task = asyncio.create_task(
            run_jax_worker(
                runtime,
                model_name=args.model_name,
                preset=args.preset,
                served_event=served,
            )
        )
    elif out_mode == "echo":
        from dynamo_tpu.llm.discovery import register_llm
        from dynamo_tpu.llm.model_card import ModelDeploymentCard

        engine = _EchoEngine()
        endpoint = runtime.namespace("dynamo").component("backend").endpoint("generate")

        async def handler(request, context):
            async for out in engine.generate(request, context):
                yield out

        await endpoint.serve(handler)
        await register_llm(
            endpoint,
            ModelDeploymentCard(
                name=args.model_name, tokenizer="byte", model_type="chat",
                context_length=8192, kv_block_size=32,
            ),
        )
        served.set()
        task = None
    else:
        raise ValueError(f"unknown out mode {out_mode!r}")
    await asyncio.wait_for(served.wait(), 60)
    return task


async def _serve_http(front_rt, args) -> None:
    from dynamo_tpu.frontend.main import run_frontend

    await run_frontend(
        front_rt,
        http_host=args.http_host,
        http_port=args.http_port,
        router_mode=args.router_mode,
    )


async def _frontend_url(front_rt, args) -> tuple[asyncio.Task, str]:
    from dynamo_tpu.frontend.main import run_frontend

    ready = asyncio.Event()
    services: list = []
    task = asyncio.create_task(
        run_frontend(
            front_rt, http_host="127.0.0.1", http_port=0,
            router_mode=args.router_mode, ready_event=ready, service_out=services,
        )
    )
    await asyncio.wait_for(ready.wait(), 30)
    url = f"http://127.0.0.1:{services[0].port}"
    async with aiohttp.ClientSession() as s:
        for _ in range(400):
            async with s.get(f"{url}/v1/models") as r:
                if (await r.json())["data"]:
                    return task, url
            await asyncio.sleep(0.05)
    raise TimeoutError("model never appeared on the embedded frontend")


async def _chat_once(url: str, model: str, content: str, max_tokens: int) -> str:
    async with aiohttp.ClientSession() as s:
        body = {
            "model": model,
            "messages": [{"role": "user", "content": content}],
            "max_tokens": max_tokens,
        }
        async with s.post(f"{url}/v1/chat/completions", json=body) as r:
            data = await r.json()
            if "error" in data:
                return f"[error] {data['error']['message']}"
            return data["choices"][0]["message"]["content"]


async def _amain(args) -> None:
    store = None
    store_address = args.store_address
    if store_address is None:
        store = StoreServer()
        await store.start()
        store_address = store.address

    runtimes = []
    try:
        worker_task = None
        if not args.out.startswith("dyn://"):
            worker_rt = await DistributedRuntime.create(store_address)
            runtimes.append(worker_rt)
            worker_task = await _start_worker(worker_rt, args.out, args)

        front_rt = await DistributedRuntime.create(store_address)
        runtimes.append(front_rt)

        if args.in_mode == "http":
            print(f"serving OpenAI API on http://{args.http_host}:{args.http_port}")
            await _serve_http(front_rt, args)
        elif args.in_mode == "text":
            _, url = await _frontend_url(front_rt, args)
            if args.prompt:
                print(await _chat_once(url, args.model_name, args.prompt, args.max_tokens))
            else:
                print("interactive mode — empty line exits")
                while True:
                    line = await asyncio.to_thread(input, "> ")
                    if not line.strip():
                        break
                    print(await _chat_once(url, args.model_name, line, args.max_tokens))
        elif args.in_mode == "batch":
            _, url = await _frontend_url(front_rt, args)
            raw = await asyncio.to_thread(Path(args.input).read_text)
            prompts = [json.loads(ln) for ln in raw.splitlines() if ln.strip()]
            out_fh = (
                await asyncio.to_thread(open, args.output, "w")
                if args.output else sys.stdout
            )
            for item in prompts:
                text = item["prompt"] if isinstance(item, dict) else str(item)
                reply = await _chat_once(url, args.model_name, text, args.max_tokens)
                out_fh.write(json.dumps({"prompt": text, "completion": reply}) + "\n")
            if args.output:
                out_fh.close()
        else:
            raise ValueError(f"unknown in mode {args.in_mode!r}")
    finally:
        for rt in runtimes:
            rt.signal_shutdown()
            try:
                await rt.shutdown()
            except Exception:  # noqa: BLE001 — best-effort teardown
                log.debug("runtime shutdown raced", exc_info=True)
        if store is not None:
            await store.stop()


def main() -> None:
    ap = argparse.ArgumentParser(description="dynamo-tpu single-command runner")
    ap.add_argument("--in", dest="in_mode", default="http", choices=["http", "text", "batch"])
    ap.add_argument("--out", default="mocker", help="mocker | jax | echo | dyn://<ns>")
    ap.add_argument("--model-name", default="model")
    ap.add_argument("--preset", default="tiny")
    ap.add_argument("--speedup-ratio", type=float, default=1.0)
    ap.add_argument("--router-mode", default="kv")
    ap.add_argument("--http-host", default="0.0.0.0")
    ap.add_argument("--http-port", type=int, default=8080)
    ap.add_argument("--store-address", default=None, help="external store (else embedded)")
    ap.add_argument("--prompt", default=None, help="in=text: one-shot prompt")
    ap.add_argument("--max-tokens", type=int, default=64)
    ap.add_argument("--input", default=None, help="in=batch: prompts JSONL")
    ap.add_argument("--output", default=None, help="in=batch: output JSONL")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    asyncio.run(_amain(args))


if __name__ == "__main__":
    main()
