from dynamo_tpu.runtime.component import (
    Component,
    DistributedRuntime,
    Endpoint,
    EndpointClient,
    Instance,
    Namespace,
    NoInstancesError,
)
from dynamo_tpu.runtime.engine import Annotated, AsyncEngine, Context
from dynamo_tpu.runtime.config import RuntimeConfig
from dynamo_tpu.runtime.metrics import MetricsRegistry
from dynamo_tpu.runtime.worker import dynamo_worker

__all__ = [
    "Annotated",
    "AsyncEngine",
    "Component",
    "Context",
    "DistributedRuntime",
    "Endpoint",
    "EndpointClient",
    "Instance",
    "MetricsRegistry",
    "Namespace",
    "NoInstancesError",
    "RuntimeConfig",
    "dynamo_worker",
]
