"""Leader/worker distributed barrier over the control-plane store.

Capability parity: reference `lib/runtime/src/utils/leader_worker_barrier.rs:
137,230` (LeaderBarrier posts data and waits for N workers to check in;
WorkerBarrier reads the data and checks in) — the KVBM leader/worker and
multi-host engine startups synchronize through this.
"""

from __future__ import annotations

import asyncio
import json

_BARRIER_PREFIX = "/dynamo/barrier"


def _data_key(barrier_id: str) -> str:
    return f"{_BARRIER_PREFIX}/{barrier_id}/data"


def _worker_key(barrier_id: str, worker_id: str) -> str:
    return f"{_BARRIER_PREFIX}/{barrier_id}/workers/{worker_id}"


class LeaderBarrier:
    def __init__(self, store, barrier_id: str, num_workers: int):
        self.store = store
        self.barrier_id = barrier_id
        self.num_workers = num_workers

    async def sync(self, data: dict, timeout: float = 60.0) -> list[str]:
        """Post ``data``, wait for all workers; returns their ids."""
        await self.store.kv_put(_data_key(self.barrier_id), json.dumps(data).encode())
        prefix = f"{_BARRIER_PREFIX}/{self.barrier_id}/workers/"

        async def _wait() -> list[str]:
            while True:
                entries = await self.store.kv_get_prefix(prefix)
                if len(entries) >= self.num_workers:
                    return [k[len(prefix):] for k in entries]
                await asyncio.sleep(0.05)

        return await asyncio.wait_for(_wait(), timeout)


class WorkerBarrier:
    def __init__(self, store, barrier_id: str, worker_id: str):
        self.store = store
        self.barrier_id = barrier_id
        self.worker_id = worker_id

    async def sync(self, timeout: float = 60.0, lease: int = 0) -> dict:
        """Wait for the leader's data, then check in; returns the data.

        ``lease`` binds the check-in key to the caller's lease so a dead
        worker's check-in disappears instead of satisfying a later run's
        barrier."""

        async def _wait() -> dict:
            while True:
                raw = await self.store.kv_get(_data_key(self.barrier_id))
                if raw is not None:
                    return json.loads(raw)
                await asyncio.sleep(0.05)

        data = await asyncio.wait_for(_wait(), timeout)
        await self.store.kv_put(
            _worker_key(self.barrier_id, self.worker_id), b"1", lease=lease
        )
        return data
