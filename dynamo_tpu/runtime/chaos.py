"""Deterministic fault injection ("chaos") for every dynamo-tpu plane.

Failure handling that is only exercised by real outages is failure
handling that does not work. This module gives the runtime NAMED
INJECTION POINTS — one line at each place where the system touches a
network, a peer, or an engine loop — and a :class:`ChaosPlan` that turns
a seeded, declarative rule list into faults at those points: dropped or
delayed frames, severed connections, a flapping store session, a stalled
or killed engine loop, a partitioned peer.

Design constraints (the reasons this is not "just a mock"):

* **Compiled to a no-op when disabled.** Every injection site guards on
  ``chaos.active()`` — a single module-global ``is not None`` check — so
  the production hot path pays one pointer read per frame and nothing
  else. ``tests/test_chaos.py`` pins the disabled-path overhead.
* **Deterministic.** A plan owns a ``random.Random(seed)``; the same
  plan against the same traffic fires the same faults. Probabilistic
  rules (``p < 1``) exist for soak-style runs, counted rules
  (``after``/``count``) for surgical repros.
* **Virtual-clock aware.** Delays and stalls go through the plan's
  injectable ``sleep`` so mocker-fleet tests on a sped-up clock can
  scale faults with the same knob.
* **Env/CLI loadable.** ``DYN_CHAOS_PLAN='{"seed":7,"rules":[...]}'``
  (or ``DYN_CHAOS_PLAN=@plan.json``) arms a worker at startup
  (``runtime/worker.py``), so a whole deployment can run under chaos
  without code changes.

Injection-point inventory (the contract between this module and the
call sites; tests assert against these names):

====================  ====================================================
``framing.send``      any outbound frame, every TCP plane (codec level)
``framing.recv``      any inbound frame, every TCP plane (codec level)
``dataplane.connect`` egress dial to a worker (target: ``host:port``)
``dataplane.send``    egress request/cancel frame (target: ``host:port``)
``dataplane.recv``    egress response frame (target: ``host:port``)
``store.connect``     control-plane dial/redial (target: store address)
``store.frame``       control-plane inbound frame (target: store address)
``engine.step``       one engine/sim-loop iteration (target: worker tag)
``kv_transfer.pull``  disagg/peer KV block pull (target: source worker)
``frontend.admit``    one HTTP LLM request at admission (target:
                      ``tenant/model``) — the overload/burst point:
                      ``delay`` slows admission, ``drop``/``sever`` shed
                      the request with a clean retryable 503, and a
                      ``delay`` rule on ``engine.step`` alongside it
                      turns nominal traffic into a saturating burst
                      (see :func:`burst_plan`)
====================  ====================================================

Rule actions:

``delay``  sleep ``delay_s`` before the operation proceeds
``drop``   swallow the frame (send: never written; recv: discarded)
``sever``  raise ``ConnectionError`` (connection/stream death)
``stall``  sleep ``stall_s`` (a wedged-but-connected peer — the failure
           mode deadlines and stall detection exist for)
``kill``   raise :class:`ChaosKill` (engine-loop death; the loop owner
           decides what dying means)

Capability parity: the reference leans on external chaos tooling
(pod-kill tests in its deploy layer); we pull the capability into the
runtime so a laptop test can partition a dataplane deterministically.
"""

from __future__ import annotations

import asyncio
import json
import logging
import random
import time
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable

from dynamo_tpu import knobs

log = logging.getLogger("dynamo_tpu.chaos")

CHAOS_PLAN_ENV = "DYN_CHAOS_PLAN"

POINTS = (
    "framing.send",
    "framing.recv",
    "dataplane.connect",
    "dataplane.send",
    "dataplane.recv",
    "store.connect",
    "store.frame",
    "engine.step",
    "kv_transfer.pull",
    "frontend.admit",
)

ACTIONS = ("delay", "drop", "sever", "stall", "kill")


class ChaosKill(Exception):
    """An engine loop was ordered to die at an injection point."""


@dataclass
class ChaosRule:
    """One fault: WHERE (point + target match), WHEN (after/count/p),
    WHAT (action + timing)."""

    point: str
    action: str
    # Substring match against the site's target descriptor ("" = any).
    match: str = ""
    # Fire probability per eligible hit (evaluated on the plan's seeded
    # RNG, so runs reproduce).
    p: float = 1.0
    # Skip the first `after` matching hits (lets a stream start cleanly
    # before the fault lands mid-flight).
    after: int = 0
    # Maximum number of fires (None = unlimited).
    count: int | None = None
    delay_s: float = 0.05
    stall_s: float = 3600.0
    # Sustained-fault window: when > 0 the rule only fires within
    # ``window_s`` seconds of its FIRST eligible hit (measured on the
    # plan's injectable clock) and goes permanently quiet after — the
    # shape a control-plane blackout needs (sever everything for 60 s,
    # then let recovery proceed). 0 = no window (count/p gate instead).
    window_s: float = 0.0
    # Bookkeeping (not config).
    hits: int = 0
    fires: int = 0
    first_hit_t: float | None = None

    def __post_init__(self) -> None:
        if self.point not in POINTS:
            raise ValueError(
                f"unknown chaos point {self.point!r} (known: {', '.join(POINTS)})"
            )
        if self.action not in ACTIONS:
            raise ValueError(
                f"unknown chaos action {self.action!r} (known: {', '.join(ACTIONS)})"
            )


class ChaosPlan:
    """A seeded set of rules plus the fire log.

    ``sleep`` is injectable for virtual-clock tests; it must be an async
    callable taking seconds.
    """

    def __init__(
        self,
        rules: list[ChaosRule] | None = None,
        seed: int = 0,
        sleep: Callable[[float], Awaitable[None]] | None = None,
        clock: Callable[[], float] | None = None,
    ):
        self.rules = list(rules or [])
        self.seed = seed
        self.rng = random.Random(seed)
        self.sleep = sleep or asyncio.sleep
        # Window gating (``ChaosRule.window_s``) reads this clock;
        # injectable so virtual-clock fleets scale sustained faults with
        # the same knob as delays.
        self.clock = clock or time.monotonic
        # (point, action, target) per fire, in order — the deterministic
        # record tests and operators compare runs with.
        self.fired: list[tuple[str, str, str]] = []

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ChaosPlan":
        rules = [ChaosRule(**r) for r in d.get("rules", [])]
        return cls(rules=rules, seed=int(d.get("seed", 0)))

    @classmethod
    def burst(
        cls,
        slow_s: float = 0.05,
        shed_p: float = 0.0,
        match: str = "",
        seed: int = 0,
        count: int | None = None,
    ) -> "ChaosPlan":
        """The canonical overload/burst rule set (ISSUE 10): slow every
        matching engine iteration by ``slow_s`` — normal arrival rate
        against a 1/slow_s-times-slower fleet IS a burst, queues build
        exactly as under a traffic spike — and optionally shed
        ``shed_p`` of frontend admissions (deterministic on the seed).
        Used by the overload tests to create saturation without
        touching client code."""
        rules = [
            ChaosRule(
                point="engine.step", action="delay", match=match,
                delay_s=slow_s, count=count,
            )
        ]
        if shed_p > 0.0:
            rules.append(
                ChaosRule(
                    point="frontend.admit", action="drop", p=shed_p,
                    match=match, count=count,
                )
            )
        return cls(rules=rules, seed=seed)

    @classmethod
    def store_outage(
        cls,
        duration_s: float = 60.0,
        after_frames: int = 0,
        seed: int = 0,
    ) -> "ChaosPlan":
        """The canonical control-plane blackout (ISSUE 15): sever EVERY
        store session sustainedly for ``duration_s``. The ``store.frame``
        rule kills each live session the moment its next inbound frame
        arrives (keepalive replies flow at ttl/3, so sessions die within
        a beat); the ``store.connect`` rule keeps every redial failing
        until the window passes — then reconnection and session replay
        proceed untouched. ``after_frames`` lets traffic start cleanly
        before the blackout lands. The window clocks from each rule's
        first eligible hit, so arm the plan right before the blackout
        should begin."""
        return cls(
            rules=[
                ChaosRule(
                    point="store.frame", action="sever",
                    after=after_frames, window_s=duration_s,
                ),
                ChaosRule(
                    point="store.connect", action="sever",
                    window_s=duration_s,
                ),
            ],
            seed=seed,
        )

    @classmethod
    def from_env(cls, env: str = CHAOS_PLAN_ENV) -> "ChaosPlan | None":
        """Build a plan from ``$DYN_CHAOS_PLAN`` (inline JSON, or
        ``@/path/to/plan.json``); None when unset/empty."""
        raw = (knobs.raw(env) or "").strip()
        if not raw:
            return None
        if raw.startswith("@"):
            with open(raw[1:], encoding="utf-8") as f:
                raw = f.read()
        return cls.from_dict(json.loads(raw))

    async def fire(self, point: str, target: str | None) -> bool:
        """Run every matching rule; returns False when the operation
        should be dropped, True to proceed. Raises for sever/kill."""
        proceed = True
        tgt = target or ""
        for rule in self.rules:
            if rule.point != point:
                continue
            if rule.match and rule.match not in tgt:
                continue
            rule.hits += 1
            if rule.hits <= rule.after:
                continue
            if rule.window_s > 0.0:
                now = self.clock()
                if rule.first_hit_t is None:
                    rule.first_hit_t = now
                if now - rule.first_hit_t > rule.window_s:
                    continue  # the sustained-fault window has passed
            if rule.count is not None and rule.fires >= rule.count:
                continue
            if rule.p < 1.0 and self.rng.random() >= rule.p:
                continue
            rule.fires += 1
            self.fired.append((point, rule.action, tgt))
            log.debug("chaos: %s at %s (%s)", rule.action, point, tgt or "any")
            if rule.action == "delay":
                await self.sleep(rule.delay_s)
            elif rule.action == "drop":
                proceed = False
            elif rule.action == "sever":
                raise ConnectionError(f"chaos: severed {point} ({tgt or 'any'})")
            elif rule.action == "stall":
                await self.sleep(rule.stall_s)
            elif rule.action == "kill":
                raise ChaosKill(f"chaos: kill at {point} ({tgt or 'any'})")
        return proceed


# ---------------------------------------------------------------------------
# Module-level switch. `_PLAN is None` IS the disabled state; injection
# sites guard on `active()` so the disabled hot path is one global read.
# ---------------------------------------------------------------------------

_PLAN: ChaosPlan | None = None


def install(plan: ChaosPlan) -> None:
    global _PLAN
    _PLAN = plan
    log.warning(
        "CHAOS ENABLED: %d rule(s), seed=%d — this process will inject faults",
        len(plan.rules), plan.seed,
    )


def uninstall() -> None:
    global _PLAN
    _PLAN = None


def active() -> bool:
    return _PLAN is not None


def plan() -> ChaosPlan | None:
    return _PLAN


def install_from_env() -> ChaosPlan | None:
    """Arm this process from ``$DYN_CHAOS_PLAN`` if set (worker startup
    path); returns the installed plan or None."""
    p = ChaosPlan.from_env()
    if p is not None:
        install(p)
    return p


async def inject(point: str, target: str | None = None) -> bool:
    """Fire the active plan at an injection point. Returns True when the
    guarded operation should proceed, False when it must be dropped.
    Call sites guard with ``chaos.active()`` first so the disabled path
    never awaits."""
    p = _PLAN
    if p is None:
        return True
    return await p.fire(point, target)
