"""Component model: Namespace → Component → Endpoint → Instance.

Every deployable process attaches to the distributed runtime, carves out
endpoints under a namespace/component path, serves them on its ingress
server, and registers each endpoint instance in the control-plane KV under
its primary lease — so instances vanish from discovery the moment the
process dies.

Addressing scheme: ``dynamo://{namespace}/{component}/{endpoint}`` with
instances at ``/dynamo/instances/{ns}/{component}/{endpoint}/{instance_id}``.

Capability parity: reference `lib/runtime/src/component.rs:98-520`
(Component/Endpoint/Namespace/Instance, ETCD_ROOT_PATH scheme),
`distributed.rs:53` (DistributedRuntime), `pipeline/network/egress/
push_router.rs:30-179` (round_robin/random/direct routing modes).
"""

from __future__ import annotations

import asyncio
import logging
import os
import random as _random
import time
from dataclasses import dataclass
from typing import Any, AsyncIterator, Callable

import msgpack

from dynamo_tpu import knobs
from dynamo_tpu.runtime import wire
from dynamo_tpu.runtime.dataplane import EgressClient, Handler, IngressServer, ResponseStream
from dynamo_tpu.runtime.store import StoreClient, Subscription
from dynamo_tpu.runtime.store.client import StoreError
from dynamo_tpu.runtime.tasks import spawn_logged

log = logging.getLogger("dynamo_tpu.runtime")

INSTANCE_ROOT = "/dynamo/instances"
DEFAULT_STORE_ADDRESS = knobs.get_str("DYN_STORE_ADDRESS")

# Degraded-mode discovery (ISSUE 15): how long a consumer may keep
# serving on a cached instance whose lease the control plane declared
# dead, while the DATA PLANE says the instance is alive (breaker closed /
# pooled conn / direct probe). 0 disables degraded mode: every
# lease-expiry delete is honored immediately (the pre-ISSUE-15 behavior,
# where a store blackout collapses routing a TTL later).
DISCOVERY_STALE_GRACE_ENV = "DYN_DISCOVERY_STALE_GRACE_S"
# One quarantine liveness probe's dial budget.
DISCOVERY_PROBE_TIMEOUT_S = 1.0
# First re-judgment delay for a lease-expiry delete the egress pool has
# no opinion on: the instance stays provisionally routable for this long
# and the quarantine sweep's off-loop probe decides — the watch loop
# itself never dials, so a mass lease expiry cannot stall discovery
# event processing behind serialized probe timeouts.
DISCOVERY_PROBE_SOON_S = 0.2


def discovery_stale_grace() -> float:
    return knobs.get_float(DISCOVERY_STALE_GRACE_ENV)


@dataclass(frozen=True)
class Instance:
    namespace: str
    component: str
    endpoint: str
    instance_id: int
    address: str  # data-plane host:port
    metadata: dict | None = None

    @property
    def path(self) -> str:
        return f"{self.namespace}/{self.component}/{self.endpoint}"

    def to_wire(self) -> bytes:
        return msgpack.packb(
            {
                wire.INST_NS: self.namespace,
                wire.INST_COMPONENT: self.component,
                wire.INST_ENDPOINT: self.endpoint,
                wire.INST_ID: self.instance_id,
                wire.INST_ADDR: self.address,
                wire.INST_META: self.metadata,
            }
        )

    @classmethod
    def from_wire(cls, raw: bytes) -> "Instance":
        d = msgpack.unpackb(raw, raw=False)
        return cls(
            namespace=d[wire.INST_NS],
            component=d[wire.INST_COMPONENT],
            endpoint=d[wire.INST_ENDPOINT],
            instance_id=d[wire.INST_ID],
            address=d[wire.INST_ADDR],
            metadata=d.get(wire.INST_META),
        )


class DistributedRuntime:
    """A process's handle on the distributed system.

    Bundles the control-plane client, the primary lease (process liveness),
    the ingress server (data-plane listener), and the egress client pool.
    """

    def __init__(self, store: StoreClient, lease_id: int, ingress_host: str = "127.0.0.1"):
        self.store = store
        self.primary_lease_id = lease_id
        self.ingress = IngressServer(host=ingress_host)
        self.egress = EgressClient()
        # Optional per-process status server (worker.py starts it from
        # DYN_SYSTEM_* config); endpoints report health into it on serve.
        self.status = None
        self._ingress_started = False
        self._ingress_lock = asyncio.Lock()
        self._shutdown = asyncio.Event()
        # Instances this process registered (Endpoint.serve) — the drain
        # path deregisters them from discovery before anything else.
        self._served: list[tuple["Endpoint", int]] = []
        self._draining = False
        # Drain-time retraction hooks (async callables), run right after
        # discovery deregistration: workers append their published-state
        # retractions here — e.g. the KV inventory `cleared` event — so
        # routers stop serving stale hints NOW instead of at lease expiry
        # (ISSUE 11 satellite: drain used to leave the KV index stale).
        self.on_drain: list[Callable[[], Any]] = []

    @classmethod
    async def create(
        cls,
        store_address: str | None = None,
        lease_ttl: float = 10.0,
        ingress_host: str = "127.0.0.1",
    ) -> "DistributedRuntime":
        store = await StoreClient.open(store_address or DEFAULT_STORE_ADDRESS)
        lease_id = await store.lease_grant(ttl=lease_ttl)
        return cls(store, lease_id, ingress_host=ingress_host)

    def namespace(self, name: str) -> "Namespace":
        return Namespace(self, name)

    async def ensure_ingress(self) -> IngressServer:
        async with self._ingress_lock:
            if not self._ingress_started:
                await self.ingress.start()
                self._ingress_started = True
        return self.ingress

    async def shutdown(self) -> None:
        self._shutdown.set()
        if self._ingress_started:
            await self.ingress.stop()
        self.egress.close()
        await self.store.close()

    async def drain(self, timeout: float = 30.0) -> bool:
        """Graceful worker drain (SIGTERM path), in containment order:

        1. deregister every served instance from discovery — routers stop
           picking this worker the moment the watch event lands;
        2. stop admitting on the ingress (late arrivals racing the watch
           get a retryable "draining" err → migration replays elsewhere);
        3. let in-flight streams finish within ``timeout`` (stragglers
           are killed by the subsequent shutdown, which peers see as
           worker death → token-replay migration — no request is lost);
        4. revoke the primary lease so lease-bound state (model cards,
           KV inventories) vanishes now rather than at TTL expiry;
        5. release the shutdown waiter so the worker main exits.

        Returns True when all in-flight work completed within budget.
        Parity: reference graceful-shutdown flow (PAPER.md §L1 —
        deregister first, drain, then exit).
        """
        if self._draining:
            await self._shutdown.wait()
            return True
        self._draining = True
        log.info("draining: deregistering %d instance(s)", len(self._served))
        for ep, instance_id in self._served:
            try:
                await ep.deregister(instance_id)
            except (ConnectionError, StoreError):
                log.warning("drain: deregister %s failed", ep.path, exc_info=True)
        # Published-state retraction (KV inventory `cleared`, ...): after
        # deregistration so no new routes target us, before the lease
        # revoke so the events still reach the store.
        for cb in list(self.on_drain):
            try:
                await cb()
            except Exception:  # noqa: BLE001 — retraction is best-effort; lease expiry is the backstop
                log.warning("drain: retraction hook failed", exc_info=True)
        # Flight-recorder post-mortem (ISSUE 13): every engine ring in
        # this process dumps a redacted artifact before the lease goes —
        # the SIGTERM twin of the chaos-kill dump. Off the loop: the
        # dump is file I/O.
        from dynamo_tpu.obs import flight_recorder

        if flight_recorder.enabled():
            try:
                await asyncio.to_thread(
                    flight_recorder.dump_all, "sigterm_drain"
                )
            except Exception:  # noqa: BLE001 — a failed dump must not block the drain
                log.warning("drain: flight-recorder dump failed", exc_info=True)
        completed = True
        if self._ingress_started:
            completed = await self.ingress.drain(timeout)
        try:
            await self.store.lease_revoke(self.primary_lease_id)
        except (ConnectionError, StoreError):
            log.warning("drain: lease revoke failed", exc_info=True)
        self._shutdown.set()
        return completed

    @property
    def draining(self) -> bool:
        """True once a graceful drain began: health surfaces go dark and
        frontends answer new requests with a retryable 503."""
        return self._draining

    def signal_shutdown(self) -> None:
        self._shutdown.set()

    def request_drain(self, timeout: float = 30.0) -> None:
        """Signal-handler-safe drain entry: schedules :meth:`drain` on
        the running loop (SIGTERM → graceful; SIGINT stays immediate via
        :meth:`signal_shutdown`)."""
        spawn_logged(self.drain(timeout), name="graceful-drain", logger=log)

    async def wait_for_shutdown(self) -> None:
        await self._shutdown.wait()


class Namespace:
    def __init__(self, runtime: DistributedRuntime, name: str):
        self.runtime = runtime
        self.name = name

    def component(self, name: str) -> "Component":
        return Component(self.runtime, self.name, name)


class Component:
    def __init__(self, runtime: DistributedRuntime, namespace: str, name: str):
        self.runtime = runtime
        self.namespace = namespace
        self.name = name

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self.runtime, self.namespace, self.name, name)

    @property
    def path(self) -> str:
        return f"{self.namespace}/{self.name}"


class Endpoint:
    def __init__(self, runtime: DistributedRuntime, namespace: str, component: str, name: str):
        self.runtime = runtime
        self.namespace = namespace
        self.component = component
        self.name = name

    @property
    def path(self) -> str:
        return f"{self.namespace}/{self.component}/{self.name}"

    @property
    def instance_prefix(self) -> str:
        return f"{INSTANCE_ROOT}/{self.path}/"

    async def serve(
        self,
        handler: Handler,
        metadata: dict | None = None,
        instance_id: int | None = None,
    ) -> Instance:
        """Serve this endpoint on the process ingress + register the instance.

        Parity: reference `serve_endpoint` (bindings lib.rs:519 →
        endpoint.rs:65) — graceful-deregistration on shutdown is the caller's
        job via `deregister`; process death handles it via lease expiry.
        """
        ingress = await self.runtime.ensure_ingress()
        ingress.register(self.path, handler)
        if self.runtime.status is not None:
            self.runtime.status.set_endpoint_health(self.path, True)
        inst = Instance(
            namespace=self.namespace,
            component=self.component,
            endpoint=self.name,
            instance_id=instance_id
            if instance_id is not None
            else self.runtime.primary_lease_id,
            address=ingress.address,
            metadata=metadata,
        )
        await self.runtime.store.kv_put(
            f"{self.instance_prefix}{inst.instance_id:016x}",
            inst.to_wire(),
            lease=self.runtime.primary_lease_id,
        )
        self.runtime._served.append((self, inst.instance_id))
        log.info("serving %s as instance %d at %s", self.path, inst.instance_id, inst.address)
        return inst

    async def deregister(self, instance_id: int) -> None:
        await self.runtime.store.kv_del(f"{self.instance_prefix}{instance_id:016x}")
        self.runtime.ingress.unregister(self.path)
        self.runtime._served = [
            (ep, iid)
            for ep, iid in self.runtime._served
            if not (iid == instance_id and ep.path == self.path)
        ]

    async def client(self) -> "EndpointClient":
        client = EndpointClient(self)
        await client.start()
        return client


class EndpointClient:
    """Watches an endpoint's instances and routes requests to them.

    Routing modes (parity: reference PushRouter `push_router.rs:138-179`):
    ``round_robin`` | ``random`` | ``direct(instance_id)``.
    """

    def __init__(self, endpoint: Endpoint, stale_grace_s: float | None = None):
        self.endpoint = endpoint
        self.runtime = endpoint.runtime
        self.instances: dict[int, Instance] = {}
        self._watch: Subscription | None = None
        self._watch_task: asyncio.Task | None = None
        self._rr_counter = 0
        self._instances_changed = asyncio.Event()
        self.on_instance_added: list[Callable[[Instance], None]] = []
        self.on_instance_removed: list[Callable[[int], None]] = []
        # Degraded-mode state (ISSUE 15): lease-expiry deletes for
        # instances the data plane still reaches are QUARANTINED (kept
        # routable, probe-rechecked) instead of dropped — the instance
        # snapshot above is last-known-good through a store blackout.
        # Loop-affine: mutated only by the watch loop, the quarantine
        # sweep, and the reconnect reconcile, all on one event loop.
        self.stale_grace_s = (
            discovery_stale_grace() if stale_grace_s is None else stale_grace_s
        )
        self.probe_timeout_s = DISCOVERY_PROBE_TIMEOUT_S
        self._quarantine: dict[int, float] = {}  # id -> monotonic deadline
        self._quarantine_task: asyncio.Task | None = None
        self.quarantined_total = 0
        self.quarantine_recovered_total = 0  # re-registered within grace
        self.quarantine_expired_total = 0    # probe failed; delete applied

    async def start(self) -> None:
        self._watch = await self.runtime.store.kv_watch(self.endpoint.instance_prefix)
        self._watch_task = asyncio.create_task(self._watch_loop())
        # After a store-session rebuild the watch replays current state
        # as puts, but keys that vanished DURING the outage produce no
        # delete — reconcile against the authoritative listing, routing
        # the misses through the same quarantine judgment.
        self.runtime.store.on_reconnect.append(self._reconcile)

    async def stop(self) -> None:
        """Idempotent; awaits task cancellation (same contract as
        ModelWatcher.stop) so no watcher/sweep coroutine outlives it."""
        try:
            self.runtime.store.on_reconnect.remove(self._reconcile)
        except ValueError:
            pass
        tasks = [
            t for t in (self._watch_task, self._quarantine_task) if t is not None
        ]
        self._watch_task = self._quarantine_task = None
        for task in tasks:
            task.cancel()
        for task in tasks:
            try:
                await task
            except asyncio.CancelledError:
                pass
            except Exception:  # noqa: BLE001 — teardown must not raise
                log.exception("endpoint client task failed during stop")
        watch, self._watch = self._watch, None
        if watch:
            await watch.unsubscribe()

    async def _watch_loop(self) -> None:
        assert self._watch is not None
        async for ev in self._watch:
            event = StoreClient.as_watch_event(ev)
            instance_id = int(event.key.rsplit("/", 1)[-1], 16)
            if event.type == wire.EV_PUT:
                inst = Instance.from_wire(event.value)
                known = instance_id in self.instances
                self.instances[instance_id] = inst
                if self._quarantine.pop(instance_id, None) is not None:
                    self.quarantine_recovered_total += 1
                    log.info(
                        "instance %d re-registered within grace on %s",
                        instance_id, self.endpoint.path,
                    )
                if not known:
                    # Replay puts for already-known instances (session
                    # rebuild) must not re-fire add callbacks.
                    for cb in self.on_instance_added:
                        cb(inst)
            else:
                inst = self.instances.get(instance_id)
                if inst is None:
                    continue  # duplicate delete — nothing to retire
                if event.reason == wire.EV_R_LEASE and self.stale_grace_s > 0:
                    # Synchronous judgment only — the watch loop must
                    # never dial (a mass lease expiry would serialize
                    # probe timeouts ahead of replacement-worker puts).
                    judged = self._judge_sync(inst)
                    if judged is not False:
                        # Known-alive: full grace. Unknown: provisional
                        # quarantine; the sweep's off-loop probe decides
                        # within DISCOVERY_PROBE_SOON_S.
                        delay = (
                            self.stale_grace_s
                            if judged
                            else DISCOVERY_PROBE_SOON_S
                        )
                        self._quarantine[instance_id] = time.monotonic() + delay
                        self.quarantined_total += 1
                        log.warning(
                            "instance %d lease-expired on %s; quarantining "
                            "(%s) instead of dropping",
                            instance_id, self.endpoint.path,
                            "data plane alive" if judged else "probe pending",
                        )
                        self._ensure_quarantine_sweep()
                        continue
                self._remove_instance(instance_id)
            self._instances_changed.set()
            self._instances_changed = asyncio.Event()

    def _remove_instance(self, instance_id: int) -> None:
        if self.instances.pop(instance_id, None) is not None:
            log.info(
                "instance %d removed from %s", instance_id, self.endpoint.path
            )
            for cb in self.on_instance_removed:
                cb(instance_id)
        self._quarantine.pop(instance_id, None)
        self._instances_changed.set()
        self._instances_changed = asyncio.Event()

    def _judge_sync(self, inst: Instance) -> bool | None:
        """The egress pool's opinion of an address, without dialing:
        open breaker → False (dead), pooled live connection → True
        (alive), no opinion → None (a probe must decide)."""
        st = self.runtime.egress.stats().get(inst.address)
        if st is not None:
            if st["state"] == "open":
                return False
            if st["connected"]:
                return True
        return None

    async def _should_quarantine(self, inst: Instance) -> bool:
        """Degraded-mode judgment: the control plane said this lease
        died, but lease expiry during a store outage (or a worker↔store
        partition) says nothing about the WORKER. Believe the data plane:
        open breaker → dead; pooled live connection → alive; otherwise
        one cheap direct dial decides."""
        if self.stale_grace_s <= 0:
            return False
        judged = self._judge_sync(inst)
        if judged is not None:
            return judged
        return await self._probe(inst.address)

    async def _probe(self, address: str) -> bool:
        host, _, port = address.rpartition(":")
        try:
            _r, w = await asyncio.wait_for(
                asyncio.open_connection(host or "127.0.0.1", int(port)),
                self.probe_timeout_s,
            )
        except (OSError, asyncio.TimeoutError, ValueError):
            return False
        w.close()
        return True

    def _ensure_quarantine_sweep(self) -> None:
        if self._quarantine_task is None or self._quarantine_task.done():
            self._quarantine_task = asyncio.create_task(self._sweep_quarantine())

    async def _sweep_quarantine(self) -> None:
        """Re-judge quarantined instances at their grace deadlines: a
        data plane that still answers extends the quarantine (liveness is
        the data plane's call during an outage); one that stopped
        answering applies the original delete."""
        while self._quarantine:
            now = time.monotonic()
            due = min(self._quarantine.values())
            # Sleep in DISCOVERY_PROBE_SOON_S-bounded slices: a
            # provisional (probe-pending) entry added mid-sleep must be
            # judged at ITS deadline, not after the earliest pre-existing
            # grace deadline — an uncapped sleep would keep a dead
            # address routable for up to a full grace window.
            await asyncio.sleep(
                max(0.05, min(due - now, DISCOVERY_PROBE_SOON_S))
            )
            now = time.monotonic()
            for iid, deadline in list(self._quarantine.items()):
                if deadline > now:
                    continue
                inst = self.instances.get(iid)
                if inst is None:
                    self._quarantine.pop(iid, None)
                    continue
                # Full judgment, breaker state included: a hung worker
                # whose socket still accepts dials has an OPEN breaker —
                # the raw probe alone would re-quarantine it forever.
                if await self._should_quarantine(inst):
                    self._quarantine[iid] = now + self.stale_grace_s
                else:
                    self.quarantine_expired_total += 1
                    log.warning(
                        "quarantined instance %d on %s stopped answering; "
                        "applying the deferred delete",
                        iid, self.endpoint.path,
                    )
                    self._remove_instance(iid)

    async def _reconcile(self) -> None:
        listed = await self.runtime.store.kv_get_prefix(
            self.endpoint.instance_prefix
        )
        live_ids = {
            int(k.rsplit("/", 1)[-1], 16) for k in listed
        }
        for iid in [i for i in self.instances if i not in live_ids]:
            # .get: a concurrent quarantine-sweep removal between the
            # awaits here must skip the id, not KeyError out of the
            # whole reconcile (stale keys would then stay routable
            # forever — no real delete event is ever coming for them).
            inst = self.instances.get(iid)
            if inst is None:
                continue
            if await self._should_quarantine(inst):
                if iid not in self._quarantine:
                    self._quarantine[iid] = time.monotonic() + self.stale_grace_s
                    self.quarantined_total += 1
                self._ensure_quarantine_sweep()
            else:
                self._remove_instance(iid)

    def degraded_stats(self) -> dict:
        """Quarantine counters + store connectivity for /metrics and
        /health export."""
        return {
            "cached_instances": len(self.instances),
            "quarantined": len(self._quarantine),
            "quarantined_total": self.quarantined_total,
            "quarantine_recovered_total": self.quarantine_recovered_total,
            "quarantine_expired_total": self.quarantine_expired_total,
            "store_connected": getattr(self.runtime.store, "connected", True),
        }

    def instance_ids(self) -> list[int]:
        return sorted(self.instances)

    async def wait_for_instances(self, n: int = 1, timeout: float = 30.0) -> list[int]:
        async def _wait() -> list[int]:
            while len(self.instances) < n:
                await self._instances_changed.wait()
            return self.instance_ids()

        return await asyncio.wait_for(_wait(), timeout)

    # -- routing -----------------------------------------------------------

    def _eligible(self, exclude: set[int] | None) -> list[int]:
        ids = self.instance_ids()
        if exclude:
            filtered = [i for i in ids if i not in exclude]
            # All excluded (e.g. every worker failed once): retry the full
            # set rather than dead-ending — instances may have recovered.
            ids = filtered or ids
        if not ids:
            raise NoInstancesError(self.endpoint.path)
        return ids

    def _pick_round_robin(self, exclude: set[int] | None = None) -> Instance:
        ids = self._eligible(exclude)
        inst = self.instances[ids[self._rr_counter % len(ids)]]
        self._rr_counter += 1
        return inst

    def _pick_random(self, exclude: set[int] | None = None) -> Instance:
        ids = self._eligible(exclude)
        return self.instances[_random.choice(ids)]

    def pick_instance(self, mode: str = "round_robin", exclude: set[int] | None = None) -> int:
        """Choose a live instance id without dispatching (migration uses
        this to know which worker a later stream failure belongs to)."""
        picker = self._pick_random if mode == "random" else self._pick_round_robin
        return picker(exclude).instance_id

    async def direct(
        self, instance_id: int, payload: Any, headers: dict[str, str] | None = None
    ) -> ResponseStream:
        inst = self.instances.get(instance_id)
        if inst is None:
            raise NoInstancesError(f"{self.endpoint.path} instance {instance_id}")
        # Failure attribution: errors the stream synthesizes (conn death,
        # stall deadline, drain refusal) carry the instance id so the
        # migration layer excludes the right worker on replay.
        return await self.runtime.egress.request(
            inst.address, inst.path, payload, headers, worker_id=instance_id
        )

    async def round_robin(self, payload: Any, headers: dict[str, str] | None = None) -> ResponseStream:
        inst = self._pick_round_robin()
        return await self.runtime.egress.request(
            inst.address, inst.path, payload, headers, worker_id=inst.instance_id
        )

    async def random(self, payload: Any, headers: dict[str, str] | None = None) -> ResponseStream:
        inst = self._pick_random()
        return await self.runtime.egress.request(
            inst.address, inst.path, payload, headers, worker_id=inst.instance_id
        )

    async def generate(self, payload: Any, headers: dict[str, str] | None = None) -> ResponseStream:
        return await self.round_robin(payload, headers)


class NoInstancesError(RuntimeError):
    def __init__(self, path: str):
        super().__init__(f"no live instances for endpoint {path}")
