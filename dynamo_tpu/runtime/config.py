"""Runtime configuration from environment (+ optional file overlay).

Env prefix scheme mirrors the reference's figment config
(`lib/runtime/src/config.rs:37,69-181`): ``DYN_RUNTIME_*`` for runtime
knobs, ``DYN_SYSTEM_*`` for the status server, ``DYN_WORKER_*`` for worker
behavior. Values: env beats file beats defaults.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field, fields

from dynamo_tpu import knobs


def _env(name: str, default, cast=None):
    # Fallbacks here are non-literal (file-overlaid RuntimeConfig field
    # values), which is exactly why this wrapper survives next to
    # dynamo_tpu.knobs: env beats file beats registry default.
    raw = os.environ.get(name)
    if raw is None:
        return default
    cast = cast or type(default)
    if cast is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    return cast(raw)


@dataclass
class RuntimeConfig:
    store_address: str = knobs.default("DYN_STORE_ADDRESS")
    lease_ttl_s: float = knobs.default("DYN_RUNTIME_LEASE_TTL_S")
    ingress_host: str = knobs.default("DYN_RUNTIME_INGRESS_HOST")
    namespace: str = knobs.default("DYN_NAMESPACE")
    # System status server (health/metrics), 0 port = ephemeral, None = off
    system_enabled: bool = knobs.default("DYN_SYSTEM_ENABLED")
    system_port: int = knobs.default("DYN_SYSTEM_PORT")
    # Logging
    logging_jsonl: bool = knobs.default("DYN_LOGGING_JSONL")
    log_level: str = knobs.default("DYN_LOG_LEVEL")
    # Request tracing (dynamo_tpu/tracing): DYN_TRACE_* prefix
    trace_enabled: bool = knobs.default("DYN_TRACE_ENABLED")
    trace_sample: float = knobs.default("DYN_TRACE_SAMPLE")
    trace_buffer: int = knobs.default("DYN_TRACE_BUFFER")
    # Graceful drain budget on SIGTERM: how long in-flight streams get
    # to finish after the worker deregisters from discovery. Stragglers
    # past the budget are killed (peers migrate them by token replay).
    drain_timeout_s: float = knobs.default("DYN_WORKER_DRAIN_TIMEOUT_S")

    @classmethod
    def from_env(cls, config_file: str | None = None) -> "RuntimeConfig":
        base: dict = {}
        path = config_file or knobs.raw("DYN_RUNTIME_CONFIG")
        if path and os.path.exists(path):
            with open(path) as f:
                base = json.load(f)
        cfg = cls(**{k: v for k, v in base.items() if k in {f.name for f in fields(cls)}})
        cfg.store_address = _env("DYN_STORE_ADDRESS", cfg.store_address)
        cfg.lease_ttl_s = _env("DYN_RUNTIME_LEASE_TTL_S", cfg.lease_ttl_s)
        cfg.ingress_host = _env("DYN_RUNTIME_INGRESS_HOST", cfg.ingress_host)
        cfg.namespace = _env("DYN_NAMESPACE", cfg.namespace)
        cfg.system_enabled = _env("DYN_SYSTEM_ENABLED", cfg.system_enabled)
        cfg.system_port = _env("DYN_SYSTEM_PORT", cfg.system_port)
        cfg.logging_jsonl = _env("DYN_LOGGING_JSONL", cfg.logging_jsonl)
        cfg.log_level = _env("DYN_LOG_LEVEL", cfg.log_level)
        cfg.trace_enabled = _env("DYN_TRACE_ENABLED", cfg.trace_enabled)
        cfg.trace_sample = _env("DYN_TRACE_SAMPLE", cfg.trace_sample)
        cfg.trace_buffer = _env("DYN_TRACE_BUFFER", cfg.trace_buffer)
        cfg.drain_timeout_s = _env("DYN_WORKER_DRAIN_TIMEOUT_S", cfg.drain_timeout_s)
        return cfg
