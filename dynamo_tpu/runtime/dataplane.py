"""Request/response data plane: direct TCP streaming between processes.

The reference pushes requests over NATS and streams responses back over a
separate raw TCP channel (`lib/runtime/src/pipeline/network.rs:246-284`,
`tcp/server.rs`, `tcp/client.rs`). We collapse both hops into one
multiplexed TCP connection per (client, worker) pair: the client pushes a
request frame carrying a control header + payload (the two-part codec,
`codec/two_part.rs`) and response frames stream back on the same socket.
One fewer network hop and no broker on the hot path — on TPU pods the
request plane is latency-critical for disaggregation handoffs.

Frames (framing.py codec; key constants in runtime/wire.py, schema
``dataplane`` — checked by dynacheck's wire-contract rule):
  client→server:  {"t":"req","i":id,"m":"ns/comp/ep","h":{...},"p":payload}
                  {"t":"stop","i":id}            (graceful cancel)
                  {"t":"kill","i":id}            (hard cancel)
  server→client:  {"t":"rsp","i":id,"p":payload} (zero or more)
                  {"t":"end","i":id}             (stream complete)
                  {"t":"err","i":id,"err":msg}   (stream failed)

Backpressure: response writes go through ``drain()``; a slow client
throttles the producing engine naturally through TCP flow control.

Header contract: the ``h`` map on a request frame carries per-request
metadata end to end — at minimum ``x-request-id`` (log/trace correlation)
and ``traceparent`` (W3C ``00-<32 hex trace id>-<16 hex span id>-01``).
The server hands ``h`` to the handler as ``Context.headers`` untouched;
dynamo_tpu/tracing parses ``traceparent`` there so spans recorded in the
receiving process parent to the sender's span and the whole request
stitches into one trace across disagg and migration hops. Intermediaries
must forward both keys verbatim (mint a child traceparent only when
starting a new span of their own).

Failure containment (the availability contract request migration builds
on, reference RetryManager `lib/llm/src/migration.rs:26`):

* **Deadlines.** Dials are bounded by ``EgressPolicy.connect_s``;
  consumer waits on a response stream are bounded by the per-token
  STALL deadline ``EgressPolicy.stall_s`` — a wedged-but-connected
  worker (engine loop dead, socket alive) surfaces as a synthesized
  ``ConnectionError`` carrying ``worker_id``, which the migration layer
  treats exactly like worker death: replay on another instance.
* **Circuit breaker.** Consecutive connect failures / connection deaths
  per address open a breaker; while open, dials fail fast (no connect
  timeout burned per attempt); after ``breaker_reset_s`` a single
  half-open probe decides. State exports on ``/metrics``
  (status_server.bind_egress_gauges).
* **Eager eviction.** A dead connection is removed from the pool the
  moment its reader loop exits — not at the next ``_get_conn`` — so
  every routing decision sees live pool state.
* **Drain-aware errors.** A draining server (graceful SIGTERM) answers
  new requests with a distinguished err frame that the client surfaces
  as ``ConnectionError`` — i.e. "retry elsewhere", not "request failed".

KV-page payload contract (the ``kv_transfer``/``kv_fetch`` endpoints
that ride this plane): a block's page bytes are OPAQUE to the transport
but self-describing at the endpoint layer — every stream opens with a
geometry/descriptor frame carrying ``shape``, ``dtype``, and (for
kv_transfer) a ``layout`` map with ``kv_dtype``. ``dtype == "int8"``
(quantized KV cache, engine/kv_quant.py) means each block is the
canonical packed buffer: int8 kv bytes ``[L, bs, 2kv, d]`` followed by
f32 per-slot-per-head scales ``[L, bs, 2kv]``. Consumers import the
buffer verbatim (quantize-once bit-stability); a dtype mismatch where
either side is int8 fails the import fast instead of re-quantizing.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import time
from dataclasses import dataclass
from typing import Any, AsyncIterator, Callable

from dynamo_tpu import knobs
from dynamo_tpu.runtime import chaos, framing, wire
from dynamo_tpu.runtime import engine as _engine_errors
from dynamo_tpu.runtime.engine import Context, DeadlineExceededError
from dynamo_tpu.runtime.tasks import spawn_logged

log = logging.getLogger("dynamo_tpu.dataplane")


def _flight_dump(reason: str, detail: str) -> None:
    """Failure-path flight-recorder dump (stall deadline / breaker open).

    These sites fire INSIDE the containment path, on the event loop —
    serializing + writing every ring synchronously here would delay the
    very eviction/failover the dump is documenting (and starve lease
    keepalives in single-process deployments). So when a loop is running
    the dump is handed to the default executor; the rings keep recording
    and the wedged victim's ring is static anyway, so a few milliseconds
    of deferral loses nothing. dump_all is budgeted per reason with a
    cooldown. No-op when nothing records."""
    from dynamo_tpu.obs import flight_recorder

    if not flight_recorder.enabled():
        return

    def _dump() -> None:
        try:
            flight_recorder.dump_all(reason, detail)
        except Exception:  # noqa: BLE001 — a failed dump must not change containment behavior
            log.exception("flight dump failed (%s)", reason)

    try:
        loop = asyncio.get_running_loop()
    except RuntimeError:
        _dump()
        return
    loop.run_in_executor(None, _dump)

Handler = Callable[[Any, Context], AsyncIterator[Any]]

# Distinguished err payload a draining server answers new requests with;
# clients map it to ConnectionError so migration replays elsewhere.
DRAINING_ERR = "worker draining"

# Typed overload markers (runtime/engine.py): an engine-side
# EngineOverloadedError/DeadlineExceededError serializes as an err frame
# whose payload starts with its ``wire`` marker; the client maps the
# marker back (shed -> retryable ConnectionError like DRAINING_ERR,
# deadline -> client-side DeadlineExceededError, never migrated).
SHED_WIRE = _engine_errors.SHED_WIRE
DEADLINE_WIRE = _engine_errors.DEADLINE_WIRE


@dataclass
class EgressPolicy:
    """Client-side containment knobs (env-overridable per process).
    Defaults live in the central knob registry (dynamo_tpu/knobs.py)."""

    # Dial deadline for one egress connect.
    connect_s: float = knobs.default("DYN_DATAPLANE_CONNECT_TIMEOUT_S")
    # Per-frame stall deadline on a response stream: maximum time a
    # consumer waits for the NEXT frame before the stream is declared
    # stalled and synthesized into a ConnectionError. <= 0 disables.
    stall_s: float | None = knobs.default("DYN_DATAPLANE_STALL_TIMEOUT_S")
    # Circuit breaker: consecutive failures to open; cooldown before the
    # half-open probe.
    breaker_threshold: int = knobs.default("DYN_DATAPLANE_BREAKER_THRESHOLD")
    breaker_reset_s: float = knobs.default("DYN_DATAPLANE_BREAKER_RESET_S")

    @classmethod
    def from_env(cls) -> "EgressPolicy":
        stall = knobs.get_float("DYN_DATAPLANE_STALL_TIMEOUT_S")
        return cls(
            connect_s=knobs.get_float("DYN_DATAPLANE_CONNECT_TIMEOUT_S"),
            stall_s=None if stall <= 0 else stall,
            breaker_threshold=knobs.get_int("DYN_DATAPLANE_BREAKER_THRESHOLD"),
            breaker_reset_s=knobs.get_float("DYN_DATAPLANE_BREAKER_RESET_S"),
        )


class CircuitBreaker:
    """Per-address three-state breaker (closed → open → half-open).

    Closed: all dials pass. After ``threshold`` consecutive failures the
    breaker opens and dials fail fast for ``reset_s``; then exactly one
    probe is let through (half-open) — its outcome closes or re-opens.
    Parity: the availability pattern the reference delegates to its NATS
    client; our dataplane owns its own connections so it owns this too.
    """

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half-open"

    def __init__(
        self,
        threshold: int = 5,
        reset_s: float = 2.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.threshold = max(1, threshold)
        self.reset_s = reset_s
        self._clock = clock
        self.state = self.CLOSED
        self.consecutive_failures = 0
        self.opens_total = 0
        self._opened_at = 0.0
        self._probe_at = 0.0
        # Optional closed->open notification (the flight recorder's
        # breaker_open dump trigger). None = no observer (the dynacheck
        # model and unit tests drive the breaker bare).
        self.on_open: Callable[[], None] | None = None

    def allow(self) -> bool:
        if self.state == self.CLOSED:
            return True
        now = self._clock()
        if self.state == self.OPEN:
            if now - self._opened_at >= self.reset_s:
                self.state = self.HALF_OPEN
                self._probe_at = now
                return True  # the single probe
            return False
        # Half-open: a probe is in flight — hold further dials, UNLESS
        # the probe went stale (its task was cancelled mid-dial and never
        # reported back); re-arm rather than wedging the address forever.
        if now - self._probe_at >= self.reset_s:
            self._probe_at = now
            return True
        return False

    def record_success(self) -> None:
        self.state = self.CLOSED
        self.consecutive_failures = 0

    def record_failure(self) -> None:
        self.consecutive_failures += 1
        if (
            self.state == self.HALF_OPEN
            or self.consecutive_failures >= self.threshold
        ):
            opened = self.state != self.OPEN
            if opened:
                self.opens_total += 1
            self.state = self.OPEN
            self._opened_at = self._clock()
            if opened and self.on_open is not None:
                try:
                    self.on_open()
                except Exception:  # noqa: BLE001 — observability must not change breaker behavior
                    log.exception("breaker on_open hook failed")

    def stats(self) -> dict:
        return {
            "state": self.state,
            "consecutive_failures": self.consecutive_failures,
            "opens_total": self.opens_total,
        }


class BreakerOpenError(ConnectionError):
    """Dial rejected fast: the address's circuit breaker is open."""

    def __init__(self, address: str):
        super().__init__(f"circuit breaker open for {address}")
        self.address = address


class IngressServer:
    """Per-process TCP listener dispatching requests to registered engines.

    Parity: reference `PushEndpoint` worker loop
    (`pipeline/network/ingress/push_endpoint.rs:18`).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._routes: dict[str, Handler] = {}
        self._server: asyncio.Server | None = None
        self._inflight: dict[tuple[int, int], tuple[asyncio.Task, Context]] = {}
        self._conn_ids = itertools.count(1)
        self._writers: set[asyncio.StreamWriter] = set()
        # Graceful drain: while True, new requests are refused with a
        # retryable err frame; _idle signals the in-flight set emptied.
        self.draining = False
        self._idle = asyncio.Event()
        self._idle.set()

    def register(self, route: str, handler: Handler) -> None:
        self._routes[route] = handler

    def unregister(self, route: str) -> None:
        self._routes.pop(route, None)

    @property
    def num_inflight(self) -> int:
        return len(self._inflight)

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._on_conn, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def drain(self, timeout: float = 30.0) -> bool:
        """Stop admitting new requests and wait for in-flight handlers
        to finish. Returns True when everything completed within the
        deadline, False when stragglers remain (the caller's stop() will
        kill them, which surfaces to peers as worker death → migration)."""
        self.draining = True
        if not self._inflight:
            return True
        try:
            await asyncio.wait_for(self._idle.wait(), timeout)
            return True
        except asyncio.TimeoutError:
            log.warning(
                "drain deadline passed with %d request(s) still in flight",
                len(self._inflight),
            )
            return False

    async def stop(self) -> None:
        for task, ctx in self._inflight.values():
            ctx.kill()
            task.cancel()
        # Close live connections too, so peers see worker death immediately
        # (the signal request migration keys off).
        for writer in list(self._writers):
            writer.close()
        if self._server:
            self._server.close()
            await self._server.wait_closed()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def _on_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        conn_id = next(self._conn_ids)
        write_lock = asyncio.Lock()
        self._writers.add(writer)
        try:
            while True:
                # Server side waits indefinitely for client traffic by
                # design: an idle multiplexed conn is healthy, and conn
                # death surfaces as EOF.
                # dynalint: unbounded-ok — server read loop idles between frames
                msg = await framing.read_frame(reader)
                kind = msg.get(wire.DP_TYPE)
                if kind == wire.DP_T_REQ:
                    if self.draining:
                        async with write_lock:
                            await framing.send_frame(
                                writer,
                                {wire.DP_TYPE: wire.DP_T_ERR, wire.DP_ID: msg[wire.DP_ID],
                                 wire.DP_ERR: DRAINING_ERR},
                            )
                        continue
                    key = (conn_id, msg[wire.DP_ID])
                    ctx = Context(
                        request_id=msg.get(wire.DP_HEADERS, {}).get("x-request-id"),
                        headers=msg.get(wire.DP_HEADERS, {}),
                    )
                    task = asyncio.create_task(
                        self._serve_one(writer, write_lock, key, msg, ctx)
                    )
                    self._inflight[key] = (task, ctx)
                    self._idle.clear()
                elif kind in (wire.DP_T_STOP, wire.DP_T_KILL):
                    entry = self._inflight.get((conn_id, msg[wire.DP_ID]))
                    if entry is not None:
                        task, ctx = entry
                        if kind == wire.DP_T_KILL:
                            ctx.kill()
                            task.cancel()
                        else:
                            ctx.stop_generating()
        except (asyncio.IncompleteReadError, ConnectionError, ValueError):
            pass
        finally:
            # Peer gone: kill everything it had in flight on this connection.
            for key in [k for k in self._inflight if k[0] == conn_id]:
                task, ctx = self._inflight.pop(key)
                ctx.kill()
                task.cancel()
            if not self._inflight:
                self._idle.set()
            self._writers.discard(writer)
            writer.close()

    async def _serve_one(
        self,
        writer: asyncio.StreamWriter,
        write_lock: asyncio.Lock,
        key: tuple[int, int],
        msg: dict,
        ctx: Context,
    ) -> None:
        req_id = msg[wire.DP_ID]

        async def send(frame: dict) -> None:
            async with write_lock:
                await framing.send_frame(writer, frame)

        try:
            handler = self._routes.get(msg[wire.DP_ROUTE])
            if handler is None:
                await send({wire.DP_TYPE: wire.DP_T_ERR, wire.DP_ID: req_id,
                            wire.DP_ERR: f"no route {msg[wire.DP_ROUTE]!r}"})
                return
            async for item in handler(msg.get(wire.DP_PAYLOAD), ctx):
                await send({wire.DP_TYPE: wire.DP_T_RSP, wire.DP_ID: req_id,
                            wire.DP_PAYLOAD: item})
            await send({wire.DP_TYPE: wire.DP_T_END, wire.DP_ID: req_id})
        except asyncio.CancelledError:
            raise
        except ConnectionError:
            pass
        except Exception as e:  # noqa: BLE001 — stream errors go to the peer
            # Typed overload rejections (EngineOverloadedError /
            # DeadlineExceededError) serialize their canonical wire
            # marker so the client maps them back; they are expected
            # load-shedding behavior, logged at info, not exception.
            wire_code = getattr(e, "wire", None)
            if wire_code:
                log.info("handler %s shed request: %s", msg.get(wire.DP_ROUTE), e)
                payload = f"{wire_code}: {e}"
            else:
                log.exception("handler %s failed", msg.get(wire.DP_ROUTE))
                payload = f"{type(e).__name__}: {e}"
            try:
                await send({wire.DP_TYPE: wire.DP_T_ERR, wire.DP_ID: req_id,
                            wire.DP_ERR: payload})
            except ConnectionError:
                pass
        finally:
            self._inflight.pop(key, None)
            if not self._inflight:
                self._idle.set()


class ResponseStream:
    """Client-side view of one in-flight streamed response.

    ``worker_id`` (set by EndpointClient.direct) rides every failure this
    stream synthesizes, so the migration layer knows WHICH instance to
    exclude on replay — including the stall case, where no transport
    error ever fires.
    """

    _END = object()

    def __init__(self, conn: "_EgressConn", req_id: int, stall_s: float | None = None):
        self._conn = conn
        self._req_id = req_id
        self._queue: asyncio.Queue[Any] = asyncio.Queue()
        self._done = False
        self._stall_s = stall_s
        self.worker_id: int | None = None

    def _push(self, item: Any) -> None:
        self._queue.put_nowait(item)

    def __aiter__(self) -> "ResponseStream":
        return self

    async def __anext__(self) -> Any:
        if self._done:
            raise StopAsyncIteration
        try:
            # Fast path: a frame is already buffered — no deadline task.
            item = self._queue.get_nowait()
        except asyncio.QueueEmpty:
            try:
                item = await asyncio.wait_for(self._queue.get(), self._stall_s)
            except asyncio.TimeoutError:
                # Stalled-but-connected worker: the socket is alive but no
                # frame arrived within the stall budget. Synthesize the
                # same failure shape as worker death so migration replays
                # the request elsewhere, and abandon the stream so a
                # late-reviving worker cannot double-deliver.
                self._done = True
                self._conn.abandon(self._req_id)
                err = ConnectionError(
                    f"stream from {self._conn.address} stalled for "
                    f"{self._stall_s:.1f}s (req {self._req_id})"
                )
                err.worker_id = self.worker_id  # type: ignore[attr-defined]
                raise err from None
        if item is self._END:
            self._done = True
            raise StopAsyncIteration
        if isinstance(item, Exception):
            self._done = True
            raise item
        return item

    async def stop(self) -> None:
        """Graceful cancel: worker finishes current state and ends stream."""
        await self._conn.send({wire.DP_TYPE: wire.DP_T_STOP, wire.DP_ID: self._req_id})

    async def kill(self) -> None:
        # Deregister first: a killed server task sends no end frame, so
        # leaving the entry would leak one registry slot per kill (and a
        # late frame racing the kill must be discarded, not delivered).
        self._conn._streams.pop(self._req_id, None)
        await self._conn.send({wire.DP_TYPE: wire.DP_T_KILL, wire.DP_ID: self._req_id})
        self._push(self._END)

    async def kill_quietly(self) -> None:
        """Best-effort kill for fire-and-forget callers (consumer-
        abandonment cleanup): a connection that died first means the
        server already reaped the request — nothing to report."""
        try:
            await self.kill()
        except (ConnectionError, OSError):
            pass


class _EgressConn:
    def __init__(
        self,
        address: str,
        policy: EgressPolicy | None = None,
        on_dead: Callable[["_EgressConn"], None] | None = None,
        on_stall: Callable[[], None] | None = None,
    ):
        self.address = address
        host, _, port = address.rpartition(":")
        self._host, self._port = host or "127.0.0.1", int(port)
        self.policy = policy or EgressPolicy()
        self._writer: asyncio.StreamWriter | None = None
        self._streams: dict[int, ResponseStream] = {}
        self._ids = itertools.count(1)
        self._lock = asyncio.Lock()
        self._reader_task: asyncio.Task | None = None
        self.healthy = True
        self._on_dead = on_dead
        self._on_stall = on_stall

    async def connect(self) -> None:
        if chaos.active():
            await chaos.inject("dataplane.connect", self.address)
        reader, self._writer = await asyncio.wait_for(
            asyncio.open_connection(self._host, self._port),
            self.policy.connect_s,
        )
        self._reader_task = asyncio.create_task(self._recv_loop(reader))

    async def send(self, frame: dict) -> None:
        if self._writer is None:
            raise ConnectionError("egress not connected")
        if chaos.active() and not await chaos.inject("dataplane.send", self.address):
            return  # frame dropped by the active chaos plan
        async with self._lock:
            await framing.send_frame(self._writer, frame)

    async def request(
        self,
        route: str,
        payload: Any,
        headers: dict[str, str],
        worker_id: int | None = None,
    ) -> ResponseStream:
        req_id = next(self._ids)
        stream = ResponseStream(self, req_id, stall_s=self.policy.stall_s)
        # Attribution BEFORE the frame is written: a refusal/death raced
        # against the send must already carry the instance id.
        stream.worker_id = worker_id
        self._streams[req_id] = stream
        await self.send({
            wire.DP_TYPE: wire.DP_T_REQ, wire.DP_ID: req_id,
            wire.DP_ROUTE: route, wire.DP_HEADERS: headers,
            wire.DP_PAYLOAD: payload,
        })
        return stream

    def abandon(self, req_id: int) -> None:
        """Forget one stream (stall eviction): deregister it so late
        frames are discarded, and best-effort kill the server side."""
        if self._streams.pop(req_id, None) is None:
            return
        if self._on_stall is not None:
            self._on_stall()
        if self._writer is not None and self.healthy:
            spawn_logged(
                self._kill_quietly(req_id),
                name=f"dataplane-kill-{req_id}",
                logger=log,
            )

    async def _kill_quietly(self, req_id: int) -> None:
        try:
            await self.send({wire.DP_TYPE: wire.DP_T_KILL, wire.DP_ID: req_id})
        except (ConnectionError, OSError):
            pass  # the conn died under us; the server reaps on EOF

    async def _recv_loop(self, reader: asyncio.StreamReader) -> None:
        try:
            while True:
                # Idle multiplexed conn between streams is healthy; the
                # consumer-facing bound is the per-stream stall deadline
                # in ResponseStream.
                # dynalint: unbounded-ok — bounded per stream by the stall deadline
                msg = await framing.read_frame(reader)
                if chaos.active() and not await chaos.inject(
                    "dataplane.recv", self.address
                ):
                    continue  # frame dropped by the active chaos plan
                stream = self._streams.get(msg[wire.DP_ID])
                if stream is None:
                    continue
                kind = msg[wire.DP_TYPE]
                if kind == wire.DP_T_RSP:
                    stream._push(msg[wire.DP_PAYLOAD])
                elif kind == wire.DP_T_END:
                    stream._push(ResponseStream._END)
                    self._streams.pop(msg[wire.DP_ID], None)
                elif kind == wire.DP_T_ERR:
                    if msg[wire.DP_ERR] == DRAINING_ERR:
                        # Graceful drain refusal: retryable, not a
                        # request failure — migration replays elsewhere.
                        err: Exception = ConnectionError(
                            f"worker at {self.address} is draining"
                        )
                        err.worker_id = stream.worker_id  # type: ignore[attr-defined]
                    elif msg[wire.DP_ERR].startswith(SHED_WIRE):
                        # Overload shed: same retryable shape as the
                        # drain refusal — migration retries the request
                        # on a less-loaded instance.
                        err = ConnectionError(
                            f"worker at {self.address} shed the request: "
                            f"{msg['err']}"
                        )
                        err.worker_id = stream.worker_id  # type: ignore[attr-defined]
                    elif msg[wire.DP_ERR].startswith(DEADLINE_WIRE):
                        # Deadline expiry is typed but NOT retryable via
                        # migration — the budget is already spent.
                        err = DeadlineExceededError(msg[wire.DP_ERR])
                    else:
                        err = EngineStreamError(msg[wire.DP_ERR])
                    stream._push(err)
                    self._streams.pop(msg[wire.DP_ID], None)
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            pass
        finally:
            self.healthy = False
            # Exactly-once failure delivery: drain the registry FIRST so
            # no other path can push to these streams again, then hand
            # each its own tagged error.
            streams = list(self._streams.values())
            self._streams.clear()
            for stream in streams:
                err = ConnectionError(f"connection to {self.address} lost")
                err.worker_id = stream.worker_id  # type: ignore[attr-defined]
                stream._push(err)
            if self._on_dead is not None:
                self._on_dead(self)

    def close(self) -> None:
        self.healthy = False
        if self._reader_task:
            self._reader_task.cancel()
        if self._writer:
            self._writer.close()


class EgressClient:
    """Connection pool to worker ingress servers, keyed by address.

    Parity: reference `pipeline/network/egress/addressed_router.rs` +
    `tcp/client.rs` (addressed request push + response registration).
    """

    def __init__(self, policy: EgressPolicy | None = None) -> None:
        self.policy = policy or EgressPolicy.from_env()
        self._conns: dict[str, _EgressConn] = {}
        self._locks: dict[str, asyncio.Lock] = {}
        self._breakers: dict[str, CircuitBreaker] = {}
        self._stalls: dict[str, int] = {}

    def _breaker(self, address: str) -> CircuitBreaker:
        br = self._breakers.get(address)
        if br is None:
            br = self._breakers[address] = CircuitBreaker(
                threshold=self.policy.breaker_threshold,
                reset_s=self.policy.breaker_reset_s,
            )
            # Flight-recorder trigger (ISSUE 13): a breaker opening is a
            # containment event worth a post-mortem — dump every engine
            # ring in this process (budgeted + cooldown inside dump_all).
            br.on_open = lambda addr=address: _flight_dump(
                "breaker_open", addr
            )
        return br

    def _on_conn_dead(self, conn: _EgressConn) -> None:
        """Eager eviction: the reader loop saw the conn die — remove it
        from the pool NOW (not at the next dial) and count the failure.
        A conn no longer pooled (replaced, or already evicted by the
        stall path) is not re-debited."""
        if self._conns.get(conn.address) is conn:
            del self._conns[conn.address]
            self._breaker(conn.address).record_failure()

    def _note_stall(self, address: str) -> None:
        """A stall counts against the breaker (a wedged worker is as
        unroutable as a dead one), and the stalled conn is evicted AND
        closed: its socket is alive but its worker is not answering, so
        leaving it pooled would route fresh requests into the same
        stall_s black hole, and its other in-flight streams are doomed
        anyway — closing fails them over NOW instead of one stall budget
        each."""
        self._stalls[address] = self._stalls.get(address, 0) + 1
        # Flight-recorder trigger (ISSUE 13): the stall deadline firing
        # means a worker wedged mid-stream — dump every engine ring in
        # this process (in single-process deployments the victim's
        # recorder lives here too; its ring is static while wedged, so
        # the dump riding the executor loses nothing).
        _flight_dump("stall_deadline", address)
        self._breaker(address).record_failure()
        conn = self._conns.pop(address, None)
        if conn is not None:
            conn.close()

    async def _get_conn(self, address: str) -> _EgressConn:
        conn = self._conns.get(address)
        if conn is not None and conn.healthy:
            return conn
        lock = self._locks.setdefault(address, asyncio.Lock())
        async with lock:
            conn = self._conns.get(address)
            if conn is not None and conn.healthy:
                return conn
            breaker = self._breaker(address)
            if not breaker.allow():
                raise BreakerOpenError(address)
            conn = _EgressConn(
                address,
                policy=self.policy,
                on_dead=self._on_conn_dead,
                on_stall=lambda addr=address: self._note_stall(addr),
            )
            try:
                await conn.connect()
            except (OSError, asyncio.TimeoutError) as e:
                breaker.record_failure()
                raise ConnectionError(f"connect to {address} failed: {e}") from e
            breaker.record_success()
            self._conns[address] = conn
            return conn

    async def request(
        self,
        address: str,
        route: str,
        payload: Any,
        headers: dict[str, str] | None = None,
        worker_id: int | None = None,
    ) -> ResponseStream:
        conn = await self._get_conn(address)
        return await conn.request(route, payload, headers or {}, worker_id=worker_id)

    def stats(self) -> dict[str, dict]:
        """Per-address containment state (breaker + stall counters) for
        /metrics export and operator introspection."""
        out: dict[str, dict] = {}
        for address, br in self._breakers.items():
            st = br.stats()
            conn = self._conns.get(address)
            st["connected"] = bool(conn is not None and conn.healthy)
            st["stalls_total"] = self._stalls.get(address, 0)
            out[address] = st
        return out

    def close(self) -> None:
        for conn in self._conns.values():
            conn.close()
        self._conns.clear()
        self._locks.clear()


class EngineStreamError(RuntimeError):
    """The remote engine reported a failure mid-stream."""
