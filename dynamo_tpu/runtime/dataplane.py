"""Request/response data plane: direct TCP streaming between processes.

The reference pushes requests over NATS and streams responses back over a
separate raw TCP channel (`lib/runtime/src/pipeline/network.rs:246-284`,
`tcp/server.rs`, `tcp/client.rs`). We collapse both hops into one
multiplexed TCP connection per (client, worker) pair: the client pushes a
request frame carrying a control header + payload (the two-part codec,
`codec/two_part.rs`) and response frames stream back on the same socket.
One fewer network hop and no broker on the hot path — on TPU pods the
request plane is latency-critical for disaggregation handoffs.

Frames (framing.py codec):
  client→server:  {"t":"req","i":id,"m":"ns/comp/ep","h":{...},"p":payload}
                  {"t":"stop","i":id}            (graceful cancel)
                  {"t":"kill","i":id}            (hard cancel)
  server→client:  {"t":"rsp","i":id,"p":payload} (zero or more)
                  {"t":"end","i":id}             (stream complete)
                  {"t":"err","i":id,"err":msg}   (stream failed)

Backpressure: response writes go through ``drain()``; a slow client
throttles the producing engine naturally through TCP flow control.

Header contract: the ``h`` map on a request frame carries per-request
metadata end to end — at minimum ``x-request-id`` (log/trace correlation)
and ``traceparent`` (W3C ``00-<32 hex trace id>-<16 hex span id>-01``).
The server hands ``h`` to the handler as ``Context.headers`` untouched;
dynamo_tpu/tracing parses ``traceparent`` there so spans recorded in the
receiving process parent to the sender's span and the whole request
stitches into one trace across disagg and migration hops. Intermediaries
must forward both keys verbatim (mint a child traceparent only when
starting a new span of their own).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
from typing import Any, AsyncIterator, Awaitable, Callable

from dynamo_tpu.runtime import framing
from dynamo_tpu.runtime.engine import Context

log = logging.getLogger("dynamo_tpu.dataplane")

Handler = Callable[[Any, Context], AsyncIterator[Any]]


class IngressServer:
    """Per-process TCP listener dispatching requests to registered engines.

    Parity: reference `PushEndpoint` worker loop
    (`pipeline/network/ingress/push_endpoint.rs:18`).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._routes: dict[str, Handler] = {}
        self._server: asyncio.Server | None = None
        self._inflight: dict[tuple[int, int], tuple[asyncio.Task, Context]] = {}
        self._conn_ids = itertools.count(1)
        self._writers: set[asyncio.StreamWriter] = set()

    def register(self, route: str, handler: Handler) -> None:
        self._routes[route] = handler

    def unregister(self, route: str) -> None:
        self._routes.pop(route, None)

    @property
    def num_inflight(self) -> int:
        return len(self._inflight)

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._on_conn, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        for task, ctx in self._inflight.values():
            ctx.kill()
            task.cancel()
        # Close live connections too, so peers see worker death immediately
        # (the signal request migration keys off).
        for writer in list(self._writers):
            writer.close()
        if self._server:
            self._server.close()
            await self._server.wait_closed()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def _on_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        conn_id = next(self._conn_ids)
        write_lock = asyncio.Lock()
        self._writers.add(writer)
        try:
            while True:
                msg = await framing.read_frame(reader)
                kind = msg.get("t")
                if kind == "req":
                    key = (conn_id, msg["i"])
                    ctx = Context(
                        request_id=msg.get("h", {}).get("x-request-id"),
                        headers=msg.get("h", {}),
                    )
                    task = asyncio.create_task(
                        self._serve_one(writer, write_lock, key, msg, ctx)
                    )
                    self._inflight[key] = (task, ctx)
                elif kind in ("stop", "kill"):
                    entry = self._inflight.get((conn_id, msg["i"]))
                    if entry is not None:
                        task, ctx = entry
                        if kind == "kill":
                            ctx.kill()
                            task.cancel()
                        else:
                            ctx.stop_generating()
        except (asyncio.IncompleteReadError, ConnectionError, ValueError):
            pass
        finally:
            # Peer gone: kill everything it had in flight on this connection.
            for key in [k for k in self._inflight if k[0] == conn_id]:
                task, ctx = self._inflight.pop(key)
                ctx.kill()
                task.cancel()
            self._writers.discard(writer)
            writer.close()

    async def _serve_one(
        self,
        writer: asyncio.StreamWriter,
        write_lock: asyncio.Lock,
        key: tuple[int, int],
        msg: dict,
        ctx: Context,
    ) -> None:
        req_id = msg["i"]

        async def send(frame: dict) -> None:
            async with write_lock:
                await framing.send_frame(writer, frame)

        try:
            handler = self._routes.get(msg["m"])
            if handler is None:
                await send({"t": "err", "i": req_id, "err": f"no route {msg['m']!r}"})
                return
            async for item in handler(msg.get("p"), ctx):
                await send({"t": "rsp", "i": req_id, "p": item})
            await send({"t": "end", "i": req_id})
        except asyncio.CancelledError:
            raise
        except ConnectionError:
            pass
        except Exception as e:  # noqa: BLE001 — stream errors go to the peer
            log.exception("handler %s failed", msg.get("m"))
            try:
                await send({"t": "err", "i": req_id, "err": f"{type(e).__name__}: {e}"})
            except ConnectionError:
                pass
        finally:
            self._inflight.pop(key, None)


class ResponseStream:
    """Client-side view of one in-flight streamed response."""

    _END = object()

    def __init__(self, conn: "_EgressConn", req_id: int):
        self._conn = conn
        self._req_id = req_id
        self._queue: asyncio.Queue[Any] = asyncio.Queue()
        self._done = False

    def _push(self, item: Any) -> None:
        self._queue.put_nowait(item)

    def __aiter__(self) -> "ResponseStream":
        return self

    async def __anext__(self) -> Any:
        if self._done:
            raise StopAsyncIteration
        item = await self._queue.get()
        if item is self._END:
            self._done = True
            raise StopAsyncIteration
        if isinstance(item, Exception):
            self._done = True
            raise item
        return item

    async def stop(self) -> None:
        """Graceful cancel: worker finishes current state and ends stream."""
        await self._conn.send({"t": "stop", "i": self._req_id})

    async def kill(self) -> None:
        await self._conn.send({"t": "kill", "i": self._req_id})
        self._push(self._END)


class _EgressConn:
    def __init__(self, address: str):
        self.address = address
        host, _, port = address.rpartition(":")
        self._host, self._port = host or "127.0.0.1", int(port)
        self._writer: asyncio.StreamWriter | None = None
        self._streams: dict[int, ResponseStream] = {}
        self._ids = itertools.count(1)
        self._lock = asyncio.Lock()
        self._reader_task: asyncio.Task | None = None
        self.healthy = True

    async def connect(self) -> None:
        reader, self._writer = await asyncio.open_connection(self._host, self._port)
        self._reader_task = asyncio.create_task(self._recv_loop(reader))

    async def send(self, frame: dict) -> None:
        if self._writer is None:
            raise ConnectionError("egress not connected")
        async with self._lock:
            await framing.send_frame(self._writer, frame)

    async def request(self, route: str, payload: Any, headers: dict[str, str]) -> ResponseStream:
        req_id = next(self._ids)
        stream = ResponseStream(self, req_id)
        self._streams[req_id] = stream
        await self.send({"t": "req", "i": req_id, "m": route, "h": headers, "p": payload})
        return stream

    async def _recv_loop(self, reader: asyncio.StreamReader) -> None:
        try:
            while True:
                msg = await framing.read_frame(reader)
                stream = self._streams.get(msg["i"])
                if stream is None:
                    continue
                kind = msg["t"]
                if kind == "rsp":
                    stream._push(msg["p"])
                elif kind == "end":
                    stream._push(ResponseStream._END)
                    self._streams.pop(msg["i"], None)
                elif kind == "err":
                    stream._push(EngineStreamError(msg["err"]))
                    self._streams.pop(msg["i"], None)
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            pass
        finally:
            self.healthy = False
            err = ConnectionError(f"connection to {self.address} lost")
            for stream in self._streams.values():
                stream._push(err)
            self._streams.clear()

    def close(self) -> None:
        self.healthy = False
        if self._reader_task:
            self._reader_task.cancel()
        if self._writer:
            self._writer.close()


class EgressClient:
    """Connection pool to worker ingress servers, keyed by address.

    Parity: reference `pipeline/network/egress/addressed_router.rs` +
    `tcp/client.rs` (addressed request push + response registration).
    """

    def __init__(self) -> None:
        self._conns: dict[str, _EgressConn] = {}
        self._locks: dict[str, asyncio.Lock] = {}

    async def _get_conn(self, address: str) -> _EgressConn:
        conn = self._conns.get(address)
        if conn is not None and conn.healthy:
            return conn
        lock = self._locks.setdefault(address, asyncio.Lock())
        async with lock:
            conn = self._conns.get(address)
            if conn is not None and conn.healthy:
                return conn
            conn = _EgressConn(address)
            await conn.connect()
            self._conns[address] = conn
            return conn

    async def request(
        self,
        address: str,
        route: str,
        payload: Any,
        headers: dict[str, str] | None = None,
    ) -> ResponseStream:
        conn = await self._get_conn(address)
        return await conn.request(route, payload, headers or {})

    def close(self) -> None:
        for conn in self._conns.values():
            conn.close()
        self._conns.clear()


class EngineStreamError(RuntimeError):
    """The remote engine reported a failure mid-stream."""
