"""The universal streaming-engine abstraction.

Every request-serving unit in the framework — preprocessor, router, network
egress, model worker, mock engine — is an *async engine*: a callable taking
one request plus a :class:`Context` and yielding a stream of responses.
Engines compose into pipelines by wrapping each other.

Capability parity: reference `lib/runtime/src/engine.rs:90-219`
(`AsyncEngine<SingleIn<Req>, ManyOut<Resp>>`, `AsyncEngineContext`) and
`lib/runtime/src/protocols/annotated.rs:21` (`Annotated<R>` envelope).
Re-designed: Python async generators *are* ManyOut streams, so the trait
collapses to a protocol with one method; context propagation rides
contextvars-free explicit argument passing (explicit beats implicit in a
codebase with process boundaries).
"""

from __future__ import annotations

import asyncio
import uuid
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Protocol, runtime_checkable


class Context:
    """Per-request context: identity, tracing, and two-stage cancellation.

    ``stop`` asks the engine to finish gracefully (emit what it has);
    ``kill`` demands immediate abandonment. Mirrors AsyncEngineContext's
    stop/kill semantics (reference engine.rs:124-180).
    """

    def __init__(self, request_id: str | None = None, headers: dict[str, str] | None = None):
        self.id = request_id or uuid.uuid4().hex
        self.headers: dict[str, str] = headers or {}
        # Open per-request scratch for pipeline operators (runtime/
        # pipeline.py) to pass hints to downstream nodes — e.g. the
        # migration operator's exclude-list for the router egress.
        self.meta: dict[str, Any] = {}
        self._stopped = asyncio.Event()
        self._killed = asyncio.Event()

    @property
    def is_stopped(self) -> bool:
        return self._stopped.is_set()

    @property
    def is_killed(self) -> bool:
        return self._killed.is_set()

    def stop_generating(self) -> None:
        self._stopped.set()

    def kill(self) -> None:
        self._killed.set()
        self._stopped.set()

    async def wait_stopped(self) -> None:
        await self._stopped.wait()

    def child(self) -> "Context":
        """A context sharing identity+cancellation with its parent."""
        child = Context.__new__(Context)
        child.id = self.id
        child.headers = self.headers
        # Copied, not aliased: a child scopes one downstream attempt, and
        # its hints (e.g. migration's exclude list) must not leak back
        # into the parent or into sibling attempts.
        child.meta = dict(self.meta)
        child._stopped = self._stopped
        child._killed = self._killed
        return child


@runtime_checkable
class AsyncEngine(Protocol):
    """Anything that turns one request into a stream of responses."""

    def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        ...


# -- typed overload errors (the shed/deadline contract) ----------------------
#
# Graceful degradation under overload (ISSUE 10) needs REJECTIONS to be
# typed end to end: an engine that cannot take a request raises one of
# these, the ingress server serializes the exception's ``wire`` marker as
# the err-frame payload (dataplane.IngressServer._serve_one), and the
# egress client maps the marker back to the right client-side behavior
# (dataplane._EgressConn._recv_loop):
#
#   EngineOverloadedError -> ConnectionError carrying worker_id
#       ("retry elsewhere"): migration replays the request on another
#       instance, exactly like the PR 6 "draining" refusal — a shed
#       worker is a worker you route around, not a failed request.
#   DeadlineExceededError -> DeadlineExceededError on the client
#       (NOT retried by migration: the deadline has already passed, so
#       replaying elsewhere burns capacity to miss it again). The HTTP
#       frontend maps it to a clean, retryable 503 with Retry-After.
#
# The markers live here (not in dataplane.py) because engines raise these
# without importing the dataplane; dataplane imports this module already.

SHED_WIRE = "worker overloaded (shed)"
DEADLINE_WIRE = "deadline exceeded"


class EngineOverloadedError(ValueError):
    """Admission refused: the engine's bounded queue is full (or the
    frontend's in-flight ceiling is hit). Retryable — on the data plane
    this surfaces to peers as a ConnectionError so the migration layer
    retries on another instance. Subclasses ValueError so a multihost
    follower replaying a leader-rejected add swallows the symmetric
    rejection the same way it swallows validation errors."""

    wire = SHED_WIRE


class DeadlineExceededError(RuntimeError):
    """The request's deadline passed while it was still queued (never
    admitted, nothing streamed). Typed and clean — the client can retry
    with a fresh deadline — but never replayed by migration."""

    wire = DEADLINE_WIRE


@dataclass
class Annotated:
    """SSE-shaped event envelope flowing through LLM pipelines.

    Exactly one of ``data`` (a payload chunk) or ``event``+``comment``
    (a named signal, e.g. ``error`` or an annotation) is typically set.
    """

    data: Any = None
    event: str | None = None
    comment: list[str] = field(default_factory=list)
    id: str | None = None

    @classmethod
    def from_data(cls, data: Any) -> "Annotated":
        return cls(data=data)

    @classmethod
    def from_error(cls, message: str) -> "Annotated":
        return cls(event="error", comment=[message])

    @property
    def is_error(self) -> bool:
        return self.event == "error"

    def error_message(self) -> str | None:
        return "; ".join(self.comment) if self.is_error else None

    def to_wire(self) -> dict:
        out: dict[str, Any] = {}
        if self.data is not None:
            out["d"] = self.data
        if self.event is not None:
            out["e"] = self.event
        if self.comment:
            out["c"] = self.comment
        if self.id is not None:
            out["id"] = self.id
        return out

    @classmethod
    def from_wire(cls, msg: dict) -> "Annotated":
        return cls(
            data=msg.get("d"),
            event=msg.get("e"),
            comment=msg.get("c", []),
            id=msg.get("id"),
        )
