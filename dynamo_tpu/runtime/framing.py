"""Wire framing for all dynamo_tpu TCP planes.

Every control-plane and data-plane connection speaks the same codec:
a 4-byte big-endian length prefix followed by one msgpack-encoded message.
Messages are dicts with short keys; the per-plane key constants and
schemas live in :mod:`dynamo_tpu.runtime.wire`.

Capability parity: reference `lib/runtime/src/pipeline/network/codec/
two_part.rs` (TwoPartMessage: control header + payload in one frame). We get
the same two-part shape by carrying ``h`` (header/control) and ``p``
(payload bytes) keys inside a single msgpack map, so the payload bytes are
never re-encoded — msgpack bin passes them through.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Any

import msgpack

from dynamo_tpu.runtime import chaos

_LEN = struct.Struct(">I")

MAX_FRAME = 512 * 1024 * 1024  # 512 MiB hard cap (KV block transfers are big)


def pack(msg: Any) -> bytes:
    body = msgpack.packb(msg, use_bin_type=True)
    return _LEN.pack(len(body)) + body


async def read_frame(reader: asyncio.StreamReader) -> Any:
    """Read one frame; raises IncompleteReadError / ConnectionError on EOF."""
    while True:
        header = await reader.readexactly(4)
        (length,) = _LEN.unpack(header)
        if length > MAX_FRAME:
            raise ValueError(f"frame of {length} bytes exceeds MAX_FRAME")
        body = await reader.readexactly(length)
        msg = msgpack.unpackb(body, raw=False)
        # Codec-level chaos (every plane): a dropped frame is read and
        # discarded, so the stream stays framed; sever raises here.
        if chaos.active() and not await chaos.inject("framing.recv"):
            continue
        return msg


def write_frame(writer: asyncio.StreamWriter, msg: Any) -> None:
    writer.write(pack(msg))


async def send_frame(writer: asyncio.StreamWriter, msg: Any) -> None:
    if chaos.active() and not await chaos.inject("framing.send"):
        return  # dropped by the active chaos plan
    writer.write(pack(msg))
    await writer.drain()
