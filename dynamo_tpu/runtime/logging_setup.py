"""Structured logging + W3C trace-context propagation.

JSONL log records and per-request ``traceparent`` generation/extraction so
worker spans parent to frontend spans across process boundaries — the
headers dict on every data-plane request carries the traceparent.

Capability parity: reference `lib/runtime/src/logging.rs:111-253`
(trace-id generation, header extraction into NATS headers, JSONL via
DYN_LOGGING_JSONL).
"""

from __future__ import annotations

import json
import logging
import secrets
import sys
import time

TRACEPARENT_HEADER = "traceparent"


def make_traceparent(trace_id: str | None = None, span_id: str | None = None) -> str:
    return "00-{}-{}-01".format(
        trace_id or secrets.token_hex(16), span_id or secrets.token_hex(8)
    )


def parse_traceparent(value: str) -> tuple[str, str] | None:
    """Returns (trace_id, parent_span_id) or None if malformed."""
    parts = value.split("-")
    if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    return parts[1], parts[2]


def child_traceparent(parent: str | None) -> str:
    """New span under the same trace (or a brand-new trace)."""
    if parent:
        parsed = parse_traceparent(parent)
        if parsed:
            return make_traceparent(trace_id=parsed[0])
    return make_traceparent()


class JsonlFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(time.time(), 6),
            "level": record.levelname,
            "target": record.name,
            "msg": record.getMessage(),
        }
        for attr in ("trace_id", "span_id", "request_id"):
            val = getattr(record, attr, None)
            if val is not None:
                out[attr] = val
        if record.exc_info:
            out["exception"] = self.formatException(record.exc_info)
        return json.dumps(out)


def setup_logging(level: str = "INFO", jsonl: bool = False) -> None:
    handler = logging.StreamHandler(sys.stderr)
    if jsonl:
        handler.setFormatter(JsonlFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
        )
    root = logging.getLogger()
    root.handlers.clear()
    root.addHandler(handler)
    root.setLevel(level.upper())
