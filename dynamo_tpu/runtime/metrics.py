"""Hierarchical metrics registry auto-labelled by namespace/component/endpoint.

Thin, opinionated layer over ``prometheus_client``: every metric created
through a registry handle carries the position in the component tree as
constant labels, and the whole tree exposes one ``/metrics`` text blob.

Capability parity: reference `lib/runtime/src/metrics.rs` (MetricsRegistry
with auto ns/component/endpoint labels) and `metrics/prometheus_names.rs`
(the ``dynamo_*`` name scheme).
"""

from __future__ import annotations

import prometheus_client
from prometheus_client import CollectorRegistry, Counter, Gauge, Histogram

PREFIX = "dynamo"


class MetricsRegistry:
    """One per process; `scoped()` handles add constant labels."""

    def __init__(self) -> None:
        self.registry = CollectorRegistry()
        self._metrics: dict[str, object] = {}

    def scoped(self, **labels: str) -> "ScopedMetrics":
        return ScopedMetrics(self, labels)

    def render(self) -> bytes:
        return prometheus_client.generate_latest(self.registry)

    def _get_or_create(self, kind, name: str, doc: str, labelnames: tuple[str, ...], **kwargs):
        full = f"{PREFIX}_{name}"
        key = f"{kind.__name__}:{full}:{labelnames}"
        metric = self._metrics.get(key)
        if metric is None:
            metric = kind(full, doc, labelnames=labelnames, registry=self.registry, **kwargs)
            self._metrics[key] = metric
        return metric


class ScopedMetrics:
    def __init__(self, root: MetricsRegistry, labels: dict[str, str]):
        self._root = root
        self._labels = labels

    def counter(self, name: str, doc: str = "") -> Counter:
        metric = self._root._get_or_create(Counter, name, doc, tuple(self._labels))
        return metric.labels(**self._labels)

    def gauge(self, name: str, doc: str = "") -> Gauge:
        metric = self._root._get_or_create(Gauge, name, doc, tuple(self._labels))
        return metric.labels(**self._labels)

    def remove_gauge(self, name: str) -> None:
        """Drop this label-set's child of a gauge (no-op if absent).
        For exporters with CLIENT-CONTROLLED label values (per-tenant
        queue gauges): without removal, every value ever seen leaves a
        permanent series in /metrics — unbounded output from a header."""
        key = f"Gauge:{PREFIX}_{name}:{tuple(self._labels)}"
        metric = self._root._metrics.get(key)
        if metric is not None:
            try:
                metric.remove(*self._labels.values())
            except KeyError:
                pass

    def histogram(self, name: str, doc: str = "", buckets: tuple | None = None) -> Histogram:
        kwargs = {"buckets": buckets} if buckets else {}
        metric = self._root._get_or_create(Histogram, name, doc, tuple(self._labels), **kwargs)
        return metric.labels(**self._labels)
