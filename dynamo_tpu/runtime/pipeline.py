"""Composable service-pipeline graph: operators over streaming engines.

Capability parity: reference `lib/runtime/src/pipeline/nodes.rs` — a
ServicePipeline is a directed chain where each node acts on BOTH paths:
the forward/request direction and the backward/response direction. The
reference builds this from Source/Sink traits, typed edges, and
`PipelineOperator::forward_edge`/`backward_edge` plumbing; in Python the
whole construction collapses onto async generators (a node that receives
the request, may rewrite it, calls downstream, and transforms the yielded
stream IS both edges), so the graph machinery reduces to one protocol and
a linker. What survives the redesign is the load-bearing property the
reference calls out: an :class:`Operator` sees the forward path AND the
backward path of the same request, so it can carry state from one to the
other (retry-with-replay, usage accounting, tracing) — which a plain
"map over requests" or "map over responses" middleware cannot.

Assembly (reference `ServiceFrontend::link` chains):

    pipe = (
        PipelineBuilder()
        .link(TraceOperator())
        .link(MigrationOperator(limit=3))
        .backend(RouterEgress(client, router))
    )
    async for out in pipe.generate(request, Context()): ...

The assembled :class:`ServicePipeline` is itself an AsyncEngine, so
pipelines nest as nodes of larger pipelines (`lib/runtime/src/pipeline/
network.rs` achieves the same by making a remote segment look like a
local sink; here the data plane's client is just another backend).
"""

from __future__ import annotations

from typing import Any, AsyncIterator, Callable, Protocol, runtime_checkable

from dynamo_tpu.runtime.engine import AsyncEngine, Context

# The downstream continuation an operator drives: "send this (possibly
# rewritten) request onward, stream me the responses".
NextFn = Callable[[Any, Context], AsyncIterator[Any]]


@runtime_checkable
class Operator(Protocol):
    """A node that transforms the forward and/or backward path.

    ``generate`` receives the request, the per-request context, and the
    downstream continuation. It may rewrite the request before invoking
    ``next``, transform or annotate the items the downstream yields,
    re-invoke ``next`` (retries), or short-circuit without calling it at
    all (caches, guards). Reference: `pipeline/nodes.rs` Operator trait.
    """

    def generate(
        self, request: Any, context: Context, next: NextFn
    ) -> AsyncIterator[Any]:
        ...


class FunctionOperator:
    """Adapter lifting plain functions into an :class:`Operator`:
    ``forward`` rewrites the request, ``backward`` maps each response
    item. Either may be ``None`` (identity)."""

    def __init__(
        self,
        forward: Callable[[Any, Context], Any] | None = None,
        backward: Callable[[Any, Context], Any] | None = None,
    ):
        self._forward = forward
        self._backward = backward

    async def generate(self, request: Any, context: Context, next: NextFn):
        if self._forward is not None:
            request = self._forward(request, context)
        async for item in next(request, context):
            yield self._backward(item, context) if self._backward else item


class ServicePipeline:
    """A linked operator chain terminating in a backend engine. The
    pipeline is itself an :class:`AsyncEngine` (nestable as a node)."""

    def __init__(self, operators: list[Operator], backend: AsyncEngine):
        self.operators = list(operators)
        self.backend = backend

    def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        return self._stage(0)(request, context)

    def _stage(self, i: int) -> NextFn:
        if i == len(self.operators):
            return self.backend.generate
        op = self.operators[i]

        def run(request: Any, context: Context) -> AsyncIterator[Any]:
            return op.generate(request, context, self._stage(i + 1))

        return run


class PipelineBuilder:
    """`link()` operators in forward order, close with `backend()`.
    Reference: `ServiceFrontend::link(...).link(...)` chains
    (`pipeline/nodes.rs`), minus the typed-edge ceremony."""

    def __init__(self) -> None:
        self._operators: list[Operator] = []

    def link(self, operator: Operator) -> "PipelineBuilder":
        self._operators.append(operator)
        return self

    def backend(self, engine: AsyncEngine | Callable) -> ServicePipeline:
        if not isinstance(engine, AsyncEngine):
            engine = _CallableEngine(engine)
        return ServicePipeline(self._operators, engine)


class _CallableEngine:
    """Wrap a bare async-generator function as the terminal engine."""

    def __init__(self, fn: Callable[[Any, Context], AsyncIterator[Any]]):
        self._fn = fn

    def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        return self._fn(request, context)
