"""Per-worker system status server: /health, /live, /metrics, /traces.

Capability parity: reference `lib/runtime/src/system_status_server.rs:31-712`
(axum server per process; per-endpoint health states; uptime gauge;
Prometheus text). Enabled through `DYN_SYSTEM_ENABLED` / `DYN_SYSTEM_PORT`
(`config.rs` DYN_SYSTEM_* prefix).

``/traces`` serves the process-local tracing ring buffer
(dynamo_tpu/tracing) as JSON: recent traces with per-phase waterfalls.
Spans recorded in *other* processes of the same deployment share trace
ids (traceparent propagation over the dataplane), so an operator stitches
a full request by querying each process's ``/traces`` for one trace id —
or, in single-process/frontends, reads the whole waterfall in one place.
"""

from __future__ import annotations

import logging
import time
from typing import Callable

from aiohttp import web

from dynamo_tpu import tracing
from dynamo_tpu.runtime.metrics import MetricsRegistry

log = logging.getLogger("dynamo_tpu.status")

# Scheduler gauge export: stats-dict key -> (metric name, doc). Shared by
# the real engine and the mocker (both expose scheduler_stats() dicts with
# these keys), so every worker's /metrics carries the same series.
SCHEDULER_GAUGES: dict[str, tuple[str, str]] = {
    "waiting": (
        "scheduler_waiting_seqs",
        "Sequences queued for admission (inbox + waiting)",
    ),
    "running": (
        "scheduler_running_seqs",
        "Sequences admitted and running",
    ),
    "preemptions": (
        "scheduler_preemptions_total",
        "Sequences preempted (released + re-queued) since start",
    ),
    "decode_stalls": (
        "scheduler_decode_stalls_total",
        "Decode iterations skipped waiting on a free block (mocker's "
        "preemption-lite; always 0 on the real engine, which preempts)",
    ),
    "last_step_batched_tokens": (
        "scheduler_last_step_batched_tokens",
        "Tokens batched into the most recent mixed step",
    ),
    "last_step_budget_utilization": (
        "scheduler_token_budget_utilization",
        "Most recent mixed step's batched tokens / max_num_batched_tokens",
    ),
    "chunked_prefills_in_flight": (
        "scheduler_chunked_prefills_in_flight",
        "Sequences mid-prefill (first chunk run, prompt not finished)",
    ),
    "chunked_scheduling": (
        "scheduler_chunked_enabled",
        "1 when the chunked token-budget scheduler is active",
    ),
    "token_budget": (
        "scheduler_token_budget",
        "Resolved per-step batched-token budget",
    ),
    # Decode megastep (PERF.md r9): the dispatch-amortization evidence.
    "megastep_k": (
        "scheduler_megastep_k",
        "Resolved decode-megastep length (inner iterations per dispatch)",
    ),
    "megastep_dispatches": (
        "scheduler_megastep_dispatches_total",
        "Device dispatches that fused k > 1 decode iterations",
    ),
    "single_step_dispatches": (
        "scheduler_single_step_dispatches_total",
        "Single-iteration device dispatches (prefill waves, k == 1 "
        "mixed steps / verify rows / decode)",
    ),
    # Universal megastep (ISSUE 12): the lifted-carve-out evidence.
    "fused_mixed_dispatches": (
        "scheduler_fused_mixed_dispatches_total",
        "Universal-megastep dispatches that fused a ragged mixed/verify "
        "first iteration (prefill chunks / spec verify rows) with "
        "scanned decode continuation",
    ),
    "megastep_forced_single": (
        "scheduler_megastep_forced_single_total",
        "Megastep batches forced back to k=1 because a lane's stop "
        "watch overflowed the device's slots — the ONE documented "
        "un-fused path; anything non-zero without >8-stop-id requests "
        "is a bug",
    ),
    "dispatches_per_token": (
        "engine_dispatches_per_token",
        "Device dispatches / committed (client-visible) tokens since "
        "start — < 1.0 means multi-token dispatches are amortizing the "
        "fixed per-dispatch overhead",
    ),
    # Pipeline parallelism (ISSUE 20): fused pp megasteps on the fast path.
    "pp_stages": (
        "scheduler_pp_stages",
        "Pipeline-parallel stages this engine runs (1 = pp off)",
    ),
    "pp_pipe_occupancy": (
        "scheduler_pp_pipe_occupancy",
        "Steady-state pipe occupancy k*M / (k*M + pp - 1) for the "
        "resolved megastep length and microbatch count (1.0 when pp off)",
    ),
    "pp_fused_dispatches": (
        "scheduler_pp_fused_dispatches_total",
        "Fused pp megastep dispatches (k > 1 decode iterations wavefront-"
        "interleaved across the pipe in one device program)",
    ),
    "pp_forced_single": (
        "scheduler_pp_forced_single_total",
        "pp decode dispatches forced back to k=1 (stop-watch overflow — "
        "same documented un-fused path as megastep_forced_single)",
    ),
    # Overload robustness (ISSUE 10): bounded-queue + deadline shedding
    # and the fair-scheduler switch, on BOTH backends.
    "queue_limit": (
        "scheduler_queue_limit",
        "Bounded admission-queue ceiling (0 = unbounded); at the limit "
        "new requests get the typed retryable shed error",
    ),
    "shed_total": (
        "scheduler_requests_shed_total",
        "Requests refused at add_request because the bounded queue was "
        "full (each became a retry-elsewhere error, never a broken stream)",
    ),
    "deadline_expired_total": (
        "scheduler_deadline_expired_total",
        "Queued requests expired past their deadline (typed retryable "
        "error frame; admitted requests always run to completion)",
    ),
    "fair_enabled": (
        "scheduler_fair_enabled",
        "1 when per-tenant deficit-round-robin admission is active",
    ),
}


def bind_scheduler_gauges(
    status: "SystemStatusServer | None", scheduler_stats: Callable[[], dict]
) -> None:
    """Export a worker's scheduler gauges on its status-server /metrics,
    evaluated at scrape time (prometheus set_function — no polling task).
    No-op when the status server is disabled."""
    if status is None:
        return
    scoped = status.metrics.scoped(service="engine")
    for key, (name, doc) in SCHEDULER_GAUGES.items():
        scoped.gauge(name, doc).set_function(
            lambda k=key: float(scheduler_stats().get(k, 0) or 0)
        )


# Speculative-decoding gauge export: stats-dict key -> (name, doc). Keys
# match EngineCore.spec_decode_stats() / MockTpuEngine.spec_decode_stats()
# (SpecStats.as_dict + "enabled").
SPEC_GAUGES: dict[str, tuple[str, str]] = {
    "enabled": (
        "spec_decode_enabled",
        "1 when an engine-level speculative-decoding policy is configured",
    ),
    "acceptance_rate": (
        "spec_decode_acceptance_rate",
        "Drafted tokens the target model accepted / drafted tokens",
    ),
    "mean_accepted_len": (
        "spec_decode_mean_accepted_len",
        "Mean tokens emitted per verify row (>= 1.0; the dispatch "
        "amortization speculation buys)",
    ),
    "drafted_tokens": (
        "spec_decode_drafted_tokens_total",
        "Draft tokens proposed (and verified) since start",
    ),
    "accepted_tokens": (
        "spec_decode_accepted_tokens_total",
        "Draft tokens accepted since start",
    ),
    "wasted_tokens": (
        "spec_decode_wasted_tokens_total",
        "Draft tokens verified and rejected since start (speculation loss)",
    ),
    "verify_steps": (
        "spec_decode_verify_steps_total",
        "Engine steps that carried at least one verify row",
    ),
    # On-device drafting (ISSUE 18): draft->verify->accept rounds riding
    # INSIDE megastep dispatches, and the amortization gauge they move.
    "device_rounds": (
        "spec_device_rounds_total",
        "On-device draft rounds ridden inside megastep dispatches",
    ),
    "device_hits": (
        "spec_device_draft_hits_total",
        "On-device draft rounds whose history-ring match proposed at "
        "least one token",
    ),
    "dispatches_per_accepted_token": (
        "spec_decode_dispatches_per_accepted_token",
        "Device dispatches per accepted draft token (lower is better; "
        "on-device drafting compounds accepted depth per dispatch)",
    ),
}


def bind_spec_gauges(
    status: "SystemStatusServer | None", spec_stats: Callable[[], dict]
) -> None:
    """Export a worker's speculative-decoding gauges on /metrics (same
    scrape-time evaluation as the scheduler gauges)."""
    if status is None:
        return
    scoped = status.metrics.scoped(service="engine")
    for key, (name, doc) in SPEC_GAUGES.items():
        scoped.gauge(name, doc).set_function(
            lambda k=key: float(spec_stats().get(k, 0) or 0)
        )


# Prefix-cache gauge export: stats-dict key -> (name, doc). Keys match
# EngineCore.kv_cache_stats() / MockTpuEngine.kv_cache_stats() — the
# allocator has counted prefix queries/hits since the prefix cache
# landed, but never surfaced them on /metrics.
KV_CACHE_GAUGES: dict[str, tuple[str, str]] = {
    # Quantized-KV capacity observability (ISSUE 8): the int8 capacity
    # doubling must be readable off /metrics, not just asserted in tests.
    "kv_dtype_int8": (
        "kv_cache_dtype_int8",
        "1 when the paged KV cache stores int8 pages + scale metadata "
        "(kv_dtype=int8), 0 for the bf16/model-dtype layout",
    ),
    "bytes_per_block": (
        "kv_cache_bytes_per_block",
        "Bytes one KV block occupies across all layers, scale metadata "
        "included (int8 is ~0.52x the bf16 page at head_dim 128)",
    ),
    "capacity_blocks": (
        "kv_cache_capacity_blocks",
        "Total resident-block capacity of the device KV pool",
    ),
    "resident_blocks": (
        "kv_cache_resident_blocks",
        "KV blocks currently resident (pinned + cached)",
    ),
    "prefix_queries": (
        "kv_prefix_cache_queries_total",
        "match_prefix probes (router overlap scoring, disagg "
        "local-vs-remote decisions) since start",
    ),
    "prefix_hits": (
        "kv_prefix_cache_hits_total",
        "match_prefix probes that found at least one cached leading block",
    ),
    "prefix_hit_rate": (
        "kv_prefix_cache_hit_rate",
        "prefix_hits / prefix_queries (probe series; 0 when no queries)",
    ),
    "admitted_queries": (
        "kv_prefix_cache_admitted_queries_total",
        "Sequences admitted by the scheduler since start",
    ),
    "admitted_hits": (
        "kv_prefix_cache_admitted_hits_total",
        "Admitted sequences whose prompt prefix was served from cache "
        "(device blocks or host-tier onboard)",
    ),
    "admitted_hit_rate": (
        "kv_prefix_cache_admitted_hit_rate",
        "admitted_hits / admitted_queries (0 when nothing admitted yet)",
    ),
}


def bind_kv_cache_gauges(
    status: "SystemStatusServer | None", kv_cache_stats: Callable[[], dict]
) -> None:
    """Export a worker's prefix-cache + KV-layout gauges on /metrics
    (same scrape-time evaluation as the scheduler gauges). The cache
    dtype also exports as a labeled info gauge —
    ``kv_cache_dtype{kv_dtype="int8"} 1`` — the Prometheus idiom for
    string-valued facts."""
    if status is None:
        return
    scoped = status.metrics.scoped(service="engine")
    for key, (name, doc) in KV_CACHE_GAUGES.items():
        scoped.gauge(name, doc).set_function(
            lambda k=key: float(kv_cache_stats().get(k, 0) or 0)
        )
    dtype = str(kv_cache_stats().get("kv_dtype", "") or "")
    if dtype:
        status.metrics.scoped(service="engine", kv_dtype=dtype).gauge(
            "kv_cache_dtype",
            "KV cache storage dtype as an info gauge (value label)",
        ).set(1.0)


# Cluster KV pool gauges (ISSUE 11): the worker's peer-pull outcomes and
# its published global-index contribution. Keys match
# PeerKvClient.pool_stats() + KvEventPublisher.stats() on the jax backend
# and MockTpuEngine.kv_pool_stats() on the mocker — identical series on
# both, like every other gauge family here.
KV_POOL_GAUGES: dict[str, tuple[str, str]] = {
    "pulls_attempted": (
        "kv_pool_peer_pulls_attempted_total",
        "Peer prefix pulls started (router hinted a better-overlapping peer)",
    ),
    "pulls_succeeded": (
        "kv_pool_peer_pulls_succeeded_total",
        "Peer pulls that streamed to completion (imported blocks prefix-hit)",
    ),
    "pulls_fallback": (
        "kv_pool_peer_pulls_fallback_total",
        "Peer pulls that degraded to local recompute (sever/stall/dead "
        "peer/dtype mismatch — never a stalled request)",
    ),
    "blocks_pulled": (
        "kv_pool_blocks_pulled_total",
        "KV blocks imported from peers since start",
    ),
    "bytes_pulled": (
        "kv_pool_bytes_pulled_total",
        "KV page bytes received from peers (canonical packed wire buffer)",
    ),
    "last_pull_ms": (
        "kv_pool_last_pull_latency_ms",
        "Wall-clock latency of the most recent peer pull",
    ),
    "pull_ms_total": (
        "kv_pool_pull_latency_ms_total",
        "Cumulative peer-pull wall-clock milliseconds",
    ),
    "breaker_fast_fails": (
        "kv_pool_breaker_fast_fails_total",
        "Peer pulls refused in microseconds by an open dataplane circuit "
        "breaker (recompute instead of burning a connect timeout)",
    ),
    "dtype_mismatches": (
        "kv_pool_dtype_mismatch_total",
        "Peer pulls refused by the kv_dtype fail-fast contract (mixed "
        "int8/float fleet; re-quantizing would break bit-stability)",
    ),
    "published_blocks": (
        "kv_pool_published_blocks",
        "Net blocks this worker currently advertises to the global index "
        "(its stored-minus-removed contribution, all tiers)",
    ),
    "events_dropped": (
        "kv_events_dropped_total",
        "KV events dropped by the bounded publisher buffer (each schedules "
        "an anti-entropy full-inventory resync)",
    ),
    "events_published": (
        "kv_events_published_total",
        "KV events published to the control plane since start",
    ),
    "resyncs": (
        "kv_events_resyncs_total",
        "Full-inventory re-publishes (after buffer overflow or an "
        "indexer-requested resync)",
    ),
}


def bind_kv_pool_gauges(
    status: "SystemStatusServer | None", kv_pool_stats: Callable[[], dict]
) -> None:
    """Export a worker's cluster-KV-pool gauges on /metrics (same
    scrape-time evaluation as the scheduler gauges). No-op when the
    status server is disabled."""
    if status is None:
        return
    scoped = status.metrics.scoped(service="kv_pool")
    for key, (name, doc) in KV_POOL_GAUGES.items():
        scoped.gauge(name, doc).set_function(
            lambda k=key: float(kv_pool_stats().get(k, 0) or 0)
        )


# Streaming-disaggregation handoff gauges (ISSUE 17): chunk-pipelined
# pull progress on the decode side. `early_chunks` is the headline — a
# nonzero value PROVES transfer/compute overlap (chunks landed before
# the prefill's final cursor), which is what the disagg smoke asserts.
DISAGG_GAUGES: dict[str, tuple[str, str]] = {
    "handoffs_started": (
        "disagg_handoffs_total",
        "Streaming handoffs attempted for remotely-prefilled requests",
    ),
    "handoffs_streamed": (
        "disagg_handoffs_streamed_total",
        "Handoffs fully streamed chunk-by-chunk (legacy pull skipped)",
    ),
    "handoffs_fallback": (
        "disagg_handoff_fallback_total",
        "Handoffs degraded to the reply-gated pull (cursor timeout, "
        "severed window, or import refusal)",
    ),
    "chunks_pulled": (
        "disagg_chunks_pulled_total",
        "KV chunk windows pulled over the streaming handoff",
    ),
    "early_chunks": (
        "disagg_early_chunks_total",
        "Chunk windows pulled BEFORE the prefill finished (the overlap "
        "the subsystem exists to create)",
    ),
    "blocks_streamed": (
        "disagg_streamed_blocks_total",
        "KV blocks moved by streaming windows",
    ),
    "cursor_timeouts": (
        "disagg_cursor_timeouts_total",
        "Handoffs that saw no cursor advance within the timeout",
    ),
}


def bind_disagg_gauges(
    status: "SystemStatusServer | None", disagg_stats: Callable[[], dict]
) -> None:
    """Export a decode worker's streaming-handoff gauges on /metrics."""
    if status is None:
        return
    scoped = status.metrics.scoped(service="disagg")
    for key, (name, doc) in DISAGG_GAUGES.items():
        scoped.gauge(name, doc).set_function(
            lambda k=key: float(disagg_stats().get(k, 0) or 0)
        )


# Per-tenant fair-queue gauges: queue depth and DRR deficit per tenant.
# Tenant labels are dynamic (tenants appear as their first request
# arrives), so these sync via a before_render hook like the egress
# gauges rather than pre-bound set_function children.
FAIR_QUEUE_GAUGES: dict[str, tuple[str, str]] = {
    "depth": (
        "scheduler_tenant_queue_depth",
        "Requests waiting in this tenant's admission queue",
    ),
    "deficit": (
        "scheduler_tenant_deficit_tokens",
        "The tenant's current deficit-round-robin token balance",
    ),
}


# Tenant labels come from the CLIENT-controlled x-tenant-id header, so
# the export is bounded: at most this many distinct tenant series, the
# overflow aggregated under tenant="__other__", and drained tenants'
# series REMOVED (not zeroed) so /metrics output cannot grow without
# bound from a rotating-tenant spray.
MAX_TENANT_GAUGES = 64


def bind_fair_queue_gauges(
    status: "SystemStatusServer | None", fair_queue_stats: Callable[[], dict]
) -> None:
    """Export a worker's per-tenant admission-queue gauges on /metrics
    (labels: service=engine, tenant=<id>). ``fair_queue_stats`` returns
    {tenant: {"depth": n, "deficit": d}} (EngineCore/MockTpuEngine
    fair_queue_stats). No-op when the status server is disabled."""
    if status is None:
        return

    seen: set[str] = set()

    def sync() -> None:
        stats = fair_queue_stats()
        if len(stats) > MAX_TENANT_GAUGES:
            ranked = sorted(
                stats.items(), key=lambda kv: -kv[1].get("depth", 0.0)
            )
            stats = dict(ranked[:MAX_TENANT_GAUGES])
            other = {"depth": 0.0, "deficit": 0.0}
            for _t, st in ranked[MAX_TENANT_GAUGES:]:
                for k in other:
                    other[k] += st.get(k, 0.0)
            stats["__other__"] = other
        # Tenants that left the snapshot take their series with them —
        # a stale zeroed series per tenant-ever-seen is still unbounded
        # /metrics growth.
        for tenant in seen - set(stats):
            scoped = status.metrics.scoped(service="engine", tenant=tenant)
            for _key, (name, _doc) in FAIR_QUEUE_GAUGES.items():
                scoped.remove_gauge(name)
        seen.intersection_update(stats)
        for tenant, st in stats.items():
            seen.add(tenant)
            scoped = status.metrics.scoped(service="engine", tenant=tenant)
            for key, (name, doc) in FAIR_QUEUE_GAUGES.items():
                scoped.gauge(name, doc).set(float(st.get(key, 0.0)))

    status.before_render.append(sync)


# Dataplane egress containment gauges: per-address circuit-breaker state
# and stall counters (EgressClient.stats() keys). Addresses are dynamic —
# they appear as the pool dials — so these sync via a before_render hook
# instead of set_function children.
EGRESS_GAUGES: dict[str, tuple[str, str]] = {
    "breaker_open": (
        "egress_breaker_open",
        "1 when the address's circuit breaker is open (dials fail fast)",
    ),
    "breaker_half_open": (
        "egress_breaker_half_open",
        "1 while a single half-open probe decides the breaker's fate",
    ),
    "consecutive_failures": (
        "egress_consecutive_failures",
        "Consecutive connect failures / conn deaths / stalls for the address",
    ),
    "opens_total": (
        "egress_breaker_opens_total",
        "Times the address's breaker has opened since start",
    ),
    "stalls_total": (
        "egress_stream_stalls_total",
        "Response streams declared stalled (per-token deadline) for the address",
    ),
    "connected": (
        "egress_connected",
        "1 while a live pooled connection to the address exists",
    ),
}


def bind_egress_gauges(status: "SystemStatusServer | None", egress) -> None:
    """Export the egress pool's per-address breaker/stall state on
    /metrics (labels: service=dataplane, address=<host:port>). No-op when
    the status server is disabled."""
    if status is None:
        return

    def sync() -> None:
        for address, st in egress.stats().items():
            scoped = status.metrics.scoped(service="dataplane", address=address)
            values = {
                "breaker_open": 1.0 if st["state"] == "open" else 0.0,
                "breaker_half_open": 1.0 if st["state"] == "half-open" else 0.0,
                "consecutive_failures": float(st["consecutive_failures"]),
                "opens_total": float(st["opens_total"]),
                "stalls_total": float(st["stalls_total"]),
                "connected": 1.0 if st["connected"] else 0.0,
            }
            for key, (name, doc) in EGRESS_GAUGES.items():
                scoped.gauge(name, doc).set(values[key])

    status.before_render.append(sync)


# Control-plane connectivity gauges (ISSUE 15): the store client's
# connection-state surface, exported on every process's /metrics (both
# backends via their mains, the frontend via _bind_store_gauges on its
# own registry). Keys match StoreClient.stats().
STORE_GAUGES: dict[str, tuple[str, str]] = {
    "connected": (
        "store_connected",
        "1 while a live control-plane store session exists; 0 means this "
        "process is serving in degraded mode on cached discovery state",
    ),
    "outage_seconds": (
        "store_outage_seconds",
        "Cumulative seconds without a store session since start, the "
        "current outage included",
    ),
    "disconnected_for_s": (
        "store_disconnected_seconds",
        "Seconds since the current outage began (0 while connected)",
    ),
    "keepalive_failures": (
        "store_keepalive_failures_total",
        "Lease-keepalive beats that failed transiently (the loop "
        "survives them and re-attaches expired leases; a rising counter "
        "with store_connected=1 means keepalives are being lost)",
    ),
    "reconnects": (
        "store_session_rebuilds_total",
        "Store sessions rebuilt after an outage (leases re-granted, "
        "lease-bound KV replayed, watches and subscriptions resumed)",
    ),
}


def _bind_store_gauges(metrics: MetricsRegistry, hooks: list, store) -> None:
    """Registry-level binder (the HTTP frontend reuses it on its own
    metrics registry + before_metrics hooks)."""
    scoped = metrics.scoped(service="store")

    def sync() -> None:
        st = store.stats()
        for key, (name, doc) in STORE_GAUGES.items():
            scoped.gauge(name, doc).set(float(st.get(key, 0) or 0))

    hooks.append(sync)


def control_plane_section(store) -> tuple[dict, bool]:
    """The /health ``control_plane`` payload + connected flag, shared by
    the worker status server and the HTTP frontend so the two health
    surfaces can never diverge."""
    st = store.stats()
    connected = bool(st.get("connected"))
    return (
        {
            "connected": connected,
            "outage_seconds": round(float(st.get("outage_seconds", 0.0)), 3),
            "session_rebuilds": int(st.get("reconnects", 0)),
        },
        connected,
    )


def bind_store_gauges(status: "SystemStatusServer | None", store) -> None:
    """Export the process's control-plane connection state on /metrics
    and surface it in /health's ``control_plane`` section. No-op when the
    status server is disabled."""
    if status is None:
        return
    status.store = store
    _bind_store_gauges(status.metrics, status.before_render, store)


class SystemStatusServer:
    def __init__(
        self,
        metrics: MetricsRegistry | None = None,
        host: str = "0.0.0.0",
        port: int = 0,
    ):
        self.metrics = metrics or MetricsRegistry()
        self.host = host
        self.port = port
        self._started_at = time.monotonic()
        # Hooks run before each /metrics render — for exporters whose
        # label sets are dynamic (e.g. per-address breaker gauges, where
        # addresses appear as the egress pool dials new workers) and so
        # cannot pre-bind set_function children.
        self.before_render: list[Callable[[], None]] = []
        # endpoint path -> "ready" | "notready"
        self.endpoint_health: dict[str, str] = {}
        # Store client whose connectivity /health reports (wired by
        # bind_store_gauges); None = no control-plane section.
        self.store = None
        self.app = web.Application()
        self.app.router.add_get("/health", self.health)
        self.app.router.add_get("/live", self.live)
        self.app.router.add_get("/metrics", self.prometheus)
        self.app.router.add_get("/traces", self.traces)
        self._runner: web.AppRunner | None = None
        # Per-phase latency histograms ride this registry (scraped by the
        # planner observer alongside the frontend series).
        tracing.get_collector().bind_metrics(self.metrics)

    def set_endpoint_health(self, path: str, ready: bool) -> None:
        self.endpoint_health[path] = "ready" if ready else "notready"

    @property
    def uptime_s(self) -> float:
        return time.monotonic() - self._started_at

    async def start(self) -> None:
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for addr in self._runner.addresses:
            self.port = addr[1]
        log.info("status server on http://%s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    async def health(self, request: web.Request) -> web.Response:
        ready = all(s == "ready" for s in self.endpoint_health.values())
        status = "healthy" if ready and self.endpoint_health else "starting"
        payload = {
            "status": status,
            "uptime_s": round(self.uptime_s, 3),
            "endpoints": dict(self.endpoint_health),
        }
        if self.store is not None:
            payload["control_plane"], connected = control_plane_section(
                self.store
            )
            if status == "healthy" and not connected:
                # Degraded, NOT unhealthy: the data plane still serves
                # (that is the whole point of ISSUE 15) — stay 200 so
                # orchestrators don't kill a working worker over a store
                # blackout, but make the state visible.
                payload["status"] = status = "degraded"
        return web.json_response(
            payload,
            status=200 if status in ("healthy", "degraded") else 503,
        )

    async def live(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "live"})

    async def prometheus(self, request: web.Request) -> web.Response:
        self.metrics.scoped(service="system").gauge("system_uptime_seconds").set(
            self.uptime_s
        )
        for hook in self.before_render:
            hook()
        return web.Response(body=self.metrics.render(), content_type="text/plain")

    async def traces(self, request: web.Request) -> web.Response:
        return web.json_response(render_traces(request))


def render_traces(request: web.Request) -> dict:
    """Shared ``/traces`` payload (status server + HTTP frontend):
    ``?limit=N`` recent traces, ``?trace_id=...`` to pin one."""
    collector = tracing.get_collector()
    trace_id = request.query.get("trace_id")
    if trace_id:
        traces = collector.traces(trace_id=trace_id)
    else:
        try:
            limit = max(1, min(200, int(request.query.get("limit", "20"))))
        except ValueError:
            limit = 20
        traces = collector.traces(limit=limit)
    return {
        "enabled": tracing.trace_enabled(),
        "buffered_spans": len(collector),
        "stat_spans": len(collector.stats()),
        "capacity": collector.capacity,
        "traces": traces,
    }
