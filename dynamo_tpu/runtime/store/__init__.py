from dynamo_tpu.runtime.store.client import StoreClient, WatchEvent, Subscription
from dynamo_tpu.runtime.store.server import StoreServer

__all__ = ["StoreClient", "StoreServer", "WatchEvent", "Subscription"]
