from dynamo_tpu.runtime.store.server import main

main()
