"""Async client for the control-plane store (see server.py for the contract).

One TCP connection multiplexes all requests, watches, subscriptions, and
queue ops for a process. Leases are kept alive by a background task at
ttl/3, mirroring the reference's etcd lease keep-alive
(`lib/runtime/src/transports/etcd.rs:54-128`).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import random
import time
from dataclasses import dataclass
from typing import Any, AsyncIterator

from dynamo_tpu.runtime import chaos, framing, wire

log = logging.getLogger("dynamo_tpu.store.client")

# Reconnect backoff schedule: exponential ceiling 0.2 -> x2 -> cap 2.0.
RECONNECT_BASE_S = 0.2
RECONNECT_FACTOR = 2.0
RECONNECT_CAP_S = 2.0

# Dial deadline for one store connect/redial attempt: an unreachable (as
# opposed to refusing) store must fail the attempt into the backoff loop,
# not hang it.
CONNECT_TIMEOUT_S = 5.0


def reconnect_delay(attempt: int, rng: random.Random | None = None) -> float:
    """Full-jitter reconnect delay for the given 0-based attempt:
    uniform in [0, min(base * factor**attempt, cap)].

    A store restart disconnects EVERY client in the deployment at the
    same instant; a deterministic schedule would have the whole fleet
    redial in synchronized waves exactly when the store is busiest
    recovering (the thundering-herd shape AWS's backoff-and-jitter note
    measured). Full jitter decorrelates the redials while keeping the
    same ceiling."""
    ceiling = min(RECONNECT_BASE_S * RECONNECT_FACTOR ** attempt, RECONNECT_CAP_S)
    return (rng or random).uniform(0.0, ceiling)


@dataclass(frozen=True)
class WatchEvent:
    type: str  # "put" | "delete"
    key: str
    value: bytes
    revision: int
    # Delete provenance: "del" (explicit retraction) | "lease" (expiry /
    # conn-death revoke — the liveness judgment degraded-mode consumers
    # may second-guess against the data plane). "" on puts.
    reason: str = ""


@dataclass(frozen=True)
class Message:
    subject: str
    payload: bytes


class Subscription:
    """Stream of server-push events for one watch/subscription."""

    _CLOSED = object()

    def __init__(self, client: "StoreClient", sub_id: int):
        self._client = client
        self.sub_id = sub_id
        self.queue: asyncio.Queue[Any] = asyncio.Queue()

    async def __aiter__(self) -> AsyncIterator[Any]:
        while True:
            # Push stream; consumers needing a deadline use .get(timeout).
            # dynalint: unbounded-ok — server-push subscription stream
            item = await self.queue.get()
            if item is self._CLOSED:
                return
            yield item

    async def get(self, timeout: float | None = None) -> Any:
        item = await asyncio.wait_for(self.queue.get(), timeout)
        if item is self._CLOSED:
            raise ConnectionError("subscription closed")
        return item

    def close_nowait(self) -> None:
        self.queue.put_nowait(self._CLOSED)

    async def unsubscribe(self) -> None:
        await self._client.unsubscribe(self)


class StoreClient:
    def __init__(self, address: str):
        self.address = address
        host, _, port = address.rpartition(":")
        self._host, self._port = host or "127.0.0.1", int(port)
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._ids = itertools.count(1)
        self._pending: dict[int, asyncio.Future[Any]] = {}
        self._subs: dict[int, Subscription] = {}
        self._reader_task: asyncio.Task | None = None
        self._keepalive_tasks: dict[int, asyncio.Task] = {}
        self._send_lock = asyncio.Lock()
        self._closed = False
        # Reconnect state: enough to rebuild the session after a store
        # restart or connection blip (VERDICT r3 weak #9 — the reference
        # leans on etcd/NATS client reconnection; this store's client
        # owns the same responsibility). Leases re-attach under their old
        # id (worker identity embeds it) and lease-bound KV is replayed.
        self.auto_reconnect = True
        self._sub_meta: dict[int, tuple[str, dict]] = {}   # sub_id -> (op, params)
        self._lease_meta: dict[int, tuple[float, bool]] = {}  # id -> (ttl, keepalive)
        self._leased_kv: dict[str, tuple[bytes, int]] = {}    # key -> (value, lease)
        # One-shot leases, never replayed; id -> local expiry (pruned on
        # each grant so the map stays bounded).
        self._ephemeral_leases: dict[int, float] = {}
        self.on_reconnect: list = []  # async callbacks, fired after replay
        self._reconnect_task: asyncio.Task | None = None
        # Connection-state surface (ISSUE 15): consumers judge degraded
        # mode off `connected`, operators off the exported counters.
        self._disconnected_since: float | None = None
        self.outage_seconds_total = 0.0
        self.keepalive_failures_total = 0
        self.reconnects_total = 0

    # -- lifecycle ---------------------------------------------------------

    async def connect(self) -> "StoreClient":
        if self._writer is not None:
            return self
        if chaos.active():
            await chaos.inject("store.connect", self.address)
        self._reader, self._writer = await asyncio.wait_for(
            asyncio.open_connection(self._host, self._port), CONNECT_TIMEOUT_S
        )
        self._reader_task = asyncio.create_task(self._recv_loop())
        return self

    @classmethod
    async def open(cls, address: str) -> "StoreClient":
        return await cls(address).connect()

    @property
    def connected(self) -> bool:
        """True while a live session to the store exists. False means the
        control plane is dark for this process: consumers should treat
        discovery state as a last-known-good snapshot, not authority."""
        return self._writer is not None and not self._closed

    @property
    def disconnected_since(self) -> float | None:
        """``time.monotonic()`` of the current outage's start, or None."""
        return self._disconnected_since

    def outage_seconds(self) -> float:
        """Cumulative seconds without a store session, current outage
        included (the `store_outage_seconds` gauge)."""
        total = self.outage_seconds_total
        if self._disconnected_since is not None:
            total += time.monotonic() - self._disconnected_since
        return total

    def stats(self) -> dict:
        """Connection-state payload for /metrics + /health export."""
        now = time.monotonic()
        return {
            "connected": self.connected,
            "outage_seconds": self.outage_seconds(),
            "disconnected_for_s": (
                now - self._disconnected_since
                if self._disconnected_since is not None
                else 0.0
            ),
            "keepalive_failures": self.keepalive_failures_total,
            "reconnects": self.reconnects_total,
        }

    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._reconnect_task:
            self._reconnect_task.cancel()
        for task in self._keepalive_tasks.values():
            task.cancel()
        if self._reader_task:
            self._reader_task.cancel()
        if self._writer:
            self._writer.close()
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionError("store client closed"))
        for sub in self._subs.values():
            sub.close_nowait()

    async def __aenter__(self) -> "StoreClient":
        return await self.connect()

    async def __aexit__(self, *exc) -> None:
        await self.close()

    async def _recv_loop(self) -> None:
        assert self._reader is not None
        try:
            while True:
                # Long-lived multiplexed session: idle is healthy, death
                # surfaces as EOF and enters the reconnect loop; request
                # futures are the bounded consumer surface.
                # dynalint: unbounded-ok — session read loop idles between pushes
                msg = await framing.read_frame(self._reader)
                if chaos.active() and not await chaos.inject(
                    "store.frame", self.address
                ):
                    continue  # frame dropped by the active chaos plan
                if wire.ST_PUSH_SUB in msg:  # server push
                    sub = self._subs.get(msg[wire.ST_PUSH_SUB])
                    if sub is not None:
                        sub.queue.put_nowait(msg[wire.ST_EVENT])
                    continue
                fut = self._pending.pop(msg[wire.ST_ID], None)
                if fut is None or fut.done():
                    continue
                if msg[wire.ST_OK]:
                    fut.set_result(msg[wire.ST_RESULT])
                else:
                    fut.set_exception(StoreError(msg[wire.ST_ERR]))
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            pass
        except OSError:
            pass
        finally:
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError("store connection lost"))
            self._pending.clear()
            if self._closed or not self.auto_reconnect:
                for sub in self._subs.values():
                    sub.close_nowait()
            elif self._reconnect_task is None or self._reconnect_task.done():
                # Subscriptions stay open; their queues resume after the
                # session is rebuilt.
                self._writer = None
                if self._disconnected_since is None:
                    self._disconnected_since = time.monotonic()
                self._reconnect_task = asyncio.create_task(self._reconnect_loop())

    async def _reconnect_loop(self) -> None:
        """Rebuild the session after a lost connection: dial with backoff,
        re-attach leases under their old ids, replay lease-bound KV
        registrations, re-establish subscriptions and watches (the old
        Subscription objects keep their queues — consumers just see a
        gap), then fire ``on_reconnect`` callbacks."""
        if self._writer is not None:
            return  # session already live (duplicate schedule)
        attempt = 0
        while not self._closed:
            try:
                if chaos.active():
                    await chaos.inject("store.connect", self.address)
                self._reader, self._writer = await asyncio.wait_for(
                    asyncio.open_connection(self._host, self._port),
                    CONNECT_TIMEOUT_S,
                )
                break
            except (OSError, asyncio.TimeoutError):
                await asyncio.sleep(reconnect_delay(attempt))
                attempt += 1
        if self._closed:
            return
        self._reader_task = asyncio.create_task(self._recv_loop())
        try:
            # Subscriptions first (so watchers see the lease/KV replay
            # below as live events), drained from a pending list that
            # survives a mid-replay disconnect. Old ids are dropped from
            # the maps up front: a re-issued id may collide with a
            # not-yet-replayed old id, and a half-updated map would
            # cross-wire or silently kill subscriptions.
            pending: list = getattr(self, "_replay_pending", [])
            for old_id in list(self._sub_meta):
                sub = self._subs.pop(old_id, None)
                meta = self._sub_meta.pop(old_id)
                if sub is not None:
                    pending.append((sub, meta))
            self._replay_pending = pending
            while pending:
                sub, (op, params) = pending[0]
                r = await self._request(op, **params)
                sub.sub_id = r[wire.ST_SUB]
                self._subs[r[wire.ST_SUB]] = sub
                self._sub_meta[r[wire.ST_SUB]] = (op, params)
                for ev in r.get(wire.ST_INITIAL) or []:
                    sub.queue.put_nowait(ev)
                pending.pop(0)
            # Leases next: replayed KV entries reference them.
            for lease_id, (ttl, keepalive) in list(self._lease_meta.items()):
                old = self._keepalive_tasks.pop(lease_id, None)
                if old:
                    old.cancel()
                await self._request("lease_grant", ttl=ttl, want=lease_id)
                if keepalive:
                    self._keepalive_tasks[lease_id] = asyncio.create_task(
                        self._keepalive_loop(lease_id, ttl)
                    )
            for key, (value, lease) in list(self._leased_kv.items()):
                try:
                    await self._request("kv_put", k=key, v=value, lease=lease)
                except StoreError:
                    # The lease no longer exists (e.g. an expired ephemeral
                    # lease recorded before its id was pruned): drop the
                    # entry instead of refailing the whole rebuild forever.
                    log.warning("dropping leased key %r (lease %d gone)", key, lease)
                    self._leased_kv.pop(key, None)
            self.reconnects_total += 1
            if self._disconnected_since is not None:
                self.outage_seconds_total += (
                    time.monotonic() - self._disconnected_since
                )
                self._disconnected_since = None
            log.info(
                "store session rebuilt (%d leases, %d registrations, %d subs)",
                len(self._lease_meta), len(self._leased_kv), len(self._sub_meta),
            )
            for cb in self.on_reconnect:
                try:
                    await cb()
                except Exception:  # noqa: BLE001
                    log.exception("on_reconnect callback failed")
        except (ConnectionError, StoreError, OSError):
            # The new connection died mid-replay; try again (the recv
            # loop's finally may have skipped scheduling because this
            # task was still running).
            log.warning("store session replay interrupted; retrying")
            if not self._closed:
                self._writer = None
                self._reconnect_task = asyncio.create_task(self._reconnect_loop())

    async def _request(self, op: str, **params: Any) -> Any:
        if self._writer is None:
            raise ConnectionError("not connected")
        req_id = next(self._ids)
        fut: asyncio.Future[Any] = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        async with self._send_lock:
            await framing.send_frame(
                self._writer,
                {wire.ST_ID: req_id, wire.ST_OP: op, **params},
            )
        return await fut

    # -- KV ----------------------------------------------------------------

    async def kv_put(
        self, key: str, value: bytes, lease: int = 0, create_only: bool = False
    ) -> int:
        r = await self._request("kv_put", k=key, v=value, lease=lease, create_only=create_only)
        if lease and lease not in self._ephemeral_leases:
            # Lease-bound registrations evaporate on a store restart;
            # remember them so the reconnect replay can restore them.
            self._leased_kv[key] = (value, lease)
        else:
            # A permanent overwrite supersedes any earlier lease-bound
            # value; replaying the stale entry would resurrect it.
            self._leased_kv.pop(key, None)
        return r[wire.ST_REV]

    async def kv_get(self, key: str) -> bytes | None:
        r = await self._request("kv_get", k=key)
        return None if r is None else r[wire.ST_VALUE]

    async def kv_del(self, key: str) -> int:
        self._leased_kv.pop(key, None)
        return await self._request("kv_del", k=key)

    async def kv_get_prefix(self, prefix: str) -> dict[str, bytes]:
        r = await self._request("kv_get_prefix", k=prefix)
        return {e[wire.ST_KEY]: e[wire.ST_VALUE] for e in r}

    async def kv_watch(self, prefix: str, with_initial: bool = True) -> Subscription:
        r = await self._request("kv_watch", k=prefix, with_initial=with_initial)
        sub = Subscription(self, r[wire.ST_SUB])
        self._subs[r[wire.ST_SUB]] = sub
        self._sub_meta[r[wire.ST_SUB]] = (
            "kv_watch", {wire.ST_KEY: prefix, wire.ST_WITH_INITIAL: with_initial}
        )
        for ev in r[wire.ST_INITIAL]:
            sub.queue.put_nowait(ev)
        return sub

    @staticmethod
    def as_watch_event(ev: dict) -> WatchEvent:
        return WatchEvent(
            type=ev[wire.EV_TYPE], key=ev[wire.EV_KEY],
            value=ev[wire.EV_VALUE], revision=ev[wire.EV_REV],
            reason=ev.get(
                wire.EV_REASON,
                wire.EV_R_DEL if ev[wire.EV_TYPE] == wire.EV_DELETE else "",
            ),
        )

    # -- leases ------------------------------------------------------------

    async def lease_grant(self, ttl: float = 10.0, keepalive: bool = True) -> int:
        """``keepalive=False`` grants an EPHEMERAL lease: it expires after
        ``ttl`` (deleting its keys) and is deliberately NOT replayed on
        store reconnect — the one-shot reply-key pattern, where replay
        would resurrect a key the consumer already deleted."""
        # conn_bound is the server default, sent explicitly so the wire
        # contract has a producer for the key (dynacheck wire-contract).
        r = await self._request("lease_grant", ttl=ttl, conn_bound=True)
        lease_id = r[wire.ST_LEASE]
        if keepalive:
            self._lease_meta[lease_id] = (ttl, keepalive)
            self._keepalive_tasks[lease_id] = asyncio.create_task(
                self._keepalive_loop(lease_id, ttl)
            )
        else:
            now = time.monotonic()
            self._ephemeral_leases = {
                lid: exp for lid, exp in self._ephemeral_leases.items() if exp > now
            }
            self._ephemeral_leases[lease_id] = now + ttl
        return lease_id

    async def _keepalive_loop(self, lease_id: int, ttl: float) -> None:
        """Keep one lease alive at ttl/3. This loop MUST NOT die on a
        transient failure (the pre-ISSUE-15 bug: the first blip killed it
        silently and the lease expired a TTL later with the process still
        healthy). ConnectionError waits out the outage — the reconnect
        replay re-grants the lease and restarts this task; StoreError
        means the lease vanished server-side while the session stayed up
        (keepalive delayed past TTL, or a restarted store that kept the
        connection), so re-attach it under the same id and re-put its
        keys right here."""
        try:
            while not self._closed and lease_id in self._lease_meta:
                await asyncio.sleep(ttl / 3.0)
                try:
                    await self._request("lease_keepalive", lease=lease_id)
                except ConnectionError:
                    self.keepalive_failures_total += 1
                    # Session down: the reconnect loop owns recovery (it
                    # cancels this task and starts a fresh one after the
                    # lease is re-granted). Keep looping — if the session
                    # comes back under us first, the next beat succeeds.
                except StoreError:
                    self.keepalive_failures_total += 1
                    try:
                        await self._request(
                            "lease_grant", ttl=ttl, want=lease_id
                        )
                        for key, (value, lease) in list(self._leased_kv.items()):
                            if lease == lease_id:
                                await self._request(
                                    "kv_put", k=key, v=value, lease=lease
                                )
                        log.warning(
                            "lease %d re-attached after server-side expiry",
                            lease_id,
                        )
                    except (ConnectionError, StoreError):
                        pass  # retry at the next keepalive beat
        except asyncio.CancelledError:
            pass

    async def lease_revoke(self, lease_id: int) -> bool:
        self._lease_meta.pop(lease_id, None)
        self._leased_kv = {
            k: v for k, v in self._leased_kv.items() if v[1] != lease_id
        }
        task = self._keepalive_tasks.pop(lease_id, None)
        if task:
            task.cancel()
        return await self._request("lease_revoke", lease=lease_id)

    # -- pub/sub -----------------------------------------------------------

    async def subscribe(self, subject: str) -> Subscription:
        r = await self._request("sub", subject=subject)
        sub = Subscription(self, r[wire.ST_SUB])
        self._subs[r[wire.ST_SUB]] = sub
        self._sub_meta[r[wire.ST_SUB]] = ("sub", {wire.ST_SUBJECT: subject})
        return sub

    async def publish(self, subject: str, payload: bytes) -> int:
        return await self._request("pub", subject=subject, p=payload)

    async def unsubscribe(self, sub: Subscription) -> None:
        self._subs.pop(sub.sub_id, None)
        self._sub_meta.pop(sub.sub_id, None)
        sub.close_nowait()
        try:
            await self._request("unsub", sub=sub.sub_id)
        except (ConnectionError, StoreError):
            pass

    @staticmethod
    def as_message(ev: dict) -> Message:
        return Message(subject=ev[wire.EV_SUBJECT], payload=ev[wire.EV_PAYLOAD])

    # -- work queues -------------------------------------------------------

    async def queue_push(self, name: str, payload: bytes) -> int:
        return await self._request("q_push", q=name, p=payload)

    async def queue_pop(self, name: str, timeout: float = 0.0) -> bytes | None:
        return await self._request("q_pop", q=name, timeout=timeout)

    async def queue_len(self, name: str) -> int:
        return await self._request("q_len", q=name)

    # -- object store ------------------------------------------------------

    async def obj_put(self, bucket: str, name: str, payload: bytes) -> None:
        await self._request("obj_put", b=bucket, name=name, p=payload)

    async def obj_get(self, bucket: str, name: str) -> bytes | None:
        return await self._request("obj_get", b=bucket, name=name)

    async def obj_del(self, bucket: str, name: str) -> bool:
        return await self._request("obj_del", b=bucket, name=name)

    async def obj_list(self, bucket: str) -> list[str]:
        return await self._request("obj_list", b=bucket)

    async def ping(self) -> str:
        return await self._request("ping")


class StoreError(RuntimeError):
    pass
