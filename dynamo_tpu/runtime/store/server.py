"""The control-plane store: discovery + messaging for the whole framework.

One asyncio TCP server providing the services the reference sources from two
external systems (SURVEY.md §1 L0):

- **KV with leases and prefix watches** (etcd-equivalent): service discovery,
  instance registration under leases, config hot-reload, barriers.
  Parity: reference `lib/runtime/src/transports/etcd.rs:46-309`.
- **Pub/sub subjects, work queues, object store** (NATS-equivalent):
  KV events, metrics fan-out, the prefill work queue, model-card storage.
  Parity: reference `lib/runtime/src/transports/nats.rs:58-253,433-600`.

Design notes (TPU build): the reference assumes operators run etcd + NATS
next to the cluster. We ship the control plane in-tree instead — it is
hardware-neutral asyncio code, one process, zero external dependencies —
while keeping etcd's *semantics* (leases expire → instances vanish from
discovery; watches see PUT/DELETE with revisions) so every layer above
(discovery, router, disagg, planner) behaves like the reference's.

Failure detection: a lease dies when its TTL lapses without keepalive OR
when the owning connection drops — the latter gives sub-second worker-death
detection (faster than etcd's TTL-only model) and is what request migration
keys off.

Wire protocol (framing.py; key constants in runtime/wire.py, schemas
``store`` + ``store.event`` — checked by dynacheck's wire-contract
rule): requests ``{"i": id, "op": str, ...}`` → responses
``{"i": id, "ok": bool, "r"/"err": ...}``; server-push events
``{"s": sub_id, "ev": {...}}``.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import defaultdict, deque
from dataclasses import dataclass, field
from typing import Any

from dynamo_tpu.runtime import framing, wire

log = logging.getLogger("dynamo_tpu.store")

SWEEP_INTERVAL_S = 0.5
SUB_QUEUE_LIMIT = 16384


@dataclass
class _KvEntry:
    value: bytes
    lease_id: int
    create_rev: int
    mod_rev: int


@dataclass
class _Lease:
    lease_id: int
    ttl_s: float
    deadline: float
    conn_id: int  # owning connection; 0 = detached
    keys: set[str] = field(default_factory=set)


@dataclass
class _Sub:
    sub_id: int
    conn: "_Conn"
    kind: str  # "watch" | "sub"
    pattern: str


def subject_matches(pattern: str, subject: str) -> bool:
    """NATS-style subject matching: '.' tokens, '*' one token, '>' tail."""
    p_toks = pattern.split(".")
    s_toks = subject.split(".")
    for i, p in enumerate(p_toks):
        if p == ">":  # '>' requires at least one remaining subject token
            return len(s_toks) > i
        if i >= len(s_toks):
            return False
        if p != "*" and p != s_toks[i]:
            return False
    return len(p_toks) == len(s_toks)


class _Conn:
    def __init__(self, conn_id: int, writer: asyncio.StreamWriter):
        self.conn_id = conn_id
        self.writer = writer
        self.queue: asyncio.Queue[bytes | None] = asyncio.Queue(maxsize=SUB_QUEUE_LIMIT)
        self.closed = False

    def push(self, msg: Any) -> None:
        """Enqueue an outbound frame; drops (with a log) if the peer is slow."""
        if self.closed:
            return
        try:
            self.queue.put_nowait(framing.pack(msg))
        except asyncio.QueueFull:
            log.warning("conn %d slow consumer, dropping frame", self.conn_id)


class StoreServer:
    """In-process control-plane server. ``async with`` or start()/stop()."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._server: asyncio.Server | None = None
        self._rev = 0
        self._next_id = 1
        self._kv: dict[str, _KvEntry] = {}
        self._leases: dict[int, _Lease] = {}
        self._subs: dict[int, _Sub] = {}
        self._conns: dict[int, _Conn] = {}
        self._queues: dict[str, deque[bytes]] = defaultdict(deque)
        self._queue_waiters: dict[str, deque[asyncio.Future[bytes]]] = defaultdict(deque)
        self._objects: dict[str, dict[str, bytes]] = defaultdict(dict)
        self._sweeper: asyncio.Task | None = None

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._on_conn, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        self._sweeper = asyncio.create_task(self._sweep_loop())
        log.info("store server listening on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._sweeper:
            self._sweeper.cancel()
        # Close live connections BEFORE wait_closed(): since 3.12 it waits
        # for every connection handler, so a connected client (e.g. one
        # about to exercise reconnect) would hang shutdown forever.
        for conn in list(self._conns.values()):
            conn.closed = True
            conn.writer.close()
        if self._server:
            self._server.close()
            await self._server.wait_closed()

    async def __aenter__(self) -> "StoreServer":
        await self.start()
        return self

    async def __aexit__(self, *exc) -> None:
        await self.stop()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    # -- connection handling ----------------------------------------------

    async def _on_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        conn = _Conn(self._new_id(), writer)
        self._conns[conn.conn_id] = conn
        sender = asyncio.create_task(self._send_loop(conn))
        try:
            while True:
                # dynalint: unbounded-ok — server read loop idles between requests
                msg = await framing.read_frame(reader)
                try:
                    result = await self._dispatch(conn, msg)
                    conn.push({wire.ST_ID: msg[wire.ST_ID], wire.ST_OK: True,
                               wire.ST_RESULT: result})
                except Exception as e:  # noqa: BLE001 — report op errors to client
                    conn.push({wire.ST_ID: msg[wire.ST_ID], wire.ST_OK: False,
                               wire.ST_ERR: str(e)})
        except (asyncio.IncompleteReadError, ConnectionError, ValueError):
            pass
        finally:
            self._drop_conn(conn)
            sender.cancel()

    async def _send_loop(self, conn: _Conn) -> None:
        try:
            while True:
                # dynalint: unbounded-ok — local outbound queue, fed in-process
                frame = await conn.queue.get()
                if frame is None:
                    break
                conn.writer.write(frame)
                await conn.writer.drain()
        except (ConnectionError, asyncio.CancelledError):
            pass

    def _drop_conn(self, conn: _Conn) -> None:
        conn.closed = True
        self._conns.pop(conn.conn_id, None)
        for sub_id in [s for s, sub in self._subs.items() if sub.conn is conn]:
            self._subs.pop(sub_id, None)
        # Connection death revokes its leases → fast failure detection.
        for lease in [l for l in self._leases.values() if l.conn_id == conn.conn_id]:
            self._revoke_lease(lease.lease_id)
        conn.writer.close()

    # -- op dispatch -------------------------------------------------------

    async def _dispatch(self, conn: _Conn, msg: dict) -> Any:
        op = msg[wire.ST_OP]
        handler = getattr(self, f"_op_{op}", None)
        if handler is None:
            raise ValueError(f"unknown op {op!r}")
        return await handler(conn, msg)

    # -- KV ----------------------------------------------------------------

    def _notify_kv(
        self, event: str, key: str, value: bytes, rev: int, reason: str = ""
    ) -> None:
        for sub in self._subs.values():
            if sub.kind == "watch" and key.startswith(sub.pattern):
                ev = {wire.EV_TYPE: event, wire.EV_KEY: key,
                      wire.EV_VALUE: value, wire.EV_REV: rev}
                if reason:
                    # Delete provenance: "lease" (expiry / conn-death
                    # revoke — a liveness *judgment* degraded-mode
                    # consumers may second-guess against the data plane)
                    # vs "del" (an explicit retraction, always honored).
                    ev[wire.EV_REASON] = reason
                sub.conn.push({wire.ST_PUSH_SUB: sub.sub_id, wire.ST_EVENT: ev})

    async def _op_kv_put(self, conn: _Conn, msg: dict) -> dict:
        key, value = msg[wire.ST_KEY], msg[wire.ST_VALUE]
        lease_id = msg.get(wire.ST_LEASE, 0)
        existing = self._kv.get(key)
        if msg.get(wire.ST_CREATE_ONLY) and existing is not None:
            raise ValueError(f"key exists: {key}")
        if lease_id:
            lease = self._leases.get(lease_id)
            if lease is None:
                raise ValueError(f"no such lease {lease_id}")
            lease.keys.add(key)
        self._rev += 1
        self._kv[key] = _KvEntry(
            value=value,
            lease_id=lease_id,
            create_rev=existing.create_rev if existing else self._rev,
            mod_rev=self._rev,
        )
        self._notify_kv(wire.EV_PUT, key, value, self._rev)
        return {wire.ST_REV: self._rev}

    async def _op_kv_get(self, conn: _Conn, msg: dict) -> dict | None:
        entry = self._kv.get(msg[wire.ST_KEY])
        if entry is None:
            return None
        return {wire.ST_VALUE: entry.value, wire.ST_REV: entry.mod_rev,
                wire.ST_LEASE: entry.lease_id}

    async def _op_kv_del(self, conn: _Conn, msg: dict) -> int:
        return self._delete_key(msg[wire.ST_KEY])

    def _delete_key(self, key: str, reason: str = wire.EV_R_DEL) -> int:
        entry = self._kv.pop(key, None)
        if entry is None:
            return 0
        if entry.lease_id and entry.lease_id in self._leases:
            self._leases[entry.lease_id].keys.discard(key)
        self._rev += 1
        self._notify_kv(wire.EV_DELETE, key, b"", self._rev, reason=reason)
        return 1

    async def _op_kv_get_prefix(self, conn: _Conn, msg: dict) -> list:
        prefix = msg[wire.ST_KEY]
        return [
            {wire.ST_KEY: k, wire.ST_VALUE: e.value, wire.ST_REV: e.mod_rev}
            for k, e in sorted(self._kv.items())
            if k.startswith(prefix)
        ]

    async def _op_kv_watch(self, conn: _Conn, msg: dict) -> dict:
        sub_id = self._new_id()
        self._subs[sub_id] = _Sub(sub_id, conn, "watch", msg[wire.ST_KEY])
        initial = []
        if msg.get(wire.ST_WITH_INITIAL, True):
            initial = [
                {wire.EV_TYPE: wire.EV_PUT, wire.EV_KEY: k,
                 wire.EV_VALUE: e.value, wire.EV_REV: e.mod_rev}
                for k, e in sorted(self._kv.items())
                if k.startswith(msg[wire.ST_KEY])
            ]
        return {wire.ST_SUB: sub_id, wire.ST_INITIAL: initial}

    async def _op_unsub(self, conn: _Conn, msg: dict) -> bool:
        return self._subs.pop(msg[wire.ST_SUB], None) is not None

    # -- leases ------------------------------------------------------------

    def _new_id(self) -> int:
        i = self._next_id
        self._next_id += 1
        return i

    async def _op_lease_grant(self, conn: _Conn, msg: dict) -> dict:
        ttl = float(msg.get(wire.ST_TTL, 10.0))
        conn_bound = bool(msg.get(wire.ST_CONN_BOUND, True))
        want = msg.get(wire.ST_WANT)
        if want:
            # Reconnect re-attach: adopt an existing lease (connection
            # blip) or recreate it under the same id (server restart) —
            # higher layers key worker identity on the lease id, so a
            # fresh id would orphan every registration that embeds it.
            lease_id = int(want)
            self._next_id = max(self._next_id, lease_id + 1)
            existing = self._leases.get(lease_id)
            if existing is not None:
                existing.conn_id = conn.conn_id if conn_bound else 0
                existing.deadline = time.monotonic() + existing.ttl_s
                return {wire.ST_LEASE: lease_id, wire.ST_TTL: existing.ttl_s}
        else:
            lease_id = self._new_id()
        self._leases[lease_id] = _Lease(
            lease_id=lease_id,
            ttl_s=ttl,
            deadline=time.monotonic() + ttl,
            conn_id=conn.conn_id if conn_bound else 0,
        )
        return {wire.ST_LEASE: lease_id, wire.ST_TTL: ttl}

    async def _op_lease_keepalive(self, conn: _Conn, msg: dict) -> dict:
        lease = self._leases.get(msg[wire.ST_LEASE])
        if lease is None:
            raise ValueError(f"no such lease {msg[wire.ST_LEASE]}")
        lease.deadline = time.monotonic() + lease.ttl_s
        return {wire.ST_TTL: lease.ttl_s}

    async def _op_lease_revoke(self, conn: _Conn, msg: dict) -> bool:
        return self._revoke_lease(msg[wire.ST_LEASE])

    def _revoke_lease(self, lease_id: int) -> bool:
        lease = self._leases.pop(lease_id, None)
        if lease is None:
            return False
        for key in list(lease.keys):
            self._delete_key(key, reason=wire.EV_R_LEASE)
        return True

    async def _sweep_loop(self) -> None:
        while True:
            await asyncio.sleep(SWEEP_INTERVAL_S)
            now = time.monotonic()
            for lease_id in [l.lease_id for l in self._leases.values() if l.deadline < now]:
                log.info("lease %d expired", lease_id)
                self._revoke_lease(lease_id)

    # -- pub/sub -----------------------------------------------------------

    async def _op_sub(self, conn: _Conn, msg: dict) -> dict:
        sub_id = self._new_id()
        self._subs[sub_id] = _Sub(sub_id, conn, "sub", msg[wire.ST_SUBJECT])
        return {wire.ST_SUB: sub_id}

    async def _op_pub(self, conn: _Conn, msg: dict) -> int:
        subject, payload = msg[wire.ST_SUBJECT], msg[wire.ST_PAYLOAD]
        n = 0
        for sub in self._subs.values():
            if sub.kind == "sub" and subject_matches(sub.pattern, subject):
                sub.conn.push({
                    wire.ST_PUSH_SUB: sub.sub_id,
                    wire.ST_EVENT: {wire.EV_SUBJECT: subject,
                                    wire.EV_PAYLOAD: payload},
                })
                n += 1
        return n

    # -- work queues -------------------------------------------------------

    async def _op_q_push(self, conn: _Conn, msg: dict) -> int:
        name, payload = msg[wire.ST_QUEUE], msg[wire.ST_PAYLOAD]
        waiters = self._queue_waiters[name]
        while waiters:
            fut = waiters.popleft()
            if not fut.done():
                fut.set_result(payload)
                return 0
        self._queues[name].append(payload)
        return len(self._queues[name])

    async def _op_q_pop(self, conn: _Conn, msg: dict) -> bytes | None:
        name = msg[wire.ST_QUEUE]
        timeout = msg.get(wire.ST_TIMEOUT, 0.0)
        queue = self._queues[name]
        if queue:
            return queue.popleft()
        if timeout <= 0:
            return None
        fut: asyncio.Future[bytes] = asyncio.get_running_loop().create_future()
        self._queue_waiters[name].append(fut)
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            return None

    async def _op_q_len(self, conn: _Conn, msg: dict) -> int:
        return len(self._queues[msg[wire.ST_QUEUE]])

    # -- object store ------------------------------------------------------

    async def _op_obj_put(self, conn: _Conn, msg: dict) -> bool:
        self._objects[msg[wire.ST_BUCKET]][msg[wire.ST_NAME]] = msg[wire.ST_PAYLOAD]
        return True

    async def _op_obj_get(self, conn: _Conn, msg: dict) -> bytes | None:
        return self._objects.get(msg[wire.ST_BUCKET], {}).get(msg[wire.ST_NAME])

    async def _op_obj_del(self, conn: _Conn, msg: dict) -> bool:
        return self._objects.get(msg[wire.ST_BUCKET], {}).pop(msg[wire.ST_NAME], None) is not None

    async def _op_obj_list(self, conn: _Conn, msg: dict) -> list[str]:
        return sorted(self._objects.get(msg[wire.ST_BUCKET], {}).keys())

    async def _op_ping(self, conn: _Conn, msg: dict) -> str:
        return "pong"


async def _amain(host: str, port: int) -> None:
    server = StoreServer(host, port)
    await server.start()
    print(f"dynamo-tpu store listening on {server.address}", flush=True)
    await asyncio.Event().wait()


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(description="dynamo-tpu control-plane store server")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=6650)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    asyncio.run(_amain(args.host, args.port))


if __name__ == "__main__":
    main()
