"""Background-task hygiene: spawn asyncio tasks without losing exceptions.

The event loop keeps only weak references to tasks, so a bare
``asyncio.create_task(coro())`` can be garbage-collected mid-flight, and
its exception surfaces (if ever) only as an "exception was never
retrieved" message at gc time. ``spawn_logged`` keeps a strong reference
until completion and logs unexpected failures — the pattern dynalint's
``fire-and-forget-task`` rule pushes call sites toward.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Coroutine

log = logging.getLogger("dynamo_tpu.runtime.tasks")

# Strong refs: a spawned task must not be collectable before it finishes.
_BACKGROUND: set[asyncio.Task] = set()


def spawn_logged(
    coro: Coroutine[Any, Any, Any],
    *,
    name: str | None = None,
    logger: logging.Logger | None = None,
) -> asyncio.Task:
    """``create_task`` + strong reference + failure logging.

    Cancellation is normal shutdown and stays silent; any other exception
    is logged with its traceback. Returns the task so callers that want a
    handle (to cancel on shutdown) can keep one — but unlike a bare
    ``create_task`` they don't have to.
    """
    task = asyncio.get_running_loop().create_task(coro, name=name)
    _BACKGROUND.add(task)
    lg = logger or log

    def _done(t: asyncio.Task) -> None:
        _BACKGROUND.discard(t)
        if t.cancelled():
            return
        exc = t.exception()
        if exc is not None:
            lg.error("background task %r failed", t.get_name(), exc_info=exc)

    task.add_done_callback(_done)
    return task
