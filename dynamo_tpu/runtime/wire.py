"""Per-plane wire frame-key schema registry.

Every msgpack frame the tree sends is a dict with short keys. Those keys
ARE the wire contract: a producer writing a key nobody parses, or a
consumer parsing a key nobody sends, is protocol drift that no unit test
of either side catches. This module hoists every frame key into a named
constant, grouped by *plane* (one protocol surface = one schema), and
``tools/dynacheck``'s ``wire-contract`` rule statically checks that every
registered key is produced somewhere AND consumed somewhere in the tree,
that registered plane files don't backslide to raw string literals at
send sites, and that no two planes sharing a parse context reuse a key
string with conflicting meaning.

Planes and their parse contexts:

- ``dataplane``  — request/response envelope on worker ingress TCP
  (``runtime/dataplane.py``).
- ``store``      — control-plane store RPC envelope; op params splice
  into the envelope (``{ST_ID: ..., ST_OP: ..., **params}``), so the
  envelope and every op's params are ONE flat schema
  (``runtime/store/client.py`` / ``server.py``).
- ``store.event``— the event body carried inside a store push frame
  (watch events and bus messages); parsed from the ``ST_EVENT`` value,
  a different context than the envelope, so e.g. ``"r"`` may mean
  "rpc result" in the envelope and "delete reason" here without
  ambiguity.
- ``instance``   — discovery instance records (``Instance.to_wire``).
- ``snapshot``   — fleet metrics snapshot records (``obs/snapshot.py``).
- ``kvstream``   — KV block streams: peer prefix pulls AND disagg
  transfers (``llm/kv_pool/peer_client.py``, ``backends/*/main.py``).
- ``kvimport``   — per-block import descriptors handed to
  ``EngineCore.import_blocks`` (host-side record, same codec).
- ``disagg.cursor`` — per-request chunk-cursor events the prefill
  worker publishes on the event plane as committed KV blocks land
  (``llm/disagg_pool/cursor.py``); the decode worker's streaming
  handoff consumes them to pull chunks while prefill is still running.

Keep this module stdlib-only and leaf-level: the checker imports
nothing from it (it parses the AST), but product code imports it from
every layer.
"""

from __future__ import annotations

# -- dataplane envelope -----------------------------------------------------

DP_TYPE = "t"           # frame discriminator (see DP_T_* values)
DP_ID = "i"             # request id, pairs responses with requests
DP_ROUTE = "m"          # method / endpoint route name
DP_HEADERS = "h"        # control header map (two-part frame: control part)
DP_PAYLOAD = "p"        # opaque payload bytes (two-part frame: payload part)
DP_ERR = "err"          # error text on an error frame

DP_T_REQ = "req"        # client -> worker: start a request
DP_T_STOP = "stop"      # client -> worker: cooperative cancel
DP_T_KILL = "kill"      # client -> worker: hard cancel (stalled stream)
DP_T_RSP = "rsp"        # worker -> client: one response item
DP_T_END = "end"        # worker -> client: stream finished cleanly
DP_T_ERR = "err"        # worker -> client: stream failed

# -- store RPC envelope (+ spliced op params) -------------------------------

ST_ID = "i"             # rpc id, pairs responses with requests
ST_OP = "op"            # rpc op name
ST_OK = "ok"            # response: success flag
ST_RESULT = "r"         # response: op result
ST_ERR = "err"          # response: error text
ST_PUSH_SUB = "s"       # push frame: subscription id (presence = push)
ST_EVENT = "ev"         # push frame: event body (store.event schema)
ST_KEY = "k"            # kv op param: key
ST_VALUE = "v"          # kv op param/result: value
ST_REV = "rev"          # kv result: revision
ST_LEASE = "lease"      # kv/lease param+result: lease id
ST_CREATE_ONLY = "create_only"    # kv_put param: fail if key exists
ST_WITH_INITIAL = "with_initial"  # kv_watch param: replay current state
ST_SUB = "sub"          # watch/bus result+param: subscription id
ST_INITIAL = "initial"  # kv_watch result: initial replay events
ST_TTL = "ttl"          # lease param+result: ttl seconds
ST_WANT = "want"        # lease_grant param: resurrect this lease id
ST_CONN_BOUND = "conn_bound"      # lease_grant param: die with the conn
ST_SUBJECT = "subject"  # bus param: subject
ST_PAYLOAD = "p"        # bus/queue/object param: payload bytes
ST_QUEUE = "q"          # work-queue param: queue name
ST_TIMEOUT = "timeout"  # q_pop param: blocking wait seconds
ST_BUCKET = "b"         # object-store param: bucket
ST_NAME = "name"        # object-store param: object name

# -- store event body (inside ST_EVENT) -------------------------------------

EV_TYPE = "t"           # event discriminator (EV_PUT / EV_DELETE)
EV_KEY = "k"            # kv watch event: key
EV_VALUE = "v"          # kv watch event: value
EV_REV = "rev"          # kv watch event: revision
EV_REASON = "r"         # kv delete event: reason (EV_R_LEASE / EV_R_DEL)
EV_SUBJECT = "subject"  # bus message: subject
EV_PAYLOAD = "p"        # bus message: payload bytes

EV_PUT = "put"          # key created or updated
EV_DELETE = "delete"    # key removed
EV_R_LEASE = "lease"    # delete reason: lease expiry
EV_R_DEL = "del"        # delete reason: explicit delete

# -- discovery instance records ---------------------------------------------

INST_NS = "ns"          # namespace
INST_COMPONENT = "comp" # component name
INST_ENDPOINT = "ep"    # endpoint name
INST_ID = "id"          # instance id (lease id)
INST_ADDR = "addr"      # dataplane host:port
INST_META = "meta"      # optional metadata map

# -- fleet metrics snapshot records -----------------------------------------

SNAP_WORKER = "w"       # worker id
SNAP_ROLE = "r"         # worker role
SNAP_COMPONENT = "c"    # component name
SNAP_SEQ = "s"          # publisher sequence number
SNAP_TIME = "t"         # publish wall time
SNAP_EPOCH = "e"        # publisher epoch (restarts bump it)
SNAP_FAMILIES = "f"     # metric families map
SNAP_TENANTS = "tn"     # per-tenant rollups
SNAP_PHASES = "ph"      # per-phase latency rollups
SNAP_REQUESTS = "rq"    # per-request terminal records
SNAP_RETIRED = "x"      # tombstone flag: publisher retiring

# -- KV block streams (peer prefix pull + disagg transfer) ------------------

KV_HASHES = "hashes"    # pull request: block hash chain wanted
KV_CHUNK_BLOCKS = "chunk_blocks"  # request: blocks per data frame
KV_REQUEST_ID = "request_id"      # transfer request: prefill request id
KV_VERSION = "version"  # stream wire version
KV_SHAPE = "shape"      # geometry frame: per-block page shape
KV_DTYPE = "dtype"      # geometry frame: page dtype
KV_BLOCKS = "blocks"    # transfer descriptor frame: block descriptors
KV_START = "start"      # data frame: index of first block in this chunk
KV_PAGES = "kv"         # data frame: raw page bytes, one per block
KV_DONE = "done"        # trailer frame: total blocks sent
KV_HELD = "held"        # mocker data frame: held prefix length
KV_ERROR = "error"      # error frame: abort reason
KV_WINDOW_START = "ws"  # windowed transfer request: first committed block
KV_WINDOW_COUNT = "wc"  # windowed transfer request: max blocks this window
KV_WINDOW_FINAL = "wf"  # windowed transfer request: release the hold after

# -- disagg chunk-cursor events (streaming handoff, bus subject) ------------

CUR_REQUEST_ID = "rid"  # cursor event: prefill request id
CUR_WORKER = "w"        # cursor event: prefill worker id holding the blocks
CUR_COMMITTED = "c"     # cursor event: committed KV blocks so far
CUR_DONE = "d"          # cursor event: prefill finished (cursor is final)

# -- KV import descriptors (EngineCore.import_blocks) -----------------------

IMP_HASH = "hash"       # block content hash
IMP_PARENT = "parent"   # parent block hash (prefix chain)
IMP_SHAPE = "shape"     # page shape the bytes were serialized with
IMP_DTYPE = "dtype"     # page dtype the bytes were serialized with
IMP_KV = "kv"           # raw page bytes
IMP_LAYOUT = "layout"   # producer page-layout record (kind, tp, kv_dtype)

# ---------------------------------------------------------------------------
# Registry: plane -> {constant name -> meaning}. The dynacheck
# wire-contract rule reads THIS table (statically) and resolves each
# constant name against the assignments above.
# ---------------------------------------------------------------------------

SCHEMAS: dict[str, dict[str, str]] = {
    "dataplane": {
        "DP_TYPE": "frame discriminator",
        "DP_ID": "request id",
        "DP_ROUTE": "endpoint route",
        "DP_HEADERS": "control header map",
        "DP_PAYLOAD": "payload bytes",
        "DP_ERR": "error text",
    },
    "store": {
        "ST_ID": "rpc id",
        "ST_OP": "rpc op name",
        "ST_OK": "success flag",
        "ST_RESULT": "op result",
        "ST_ERR": "error text",
        "ST_PUSH_SUB": "push subscription id",
        "ST_EVENT": "push event body",
        "ST_KEY": "kv key",
        "ST_VALUE": "kv value",
        "ST_REV": "kv revision",
        "ST_LEASE": "lease id",
        "ST_CREATE_ONLY": "fail if key exists",
        "ST_WITH_INITIAL": "replay current state",
        "ST_SUB": "subscription id",
        "ST_INITIAL": "initial replay events",
        "ST_TTL": "lease ttl seconds",
        "ST_WANT": "resurrect lease id",
        "ST_CONN_BOUND": "lease dies with conn",
        "ST_SUBJECT": "bus subject",
        "ST_PAYLOAD": "payload bytes",
        "ST_QUEUE": "work queue name",
        "ST_TIMEOUT": "pop wait seconds",
        "ST_BUCKET": "object bucket",
        "ST_NAME": "object name",
    },
    "store.event": {
        "EV_TYPE": "event discriminator",
        "EV_KEY": "kv key",
        "EV_VALUE": "kv value",
        "EV_REV": "kv revision",
        "EV_REASON": "delete reason",
        "EV_SUBJECT": "bus subject",
        "EV_PAYLOAD": "payload bytes",
    },
    "instance": {
        "INST_NS": "namespace",
        "INST_COMPONENT": "component name",
        "INST_ENDPOINT": "endpoint name",
        "INST_ID": "instance id",
        "INST_ADDR": "dataplane address",
        "INST_META": "metadata map",
    },
    "snapshot": {
        "SNAP_WORKER": "worker id",
        "SNAP_ROLE": "worker role",
        "SNAP_COMPONENT": "component name",
        "SNAP_SEQ": "sequence number",
        "SNAP_TIME": "publish wall time",
        "SNAP_EPOCH": "publisher epoch",
        "SNAP_FAMILIES": "metric families",
        "SNAP_TENANTS": "tenant rollups",
        "SNAP_PHASES": "phase rollups",
        "SNAP_REQUESTS": "request records",
        "SNAP_RETIRED": "retiring tombstone",
    },
    "kvstream": {
        "KV_HASHES": "block hash chain wanted",
        "KV_CHUNK_BLOCKS": "blocks per data frame",
        "KV_REQUEST_ID": "prefill request id",
        "KV_VERSION": "stream wire version",
        "KV_SHAPE": "page shape",
        "KV_DTYPE": "page dtype",
        "KV_BLOCKS": "block descriptors",
        "KV_START": "first block index",
        "KV_PAGES": "raw page bytes",
        "KV_DONE": "total blocks sent",
        "KV_HELD": "held prefix length",
        "KV_ERROR": "abort reason",
        "KV_WINDOW_START": "window first block index",
        "KV_WINDOW_COUNT": "window max blocks",
        "KV_WINDOW_FINAL": "release hold after window",
    },
    "disagg.cursor": {
        "CUR_REQUEST_ID": "prefill request id",
        "CUR_WORKER": "prefill worker id",
        "CUR_COMMITTED": "committed blocks so far",
        "CUR_DONE": "prefill finished",
    },
    "kvimport": {
        "IMP_HASH": "block content hash",
        "IMP_PARENT": "parent block hash",
        "IMP_SHAPE": "page shape",
        "IMP_DTYPE": "page dtype",
        "IMP_KV": "raw page bytes",
        "IMP_LAYOUT": "producer page-layout record",
    },
}

# Parse context per plane: two planes may reuse one key string with
# DIFFERENT meanings only if their contexts differ (a reader always
# knows which context it is parsing). Same context + same key string +
# different meaning = ambiguity = a wire-contract finding.
CONTEXTS: dict[str, str] = {
    "dataplane": "dataplane-envelope",
    "store": "store-envelope",
    "store.event": "store-event-body",
    "instance": "instance-record",
    "snapshot": "snapshot-record",
    "kvstream": "kv-stream-frame",
    "kvimport": "kv-import-record",
    "disagg.cursor": "disagg-cursor-event",
}

# Discriminator VALUES (not keys): registered so the module self-check
# below accounts for every wire constant defined above.
VALUES: dict[str, str] = {
    "DP_T_REQ": "start a request",
    "DP_T_STOP": "cooperative cancel",
    "DP_T_KILL": "hard cancel",
    "DP_T_RSP": "one response item",
    "DP_T_END": "clean end of stream",
    "DP_T_ERR": "stream failed",
    "EV_PUT": "key created/updated",
    "EV_DELETE": "key removed",
    "EV_R_LEASE": "lease expiry",
    "EV_R_DEL": "explicit delete",
}


def _self_check() -> None:
    """Registry consistency, enforced at import: every schema constant
    exists, and every module-level wire constant is registered."""
    g = globals()
    for plane, schema in SCHEMAS.items():
        if plane not in CONTEXTS:
            raise AssertionError(f"plane {plane!r} has no parse context")
        for const in schema:
            if not isinstance(g.get(const), str):
                raise AssertionError(
                    f"SCHEMAS[{plane!r}] names {const}, which is not a "
                    "str constant in dynamo_tpu.runtime.wire"
                )
    registered = {c for s in SCHEMAS.values() for c in s} | set(VALUES)
    for name, value in g.items():
        if name.isupper() and isinstance(value, str) and name not in registered:
            raise AssertionError(
                f"wire constant {name} is not registered in SCHEMAS or VALUES"
            )


_self_check()
