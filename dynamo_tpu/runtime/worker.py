"""Worker process entrypoint helpers.

``@dynamo_worker`` turns an ``async def main(runtime, ...)`` into a process
entry: builds the DistributedRuntime from env/config, installs SIGINT/SIGTERM
→ graceful shutdown, runs the coroutine, and tears the runtime down.

Capability parity: reference `lib/runtime/src/worker.rs` (`Worker::execute`)
and the Python `@dynamo_worker` decorator
(`lib/bindings/python/src/dynamo/runtime`).
"""

from __future__ import annotations

import asyncio
import functools
import logging
import signal
from typing import Any, Awaitable, Callable

from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.config import RuntimeConfig
from dynamo_tpu.runtime.logging_setup import setup_logging

log = logging.getLogger("dynamo_tpu.worker")


def dynamo_worker(
    config: RuntimeConfig | None = None,
) -> Callable[[Callable[..., Awaitable[Any]]], Callable[..., Any]]:
    def decorator(fn: Callable[..., Awaitable[Any]]) -> Callable[..., Any]:
        @functools.wraps(fn)
        def entry(*args: Any, **kwargs: Any) -> Any:
            cfg = config or RuntimeConfig.from_env()
            setup_logging(cfg.log_level, cfg.logging_jsonl)
            return asyncio.run(_run(fn, cfg, *args, **kwargs))

        return entry

    return decorator


async def _run(fn: Callable[..., Awaitable[Any]], cfg: RuntimeConfig, *args, **kwargs) -> Any:
    from dynamo_tpu import tracing
    from dynamo_tpu.runtime import chaos

    # Config-file overlays can differ from the env the tracing module
    # read at import — re-apply the resolved values.
    tracing.configure(
        enabled=cfg.trace_enabled, sample=cfg.trace_sample, buffer=cfg.trace_buffer
    )
    # Fault injection (DYN_CHAOS_PLAN): armed before any connection
    # exists so even the first store dial is under the plan.
    chaos.install_from_env()
    runtime = await DistributedRuntime.create(
        cfg.store_address, lease_ttl=cfg.lease_ttl_s, ingress_host=cfg.ingress_host
    )
    if cfg.system_enabled:
        from dynamo_tpu.runtime.status_server import SystemStatusServer, bind_egress_gauges

        runtime.status = SystemStatusServer(port=cfg.system_port)
        await runtime.status.start()
        bind_egress_gauges(runtime.status, runtime.egress)
    loop = asyncio.get_running_loop()
    # SIGINT: immediate shutdown. SIGTERM: graceful drain — deregister
    # from discovery, stop admitting, finish (or migrate) in-flight
    # streams within the drain budget, release the lease, then exit.
    try:
        loop.add_signal_handler(signal.SIGINT, runtime.signal_shutdown)
        loop.add_signal_handler(
            signal.SIGTERM, runtime.request_drain, cfg.drain_timeout_s
        )
    except NotImplementedError:  # non-main thread
        pass
    try:
        return await fn(runtime, *args, **kwargs)
    finally:
        if runtime.status is not None:
            await runtime.status.stop()
        await runtime.shutdown()
