"""Worker load monitor: mark workers busy above a KV-usage threshold.

Capability parity: reference `lib/runtime/src/utils/worker_monitor.rs:50-89`
— the frontend watches per-worker ForwardPassMetrics and routes around
workers whose KV usage exceeds ``busy_threshold`` (busy-aware routing).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Callable

from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics, load_metrics_subject

log = logging.getLogger("dynamo_tpu.worker_monitor")


class WorkerMonitor:
    def __init__(
        self,
        store,
        namespace: str,
        component: str,
        busy_threshold: float = 0.95,
        on_busy_change: Callable[[int, bool], None] | None = None,
    ):
        self.store = store
        self.subject = load_metrics_subject(namespace, component)
        self.busy_threshold = busy_threshold
        self.on_busy_change = on_busy_change or (lambda w, b: None)
        self.metrics: dict[int, ForwardPassMetrics] = {}
        self.busy: set[int] = set()
        self._task: asyncio.Task | None = None
        self._sub = None

    async def start(self) -> None:
        self._sub = await self.store.subscribe(self.subject)
        self._task = asyncio.create_task(self._loop())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
        if self._sub:
            await self._sub.unsubscribe()

    async def _loop(self) -> None:
        assert self._sub is not None
        async for msg in self._sub:
            try:
                fpm = ForwardPassMetrics.from_wire(msg["p"])
            except Exception:  # noqa: BLE001
                continue
            worker_id = fpm.worker_id
            self.metrics[worker_id] = fpm
            usage = fpm.kv.gpu_cache_usage_perc
            was_busy = worker_id in self.busy
            now_busy = usage >= self.busy_threshold
            if now_busy != was_busy:
                (self.busy.add if now_busy else self.busy.discard)(worker_id)
                log.info("worker %d busy=%s (kv %.0f%%)", worker_id, now_busy, usage * 100)
                self.on_busy_change(worker_id, now_busy)

    def eligible(self, workers: list[int]) -> list[int]:
        """Filter busy workers out (all-busy falls back to the full set)."""
        free = [w for w in workers if w not in self.busy]
        return free or workers

    def remove_worker(self, worker_id: int) -> None:
        self.metrics.pop(worker_id, None)
        self.busy.discard(worker_id)
