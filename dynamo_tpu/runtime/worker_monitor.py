"""Worker load monitor: mark workers busy above a KV-usage threshold.

Capability parity: reference `lib/runtime/src/utils/worker_monitor.rs:50-89`
— the frontend watches per-worker ForwardPassMetrics and routes around
workers whose KV usage exceeds ``busy_threshold`` (busy-aware routing).

Built on :class:`~dynamo_tpu.llm.kv_router.publisher.MetricsAggregator`
(the one subscription to the load-metrics subject): the aggregator owns
the latest-metrics view and ProcessedEndpoints snapshots; this monitor is
the incremental busy-set policy on top of it. One subject subscription,
one busy implementation.
"""

from __future__ import annotations

import logging
from typing import Callable

from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics
from dynamo_tpu.llm.kv_router.publisher import MetricsAggregator

log = logging.getLogger("dynamo_tpu.worker_monitor")


class WorkerMonitor:
    def __init__(
        self,
        store,
        namespace: str,
        component: str,
        busy_threshold: float = 0.95,
        on_busy_change: Callable[[int, bool], None] | None = None,
        aggregator: MetricsAggregator | None = None,
    ):
        self.aggregator = aggregator or MetricsAggregator(store, namespace, component)
        self.busy_threshold = busy_threshold
        self.on_busy_change = on_busy_change or (lambda w, b: None)
        self.busy: set[int] = set()
        self.aggregator.on_update.append(self._on_metrics)

    @property
    def metrics(self) -> dict[int, ForwardPassMetrics]:
        return self.aggregator.latest

    async def start(self) -> None:
        await self.aggregator.start()

    async def stop(self) -> None:
        await self.aggregator.stop()

    def _on_metrics(self, fpm: ForwardPassMetrics) -> None:
        worker_id = fpm.worker_id
        usage = fpm.kv.gpu_cache_usage_perc
        was_busy = worker_id in self.busy
        now_busy = usage >= self.busy_threshold
        if now_busy != was_busy:
            (self.busy.add if now_busy else self.busy.discard)(worker_id)
            log.info("worker %d busy=%s (kv %.0f%%)", worker_id, now_busy, usage * 100)
            self.on_busy_change(worker_id, now_busy)

    def eligible(self, workers: list[int]) -> list[int]:
        """Filter busy workers out (all-busy falls back to the full set —
        shedding beats rejecting)."""
        free = [w for w in workers if w not in self.busy]
        return free or workers

    def remove_worker(self, worker_id: int) -> None:
        self.aggregator.remove_worker(worker_id)
        self.busy.discard(worker_id)
