"""Worker load monitor: mark workers busy above a KV-usage threshold.

Capability parity: reference `lib/runtime/src/utils/worker_monitor.rs:50-89`
— the frontend watches per-worker ForwardPassMetrics and routes around
workers whose KV usage exceeds ``busy_threshold`` (busy-aware routing).

Built on :class:`~dynamo_tpu.llm.kv_router.publisher.MetricsAggregator`
(the one subscription to the load-metrics subject): the aggregator owns
the latest-metrics view and ProcessedEndpoints snapshots; this monitor is
the incremental busy-set policy on top of it. One subject subscription,
one busy implementation.
"""

from __future__ import annotations

import logging
from typing import Callable

from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics
from dynamo_tpu.llm.kv_router.publisher import MetricsAggregator

log = logging.getLogger("dynamo_tpu.worker_monitor")


class WorkerMonitor:
    # Class-level default: tests (and older callers) build partial
    # monitors via __new__ without running __init__.
    queue_threshold: int | None = None

    def __init__(
        self,
        store,
        namespace: str,
        component: str,
        busy_threshold: float = 0.95,
        queue_threshold: int | None = None,
        on_busy_change: Callable[[int, bool], None] | None = None,
        aggregator: MetricsAggregator | None = None,
    ):
        self.aggregator = aggregator or MetricsAggregator(store, namespace, component)
        self.busy_threshold = busy_threshold
        # Saturation-aware routing (ISSUE 10): a worker is also busy when
        # its scheduler queue is saturated — at `queue_threshold` queued
        # requests, or (None = auto) at the bounded-queue limit the
        # worker itself exports in WorkerStats.queue_limit. Routing to a
        # worker that is about to shed just burns a dial + a migration.
        self.queue_threshold = queue_threshold
        self.on_busy_change = on_busy_change or (lambda w, b: None)
        self.busy: set[int] = set()
        self.aggregator.on_update.append(self._on_metrics)

    @property
    def metrics(self) -> dict[int, ForwardPassMetrics]:
        return self.aggregator.latest

    @property
    def degraded(self) -> bool:
        """True while the control plane is dark (ISSUE 15): the busy set
        and metrics view freeze at last-known-good — silence on the
        metrics subject is an outage symptom, not a fleet-wide idle."""
        return self.aggregator.degraded

    async def start(self) -> None:
        await self.aggregator.start()

    async def stop(self) -> None:
        await self.aggregator.stop()

    def _saturated(self, fpm: ForwardPassMetrics) -> bool:
        w = fpm.worker
        limit = self.queue_threshold
        if limit is None:
            limit = w.queue_limit or 0
        return bool(limit) and w.num_requests_waiting >= limit

    def _on_metrics(self, fpm: ForwardPassMetrics) -> None:
        worker_id = fpm.worker_id
        usage = fpm.kv.gpu_cache_usage_perc
        was_busy = worker_id in self.busy
        now_busy = usage >= self.busy_threshold or self._saturated(fpm)
        if now_busy != was_busy:
            (self.busy.add if now_busy else self.busy.discard)(worker_id)
            log.info(
                "worker %d busy=%s (kv %.0f%%, queued %d)",
                worker_id, now_busy, usage * 100,
                fpm.worker.num_requests_waiting,
            )
            self.on_busy_change(worker_id, now_busy)

    def eligible(self, workers: list[int]) -> list[int]:
        """Filter busy workers out (all-busy falls back to the full set —
        shedding beats rejecting)."""
        free = [w for w in workers if w not in self.busy]
        return free or workers

    def remove_worker(self, worker_id: int) -> None:
        self.aggregator.remove_worker(worker_id)
        self.busy.discard(worker_id)
