"""dynamo_tpu.spec — speculative decoding (draft-and-verify).

A speculating sequence drafts up to ``k`` continuation tokens with a
model-free drafter, then the engine verifies pending + draft as ONE
``q_len=k+1`` row of the same ragged program that serves prefill chunks
and decode rows (engine/core.py `_dispatch_ragged`) — amortizing one
device dispatch over several emitted tokens. Verification samples the
target model's own per-lane (seed, counter)-keyed choice at every drafted
position, so accepted output is **bit-identical** to non-speculative
decoding for greedy AND seeded temperature lanes; the drafter only
decides how many of those choices land per dispatch.

The reference wraps engines that own their own spec-decode
(vLLM `--speculative-config`); here the subsystem is first-party and
TPU-shaped: the verify row is just another ragged chunk, so XLA replays
the existing compiled programs at a wider sample gather.
"""

from dynamo_tpu.spec.config import SpecConfig, resolve_spec_config
from dynamo_tpu.spec.ngram import propose_ngram
from dynamo_tpu.spec.stats import SpecStats

__all__ = [
    "SpecConfig",
    "SpecStats",
    "propose_ngram",
    "resolve_spec_config",
]
