"""Speculative-decoding configuration: engine defaults + per-request
overrides.

The engine ships a default policy in :class:`EngineConfig`
(``spec_decode`` / ``spec_k`` / ``spec_ngram_*``); a request may override
it through the OpenAI ``dyn.spec_decode`` extension, which rides
:class:`PreprocessedRequest.spec_decode` over the data plane (the field
the router used to drop — see ISSUE 4 satellite). Resolution happens
once, at admission, into an immutable :class:`SpecConfig` on the
sequence.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

#: Draft methods the engine implements. "off" is only valid as a request
#: override (it disables an engine-level default for that request).
SPEC_METHODS = ("ngram",)


@dataclass(frozen=True)
class SpecConfig:
    """Resolved per-sequence speculation policy.

    ``k`` is the draft length per verify step — the verify row is
    ``k+1`` query tokens. ``ngram_min``/``ngram_max`` bound the suffix
    lengths the prompt-lookup drafter tries (longest first);
    ``window`` bounds how far back it searches (host CPU cost per draft
    is O(window * ngram_max)).
    """

    method: str = "ngram"
    k: int = 4
    ngram_min: int = 1
    ngram_max: int = 3
    window: int = 1024
    #: Draft ON DEVICE between megastep inner iterations (ISSUE 18): the
    #: lane carries a packed history ring through the scanned body and
    #: redrafts after every accept/reject, so multiple draft rounds ride
    #: one dispatch. Requires the engine flag — a request can only turn
    #: it off (the ring buffers are sized at engine construction).
    device: bool = False

    def __post_init__(self) -> None:
        if self.method not in SPEC_METHODS:
            raise ValueError(
                f"unknown spec-decode method {self.method!r} "
                f"(expected one of {SPEC_METHODS})"
            )
        if self.k < 1:
            raise ValueError(f"spec k must be >= 1, got {self.k}")
        if not (1 <= self.ngram_min <= self.ngram_max):
            raise ValueError(
                f"need 1 <= ngram_min <= ngram_max, got "
                f"[{self.ngram_min}, {self.ngram_max}]"
            )


def resolve_spec_config(
    default: SpecConfig | None,
    request: dict[str, Any] | None,
    k_cap: int,
) -> SpecConfig | None:
    """Merge the engine default with a request's ``spec_decode`` dict.

    Returns None when speculation is off for this sequence. The
    per-request ``k`` is clamped to ``k_cap`` (the engine's configured
    ``spec_k``): the verify program's sample-gather width is static, so a
    request cannot widen it. Unknown methods raise — admission is the
    right place to reject, not the first verify step.
    """
    if request is None:
        return default
    method = request.get("method", default.method if default else "ngram")
    if method in ("off", None):
        return None
    base = default or SpecConfig(method=method, k=k_cap)
    # Every knob clamps to the engine baseline, not just k: the drafter
    # scan is host CPU on the decode path, so an unclamped per-request
    # ngram_max/window would let one client request inject O(window x
    # ngram_max) work into every engine step for every co-scheduled lane.
    return SpecConfig(
        method=method,
        k=max(1, min(int(request.get("k", base.k)), k_cap)),
        ngram_min=max(1, int(request.get("ngram_min", base.ngram_min))),
        ngram_max=min(int(request.get("ngram_max", base.ngram_max)), base.ngram_max),
        window=min(int(request.get("window", base.window)), base.window),
        # Device drafting clamps like every other knob: the engine sized
        # its ring buffers (window + ngram_max) at construction, so a
        # request may opt out but never opt in past the engine baseline.
        device=bool(request.get("device", base.device)) and base.device,
    )
