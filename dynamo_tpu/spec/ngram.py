"""Model-free n-gram / prompt-lookup drafter.

Proposes up to ``k`` continuation tokens by matching the sequence's
recent suffix against its OWN prompt+output history: if the last ``n``
tokens appeared earlier in the context, the tokens that followed that
occurrence are likely to follow again (the prompt-lookup decoding trick —
strongest on extraction/summarization/code-edit workloads, where the
output quotes its input). Deterministic, zero device work, CPU-testable.

The drafter never affects output content — verification accepts only
tokens the target model would have chosen anyway (engine/core.py) — so a
bad draft costs wasted verify rows, never wrong tokens.
"""

from __future__ import annotations


def propose_ngram(
    context: list[int],
    k: int,
    ngram_min: int = 1,
    ngram_max: int = 3,
    window: int = 1024,
) -> list[int]:
    """Draft up to ``k`` tokens continuing ``context``.

    Tries suffix lengths ``ngram_max`` down to ``ngram_min``; for each,
    scans the last ``window`` tokens right-to-left for the most recent
    earlier occurrence of that suffix and proposes the tokens that
    followed it. Returns [] when nothing matches (the caller falls back
    to a plain 1-token decode row).
    """
    L = len(context)
    if L < 2 or k <= 0:
        return []
    lo = max(0, L - window)
    for n in range(min(ngram_max, L - 1), ngram_min - 1, -1):
        suffix = context[L - n:]
        first = suffix[0]
        # Most recent earlier occurrence wins: recent history predicts
        # the immediate continuation better than the distant prompt.
        # The first-token guard keeps the no-match worst case (the
        # incompressible-output workload) at one int compare per
        # position instead of one list-slice allocation per position —
        # this scan runs on the host per speculating lane per step, so
        # its constant factor is decode-path cost.
        for start in range(L - n - 1, lo - 1, -1):
            if context[start] != first:
                continue
            if n == 1 or context[start : start + n] == suffix:
                follow = context[start + n : start + n + k]
                if follow:
                    return follow
                break  # suffix only recurs at the very end: shorter n
    return []
