"""Speculation accounting shared by the real engine and the mocker.

One instance per engine; every verify step feeds it and the derived
gauges export on ``/metrics`` (status_server.SPEC_GAUGES) and publish in
``ForwardPassMetrics.spec_decode`` — the wire field that predates this
subsystem (llm/kv_router/protocols.py).
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class SpecStats:
    verify_steps: int = 0      # dispatches that carried >= 1 verify row
    verify_rows: int = 0       # speculating rows across those dispatches
    drafted_tokens: int = 0    # draft tokens proposed (and verified)
    accepted_tokens: int = 0   # draft tokens the target agreed with
    emitted_tokens: int = 0    # tokens emitted by verify rows (accept + 1)
    device_rounds: int = 0     # on-device draft rounds ridden inside dispatches
    device_hits: int = 0       # device rounds whose ring match proposed >= 1 token

    @property
    def wasted_tokens(self) -> int:
        """Draft tokens computed by the verify program and thrown away
        (the speculation-loss side of the A/B)."""
        return self.drafted_tokens - self.accepted_tokens

    @property
    def acceptance_rate(self) -> float:
        return (
            self.accepted_tokens / self.drafted_tokens
            if self.drafted_tokens
            else 0.0
        )

    @property
    def mean_accepted_len(self) -> float:
        """Mean tokens emitted per speculating row per step (>= 1.0; the
        dispatch-amortization factor speculation buys)."""
        return self.emitted_tokens / self.verify_rows if self.verify_rows else 0.0

    @property
    def dispatches_per_accepted_token(self) -> float:
        """Device dispatches per accepted draft token — the amortization
        gauge on-device drafting moves (lower is better; 0 when no draft
        token has been accepted yet)."""
        return (
            self.verify_steps / self.accepted_tokens
            if self.accepted_tokens
            else 0.0
        )

    def observe_row(self, drafted: int, accepted: int) -> None:
        """Account one verify row: ``drafted`` proposed, ``accepted``
        matched; the row emitted ``accepted + 1`` tokens (the bonus /
        correction token is free)."""
        self.verify_rows += 1
        self.drafted_tokens += drafted
        self.accepted_tokens += accepted
        self.emitted_tokens += accepted + 1

    def as_dict(self) -> dict:
        return {
            "verify_steps": self.verify_steps,
            "verify_rows": self.verify_rows,
            "drafted_tokens": self.drafted_tokens,
            "accepted_tokens": self.accepted_tokens,
            "wasted_tokens": self.wasted_tokens,
            "emitted_tokens": self.emitted_tokens,
            "acceptance_rate": self.acceptance_rate,
            "mean_accepted_len": self.mean_accepted_len,
            "device_rounds": self.device_rounds,
            "device_hits": self.device_hits,
            "dispatches_per_accepted_token": self.dispatches_per_accepted_token,
        }
