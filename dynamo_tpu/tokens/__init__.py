from dynamo_tpu.tokens.blocks import (
    BLOCK_HASH_SEED,
    PartialTokenBlock,
    TokenBlock,
    TokenBlockSequence,
    compute_block_hash,
    compute_seq_hashes,
    tokens_to_blocks,
)

__all__ = [
    "BLOCK_HASH_SEED",
    "PartialTokenBlock",
    "TokenBlock",
    "TokenBlockSequence",
    "compute_block_hash",
    "compute_seq_hashes",
    "tokens_to_blocks",
]
