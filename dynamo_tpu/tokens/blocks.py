"""Block-aligned token sequences with chained content hashes.

The single hashing scheme shared by the KV router's radix indexer, the KV
block manager's registry, the mocker engine, and the JAX engine's prefix
cache. A sequence of tokens is chunked into fixed-size blocks; each complete
block gets a 64-bit hash chained through its parent:

    seq_hash[0] = xxh3_64(le_bytes(tokens[0:B]),      seed=SALT)
    seq_hash[i] = xxh3_64(le_bytes(tokens[iB:(i+1)B]), seed=seq_hash[i-1])

Two sequences share a prefix of k blocks iff their first k seq hashes agree,
so a radix tree over hashes *is* a prefix tree over token content.

Capability parity: reference `lib/llm/src/tokens.rs:56,196,400,491` (Tokens /
PartialTokenBlock / TokenBlock / TokenBlockSequence, chained xxh3 with salt).
Re-designed: we hash little-endian u32 token bytes with xxhash's xxh3_64 and
use the parent hash directly as the seed rather than splicing it into the
payload — same chaining semantics, one fewer copy.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Iterable, Sequence

import xxhash

# Salt seeding the root of every hash chain. Changing it invalidates every
# cached block everywhere, so it is part of the on-the-wire contract.
BLOCK_HASH_SEED: int = 0x6AE2_D7C3_11F0_51B7

_U32 = struct.Struct("<I")


def _tokens_bytes(tokens: Sequence[int]) -> bytes:
    return b"".join(_U32.pack(t & 0xFFFFFFFF) for t in tokens)


def compute_block_hash(tokens: Sequence[int], parent_hash: int | None = None) -> int:
    """Chained 64-bit hash of one block of tokens.

    ``parent_hash=None`` marks the first block of a sequence (seeded by
    BLOCK_HASH_SEED); otherwise the parent block's hash seeds the chain.
    """
    seed = BLOCK_HASH_SEED if parent_hash is None else parent_hash
    return xxhash.xxh3_64_intdigest(_tokens_bytes(tokens), seed=seed)


def compute_seq_hashes(tokens: Sequence[int], block_size: int) -> list[int]:
    """Hashes of every *complete* block of ``tokens`` (trailing partial block
    excluded), chained left to right."""
    hashes: list[int] = []
    parent: int | None = None
    for start in range(0, len(tokens) - len(tokens) % block_size, block_size):
        parent = compute_block_hash(tokens[start : start + block_size], parent)
        hashes.append(parent)
    return hashes


@dataclass(frozen=True)
class TokenBlock:
    """An immutable, complete, hash-addressed block of tokens."""

    tokens: tuple[int, ...]
    block_hash: int
    parent_hash: int | None
    position: int  # block index within its sequence

    @property
    def block_size(self) -> int:
        return len(self.tokens)


@dataclass
class PartialTokenBlock:
    """The mutable tail of a sequence: fewer than ``block_size`` tokens."""

    block_size: int
    parent_hash: int | None = None
    position: int = 0
    tokens: list[int] = field(default_factory=list)

    @property
    def remaining(self) -> int:
        return self.block_size - len(self.tokens)

    def push(self, token: int) -> TokenBlock | None:
        """Append one token; returns the completed TokenBlock when full."""
        self.tokens.append(token)
        if len(self.tokens) < self.block_size:
            return None
        block = TokenBlock(
            tokens=tuple(self.tokens),
            block_hash=compute_block_hash(self.tokens, self.parent_hash),
            parent_hash=self.parent_hash,
            position=self.position,
        )
        self.parent_hash = block.block_hash
        self.position += 1
        self.tokens = []
        return block


class TokenBlockSequence:
    """A growing token sequence maintaining its complete blocks + hash chain.

    The incremental counterpart of :func:`compute_seq_hashes`: append tokens
    one at a time (decode) or in bulk (prefill) and read back the chained
    hashes of all complete blocks in O(1) per token.
    """

    def __init__(self, tokens: Iterable[int] = (), block_size: int = 32):
        if block_size <= 0:
            raise ValueError(f"block_size must be positive, got {block_size}")
        self.block_size = block_size
        self.blocks: list[TokenBlock] = []
        self._tail = PartialTokenBlock(block_size=block_size)
        self.extend(tokens)

    def __len__(self) -> int:
        return len(self.blocks) * self.block_size + len(self._tail.tokens)

    @property
    def total_tokens(self) -> int:
        return len(self)

    @property
    def partial_tokens(self) -> list[int]:
        return list(self._tail.tokens)

    @property
    def block_hashes(self) -> list[int]:
        return [b.block_hash for b in self.blocks]

    @property
    def last_hash(self) -> int | None:
        return self.blocks[-1].block_hash if self.blocks else None

    def append(self, token: int) -> TokenBlock | None:
        """Append one token; returns a TokenBlock if one was completed."""
        block = self._tail.push(token)
        if block is not None:
            self.blocks.append(block)
        return block

    def extend(self, tokens: Iterable[int]) -> list[TokenBlock]:
        """Append many tokens; returns the blocks completed along the way."""
        completed: list[TokenBlock] = []
        for t in tokens:
            block = self.append(t)
            if block is not None:
                completed.append(block)
        return completed

    def all_tokens(self) -> list[int]:
        out: list[int] = []
        for b in self.blocks:
            out.extend(b.tokens)
        out.extend(self._tail.tokens)
        return out

    def truncate(self, num_tokens: int) -> None:
        """Truncate to the first ``num_tokens`` tokens (migration replay)."""
        if num_tokens > len(self):
            raise ValueError(f"cannot truncate {len(self)} tokens to {num_tokens}")
        tokens = self.all_tokens()[:num_tokens]
        self.blocks = []
        self._tail = PartialTokenBlock(block_size=self.block_size)
        self.extend(tokens)


def tokens_to_blocks(
    tokens: Sequence[int], block_size: int
) -> tuple[list[TokenBlock], list[int]]:
    """One-shot chunking: (complete blocks, leftover partial tokens)."""
    seq = TokenBlockSequence(tokens, block_size)
    return seq.blocks, seq.partial_tokens
