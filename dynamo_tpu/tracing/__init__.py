"""dynamo_tpu.tracing — distributed request tracing with per-phase
latency attribution.

See :mod:`dynamo_tpu.tracing.core` for the model. Quick tour::

    from dynamo_tpu import tracing

    tracer = tracing.get_tracer("frontend")
    with tracer.span("http", headers=request.headers) as root:
        with tracer.span("tokenize", parent=root) as t:
            ids = tok.encode(prompt)
            t.set("tokens", len(ids))
        headers = tracing.inject_headers(root, {"x-request-id": rid})
        ...  # downstream processes parent to `root` via the header

    tracing.get_collector().traces(limit=10)   # what /traces serves
"""

from dynamo_tpu.tracing.core import (
    NOOP_SPAN,
    Span,
    SpanContext,
    TraceCollector,
    Tracer,
    configure,
    extract_context,
    get_collector,
    get_tracer,
    inject_headers,
    phase_order,
    trace_enabled,
)

__all__ = [
    "NOOP_SPAN",
    "Span",
    "SpanContext",
    "TraceCollector",
    "Tracer",
    "configure",
    "extract_context",
    "get_collector",
    "get_tracer",
    "inject_headers",
    "phase_order",
    "trace_enabled",
]
