"""Distributed request tracing: spans, tracers, and the ring-buffer collector.

Per-phase latency attribution for a single request across frontend →
router → prefill → decode (the decomposition "Understanding Bottlenecks
for Efficiently Serving LLM Inference With KV Offloading" and NetKV
attribute their wins to — PAPERS.md). Aggregate Prometheus histograms say
*that* TTFT regressed; a stitched trace says *where* the time went.

Design constraints (ISSUE 2):

- stdlib only — no OpenTelemetry dependency; spans are plain dataclasses.
- Hot-path safe: a finished span is one ``deque.append`` (atomic under the
  GIL — the "lock-free" per-process collector; engine threads and the
  event loop share it without a mutex). A *disabled* tracer returns a
  shared no-op span: one attribute check + one return, < 1 µs per call
  (pinned by the micro-bench in tests/test_tracing.py).
- Cross-process stitching rides the W3C ``traceparent`` header the
  dataplane already carries next to ``x-request-id`` (runtime/framing.py
  ``h`` map → runtime/dataplane.py → Context.headers), so spans recorded
  in different processes (disagg prefill fleet, migrated attempts) share
  one trace id and parent links.

Configuration (read from env at import, overridable via :func:`configure`;
mirrored in runtime/config.py RuntimeConfig):

- ``DYN_TRACE_ENABLED`` — "0"/"false" disables all recording (default on).
- ``DYN_TRACE_SAMPLE``  — root-span sampling rate in [0,1] (default 1.0).
  Sampling is deterministic on the trace id, so every process in a
  deployment keeps or drops the *same* traces without coordination.
- ``DYN_TRACE_BUFFER``  — ring-buffer capacity in spans (default 4096).
"""

from __future__ import annotations

import secrets
import threading
import time
import weakref
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Iterable

from dynamo_tpu import knobs
from dynamo_tpu.runtime.logging_setup import TRACEPARENT_HEADER, parse_traceparent

__all__ = [
    "Span",
    "SpanContext",
    "Tracer",
    "TraceCollector",
    "configure",
    "extract_context",
    "get_collector",
    "get_tracer",
    "inject_headers",
    "trace_enabled",
]


# ---------------------------------------------------------------------------
# Span model
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class SpanContext:
    """The cross-process identity of a span: what rides the wire."""

    trace_id: str  # 32 hex chars
    span_id: str   # 16 hex chars

    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"


@dataclass
class Span:
    """One timed phase of a request. Plain data + context-manager sugar.

    ``start_s``/``end_s`` are ``time.time()`` wall-clock seconds so spans
    from different processes on one host order correctly in a waterfall.
    """

    name: str
    service: str
    trace_id: str
    span_id: str
    parent_id: str | None = None
    start_s: float = 0.0
    end_s: float = 0.0
    attrs: dict[str, Any] = field(default_factory=dict)
    _collector: "TraceCollector | None" = None

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    @property
    def duration_s(self) -> float:
        return max(0.0, self.end_s - self.start_s)

    @property
    def recording(self) -> bool:
        return True

    def set(self, key: str, value: Any) -> "Span":
        self.attrs[key] = value
        return self

    def finish(self, end_s: float | None = None) -> None:
        if self._collector is None:
            return  # already finished (idempotent)
        self.end_s = end_s if end_s is not None else time.time()
        collector, self._collector = self._collector, None
        collector.add(self)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        self.finish()

    def to_dict(self) -> dict[str, Any]:
        return {
            "name": self.name,
            "service": self.service,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_s": self.start_s,
            "end_s": self.end_s,
            "duration_ms": round(self.duration_s * 1e3, 4),
            "attrs": self.attrs,
        }


class _NoopSpan:
    """Shared do-nothing span: the disabled/unsampled fast path."""

    __slots__ = ()

    recording = False
    trace_id = ""
    span_id = ""
    name = ""
    attrs: dict[str, Any] = {}

    @property
    def context(self) -> None:
        return None

    def set(self, key: str, value: Any) -> "_NoopSpan":
        return self

    def finish(self, end_s: float | None = None) -> None:
        return None

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        return None


NOOP_SPAN = _NoopSpan()


# ---------------------------------------------------------------------------
# Collector: the lock-free per-process ring buffer
# ---------------------------------------------------------------------------

# Phase-histogram bucket edges, tuned to the MEASURED phase ranges
# (ISSUE 13 satellite; the old edges were generic defaults): the fast end
# resolves sub-ms decode iterations and host_gap stats (50 µs floor), the
# middle covers queue/route/TTFT (10 ms – 1 s), and the slow end keeps
# resolution through multi-second chunked prefills and megastep drains up
# to 120 s — so a p99 estimated off /metrics interpolates inside a
# bucket instead of saturating the top one. Pinned by
# tests/test_obs.py::test_phase_buckets_cover_measured_ranges.
_PHASE_BUCKETS = (
    0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
    0.025, 0.05, 0.1, 0.25, 0.5, 0.75, 1.0, 1.5, 2.5, 4.0, 6.0,
    10.0, 15.0, 30.0, 60.0, 120.0,
)


class TraceCollector:
    """Fixed-size span sink; one per process.

    ``deque(maxlen=N).append`` is atomic, so engine threads (EngineCore
    step runs under ``asyncio.to_thread``) and event-loop code feed the
    same buffer without locking. Readers (``/traces``) take a snapshot via
    ``list(deque)`` — also atomic — so rendering never blocks recording.
    """

    def __init__(self, capacity: int = 4096):
        self._spans: deque[Span] = deque(maxlen=capacity)
        # High-frequency process-local stat spans (engine step timings)
        # live in their own, smaller ring so a busy decode loop can never
        # evict per-request spans out of the trace buffer.
        self._stats: deque[Span] = deque(maxlen=min(1024, capacity))
        # Cumulative per-phase (count, sum-seconds) totals — the metric
        # snapshots ship these over the event plane so the fleet
        # aggregator can diff per-window phase means without scraping.
        # Unlike the rings these survive eviction, so they are CUMULATIVE
        # counters like the prometheus histograms. The tiny lock guards
        # the two-field update against the engine-thread/event-loop race
        # (the ring appends stay lock-free).
        self._phase_lock = threading.Lock()
        self._phase_totals: dict[str, list[float]] = {}
        # Bound metrics registries: per-phase latency histograms
        # (planner/observer.py consumes these for the TTFT/ITL
        # decomposition). Held weakly — a restarted service's dead
        # registry unbinds itself instead of accumulating forever.
        self._metrics: list[weakref.ref] = []

    @property
    def capacity(self) -> int:
        return self._spans.maxlen or 0

    def __len__(self) -> int:
        return len(self._spans)

    def add(self, span: Span) -> None:
        self._spans.append(span)
        self._observe(span)

    def add_stat(self, span: Span) -> None:
        """File a stat span: histogram-observed like any other, but kept
        out of the request-trace ring and the ``/traces`` grouping."""
        self._stats.append(span)
        self._observe(span)

    def _observe(self, span: Span) -> None:
        key = f"{span.service}/{span.name}"
        with self._phase_lock:
            totals = self._phase_totals.get(key)
            if totals is None:
                totals = self._phase_totals[key] = [0.0, 0.0]
            totals[0] += 1.0
            totals[1] += span.duration_s
        dead = False
        for ref in self._metrics:
            registry = ref()
            if registry is None:
                dead = True
                continue
            registry.scoped(service=span.service, phase=span.name).histogram(
                "trace_phase_duration_seconds",
                doc="Per-phase request latency attributed by the tracer",
                buckets=_PHASE_BUCKETS,
            ).observe(span.duration_s)
        if dead:
            self._metrics[:] = [r for r in self._metrics if r() is not None]

    def bind_metrics(self, registry: Any) -> None:
        """Mirror every finished span into per-phase histograms
        (``dynamo_trace_phase_duration_seconds{service,phase}``) on the
        given :class:`~dynamo_tpu.runtime.metrics.MetricsRegistry`."""
        live = [r for r in self._metrics if r() is not None]
        if not any(r() is registry for r in live):
            live.append(weakref.ref(registry))
        self._metrics[:] = live

    def phase_totals(self) -> dict[str, tuple[float, float]]:
        """Cumulative ``{"service/phase": (count, sum_seconds)}`` since
        process start — the snapshot publisher's phase source."""
        with self._phase_lock:
            return {k: (v[0], v[1]) for k, v in self._phase_totals.items()}

    def clear(self) -> None:
        self._spans.clear()
        self._stats.clear()
        with self._phase_lock:
            self._phase_totals.clear()

    def spans(self) -> list[Span]:
        return list(self._spans)

    def stats(self) -> list[Span]:
        return list(self._stats)

    def trace(self, trace_id: str) -> list[Span]:
        # list() first: iterating the live deque races recording threads
        # (deques forbid mutation during iteration); the copy is atomic.
        return sorted(
            (s for s in list(self._spans) if s.trace_id == trace_id),
            key=lambda s: (s.start_s, s.end_s),
        )

    def traces(
        self, limit: int = 20, trace_id: str | None = None
    ) -> list[dict[str, Any]]:
        """The most recent ``limit`` traces (or the one ``trace_id``),
        each with spans in start order and a per-phase waterfall (offsets
        relative to the trace root) — the ``/traces`` endpoint payload."""
        if trace_id is not None:
            spans = self.trace(trace_id)
            return [self._payload(trace_id, spans)] if spans else []
        grouped: dict[str, list[Span]] = {}
        for span in list(self._spans):  # snapshot; oldest → newest
            grouped.setdefault(span.trace_id, []).append(span)
        out = [
            self._payload(tid, sorted(grouped[tid], key=lambda s: (s.start_s, s.end_s)))
            for tid in list(grouped)[-limit:]
        ]
        out.reverse()  # newest first
        return out

    @staticmethod
    def _payload(trace_id: str, spans: list[Span]) -> dict[str, Any]:
        t0 = spans[0].start_s
        return {
            "trace_id": trace_id,
            "start_s": t0,
            "duration_ms": round((max(s.end_s for s in spans) - t0) * 1e3, 4),
            "spans": [s.to_dict() for s in spans],
            "waterfall": [
                {
                    "phase": s.name,
                    "service": s.service,
                    "offset_ms": round((s.start_s - t0) * 1e3, 4),
                    "duration_ms": round(s.duration_s * 1e3, 4),
                }
                for s in spans
            ],
        }


# ---------------------------------------------------------------------------
# Tracer
# ---------------------------------------------------------------------------


def _sampled(trace_id: str, rate: float) -> bool:
    """Deterministic head sampling: the same trace id samples identically
    in every process, so distributed traces never arrive half-recorded."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return int(trace_id[:8], 16) / 0xFFFFFFFF < rate


class Tracer:
    """Factory for spans of one service ("frontend", "router", "engine"...).

    ``span(...)`` starts a live span (use as a context manager — the
    dynalint ``unclosed-span`` rule enforces this); ``record(...)`` files
    a phase from timestamps already taken, for retroactive attribution
    (e.g. the engine marks prefill-done inside its step loop and emits
    the span when the stream closes).
    """

    def __init__(self, service: str, collector: TraceCollector):
        self.service = service
        self.collector = collector

    # NOTE: parent can be a Span, a SpanContext, or None. headers (the
    # dataplane `h` map / aiohttp request headers) are consulted when no
    # explicit parent is given.
    def _resolve_parent(
        self, parent: Any, headers: Any
    ) -> SpanContext | None:
        if isinstance(parent, Span):
            return parent.context
        if isinstance(parent, SpanContext):
            return parent
        if parent is None:
            if headers is not None:
                return extract_context(headers)
            return None
        return None

    def span(
        self,
        name: str,
        parent: Any = None,
        headers: Any = None,
        attrs: dict[str, Any] | None = None,
    ):
        """Start a span. Returns the shared no-op span when tracing is
        disabled or the trace is head-sampled out."""
        if not _STATE.enabled:
            return NOOP_SPAN
        if parent is NOOP_SPAN:
            # The parent's trace was sampled out: propagate the drop
            # instead of minting an orphan trace for the child.
            return NOOP_SPAN
        ctx = self._resolve_parent(parent, headers)
        if ctx is None:
            trace_id = secrets.token_hex(16)
            if not _sampled(trace_id, _STATE.sample):
                return NOOP_SPAN
            parent_id = None
        else:
            if not _sampled(ctx.trace_id, _STATE.sample):
                return NOOP_SPAN
            trace_id, parent_id = ctx.trace_id, ctx.span_id
        return Span(
            name=name,
            service=self.service,
            trace_id=trace_id,
            span_id=secrets.token_hex(8),
            parent_id=parent_id,
            start_s=time.time(),
            attrs=dict(attrs) if attrs else {},
            _collector=self.collector,
        )

    def record(
        self,
        name: str,
        start_s: float,
        end_s: float,
        parent: Any = None,
        headers: Any = None,
        attrs: dict[str, Any] | None = None,
        stat: bool = False,
    ) -> None:
        """File an already-elapsed phase as a finished span. ``stat=True``
        routes it to the collector's stat ring (histograms only, excluded
        from ``/traces``) — for high-frequency per-step timings that would
        otherwise evict request spans."""
        span = self.span(name, parent=parent, headers=headers, attrs=attrs)
        if span.recording:
            span.start_s = start_s
            if stat:
                span.end_s = end_s
                span._collector = None
                self.collector.add_stat(span)
            else:
                span.finish(end_s)


# ---------------------------------------------------------------------------
# W3C trace-context propagation (rides the existing header path)
# ---------------------------------------------------------------------------


def extract_context(headers: Any) -> SpanContext | None:
    """Parse ``traceparent`` out of a headers mapping (dataplane ``h``
    dict or aiohttp CIMultiDict — both expose ``.get``)."""
    if headers is None:
        return None
    value = headers.get(TRACEPARENT_HEADER)
    if not value:
        return None
    parsed = parse_traceparent(value)
    if parsed is None:
        return None
    return SpanContext(trace_id=parsed[0], span_id=parsed[1])


def inject_headers(span: Any, headers: dict[str, str]) -> dict[str, str]:
    """Stamp ``headers`` with the span's traceparent so downstream
    processes parent to it. A no-op span leaves headers untouched (the
    caller's own child_traceparent fallback stays in effect)."""
    ctx = getattr(span, "context", None)
    if ctx is not None:
        headers[TRACEPARENT_HEADER] = ctx.traceparent()
    return headers


# ---------------------------------------------------------------------------
# Process-global wiring
# ---------------------------------------------------------------------------


class _State:
    __slots__ = ("enabled", "sample", "collector")

    def __init__(self) -> None:
        self.enabled = knobs.get_bool("DYN_TRACE_ENABLED")
        self.sample = knobs.get_float("DYN_TRACE_SAMPLE")
        self.collector = TraceCollector(
            capacity=max(1, knobs.get_int("DYN_TRACE_BUFFER"))
        )


_STATE = _State()
_tracers: dict[str, Tracer] = {}


def configure(
    enabled: bool | None = None,
    sample: float | None = None,
    buffer: int | None = None,
) -> None:
    """Re-apply tracing config (tests; runtime/config.py overlay). A new
    ``buffer`` swaps in a fresh ring buffer and rebinds live tracers."""
    if enabled is not None:
        _STATE.enabled = enabled
    if sample is not None:
        _STATE.sample = max(0.0, min(1.0, sample))
    if buffer is not None and buffer != _STATE.collector.capacity:
        old = _STATE.collector
        _STATE.collector = TraceCollector(capacity=max(1, buffer))
        for ref in old._metrics:
            registry = ref()
            if registry is not None:
                _STATE.collector.bind_metrics(registry)
        for tracer in _tracers.values():
            tracer.collector = _STATE.collector


def trace_enabled() -> bool:
    return _STATE.enabled


def get_collector() -> TraceCollector:
    return _STATE.collector


def get_tracer(service: str) -> Tracer:
    tracer = _tracers.get(service)
    if tracer is None:
        tracer = _tracers[service] = Tracer(service, _STATE.collector)
    elif tracer.collector is not _STATE.collector:
        tracer.collector = _STATE.collector
    return tracer


def phase_order(spans: Iterable[Span | dict]) -> list[str]:
    """Phase names in start order — test/debug helper for asserting the
    waterfall shape ({http, tokenize, route, prefill, decode})."""
    def key(s):
        if isinstance(s, dict):
            return (s["start_s"], s["end_s"])
        return (s.start_s, s.end_s)

    def name(s):
        return s["name"] if isinstance(s, dict) else s.name

    return [name(s) for s in sorted(spans, key=key)]
