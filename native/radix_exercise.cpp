// Sanitizer exercise for the native radix index (radix_tree.cpp).
//
// Built by `make test-native` with -fsanitize=address,undefined and run
// directly; every code path of the C ABI is driven with deterministic
// pseudo-random traffic plus the edge cases ctypes callers can produce
// (zero-length batches, cap smaller than the result set, replayed event
// ids, removes of unknown hashes, double worker removal). Asserts check
// the same invariants tests/test_native_radix.py checks from Python, so
// a sanitizer hit here means a real heap/UB bug, not a harness artifact.

#include <cassert>
#include <cstdint>
#include <cstdio>
#include <vector>

extern "C" {
void* radix_new();
void radix_free(void* t);
void radix_apply_stored(void* tp, int64_t worker, int64_t event_id,
                        const uint64_t* hashes, int32_t n, uint64_t parent,
                        int32_t has_parent);
void radix_apply_removed(void* tp, int64_t worker, int64_t event_id,
                         const uint64_t* hashes, int32_t n);
void radix_remove_worker(void* tp, int64_t worker);
int32_t radix_find_matches(void* tp, const uint64_t* hashes, int32_t n,
                           int64_t* out_workers, int32_t* out_depths,
                           int32_t cap);
int32_t radix_num_blocks(void* tp, int64_t worker);
int32_t radix_dump_worker(void* tp, int64_t worker, uint64_t* out_hashes,
                          uint64_t* out_parents, int32_t* out_has_parent,
                          int32_t cap);
}

namespace {

// Deterministic 64-bit LCG (no <random> so the run reproduces everywhere).
uint64_t rng_state = 0x9e3779b97f4a7c15ULL;
uint64_t next_u64() {
    rng_state = rng_state * 6364136223846793005ULL + 1442695040888963407ULL;
    return rng_state;
}

// Chained block hashes: hash[i] depends on hash[i-1], like dynamo_tpu/tokens.
std::vector<uint64_t> chain(uint64_t seed, int n) {
    std::vector<uint64_t> out;
    uint64_t h = seed;
    for (int i = 0; i < n; ++i) {
        h = h * 0x100000001b3ULL ^ (seed + i);
        out.push_back(h);
    }
    return out;
}

int find_depth(void* t, const std::vector<uint64_t>& hashes, int64_t worker) {
    std::vector<int64_t> workers(4096);
    std::vector<int32_t> depths(4096);
    int32_t n = radix_find_matches(t, hashes.data(),
                                   static_cast<int32_t>(hashes.size()),
                                   workers.data(), depths.data(), 4096);
    for (int32_t i = 0; i < n; ++i)
        if (workers[i] == worker) return depths[i];
    return 0;
}

void basic_lifecycle() {
    void* t = radix_new();
    auto c = chain(1, 8);

    radix_apply_stored(t, /*worker=*/7, /*event=*/1, c.data(), 8, 0, 0);
    assert(radix_num_blocks(t, 7) == 8);
    assert(radix_num_blocks(t, -1) == 8);
    assert(find_depth(t, c, 7) == 8);

    // Replayed event id must deduplicate (no double insert, no UB).
    radix_apply_stored(t, 7, 1, c.data(), 8, 0, 0);
    assert(radix_num_blocks(t, 7) == 8);

    // Second worker shares a prefix then diverges.
    auto c2 = chain(1, 4);
    auto tail = chain(2, 4);
    radix_apply_stored(t, 8, 1, c2.data(), 4, 0, 0);
    radix_apply_stored(t, 8, 2, tail.data(), 4, c2.back(), 1);
    assert(find_depth(t, c, 8) == 4);

    // Removing a mid-chain block prunes worker 7's orphaned suffix only.
    radix_apply_removed(t, 7, 2, &c[4], 1);
    assert(find_depth(t, c, 7) == 4);

    // Remove of an unknown hash is a no-op, not a crash.
    uint64_t bogus = 0xdeadbeefULL;
    radix_apply_removed(t, 7, 3, &bogus, 1);

    // Zero-length batches round-trip.
    radix_apply_stored(t, 9, 1, c.data(), 0, 0, 0);
    radix_apply_removed(t, 9, 2, c.data(), 0);
    assert(radix_find_matches(t, c.data(), 0, nullptr, nullptr, 0) == 0);

    // cap smaller than the result set truncates without writing past it.
    int64_t one_worker[1];
    int32_t one_depth[1];
    int32_t n = radix_find_matches(t, c.data(), 4, one_worker, one_depth, 1);
    assert(n == 1);

    // Dump honors cap and reports parents consistently.
    std::vector<uint64_t> hs(16), ps(16);
    std::vector<int32_t> hp(16);
    n = radix_dump_worker(t, 8, hs.data(), ps.data(), hp.data(), 16);
    assert(n == 8);
    n = radix_dump_worker(t, 8, hs.data(), ps.data(), hp.data(), 3);
    assert(n == 3);

    radix_remove_worker(t, 7);
    assert(radix_num_blocks(t, 7) == 0);
    radix_remove_worker(t, 7);  // double removal is a no-op
    radix_remove_worker(t, 8);
    assert(radix_num_blocks(t, -1) == 0);
    radix_free(t);
}

void randomized_churn() {
    void* t = radix_new();
    const int WORKERS = 17;
    const int ROUNDS = 400;
    std::vector<int64_t> event_ids(WORKERS, 0);
    std::vector<std::vector<uint64_t>> chains;
    for (int w = 0; w < WORKERS; ++w)
        chains.push_back(chain(100 + w % 5, 1 + static_cast<int>(next_u64() % 32)));

    for (int r = 0; r < ROUNDS; ++r) {
        int w = static_cast<int>(next_u64() % WORKERS);
        const auto& c = chains[w];
        switch (next_u64() % 4) {
            case 0: {
                int n = 1 + static_cast<int>(next_u64() % c.size());
                radix_apply_stored(t, w, ++event_ids[w], c.data(), n, 0, 0);
                break;
            }
            case 1: {
                int off = static_cast<int>(next_u64() % c.size());
                int n = 1 + static_cast<int>(next_u64() % (c.size() - off));
                radix_apply_removed(t, w, ++event_ids[w], c.data() + off, n);
                break;
            }
            case 2:
                radix_remove_worker(t, w);
                event_ids[w] = 0;
                break;
            default: {
                int d = find_depth(t, c, w);
                assert(d >= 0 && d <= static_cast<int>(c.size()));
                // Depth is a contiguous prefix: every shallower block is
                // held in one snapshot of the worker's dump.
                std::vector<uint64_t> hs(4096), ps(4096);
                std::vector<int32_t> hp(4096);
                int32_t n = radix_dump_worker(t, w, hs.data(), ps.data(),
                                              hp.data(), 4096);
                for (int i = 0; i < d; ++i) {
                    int held = 0;
                    for (int32_t j = 0; j < n; ++j)
                        if (hs[j] == c[i]) held = 1;
                    assert(held);
                }
                break;
            }
        }
        int total = radix_num_blocks(t, -1);
        int per_worker_max = 0;
        for (int w2 = 0; w2 < WORKERS; ++w2) {
            int nb = radix_num_blocks(t, w2);
            assert(nb >= 0);
            if (nb > per_worker_max) per_worker_max = nb;
        }
        assert(per_worker_max <= total);
    }
    radix_free(t);
}

}  // namespace

int main() {
    basic_lifecycle();
    randomized_churn();
    std::puts("radix_exercise: OK");
    return 0;
}
