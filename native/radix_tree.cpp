// Native radix prefix index — the KV router's hot loop in C++.
//
// Same semantics as the Python RadixTree (dynamo_tpu/llm/kv_router/
// indexer.py), which itself mirrors the reference's Rust RadixTree
// (lib/llm/src/kv_router/indexer.rs:222-747): chained block hashes flatten
// the radix tree into a hash -> node map; find_matches scores each worker
// by contiguous leading blocks held; removed blocks prune their orphaned
// subtree; per-worker event ids deduplicate replays.
//
// Exposed as a C ABI consumed via ctypes (no pybind11 in the image).
// Single-writer discipline is preserved by the Python owner: only the
// indexer's event task calls mutating functions.

#include <cstdint>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

struct Node {
    std::unordered_set<int64_t> workers;
    uint64_t parent = 0;
    bool has_parent = false;
    std::unordered_set<uint64_t> children;
};

struct Tree {
    std::unordered_map<uint64_t, Node> nodes;
    std::unordered_map<int64_t, int64_t> last_event_id;

    bool dedup(int64_t worker, int64_t event_id) {
        auto it = last_event_id.find(worker);
        if (it != last_event_id.end() && event_id <= it->second) return true;
        last_event_id[worker] = event_id;
        return false;
    }

    void prune(uint64_t h) {
        auto it = nodes.find(h);
        if (it == nodes.end() || !it->second.workers.empty()) return;
        // Iterative DFS over the orphaned subtree.
        std::vector<uint64_t> stack{h};
        std::vector<uint64_t> order;
        while (!stack.empty()) {
            uint64_t cur = stack.back();
            stack.pop_back();
            auto nit = nodes.find(cur);
            if (nit == nodes.end() || !nit->second.workers.empty()) continue;
            order.push_back(cur);
            for (uint64_t c : nit->second.children) stack.push_back(c);
        }
        for (uint64_t cur : order) {
            auto nit = nodes.find(cur);
            if (nit == nodes.end()) continue;
            if (nit->second.has_parent) {
                auto pit = nodes.find(nit->second.parent);
                if (pit != nodes.end()) pit->second.children.erase(cur);
            }
            nodes.erase(nit);
        }
    }
};

}  // namespace

extern "C" {

void* radix_new() { return new Tree(); }

void radix_free(void* t) { delete static_cast<Tree*>(t); }

void radix_apply_stored(void* tp, int64_t worker, int64_t event_id,
                        const uint64_t* hashes, int32_t n, uint64_t parent,
                        int32_t has_parent) {
    Tree* t = static_cast<Tree*>(tp);
    if (t->dedup(worker, event_id)) return;
    bool hp = has_parent != 0;
    uint64_t p = parent;
    for (int32_t i = 0; i < n; ++i) {
        uint64_t h = hashes[i];
        auto it = t->nodes.find(h);
        if (it == t->nodes.end()) {
            Node node;
            node.parent = p;
            node.has_parent = hp;
            it = t->nodes.emplace(h, std::move(node)).first;
            if (hp) {
                auto pit = t->nodes.find(p);
                if (pit != t->nodes.end()) pit->second.children.insert(h);
            }
        }
        it->second.workers.insert(worker);
        p = h;
        hp = true;
    }
}

void radix_apply_removed(void* tp, int64_t worker, int64_t event_id,
                         const uint64_t* hashes, int32_t n) {
    Tree* t = static_cast<Tree*>(tp);
    if (t->dedup(worker, event_id)) return;
    for (int32_t i = 0; i < n; ++i) {
        auto it = t->nodes.find(hashes[i]);
        if (it == t->nodes.end()) continue;
        it->second.workers.erase(worker);
        if (it->second.workers.empty()) t->prune(hashes[i]);
    }
}

void radix_remove_worker(void* tp, int64_t worker) {
    Tree* t = static_cast<Tree*>(tp);
    std::vector<uint64_t> dead;
    for (auto& [h, node] : t->nodes) {
        if (node.workers.erase(worker)) {
            if (node.workers.empty()) dead.push_back(h);
        }
    }
    for (uint64_t h : dead) t->prune(h);
    t->last_event_id.erase(worker);
}

// Per-worker contiguous-prefix depths. Writes up to `cap` (worker, depth)
// pairs; returns the count.
int32_t radix_find_matches(void* tp, const uint64_t* hashes, int32_t n,
                           int64_t* out_workers, int32_t* out_depths,
                           int32_t cap) {
    Tree* t = static_cast<Tree*>(tp);
    std::unordered_map<int64_t, int32_t> scores;
    std::unordered_set<int64_t> alive;
    bool first = true;
    for (int32_t depth = 1; depth <= n; ++depth) {
        auto it = t->nodes.find(hashes[depth - 1]);
        if (it == t->nodes.end() || it->second.workers.empty()) break;
        std::unordered_set<int64_t> present;
        if (first) {
            present = it->second.workers;
        } else {
            for (int64_t w : alive)
                if (it->second.workers.count(w)) present.insert(w);
        }
        if (present.empty()) break;
        for (int64_t w : present) scores[w] = depth;
        alive = std::move(present);
        first = false;
    }
    int32_t i = 0;
    for (auto& [w, d] : scores) {
        if (i >= cap) break;
        out_workers[i] = w;
        out_depths[i] = d;
        ++i;
    }
    return i;
}

int32_t radix_num_blocks(void* tp, int64_t worker) {
    Tree* t = static_cast<Tree*>(tp);
    if (worker < 0) return static_cast<int32_t>(t->nodes.size());
    int32_t n = 0;
    for (auto& [h, node] : t->nodes)
        if (node.workers.count(worker)) ++n;
    return n;
}

// Dump one worker's blocks for replica re-sync. Writes up to `cap`
// (hash, parent, has_parent) triples; returns the count.
int32_t radix_dump_worker(void* tp, int64_t worker, uint64_t* out_hashes,
                          uint64_t* out_parents, int32_t* out_has_parent,
                          int32_t cap) {
    Tree* t = static_cast<Tree*>(tp);
    int32_t i = 0;
    for (auto& [h, node] : t->nodes) {
        if (!node.workers.count(worker)) continue;
        if (i >= cap) break;
        out_hashes[i] = h;
        out_parents[i] = node.parent;
        out_has_parent[i] = node.has_parent ? 1 : 0;
        ++i;
    }
    return i;
}

}  // extern "C"
