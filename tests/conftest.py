"""Test harness configuration.

JAX tests run on a virtual 8-device CPU mesh (multi-chip shardings compile
and execute without TPU hardware), mirroring the reference's cluster-free
test strategy (SURVEY.md §4: mocker engine + real control-plane fixtures).
Must set env before anything imports jax.
"""

import asyncio
import inspect
import os

# Force CPU even when the ambient environment points at real TPU hardware
# (tests are deterministic and cluster-free; bench.py uses the real chip).
# The TPU PJRT plugin ignores the JAX_PLATFORMS env var, so the config
# update below — which does win — is the load-bearing line; the env vars
# cover subprocesses.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
# Tests are compile-bound on CPU (every EngineCore build jits an 8-device
# program); dropping the LLVM optimization level roughly halves wall time
# without touching numerics — no fast-math, so bit-identical-parity tests
# still compare programs compiled under identical semantics. Opt out by
# passing your own --xla_backend_optimization_level in XLA_FLAGS.
if "xla_backend_optimization_level" not in _flags:
    _flags = (
        _flags + " --xla_backend_optimization_level=0"
        " --xla_llvm_disable_expensive_passes=true"
    ).strip()
os.environ["XLA_FLAGS"] = _flags

import jax

jax.config.update("jax_platforms", "cpu")

import pytest


def pytest_pyfunc_call(pyfuncitem):
    """Minimal async test support (no pytest-asyncio in the image)."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(asyncio.wait_for(fn(**kwargs), timeout=120))
        return True
    return None
