"""Test harness configuration.

JAX tests run on a virtual 8-device CPU mesh (multi-chip shardings compile
and execute without TPU hardware), mirroring the reference's cluster-free
test strategy (SURVEY.md §4: mocker engine + real control-plane fixtures).
Must set env before anything imports jax.
"""

import asyncio
import inspect
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest


def pytest_pyfunc_call(pyfuncitem):
    """Minimal async test support (no pytest-asyncio in the image)."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(asyncio.wait_for(fn(**kwargs), timeout=120))
        return True
    return None
