"""Fixture for loop-affinity: a loop-owned ring buffer touched from a
``to_thread`` context two call-graph hops down, next to a healthy
on-loop write of the same attribute."""

import asyncio


class Publisher:
    def __init__(self):
        self._ringbuf = []

    async def start(self):
        await asyncio.to_thread(self._drain_blocking)

    def _drain_blocking(self):
        self._flush()

    def _flush(self):
        # Reached from the thread spawned in start(): the violation.
        self._ringbuf.append("drained")

    def publish(self, item):
        # On-loop write of the same buffer: must stay quiet.
        self._ringbuf.append(item)
