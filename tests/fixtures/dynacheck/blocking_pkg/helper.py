"""The helper the hot path reaches: a host sync (np.asarray over what
could be a device array) plus a sleep, both invisible to a
single-function pass at the hot function."""

import time

import numpy as np


def assemble_tables(rows):
    tables = np.asarray(rows)
    time.sleep(0.001)
    return tables
