"""Transitive-blocking fixture: the hot plan path reaches a device sync
and an event-loop blocker TWO call frames down — dynalint's direct-site
rule sees nothing here."""

from tests.fixtures.dynacheck.blocking_pkg.helper import assemble_tables


def plan_step(rows):
    total = 0
    for row in rows:
        total += stage_row(row)
    return assemble_tables(rows), total


def stage_row(row):
    return len(row)
