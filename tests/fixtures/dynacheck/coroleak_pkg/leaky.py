"""Coroutine-leak fixture: project-local async defs created but never
awaited, spawned, returned, or reused — plus clean shapes that must NOT
be flagged."""

import asyncio


async def flush_queue(items):
    for item in items:
        await asyncio.sleep(0)
    return len(items)


def drops_coroutine(items):
    flush_queue(items)  # leak: created and immediately dropped
    return True


def binds_and_forgets(items):
    pending = flush_queue(items)  # leak: bound but never used again
    return len(items)


async def clean_awaits(items):
    return await flush_queue(items)


def clean_spawns(items):
    return asyncio.create_task(flush_queue(items))


def clean_returns(items):
    return flush_queue(items)  # caller awaits the tail call


async def clean_bound_then_awaited(items):
    coro = flush_queue(items)
    return await coro
