"""Cursor-discipline fixture: writes to protocol state
(num_computed_tokens cursor, pinned hashes, refcounts) from functions
that are not audited commit/rollback/release entry points."""


def fast_forward(seq, n):
    seq.processed += n          # cursor write outside the audited set
    return seq


def prune_pins(seq):
    seq.pinned_hashes.clear()   # pin mutation outside the audited set
    return seq


def bump_ref(blk):
    blk.refcount += 1           # refcount write outside the allocator
    return blk


def reads_are_fine(seq):
    return seq.processed + len(seq.pinned_hashes)
