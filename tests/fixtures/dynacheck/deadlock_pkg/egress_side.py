"""Deadlock fixture, egress side: takes lock B then lock A — the
opposite order of engine_side.py. Together they form a B->A / A->B
cycle across modules and call frames."""

from tests.fixtures.dynacheck.deadlock_pkg.engine_side import EngineSide


def reversed_order(engine: EngineSide):
    with engine._block:
        with engine._alock:
            pass
