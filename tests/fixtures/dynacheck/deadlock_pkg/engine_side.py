"""Deadlock fixture, engine side: takes lock A then (via a helper call
two frames deep) lock B. The egress side takes them in the opposite
order — dynacheck must extract the cross-module cycle."""

import threading


class EngineSide:
    def __init__(self):
        self._alock = threading.Lock()
        self._block = threading.Lock()

    def outer(self, other):
        with self._alock:
            self.middle(other)

    def middle(self, other):
        # The second acquisition lives a call frame down — a
        # single-function pass cannot see the A->B edge.
        other.take_b()


class HelperSide:
    def __init__(self, engine: "EngineSide"):
        self.engine = engine

    def take_b(self):
        with self.engine._block:
            pass
