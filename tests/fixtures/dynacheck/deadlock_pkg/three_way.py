"""Three-lock deadlock fixture: X -> Y -> Z -> X across three functions.
The cycle's node set sorts differently from its edge order, which is
exactly the shape that must be reported (not crash) — the witness lookup
must follow actual graph edges, not consecutive sorted pairs."""

import threading


class ThreeWay:
    def __init__(self):
        self._xlock = threading.Lock()
        self._ylock = threading.Lock()
        self._zlock = threading.Lock()

    def x_then_y(self):
        with self._xlock:
            with self._ylock:
                pass

    def y_then_z(self):
        with self._ylock:
            with self._zlock:
                pass

    def z_then_x(self):
        with self._zlock:
            with self._xlock:
                pass
