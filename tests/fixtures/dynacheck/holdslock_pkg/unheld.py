"""holds-lock-unverified fixture: a helper annotated as requiring the
lock, called from one context that really holds it and one that does
not — only the second is a finding."""

import threading


class Guarded:
    def __init__(self):
        self._lock = threading.Lock()
        self.table = {}

    # dynalint: holds-lock(_lock)
    def mutate_locked(self, k, v):
        self.table[k] = v

    def good_caller(self, k, v):
        with self._lock:
            self.mutate_locked(k, v)

    def bad_caller(self, k, v):
        self.mutate_locked(k, v)  # annotation violated: no lock held
