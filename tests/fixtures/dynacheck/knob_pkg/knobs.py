"""Fixture knob registry: four knobs, one never read, one undocumented."""

import os

PREFIXES = ("FIX_",)


class Knob:
    def __init__(self, name, default, kind, section, doc):
        self.name = name
        self.default = default
        self.kind = kind
        self.section = section
        self.doc = doc


def _freeze(*knobs):
    return {k.name: k for k in knobs}


KNOBS = _freeze(
    Knob("FIX_ALPHA", "a", "str", "s", "alpha knob"),
    Knob("FIX_BETA", 1, "int", "s", "beta knob"),
    Knob("FIX_DEAD", 0, "int", "s", "registered but read nowhere"),
    Knob("FIX_SECRET", "", "str", "s", "registered but undocumented"),
)


def get(name):
    knob = KNOBS[name]
    return os.environ.get(knob.name, knob.default)
