"""Fixture knob reads: clean accessor reads next to every violation
shape the config-knob rule exists for."""

import os

from tests.fixtures.dynacheck.knob_pkg import knobs

BETA = "FIX_BETA"


def _env(name, fallback):
    # Registry-backed wrapper: call sites carry the knob names.
    v = os.environ.get(name)
    return v if v is not None else fallback


def load(cfg):
    a = knobs.get("FIX_ALPHA")                  # clean
    b = knobs.get(BETA)                         # clean, via module constant
    s = knobs.get("FIX_SECRET")                 # clean read; doc is missing
    g = knobs.get("FIX_GHOST")                  # unregistered
    direct = os.environ.get("FIX_DIRECT", "7")  # bypass + unregistered
    dup = _env("FIX_ALPHA", "dup-default")      # literal duplicate default
    dyn = os.environ.get("FIX_" + cfg.suffix)   # unresolvable, no pragma
    ok = os.environ.get(cfg.plugin_env)  # dynacheck: knob-dynamic(plugin-chosen name)
    return a, b, s, g, direct, dup, dyn, ok
