"""Fixture plane file: produces an orphan key, consumes a ghost key, and
backslides into a raw string literal at a send site."""

from tests.fixtures.dynacheck.wire_pkg import wire


async def emit(sock):
    frame = {wire.A_TYPE: "req", wire.A_BODY: b"x", wire.A_ORPHAN: 1}
    await sock.send(frame)
    # Raw literal "b" where wire.A_BODY belongs — the backslide shape.
    yield {wire.A_TYPE: "rsp", "b": b"raw"}


def parse(frame):
    if wire.A_GHOST in frame:
        return frame[wire.A_BODY]
    return frame.get(wire.A_TYPE)
