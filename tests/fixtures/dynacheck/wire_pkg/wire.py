"""Fixture wire registry: two planes sharing one parse context, with a
deliberately conflicting key meaning and an unused key."""

A_TYPE = "t"
A_BODY = "b"
A_ORPHAN = "o"
A_GHOST = "g"
B_TYPE = "t"      # same key string, same context, different meaning
B_UNUSED = "u"

SCHEMAS = {
    "alpha": {
        "A_TYPE": "frame discriminator",
        "A_BODY": "payload bytes",
        "A_ORPHAN": "produced but never consumed",
        "A_GHOST": "consumed but never produced",
    },
    "beta": {
        "B_TYPE": "retry budget",
        "B_UNUSED": "registered but never referenced",
    },
}

CONTEXTS = {"alpha": "shared-envelope", "beta": "shared-envelope"}

VALUES = {}
