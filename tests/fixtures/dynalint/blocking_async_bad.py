"""Seeded violations: synchronous work on the event loop."""

import subprocess
import time

import requests  # noqa: F401 — fixture is parsed, never imported


async def fetch(url: str) -> None:
    time.sleep(1)                          # finding
    subprocess.run(["ls"])                 # finding
    requests.get(url)                      # finding
    fh = open("/tmp/f")                    # finding
    fh.close()
