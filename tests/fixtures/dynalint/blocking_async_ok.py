"""Negative cases: async-safe equivalents and sync-context calls."""

import asyncio
import subprocess
import time


async def fetch(url: str) -> None:
    await asyncio.sleep(1)
    await asyncio.to_thread(subprocess.run, ["ls"], check=True)
    fh = await asyncio.to_thread(open, "/tmp/f")
    fh.close()


def sync_helper() -> None:
    time.sleep(1)       # fine: not on the event loop
    open("/tmp/f").close()


async def outer() -> None:
    def callback() -> None:
        # fine: nested sync def — typically handed to to_thread/executor
        subprocess.run(["ls"], check=True)

    await asyncio.to_thread(callback)
