"""Seeded violations: broad handlers that swallow everything silently."""


def swallow() -> None:
    try:
        raise RuntimeError("boom")
    except Exception:                    # finding: silent
        pass


def bare() -> int:
    try:
        return 1
    except:                              # finding: bare and silent  # noqa: E722
        return 0


def tupled() -> None:
    try:
        raise RuntimeError("boom")
    except (ValueError, Exception):      # finding: Exception in tuple
        return None


def fake_logging(n: float) -> float:
    import math

    try:
        raise RuntimeError("boom")
    except Exception:                    # finding: math.log is not logging
        return math.log(n)
