"""Negative cases: broad handlers that log, re-raise, surface, or opt out."""

import logging

log = logging.getLogger(__name__)


def logs() -> None:
    try:
        raise RuntimeError("boom")
    except Exception:
        log.warning("operation failed", exc_info=True)


def reraises() -> None:
    try:
        raise RuntimeError("boom")
    except Exception:
        raise


def surfaces() -> str:
    try:
        raise RuntimeError("boom")
    except Exception as e:
        return f"error: {e}"             # bound exception is reported


def pragma_opt_out() -> None:
    try:
        raise RuntimeError("boom")
    # dynalint: allow-broad-except(fixture demonstrating the pragma format)
    except Exception:
        pass


def narrow() -> None:
    try:
        raise ValueError("boom")
    except ValueError:                   # narrow excepts are never flagged
        pass
