"""Seeded violations: every spawn here drops the Task on the floor."""

import asyncio
from asyncio import create_task


async def work() -> None:
    pass


async def main() -> None:
    asyncio.create_task(work())          # finding: bare statement
    asyncio.ensure_future(work())        # finding: bare statement
    loop = asyncio.get_event_loop()
    loop.create_task(work())             # finding: loop receiver
    create_task(work())                  # finding: bare imported name
