"""Negative cases: every spawned Task is kept, awaited, or supervised."""

import asyncio


async def work() -> None:
    pass


async def main() -> None:
    t = asyncio.create_task(work())               # stored
    await t
    tasks = [asyncio.create_task(work())]         # stored in a list
    supervised = asyncio.create_task(work())
    supervised.add_done_callback(print)           # done-callback attached
    await asyncio.gather(*tasks, supervised)
    async with asyncio.TaskGroup() as tg:         # TaskGroup holds the ref
        tg.create_task(work())
