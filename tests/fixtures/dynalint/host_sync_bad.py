"""Seeded blocking-host-sync violations (4 findings): device->host
synchronization calls inside registered step-loop hot paths with no
`# dynalint: sync-ok` pragma."""

import numpy as np

from dynamo_tpu.parallel.multihost import fetch_replicated


def plan_step(dev):
    host = np.asarray(dev)          # landing mid-plan: finding 1
    val = dev.item()                # scalar sync mid-plan: finding 2
    toks = fetch_replicated(dev)    # blocking fetch mid-plan: finding 3
    return host, val, toks


def dispatch(dev):
    dev.block_until_ready()         # device barrier mid-dispatch: finding 4
    return dev
