"""Clean twin of host_sync_bad.py: syncs carry the sync-ok pragma inside
hot paths, or live outside them (the commit side), so the detector stays
quiet."""

import numpy as np

from dynamo_tpu.parallel.multihost import fetch_replicated


def plan_step(rows, dev):
    ids = np.asarray(rows)  # dynalint: sync-ok — host list, not a device array
    # dynalint: sync-ok — intentional landing, pragma on the line above
    toks = fetch_replicated(dev)
    return ids, toks


def commit(dev):
    # Not a registered hot path: commit-side landings sync freely.
    return np.asarray(dev), dev.item()


def dispatch(dev):
    return dev + 1  # pure enqueue, nothing to flag
