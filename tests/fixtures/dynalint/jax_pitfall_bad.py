"""Seeded violations: jax/jnp in hazardous contexts, impure traced fns."""

import signal

import jax
import jax.numpy as jnp


class Holder:
    def __del__(self):
        jnp.zeros(1)                     # finding: device work at gc time

    @jax.jit
    def step(self, x):                   # finding: jit over a bound method
        return x + self.offset


def _on_term(signum, frame):
    jax.device_get(jnp.zeros(1))         # finding: jax in a signal handler


signal.signal(signal.SIGTERM, _on_term)


def traced(x):
    print("tracing", x)                  # finding: trace-time print
    return x * 2


fast = jax.jit(traced)


class Model:
    def build(self):
        def impure(x):
            self.cache = x               # finding: self-mutation under trace
            return x

        return jax.jit(impure)
