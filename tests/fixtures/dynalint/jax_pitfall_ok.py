"""Negative cases: pure jitted functions, jax-free finalizers."""

import jax
import jax.numpy as jnp


class Holder:
    def __del__(self):
        self._handle = None              # fine: no device work


def pure(x):
    jax.debug.print("x={x}", x=x)        # fine: the traced-safe print
    return jnp.sum(x)


fast = jax.jit(pure)


class Model:
    def build(self):
        return jax.jit(lambda p, x: p @ x)   # pure lambda
