"""Seeded violations: GUARDED_BY attributes mutated without the lock.

tests/test_dynalint.py registers this file in the GUARDED_BY registry:
Guarded._table and Guarded.count guarded by _lock; module global _handle
guarded by _glock.
"""

import threading

_glock = threading.Lock()
_handle = None


def load():
    global _handle
    _handle = object()                   # finding: _glock not held


class Guarded:
    def __init__(self):
        self._lock = threading.Lock()
        self._table = {}
        self.count = 0                   # fine: __init__ is exempt

    def bad_set(self, k, v):
        self._table[k] = v               # finding

    def bad_incr(self):
        self.count += 1                  # finding

    def bad_clear(self):
        self._table.clear()              # finding: mutator method

    def bad_del(self, k):
        del self._table[k]               # finding

    def bad_global_from_method(self):
        global _handle
        _handle = object()               # finding: _glock not held
