"""Negative cases: mutations under the lock, holds-lock pragma, reads.

Registered with the same GUARDED_BY entries as lock_discipline_bad.py.
"""

import threading

_glock = threading.Lock()
_handle = None


def load():
    global _handle
    with _glock:
        _handle = object()               # fine: module lock held


class Guarded:
    def __init__(self):
        self._lock = threading.Lock()
        self._table = {}
        self.count = 0

    def good_set(self, k, v):
        with self._lock:
            self._table[k] = v           # fine: lock held lexically

    # dynalint: holds-lock(_lock)
    def good_annotated(self):
        self.count += 1                  # fine: caller holds the lock

    def reads_are_free(self):
        return len(self._table) + self.count


def shadowing_local_is_not_the_global():
    _handle = object()                   # fine: local, no `global` decl
    return _handle
