"""Seeded violations: pragmas the linter must reject, not silently obey."""


def empty_reason() -> None:
    try:
        raise RuntimeError("boom")
    # dynalint: allow-broad-except()
    except Exception:
        pass


def unknown_rule() -> None:
    pass  # dynalint: allow-frobnicate(not a rule)


def unparseable() -> None:
    pass  # dynalint: do something
