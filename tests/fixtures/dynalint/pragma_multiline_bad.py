"""The unpragma'd twin of pragma_multiline_ok.py: identical multi-line
statements with no pragmas — every violation must still fire (the span
anchoring must not silently widen into blanket suppression), and a
pragma INSIDE a function body must not cover sibling statements."""

import time

import requests


async def wrapped_call_still_flagged(log):
    result = log.wrap(
        time.sleep(
            1.0
        ),
    )
    return result


async def comprehension_still_flagged(items):
    return [
        requests.get(url)
        for url in items
    ]


async def pragma_does_not_blanket_the_function(log):
    # dynalint: allow-blocking-in-async(fixture: covers only this statement)
    time.sleep(1.0)
    time.sleep(2.0)  # must still be flagged: the pragma above covers one statement
    return log


async def trailing_pragma_does_not_bleed_to_sibling(log):
    time.sleep(
        1.0
    )  # dynalint: allow-blocking-in-async(fixture: trailing pragma on the last span line)
    time.sleep(2.0)  # must still be flagged: the next sibling is not covered
    return log


async def header_pragma_does_not_cover_body(
    log,
):  # dynalint: allow-blocking-in-async(fixture: multi-line def header — pragma anchors to the header, not the body)
    time.sleep(1.0)  # must still be flagged: body is not header
    return log
