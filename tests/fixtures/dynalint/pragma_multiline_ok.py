"""Pragmas on multi-line statements: every violation here is suppressed
by a pragma anchored somewhere on the statement's line span — the
opening line, the line above, or an argument line — even though the
flagged AST node reports a different lineno. The line-based matcher
missed all of these."""

import time

import requests


async def pragma_on_opening_line(log):
    # The flagged node (time.sleep) sits on the argument line, two lines
    # below the pragma'd opening line of the wrapped call.
    result = log.wrap(  # dynalint: allow-blocking-in-async(fixture: pragma on the opening line of a wrapped call)
        time.sleep(
            1.0
        ),
    )
    return result


async def pragma_above_wrapped_statement(items):
    # dynalint: allow-blocking-in-async(fixture: pragma above a statement whose flagged node is on a later line)
    return [
        requests.get(url)
        for url in items
    ]


async def pragma_on_argument_line(log):
    result = log.wrap(
        time.sleep(2.0),  # dynalint: allow-blocking-in-async(fixture: pragma on the argument line covers the statement)
    )
    return result
