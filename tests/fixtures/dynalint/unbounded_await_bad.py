"""Seeded unbounded-await violations (4 findings): network awaits with
no deadline scope and no `# dynalint: unbounded-ok` pragma."""

import asyncio

from dynamo_tpu.runtime import framing


async def dial(host, port):
    reader, writer = await asyncio.open_connection(host, port)   # finding 1
    msg = await framing.read_frame(reader)                       # finding 2
    return writer, msg


class Stream:
    def __init__(self):
        self._queue = asyncio.Queue()

    async def __anext__(self):
        return await self._queue.get()                           # finding 3


async def pop_event(sub):
    return await sub.queue.get()                                 # finding 4
