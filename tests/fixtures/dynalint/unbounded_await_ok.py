"""Clean twin: every network await is bounded (wait_for / asyncio.timeout)
or carries an explicit unbounded-ok pragma; non-network `.get` receivers
stay quiet."""

import asyncio

from dynamo_tpu.runtime import framing


async def dial_bounded(host, port):
    # wait_for-wrapped: the inner call is an argument, not awaited.
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host, port), 5.0
    )
    async with asyncio.timeout(10.0):
        msg = await framing.read_frame(reader)  # inside a timeout scope
    return writer, msg


class Stream:
    def __init__(self):
        self._queue = asyncio.Queue()

    async def __anext__(self):
        try:
            return self._queue.get_nowait()  # sync fast path: not an await
        except asyncio.QueueEmpty:
            return await asyncio.wait_for(self._queue.get(), 30.0)


async def serve_loop(reader):
    # dynalint: unbounded-ok — server read loop idles between frames
    return await framing.read_frame(reader)


async def not_network(msg, settings):
    # Plain dict/config `.get` receivers never match the rule.
    kind = msg.get("t")
    level = settings.get("level")
    return kind, level
