"""Seeded violations: every span here leaks open (never reaches the collector)."""

from dynamo_tpu import tracing

tracer = tracing.get_tracer("fixture")


def bare_statement() -> None:
    tracer.span("phase")                       # finding: result discarded


def assigned_never_finished() -> None:
    s = tracer.span("phase")                   # finding: no s.finish() in scope
    s.set("k", 1)


def direct_chain() -> None:
    tracing.get_tracer("svc").span("phase")    # finding: get_tracer(...).span chain


class Worker:
    def __init__(self) -> None:
        self._tracer = tracing.get_tracer("worker")

    def handle(self) -> None:
        span = self._tracer.span("handle")     # finding: attribute receiver, unfinished
        span.set("k", 2)
