"""Negative cases: every span closes — with-statement or explicit finish()."""

from dynamo_tpu import tracing

tracer = tracing.get_tracer("fixture")


def with_statement() -> None:
    with tracer.span("phase") as s:
        s.set("k", 1)


def finished_on_every_path() -> None:
    # Root-span shape (llm/http_service.py): bound to a name, closed in
    # a finally so error paths still record.
    root = tracer.span("http")
    try:
        root.set("k", 2)
    finally:
        root.finish()


class Worker:
    def __init__(self) -> None:
        self._tracer = tracing.get_tracer("worker")

    def handle(self) -> None:
        with self._tracer.span("handle"):
            pass


class Row:
    def span(self, width: int) -> int:
        return width


def not_a_tracer(row: Row) -> None:
    row.span(3)  # unrelated .span() method on a non-tracer receiver
