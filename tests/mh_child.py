"""Child process for multi-host tests: joins a 2-process CPU "pod",
builds the EngineCore over the GLOBAL dp=2 x tp=4 mesh, runs a scripted
greedy workload, and writes its emitted tokens to a file.

Run: python tests/mh_child.py <coordinator> <rank> <out_path> [ckpt_dir]

With ``ckpt_dir``, every rank loads the SAME HF checkpoint host-side
(engine/loader.py) and shard_params places each process's addressable
shards onto the global mesh — the multi-host real-weights path that
``--model-path --nnodes N`` exercises in production.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    coordinator, rank, out_path = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    ckpt = sys.argv[4] if len(sys.argv) > 4 else None
    os.environ.pop("XLA_FLAGS", None)  # the pod size comes from init_multihost
    from dynamo_tpu.parallel.multihost import init_multihost

    init_multihost(coordinator, num_processes=2, process_id=rank,
                   local_cpu_devices=4)

    from dynamo_tpu.engine.config import EngineConfig, ModelConfig
    from dynamo_tpu.engine.core import EngineCore
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.parallel.sharding import make_mesh

    params = None
    if ckpt is not None:
        import jax.numpy as jnp

        from dynamo_tpu.engine.loader import load_hf_llama

        cfg, params = load_hf_llama(ckpt, dtype=jnp.float32, tp=4)
    else:
        cfg = ModelConfig(
            name="dryrun", vocab_size=512, hidden_size=64,
            intermediate_size=128, num_layers=2, num_heads=8, num_kv_heads=8,
            head_dim=16, dtype="float32", tie_embeddings=True,
        )
    eng = EngineConfig(
        num_kv_blocks=32, block_size=8, max_num_seqs=8, max_model_len=128,
        prefill_buckets=(32, 64, 128), decode_buckets=(4, 8),
    )
    core = EngineCore(cfg, eng, params=params, seed=0, mesh=make_mesh(dp=2, tp=4))
    seqs = [
        core.add_request(
            PreprocessedRequest(
                model="t", token_ids=list(range(3 + i, 40 + i)),
                request_id=f"r{i}",
                sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=5),
            )
        )
        for i in range(3)
    ]
    done = {s.request_id: [] for s in seqs}
    fins = 0
    for _ in range(200):
        for seq, out in core.step():
            done[seq.request_id].extend(out.token_ids)
            if out.finish_reason:
                fins += 1
        if fins == 3:
            break
    with open(out_path, "w") as f:
        json.dump(done, f)


if __name__ == "__main__":
    main()
