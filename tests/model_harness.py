"""Shared test helpers for driving `model.forward_tokens` directly:
assemble the ragged-batch operands for a single-sequence prefill chunk
the same way EngineCore._run_prefill_wave does."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.model import forward_tokens


def prefill_chunk(
    params,
    cache,
    chunk: list[int],
    start_pos: int,
    block_ids: list[int],
    cfg: ModelConfig,
    eng: EngineConfig,
    bucket: int,
    mesh=None,
):
    """Prefill one chunk of a single sequence (tokens at positions
    start_pos .. start_pos+len(chunk)-1). Returns (last-token logits
    [vocab], cache)."""
    n = len(chunk)
    assert n <= bucket
    bs = eng.block_size
    ids = np.asarray(block_ids, np.int32)

    tokens = np.zeros(bucket, np.int32)
    tokens[:n] = chunk
    positions = np.zeros(bucket, np.int32)
    pos = np.arange(start_pos, start_pos + n, dtype=np.int32)
    positions[:n] = pos
    write_pages = np.full(bucket, eng.garbage_block, np.int32)
    write_pages[:n] = ids[pos // bs]
    write_offs = np.zeros(bucket, np.int32)
    write_offs[:n] = pos % bs

    table = np.full((1, eng.max_blocks_per_seq), eng.garbage_block, np.int32)
    table[0, : len(ids)] = ids
    kv_lens = np.array([start_pos + n], np.int32)
    cu = np.array([0, n], np.int32)
    last_rows = np.array([n - 1], np.int32)

    logits, cache = forward_tokens(
        params,
        cache,
        jnp.asarray(tokens),
        jnp.asarray(positions),
        jnp.asarray(write_pages),
        jnp.asarray(write_offs),
        jnp.asarray(kv_lens),
        jnp.asarray(table),
        jnp.asarray(cu),
        jnp.asarray(np.array([1], np.int32)),
        jnp.asarray(last_rows),
        cfg,
        eng,
        mesh,
    )
    return logits[0], cache
