"""Async pipelined execution loop (ISSUE 5): the one-step-ahead engine.

The tentpole contract: with ``async_exec`` on, the engine plans and
enqueues step N+1 while step N executes on device (device-resident token
feedback, optimistic cursor overlays, double-buffered host fetch) and the
token stream stays BIT-IDENTICAL to the synchronous loop — greedy AND
seeded temperature, waves + chunked mixed steps + spec-decode verify rows,
including stops that land one step late and roll back via the
``num_computed_tokens`` cursor.
"""

import math

import numpy as np
import pytest

from dynamo_tpu import tracing
from dynamo_tpu.engine import EngineCore, tiny_engine, tiny_model
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)

pytestmark = [pytest.mark.unit]

CFG = tiny_model()


def _req(prompt, rid, max_tokens=8, temperature=0.0, seed=None, top_k=0,
         top_p=1.0, logprobs=None, **stop_kw):
    pre = PreprocessedRequest(
        model="tiny",
        token_ids=prompt,
        request_id=rid,
        sampling=SamplingOptions(
            temperature=temperature, seed=seed, top_k=top_k, top_p=top_p
        ),
        stop=StopConditions(max_tokens=max_tokens, **stop_kw),
    )
    if logprobs is not None:
        pre.output.logprobs = logprobs
    return pre


def drive(core, seqs, max_steps=4000):
    """Run to completion, draining the pipeline tail (an in-flight step
    holds a stream's final tokens until the next step() call)."""
    done = {s.request_id: [] for s in seqs}
    fins: dict[str, str] = {}
    lps = {s.request_id: [] for s in seqs}
    for _ in range(max_steps):
        for s, out in core.step():
            done[s.request_id].extend(out.token_ids)
            if out.logprobs:
                lps[s.request_id].extend(out.logprobs)
            if out.finish_reason:
                fins[s.request_id] = out.finish_reason
        if len(fins) == len(seqs) and not core.has_work():
            break
    return done, fins, lps


def _mixed_workload(core):
    rng = np.random.RandomState(0)
    long_prompt = list(rng.randint(1, 200, size=200))
    seqs = [
        core.add_request(_req(list(range(i + 1, i + 9)), f"s{i}", max_tokens=12))
        for i in range(4)
    ]
    seqs.append(core.add_request(_req(long_prompt, "long", max_tokens=6)))
    return seqs


# -- config validation --------------------------------------------------------


def test_async_constructs_on_pp_mesh():
    # The async x pp rejection is LIFTED (ISSUE 20): fused pp megasteps
    # compose with async execution. Stream parity for that combination
    # is pinned by tests/test_pp_megastep.py::test_parity_pp_async_composition;
    # here we pin that construction succeeds and reports its stages.
    from dynamo_tpu.parallel.pipeline import make_pp_mesh

    core = EngineCore(
        CFG, tiny_engine(async_exec=True), seed=0, pp_mesh=make_pp_mesh(2)
    )
    assert core.scheduler_stats()["pp_stages"] == 2


# -- bit-identical parity -----------------------------------------------------


@pytest.mark.parametrize("scheduling", ["waves", "chunked"])
def test_greedy_parity_async_on_vs_off(scheduling):
    """Same seeds/prompts, same tokens, same finish reasons — async
    changes WHEN work happens (one step late), never what is emitted."""

    def run(async_exec):
        core = EngineCore(
            CFG,
            tiny_engine(
                async_exec=async_exec, scheduling=scheduling, prefill_chunk=32
            ),
            seed=0,
        )
        return drive(core, _mixed_workload(core))[:2]

    assert run(False) == run(True)


@pytest.mark.parametrize("scheduling", ["waves", "chunked"])
def test_seeded_temperature_parity_async_on_vs_off(scheduling):
    """Seeded sampling lanes (plain temperature, top-k, top-p mixed in
    one batch) replay the same (seed, counter) keys through the overlay,
    so the sampled ids match bit for bit; logprob payloads too."""

    def run(async_exec):
        core = EngineCore(
            CFG,
            tiny_engine(
                async_exec=async_exec, scheduling=scheduling, prefill_chunk=32
            ),
            seed=0,
        )
        seqs = [
            core.add_request(_req(
                [3, 5, 7, 9], "t", max_tokens=10, temperature=0.8, seed=11,
                ignore_eos=True,
            )),
            core.add_request(_req(
                [4, 6, 8], "k", max_tokens=10, temperature=0.7, seed=12,
                top_k=8, ignore_eos=True,
            )),
            core.add_request(_req(
                [2, 4, 6, 8, 10], "p", max_tokens=10, temperature=0.9,
                seed=13, top_p=0.8, logprobs=3, ignore_eos=True,
            )),
        ]
        return drive(core, seqs)

    d0, f0, l0 = run(False)
    d1, f1, l1 = run(True)
    assert d0 == d1
    assert f0 == f1
    assert l0 == l1


@pytest.mark.parametrize("scheduling", ["waves", "chunked"])
def test_spec_decode_parity_async_on_vs_off(scheduling):
    """Speculating lanes: drafts propose from (possibly lagged) host
    history and the verify row consumes the device-resident pending
    token; verification replays the target's own counter-keyed choices,
    so the stream is identical regardless of WHAT was drafted."""

    def run(async_exec):
        core = EngineCore(
            CFG,
            tiny_engine(
                async_exec=async_exec, scheduling=scheduling,
                prefill_chunk=32, spec_decode="ngram", spec_k=4,
            ),
            seed=0,
        )
        repeat = [3, 4, 5, 3, 4, 5, 3, 4]  # n-gram bait
        seqs = [
            core.add_request(_req(repeat, "sp", max_tokens=16, ignore_eos=True)),
            core.add_request(_req(
                [7] * 40, "q", max_tokens=10, temperature=0.7, seed=5,
                ignore_eos=True,
            )),
        ]
        return drive(core, seqs)[:2]

    assert run(False) == run(True)


def test_prefix_cache_replay_parity_async():
    """A prefix-cache-served replay must emit identical tokens under
    async execution (the admission path runs at plan time)."""
    prompt = list(range(3, 63))

    def run(async_exec):
        core = EngineCore(
            CFG,
            tiny_engine(
                async_exec=async_exec, scheduling="chunked", prefill_chunk=32
            ),
            seed=0,
        )
        s1 = core.add_request(_req(prompt, "warm", max_tokens=5))
        d1, _, _ = drive(core, [s1])
        s2 = core.add_request(_req(prompt, "hit", max_tokens=5))
        d2, _, _ = drive(core, [s2])
        assert s2.num_cached_tokens >= 48
        return d1["warm"], d2["hit"]

    assert run(False) == run(True)


# -- late-stop rollback -------------------------------------------------------


def test_late_stop_rolls_back_optimistic_step():
    """With 1-step chains, a stop token commits one step AFTER the next
    step was already dispatched optimistically: the zombie lane's
    in-flight tokens are discarded (its K/V writes sit past the cursor,
    never attended) and the stream matches the synchronous loop."""
    ref = EngineCore(CFG, tiny_engine(decode_chain=1), seed=0)
    s = ref.add_request(_req([9, 9, 9], "r", max_tokens=12, ignore_eos=True))
    d, _, _ = drive(ref, [s])
    stop_tok = d["r"][5]  # mid-stream stop: 5 tokens then the stop

    def run(async_exec):
        core = EngineCore(
            CFG, tiny_engine(async_exec=async_exec, decode_chain=1), seed=0
        )
        seq = core.add_request(_req(
            [9, 9, 9], "x", max_tokens=12, stop_token_ids=[stop_tok],
            ignore_eos=True,
        ))
        out = drive(core, [seq])
        return out, core

    (d0, f0, _), sync_core = run(False)
    (d1, f1, _), async_core = run(True)
    assert d0 == d1
    assert f0 == f1 == {"x": "stop"}
    # The rollback actually happened: the async engine dispatched at
    # least one optimistic step past the stop and discarded it.
    assert (
        async_core.exec_stats["dispatches"]
        > sync_core.exec_stats["dispatches"]
    )


def test_late_eos_rollback_async():
    """Same rollback through the EOS path (engine-level eos_token_ids)."""
    probe = EngineCore(CFG, tiny_engine(decode_chain=1), seed=0)
    s = probe.add_request(_req([1, 2, 3], "p", max_tokens=10, ignore_eos=True))
    d, _, _ = drive(probe, [s])
    eos = d["p"][4]
    if eos in d["p"][:4]:
        pytest.skip("greedy stream repeats before position 4; stop-token "
                    "rollback is covered by test_late_stop_rolls_back")

    def run(async_exec):
        core = EngineCore(
            CFG, tiny_engine(async_exec=async_exec, decode_chain=1),
            seed=0, eos_token_ids=(eos,),
        )
        seq = core.add_request(_req([1, 2, 3], "e", max_tokens=10))
        return drive(core, [seq])[:2]

    assert run(False) == run(True)


# -- the pipelining contract --------------------------------------------------


def test_steady_decode_dispatch_precedes_landing():
    """The acceptance invariant: in steady-state decode, dispatch N+1 is
    enqueued BEFORE step N's outputs land — the host never syncs on the
    device between consecutive dispatches, so the device queue is never
    empty when the host blocks (asserted via the dispatch/land event
    hook)."""
    core = EngineCore(CFG, tiny_engine(async_exec=True, decode_chain=1), seed=0)
    core._exec_log = []
    seqs = [
        core.add_request(_req([1, 2, 3, 4], "a", max_tokens=20, ignore_eos=True)),
        core.add_request(_req([5, 6, 7, 8], "b", max_tokens=20, ignore_eos=True)),
    ]
    drive(core, seqs)
    log = core._exec_log
    disp_pos = {n: i for i, (k, n) in enumerate(log) if k == "dispatch"}
    land_pos = {n: i for i, (k, n) in enumerate(log) if k == "land"}
    assert len(disp_pos) >= 20  # 1-step chains: a real steady state
    # Every landing of step n happens after dispatch n+1 (the final
    # step's drain, with nothing left to dispatch, is the one exception).
    max_d = max(disp_pos)
    violations = [
        n for n in land_pos
        if n < max_d and disp_pos.get(n + 1, 10 ** 9) > land_pos[n]
    ]
    assert violations == [], (violations, log[:12])


def test_sync_loop_lands_before_next_dispatch():
    """The synchronous twin of the hook test: async off, every landing
    precedes the next dispatch (plan+commit per call)."""
    core = EngineCore(CFG, tiny_engine(async_exec=False, decode_chain=1), seed=0)
    core._exec_log = []
    seq = core.add_request(_req([1, 2, 3], "a", max_tokens=8, ignore_eos=True))
    drive(core, [seq])
    log = core._exec_log
    disp_pos = {n: i for i, (k, n) in enumerate(log) if k == "dispatch"}
    land_pos = {n: i for i, (k, n) in enumerate(log) if k == "land"}
    assert all(
        land_pos[n] < disp_pos[n + 1] for n in land_pos if n + 1 in disp_pos
    )


def test_block_pressure_drains_pipeline_and_recovers():
    """Out-of-blocks mid-plan with a step in flight: the engine commits
    the in-flight step (a drain), re-plans settled, preempts normally,
    and the replayed stream still matches the synchronous loop."""

    def run(async_exec):
        core = EngineCore(
            CFG,
            tiny_engine(
                num_kv_blocks=12, max_model_len=64, async_exec=async_exec,
                scheduling="chunked", prefill_chunk=16, decode_chain=1,
            ),
            seed=0,
        )
        seqs = [
            core.add_request(_req(list(range(1, 17)), "a", max_tokens=24)),
            core.add_request(_req(list(range(20, 36)), "b", max_tokens=24)),
            core.add_request(_req(list(range(40, 80)), "c", max_tokens=8)),
        ]
        done, fins, _ = drive(core, seqs, max_steps=8000)
        assert core.allocator._partials == 0
        return done, fins, core

    d0, f0, _ = run(False)
    d1, f1, core1 = run(True)
    assert d0 == d1
    assert f0 == f1
    # The pressure path actually ran (deterministic at this config):
    # growth failed mid-plan with a step in flight (drain), and the
    # settled re-plan preempted a victim.
    assert core1.exec_stats["drains"] >= 1
    assert core1.sched_stats["preemptions"] >= 1


def test_cancel_mid_flight_discards_in_flight_tokens():
    core = EngineCore(CFG, tiny_engine(async_exec=True, decode_chain=1), seed=0)
    seq = core.add_request(_req([1, 2, 3], "c", max_tokens=50, ignore_eos=True))
    core.step()  # dispatch prefill
    core.step()  # dispatch decode 1, commit prefill
    core.cancel_request(seq)
    for _ in range(5):
        core.step()
    assert not core.has_work()
    assert seq not in core.running
    assert core.allocator._partials == 0


# -- observability ------------------------------------------------------------


def test_plan_commit_and_host_gap_spans_recorded():
    tracing.configure(enabled=True, sample=1.0)
    collector = tracing.get_collector()
    collector.clear()
    core = EngineCore(CFG, tiny_engine(async_exec=True, decode_chain=1), seed=0)
    seq = core.add_request(_req([1, 2, 3], "t", max_tokens=8, ignore_eos=True))
    drive(core, [seq])
    stats = collector.stats()
    names = {s.name for s in stats}
    assert "engine_plan" in names
    assert "engine_commit" in names
    gaps = [s for s in stats if s.name == "host_gap"]
    assert gaps, "host_gap stat missing"
    # Steady-state decode gaps are overlapped (a step was in flight when
    # the next dispatch was enqueued).
    assert any(g.attrs.get("overlapped") for g in gaps)
    assert core.exec_stats["last_host_gap_ms"] >= 0.0
    # Idle reset: with all work drained, the gap chain is broken so the
    # next burst's first dispatch won't record inter-arrival time as
    # per-dispatch host overhead.
    assert core._t_prev_dispatch == 0.0
    st = core.scheduler_stats()
    assert st["async_exec"] == 1
    assert st["dispatches"] == core.exec_stats["dispatches"]


def test_kv_cache_stats_surface():
    core = EngineCore(CFG, tiny_engine(), seed=0)
    st = core.kv_cache_stats()
    # Counter/usage series start at zero; the static layout facts
    # (kv_dtype, bytes_per_block, capacity_blocks) are nonzero by design.
    static = {"kv_dtype", "kv_dtype_int8", "bytes_per_block", "capacity_blocks"}
    assert all(v == 0 for k, v in st.items() if k not in static)
    prompt = list(range(3, 63))
    s1 = core.add_request(_req(prompt, "w", max_tokens=3))
    drive(core, [s1])
    s2 = core.add_request(_req(prompt, "h", max_tokens=3))
    drive(core, [s2])
    st = core.kv_cache_stats()
    # Admission series: warm miss + replay hit.
    assert st["admitted_queries"] == 2
    assert st["admitted_hits"] == 1
    assert st["admitted_hit_rate"] == 0.5
    # Probe series stays untouched by admissions (match_prefix only) —
    # the two definitions must never double-count each other.
    assert st["prefix_queries"] == 0
    core.cached_prefix_tokens(prompt)
    st = core.kv_cache_stats()
    assert st["prefix_queries"] == 1
    assert st["prefix_hits"] == 1
    assert st["admitted_queries"] == 2  # probes don't touch admissions


# -- mocker virtual-clock overlap A/B ----------------------------------------


def _mock_decode_sim(async_exec, B=16, osl=64):
    """Decode-heavy workload on the mocker's virtual clock: per-iteration
    cost from iter_time_s (deterministic, no sleeping)."""
    import asyncio

    from dynamo_tpu.llm.mocker.engine import MockEngineArgs, MockTpuEngine, _Seq
    from dynamo_tpu.tokens import TokenBlockSequence, compute_seq_hashes

    args = MockEngineArgs(
        num_kv_blocks=8192, block_size=32, max_num_seqs=B,
        max_num_batched_tokens=2048, enable_prefix_caching=False,
        async_exec=async_exec,
    )
    eng = MockTpuEngine(args)
    seqs = []
    for j in range(B):
        prompt = [1 + (j % 7)] * 128
        s = _Seq(
            request_id=f"s{j}", prompt=prompt, max_tokens=osl,
            out=asyncio.Queue(),
            seq=TokenBlockSequence(prompt, args.block_size),
            prompt_hashes=compute_seq_hashes(prompt, args.block_size),
            stop=StopConditions(max_tokens=osl, ignore_eos=True),
        )
        seqs.append(s)
        eng._waiting.append(s)
    vt = 0.0
    first, prev = {}, {}
    gaps = []
    streams = {s.request_id: [] for s in seqs}
    while any(s in eng._running or s in eng._waiting for s in seqs):
        eng._admit()
        p, d = eng._step()
        vt += eng.iter_time_s(p, d)
        for s in seqs:
            while not s.out.empty():
                item = s.out.get_nowait()
                if not isinstance(item, dict):
                    continue
                toks = item.get("token_ids", [])
                if not toks:
                    continue
                streams[s.request_id].extend(toks)
                rid = s.request_id
                if rid in first:
                    gaps.append(vt - prev[rid])
                first.setdefault(rid, vt)
                prev[rid] = vt
    gaps.sort()
    return {
        "tpot_p50": gaps[len(gaps) // 2],
        "streams": streams,
    }


def test_mocker_async_ab_improves_tpot_when_overhead_dominates():
    """The acceptance A/B on the mocker's virtual clock: at B=16 decode
    the fixed per-dispatch host overhead (base_iter_us=500) dominates the
    device term (16 * 100us / ... ), and the one-step-ahead overlap model
    must cut decode TPOT p50 — with a BIT-IDENTICAL stream."""
    off = _mock_decode_sim(False)
    on = _mock_decode_sim(True)
    assert on["streams"] == off["streams"], "async changed token values"
    assert on["tpot_p50"] < off["tpot_p50"], (on["tpot_p50"], off["tpot_p50"])
    # max(host, device) vs host + device at these shapes: >= 20% better.
    assert on["tpot_p50"] < off["tpot_p50"] * 0.8


def test_mocker_host_gap_stat_shrinks_with_async():
    from dynamo_tpu.llm.mocker.engine import MockEngineArgs, MockTpuEngine

    tracing.configure(enabled=True, sample=1.0)
    collector = tracing.get_collector()
    for async_exec in (False, True):
        collector.clear()
        eng = MockTpuEngine(MockEngineArgs(async_exec=async_exec))
        t = eng.iter_time_s(0, 32)  # decode-heavy: device 3.2ms > host 0.5ms
        gaps = [s for s in collector.stats() if s.name == "host_gap"]
        assert len(gaps) == 1
        if async_exec:
            assert gaps[0].duration_s == 0.0  # fully hidden
            assert math.isclose(t, 32 * 100e-6, rel_tol=1e-6)
        else:
            assert gaps[0].duration_s > 0.0
            assert math.isclose(t, 500e-6 + 32 * 100e-6, rel_tol=1e-6)
