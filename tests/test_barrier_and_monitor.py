"""Leader/worker barrier + busy-aware worker monitor over the store."""

import asyncio

import pytest

from dynamo_tpu.llm.kv_router.protocols import (
    ForwardPassMetrics,
    KvStats,
    WorkerStats,
    load_metrics_subject,
)
from dynamo_tpu.runtime.barrier import LeaderBarrier, WorkerBarrier
from dynamo_tpu.runtime.store import StoreServer
from dynamo_tpu.runtime.store.client import StoreClient
from dynamo_tpu.runtime.worker_monitor import WorkerMonitor

pytestmark = [pytest.mark.integration]


async def test_leader_worker_barrier():
    store = StoreServer()
    await store.start()
    client = await StoreClient.open(store.address)
    try:
        leader = LeaderBarrier(client, "kvbm-init", num_workers=3)
        workers = [WorkerBarrier(client, "kvbm-init", f"w{i}") for i in range(3)]

        async def worker(b):
            return await b.sync(timeout=10)

        leader_task = asyncio.create_task(leader.sync({"layout": "flat", "n": 7}))
        datas = await asyncio.gather(*[worker(b) for b in workers])
        checked_in = await leader_task
        assert sorted(checked_in) == ["w0", "w1", "w2"]
        assert all(d == {"layout": "flat", "n": 7} for d in datas)
    finally:
        await client.close()
        await store.stop()


async def test_worker_monitor_busy_marking():
    store = StoreServer()
    await store.start()
    client = await StoreClient.open(store.address)
    pub = await StoreClient.open(store.address)
    try:
        changes: list[tuple[int, bool]] = []
        mon = WorkerMonitor(
            client, "dynamo", "backend", busy_threshold=0.9,
            on_busy_change=lambda w, b: changes.append((w, b)),
        )
        await mon.start()
        subject = load_metrics_subject("dynamo", "backend")

        def fpm(worker, usage):
            return ForwardPassMetrics(
                worker_id=worker,
                worker=WorkerStats(request_active_slots=1, request_total_slots=4),
                kv=KvStats(gpu_cache_usage_perc=usage),
            ).to_wire()

        await pub.publish(subject, fpm(1, 0.5))
        await pub.publish(subject, fpm(2, 0.97))
        await asyncio.sleep(0.2)
        assert mon.eligible([1, 2]) == [1]
        assert (2, True) in changes

        await pub.publish(subject, fpm(2, 0.3))
        await asyncio.sleep(0.2)
        assert mon.eligible([1, 2]) == [1, 2]
        assert (2, False) in changes

        # All busy -> fall back to everyone rather than dead-ending.
        await pub.publish(subject, fpm(1, 0.99))
        await pub.publish(subject, fpm(2, 0.99))
        await asyncio.sleep(0.2)
        assert mon.eligible([1, 2]) == [1, 2]
        await mon.stop()
    finally:
        await client.close()
        await pub.close()
        await store.stop()
