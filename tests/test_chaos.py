"""Chaos harness + failure containment (ISSUE 6).

Every scenario runs a mocker fleet under a seeded ChaosPlan and asserts
the containment contract: accepted requests complete with token streams
BIT-IDENTICAL to the no-fault run, no token lost or duplicated —
worker-kill mid-decode, a stalled-but-connected engine loop, a flapping
store session, and a partitioned dataplane all reduce to the same
client-visible outcome. Plus the unit surface: circuit breaker state
machine, exactly-once failure delivery, eager conn eviction, graceful
drain ordering, migration backoff bounds, replay usage accounting, and
the disabled-chaos no-op guarantee.
"""

import asyncio
import random
import struct
import time
from contextlib import suppress

import msgpack
import pytest

from dynamo_tpu.llm.migration import Migration, MigrationOperator, RouterEgress
from dynamo_tpu.llm.mocker import MockEngineArgs, MockTpuEngine
from dynamo_tpu.llm.protocols.common import (
    LLMEngineOutput,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime import DistributedRuntime, chaos
from dynamo_tpu.runtime.chaos import ChaosPlan, ChaosRule
from dynamo_tpu.runtime.dataplane import (
    BreakerOpenError,
    CircuitBreaker,
    EgressClient,
    EgressPolicy,
    IngressServer,
)
from dynamo_tpu.runtime.pipeline import PipelineBuilder
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.store import StoreServer
from dynamo_tpu.runtime.store.client import reconnect_delay

pytestmark = [pytest.mark.integration, pytest.mark.pre_merge]


def expected_tokens(n: int) -> list[int]:
    """The mocker's deterministic 'a'..'z' cycle — the no-fault stream."""
    return [97 + (i % 26) for i in range(n)]


def make_req(rid: str, max_tokens: int = 12) -> PreprocessedRequest:
    return PreprocessedRequest(
        model="mock",
        token_ids=[1, 2, 3, 4],
        request_id=rid,
        sampling=SamplingOptions(),
        stop=StopConditions(max_tokens=max_tokens),
    )


class Fleet:
    """Store + N mocker-engine workers + a routing client with the full
    migration pipeline — the minimal real-runtime fleet every chaos
    scenario runs against."""

    def __init__(
        self,
        n: int = 2,
        args: MockEngineArgs | None = None,
        stall_s: float | None = None,
    ):
        self.n = n
        self.args = args or MockEngineArgs(num_kv_blocks=512, block_size=8)
        self.stall_s = stall_s
        self.workers: list[tuple[DistributedRuntime, MockTpuEngine]] = []

    async def __aenter__(self) -> "Fleet":
        self.store = StoreServer()
        await self.store.start()
        for i in range(self.n):
            rt = await DistributedRuntime.create(self.store.address)
            engine = MockTpuEngine(self.args)
            engine.chaos_tag = f"w{i}"
            ep = rt.namespace("chaos").component("w").endpoint("generate")

            async def handler(req, ctx, engine=engine):
                async for out in engine.generate(req, ctx):
                    yield out

            await ep.serve(handler)
            self.workers.append((rt, engine))
        self.client_rt = await DistributedRuntime.create(self.store.address)
        if self.stall_s is not None:
            self.client_rt.egress.policy.stall_s = self.stall_s
        self.client = await (
            self.client_rt.namespace("chaos").component("w").endpoint("generate").client()
        )
        await self.client.wait_for_instances(self.n, timeout=10)
        self.migration = Migration(
            client=self.client, push_router=None, mode="round_robin", limit=3
        )
        return self

    async def __aexit__(self, *exc) -> None:
        chaos.uninstall()
        await self.client.stop()
        await self.client_rt.shutdown()
        for rt, _ in self.workers:
            with suppress(ConnectionError, OSError):
                await rt.shutdown()
        await self.store.stop()

    def serving_worker(self) -> tuple[DistributedRuntime, MockTpuEngine]:
        """The worker whose engine currently holds a running sequence."""
        for rt, engine in self.workers:
            if engine._running:
                return rt, engine
        raise AssertionError("no worker is serving")


# ---------------------------------------------------------------------------
# Scenario 1: worker killed mid-decode — stream bit-identical, usage sane.
# ---------------------------------------------------------------------------


async def test_worker_kill_mid_decode_bit_identical_stream():
    # ~20 ms per decode iteration so the kill reliably lands mid-stream.
    args = MockEngineArgs(num_kv_blocks=512, block_size=8, decode_us_per_seq=20000.0)

    # No-fault baseline first (fresh fleet: no shared state).
    async with Fleet(1, args, stall_s=5.0) as f:
        baseline = []
        async for out in f.migration.generate(make_req("base-1")):
            baseline.extend(out.token_ids)
    assert baseline == expected_tokens(12)

    async with Fleet(2, args, stall_s=5.0) as f:
        tokens: list[int] = []
        outs: list[LLMEngineOutput] = []
        killed = False
        async for out in f.migration.generate(make_req("kill-1")):
            tokens.extend(out.token_ids)
            outs.append(out)
            if not killed and len(tokens) >= 3:
                killed = True
                victim, _ = f.serving_worker()
                await victim.shutdown()  # worker dies with the stream in flight
        assert killed, "stream finished before the kill landed — slow the mocker"
        # Bit-identical to the no-fault run: nothing lost, nothing duplicated.
        assert tokens == baseline
        # Late-failure replay accounting: the replayed tokens are charged
        # once — prompt_tokens is the ORIGINAL prompt, completion_tokens
        # the full client-visible stream (not just the final attempt's).
        final = outs[-1]
        assert final.finish_reason is not None
        assert final.prompt_tokens == 4
        assert final.completion_tokens == 12


# ---------------------------------------------------------------------------
# Scenario 2: stalled-but-connected worker — stall deadline detects it,
# migration replays, stream stays bit-identical.
# ---------------------------------------------------------------------------


async def test_stalled_worker_detected_and_migrated_within_budget():
    args = MockEngineArgs(num_kv_blocks=512, block_size=8, decode_us_per_seq=5000.0)
    async with Fleet(2, args, stall_s=0.4) as f:
        tokens: list[int] = []
        stalled_at = None
        stalled_tag = None
        async for out in f.migration.generate(make_req("stall-1")):
            tokens.extend(out.token_ids)
            if stalled_at is None and len(tokens) >= 3:
                _, engine = f.serving_worker()
                stalled_tag = engine.chaos_tag
                chaos.install(ChaosPlan([
                    ChaosRule(
                        point="engine.step", action="stall",
                        match=stalled_tag, stall_s=60.0,
                    ),
                ], seed=42))
                stalled_at = time.monotonic()
        assert stalled_at is not None
        # The wedged worker never closed its socket — only the per-token
        # stall deadline can have fired. Detection + migration + replayed
        # completion must fit a small multiple of the 0.4s budget.
        assert time.monotonic() - stalled_at < 5.0
        assert tokens == expected_tokens(12)
        stats = f.client_rt.egress.stats()
        assert any(st["stalls_total"] >= 1 for st in stats.values()), stats
        # The stalled conn was evicted from the pool — a fresh request
        # must not be routed into the same stall_s black hole.
        stalled_rt = next(rt for rt, e in f.workers if e.chaos_tag == stalled_tag)
        assert stalled_rt.ingress.address not in f.client_rt.egress._conns
        # The migration replayed on the OTHER worker.
        others = [e for _, e in f.workers if e.chaos_tag != stalled_tag]
        assert sum(1 for e in others if e._iterations > 0) >= 1


# ---------------------------------------------------------------------------
# Scenario 2b (ISSUE 12): a worker running the UNIVERSAL megastep —
# chunked scheduling + spec decode, k=8 — fails MID-MEGASTEP with fused
# verify rows in flight. Kill (dead socket) and stall (wedged loop, only
# the per-frame deadline can see it) both route the stream through
# migration, and the replayed continuation is bit-identical to the
# no-fault run: the fused chunking changes how many tokens ride each
# frame, never which tokens the client sees.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("action", ["kill", "stall"])
async def test_fused_megastep_worker_failure_bit_identical(action):
    args = MockEngineArgs(
        num_kv_blocks=512, block_size=8, decode_us_per_seq=20000.0,
        scheduling="chunked", prefill_chunk=8,
        megastep_k=8, spec_decode="ngram", spec_k=4,
    )

    # No-fault baseline first (fresh fleet: no shared state).
    async with Fleet(1, args, stall_s=5.0) as f:
        baseline: list[int] = []
        async for out in f.migration.generate(make_req("base-f", max_tokens=40)):
            baseline.extend(out.token_ids)
    assert baseline == expected_tokens(40)

    async with Fleet(2, args, stall_s=0.8) as f:
        tokens: list[int] = []
        hit = False
        async for out in f.migration.generate(make_req("fused-1", max_tokens=40)):
            tokens.extend(out.token_ids)
            if not hit and len(tokens) >= 3:
                hit = True
                victim_rt, victim = f.serving_worker()
                # The victim really is mid-fused-traffic: fused verify
                # dispatches ran (not plain single-step decode).
                assert victim.sched_stats["megastep_dispatches"] >= 1
                assert victim.sched_stats["fused_mixed_dispatches"] >= 1
                assert victim.spec_stats.verify_rows >= 1
                if action == "kill":
                    await victim_rt.shutdown()  # dies with the stream in flight
                else:
                    chaos.install(ChaosPlan([
                        ChaosRule(
                            point="engine.step", action="stall",
                            match=victim.chaos_tag, stall_s=60.0,
                        ),
                    ], seed=7))
        assert hit, "stream finished before the failure landed — slow the mocker"
        # Bit-identical to the no-fault run: the fused in-flight verify
        # rows were lost with the worker and replayed exactly.
        assert tokens == baseline


# ---------------------------------------------------------------------------
# Scenario 3: store session flap — sever the control-plane stream; the
# session rebuilds (leases re-attached, watches replayed) and the fleet
# keeps serving.
# ---------------------------------------------------------------------------


async def test_store_flap_session_rebuilds_and_requests_complete():
    args = MockEngineArgs(num_kv_blocks=512, block_size=8)
    async with Fleet(1, args, stall_s=5.0) as f:
        # Sever exactly one inbound store frame: the client runtime's
        # session drops mid-request and must rebuild.
        chaos.install(ChaosPlan([
            ChaosRule(point="store.frame", action="sever", count=1),
        ], seed=7))
        with pytest.raises(ConnectionError):
            await f.client_rt.store.ping()
        chaos.uninstall()
        # Reconnect loop redials with jittered backoff; poll until live.
        for _ in range(200):
            try:
                await f.client_rt.store.ping()
                break
            except ConnectionError:
                await asyncio.sleep(0.02)
        else:
            raise AssertionError("store session never rebuilt after flap")
        # The instance watch was REPLAYED, not dropped: a worker joining
        # after the flap appears through the same subscription object.
        rt2 = await DistributedRuntime.create(f.store.address)
        engine2 = MockTpuEngine(args)
        engine2.chaos_tag = "w-late"
        ep2 = rt2.namespace("chaos").component("w").endpoint("generate")

        async def handler2(req, ctx):
            async for out in engine2.generate(req, ctx):
                yield out

        await ep2.serve(handler2)
        try:
            await f.client.wait_for_instances(2, timeout=10)
            # And requests still stream bit-identically end to end.
            tokens = []
            async for out in f.migration.generate(make_req("flap-1")):
                tokens.extend(out.token_ids)
            assert tokens == expected_tokens(12)
        finally:
            await rt2.shutdown()


# ---------------------------------------------------------------------------
# Scenario 4: dataplane partition — severed frames from one worker kill
# the conn; streams fail over by token replay, pool evicts eagerly.
# ---------------------------------------------------------------------------


async def test_dataplane_partition_migrates_and_evicts():
    args = MockEngineArgs(num_kv_blocks=512, block_size=8, decode_us_per_seq=20000.0)
    async with Fleet(2, args, stall_s=5.0) as f:
        tokens: list[int] = []
        addr = None
        async for out in f.migration.generate(make_req("part-1")):
            tokens.extend(out.token_ids)
            if addr is None and len(tokens) >= 3:
                victim, _ = f.serving_worker()
                addr = victim.ingress.address
                chaos.install(ChaosPlan([
                    ChaosRule(point="dataplane.recv", action="sever", match=addr),
                ], seed=3))
        assert addr is not None
        assert tokens == expected_tokens(12)
        stats = f.client_rt.egress.stats()
        assert stats[addr]["consecutive_failures"] >= 1
        # Eager eviction: the dead conn left the pool when its reader
        # died, not lazily at the next dial.
        assert addr not in f.client_rt.egress._conns


# ---------------------------------------------------------------------------
# Chaos disabled: injection points are no-ops and the wire codec is
# byte-identical to the raw length-prefixed msgpack framing.
# ---------------------------------------------------------------------------


async def test_chaos_disabled_noop_overhead_and_wire_format():
    from dynamo_tpu.runtime import framing

    chaos.uninstall()
    n = 20000
    t0 = time.perf_counter()
    for _ in range(n):
        await chaos.inject("dataplane.send", "127.0.0.1:1")
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 10e-6, f"disabled chaos costs {per_call * 1e6:.2f}µs/frame"

    # Wire format unchanged: 4-byte BE length + msgpack body, nothing
    # added or reordered by the chaos layer.
    msg = {"t": "rsp", "i": 1, "p": b"ab"}
    body = msgpack.packb(msg, use_bin_type=True)
    assert framing.pack(msg) == struct.pack(">I", len(body)) + body


async def test_empty_plan_stream_identical_to_no_plan():
    args = MockEngineArgs(num_kv_blocks=512, block_size=8)
    async with Fleet(1, args) as f:
        base = []
        async for out in f.migration.generate(make_req("noop-a")):
            base.extend(out.token_ids)
        chaos.install(ChaosPlan([], seed=1))  # armed but ruleless
        withplan = []
        async for out in f.migration.generate(make_req("noop-b")):
            withplan.extend(out.token_ids)
        assert base == withplan == expected_tokens(12)


# ---------------------------------------------------------------------------
# ChaosPlan unit surface: determinism, env loading, validation.
# ---------------------------------------------------------------------------


async def test_chaos_plan_seeded_determinism():
    async def run(seed: int):
        plan = ChaosPlan(
            [ChaosRule(point="framing.send", action="drop", p=0.5)], seed=seed
        )
        verdicts = [await plan.fire("framing.send", "t") for _ in range(64)]
        return verdicts, list(plan.fired)

    a = await run(7)
    b = await run(7)
    c = await run(8)
    assert a == b
    assert a != c


def test_chaos_plan_from_env_and_validation(monkeypatch):
    monkeypatch.setenv(
        "DYN_CHAOS_PLAN",
        '{"seed": 3, "rules": [{"point": "store.frame", "action": "sever", "count": 1}]}',
    )
    plan = ChaosPlan.from_env()
    assert plan is not None and plan.seed == 3
    assert plan.rules[0].point == "store.frame"
    monkeypatch.delenv("DYN_CHAOS_PLAN")
    assert ChaosPlan.from_env() is None
    with pytest.raises(ValueError, match="unknown chaos point"):
        ChaosRule(point="nope", action="drop")
    with pytest.raises(ValueError, match="unknown chaos action"):
        ChaosRule(point="framing.send", action="explode")


async def test_chaos_rule_after_and_count_windows():
    plan = ChaosPlan([
        ChaosRule(point="framing.recv", action="drop", after=2, count=2),
    ])
    verdicts = [await plan.fire("framing.recv", "") for _ in range(6)]
    # Hits 1-2 pass (after), 3-4 drop (count), 5-6 pass (exhausted).
    assert verdicts == [True, True, False, False, True, True]


# ---------------------------------------------------------------------------
# Circuit breaker: state machine + fail-fast dialing.
# ---------------------------------------------------------------------------


def test_circuit_breaker_state_machine():
    now = [0.0]
    br = CircuitBreaker(threshold=3, reset_s=5.0, clock=lambda: now[0])
    assert br.allow() and br.state == br.CLOSED
    br.record_failure()
    br.record_failure()
    assert br.allow()  # still closed below threshold
    br.record_failure()
    assert br.state == br.OPEN and br.opens_total == 1
    assert not br.allow()  # fail fast while open
    now[0] = 5.1
    assert br.allow() and br.state == br.HALF_OPEN  # the single probe
    assert not br.allow()  # second dial held during the probe
    # A probe that never reports back (cancelled mid-dial) must not wedge
    # the breaker: after another reset window a new probe is granted.
    now[0] = 10.2
    assert br.allow() and br.state == br.HALF_OPEN
    br.record_failure()  # probe failed -> re-open, cooldown restarts
    assert br.state == br.OPEN and br.opens_total == 2
    now[0] = 15.4
    assert br.allow() and br.state == br.HALF_OPEN
    br.record_success()
    assert br.state == br.CLOSED and br.consecutive_failures == 0
    assert br.allow()


async def test_breaker_opens_after_repeated_connect_failures():
    egress = EgressClient(
        EgressPolicy(connect_s=0.5, breaker_threshold=3, breaker_reset_s=60.0)
    )
    addr = "127.0.0.1:9"  # nothing listens -> instant refusal
    for _ in range(3):
        with pytest.raises(ConnectionError):
            await egress.request(addr, "x", {})
    with pytest.raises(BreakerOpenError):
        await egress.request(addr, "x", {})
    st = egress.stats()[addr]
    assert st["state"] == "open"
    assert st["opens_total"] == 1
    assert st["consecutive_failures"] == 3
    egress.close()


async def test_breaker_state_exports_on_metrics():
    from dynamo_tpu.runtime.status_server import SystemStatusServer, bind_egress_gauges

    egress = EgressClient(
        EgressPolicy(connect_s=0.5, breaker_threshold=1, breaker_reset_s=60.0)
    )
    addr = "127.0.0.1:9"
    with pytest.raises(ConnectionError):
        await egress.request(addr, "x", {})
    status = SystemStatusServer()
    bind_egress_gauges(status, egress)
    for hook in status.before_render:
        hook()
    text = status.metrics.render().decode()
    assert f'dynamo_egress_breaker_open{{address="{addr}",service="dataplane"}} 1.0' in text
    assert f'dynamo_egress_breaker_opens_total{{address="{addr}",service="dataplane"}} 1.0' in text
    egress.close()


# ---------------------------------------------------------------------------
# EgressClient containment details (satellites): exactly-once failure
# delivery to every in-flight stream, eager eviction, lock cleanup.
# ---------------------------------------------------------------------------


async def test_connection_loss_errors_all_inflight_streams_exactly_once():
    server = IngressServer()

    async def parked(request, context: Context):
        yield {"first": True}
        await asyncio.sleep(3600)  # parked until the server dies

    server.register("t/w/park", parked)
    await server.start()
    egress = EgressClient(EgressPolicy(stall_s=None))
    s1 = await egress.request(server.address, "t/w/park", {})
    s2 = await egress.request(server.address, "t/w/park", {})
    assert (await s1.__anext__())["first"]
    assert (await s2.__anext__())["first"]

    await server.stop()

    for stream in (s1, s2):
        errors = 0
        while True:
            try:
                await stream.__anext__()
            except ConnectionError:
                errors += 1  # exactly one per stream...
            except StopAsyncIteration:
                break
        assert errors == 1
    # Eager eviction: no dead conn lingers for the next _get_conn.
    assert egress._conns == {}
    egress.close()
    assert egress._locks == {}


# ---------------------------------------------------------------------------
# Graceful drain: deregister first, refuse new work retryably, finish
# in-flight, then release the shutdown waiter.
# ---------------------------------------------------------------------------


async def test_graceful_drain_finishes_inflight_and_deregisters():
    async with StoreServer() as store:
        worker = await DistributedRuntime.create(store.address)
        client_rt = await DistributedRuntime.create(store.address)
        try:
            async def slow(request, context: Context):
                for i in range(10):
                    yield {"i": i}
                    await asyncio.sleep(0.02)

            ep = worker.namespace("t").component("w").endpoint("slow")
            await ep.serve(slow)
            client = await client_rt.namespace("t").component("w").endpoint("slow").client()
            await client.wait_for_instances(1, timeout=5)
            addr = worker.ingress.address

            stream = await client.round_robin({})
            got = [await stream.__anext__(), await stream.__anext__()]

            drain_task = asyncio.create_task(worker.drain(timeout=10.0))
            await asyncio.sleep(0.05)  # deregistration + draining flag land

            # New work is refused RETRYABLY (ConnectionError -> migration
            # replays elsewhere), not failed.
            late = await client_rt.egress.request(addr, "t/w/slow", {})
            with pytest.raises(ConnectionError, match="draining"):
                await late.__anext__()

            # The in-flight stream runs to completion — nothing lost.
            rest = [item async for item in stream]
            assert [g["i"] for g in got] + [r["i"] for r in rest] == list(range(10))

            assert await drain_task is True
            assert worker._shutdown.is_set()
            # Discovery is empty: the instance key was deleted up front.
            assert await client_rt.store.kv_get_prefix("/dynamo/instances/") == {}
        finally:
            await client_rt.shutdown()
            with suppress(ConnectionError, OSError):
                await worker.shutdown()


# ---------------------------------------------------------------------------
# Migration pacing (satellite): jittered exponential backoff on the
# store client's bounded schedule, injectable for determinism.
# ---------------------------------------------------------------------------


def test_reconnect_delay_bounds():
    rng = random.Random(123)
    for attempt in range(8):
        ceiling = min(0.2 * 2.0 ** attempt, 2.0)
        for _ in range(50):
            d = reconnect_delay(attempt, rng)
            assert 0.0 <= d <= ceiling


async def test_migration_backoff_is_jittered_and_bounded():
    class Flaky:
        def __init__(self):
            self.calls = 0

        def pick_instance(self, mode, exclude):
            return self.calls + 1

        async def direct(self, worker_id, payload, headers=None):
            self.calls += 1
            calls = self.calls

            async def stream():
                yield LLMEngineOutput(token_ids=[calls]).to_wire()
                if calls <= 2:
                    raise ConnectionError("down")
                yield LLMEngineOutput(
                    token_ids=[99], finish_reason="stop"
                ).to_wire()

            return stream()

    delays: list[float] = []

    async def capture(d: float) -> None:
        delays.append(d)

    op = MigrationOperator(limit=3, rng=random.Random(0))
    op._sleep = capture
    pipe = PipelineBuilder().link(op).backend(
        RouterEgress(Flaky(), None, "round_robin")
    )
    out = [o async for o in pipe.generate(make_req("backoff-1"), Context())]
    assert out[-1].finish_reason == "stop"
    assert len(delays) == 2
    assert 0.0 <= delays[0] <= 0.2      # attempt 0 ceiling
    assert 0.0 <= delays[1] <= 0.4      # attempt 1 ceiling


# ---------------------------------------------------------------------------
# Replay accounting under late failure (satellite): a worker dying after
# N streamed tokens must not re-emit them nor double-charge usage.
# ---------------------------------------------------------------------------


async def test_migration_replay_accounting_under_late_failure():
    seen_replays: list[dict] = []

    class DieThenFinish:
        def pick_instance(self, mode, exclude):
            return 2 if 1 in exclude else 1

        async def direct(self, worker_id, payload, headers=None):
            pre = PreprocessedRequest.from_wire(payload)

            async def stream():
                if worker_id == 1:
                    yield LLMEngineOutput(token_ids=[10, 11, 12]).to_wire()
                    raise ConnectionError("late death")
                # Replay-aware worker: the grown prompt carries the
                # replayed tokens; it emits ONLY the continuation and
                # bills its own view of the request.
                seen_replays.append({
                    "replayed_tokens": pre.replayed_tokens,
                    "prompt_tail": pre.token_ids[-3:],
                    "max_tokens": pre.stop.max_tokens,
                })
                yield LLMEngineOutput(
                    token_ids=[13, 14],
                    finish_reason="stop",
                    prompt_tokens=len(pre.token_ids),
                    completion_tokens=2,
                ).to_wire()

            return stream()

    m = Migration(client=DieThenFinish(), push_router=None, mode="round_robin", limit=2)
    pre = PreprocessedRequest(
        model="t", token_ids=[1, 2, 3], request_id="late-1",
        sampling=SamplingOptions(), stop=StopConditions(max_tokens=5),
    )
    outs = [o async for o in m.generate(pre)]
    tokens = [t for o in outs for t in o.token_ids]
    # No re-emission of replayed tokens, exact stream.
    assert tokens == [10, 11, 12, 13, 14]
    # The replayed attempt was marked and budget-shrunk.
    assert seen_replays == [{
        "replayed_tokens": 3, "prompt_tail": [10, 11, 12], "max_tokens": 2,
    }]
    # Client-facing usage: original prompt, full completion — each
    # replayed token charged exactly once.
    final = outs[-1]
    assert final.prompt_tokens == 3
    assert final.completion_tokens == 5


# ---------------------------------------------------------------------------
# Mocker replay continuity: the replayed_tokens marker keeps the
# synthetic stream on its cycle (what makes fleet replays bit-exact).
# ---------------------------------------------------------------------------


async def test_mocker_replay_base_continues_token_cycle():
    engine = MockTpuEngine(MockEngineArgs(num_kv_blocks=128, block_size=8))
    pre = PreprocessedRequest(
        model="mock", token_ids=[1, 2, 3, 4] + expected_tokens(5),
        request_id="replay-1", sampling=SamplingOptions(),
        stop=StopConditions(max_tokens=7), replayed_tokens=5,
    )
    tokens = []
    async for out in engine.generate(pre.to_wire(), Context()):
        tokens.extend(LLMEngineOutput.from_wire(out).token_ids)
    assert tokens == expected_tokens(12)[5:]
