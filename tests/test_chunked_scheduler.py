"""Chunked-prefill token-budget scheduler: parity, interleaving,
mid-chunk preemption, and the mocker-timed saturated-mix A/B.

The tentpole contract (ISSUE 3): with ``scheduling='chunked'`` each engine
step mixes all runnable decode rows (q_len=1) with prefill chunks under
``max_num_batched_tokens``, producing IDENTICAL greedy output to the wave
scheduler while never stalling in-flight decodes for a whole wave.
"""

import asyncio
import math

import numpy as np
import pytest

from dynamo_tpu import tracing
from dynamo_tpu.engine import EngineCore, tiny_engine, tiny_model
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)

pytestmark = [pytest.mark.unit]

CFG = tiny_model()


def _req(prompt, rid, max_tokens=8, **stop_kw):
    return PreprocessedRequest(
        model="tiny",
        token_ids=prompt,
        request_id=rid,
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=max_tokens, **stop_kw),
    )


def run_to_completion(core, seqs, max_steps=2000):
    done: dict[str, list[int]] = {s.request_id: [] for s in seqs}
    finishes: dict[str, str] = {}
    for _ in range(max_steps):
        for seq, out in core.step():
            done[seq.request_id].extend(out.token_ids)
            if out.finish_reason:
                finishes[seq.request_id] = out.finish_reason
        if len(finishes) == len(seqs):
            break
    return done, finishes


# -- config validation --------------------------------------------------------


def test_scheduling_config_validation():
    with pytest.raises(ValueError, match="scheduling"):
        EngineCore(CFG, tiny_engine(scheduling="fancy"), seed=0)
    with pytest.raises(ValueError, match="block_size"):
        EngineCore(CFG, tiny_engine(prefill_chunk=12), seed=0)  # bs=8
    with pytest.raises(ValueError, match="largest prefill bucket"):
        EngineCore(CFG, tiny_engine(max_num_batched_tokens=4096), seed=0)
    with pytest.raises(ValueError, match="token budget"):
        EngineCore(
            CFG, tiny_engine(prefill_chunk=128, max_num_batched_tokens=64), seed=0
        )


# -- greedy parity ------------------------------------------------------------


def test_greedy_parity_chunked_vs_waves():
    """Chunked and wave scheduling must produce identical greedy tokens
    for the same seeds/prompts — mixed batches change the step shape,
    never the math."""
    rng = np.random.RandomState(0)
    long_prompt = list(rng.randint(1, 200, size=200))  # > largest bucket: chunks
    shorts = [list(range(i + 1, i + 9)) for i in range(4)]

    def run(scheduling):
        core = EngineCore(
            CFG, tiny_engine(scheduling=scheduling, prefill_chunk=32), seed=0
        )
        seqs = [
            core.add_request(_req(p, f"s{i}", max_tokens=12))
            for i, p in enumerate(shorts)
        ]
        seqs.append(core.add_request(_req(long_prompt, "long", max_tokens=6)))
        return run_to_completion(core, seqs)

    done_w, fin_w = run("waves")
    done_c, fin_c = run("chunked")
    assert done_w == done_c
    assert fin_w == fin_c


def test_greedy_parity_with_cached_prefix_ending_mid_chunk():
    """A prompt whose cached prefix ends at a non-chunk-aligned cursor
    (56 tokens cached, chunk 32 -> resume at 56 % 32 != 0) must replay to
    the same tokens under both schedulers."""
    prompt = list(range(3, 63))  # 60 tokens; cache cap = 7 blocks = 56 tokens

    def run(scheduling):
        core = EngineCore(
            CFG, tiny_engine(scheduling=scheduling, prefill_chunk=32), seed=0
        )
        s1 = core.add_request(_req(prompt, "warm", max_tokens=5))
        d1, _ = run_to_completion(core, [s1])
        s2 = core.add_request(_req(prompt, "hit", max_tokens=5))
        d2, _ = run_to_completion(core, [s2])
        assert s2.num_cached_tokens >= 48  # the prefix cache actually served
        return d1["warm"], d2["hit"]

    warm_w, hit_w = run("waves")
    warm_c, hit_c = run("chunked")
    assert warm_w == warm_c == hit_w == hit_c


# -- interleaving -------------------------------------------------------------


def test_long_admit_never_stalls_decodes_beyond_chunk_count():
    """Chunked scheduling: a 200-token admit streams over
    ceil(200/chunk) mixed steps and every in-flight decode emits a token
    in EVERY one of those steps. Waves stalls them for the whole wave."""
    chunk = 32
    long_prompt = list(np.random.RandomState(1).randint(1, 200, size=200))

    def run(scheduling):
        core = EngineCore(
            CFG, tiny_engine(scheduling=scheduling, prefill_chunk=chunk), seed=0
        )
        d1 = core.add_request(_req([1, 2, 3, 4], "d1", max_tokens=40, ignore_eos=True))
        d2 = core.add_request(_req([5, 6, 7, 8], "d2", max_tokens=40, ignore_eos=True))
        while not (d1.prefill_done and d2.prefill_done):
            core.step()
        lg = core.add_request(_req(long_prompt, "long", max_tokens=2, ignore_eos=True))
        steps = 0
        stalled_steps = 0
        while not lg.prefill_done and steps < 100:
            live = {s.request_id for s in (d1, d2) if s.finish is None}
            outs = core.step()
            steps += 1
            # Only unfinished decodes can stall (under the universal
            # megastep a fused mixed step emits up to k tokens per lane,
            # so short decodes may finish before the long prompt does).
            if live and not any(s.request_id in live for s, _ in outs):
                stalled_steps += 1
        return steps, stalled_steps

    steps_c, stalled_c = run("chunked")
    assert steps_c <= math.ceil(200 / chunk)
    assert stalled_c == 0, "a mixed step failed to advance in-flight decodes"

    steps_w, stalled_w = run("waves")
    assert stalled_w == steps_w > 0, "waves should stall decodes for the wave"


def test_chunked_pure_decode_uses_fused_chains():
    """With no prefill pending, chunked scheduling falls back to the
    fused decode chain (multi-token chunks per step), not 1-token steps."""
    core = EngineCore(
        CFG, tiny_engine(scheduling="chunked", decode_chain=8), seed=0
    )
    seq = core.add_request(_req([1, 2, 3], "a", max_tokens=40, ignore_eos=True))
    core.step()  # prefill + first token
    outs = core.step()  # pure decode step
    assert len(outs) == 1
    assert len(outs[0][1].token_ids) > 1  # chained, not single-token


# -- scheduler observability --------------------------------------------------


def test_sched_admit_and_chunk_spans_recorded():
    tracing.configure(enabled=True, sample=1.0)
    collector = tracing.get_collector()
    collector.clear()
    chunk = 32
    core = EngineCore(
        CFG, tiny_engine(scheduling="chunked", prefill_chunk=chunk), seed=0
    )
    prompt = list(np.random.RandomState(2).randint(1, 200, size=100))
    seq = core.add_request(_req(prompt, "traced", max_tokens=2))
    run_to_completion(core, [seq])
    stats = collector.stats()
    admits = [s for s in stats if s.name == "sched_admit"]
    chunks = [s for s in stats if s.name == "engine_prefill_chunk"]
    mixed = [s for s in stats if s.name == "engine_mixed_step"]
    assert len(admits) == 1
    assert admits[0].attrs["request_id"] == "traced"
    assert admits[0].duration_s >= 0
    assert len(chunks) == math.ceil(100 / chunk)
    assert sum(c.attrs["tokens"] for c in chunks) == 100
    assert len(mixed) == len(chunks)
    assert seq.t_first_sched >= seq.t_queued > 0


def test_scheduler_stats_gauges():
    core = EngineCore(
        CFG, tiny_engine(scheduling="chunked", prefill_chunk=32), seed=0
    )
    st = core.scheduler_stats()
    for key in (
        "waiting", "running", "preemptions", "mixed_steps",
        "last_step_batched_tokens", "last_step_budget_utilization",
        "chunked_prefills_in_flight", "chunked_scheduling", "token_budget",
    ):
        assert key in st
    assert st["chunked_scheduling"] == 1
    prompt = list(np.random.RandomState(3).randint(1, 200, size=100))
    seq = core.add_request(_req(prompt, "g", max_tokens=2))
    core.step()
    st = core.scheduler_stats()
    assert st["mixed_steps"] == 1
    assert st["last_step_batched_tokens"] == 32
    assert 0 < st["last_step_budget_utilization"] <= 1
    assert st["chunked_prefills_in_flight"] == 1
    run_to_completion(core, [seq])


# -- mid-chunk preemption (satellite: release exactly once) -------------------


def test_preempt_between_chunks_releases_exactly_once():
    """Preempting a half-prefilled sequence must release its block refs
    exactly once, keep its FULL prompt for replay, and leave the
    allocator back at baseline once the request completes."""
    prompt = list(range(1, 81))  # 80 tokens: chunks of 32 -> mid-prefill exists
    ref_core = EngineCore(CFG, tiny_engine(), seed=0)
    ref, _ = run_to_completion(
        ref_core, [ref_core.add_request(_req(prompt, "ref", max_tokens=5))]
    )

    core = EngineCore(
        CFG, tiny_engine(scheduling="chunked", prefill_chunk=32), seed=0
    )
    seq = core.add_request(_req(prompt, "L", max_tokens=5))
    core.step()  # first chunk only
    assert 0 < seq.prefilled < seq.prompt_len

    core._preempt(seq)
    assert seq.prompt == prompt, "mid-chunk preemption must keep the full prompt"
    assert seq.prefilled == 0 and seq.block_ids == [] and seq.pinned_hashes == []
    assert core.allocator._partials == 0, "uncommitted partials leaked"

    # Exactly-once: a second release is a no-op (refcounts untouched).
    free_before = core.allocator.free_blocks
    used_before = core.allocator.used_blocks
    core._release_blocks(seq)
    assert core.allocator.free_blocks == free_before
    assert core.allocator.used_blocks == used_before

    done, fin = run_to_completion(core, [seq])
    assert done["L"] == ref["ref"]
    assert fin["L"] == "length"
    # Free count back to baseline: every block unpinned (inactive cache).
    assert core.allocator.used_blocks == len(core.allocator._inactive)
    assert core.allocator._partials == 0
    assert core.sched_stats["preemptions"] == 1


def test_chunked_preemption_under_block_pressure():
    """The mixed step's preemption branch: decode growth evicts the LAST
    running sequence — a mid-prefill long prompt — which must replay its
    whole prompt and still finish correctly."""
    core = EngineCore(
        CFG,
        tiny_engine(
            num_kv_blocks=12, max_model_len=64,
            scheduling="chunked", prefill_chunk=16,
        ),
        seed=0,
    )
    seqs = [
        core.add_request(_req(list(range(1, 17)), "a", max_tokens=24)),
        core.add_request(_req(list(range(20, 36)), "b", max_tokens=24)),
    ]
    # Let the short ones start decoding, then admit the long prompt
    # (collect the prefill-sampled first tokens the warmup steps emit).
    warm: dict[str, list[int]] = {"a": [], "b": []}
    while not all(s.prefill_done for s in seqs):
        for s, out in core.step():
            warm[s.request_id].extend(out.token_ids)
    seqs.append(core.add_request(_req(list(range(40, 80)), "c", max_tokens=8)))
    done, fin = run_to_completion(core, seqs, max_steps=4000)
    done["a"] = warm["a"] + done["a"]
    done["b"] = warm["b"] + done["b"]
    assert len(done["a"]) == 24 and len(done["b"]) == 24 and len(done["c"]) == 8
    assert fin == {"a": "length", "b": "length", "c": "length"}
    assert core.allocator.used_blocks == len(core.allocator._inactive)
    assert core.allocator._partials == 0


# -- mocker: saturated-mix A/B on the virtual clock ---------------------------


def _mock_seq(prompt, rid, max_tokens, block_size):
    from dynamo_tpu.llm.mocker.engine import _Seq
    from dynamo_tpu.tokens import TokenBlockSequence, compute_seq_hashes

    return _Seq(
        request_id=rid,
        prompt=prompt,
        max_tokens=max_tokens,
        out=asyncio.Queue(),
        seq=TokenBlockSequence(prompt, block_size),
        prompt_hashes=compute_seq_hashes(prompt, block_size),
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
    )


def _simulate_saturated_mix(scheduling, prefill_chunk, horizon_s=1.5, seed=7):
    """Drive the mocker's scheduler synchronously on a VIRTUAL clock
    (iteration cost model, no sleeping): steady B=32 short streams in a
    closed loop + a 2048-token prompt injected every 150 virtual ms.
    Returns percentile metrics per cohort."""
    import random

    from dynamo_tpu.llm.mocker.engine import MockEngineArgs, MockTpuEngine

    rng = random.Random(seed)
    args = MockEngineArgs(
        num_kv_blocks=8192, block_size=32, max_num_seqs=32,
        max_num_batched_tokens=2048, scheduling=scheduling,
        prefill_chunk=prefill_chunk, enable_prefix_caching=False,
    )
    eng = MockTpuEngine(args)
    vt = 0.0
    n = 0
    live = {}
    submit, first, prev = {}, {}, {}
    decode_gaps = []       # short-stream inter-token gaps (TPOT samples)
    long_ttfts = []
    cohort_ttfts = []      # shorts submitted while a long prefill is pending

    def long_prefill_pending():
        return any(
            rid.startswith("L") and rid not in first for rid in live
        )

    def add(short=True):
        nonlocal n
        n += 1
        isl, osl = (128, 32) if short else (2048, 4)
        rid = f"{'s' if short else 'L'}{n}"
        prompt = [rng.randrange(1, 250) for _ in range(isl)]
        s = _mock_seq(prompt, rid, osl, args.block_size)
        live[rid] = s
        submit[rid] = vt
        if short and long_prefill_pending():
            submit[rid + ":cohort"] = vt
        eng._waiting.append(s)

    for _ in range(32):
        add(True)
    next_long = 0.05
    while vt < horizon_s:
        if vt >= next_long:
            add(False)
            next_long += 0.15
        eng._admit()
        p, d = eng._step()
        vt += (
            args.base_iter_us
            + p * args.prefill_us_per_token
            + d * args.decode_us_per_seq
        ) / 1e6
        for rid, s in list(live.items()):
            finished = False
            while True:
                try:
                    item = s.out.get_nowait()
                except asyncio.QueueEmpty:
                    break
                if item is MockTpuEngine._FINISHED:
                    finished = True
                    continue
                if rid not in first:
                    first[rid] = vt
                    ttft = vt - submit[rid]
                    if rid.startswith("L"):
                        long_ttfts.append(ttft)
                    elif rid + ":cohort" in submit:
                        cohort_ttfts.append(ttft)
                elif rid.startswith("s"):
                    decode_gaps.append(vt - prev[rid])
                prev[rid] = vt
            if finished:
                del live[rid]
                if rid.startswith("s"):
                    add(True)  # closed loop: steady saturation

    def pct(vals, q):
        vals = sorted(vals)
        return vals[min(len(vals) - 1, int(q * len(vals)))]

    assert long_ttfts and cohort_ttfts and decode_gaps
    return {
        "long_ttft_p50": pct(long_ttfts, 0.5),
        "cohort_ttft_p50": pct(cohort_ttfts, 0.5),
        "tpot_p50": pct(decode_gaps, 0.5),
        "tpot_p99": pct(decode_gaps, 0.99),
    }


def test_mocker_saturated_mix_chunked_vs_waves():
    """The acceptance A/B on the mocker's virtual clock (deterministic —
    no wall-clock sleeps): steady B=32 shorts + injected 2048-token
    prompts. Chunked scheduling must cut the TTFT p50 of the cohort
    arriving around the long prefills (arrivals stop queueing behind
    whole waves) AND keep decode TPOT p99 within the <10%-regression
    bound (it actually improves: decodes never stall for a wave); the
    long prompts' own TTFT may trade a bounded amount for streaming."""
    waves = _simulate_saturated_mix("waves", 0)
    chunked = _simulate_saturated_mix("chunked", 256)

    # Saturated-cohort TTFT: the headline scheduling win.
    assert chunked["cohort_ttft_p50"] < waves["cohort_ttft_p50"], (
        chunked, waves,
    )
    # TPOT p99 of in-flight decodes: < 10% regression tolerated; measured
    # it improves (waves' p99 IS the wave-stall gap).
    assert chunked["tpot_p99"] < waves["tpot_p99"] * 1.10, (chunked, waves)
    # Steady-state TPOT p50 must not degrade at all.
    assert chunked["tpot_p50"] <= waves["tpot_p50"] * 1.05
    # The long prompts' own TTFT trades a bounded amount for streaming.
    assert chunked["long_ttft_p50"] < waves["long_ttft_p50"] * 1.5


def test_mocker_waves_mode_stalls_decodes():
    """Direct step-level property: with a prefill pending, a waves
    iteration decodes nothing; a chunked iteration decodes everyone."""
    from dynamo_tpu.llm.mocker.engine import MockEngineArgs, MockTpuEngine

    for scheduling, want_decodes in (("waves", 0), ("chunked", 1)):
        args = MockEngineArgs(
            num_kv_blocks=256, block_size=4, scheduling=scheduling,
            max_num_batched_tokens=64, prefill_chunk=8,
        )
        eng = MockTpuEngine(args)
        dec = _mock_seq([1] * 8, "dec", 16, 4)
        eng._waiting.append(dec)
        eng._admit()
        eng._step()  # prefill the decoder
        assert dec.prefill_done
        eng._waiting.append(_mock_seq([2] * 40, "long", 4, 4))
        eng._admit()
        p, d = eng._step()
        assert p > 0
        assert d == want_decodes, scheduling
        if scheduling == "chunked":
            assert eng.sched_stats["mixed_steps"] == 1
            st = eng.scheduler_stats()
            assert st["chunked_scheduling"] == 1
            assert st["chunked_prefills_in_flight"] == 1
