"""Disaggregated prefill/decode: remote prefill, KV block transfer,
local continuation — outputs must match aggregated serving exactly.

Parity: reference disagg flow `docs/architecture/disagg_serving.md` +
vLLM decode-first handlers (`handlers.py:113-168`); transfer layer is the
framework's host-staged DCN path instead of NIXL RDMA.
"""

import asyncio

import aiohttp
import pytest

from dynamo_tpu.backends.jax.main import run_jax_worker
from dynamo_tpu.frontend.main import run_frontend
from dynamo_tpu.llm.disagg import DisaggConfig, DisaggRouter
from dynamo_tpu.runtime import DistributedRuntime
from dynamo_tpu.runtime.store import StoreServer

pytestmark = [pytest.mark.e2e, pytest.mark.pre_merge]


def test_disagg_router_policy():
    r = DisaggRouter(DisaggConfig(max_local_prefill_length=50, max_prefill_queue_size=2))
    assert not r.should_remote_prefill(10)
    assert r.should_remote_prefill(100)
    assert not r.should_remote_prefill(100, queue_depth=5)
    r.config.enabled = False
    assert not r.should_remote_prefill(100)


class DisaggCluster:
    """Store + 1 prefill worker + 1 decode worker + frontend, in-process."""

    def __init__(self):
        self.store = StoreServer()
        self.runtimes: list[DistributedRuntime] = []
        self.tasks: list[asyncio.Task] = []
        self.base_url = ""
        self.prefill_core = None
        self.decode_core = None

    async def __aenter__(self) -> "DisaggCluster":
        await self.store.start()

        prefill_rt = await DistributedRuntime.create(self.store.address)
        self.runtimes.append(prefill_rt)
        served = asyncio.Event()
        cores: list = []
        self.tasks.append(
            asyncio.create_task(
                run_jax_worker(
                    prefill_rt, model_name="tinyjax", preset="tiny", seed=0,
                    role="prefill", served_event=served, core_out=cores,
                )
            )
        )
        await asyncio.wait_for(served.wait(), 30)
        self.prefill_core = cores[0]

        decode_rt = await DistributedRuntime.create(self.store.address)
        self.runtimes.append(decode_rt)
        served2 = asyncio.Event()
        cores2: list = []
        self.tasks.append(
            asyncio.create_task(
                run_jax_worker(
                    decode_rt, model_name="tinyjax", preset="tiny", seed=0,
                    role="decode",
                    disagg_config=DisaggConfig(max_local_prefill_length=16),
                    served_event=served2, core_out=cores2,
                )
            )
        )
        await asyncio.wait_for(served2.wait(), 30)
        self.decode_core = cores2[0]

        front_rt = await DistributedRuntime.create(self.store.address)
        self.runtimes.append(front_rt)
        ready = asyncio.Event()
        services: list = []
        self.tasks.append(
            asyncio.create_task(
                run_frontend(
                    front_rt, http_host="127.0.0.1", http_port=0,
                    router_mode="kv", ready_event=ready, service_out=services,
                )
            )
        )
        await asyncio.wait_for(ready.wait(), 10)
        self.base_url = f"http://127.0.0.1:{services[0].port}"
        async with aiohttp.ClientSession() as s:
            for _ in range(200):
                async with s.get(f"{self.base_url}/v1/models") as r:
                    if (await r.json())["data"]:
                        return self
                await asyncio.sleep(0.05)
        raise TimeoutError("model never appeared")

    async def __aexit__(self, *exc) -> None:
        for rt in self.runtimes:
            rt.signal_shutdown()
        await asyncio.sleep(0.1)
        for t in self.tasks:
            t.cancel()
        for rt in self.runtimes:
            try:
                await rt.shutdown()
            # dynalint: allow-broad-except(best-effort teardown; runtime may already be closed)
            except Exception:
                pass
        await self.store.stop()


LONG_PROMPT = (
    "Long prompts get disaggregated: this text is deliberately padded so "
    "its tokenization spans multiple complete KV blocks end to end."
)


async def _chat(session, base_url, content, max_tokens=8):
    body = {
        "model": "tinyjax",
        "messages": [{"role": "user", "content": content}],
        "max_tokens": max_tokens,
        "temperature": 0.0,
    }
    async with session.post(f"{base_url}/v1/chat/completions", json=body) as resp:
        assert resp.status == 200, await resp.text()
        return await resp.json()


async def test_disagg_matches_aggregated_and_transfers_blocks():
    # Aggregated ground truth (same seed/model).
    from tests.test_e2e_jax_worker import JaxCluster

    async with JaxCluster() as agg:
        async with aiohttp.ClientSession() as s:
            want = await _chat(s, agg.base_url, LONG_PROMPT, max_tokens=8)

    async with DisaggCluster() as c:
        async with aiohttp.ClientSession() as s:
            got = await _chat(s, c.base_url, LONG_PROMPT, max_tokens=8)

            # Identical content through the disaggregated path.
            assert got["choices"][0]["message"] == want["choices"][0]["message"]
            assert got["usage"]["completion_tokens"] == 8

            # The prefill actually ran remotely and its blocks moved:
            assert c.prefill_core.iterations > 0, "prefill fleet never ran"
            assert len(c.prefill_core.allocator._by_hash) > 0
            # Decode worker imported the transferred prefix blocks (they
            # are registered content in its allocator).
            assert len(c.decode_core.allocator._by_hash) > 0

            # Short prompts stay local: prefill fleet iteration count frozen.
            before = c.prefill_core.iterations
            out2 = await _chat(s, c.base_url, "hi", max_tokens=4)
            assert out2["usage"]["completion_tokens"] == 4
            assert c.prefill_core.iterations == before


async def test_disagg_decode_reuses_transferred_blocks():
    async with DisaggCluster() as c:
        async with aiohttp.ClientSession() as s:
            await _chat(s, c.base_url, LONG_PROMPT, max_tokens=4)
            # Repeat: everything already cached locally on the decode
            # worker -> no new remote prefill.
            before = c.prefill_core.iterations
            out = await _chat(s, c.base_url, LONG_PROMPT, max_tokens=4)
            assert c.prefill_core.iterations == before
            cached = out["usage"].get("prompt_tokens_details", {}).get("cached_tokens", 0)
            assert cached > 0


async def test_saturated_prefill_queue_flips_to_local():
    """Queue-depth safety valve (reference disagg_router.rs:24-100 +
    JetStream queue): with the prefill fleet's backlog above
    max_prefill_queue_size, a long prompt prefills LOCALLY."""
    async with DisaggCluster() as c:
        decode_rt = c.runtimes[1]
        real_queue_len = decode_rt.store.queue_len

        async def saturated(name: str) -> int:
            return 99  # simulate a deep fleet backlog

        decode_rt.store.queue_len = saturated
        try:
            async with aiohttp.ClientSession() as s:
                before = c.prefill_core.iterations
                out = await _chat(s, c.base_url, LONG_PROMPT, max_tokens=4)
                assert out["usage"]["completion_tokens"] == 4
                # Decision flipped: the prefill fleet never saw the request.
                assert c.prefill_core.iterations == before
        finally:
            decode_rt.store.queue_len = real_queue_len

        # Valve reopens with the backlog gone: next long prompt (distinct
        # content so nothing is locally cached) goes remote again.
        async with aiohttp.ClientSession() as s:
            before = c.prefill_core.iterations
            await _chat(s, c.base_url, LONG_PROMPT + " fresh tail content", max_tokens=4)
            assert c.prefill_core.iterations > before


async def test_clear_kv_blocks_reaches_disagg_fleet():
    """/clear_kv_blocks must cover BOTH sides of a disaggregated
    deployment: the decode worker's engine (not a -1 from a KeyError in
    from_wire) and the prefill fleet, which never registers a served
    model (advisor r4 medium; reference clear_kv_blocks.rs)."""
    async with DisaggCluster() as c:
        async with aiohttp.ClientSession() as s:
            # Populate caches on both sides.
            await _chat(s, c.base_url, LONG_PROMPT, max_tokens=4)
            assert len(c.prefill_core.allocator._by_hash) > 0
            assert len(c.decode_core.allocator._by_hash) > 0

            async with s.post(f"{c.base_url}/clear_kv_blocks") as resp:
                assert resp.status == 200
                body = await resp.json()
            cleared = body["cleared"]
            # Decode fleet: real counts, not -1.
            decode_counts = list(cleared["tinyjax"].values())
            assert decode_counts and all(n >= 0 for n in decode_counts)
            assert sum(decode_counts) > 0
            # Prefill fleet reported under its namespace key.
            prefill_counts = list(cleared["prefill:dynamo"].values())
            assert prefill_counts and all(n >= 0 for n in prefill_counts)
            assert sum(prefill_counts) > 0
            # Caches actually dropped on both engines.
            assert len(c.prefill_core.allocator._by_hash) == 0
            assert len(c.decode_core.allocator._by_hash) == 0


async def test_disagg_prefill_and_decode_spans_share_root_trace():
    """The tracing acceptance for disagg (ISSUE 2): spans recorded by the
    prefill fleet (queued remote prefill) and by the decode worker stitch
    into ONE trace under the frontend's root span — the traceparent rides
    the dataplane headers and the prefill work-queue task."""
    from dynamo_tpu import tracing

    tracing.configure(enabled=True, sample=1.0)
    async with DisaggCluster() as c:
        tracing.get_collector().clear()
        async with aiohttp.ClientSession() as s:
            await _chat(s, c.base_url, LONG_PROMPT + " span stitch", max_tokens=4)

        # Engine-side spans land when streams close; poll briefly.
        trace = []
        for _ in range(40):
            spans = tracing.get_collector().spans()
            roots = [sp for sp in spans if sp.name == "http"]
            if roots:
                tid = roots[-1].trace_id
                trace = [sp for sp in spans if sp.trace_id == tid]
                if {"prefill", "decode"} <= {sp.name for sp in trace}:
                    break
            await asyncio.sleep(0.05)

        names = {sp.name for sp in trace}
        assert {"http", "tokenize", "route", "disagg_decision", "prefill_handoff",
                "prefill", "decode"} <= names, names
        # The decision actually went remote, and both engine phases are in
        # the SAME trace even though prefill ran on the other worker.
        decision = next(sp for sp in trace if sp.name == "disagg_decision")
        assert decision.attrs["remote"] is True
        services = {sp.service for sp in trace}
        assert {"frontend", "router", "disagg", "engine"} <= services, services
        root = next(sp for sp in trace if sp.name == "http")
        assert root.parent_id is None
        for sp in trace:
            assert sp.trace_id == root.trace_id
        tracing.get_collector().clear()
