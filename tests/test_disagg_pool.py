"""Streaming disaggregation (ISSUE 17): chunk cursors, windowed
handoff, policy degradation, and the mocker disagg mirror.

Layers under test, bottom up: cursor publisher/watcher coalescing on
the event plane; StreamingHandoff's window loop and every fallback edge
(timeout, sever, regression); DisaggRouter's control-plane degradation
contract (pinned) and decision path; choose_decode_target's cost model;
and the full mocker prefill+decode pools streaming byte-identically to
an aggregated run with at least one chunk pulled before the prefill
completed.
"""

import asyncio
import json
from contextlib import suppress

import pytest

from dynamo_tpu.llm.disagg import DisaggConfig, DisaggRouter, choose_decode_target
from dynamo_tpu.llm.disagg_pool import (
    ChunkCursorPublisher,
    ChunkCursorWatcher,
    StreamingHandoff,
    disagg_cursor_subject,
)
from dynamo_tpu.runtime.store.client import WatchEvent

pytestmark = [pytest.mark.e2e, pytest.mark.pre_merge]


# ---------------------------------------------------------------------------
# Cursor plane: publisher coalescing + watcher advances
# ---------------------------------------------------------------------------


def test_cursor_publisher_coalesces_to_latest():
    pub = ChunkCursorPublisher(store=None, namespace="ns", worker_id=7)
    pub.note_nowait("r1", 2, False)
    pub.note_nowait("r1", 5, False)
    pub.note_nowait("r2", 1, False)
    assert pub._pending["r1"] == (5, False)
    assert len(pub._pending) == 2
    # A final cursor is never regressed by a stale commit arriving late.
    pub.note_nowait("r1", 8, True)
    pub.note_nowait("r1", 6, False)
    assert pub._pending["r1"] == (8, True)


async def test_cursor_roundtrip_over_store():
    from dynamo_tpu.runtime import DistributedRuntime
    from dynamo_tpu.runtime.store import StoreServer

    store = StoreServer()
    await store.start()
    rt = await DistributedRuntime.create(store.address)
    try:
        watcher = ChunkCursorWatcher(rt.store, "ns")
        await watcher.start()
        pub = ChunkCursorPublisher(rt.store, "ns", worker_id=3)
        await pub.start()
        pub.note_nowait("req-a", 4, False)
        got = await asyncio.wait_for(watcher.wait_advance("req-a", 0, 5.0), 10)
        assert got == (3, 4, False)
        pub.note_nowait("req-a", 9, True)
        got = await asyncio.wait_for(watcher.wait_advance("req-a", 4, 5.0), 10)
        assert got == (3, 9, True)
        assert pub.published_total == 2
        # A final cursor satisfies ANY wait (the handoff turns it into
        # the final window); only a missing cursor times out.
        assert await watcher.wait_advance("req-a", 99, 0.1) == (3, 9, True)
        with pytest.raises(asyncio.TimeoutError):
            await watcher.wait_advance("req-never", 0, 0.1)
        watcher.forget("req-a")
        assert watcher.cursor("req-a") is None
        await pub.stop()
        await watcher.stop()
    finally:
        rt.signal_shutdown()
        with suppress(Exception):  # dynalint: allow-broad-except(best-effort teardown; runtime may already be closed)
            await rt.shutdown()
        await store.stop()


def test_cursor_subject_is_per_namespace():
    assert disagg_cursor_subject("a") != disagg_cursor_subject("b")


# ---------------------------------------------------------------------------
# StreamingHandoff: window loop + fallback edges
# ---------------------------------------------------------------------------


class _FakeWatcher:
    """Scripted cursor advances; raises TimeoutError when exhausted."""

    def __init__(self, script):
        self.script = list(script)
        self.forgotten = []

    async def wait_advance(self, rid, beyond, timeout):
        while self.script:
            cur = self.script.pop(0)
            if cur[1] > beyond or cur[2]:
                return cur
        raise asyncio.TimeoutError

    def forget(self, rid):
        self.forgotten.append(rid)

    def cursor(self, rid):
        return None


class _FakePuller:
    def __init__(self, fail_at=None):
        self.windows = []
        self.fail_at = fail_at
        self.total_timeout_s = 5.0

    async def pull_held_window(self, _c, worker, rid, start, count, final=False):
        if self.fail_at is not None and len(self.windows) == self.fail_at:
            raise ConnectionError("severed mid-handoff")
        self.windows.append((start, count, final))
        return count


async def test_handoff_streams_windows_and_marks_early_chunks():
    # Cursor: 3 committed while running, then final at 5.
    watcher = _FakeWatcher([(1, 3, False), (1, 5, True)])
    puller = _FakePuller()
    h = StreamingHandoff(puller, watcher, None, chunk_blocks=2,
                         cursor_timeout_s=1.0)
    assert await h.run("rid") is True
    # Windows cover [0,5) exactly, final flag only on the last.
    assert puller.windows == [(0, 2, False), (2, 1, False), (3, 2, True)]
    assert h.stats.handoffs_streamed == 1
    assert h.stats.early_chunks == 2          # pulled before the final cursor
    assert h.stats.blocks_streamed == 5
    assert h.stats.handoffs_fallback == 0
    assert watcher.forgotten == ["rid"]


async def test_handoff_cursor_timeout_degrades_to_fallback():
    h = StreamingHandoff(_FakePuller(), _FakeWatcher([]), None,
                         cursor_timeout_s=0.01)
    assert await h.run("rid") is False
    assert h.stats.cursor_timeouts == 1
    assert h.stats.handoffs_fallback == 1


async def test_handoff_severed_window_degrades_to_fallback():
    watcher = _FakeWatcher([(1, 4, True)])
    puller = _FakePuller(fail_at=1)  # second window dies
    h = StreamingHandoff(puller, watcher, None, chunk_blocks=2,
                         cursor_timeout_s=1.0)
    assert await h.run("rid") is False
    assert h.stats.handoffs_fallback == 1
    assert h.stats.handoffs_streamed == 0


async def test_handoff_waits_out_cursor_regression():
    # Preempted prefill: cursor regresses to 1 then re-passes to 3.
    watcher = _FakeWatcher([(1, 2, False), (1, 1, False), (1, 3, True)])
    puller = _FakePuller()
    h = StreamingHandoff(puller, watcher, None, chunk_blocks=8,
                         cursor_timeout_s=1.0)
    assert await h.run("rid") is True
    assert puller.windows == [(0, 2, False), (2, 1, True)]


# ---------------------------------------------------------------------------
# Satellite 1 (pinned): policy deferral while the control plane degrades
# ---------------------------------------------------------------------------


def _put(cfg: dict) -> WatchEvent:
    return WatchEvent("put", "k", json.dumps(cfg).encode(), 1)


def test_disagg_policy_defers_resets_while_store_degraded():
    """PINNED degradation contract: a policy flip observed as a lease
    expiry, or drained while the store is dark, must NOT revert the live
    config to defaults — last-known-good policy keeps serving until the
    control plane recovers (ISSUE 15 semantics applied to disagg)."""
    r = DisaggRouter()
    assert r.apply_watch_event(_put({"max_local_prefill_length": 7}))
    assert r.config.max_local_prefill_length == 7

    # Lease-reason delete (conn-death revoke): deferred.
    assert not r.apply_watch_event(
        WatchEvent("delete", "k", b"", 2, reason="lease"), connected=True
    )
    assert r.config.max_local_prefill_length == 7
    # Explicit retraction drained while DISCONNECTED: deferred too.
    assert not r.apply_watch_event(
        WatchEvent("delete", "k", b"", 3, reason="del"), connected=False
    )
    assert r.config.max_local_prefill_length == 7
    assert r.deferred_resets == 2

    # Puts always apply, even while dark (operator data beats liveness
    # guesses).
    assert r.apply_watch_event(_put({"max_local_prefill_length": 9}),
                               connected=False)
    assert r.config.max_local_prefill_length == 9

    # An explicit delete on a LIVE session is a real retraction.
    assert r.apply_watch_event(
        WatchEvent("delete", "k", b"", 4, reason="del"), connected=True
    )
    assert r.config.max_local_prefill_length == DisaggConfig().max_local_prefill_length


def test_disagg_policy_rejects_malformed_config():
    r = DisaggRouter(DisaggConfig(max_local_prefill_length=7))
    assert not r.apply_watch_event(WatchEvent("put", "k", b"{not json", 1))
    assert not r.apply_watch_event(
        WatchEvent("put", "k", b'{"no_such_field": 1}', 2)
    )
    assert r.config.max_local_prefill_length == 7


# ---------------------------------------------------------------------------
# Satellite 3: decision path + span attribution, and the decode chooser
# ---------------------------------------------------------------------------


def test_should_remote_prefill_thresholds_and_queue_gate():
    r = DisaggRouter(DisaggConfig(max_local_prefill_length=50,
                                  max_prefill_queue_size=2))
    assert not r.should_remote_prefill(50)     # at threshold: local
    assert r.should_remote_prefill(51)         # past threshold: remote
    assert r.should_remote_prefill(51, queue_depth=2)   # queue at cap: ok
    assert not r.should_remote_prefill(51, queue_depth=3)  # over: gated
    r.config.enabled = False
    assert not r.should_remote_prefill(10_000)


def test_decide_records_attributed_span():
    from dynamo_tpu import tracing

    tracing.configure(enabled=True, sample=1.0)
    col = tracing.get_collector()
    col.clear()
    try:
        r = DisaggRouter(DisaggConfig(max_local_prefill_length=50))
        assert r.decide(100, 1, request_id="rid-1")
        assert not r.decide(10, 0, request_id="rid-2")
        spans = [s for s in col.spans() if s.name == "disagg_decision"]
        assert len(spans) == 2
        remote = next(s for s in spans if s.attrs["request_id"] == "rid-1")
        assert remote.attrs["remote"] is True
        assert remote.attrs["prefill_length"] == 100
        assert remote.attrs["queue_depth"] == 1
        local = next(s for s in spans if s.attrs["request_id"] == "rid-2")
        assert local.attrs["remote"] is False
    finally:
        col.clear()


def test_choose_decode_target_prices_transfer_plus_queue():
    prices = {1: 2.0, 2: 0.5, 3: 0.5}
    depths = {1: 0, 2: 10, 3: 1}
    # Pure transfer: worker 2/3 tie at 0.5ms/blk -> lowest id wins.
    assert choose_decode_target([1, 2, 3], 8, prices.__getitem__) == 2
    # Queue penalty flips the tie: worker 2's backlog prices it out.
    assert choose_decode_target(
        [1, 2, 3], 8, prices.__getitem__, queue_depth=depths.__getitem__
    ) == 3
    # Large enough transfers amortize queueing over the slow link.
    assert choose_decode_target(
        [1, 2], 1000, prices.__getitem__, queue_depth=depths.__getitem__
    ) == 2
    assert choose_decode_target([], 8, prices.__getitem__) is None


# ---------------------------------------------------------------------------
# Mocker mirror e2e: streaming disagg pools, byte-identical, chunk-early
# ---------------------------------------------------------------------------


class MockDisaggPools:
    """Store + mock prefill pool + mock decode pool. Long prompts with a
    tight prefill-chunk force multi-chunk remote prefills so the cursor
    plane carries real mid-prefill advances."""

    def __init__(self, prefill_chunk=8, block_size=8, streaming=True,
                 decode_config=None):
        from dynamo_tpu.llm.mocker import MockEngineArgs

        self.streaming = streaming
        self.decode_config = decode_config or DisaggConfig(
            max_local_prefill_length=16
        )
        self.args = MockEngineArgs(
            num_kv_blocks=512, block_size=block_size, speedup_ratio=20.0,
            scheduling="chunked", prefill_chunk=prefill_chunk,
        )

    async def __aenter__(self) -> "MockDisaggPools":
        from dynamo_tpu.backends.mocker import run_mocker
        from dynamo_tpu.runtime import DistributedRuntime
        from dynamo_tpu.runtime.store import StoreServer

        self.store = StoreServer()
        await self.store.start()
        self.runtimes = []
        self.tasks = []
        self.engines = []

        for role, component in (("prefill", "prefill"), ("decode", "decode")):
            rt = await DistributedRuntime.create(self.store.address)
            self.runtimes.append(rt)
            served = asyncio.Event()
            self.tasks.append(asyncio.create_task(run_mocker(
                rt, model_name="mock", namespace="dynamo",
                component=component, engine_args=self.args,
                served_event=served, engine_out=self.engines,
                obs_publish=False, role=role,
                disagg_config=self.decode_config,
            )))
            await asyncio.wait_for(served.wait(), 15)
        self.prefill_engine, self.decode_engine = self.engines
        self.decode_client = await (
            self.runtimes[1].namespace("dynamo").component("decode")
            .endpoint("generate").client()
        )
        return self

    async def __aexit__(self, *exc) -> None:
        from dynamo_tpu.runtime import chaos

        chaos.uninstall()
        for rt in self.runtimes:
            rt.signal_shutdown()
        await asyncio.sleep(0.05)
        for t in self.tasks:
            t.cancel()
        for rt in self.runtimes:
            with suppress(Exception):  # dynalint: allow-broad-except(best-effort teardown; runtime may already be closed)
                await rt.shutdown()
        await self.store.stop()

    async def generate(self, prompt, rid, max_tokens=6):
        from dynamo_tpu.llm.protocols.common import (
            PreprocessedRequest, SamplingOptions, StopConditions,
        )

        pre = PreprocessedRequest(
            model="mock", token_ids=list(prompt), request_id=rid,
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=max_tokens),
        )
        wid = self.runtimes[1].primary_lease_id
        toks = []
        stream = await self.decode_client.direct(wid, pre.to_wire())
        async for out in stream:
            toks.extend(out.get("token_ids") or [])
        return toks


async def _aggregated_tokens(prompt, rid, args, max_tokens=6):
    """Ground truth: the same request on one aggregated mock engine."""
    from dynamo_tpu.llm.mocker import MockTpuEngine
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest, SamplingOptions, StopConditions,
    )
    from dynamo_tpu.runtime import Context

    engine = MockTpuEngine(args)
    pre = PreprocessedRequest(
        model="mock", token_ids=list(prompt), request_id=rid,
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=max_tokens),
    )
    toks = []
    async for out in engine.generate(pre.to_wire(), Context(rid)):
        toks.extend(out.get("token_ids") or [])
    return toks


LONG_PROMPT = list(range(100, 180))  # 80 tokens = 10 blocks @ bs 8


async def test_mock_disagg_streams_chunks_byte_identically():
    """The tentpole acceptance, mocker-side: a long prompt routes to the
    prefill pool, committed KV windows stream to the decode worker WHILE
    prefill is still chunking (early_chunks > 0), the stream matches the
    aggregated run byte for byte, and the legacy reply-gated pull never
    runs."""
    async with MockDisaggPools(prefill_chunk=8) as c:
        want = await _aggregated_tokens(LONG_PROMPT, "agg-1", c.args)
        got = await c.generate(LONG_PROMPT, "dis-1")
        assert got == want, "disagg stream diverged from aggregated"

        st = c.decode_engine.disagg_handoff.stats
        assert st.handoffs_started == 1
        assert st.handoffs_streamed == 1, st.as_dict()
        assert st.early_chunks >= 1, (
            "no chunk was pulled before prefill completion — the handoff "
            f"did not overlap transfer with compute: {st.as_dict()}"
        )
        assert st.blocks_streamed == len(LONG_PROMPT) // c.args.block_size
        assert st.handoffs_fallback == 0
        # Prefill ran remotely; decode imported the streamed blocks.
        assert c.prefill_engine._iterations > 0
        assert c.decode_engine.peer_stats.blocks_pulled >= st.blocks_streamed
        # The prefill side actually published mid-prefill cursors.
        pub = c.prefill_engine.cursor_publisher
        assert pub.published_total >= 2  # at least one early + the final


async def test_mock_disagg_short_prompt_stays_local():
    async with MockDisaggPools() as c:
        short = list(range(10))
        want = await _aggregated_tokens(short, "agg-s", c.args)
        got = await c.generate(short, "dis-s")
        assert got == want
        assert c.decode_engine.disagg_handoff.stats.handoffs_started == 0
        assert c.prefill_engine._iterations == 0


async def test_mock_disagg_sever_mid_handoff_is_bit_identical():
    """Degradation contract at a chunk boundary: kill the window pull
    mid-handoff; the request must complete byte-identically through the
    reply-gated pull / local-recompute path."""
    from dynamo_tpu.runtime import chaos

    async with MockDisaggPools(prefill_chunk=8) as c:
        chaos.install(chaos.ChaosPlan.from_dict({
            "rules": [{
                "point": "kv_transfer.pull", "action": "sever",
                "count": 1,
            }]
        }))
        want = await _aggregated_tokens(LONG_PROMPT, "agg-x", c.args)
        got = await c.generate(LONG_PROMPT, "dis-x")
        assert got == want, "severed handoff broke byte identity"
        st = c.decode_engine.disagg_handoff.stats
        assert st.handoffs_fallback == 1


async def test_mock_disagg_streaming_disabled_uses_reply_gated_pull():
    """DYN_DISAGG_STREAMING=0: the pre-ISSUE-17 pull-after-prefill path,
    still byte-identical."""
    import os

    os.environ["DYN_DISAGG_STREAMING"] = "0"
    try:
        async with MockDisaggPools(prefill_chunk=8) as c:
            assert c.decode_engine.disagg_handoff is None
            want = await _aggregated_tokens(LONG_PROMPT, "agg-l", c.args)
            got = await c.generate(LONG_PROMPT, "dis-l")
            assert got == want
            assert c.decode_engine.peer_stats.pulls_succeeded >= 1
    finally:
        os.environ.pop("DYN_DISAGG_STREAMING", None)
