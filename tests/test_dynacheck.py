"""Tier-1 gate for dynacheck (ISSUE 9): the tree runs both engines
clean, the suppression inventory is pinned, every interprocedural rule
and every model invariant provably fires on a seeded violation, the
report is byte-deterministic, and the full run fits the CI budget.
"""

from __future__ import annotations

import functools
import sys
import time
from collections import Counter
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.dynacheck import config as C                         # noqa: E402
from tools.dynacheck.__main__ import main, run                  # noqa: E402
from tools.dynacheck import cache as CA                         # noqa: E402
from tools.dynacheck.callgraph import build_project             # noqa: E402
from tools.dynacheck.explore import explore                     # noqa: E402
from tools.dynacheck.interproc import run_all                   # noqa: E402
from tools.dynacheck.models.allocator import AllocatorModel     # noqa: E402
from tools.dynacheck.models.breaker import BreakerModel         # noqa: E402
from tools.dynacheck.models.cursor import (                     # noqa: E402
    CursorModel,
    PPWavefrontModel,
)
from tools.dynacheck.models.keepalive import KeepaliveModel     # noqa: E402
from tools.dynacheck.models.planner import PlannerModel         # noqa: E402
from tools.dynacheck.models.quarantine import QuarantineModel   # noqa: E402
from dynamo_tpu.planner.controller import PlannerController     # noqa: E402

FIXTURES = REPO / "tests" / "fixtures" / "dynacheck"


def fixture_findings(files: list[str], monkeypatch=None, hot=None, guarded=None):
    """Engine A over explicit fixture files (the tree scan excludes the
    fixture dir, so tests hand the files in directly)."""
    if monkeypatch is not None:
        if hot is not None:
            monkeypatch.setattr(C, "HOT_STEP_FUNCS", hot)
        if guarded is not None:
            monkeypatch.setattr(C, "GUARDED_BY", guarded)
    paths = [FIXTURES / f for f in files]
    project = build_project(paths, REPO)
    return run_all(project)


@functools.lru_cache(maxsize=1)
def tree_report():
    """Full-tree dynacheck, computed once — several tests consume it."""
    return run([REPO / "dynamo_tpu"], REPO, engine="all", use_cache=False)


# ---------------------------------------------------------------------------
# The tier-1 tree gate + pinned pragma inventory.
# ---------------------------------------------------------------------------

# Every in-source dynacheck pragma, pinned: {(path, rule): count}. Adding
# a pragma without updating this table fails the build — grandfathering
# stays explicit and reviewed, exactly like dynalint's allowlist.
PRAGMA_ALLOWLIST: dict[tuple[str, str], int] = {
    # The ring-prefill path is deliberately synchronous (sp engines keep
    # the classic loop; the single long prompt IS the step), so its two
    # landings are justified, not moved.
    ("dynamo_tpu/engine/core.py", "transitive-blocking"): 2,
    # import_blocks_direct takes two instances of EngineCore._step_lock
    # under a global id()-ordered acquisition — mutual pulls can never
    # deadlock, which the analysis cannot prove but review did.
    ("dynamo_tpu/engine/core.py", "lock-order"): 1,
}


def test_tree_is_clean():
    rep = tree_report()
    assert rep.findings == [], "\n".join(str(f) for f in rep.findings)
    for m in rep.models:
        assert m.ok, "\n".join(str(v) for v in m.violations)


def test_pragma_inventory_is_pinned():
    rep = tree_report()
    counts = Counter((p.path, p.rule) for p in rep.pragmas)
    assert dict(counts) == PRAGMA_ALLOWLIST, (
        "in-source dynacheck pragmas diverge from PRAGMA_ALLOWLIST; "
        f"actual={dict(counts)}"
    )


# Per-model floor on the explored state count: exhaustion with a
# suspiciously small space usually means the action set silently shrank.
# keepalive is a compact boolean protocol — its whole space IS small.
MODEL_FLOORS = {
    "allocator": 100, "cursor": 100, "pp-wavefront": 100, "breaker": 100,
    "quarantine": 100, "keepalive": 5, "planner": 100,
}


def test_models_exhaust_their_state_spaces():
    # The bounded exploration genuinely covers everything reachable: the
    # frontier empties before the depth bound for all six models, so
    # "no violation" means no violation anywhere, not "none within an
    # arbitrary horizon".
    rep = tree_report()
    assert {m.name for m in rep.models} == set(MODEL_FLOORS)
    for m in rep.models:
        assert m.exhausted, f"{m.name}: depth bound hit before exhaustion"
        assert m.states > MODEL_FLOORS[m.name], (
            f"{m.name}: suspiciously small state space ({m.states})"
        )


def test_call_graph_covers_the_engine():
    rep = tree_report()
    assert rep.functions > 500
    assert rep.resolved_edges > 500


# ---------------------------------------------------------------------------
# Engine A fixtures: each rule catches its seeded violation and stays
# quiet on the clean shapes.
# ---------------------------------------------------------------------------


def test_deadlock_cycle_detected():
    findings = fixture_findings(
        ["deadlock_pkg/engine_side.py", "deadlock_pkg/egress_side.py"]
    )
    lock_order = [f for f in findings if f.rule == C.RULE_LOCK_ORDER]
    assert len(lock_order) == 1, [str(f) for f in findings]
    msg = lock_order[0].message
    assert "_alock" in msg and "_block" in msg and "cycle" in msg


def test_three_lock_cycle_reported_not_crashed():
    # A cycle of 3+ locks whose edge order differs from the sorted node
    # order: the witness lookup must follow ACTUAL graph edges (a sorted
    # SCC is a set, not an edge sequence).
    findings = fixture_findings(["deadlock_pkg/three_way.py"])
    lock_order = [f for f in findings if f.rule == C.RULE_LOCK_ORDER]
    assert len(lock_order) == 1, [str(f) for f in findings]
    msg = lock_order[0].message
    assert "_xlock" in msg and "_ylock" in msg and "_zlock" in msg


def test_transitive_blocking_detected(monkeypatch):
    hot = {"fixtures/dynacheck/blocking_pkg/hot.py": {"plan_step"}}
    findings = fixture_findings(
        ["blocking_pkg/hot.py", "blocking_pkg/helper.py"],
        monkeypatch, hot=hot,
    )
    trans = [f for f in findings if f.rule == C.RULE_TRANSITIVE_BLOCKING]
    whats = sorted(f.message.split(" is reachable")[0] for f in trans)
    assert whats == ["np.asarray()", "time.sleep()"], [str(f) for f in findings]
    assert all("plan_step" in f.message and "assemble_tables" in f.message
               for f in trans)


def test_coroutine_leaks_detected():
    findings = fixture_findings(["coroleak_pkg/leaky.py"])
    leaks = [f for f in findings if f.rule == C.RULE_CORO_LEAK]
    assert len(leaks) == 2, [str(f) for f in findings]
    assert any("immediately" in f.message for f in leaks)      # dropped
    assert any("'pending'" in f.message for f in leaks)        # bound, unused


def test_cursor_discipline_detected():
    findings = fixture_findings(["cursor_pkg/writer.py"])
    cursor = [f for f in findings if f.rule == C.RULE_CURSOR]
    msgs = " | ".join(f.message for f in cursor)
    assert len(cursor) == 3, [str(f) for f in findings]
    assert "seq.processed" in msgs
    assert "seq.pinned_hashes" in msgs
    assert "blk.refcount" in msgs
    assert "reads_are_fine" not in msgs


def test_holds_lock_annotation_verified():
    findings = fixture_findings(["holdslock_pkg/unheld.py"])
    holds = [f for f in findings if f.rule == C.RULE_HOLDS_LOCK_UNVERIFIED]
    assert len(holds) == 1, [str(f) for f in findings]
    assert "bad_caller" in holds[0].message
    assert "good_caller" not in holds[0].message


def test_registry_drift_detected(monkeypatch):
    guarded = {
        "fixtures/dynacheck/holdslock_pkg/unheld.py": {
            ("Guarded", "table"): "_lock",          # healthy: no finding
            ("Guarded", "ghost_attr"): "_lock",     # never mutated: stale
            ("Guarded", "unlocked"): "_other_lock", # lock doesn't exist
            ("Vanished", "x"): "_lock",             # class doesn't exist
        },
    }
    findings = fixture_findings(
        ["holdslock_pkg/unheld.py"], monkeypatch, guarded=guarded,
    )
    drift = [f for f in findings if f.rule == C.RULE_REGISTRY_DRIFT]
    msgs = " | ".join(f.message for f in drift)
    assert len(drift) == 3, [str(f) for f in findings]
    assert "ghost_attr" in msgs and "Vanished" in msgs
    assert "table" not in msgs.replace("ghost_attr", "")


def test_real_guarded_by_registry_has_no_drift():
    # The hand-maintained registry (PR 1, five refactors ago) now fails
    # CI if an entry rots — this asserts today's registry is sound.
    rep = tree_report()
    assert not [f for f in rep.findings if f.rule == C.RULE_REGISTRY_DRIFT]


def test_wire_contract_detected(monkeypatch):
    monkeypatch.setattr(
        C, "WIRE_SCHEMA_FILE", "fixtures/dynacheck/wire_pkg/wire.py"
    )
    monkeypatch.setattr(
        C, "WIRE_PLANE_FILES",
        {"fixtures/dynacheck/wire_pkg/frames.py": ("alpha", "beta")},
    )
    findings = fixture_findings(["wire_pkg/wire.py", "wire_pkg/frames.py"])
    wirefs = [f for f in findings if f.rule == C.RULE_WIRE_CONTRACT]
    msgs = " | ".join(f.message for f in wirefs)
    assert "A_ORPHAN" in msgs and "produced here but consumed nowhere" in msgs
    assert "A_GHOST" in msgs and "consumed here but produced nowhere" in msgs
    assert "raw string literal 'b'" in msgs        # send-site backslide
    assert "conflicting meaning" in msgs           # cross-plane 't' collision
    assert "B_UNUSED" in msgs                      # registered, unreferenced
    # The healthy produced+consumed pair stays quiet.
    assert "A_BODY is" not in msgs and "A_TYPE is" not in msgs


def test_loop_affinity_detected(monkeypatch):
    monkeypatch.setattr(
        C, "LOOP_AFFINE",
        {"fixtures/dynacheck/affinity_pkg/threads.py": {
            ("Publisher", "_ringbuf"): "fixture ring buffer",
        }},
    )
    findings = fixture_findings(["affinity_pkg/threads.py"])
    aff = [f for f in findings if f.rule == C.RULE_LOOP_AFFINITY]
    assert len(aff) == 1, [str(f) for f in findings]
    msg = aff[0].message
    assert "_flush" in msg and "_drain_blocking" in msg
    # The on-loop write in publish() must stay quiet.
    assert "publish" not in msg


def test_config_knobs_detected(monkeypatch):
    monkeypatch.setattr(
        C, "KNOB_REGISTRY_FILE", "fixtures/dynacheck/knob_pkg/knobs.py"
    )
    monkeypatch.setattr(
        C, "KNOB_DOC_FILE", "tests/fixtures/dynacheck/knob_pkg/README.md"
    )
    findings = fixture_findings(["knob_pkg/knobs.py", "knob_pkg/reader.py"])
    knob = [f for f in findings if f.rule == C.RULE_CONFIG_KNOB]
    msgs = " | ".join(f.message for f in knob)
    assert "'FIX_GHOST' is read here but not registered" in msgs
    assert "'FIX_DIRECT' bypasses the registry" in msgs
    assert "literal default for 'FIX_ALPHA'" in msgs
    assert "dynamically-built name" in msgs
    assert "FIX_DEAD is registered but read nowhere" in msgs
    assert "FIX_SECRET is registered but undocumented" in msgs
    assert "documents FIX_ROT" in msgs
    # Exactly one unresolvable-name finding: the pragma'd read next to it
    # is suppressed.
    assert sum("dynamically-built" in f.message for f in knob) == 1
    # Clean reads (literal, module-constant) stay quiet.
    assert "FIX_BETA" not in msgs


# ---------------------------------------------------------------------------
# Engine B: every model invariant can actually fire. Each buggy variant
# seeds the exact bug class the invariant was written against.
# ---------------------------------------------------------------------------


class _DoubleReleaseModel(AllocatorModel):
    """Re-introduces the PR-3 bug: releasing a sequence's pins twice."""

    name = "allocator-double-release"

    def actions(self, state):
        acts = super().actions(state)
        for s in ("A", "B"):
            if state.started[s] and state.pinned[s]:
                acts.append(
                    (f"double_release_{s}", self._mk(self._double_release, s))
                )
        acts.sort(key=lambda kv: kv[0])
        return acts

    @staticmethod
    def _double_release(state, s):
        st = state.clone()
        pins = list(st.pinned[s])
        st.alloc.release(pins)
        st.alloc.release(pins)   # the double-release
        st.pinned[s] = []
        st.next_idx[s] = 0
        st.started[s] = False
        return st


def test_allocator_model_catches_double_release():
    m = _DoubleReleaseModel()
    m.max_depth = 8
    res = explore(m)
    assert res.violations, "double-release survived the allocator invariants"
    assert any("refcount" in str(v) for v in res.violations)


class _NoBarrierCursorModel(CursorModel):
    """Removes the verify barrier: plans over a data-dependent in-flight
    step, reading an overlay the commit will contradict."""

    name = "cursor-no-barrier"

    def actions(self, state):
        acts = super().actions(state)
        if (
            state.inflight is not None
            and not state.inflight.deterministic
            and state.finished is None
        ):
            acts.append(("plan_over_verify", lambda s: self._step_async(s, 1)))
        acts.sort(key=lambda kv: kv[0])
        return acts


def test_cursor_model_catches_missing_verify_barrier():
    m = _NoBarrierCursorModel()
    m.max_depth = 8
    res = explore(m)
    assert res.violations, "overlay misread survived the cursor invariants"
    assert any("diverged" in str(v) or "drift" in str(v) for v in res.violations)


class _RollbackFreeCursorModel(CursorModel):
    """Commits the optimistic advance instead of the stop-scanned one —
    i.e. deletes the num_computed_tokens rollback."""

    name = "cursor-no-rollback"

    def actions(self, state):
        acts = [(n, fn) for n, fn in super().actions(state)]
        if state.inflight is not None:
            acts.append(("commit_no_rollback", self._commit_no_rollback))
        acts.sort(key=lambda kv: kv[0])
        return acts

    @staticmethod
    def _commit_no_rollback(state):
        from dataclasses import replace
        plan = state.inflight
        if state.finished is not None:
            return replace(state, inflight=None)
        toks = plan.outputs  # NO stop scan: everything lands
        return replace(
            state, inflight=None,
            processed=state.processed + plan.n_steps,
            generated=state.generated + plan.n_steps,
            emitted=state.emitted + toks,
            pending=toks[-1],
        )


def test_cursor_model_catches_missing_rollback():
    m = _RollbackFreeCursorModel()
    m.max_depth = 6
    res = explore(m)
    assert res.violations, "missing rollback survived the cursor invariants"


class _NoRingRollbackCursorModel(CursorModel):
    """Deletes the history-ring rollback for ON-DEVICE drafting: a
    device-draft commit lands the device's full optimistic emission even
    when the host stop scan truncates it — the ring keeps the un-rolled
    tail and the host believes the device's cursor."""

    name = "cursor-no-ring-rollback"

    def actions(self, state):
        acts = [(n, fn) for n, fn in super().actions(state)]
        if state.inflight is not None and state.inflight.kind == "device-draft":
            acts.append(("commit_device_keep_ring", self._commit_keep_ring))
        acts.sort(key=lambda kv: kv[0])
        return acts

    @staticmethod
    def _commit_keep_ring(state):
        from dataclasses import replace
        plan = state.inflight
        if state.finished is not None:
            return replace(state, inflight=None)
        toks = plan.outputs  # NO truncation: the ring's tail all lands
        n = len(toks)
        return replace(
            state, inflight=None,
            processed=state.processed + n,
            generated=state.generated + n,
            emitted=state.emitted + toks,
            pending=toks[-1],
        )


def test_cursor_model_catches_missing_ring_rollback():
    m = _NoRingRollbackCursorModel()
    m.max_depth = 6
    res = explore(m)
    assert res.violations, "missing ring rollback survived the cursor invariants"
    assert any("diverged" in str(v) or "drift" in str(v) for v in res.violations)


class _NoWavefrontBarrierPPModel(PPWavefrontModel):
    """Drops the pp wavefront barrier (ISSUE 20): the stage ring starts
    a microbatch group's iteration t+1 BEFORE iteration t's drain is
    visible, so stage 0 embeds a stale sampled token (and reads a stale
    alive flag) — the exact interleaving the M >= pp wavefront schedule
    makes impossible."""

    name = "pp-wavefront-no-barrier"
    barrier = False


def test_pp_wavefront_model_catches_dropped_barrier():
    m = _NoWavefrontBarrierPPModel()
    m.max_depth = 8
    res = explore(m)
    assert res.violations, "stale-feedback entry survived the pp invariants"
    assert any("diverged" in str(v) for v in res.violations)


class _WedgingBreaker:
    """A breaker whose half-open probe never re-arms: a cancelled probe
    parks the address forever (the exact bug the stale-probe re-arm in
    dataplane.py exists for)."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half-open"

    def __init__(self, threshold, reset_s, clock):
        self.threshold = threshold
        self.reset_s = reset_s
        self._clock = clock
        self.state = self.CLOSED
        self.consecutive_failures = 0
        self.opens_total = 0
        self._opened_at = 0.0
        self._probe_at = 0.0

    def allow(self):
        if self.state == self.CLOSED:
            return True
        now = self._clock()
        if self.state == self.OPEN:
            if now - self._opened_at >= self.reset_s:
                self.state = self.HALF_OPEN
                self._probe_at = now
                return True
            return False
        return False  # half-open NEVER re-arms: the wedge

    def record_success(self):
        self.state = self.CLOSED
        self.consecutive_failures = 0

    def record_failure(self):
        self.consecutive_failures += 1
        if (
            self.state == self.HALF_OPEN
            or self.consecutive_failures >= self.threshold
        ):
            if self.state != self.OPEN:
                self.opens_total += 1
            self.state = self.OPEN
            self._opened_at = self._clock()


def test_breaker_model_catches_cancelled_probe_wedge():
    m = BreakerModel()
    m.breaker_cls = _WedgingBreaker
    m.max_depth = 10
    res = explore(m)
    assert res.violations, "the wedge survived the breaker invariants"
    assert any("wedged" in str(v) for v in res.violations)


class _RearmForeverQuarantineModel(QuarantineModel):
    """A due sweep that re-arms even when the probe says dead: the
    quarantine-forever bug the expiry path exists to prevent."""

    name = "quarantine-rearm-forever"
    sweep_rearms_dead = True


def test_quarantine_model_catches_rearm_forever():
    m = _RearmForeverQuarantineModel()
    m.max_depth = 10
    res = explore(m)
    assert res.violations, "re-arm-forever survived the quarantine invariants"
    assert any("quarantined forever" in str(v) for v in res.violations)


class _NoCancelKeepaliveModel(KeepaliveModel):
    """A reconnect that starts a fresh keepalive task without cancelling
    the old one: the double-beat bug."""

    name = "keepalive-no-cancel"
    cancel_before_restart = False


def test_keepalive_model_catches_double_task():
    res = explore(_NoCancelKeepaliveModel())
    assert res.violations, "double keepalive survived the invariants"
    assert any("tasks=2" in str(v) or "keepalive tasks" in str(v)
               for v in res.violations)


class _FreshIdKeepaliveModel(KeepaliveModel):
    """A resurrection that re-grants WITHOUT ``want=old id``: the server
    hands out a fresh id, orphaning the client's meta and leased-kv
    records."""

    name = "keepalive-fresh-id"
    regrant_with_want = False


def test_keepalive_model_catches_fresh_id_regrant():
    res = explore(_FreshIdKeepaliveModel())
    assert res.violations, "fresh-id re-grant survived the invariants"
    assert any("same_id=False" in str(v) or "different id" in str(v)
               for v in res.violations)


class _NoGuardController(PlannerController):
    """PlannerController._decide with every guard rail deleted: no
    cooldowns, no hysteresis streak."""

    def _decide(self, pool, desired, now, reason):
        if desired > pool.target:
            pool.target = min(desired, pool.target + self.config.max_step_up)
            pool.last_scale_up_t = now
            return self._note(pool, "scale_up", reason)
        if desired < pool.target:
            pool.target = max(desired, pool.target - self.config.max_step_down)
            pool.last_scale_down_t = now
            return self._note(pool, "scale_down", reason)
        return self._note(pool, "hold", reason)


class _NoGuardPlannerModel(PlannerModel):
    name = "planner-no-guards"
    controller_cls = _NoGuardController


def test_planner_model_catches_missing_guard_rails():
    m = _NoGuardPlannerModel()
    m.max_depth = 6
    res = explore(m)
    assert res.violations, "guard-rail removal survived the planner invariants"
    msgs = " | ".join(str(v) for v in res.violations)
    assert "cooldown" in msgs or "below-target cycle" in msgs


# ---------------------------------------------------------------------------
# Determinism + runtime budget + cache + CLI.
# ---------------------------------------------------------------------------


def test_report_is_byte_deterministic():
    a = run([REPO / "dynamo_tpu"], REPO, engine="all", use_cache=False)
    b = run([REPO / "dynamo_tpu"], REPO, engine="all", use_cache=False)
    assert a.render(show_pragmas=True) == b.render(show_pragmas=True)


def test_full_tree_run_fits_ci_budget():
    t0 = time.monotonic()
    run([REPO / "dynamo_tpu"], REPO, engine="all", use_cache=False)
    elapsed = time.monotonic() - t0
    assert elapsed < 60.0, f"full-tree dynacheck took {elapsed:.1f}s (budget 60s)"


def test_cache_round_trips(tmp_path):
    rep = tree_report()
    CA.store(tmp_path, "k1", rep.findings, rep.pragmas,
             rep.functions, rep.resolved_edges)
    got = CA.load(tmp_path, "k1")
    assert got is not None
    findings, pragmas, functions, edges = got
    assert findings == rep.findings
    assert pragmas == rep.pragmas
    assert (functions, edges) == (rep.functions, rep.resolved_edges)
    assert CA.load(tmp_path, "other-key") is None


def test_cache_key_tracks_sources(tmp_path):
    f1 = tmp_path / "a.py"
    f1.write_text("x = 1\n")
    k1 = CA.tree_key([f1], tmp_path)
    f1.write_text("x = 2\n")
    k2 = CA.tree_key([f1], tmp_path)
    assert k1 != k2


def test_cli_exits_clean_on_tree():
    assert main([str(REPO / "dynamo_tpu"), "--no-cache"]) == 0


def test_cli_rejects_unknown_rule():
    assert main(["--rules", "not-a-rule", str(REPO / "dynamo_tpu")]) == 2


def test_cli_rejects_missing_path():
    assert main([str(REPO / "no_such_dir_xyz")]) == 2


def test_cache_key_tracks_readme(tmp_path):
    # The config-knob rule reads the README, so a doc edit must miss.
    f1 = tmp_path / "a.py"
    f1.write_text("x = 1\n")
    (tmp_path / "README.md").write_text("docs v1\n")
    k1 = CA.tree_key([f1], tmp_path)
    (tmp_path / "README.md").write_text("docs v2\n")
    k2 = CA.tree_key([f1], tmp_path)
    assert k1 != k2


def test_knobs_md_matches_readme_block():
    # The README's generated block IS the emitter's output (the CI
    # knob-drift gate, exercised in-process).
    from tools.dynacheck.__main__ import KNOBS_BEGIN, KNOBS_END, knobs_markdown

    want = knobs_markdown()
    text = (REPO / "README.md").read_text(encoding="utf-8")
    begin, end = text.find(KNOBS_BEGIN), text.find(KNOBS_END)
    assert begin >= 0 and end > begin, "README lacks the knobs markers"
    assert text[begin:end + len(KNOBS_END)] + "\n" == want


def test_knob_table_covers_every_registered_knob():
    from dynamo_tpu import knobs
    from tools.dynacheck.__main__ import knobs_markdown

    table = knobs_markdown()
    for name in knobs.KNOBS:
        assert f"`{name}`" in table, f"{name} missing from the knob table"


def test_cli_knob_drift_exits_clean():
    assert main(["--knob-drift"]) == 0


def test_malformed_pragma_is_a_finding(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "# dynacheck: allow-unknown-rule(nope)\n"
        "# dynacheck: allow-cursor-discipline()\n"
        "x = 1\n"
    )
    project = build_project([bad], tmp_path)
    findings = run_all(project)
    assert [f.rule for f in findings] == ["malformed-pragma"] * 2
    assert project.pragmas == []
