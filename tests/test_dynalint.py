"""Tier-1 gate: dynalint over the real tree + per-detector fixture tests.

The tree test is the contract the whole suite enforces: ``python -m
tools.dynalint dynamo_tpu/ tests/`` must exit clean, and every in-source
suppression pragma must be registered in the PRAGMA_ALLOWLIST table below
— adding a new pragma without updating the table fails the build, so
grandfathering stays explicit and reviewed.
"""

from __future__ import annotations

import functools
import sys
from collections import Counter
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.dynalint import config as C                     # noqa: E402
from tools.dynalint.linter import lint_file, lint_paths    # noqa: E402

FIXTURES = REPO / "tests" / "fixtures" / "dynalint"


def rules_at(path: Path) -> list[str]:
    return [f.rule for f in lint_file(path, REPO).findings]


@functools.lru_cache(maxsize=1)
def tree_result():
    """Full-tree lint, computed once — three tests consume it."""
    return lint_paths([REPO / "dynamo_tpu", REPO / "tests"], REPO)


# ---------------------------------------------------------------------------
# The suppression tables (explicit, per-file/per-rule).
# ---------------------------------------------------------------------------

# Findings grandfathered WITHOUT an in-source pragma: {(path, rule): count}.
# Empty today — every finding in the tree was either fixed or carries an
# inline pragma with a reason. New entries need a review justifying why an
# inline pragma is not possible.
GRANDFATHERED: dict[tuple[str, str], int] = {}

# Every in-source pragma, pinned: {(path, kind, arg): count}.
PRAGMA_ALLOWLIST: dict[tuple[str, str, str], int] = {
    # EngineCore helpers called only from under _step_lock (step path and
    # the disagg transfer endpoints lock before calling). Grown by the
    # dynacheck holds-lock-unverified sweep (ISSUE 9): every annotation
    # is now CHECKED along call paths, so the whole plan/commit chain
    # carries it explicitly — _step_locked/_step_async/_plan_step/
    # _plan_waves/_maybe_ring_prefill/_run_ring_prefill, the four
    # per-scheduler commit closures, _apply_verify_row, _account_transfer,
    # plus the original _finish/_sweep_expired_holds/transfer endpoints.
    # +1 in ISSUE 12: the universal-megastep fused commit closure
    # (_plan_fused.commit) joins the verified chain.
    ("dynamo_tpu/engine/core.py", "holds-lock", "_step_lock"): 16,
    # Intentional syncs inside blocking-host-sync hot paths: the
    # double-buffered landing point (_PendingFetch.land — tokens +
    # batched logprobs, and land_aux for the on-device draft round
    # counters, ISSUE 18), np.asarray over host block-id lists (dispatch
    # assembly + ring prefill), and the host-tier page staging in
    # _stage_page (host buffer, not a device array).
    ("dynamo_tpu/engine/core.py", "sync-ok", ""): 6,
    # Host-buffer asarray sites cleared by the dynacheck transitive-
    # blocking sweep: packed-page unpacking and pp microbatch planning
    # operate on host arrays only.
    ("dynamo_tpu/engine/kv_quant.py", "sync-ok", ""): 1,
    ("dynamo_tpu/parallel/pipeline.py", "sync-ok", ""): 2,
    # Deliberately deadline-free awaits (unbounded-await rule): server
    # read loops idling between frames, engine-local queues whose
    # producer is in-process, and push-subscription streams. The
    # consumer-facing bounds live elsewhere (ResponseStream's per-token
    # stall deadline, Subscription.get(timeout)).
    ("dynamo_tpu/engine/engine.py", "unbounded-ok", ""): 1,
    ("dynamo_tpu/llm/disagg_pool/cursor.py", "unbounded-ok", ""): 1,
    ("dynamo_tpu/llm/mocker/engine.py", "unbounded-ok", ""): 1,
    ("dynamo_tpu/runtime/dataplane.py", "unbounded-ok", ""): 2,
    ("dynamo_tpu/runtime/store/client.py", "unbounded-ok", ""): 2,
    ("dynamo_tpu/runtime/store/server.py", "unbounded-ok", ""): 2,
    # The netcost fleet view is a best-effort read of the worker
    # monitor: any failure degrades to local pull observations —
    # routing must never break because a metrics view did (ISSUE 14).
    ("dynamo_tpu/llm/kv_router/netcost.py", "allow", "broad-except"): 1,
    # Best-effort teardown in e2e harnesses: the runtime may already be
    # closed by the time __aexit__ re-closes it.
    ("tests/test_disagg.py", "allow", "broad-except"): 1,
    ("tests/test_disagg_pool.py", "allow", "broad-except"): 2,
    ("tests/test_e2e_frontend.py", "allow", "broad-except"): 1,
    ("tests/test_e2e_jax_worker.py", "allow", "broad-except"): 1,
    ("tests/test_grpc_kserve.py", "allow", "broad-except"): 1,
    ("tests/test_openai_surface.py", "allow", "broad-except"): 1,
    ("tests/test_kv_pool.py", "allow", "broad-except"): 1,
    ("tests/test_peer_kv.py", "allow", "broad-except"): 1,
    # The no-op micro-bench intentionally discards the shared NOOP_SPAN.
    ("tests/test_tracing.py", "allow", "unclosed-span"): 1,
}


# ---------------------------------------------------------------------------
# The tier-1 tree gate.
# ---------------------------------------------------------------------------


def test_tree_is_clean():
    res = tree_result()
    budget = dict(GRANDFATHERED)
    leaked = []
    for f in res.findings:
        key = (f.path, f.rule)
        if budget.get(key, 0) > 0:
            budget[key] -= 1
        else:
            leaked.append(f)
    assert not leaked, "dynalint findings:\n" + "\n".join(str(f) for f in leaked)
    unused = {k: v for k, v in budget.items() if v > 0}
    assert not unused, f"stale GRANDFATHERED entries (tighten the table): {unused}"


def test_pragma_inventory_is_pinned():
    res = tree_result()
    counts = Counter((p.path, p.kind, p.arg) for p in res.pragmas)
    assert dict(counts) == PRAGMA_ALLOWLIST, (
        "in-source dynalint pragmas diverge from PRAGMA_ALLOWLIST; "
        f"actual={dict(counts)}"
    )


def test_registry_covers_promised_modules():
    # The GUARDED_BY registry must keep covering the modules the lint was
    # built for (ISSUE 1): engine core, block allocator, kv_router.
    files = set(C.GUARDED_BY)
    assert "dynamo_tpu/engine/core.py" in files
    assert "dynamo_tpu/engine/block_allocator.py" in files
    assert any(f.startswith("dynamo_tpu/llm/kv_router/") for f in files)


# ---------------------------------------------------------------------------
# Detector fixtures: each rule catches its seeded violations and stays
# quiet on the clean twin.
# ---------------------------------------------------------------------------


def test_fire_and_forget_detector():
    bad = rules_at(FIXTURES / "fire_and_forget_bad.py")
    assert bad == [C.RULE_FIRE_AND_FORGET] * 4, bad
    assert rules_at(FIXTURES / "fire_and_forget_ok.py") == []


def test_blocking_in_async_detector():
    bad = rules_at(FIXTURES / "blocking_async_bad.py")
    assert bad == [C.RULE_BLOCKING_IN_ASYNC] * 4, bad
    assert rules_at(FIXTURES / "blocking_async_ok.py") == []


def test_broad_except_detector():
    bad = rules_at(FIXTURES / "broad_except_bad.py")
    assert bad == [C.RULE_BROAD_EXCEPT] * 4, bad
    assert rules_at(FIXTURES / "broad_except_ok.py") == []


def test_lock_discipline_detector(monkeypatch):
    entries = {
        ("Guarded", "_table"): "_lock",
        ("Guarded", "count"): "_lock",
        (None, "_handle"): "_glock",
    }
    registry = dict(C.GUARDED_BY)
    registry["fixtures/dynalint/lock_discipline_bad.py"] = entries
    registry["fixtures/dynalint/lock_discipline_ok.py"] = entries
    monkeypatch.setattr(C, "GUARDED_BY", registry)
    bad = rules_at(FIXTURES / "lock_discipline_bad.py")
    assert bad == [C.RULE_LOCK_DISCIPLINE] * 6, bad
    assert rules_at(FIXTURES / "lock_discipline_ok.py") == []


def test_jax_pitfall_detector():
    bad = rules_at(FIXTURES / "jax_pitfall_bad.py")
    assert bad == [C.RULE_JAX_PITFALL] * 5, bad
    assert rules_at(FIXTURES / "jax_pitfall_ok.py") == []


def test_unclosed_span_detector():
    bad = rules_at(FIXTURES / "unclosed_span_bad.py")
    assert bad == [C.RULE_UNCLOSED_SPAN] * 4, bad
    assert rules_at(FIXTURES / "unclosed_span_ok.py") == []


def test_blocking_host_sync_detector():
    bad = rules_at(FIXTURES / "host_sync_bad.py")
    assert bad == [C.RULE_HOST_SYNC] * 4, bad
    assert rules_at(FIXTURES / "host_sync_ok.py") == []


def test_unbounded_await_detector():
    bad = rules_at(FIXTURES / "unbounded_await_bad.py")
    assert bad == [C.RULE_UNBOUNDED_AWAIT] * 4, bad
    assert rules_at(FIXTURES / "unbounded_await_ok.py") == []


def test_host_sync_hot_paths_cover_engine_core():
    # The rule was built for the async engine's plan/dispatch side
    # (ISSUE 5); the megastep plan/dispatch path (ISSUE 7) rides the
    # same registry — a blocking sync inside a k-iteration dispatch
    # would serialize k steps of host work with device compute.
    assert "dynamo_tpu/engine/core.py" in C.HOT_STEP_FUNCS
    funcs = C.HOT_STEP_FUNCS["dynamo_tpu/engine/core.py"]
    assert {
        "_dispatch_ragged", "_dispatch_megastep", "_plan_megastep",
        "_plan_step",
    } <= funcs


def test_pragma_spans_cover_multiline_statements():
    # The line-based matcher missed a pragma on the opening line of a
    # wrapped call whenever the flagged node reported a later lineno;
    # pragmas now anchor to the statement's FULL line span (ISSUE 9).
    ok = lint_file(FIXTURES / "pragma_multiline_ok.py", REPO)
    assert ok.findings == [], [str(f) for f in ok.findings]
    assert len(ok.pragmas) == 3
    # ...and the span anchoring neither mutes unpragma'd statements nor
    # lets a pragma bleed beyond its own statement: a pragma inside a
    # function body must not blanket its siblings, a TRAILING pragma on
    # the last line of a multi-line statement must not cover the next
    # sibling statement, and a pragma on a multi-line def/with HEADER
    # line must not cover the first body statement.
    bad = rules_at(FIXTURES / "pragma_multiline_bad.py")
    assert bad == [C.RULE_BLOCKING_IN_ASYNC] * 5, bad


def test_malformed_pragmas_are_findings():
    res = lint_file(FIXTURES / "pragma_malformed.py", REPO)
    rules = [f.rule for f in res.findings]
    assert rules.count("malformed-pragma") == 3, rules
    # The empty-reason pragma must NOT suppress the violation under it.
    assert C.RULE_BROAD_EXCEPT in rules
    assert res.pragmas == []


def test_cli_exits_clean_on_tree():
    from tools.dynalint.__main__ import main

    assert main([str(REPO / "dynamo_tpu"), str(REPO / "tests")]) == 0


def test_cli_rejects_unknown_rule_filter():
    from tools.dynalint.__main__ import main

    assert main(["--rules", "not-a-rule", str(REPO / "tools")]) == 2


# ---------------------------------------------------------------------------
# Regression tests for the satellite fixes that ride with this lint PR.
# ---------------------------------------------------------------------------


def test_pp_int8_constructs():
    # The carve-out this test originally pinned is LIFTED (ISSUE 20):
    # int8 {w, scale} weight pages now shard per pipeline stage and the
    # engine constructs. The still-unsupported combos keep pointed
    # errors — pinned (both directions) by tests/test_pp_megastep.py.
    import jax

    from dynamo_tpu.engine.config import tiny_engine, tiny_model
    from dynamo_tpu.engine.core import EngineCore
    from dynamo_tpu.engine.model import init_params, quantize_params
    from dynamo_tpu.parallel.pipeline import make_pp_mesh

    cfg = tiny_model()
    params = quantize_params(init_params(jax.random.PRNGKey(0), cfg))
    core = EngineCore(cfg, tiny_engine(), params=params,
                      pp_mesh=make_pp_mesh(2))
    assert core.scheduler_stats()["pp_stages"] == 2


def test_eos_for_fails_fast_on_broken_tokenizer(tmp_path):
    from dynamo_tpu.backends.jax.main import _eos_for
    from dynamo_tpu.llm.tokenizer import ByteTokenizer

    assert _eos_for("byte") == (ByteTokenizer.EOS,)
    # Weights-only checkpoint dir still degrades gracefully (byte-level).
    assert _eos_for(str(tmp_path)) == (ByteTokenizer.EOS,)
    # A genuinely broken spec now fails worker startup instead of silently
    # serving without EOS for the process lifetime (ADVICE r5).
    with pytest.raises((OSError, ValueError)):
        _eos_for(str(tmp_path / "missing.gguf"))


def test_plan_microbatches_masks_zero_query_kv():
    import numpy as np

    from dynamo_tpu.parallel.pipeline import plan_microbatches

    # Two sequences, 8 tokens each, split into 2 chunks of 8 rows: each
    # chunk contains exactly one sequence, so the other sequence has zero
    # query rows there and its kv_len must be pinned to the benign 1.
    T = 16
    plan = plan_microbatches(
        tokens=np.arange(T, dtype=np.int32),
        positions=np.arange(T, dtype=np.int32),
        write_pages=np.zeros(T, np.int32),
        write_offs=np.arange(T, dtype=np.int32) % 8,
        kv_lens=np.array([8, 20], np.int32),   # seq1 carries 12 prior kv
        cu_q_lens=np.array([0, 8, 16], np.int32),
        num_seqs=2,
        last_rows=np.array([7, 15], np.int32),
        n_micro=2,
        garbage_block=31,
    )
    assert plan.kv_lens[0, 0] == 8    # seq0 fully in chunk 0
    assert plan.kv_lens[0, 1] == 1    # seq1 absent from chunk 0: masked
    assert plan.kv_lens[1, 0] == 1    # seq0 absent from chunk 1: masked
    assert plan.kv_lens[1, 1] == 20   # seq1 fully through chunk 1
