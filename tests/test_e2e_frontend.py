"""E2E: OpenAI frontend + mocker workers over the full runtime stack.

HTTP → preprocess → KV router → data plane → mock engine → detok → SSE.
Parity: reference `tests/router/test_router_e2e_with_mockers.py:24-80`
(N mockers + real frontend + concurrent streaming requests, GPU-free).
"""

import asyncio
import json

import aiohttp
import pytest

from dynamo_tpu.backends.mocker import run_mocker
from dynamo_tpu.frontend.main import run_frontend
from dynamo_tpu.llm.mocker import MockEngineArgs
from dynamo_tpu.runtime import DistributedRuntime
from dynamo_tpu.runtime.store import StoreServer

pytestmark = [pytest.mark.e2e, pytest.mark.pre_merge]

FAST_ARGS = MockEngineArgs(num_kv_blocks=2048, block_size=8, speedup_ratio=200.0)


class Cluster:
    """In-process cluster: store + frontend + N mocker workers."""

    def __init__(self, num_workers: int = 2, router_mode: str = "kv"):
        self.num_workers = num_workers
        self.router_mode = router_mode
        self.store = StoreServer()
        self.runtimes: list[DistributedRuntime] = []
        self.tasks: list[asyncio.Task] = []
        self.base_url = ""

    async def __aenter__(self) -> "Cluster":
        await self.store.start()
        for i in range(self.num_workers):
            rt = await DistributedRuntime.create(self.store.address)
            self.runtimes.append(rt)
            served = asyncio.Event()
            self.tasks.append(
                asyncio.create_task(
                    run_mocker(rt, model_name="mock", engine_args=FAST_ARGS, served_event=served)
                )
            )
            await asyncio.wait_for(served.wait(), 10)
        front_rt = await DistributedRuntime.create(self.store.address)
        self.runtimes.append(front_rt)
        ready = asyncio.Event()
        services: list = []
        self.tasks.append(
            asyncio.create_task(
                run_frontend(
                    front_rt,
                    http_host="127.0.0.1",
                    http_port=0,
                    router_mode=self.router_mode,
                    ready_event=ready,
                    service_out=services,
                )
            )
        )
        await asyncio.wait_for(ready.wait(), 10)
        self.base_url = f"http://127.0.0.1:{services[0].port}"
        # Frontend needs the model discovered before requests fly.
        async with aiohttp.ClientSession() as s:
            for _ in range(200):
                async with s.get(f"{self.base_url}/v1/models") as r:
                    data = await r.json()
                    if data["data"]:
                        return self
                await asyncio.sleep(0.05)
        raise TimeoutError("model never appeared on frontend")

    async def __aexit__(self, *exc) -> None:
        for rt in self.runtimes:
            rt.signal_shutdown()
        await asyncio.sleep(0.1)
        for t in self.tasks:
            t.cancel()
        for rt in self.runtimes:
            try:
                await rt.shutdown()
            # dynalint: allow-broad-except(best-effort teardown; runtime may already be closed)
            except Exception:
                pass
        await self.store.stop()


async def _chat(session, base_url, content, stream=False, max_tokens=8, extra=None):
    body = {
        "model": "mock",
        "messages": [{"role": "user", "content": content}],
        "max_tokens": max_tokens,
        "stream": stream,
    }
    if extra:
        body.update(extra)
    async with session.post(f"{base_url}/v1/chat/completions", json=body) as resp:
        if stream:
            text = ""
            chunks = 0
            async for line in resp.content:
                line = line.decode().strip()
                if not line.startswith("data: "):
                    continue
                payload = line[len("data: "):]
                if payload == "[DONE]":
                    break
                chunk = json.loads(payload)
                chunks += 1
                for c in chunk["choices"]:
                    text += c["delta"].get("content") or ""
            return resp.status, text, chunks
        return resp.status, await resp.json(), 0


async def test_single_request_roundtrip():
    async with Cluster(num_workers=1) as cluster:
        async with aiohttp.ClientSession() as s:
            status, body, _ = await _chat(s, cluster.base_url, "hello", max_tokens=6)
            assert status == 200
            msg = body["choices"][0]["message"]
            assert msg["role"] == "assistant"
            assert msg["content"] == "abcdef"  # mocker emits a,b,c,...
            assert body["choices"][0]["finish_reason"] == "length"
            assert body["usage"]["completion_tokens"] == 6


async def test_streaming_sse():
    async with Cluster(num_workers=1) as cluster:
        async with aiohttp.ClientSession() as s:
            status, text, chunks = await _chat(
                s, cluster.base_url, "stream me", stream=True, max_tokens=10
            )
            assert status == 200
            assert text == "abcdefghij"
            assert chunks >= 10  # role chunk + per-token deltas + finish


async def test_concurrent_streaming_requests_kv_routed():
    """100 concurrent streams across 2 mockers with KV routing."""
    async with Cluster(num_workers=2, router_mode="kv") as cluster:
        async with aiohttp.ClientSession() as s:
            async def one(i):
                # Shared prefix families exercise the radix index.
                prompt = f"family-{i % 4} " * 20 + f"tail-{i}"
                return await _chat(s, cluster.base_url, prompt, stream=True, max_tokens=5)

            results = await asyncio.gather(*(one(i) for i in range(100)))
            assert all(status == 200 for status, _, _ in results)
            assert all(text == "abcde" for _, text, _ in results)


async def test_unknown_model_404():
    async with Cluster(num_workers=1) as cluster:
        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"{cluster.base_url}/v1/chat/completions",
                json={"model": "nope", "messages": [{"role": "user", "content": "x"}]},
            ) as resp:
                assert resp.status == 404


async def test_invalid_sampling_params_rejected():
    async with Cluster(num_workers=1) as cluster:
        async with aiohttp.ClientSession() as s:
            for bad in (
                {"max_tokens": -5},
                {"max_tokens": 0},
                {"temperature": -1.0},
                {"top_p": 0.0},
                {"n": 0},
            ):
                async with s.post(
                    f"{cluster.base_url}/v1/chat/completions",
                    json={
                        "model": "mock",
                        "messages": [{"role": "user", "content": "x"}],
                        **bad,
                    },
                ) as resp:
                    assert resp.status == 400, bad


async def test_completions_endpoint():
    async with Cluster(num_workers=1) as cluster:
        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"{cluster.base_url}/v1/completions",
                json={"model": "mock", "prompt": "complete this", "max_tokens": 4},
            ) as resp:
                assert resp.status == 200
                body = await resp.json()
                assert body["choices"][0]["text"] == "abcd"


async def test_metrics_endpoint_exposes_frontend_series():
    async with Cluster(num_workers=1) as cluster:
        async with aiohttp.ClientSession() as s:
            await _chat(s, cluster.base_url, "hi", stream=True, max_tokens=3)
            async with s.get(f"{cluster.base_url}/metrics") as resp:
                text = await resp.text()
                assert "dynamo_frontend_requests_total" in text
                assert "dynamo_frontend_time_to_first_token_seconds" in text


async def test_embeddings_against_mocker_fleet():
    """/v1/embeddings works on mocker fleets too (deterministic synthetic
    vectors), keeping the full OpenAI surface exercisable without TPUs."""
    async with Cluster(num_workers=1) as c:
        async with aiohttp.ClientSession() as s:
            body = {"model": "mock", "input": "embed this"}
            async with s.post(f"{c.base_url}/v1/embeddings", json=body) as r:
                assert r.status == 200, await r.text()
                one = (await r.json())["data"][0]["embedding"]
            async with s.post(f"{c.base_url}/v1/embeddings", json=body) as r:
                two = (await r.json())["data"][0]["embedding"]
            assert one == two and len(one) == 64


async def test_clear_kv_blocks_against_mocker_fleet():
    """The admin clear endpoint must work on mocker fleets too (in-flight
    sequences keep their pinned blocks; only the unpinned cache drops)."""
    async with Cluster(num_workers=2) as c:
        async with aiohttp.ClientSession() as s:
            body = {
                "model": "mock",
                "messages": [{"role": "user", "content": "warm the cache " * 8}],
                "max_tokens": 4,
                "temperature": 0.0,
            }
            async with s.post(f"{c.base_url}/v1/chat/completions", json=body) as r:
                assert r.status == 200
            async with s.post(f"{c.base_url}/clear_kv_blocks") as r:
                assert r.status == 200
                out = await r.json()
            workers = out["cleared"]["mock"]
            assert len(workers) == 2
            assert all(n >= 0 for n in workers.values())
            assert sum(workers.values()) > 0
