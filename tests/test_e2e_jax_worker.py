"""E2E: OpenAI frontend + the real JAX engine worker (tiny model, CPU).

The full production path with the first-party engine: HTTP → preprocess →
KV router → data plane → EngineCore (jitted prefill/decode + paged cache)
→ detok → SSE. Parity: reference `tests/serve/test_vllm.py` (frontend +
real engine worker, completions asserted), minus the GPU.
"""

import asyncio

import aiohttp
import pytest

from dynamo_tpu.backends.jax.main import run_jax_worker
from dynamo_tpu.frontend.main import run_frontend
from dynamo_tpu.runtime import DistributedRuntime
from dynamo_tpu.runtime.store import StoreServer

pytestmark = [pytest.mark.e2e, pytest.mark.pre_merge]


class JaxCluster:
    def __init__(
        self,
        num_workers: int = 1,
        router_mode: str = "kv",
        tp: int = 1,
        dp: int = 1,
        sp: int = 1,
        pp: int = 1,
        ring_prefill_threshold: int | None = None,
        model_path: str | None = None,
        engine_overrides: dict | None = None,
    ):
        self.num_workers = num_workers
        self.router_mode = router_mode
        self.tp = tp
        self.dp = dp
        self.sp = sp
        self.pp = pp
        self.model_path = model_path
        self.engine_overrides = engine_overrides
        self.ring_prefill_threshold = ring_prefill_threshold
        self.store = StoreServer()
        self.runtimes: list[DistributedRuntime] = []
        self.tasks: list[asyncio.Task] = []
        self.cores: list = []
        self.base_url = ""

    async def __aenter__(self) -> "JaxCluster":
        await self.store.start()
        for i in range(self.num_workers):
            rt = await DistributedRuntime.create(self.store.address)
            self.runtimes.append(rt)
            served = asyncio.Event()
            self.tasks.append(
                asyncio.create_task(
                    run_jax_worker(
                        rt,
                        model_name="tinyjax",
                        preset="tiny",
                        seed=0,
                        served_event=served,
                        core_out=self.cores,
                        tp=self.tp,
                        dp=self.dp,
                        sp=self.sp,
                        pp=self.pp,
                        model_path=self.model_path,
                        engine_overrides=(
                            self.engine_overrides
                            if self.engine_overrides is not None
                            else {"ring_prefill_threshold": self.ring_prefill_threshold}
                            if self.ring_prefill_threshold is not None
                            else None
                        ),
                    )
                )
            )
            await asyncio.wait_for(served.wait(), 30)
        front_rt = await DistributedRuntime.create(self.store.address)
        self.runtimes.append(front_rt)
        ready = asyncio.Event()
        services: list = []
        self.tasks.append(
            asyncio.create_task(
                run_frontend(
                    front_rt,
                    http_host="127.0.0.1",
                    http_port=0,
                    router_mode=self.router_mode,
                    ready_event=ready,
                    service_out=services,
                )
            )
        )
        await asyncio.wait_for(ready.wait(), 10)
        self.base_url = f"http://127.0.0.1:{services[0].port}"
        async with aiohttp.ClientSession() as s:
            for _ in range(200):
                async with s.get(f"{self.base_url}/v1/models") as r:
                    data = await r.json()
                    if data["data"]:
                        return self
                await asyncio.sleep(0.05)
        raise TimeoutError("model never appeared on frontend")

    async def __aexit__(self, *exc) -> None:
        for rt in self.runtimes:
            rt.signal_shutdown()
        await asyncio.sleep(0.1)
        for t in self.tasks:
            t.cancel()
        for rt in self.runtimes:
            try:
                await rt.shutdown()
            # dynalint: allow-broad-except(best-effort teardown; runtime may already be closed)
            except Exception:
                pass
        await self.store.stop()


async def _chat(session, base_url, content, max_tokens=6, stream=False, extra=None):
    body = {
        "model": "tinyjax",
        "messages": [{"role": "user", "content": content}],
        "max_tokens": max_tokens,
        "stream": stream,
        "temperature": 0.0,
    }
    if extra:
        body.update(extra)
    async with session.post(f"{base_url}/v1/chat/completions", json=body) as resp:
        assert resp.status == 200, await resp.text()
        return await resp.json()


async def test_jax_worker_completion_e2e():
    async with JaxCluster() as c:
        async with aiohttp.ClientSession() as s:
            out = await _chat(s, c.base_url, "hello tpu", max_tokens=6)
            choice = out["choices"][0]
            assert choice["finish_reason"] == "length"
            assert out["usage"]["completion_tokens"] == 6
            # Greedy determinism end-to-end: same request, same content —
            # and the repeat must hit the prefix cache.
            out2 = await _chat(s, c.base_url, "hello tpu", max_tokens=6)
            assert out2["choices"][0]["message"] == choice["message"]
            cached = out2["usage"].get("prompt_tokens_details", {}).get("cached_tokens", 0)
            assert cached > 0


async def test_jax_worker_tp_dp_sharded_e2e():
    """HTTP → router → TP×DP-sharded EngineCore on the virtual CPU mesh,
    greedy-identical to the unsharded engine (VERDICT #1 done-criterion)."""
    async with JaxCluster(tp=2, dp=2) as c:
        async with aiohttp.ClientSession() as s:
            out = await _chat(s, c.base_url, "sharded hello", max_tokens=6)
            choice = out["choices"][0]
            assert choice["finish_reason"] == "length"
            assert out["usage"]["completion_tokens"] == 6
            sharded_text = choice["message"]["content"]
    async with JaxCluster() as c:
        async with aiohttp.ClientSession() as s:
            out = await _chat(s, c.base_url, "sharded hello", max_tokens=6)
            assert out["choices"][0]["message"]["content"] == sharded_text


async def test_jax_worker_concurrent_streams():
    async with JaxCluster() as c:
        async with aiohttp.ClientSession() as s:

            async def one(i: int):
                return await _chat(s, c.base_url, f"request number {i}", max_tokens=4)

            results = await asyncio.gather(*[one(i) for i in range(8)])
            for out in results:
                assert out["usage"]["completion_tokens"] == 4


async def test_jax_worker_sequence_parallel_serving_e2e():
    """A deployed worker can enable ring prefill (--sp) without touching
    test code: HTTP -> router -> EngineCore with a sequence-parallel mesh,
    long prompt takes the dense ring-attention path, output greedy-
    identical to the unsharded engine (VERDICT r5 #4: sequence-parallel
    serving must be reachable from the service, not just tests)."""
    # Long enough to clear the ring threshold once chat-templated; the
    # tiny engine's largest bucket is 128 so it must stay under that.
    long_content = "long context please " * 4  # 80 chars

    async with JaxCluster(sp=2, ring_prefill_threshold=96) as c:
        async with aiohttp.ClientSession() as s:
            out = await _chat(s, c.base_url, long_content, max_tokens=6)
            assert out["usage"]["completion_tokens"] == 6
            sp_text = out["choices"][0]["message"]["content"]
        assert c.cores[0]._ring_prefills > 0, (
            "long prompt never took the ring-prefill path"
        )
        # Short prompts stay on the paged ragged waves.
        async with aiohttp.ClientSession() as s:
            await _chat(s, c.base_url, "hi", max_tokens=4)
        assert c.cores[0]._ring_prefills == 1

    async with JaxCluster() as c:
        async with aiohttp.ClientSession() as s:
            out = await _chat(s, c.base_url, long_content, max_tokens=6)
            assert out["choices"][0]["message"]["content"] == sp_text


async def test_jax_worker_serves_hf_checkpoint_by_path():
    """--model-path serves real weights from an HF checkpoint directory
    (qwen2 family here: qkv biases + the checkpoint's own tokenizer) —
    the reference's serve-by-model-path surface (local_model.rs:429)."""
    pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    import tempfile

    import torch as _torch

    cfg = transformers.Qwen2Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, tie_word_embeddings=False,
        use_sliding_window=False,
    )
    _torch.manual_seed(0)
    model = transformers.Qwen2ForCausalLM(cfg)
    with tempfile.TemporaryDirectory() as path:
        model.save_pretrained(path)
        # Weights-only checkpoint: the tokenizer default-resolves to the
        # path, finds no tokenizer files, and degrades to byte-level
        # with a warning (llm/tokenizer.py) — serving still works.
        overrides = dict(
            num_kv_blocks=32, block_size=8, max_num_seqs=4,
            max_model_len=128, prefill_buckets=(32, 64, 128),
            decode_buckets=(4,),
        )
        async with JaxCluster(model_path=path, engine_overrides=overrides) as c:
            async with aiohttp.ClientSession() as s:
                out = await _chat(s, c.base_url, "hi qwen", max_tokens=4)
                assert out["usage"]["completion_tokens"] == 4
        core = c.cores[0]
        assert core.cfg.attn_qkv_bias  # the qwen2 config drove the engine


async def test_jax_worker_pipeline_parallel_serving_e2e():
    """A deployed worker can enable pipeline parallelism (--pp) from the
    CLI surface: HTTP -> router -> EngineCore on a pp=2 mesh (GPipe
    prefill + wavefront decode), greedy-identical to the unsharded
    engine (the row-58 lesson from VERDICT r4: a parallel mode only
    tests can construct does not count as implemented)."""
    async with JaxCluster(pp=2) as c:
        async with aiohttp.ClientSession() as s:
            out = await _chat(s, c.base_url, "staged hello", max_tokens=6)
            assert out["choices"][0]["finish_reason"] == "length"
            assert out["usage"]["completion_tokens"] == 6
            pp_text = out["choices"][0]["message"]["content"]
        assert c.cores[0]._pp == 2
    async with JaxCluster() as c:
        async with aiohttp.ClientSession() as s:
            out = await _chat(s, c.base_url, "staged hello", max_tokens=6)
            assert out["choices"][0]["message"]["content"] == pp_text
