"""EngineCore scheduler: admission, prefix reuse, stops, preemption, async."""

import asyncio

import numpy as np
import pytest

from dynamo_tpu.engine import EngineCore, TpuEngine, tiny_engine, tiny_model
from dynamo_tpu.engine.block_allocator import DeviceBlockAllocator
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.engine import Context

CFG = tiny_model()


def make_core(**eng_overrides) -> EngineCore:
    return EngineCore(CFG, tiny_engine(**eng_overrides), seed=0)


def run_to_completion(core, seqs, max_steps=500):
    done: dict[str, list[int]] = {s.request_id: [] for s in seqs}
    finishes: dict[str, str] = {}
    for _ in range(max_steps):
        for seq, out in core.step():
            done[seq.request_id].extend(out.token_ids)
            if out.finish_reason:
                finishes[seq.request_id] = out.finish_reason
        if len(finishes) == len(seqs):
            break
    return done, finishes


def _req(prompt, rid, max_tokens=8, temperature=0.0, **stop_kw):
    return PreprocessedRequest(
        model="tiny",
        token_ids=prompt,
        request_id=rid,
        sampling=SamplingOptions(temperature=temperature),
        stop=StopConditions(max_tokens=max_tokens, **stop_kw),
    )


def test_single_request_generates_to_length():
    core = make_core()
    seq = core.add_request(_req(list(range(1, 20)), "a", max_tokens=6))
    done, finishes = run_to_completion(core, [seq])
    assert len(done["a"]) == 6
    assert finishes["a"] == "length"
    # All blocks released after finish.
    assert core.allocator.used_blocks == len(core.allocator._inactive)


def test_64bit_seed_does_not_crash_step():
    # OpenAI clients send 64-bit seeds; int32 device arrays must not
    # overflow (the old failure poisoned the engine loop permanently).
    core = make_core()
    pre = PreprocessedRequest(
        model="tiny",
        token_ids=list(range(1, 20)),
        request_id="big-seed",
        sampling=SamplingOptions(temperature=0.8, seed=2**40 + 17),
        stop=StopConditions(max_tokens=4),
    )
    seq = core.add_request(pre)
    done, finishes = run_to_completion(core, [seq])
    assert len(done["big-seed"]) == 4
    assert finishes["big-seed"] == "length"


def test_greedy_determinism_and_prefix_cache_hit():
    core = make_core()
    prompt = list(range(3, 60))  # several full blocks
    s1 = core.add_request(_req(prompt, "r1", max_tokens=5))
    d1, _ = run_to_completion(core, [s1])
    assert s1.num_cached_tokens == 0

    s2 = core.add_request(_req(prompt, "r2", max_tokens=5))
    d2, _ = run_to_completion(core, [s2])
    # Same prompt, greedy: same tokens; prefix cache served full blocks.
    assert d1["r1"] == d2["r2"]
    assert s2.num_cached_tokens >= 48  # 56 prompt tokens -> 6 blocks cached (cap 55//8)


def test_concurrent_requests_interleave():
    core = make_core()
    seqs = [
        core.add_request(_req([i + 1, i + 2, i + 3, i + 4], f"c{i}", max_tokens=4))
        for i in range(5)
    ]
    done, finishes = run_to_completion(core, seqs)
    for i in range(5):
        assert len(done[f"c{i}"]) == 4
        assert finishes[f"c{i}"] == "length"


def test_stop_token_id():
    core = make_core()
    # Greedy tiny model is deterministic: find its 2nd token, then make it a stop.
    probe = core.add_request(_req([5, 6, 7], "probe", max_tokens=4))
    d, _ = run_to_completion(core, [probe])
    target = d["probe"][1]
    first_hit = d["probe"].index(target)
    core2 = make_core()
    seq = core2.add_request(
        _req([5, 6, 7], "s", max_tokens=16, stop_token_ids=[target])
    )
    d2, fin = run_to_completion(core2, [seq])
    # Stream stops at the first occurrence of the stop token (inclusive).
    assert d2["s"] == d["probe"][: first_hit + 1]
    assert fin["s"] == "stop"


def test_eos_token():
    core = make_core()
    probe = core.add_request(_req([9, 9, 9], "p", max_tokens=3))
    d, _ = run_to_completion(core, [probe])
    eos = d["p"][2]
    core2 = EngineCore(CFG, tiny_engine(), seed=0, eos_token_ids=(eos,))
    s = core2.add_request(_req([9, 9, 9], "e", max_tokens=16))
    d2, fin = run_to_completion(core2, [s])
    assert fin["e"] == "eos"
    assert len(d2["e"]) == 3


def test_long_prompt_chunked_prefill():
    core = make_core()
    prompt = list(np.random.RandomState(0).randint(1, 200, size=200))
    # largest tiny bucket is 128 < 200 -> must chunk
    seq = core.add_request(_req(prompt, "long", max_tokens=3))
    done, fin = run_to_completion(core, [seq])
    assert len(done["long"]) == 3
    assert fin["long"] == "length"


def test_context_overflow_rejected():
    core = make_core()
    with pytest.raises(ValueError):
        core.add_request(_req(list(range(1, 300)), "big", max_tokens=3))


def test_preemption_under_block_pressure():
    # Tiny pool: force decode growth to preempt a neighbor and still finish.
    core = make_core(num_kv_blocks=12, max_model_len=64)
    prompts = [list(range(1, 17)), list(range(20, 36)), list(range(40, 56))]
    seqs = [core.add_request(_req(p, f"p{i}", max_tokens=24)) for i, p in enumerate(prompts)]
    done, fin = run_to_completion(core, seqs, max_steps=2000)
    for i in range(3):
        assert len(done[f"p{i}"]) == 24, f"p{i}: {len(done[f'p{i}'])}"
        assert fin[f"p{i}"] == "length"


def test_preempted_greedy_stream_is_consistent():
    """A preempted+replayed greedy stream must equal the unpressured one."""
    base = make_core()
    s = base.add_request(_req(list(range(1, 17)), "ref", max_tokens=24))
    ref, _ = run_to_completion(base, [s])

    core = make_core(num_kv_blocks=12, max_model_len=64)
    seqs = [
        core.add_request(_req(list(range(1, 17)), "a", max_tokens=24)),
        core.add_request(_req(list(range(20, 36)), "b", max_tokens=24)),
        core.add_request(_req(list(range(40, 56)), "c", max_tokens=24)),
    ]
    done, _ = run_to_completion(core, seqs, max_steps=2000)
    assert done["a"] == ref["ref"]


def test_kv_events_emitted():
    stored, removed = [], []
    core = EngineCore(
        CFG,
        tiny_engine(),
        seed=0,
        on_stored=lambda hs, parent: stored.extend(hs),
        on_removed=lambda hs: removed.extend(hs),
    )
    seq = core.add_request(_req(list(range(1, 30)), "ev", max_tokens=12))
    run_to_completion(core, [seq])
    # 29 prompt tokens = 3 full blocks; decode crosses more boundaries.
    assert len(stored) >= 3


async def test_async_engine_streams():
    core = make_core()
    eng = TpuEngine(core)
    ctx = Context("async1")
    got = []
    async for out in eng.generate(
        _req([1, 2, 3, 4, 5], "async1", max_tokens=5).to_wire(), ctx
    ):
        got.extend(out.get("token_ids", []))
    assert len(got) == 5


async def test_async_engine_concurrent():
    core = make_core()
    eng = TpuEngine(core)

    async def one(i):
        toks = []
        async for out in eng.generate(
            _req([i, i + 1, i + 2], f"cc{i}", max_tokens=4).to_wire(), Context(f"cc{i}")
        ):
            toks.extend(out.get("token_ids", []))
        return toks

    results = await asyncio.gather(*[one(i + 1) for i in range(6)])
    for toks in results:
        assert len(toks) == 4


def test_allocator_dedup_and_eviction():
    events = {"stored": 0, "removed": 0}
    alloc = DeviceBlockAllocator(
        4, 8,
        on_stored=lambda h, p: events.__setitem__("stored", events["stored"] + len(h)),
        on_removed=lambda h: events.__setitem__("removed", events["removed"] + len(h)),
    )
    b1 = alloc.alloc()
    got = alloc.commit(b1, 111, None)
    assert got == b1 and events["stored"] == 1
    # Duplicate content: second physical copy freed, canonical returned.
    b2 = alloc.alloc()
    got2 = alloc.commit(b2, 111, None)
    assert got2 == b1 and events["stored"] == 1
    alloc.release([111]); alloc.release([111])
    # Now inactive; filling the pool evicts it.
    ids = alloc.alloc_many(4)
    assert events["removed"] == 1
    assert len(set(ids)) == 4


def test_logprobs_greedy_consistency():
    """Greedy decode with logprobs: the chosen token must be the top-1
    alternative with a matching logprob, on both the prefill-sampled first
    token and chained decode tokens (reference perf/logprobs.rs path)."""
    from dynamo_tpu.llm.protocols.common import OutputOptions

    core = make_core()
    pre = _req(list(range(1, 20)), "lp", max_tokens=6)
    pre.output = OutputOptions(logprobs=3)
    seq = core.add_request(pre)

    entries: list[dict] = []
    for _ in range(200):
        for s, out in core.step():
            assert out.logprobs is not None and len(out.logprobs) == len(out.token_ids)
            entries.extend(out.logprobs)
            if out.finish_reason:
                break
        if seq.finish:
            break
    assert len(entries) == 6
    for e in entries:
        assert len(e["top"]) == 3
        top = e["top"]
        # Greedy: chosen == argmax == first alternative; logprobs agree.
        assert e["token_id"] == top[0][0]
        assert abs(e["logprob"] - top[0][1]) < 1e-5
        assert e["logprob"] <= 0.0 + 1e-6
        # Alternatives sorted descending.
        lps = [v for _, v in top]
        assert lps == sorted(lps, reverse=True)


def test_logprobs_mixed_batch_only_requested_lanes():
    """A batch mixing logprob and plain requests: only the requesting
    sequence gets logprob records."""
    from dynamo_tpu.llm.protocols.common import OutputOptions

    core = make_core()
    p1 = _req([1, 2, 3, 4, 5], "with", max_tokens=4)
    p1.output = OutputOptions(logprobs=1)
    p2 = _req([6, 7, 8, 9, 10], "without", max_tokens=4)
    s1 = core.add_request(p1)
    s2 = core.add_request(p2)
    got = {"with": [], "without": []}
    done, _ = run_to_completion(core, [s1, s2])
    # re-run: collect logprobs per request
    core2 = make_core()
    s1 = core2.add_request(p1)
    s2 = core2.add_request(p2)
    for _ in range(200):
        for s, out in core2.step():
            if out.logprobs:
                got[s.request_id].extend(out.logprobs)
        if s1.finish and s2.finish:
            break
    assert len(got["with"]) == 4
    assert got["without"] == []


def test_chain_length_respects_generation_budgets():
    """Short-budget batches must not run full decode chains (tool-call
    workloads: max_tokens=2 with decode_chain=32 used to burn 30 wasted
    fused steps per chain)."""
    core = make_core(decode_chain=32, max_model_len=256)
    s1 = core.add_request(_req([1, 2, 3], "a", max_tokens=2))
    s2 = core.add_request(_req([4, 5, 6], "b", max_tokens=3))
    core.step()  # prefill: each seq now has 1 generated token
    n = core._chain_length([s1, s2])
    # Largest remaining budget is 2 -> chain of 2, not 32.
    assert n == 2
    # The manual prefill step above already emitted token 1 of each.
    done, fin = run_to_completion(core, [s1, s2])
    assert len(done["a"]) == 1 and len(done["b"]) == 2
    assert fin["a"] == fin["b"] == "length"


def test_chain_length_unbounded_budget_keeps_full_chain():
    core = make_core(decode_chain=8, max_model_len=256)
    s = core.add_request(_req([1, 2, 3], "a", max_tokens=200, ignore_eos=True))
    core.step()
    assert core._chain_length([s]) == 8


def test_expired_held_blocks_are_released():
    """A remote-decode prefill whose decode side never pulls (timeout,
    crash) must not pin its blocks forever: the hold expires after
    held_block_ttl_s and the next step releases it (advisor r4)."""
    import time

    core = EngineCore(CFG, tiny_engine(held_block_ttl_s=0.15), seed=0)
    pre = _req(list(range(1, 20)), "held", max_tokens=1)
    pre.kv_transfer_params = {"do_remote_decode": True}
    seq = core.add_request(pre)
    run_to_completion(core, [seq])
    assert "held" in core._held
    held_blocks = core.allocator.used_blocks
    assert held_blocks > 0

    # Within the TTL the hold survives steps, and a transfer touch
    # refreshes the deadline.
    core.step()
    assert "held" in core._held
    core.export_descriptors("held")

    time.sleep(0.2)
    core.step()  # sweep runs at the top of the step
    assert "held" not in core._held
    assert core._held_deadline == {}
    # Blocks are back in the reusable pool (inactive cached content).
    assert core.allocator.used_blocks == len(core.allocator._inactive)


def _held_prefill(core, prompt, rid):
    pre = _req(prompt, rid, max_tokens=1, ignore_eos=True)
    pre.kv_transfer_params = {"do_remote_decode": True}
    seq = core.add_request(pre)
    done, _ = run_to_completion(core, [seq])
    return done[rid]


def test_import_blocks_direct_matches_aggregated():
    """Device-direct cache->cache transfer (the within-slice ICI analogue
    of NIXL GPU->GPU): decode continuation over directly-imported blocks
    must match the aggregated output exactly."""
    prompt = list(range(1, 41))  # 5 complete 8-token blocks
    agg = make_core()
    want, _ = run_to_completion(agg, [agg.add_request(_req(prompt, "agg", max_tokens=6))])

    p_core = make_core()
    d_core = EngineCore(CFG, tiny_engine(), seed=0, params=p_core.params)
    tok1 = _held_prefill(p_core, prompt, "pf")
    n = d_core.import_blocks_direct(p_core, "pf").imported
    p_core.release_held("pf")
    assert n == 5  # all five complete prompt blocks committed and moved
    seq = d_core.add_request(_req(prompt + tok1, "dec", max_tokens=5))
    got, _ = run_to_completion(d_core, [seq])
    assert tok1 + got["dec"] == want["agg"]
    # The continuation rode the imported prefix (cached tokens > 0).
    assert seq.num_cached_tokens > 0
    assert d_core.transfer_stats["imported_blocks"] == n
    assert d_core.transfer_stats["dropped_blocks"] == 0


def test_import_blocks_direct_skips_cached_and_accounts():
    """Re-importing the same prefix skips already-cached hashes and the
    accounting distinguishes imported vs skipped vs dropped."""
    prompt = list(range(1, 41))
    p_core = make_core()
    d_core = EngineCore(CFG, tiny_engine(), seed=0, params=p_core.params)
    _held_prefill(p_core, prompt, "a")
    n1 = d_core.import_blocks_direct(p_core, "a").imported
    p_core.release_held("a")
    _held_prefill(p_core, prompt, "b")
    n2 = d_core.import_blocks_direct(p_core, "b").imported
    p_core.release_held("b")
    assert n1 > 0 and n2 == 0
    st = d_core.transfer_stats
    assert st["transfers"] == 2
    assert st["imported_blocks"] == n1
    assert st["skipped_cached_blocks"] == n1
    assert st["dropped_blocks"] == 0 and st["partial_transfers"] == 0


def test_import_blocks_partial_drop_is_accounted():
    """Allocator exhaustion mid-import drops the tail blocks and the
    stats record it (VERDICT r4 weak #7: 'transfer worked' vs 'transfer
    half-dropped' must be distinguishable)."""
    prompt = list(range(1, 41))
    p_core = make_core()
    descs = None
    _held_prefill(p_core, prompt, "a")
    descs = p_core.export_descriptors("a")
    pages = p_core.read_held_pages("a", 0, len(descs))
    blocks = [dict(d, kv=kv) for d, kv in zip(descs, pages)]
    p_core.release_held("a")

    # Destination with too few blocks: every block pinned by a running
    # sequence, so alloc_for_import starves partway through.
    d_core = EngineCore(CFG, tiny_engine(num_kv_blocks=6), seed=0, params=p_core.params)
    pin = d_core.add_request(_req(list(range(50, 70)), "pin", max_tokens=64, ignore_eos=True))
    d_core.step()  # prefill: pins 3 blocks, leaves 3 free
    res = d_core.import_blocks(blocks)
    st = d_core.transfer_stats
    assert res.imported < len(blocks)
    assert res.dropped == st["dropped_blocks"] == len(blocks) - res.imported
    assert st["partial_transfers"] == 1
    del pin
