"""Engine-core numerics: paged cache consistency, pallas parity, sampling."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine import config as cfgmod
from dynamo_tpu.engine.model import decode_step, init_cache, init_params, prefill_step
from dynamo_tpu.engine.sampler import sample
from dynamo_tpu.ops.paged_attention import (
    paged_attention_pallas,
    paged_attention_reference,
)

CFG = cfgmod.tiny_model()
ENG = cfgmod.tiny_engine()


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


def _table(blocks: list[int]) -> np.ndarray:
    t = np.full(ENG.max_blocks_per_seq, ENG.garbage_block, np.int32)
    t[: len(blocks)] = blocks
    return t


def test_prefill_then_decode_matches_monolithic_prefill(params):
    """Prefill(n) + k decode steps == prefill(n+k) logits at each position."""
    rng = np.random.RandomState(7)
    prompt = rng.randint(0, CFG.vocab_size, size=37).tolist()
    extra = rng.randint(0, CFG.vocab_size, size=5).tolist()

    # Ground truth: one monolithic prefill over the whole sequence.
    k1, v1 = init_cache(CFG, ENG)
    full = prompt + extra
    bucket = 64
    toks = np.zeros(bucket, np.int32)
    toks[: len(full)] = full
    table = _table(list(range(6)))
    want, _, _ = prefill_step(
        params, jnp.asarray(toks), k1, v1, jnp.asarray(table),
        jnp.int32(len(full)), jnp.int32(0), CFG, ENG,
    )

    # Paged path: prefill the prompt, then decode the extra tokens.
    k2, v2 = init_cache(CFG, ENG)
    toks2 = np.zeros(bucket, np.int32)
    toks2[: len(prompt)] = prompt
    logits, k2, v2 = prefill_step(
        params, jnp.asarray(toks2), k2, v2, jnp.asarray(table),
        jnp.int32(len(prompt)), jnp.int32(0), CFG, ENG,
    )
    B = ENG.max_num_seqs
    tables = np.stack([_table(list(range(6)))] + [_table([])] * (B - 1))
    for i, tok in enumerate(extra):
        toks_b = np.zeros(B, np.int32)
        toks_b[0] = tok
        pos = np.zeros(B, np.int32)
        pos[0] = len(prompt) + i
        active = np.zeros(B, bool)
        active[0] = True
        logits_b, k2, v2 = decode_step(
            params, jnp.asarray(toks_b), k2, v2, jnp.asarray(tables),
            jnp.asarray(pos), jnp.asarray(active), CFG, ENG,
        )
        logits = logits_b[0]

    np.testing.assert_allclose(np.asarray(logits), np.asarray(want), rtol=2e-3, atol=2e-3)


def test_chunked_prefill_matches_monolithic(params):
    rng = np.random.RandomState(3)
    seq = rng.randint(0, CFG.vocab_size, size=48).tolist()
    table = _table(list(range(8)))

    k1, v1 = init_cache(CFG, ENG)
    toks = np.zeros(64, np.int32)
    toks[:48] = seq
    want, k1, v1 = prefill_step(
        params, jnp.asarray(toks), k1, v1, jnp.asarray(table),
        jnp.int32(48), jnp.int32(0), CFG, ENG,
    )

    k2, v2 = init_cache(CFG, ENG)
    a = np.zeros(32, np.int32)
    a[:] = seq[:32]
    _, k2, v2 = prefill_step(
        params, jnp.asarray(a), k2, v2, jnp.asarray(table),
        jnp.int32(32), jnp.int32(0), CFG, ENG,
    )
    b = np.zeros(32, np.int32)
    b[:16] = seq[32:]
    got, k2, v2 = prefill_step(
        params, jnp.asarray(b), k2, v2, jnp.asarray(table),
        jnp.int32(16), jnp.int32(32), CFG, ENG,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-3, atol=2e-3)


def test_paged_attention_pallas_matches_reference():
    rng = jax.random.PRNGKey(42)
    B, n_q, n_kv, d, bs, max_blocks = 4, 8, 2, 16, 8, 6
    total = (max_blocks * B + 1) * bs
    ks = jax.random.split(rng, 4)
    q = jax.random.normal(ks[0], (B, n_q, d), jnp.float32)
    k_cache = jax.random.normal(ks[1], (n_kv, total, d), jnp.float32)
    v_cache = jax.random.normal(ks[2], (n_kv, total, d), jnp.float32)
    tables = np.arange(B * max_blocks, dtype=np.int32).reshape(B, max_blocks)
    seq_lens = np.array([5, 17, 48, 1], np.int32)

    want = paged_attention_reference(
        q, k_cache, v_cache, jnp.asarray(tables), jnp.asarray(seq_lens), block_size=bs
    )
    got = paged_attention_pallas(
        q, k_cache, v_cache, jnp.asarray(tables), jnp.asarray(seq_lens),
        block_size=bs, interpret=True,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_sampler_greedy_and_distributions():
    V = 50
    logits = np.full((3, V), -10.0, np.float32)
    logits[0, 7] = 5.0          # greedy lane
    logits[1, [3, 4]] = [4.0, 3.9]  # top_k=2 lane
    logits[2, 11] = 8.0         # top_p tiny => only argmax survives
    out = sample(
        jnp.asarray(logits),
        jax.random.PRNGKey(0),
        temperature=jnp.asarray([0.0, 1.0, 1.0]),
        top_k=jnp.asarray([0, 2, 0], jnp.int32),
        top_p=jnp.asarray([1.0, 1.0, 0.1]),
    )
    out = np.asarray(out)
    assert out[0] == 7
    assert out[1] in (3, 4)
    assert out[2] == 11


def test_sampler_temperature_spread():
    logits = jnp.zeros((1, 16), jnp.float32)  # uniform
    seen = {
        int(sample(
            logits, jax.random.PRNGKey(i),
            jnp.asarray([1.0]), jnp.asarray([0], jnp.int32), jnp.asarray([1.0]),
        )[0])
        for i in range(24)
    }
    assert len(seen) > 4  # actually sampling, not collapsing to argmax
