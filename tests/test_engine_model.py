"""Engine-model numerics over the unified ragged forward: paged-cache
consistency across prefill/decode splits, pallas parity, sampling."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine import config as cfgmod
from dynamo_tpu.engine.model import decode_tokens, init_cache, init_params
from dynamo_tpu.engine.sampler import sample
from dynamo_tpu.ops.paged_attention import (
    paged_attention_pallas,
    paged_attention_reference,
)
from tests.model_harness import prefill_chunk

CFG = cfgmod.tiny_model()
ENG = cfgmod.tiny_engine()


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


def _tables(block_ids: list[int], B: int) -> np.ndarray:
    t = np.full((B, ENG.max_blocks_per_seq), ENG.garbage_block, np.int32)
    t[0, : len(block_ids)] = block_ids
    return t


def test_prefill_then_decode_matches_monolithic_prefill(params):
    """Prefill(n) + k decode steps == one monolithic prefill(n+k)."""
    rng = np.random.RandomState(7)
    prompt = rng.randint(0, CFG.vocab_size, size=37).tolist()
    extra = rng.randint(0, CFG.vocab_size, size=5).tolist()
    blocks = list(range(6))

    # Ground truth: one monolithic prefill over the whole sequence.
    want, _ = prefill_chunk(
        params, init_cache(CFG, ENG), prompt + extra, 0, blocks, CFG, ENG, 64
    )

    # Paged path: prefill the prompt, then decode the extra tokens.
    logits, cache = prefill_chunk(
        params, init_cache(CFG, ENG), prompt, 0, blocks, CFG, ENG, 64
    )
    B = ENG.max_num_seqs
    tables = _tables(blocks, B)
    for i, tok in enumerate(extra):
        toks_b = np.zeros(B, np.int32)
        toks_b[0] = tok
        pos = np.zeros(B, np.int32)
        pos[0] = len(prompt) + i
        active = np.zeros(B, bool)
        active[0] = True
        logits_b, cache = decode_tokens(
            params, cache, jnp.asarray(toks_b), jnp.asarray(tables),
            jnp.asarray(pos), jnp.asarray(active), CFG, ENG,
        )
        logits = logits_b[0]

    np.testing.assert_allclose(np.asarray(logits), np.asarray(want), rtol=2e-3, atol=2e-3)


def test_chunked_prefill_matches_monolithic(params):
    rng = np.random.RandomState(3)
    seq = rng.randint(0, CFG.vocab_size, size=48).tolist()
    blocks = list(range(8))

    want, _ = prefill_chunk(
        params, init_cache(CFG, ENG), seq, 0, blocks, CFG, ENG, 64
    )

    cache = init_cache(CFG, ENG)
    _, cache = prefill_chunk(params, cache, seq[:32], 0, blocks, CFG, ENG, 32)
    got, cache = prefill_chunk(params, cache, seq[32:], 32, blocks, CFG, ENG, 32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-3, atol=2e-3)


def test_mixed_ragged_batch_matches_separate_calls(params):
    """Two sequences of different chunk lengths in ONE forward_tokens call
    match two single-sequence calls (the engine's mixed-wave shape)."""
    from dynamo_tpu.engine.model import forward_tokens

    rng = np.random.RandomState(11)
    p1 = rng.randint(0, CFG.vocab_size, size=19).tolist()
    p2 = rng.randint(0, CFG.vocab_size, size=9).tolist()
    bs = ENG.block_size

    want1, _ = prefill_chunk(
        params, init_cache(CFG, ENG), p1, 0, [0, 1, 2], CFG, ENG, 32
    )
    want2, _ = prefill_chunk(
        params, init_cache(CFG, ENG), p2, 0, [3, 4], CFG, ENG, 32
    )

    T = 32
    n = len(p1) + len(p2)
    tokens = np.zeros(T, np.int32)
    tokens[:n] = p1 + p2
    positions = np.zeros(T, np.int32)
    positions[: len(p1)] = np.arange(len(p1))
    positions[len(p1) : n] = np.arange(len(p2))
    ids1, ids2 = np.array([0, 1, 2], np.int32), np.array([3, 4], np.int32)
    write_pages = np.full(T, ENG.garbage_block, np.int32)
    write_pages[: len(p1)] = ids1[np.arange(len(p1)) // bs]
    write_pages[len(p1) : n] = ids2[np.arange(len(p2)) // bs]
    write_offs = np.zeros(T, np.int32)
    write_offs[:n] = positions[:n] % bs
    tables = np.full((2, ENG.max_blocks_per_seq), ENG.garbage_block, np.int32)
    tables[0, :3] = ids1
    tables[1, :2] = ids2
    kv_lens = np.array([len(p1), len(p2)], np.int32)
    cu = np.array([0, len(p1), n], np.int32)
    last_rows = np.array([len(p1) - 1, n - 1], np.int32)

    logits, _ = forward_tokens(
        params, init_cache(CFG, ENG),
        jnp.asarray(tokens), jnp.asarray(positions),
        jnp.asarray(write_pages), jnp.asarray(write_offs),
        jnp.asarray(kv_lens), jnp.asarray(tables), jnp.asarray(cu),
        jnp.asarray(np.array([2], np.int32)), jnp.asarray(last_rows),
        CFG, ENG,
    )
    np.testing.assert_allclose(np.asarray(logits[0]), np.asarray(want1), rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(logits[1]), np.asarray(want2), rtol=2e-3, atol=2e-3)


def test_paged_attention_pallas_matches_reference():
    rng = jax.random.PRNGKey(42)
    B, n_q, n_kv, d, bs, max_blocks = 4, 8, 2, 16, 8, 6
    total = (max_blocks * B + 1) * bs
    ks = jax.random.split(rng, 4)
    q = jax.random.normal(ks[0], (B, n_q, d), jnp.float32)
    k_cache = jax.random.normal(ks[1], (n_kv, total, d), jnp.float32)
    v_cache = jax.random.normal(ks[2], (n_kv, total, d), jnp.float32)
    tables = np.arange(B * max_blocks, dtype=np.int32).reshape(B, max_blocks)
    seq_lens = np.array([5, 17, 48, 1], np.int32)

    want = paged_attention_reference(
        q, k_cache, v_cache, jnp.asarray(tables), jnp.asarray(seq_lens), block_size=bs
    )
    got = paged_attention_pallas(
        q, k_cache, v_cache, jnp.asarray(tables), jnp.asarray(seq_lens),
        block_size=bs, interpret=True,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_sampler_greedy_and_distributions():
    V = 50
    logits = np.full((3, V), -10.0, np.float32)
    logits[0, 7] = 5.0          # greedy lane
    logits[1, [3, 4]] = [4.0, 3.9]  # top_k=2 lane
    logits[2, 11] = 8.0         # top_p tiny => only argmax survives
    out = sample(
        jnp.asarray(logits),
        jax.random.PRNGKey(0),
        temperature=jnp.asarray([0.0, 1.0, 1.0]),
        top_k=jnp.asarray([0, 2, 0], jnp.int32),
        top_p=jnp.asarray([1.0, 1.0, 0.1]),
    )
    out = np.asarray(out)
    assert out[0] == 7
    assert out[1] in (3, 4)
    assert out[2] == 11


def test_sampler_temperature_spread():
    logits = jnp.zeros((1, 16), jnp.float32)  # uniform
    seen = {
        int(sample(
            logits, jax.random.PRNGKey(i),
            jnp.asarray([1.0]), jnp.asarray([0], jnp.int32), jnp.asarray([1.0]),
        )[0])
        for i in range(24)
    }
    assert len(seen) > 4  # actually sampling, not collapsing to argmax


def test_int8_weight_only_quantization_accuracy():
    """Quantized params produce near-identical logits (per-channel int8 is
    ~0.4% weight error) and identical greedy generations on the tiny
    model."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.engine import EngineCore, tiny_engine, tiny_model
    from dynamo_tpu.engine.model import init_params, quantize_params
    from tests.test_engine_core import _req, run_to_completion

    cfg = tiny_model()
    params = init_params(jax.random.PRNGKey(0), cfg)
    qparams = quantize_params(params)
    # Quantized leaves really are int8 (the capacity point).
    assert qparams["layers"]["wqkv"]["w"].dtype == jnp.int8
    assert qparams["layers"]["w_down"]["w"].dtype == jnp.int8

    core_f = EngineCore(cfg, tiny_engine(), params=params, seed=0)
    core_q = EngineCore(cfg, tiny_engine(), params=qparams, seed=0)
    prompt = list(range(3, 40))
    sf = core_f.add_request(_req(prompt, "f", max_tokens=8))
    sq = core_q.add_request(_req(prompt, "q", max_tokens=8))
    df, _ = run_to_completion(core_f, [sf])
    dq, _ = run_to_completion(core_q, [sq])
    # Greedy tokens should survive quantization on a tiny random model;
    # allow a small divergence tail (argmax near-ties).
    agree = sum(a == b for a, b in zip(df["f"], dq["q"]))
    assert agree >= 6, (df["f"], dq["q"])
