"""Closed-loop SLA autoscaling + network-aware routing on the fleet
harness (ISSUE 14).

Three layers: controller unit tests (hysteresis, cooldown, reactive
pressure, independent prefill/decode pools — RecordingConnector, no
sim), netcost unit tests (EWMA folding, cost ratios, selector shifts),
and fleet-harness e2e (the autoscaling A/B, the NetKV routing A/B, and
the drain/kill stream-identity audits — the acceptance criteria of the
issue, at test scale; BENCH_r12.json pins the full-size run).
"""

import asyncio
import json
import pathlib

import pytest

from dynamo_tpu.fleet.harness import (
    ChaosEvent,
    FleetHarness,
    FleetSpec,
    default_tenants,
    mocker_profile,
    run_routing_ab,
)
from dynamo_tpu.fleet.workload import TenantSpec, generate_arrivals, rate_at
from dynamo_tpu.llm.kv_router.netcost import (
    MAX_COST_RATIO,
    NetCostModel,
    NetworkAwareSelector,
    best_pull_source,
)
from dynamo_tpu.llm.kv_router.protocols import RouterConfig
from dynamo_tpu.llm.kv_router.scheduler import DefaultWorkerSelector
from dynamo_tpu.llm.kv_router.sequence import ActiveSequences
from dynamo_tpu.planner.controller import ControllerConfig, PlannerController
from dynamo_tpu.planner.perf_interpolation import from_profile
from dynamo_tpu.planner.planner_core import (
    Observation,
    Planner,
    PlannerConfig,
    RecordingConnector,
    SlaTargets,
)

REPO = pathlib.Path(__file__).resolve().parent.parent


# -- workload generator ------------------------------------------------------


def test_workload_deterministic_and_diurnal():
    spec = TenantSpec(
        name="t", users=10_000, rps=20.0, diurnal_amplitude=0.6,
        diurnal_period_s=100.0, isl=64, osl=8, shared_prefix_tokens=32,
    )
    a1 = generate_arrivals([spec], 50.0, seed=7)
    a2 = generate_arrivals([spec], 50.0, seed=7)
    assert [(a.t, a.rid, a.token_ids) for a in a1] == [
        (a.t, a.rid, a.token_ids) for a in a2
    ], "same seed must replay identically"
    a3 = generate_arrivals([spec], 50.0, seed=8)
    assert [a.t for a in a1] != [a.t for a in a3]
    # Amplitude 0.6 -> 4x peak/trough swing of the instantaneous rate.
    peak = max(rate_at(spec, t / 10) for t in range(1000))
    trough = min(rate_at(spec, t / 10) for t in range(1000))
    assert peak / trough == pytest.approx(4.0, rel=0.01)
    # Every arrival opens with the tenant's shared prefix; a recurring
    # user recurs with the same tail (the prefix-cache population).
    prefix = a1[0].token_ids[:32]
    assert all(a.token_ids[:32] == prefix for a in a1)
    by_user = {}
    recur = 0
    for a in a1:
        tail = a.token_ids[32:]
        if a.user in by_user:
            recur += 1
            assert by_user[a.user] == tail
        else:
            by_user[a.user] = tail
    assert recur > 0, "no user ever recurred — prefix reuse untested"


def test_workload_bursts():
    spec = TenantSpec(
        name="b", users=100, rps=2.0, burst_rps=20.0,
        burst_every_s=30.0, burst_len_s=5.0,
    )
    assert rate_at(spec, 2.0) == pytest.approx(22.0)
    assert rate_at(spec, 10.0) == pytest.approx(2.0)
    assert rate_at(spec, 32.0) == pytest.approx(22.0)


# -- controller --------------------------------------------------------------

PROFILE = {
    "prefill": {"isl": [128, 512, 2048, 8192], "ttft_s": [0.02, 0.06, 0.2, 0.9]},
    "decode": {"concurrency": [1, 8, 32, 64], "itl_s": [0.01, 0.012, 0.02, 0.045]},
}


def make_controller(clock, **cfg):
    p, d = from_profile(PROFILE)
    connector = RecordingConnector()
    planner = Planner(
        p, d, connector,
        sla=SlaTargets(ttft_s=0.2, itl_s=0.02),
        config=PlannerConfig(predictor="constant", max_replicas=32),
    )
    config = ControllerConfig(
        interval_s=10.0,
        scale_up_cooldown_s=cfg.pop("up_cd", 0.0),
        scale_down_cooldown_s=cfg.pop("down_cd", 0.0),
        down_stable_cycles=cfg.pop("stable", 2),
        max_step_up=cfg.pop("step_up", 4),
        max_step_down=cfg.pop("step_down", 1),
        max_replicas=32,
        **cfg,
    )
    ctl = PlannerController(planner, connector, config=config, clock=clock)
    return ctl, connector


def obs(rate=10.0, isl=512, osl=128, **kw):
    return Observation(request_rate=rate, mean_isl=isl, mean_osl=osl, **kw)


def test_controller_scales_pools_independently():
    """Prefill-heavy vs decode-heavy demand must move DIFFERENT pools —
    the disaggregated scaling contract from the reference planner."""
    t = [0.0]
    ctl, conn = make_controller(lambda: t[0])

    async def run():
        # Prefill-heavy: long prompts, tiny completions.
        t[0] += 100
        await ctl.cycle(obs(rate=30.0, isl=4096, osl=4))
        prefill_1 = ctl.pools["prefill"].target
        decode_1 = ctl.pools["decode"].target
        # Decode-heavy: short prompts, long completions.
        for _ in range(12):
            t[0] += 100
            await ctl.cycle(obs(rate=30.0, isl=64, osl=2048))
        return prefill_1, decode_1

    p1, d1 = asyncio.run(run())
    assert p1 > 1, "prefill pool ignored prefill-heavy demand"
    assert ctl.pools["decode"].target > d1, "decode pool ignored osl demand"
    assert ctl.pools["prefill"].target < p1, (
        "prefill pool never released after demand moved to decode"
    )
    comps = {c for c, _ in conn.calls}
    assert comps == {"prefill", "decode"}


def test_controller_hysteresis_blocks_single_trough():
    """One trough observation must never shed capacity; a sustained
    trough sheds one bounded step per cycle."""
    t = [0.0]
    ctl, _ = make_controller(lambda: t[0], stable=3)

    async def run():
        t[0] += 100
        await ctl.cycle(obs(rate=40.0))            # scale up
        high = ctl.pools["decode"].target
        assert high > 1
        t[0] += 100
        await ctl.cycle(obs(rate=1.0))             # single trough blip
        assert ctl.pools["decode"].target == high
        assert ctl.pools["decode"].last_action == "hysteresis_hold"
        t[0] += 100
        await ctl.cycle(obs(rate=1.0))
        assert ctl.pools["decode"].target == high  # 2/3 cycles
        t[0] += 100
        await ctl.cycle(obs(rate=1.0))             # 3rd: down, one step
        assert ctl.pools["decode"].target == high - 1
        assert ctl.pools["decode"].last_action == "scale_down"
        # A recovery resets the streak — no delayed shed.
        t[0] += 100
        await ctl.cycle(obs(rate=40.0))
        t[0] += 100
        await ctl.cycle(obs(rate=1.0))
        assert ctl.pools["decode"].last_action == "hysteresis_hold"

    asyncio.run(run())


def test_controller_cooldowns_and_bounded_steps():
    t = [1000.0]
    ctl, _ = make_controller(
        lambda: t[0], up_cd=30.0, down_cd=60.0, stable=1, step_up=2,
    )

    async def run():
        await ctl.cycle(obs(rate=100.0, osl=2048))   # huge demand
        first = ctl.pools["decode"].target
        assert first == 1 + 2, "scale-up exceeded max_step_up"
        t[0] += 10                                    # inside up cooldown
        await ctl.cycle(obs(rate=100.0, osl=2048))
        assert ctl.pools["decode"].target == first
        assert ctl.pools["decode"].last_action == "cooldown_hold"
        t[0] += 30                                    # cooldown expired
        await ctl.cycle(obs(rate=100.0, osl=2048))
        assert ctl.pools["decode"].target == first + 2
        # Down cooldown: two sustained-trough downs need 60 s apart.
        t[0] += 100
        await ctl.cycle(obs(rate=0.1))
        down1 = ctl.pools["decode"].target
        assert down1 == first + 1
        t[0] += 10
        await ctl.cycle(obs(rate=0.1))
        assert ctl.pools["decode"].target == down1
        assert ctl.pools["decode"].last_action == "cooldown_hold"

    asyncio.run(run())


def test_controller_reactive_pressure():
    """Queue backlog, typed sheds, and SLO-attainment misses must raise
    capacity above the rate math's answer — before the predictor
    notices."""
    t = [0.0]

    async def run_one(**obs_kw):
        ctl, _ = make_controller(lambda: t[0], queue_depth_per_replica=8.0)
        t[0] += 100
        await ctl.cycle(obs(rate=1.0, **obs_kw))
        return ctl

    # Rate alone at 1 rps: hold at 1.
    ctl = asyncio.run(run_one())
    assert ctl.pools["decode"].target == 1

    # Deep backlog: proportional catch-up, bounded by max_step_up.
    ctl = asyncio.run(
        run_one(queue_depth=200.0, live_workers={"decode": 1, "prefill": 1})
    )
    assert ctl.pools["decode"].target == 5     # 1 + max_step_up(4)
    assert ctl.pools["decode"].last_reason == "queue_depth"
    assert ctl.pools["decode"].desired >= 25   # backlog / 8, uncapped desire

    # A typed shed in the window: one full step of pressure.
    ctl = asyncio.run(run_one(shed_delta=3.0))
    assert ctl.pools["decode"].target == 5
    assert ctl.pools["decode"].last_reason == "sheds"

    # TPOT attainment miss pushes decode; TTFT miss pushes prefill.
    ctl = asyncio.run(run_one(slo_attainment={"ttft": 1.0, "tpot": 0.7}))
    assert ctl.pools["decode"].target == 2
    assert ctl.pools["decode"].last_reason == "slo_attainment"
    assert ctl.pools["prefill"].target == 1
    ctl = asyncio.run(run_one(slo_attainment={"ttft": 0.7, "tpot": 1.0}))
    assert ctl.pools["prefill"].target == 2
    assert ctl.pools["decode"].target == 1


def test_controller_status_and_stats_shapes():
    t = [0.0]
    ctl, _ = make_controller(lambda: t[0])

    async def run():
        t[0] += 100
        await ctl.cycle(obs(rate=30.0))

    asyncio.run(run())
    st = ctl.stats()
    assert st["cycles"] == 1
    assert set(st["decisions"]) == {
        "scale_up", "scale_down", "hold", "cooldown_hold", "hysteresis_hold",
        "degraded_hold",
    }
    assert st["decisions"]["scale_up"] >= 1
    pay = ctl.status_payload()
    assert pay["last_plan"]["predicted_rate"] == pytest.approx(30.0)
    assert pay["pools"]["decode"]["last_action"] == "scale_up"
    assert pay["last_observation"]["request_rate"] == pytest.approx(30.0)


# -- netcost -----------------------------------------------------------------


def test_netcost_ewma_and_ratio_clamp():
    m = NetCostModel(recompute_ms_per_block=2.0)
    m.observe_pull(7, blocks=10, elapsed_ms=10.0)      # 1 ms/block
    assert m.pull_ms_per_block(7) == pytest.approx(1.0)
    assert m.cost_ratio(7) == pytest.approx(0.5)
    # A failed pull charges its whole elapsed budget as one block.
    m.observe_pull(7, blocks=0, elapsed_ms=500.0, ok=False)
    assert m.pull_ms_per_block(7) > 100.0
    assert m.cost_ratio(7) == MAX_COST_RATIO           # clamped
    # Unmeasured peers get the optimistic prior, not infinity.
    assert m.cost_ratio(99) == pytest.approx(0.5 / 2.0, abs=0.2)


def test_netcost_folds_fleet_reports():
    """Every reporter's EWMA of a source folds into one pull-count
    weighted cost — the aggregated fleet view of a peer's link."""
    from dynamo_tpu.llm.kv_router.protocols import (
        ForwardPassMetrics, KvStats, WorkerStats,
    )

    def fpm(waiting, net):
        return ForwardPassMetrics(
            worker_id=0,
            worker=WorkerStats(
                request_active_slots=0, request_total_slots=4,
                num_requests_waiting=waiting,
            ),
            kv=KvStats(
                kv_active_blocks=0, kv_total_blocks=64,
                gpu_cache_usage_perc=0.0, gpu_prefix_cache_hit_rate=0.0,
            ),
            net=net,
        )

    view = {
        1: fpm(3, {9: {"pulls": 3, "ms_per_block": 6.0}}),
        2: fpm(0, {9: {"pulls": 1, "ms_per_block": 2.0}}),
    }
    m = NetCostModel(recompute_ms_per_block=2.0, fleet_view=lambda: view,
                     cache_s=0.0)
    # (6*3 + 2*1) / 4 = 5.0
    assert m.pull_ms_per_block(9) == pytest.approx(5.0)
    assert m.queue_depth(1) == 3
    assert m.queue_depth(2) == 0
    assert m.snapshot()[9]["cost_ratio"] == pytest.approx(2.5)


def test_best_pull_source_prefers_cheap_useful_peer():
    m = NetCostModel(recompute_ms_per_block=2.0)
    m.observe_pull(1, 10, 40.0)     # 4 ms/block -> ratio 2: useless
    m.observe_pull(2, 10, 2.0)      # 0.2 ms/block -> ratio 0.1: cheap
    overlaps = {1: 12, 2: 8, 3: 2}  # peer 1 overlaps most but is slow
    src = best_pull_source(3, 2, overlaps, prompt_blocks=12, netcost=m)
    assert src is not None
    source, extra, ratio = src
    assert source == 2, "picked the expensive peer"
    assert extra == 6
    assert ratio == pytest.approx(0.1)
    # Every peer at ratio >= 1: no pull beats recomputing.
    m2 = NetCostModel(recompute_ms_per_block=2.0)
    m2.observe_pull(1, 10, 40.0)
    m2.observe_pull(2, 10, 80.0)
    assert best_pull_source(3, 0, {1: 12, 2: 8}, 12, m2) is None


def test_network_aware_selector_degrades_to_overlap_only():
    """With uniform (prior) costs, no queues, and no useful pulls the
    network-aware cost must pick exactly the overlap-only winner."""
    active = ActiveSequences(block_size=8)
    cfg = RouterConfig(temperature=0.0, block_size=8)
    overlaps = {1: 4, 2: 1, 3: 0}
    base = DefaultWorkerSelector().select_worker(
        [1, 2, 3], dict(overlaps), 64, active, cfg
    )
    m = NetCostModel(recompute_ms_per_block=2.0)
    aware = NetworkAwareSelector(m).select_worker(
        [1, 2, 3], dict(overlaps), 64, active, cfg
    )
    assert aware.worker_id == base.worker_id
    assert aware.overlap_blocks == base.overlap_blocks


def test_network_aware_selector_avoids_loaded_and_hints_cheap_source():
    from dynamo_tpu.llm.kv_router.protocols import (
        ForwardPassMetrics, KvStats, WorkerStats,
    )

    def fpm(waiting):
        return ForwardPassMetrics(
            worker_id=0,
            worker=WorkerStats(
                request_active_slots=0, request_total_slots=4,
                num_requests_waiting=waiting,
            ),
            kv=KvStats(
                kv_active_blocks=0, kv_total_blocks=64,
                gpu_cache_usage_perc=0.0, gpu_prefix_cache_hit_rate=0.0,
            ),
        )

    # Worker 1 overlaps best but carries a deep queue; worker 2 is idle
    # and can pull the difference from cheap worker 3.
    view = {1: fpm(10), 2: fpm(0), 3: fpm(0)}
    m = NetCostModel(recompute_ms_per_block=2.0, fleet_view=lambda: view,
                     cache_s=0.0)
    m.observe_pull(3, 10, 2.0)      # worker 3: 0.2 ms/block, ratio 0.1
    active = ActiveSequences(block_size=8)
    cfg = RouterConfig(temperature=0.0, block_size=8, queue_weight=2.0)
    sel = NetworkAwareSelector(m).select_worker(
        [1, 2], {1: 8, 2: 0, 3: 8}, 64, active, cfg
    )
    assert sel.worker_id == 2, "queue depth ignored"
    assert sel.pull_hint is not None
    source, blocks = sel.pull_hint
    assert source == 3 and blocks == 8


# -- fleet harness e2e -------------------------------------------------------


def _mini_tenants():
    return default_tenants(scale=0.5, users=20_000)


def test_fleet_ab_planner_beats_equal_budget_static():
    """The test-scale autoscaling A/B (one diurnal period): the closed
    loop tracks the swing, the same mean budget frozen in time misses
    it. BENCH_r12.json pins the full-size claim; this guards the
    mechanism in tier-1."""
    def spec(on, static=0):
        return FleetSpec(
            tenants=default_tenants(), duration_s=240.0, seed=0,
            planner_on=on, static_replicas=static, initial_replicas=4,
            max_replicas=16, keep_streams=True,
        )

    planner = FleetHarness(spec(True)).run()
    budget = max(1, round(planner.mean_replicas))
    static = FleetHarness(spec(False, static=budget)).run()

    assert planner.broken_streams == 0 and static.broken_streams == 0
    assert planner.requests == static.requests > 5000
    assert planner.attainment_ttft >= 0.95, planner.summary()
    assert static.attainment_ttft < 0.85, static.summary()
    assert planner.attainment_ttft > static.attainment_ttft + 0.1
    # Equal budget, honestly: within 15% of the frozen pool.
    assert planner.mean_replicas <= budget * 1.15
    # The loop actually closed — both directions actuated, drains real.
    assert planner.scale_ups >= 2 and planner.scale_downs >= 2
    assert planner.drained_retired >= 1, planner.summary()
    assert planner.decisions["scale_up"] >= 2
    # Identical completed requests stream identical bytes across
    # scenarios (completions only — static sheds under the peak).
    compared = 0
    for rid, toks in planner.streams.items():
        other = static.streams.get(rid)
        if toks and other and len(other) == len(toks):
            assert other == toks, f"stream {rid} diverged across scenarios"
            compared += 1
    assert compared >= 1, "no completed request overlapped both scenarios"


def test_fleet_routing_ab_shifts_off_slow_peer():
    """NetKV at test scale: placement AND pulls shift off the slow,
    loaded peer; cohort TTFT improves; streams byte-identical."""
    r = run_routing_ab(duration_s=30.0)
    base, aware = r["overlap_only"], r["network_aware"]
    assert aware.streams == base.streams, "routing changed a stream"
    assert base.broken_streams == aware.broken_streams == 0
    slow = 0
    assert aware.pulls_by_source.get(slow, 0) * 4 <= base.pulls_by_source.get(slow, 1)
    assert aware.placements.get(slow, 0) * 2 <= base.placements.get(slow, 1)
    assert aware.ttft_p99_ms < base.ttft_p99_ms


def test_fleet_scale_down_drains_bit_identically():
    """Scale-down during active decode: the drained worker finishes
    every accepted stream before retiring, and the cohort's bytes match
    a run that never scaled at all."""
    tenants = [TenantSpec(name="t", users=500, rps=10.0, isl=32, osl=8,
                          shared_prefix_tokens=16)]

    def spec(chaos):
        return FleetSpec(
            tenants=tenants, duration_s=40.0, seed=3, planner_on=False,
            static_replicas=3, keep_streams=True, chaos=chaos,
        )

    baseline = FleetHarness(spec([])).run()
    h = FleetHarness(spec([ChaosEvent(t=15.0, action="drain", worker=1)]))
    drained = h.run()
    assert drained.broken_streams == 0
    assert drained.drained_retired == 1
    assert drained.streams == baseline.streams, (
        "drain changed client-visible bytes"
    )
    # The drained worker really was mid-work when told to go.
    w1 = [rid for rid, rec in h.recs.items() if 1 in rec.workers]
    assert w1, "worker 1 never held work — drain untested"
    # And no placements landed on it after the drain point.
    for rec in h.recs.values():
        if rec.arrival.t > 15.0:
            assert 1 not in rec.workers


def test_fleet_kill_during_scale_down_degrades_to_migration():
    """Chaos kill of a DRAINING worker mid-decode: the drain's
    completion promise degrades to the PR 6 migration replay — streams
    still finish byte-identical to the no-fault run."""
    tenants = [TenantSpec(name="t", users=500, rps=20.0, isl=32, osl=8,
                          shared_prefix_tokens=16)]

    def spec(chaos):
        return FleetSpec(
            tenants=tenants, duration_s=40.0, seed=3, planner_on=False,
            static_replicas=3, keep_streams=True, chaos=chaos,
        )

    baseline = FleetHarness(spec([])).run()
    h = FleetHarness(spec([
        ChaosEvent(t=15.0, action="drain", worker=1),
        # 100 ms later, while the drain is mid-flight: kill the victim.
        ChaosEvent(t=15.1, action="kill", worker=-1),
    ]))
    killed = h.run()
    assert killed.migrations >= 1, "kill hit an already-empty worker"
    assert killed.broken_streams == 0
    assert killed.drained_retired == 0, "killed worker counted as drained"
    assert killed.streams == baseline.streams, (
        "kill-during-drain broke a stream"
    )


def test_fleet_partition_degrades_to_recompute():
    """A partitioned peer fails pulls (charged, measured) — requests
    recompute locally and every stream still completes identically."""
    tenants = [TenantSpec(name="t", users=300, rps=8.0, isl=64, osl=6,
                          shared_prefix_tokens=48)]

    def spec(chaos):
        return FleetSpec(
            tenants=tenants, duration_s=30.0, seed=5, planner_on=False,
            static_replicas=3, keep_streams=True, chaos=chaos,
        )

    baseline = FleetHarness(spec([])).run()
    cut = FleetHarness(spec([
        ChaosEvent(t=5.0, action="partition", worker=0, duration_s=20.0),
    ])).run()
    assert cut.failed_pulls > 0, "partition never intercepted a pull"
    assert cut.broken_streams == 0
    assert cut.streams == baseline.streams


def test_mocker_profile_matches_cost_model():
    prof = mocker_profile(20_000.0, 100.0, 5_000.0, 4)
    p, d = from_profile(prof)
    # One 128-token prefill iteration: 20 ms + 128*0.1 ms.
    assert p.ttft_at(128) == pytest.approx(0.0328)
    # One decode iteration at full batch: 20 ms + 4*5 ms.
    assert d.itl_at(4) == pytest.approx(0.040)


def test_bench_r12_recorded_and_holds_the_bar():
    """The acceptance numbers are pinned IN THE REPO: BENCH_r12.json is
    the full-size run of bench.run_fleet_ab, re-asserted here so a
    regression that silently weakens the recorded claim fails tier-1."""
    path = REPO / "BENCH_r12.json"
    r = json.loads(path.read_text())
    assert r["value"] >= 0.95                      # planner attainment
    rows = {row["config"]: row for row in r["rows"]}
    planner = next(v for k, v in rows.items() if k.startswith("planner"))
    static = next(v for k, v in rows.items() if k.startswith("static"))
    assert planner["attainment_ttft"] >= 0.95
    assert static["attainment_ttft"] < 0.8
    assert planner["broken_streams"] == 0 and static["broken_streams"] == 0
    assert planner["mean_replicas"] <= r["static_budget_replicas"] * 1.15
    assert planner["goodput_tok_s"] > 0
    rt = r["routing_ab"]
    assert rt["streams_bit_identical"] is True
    assert (
        rt["slow_peer_placements"]["network_aware"] * 4
        <= rt["slow_peer_placements"]["overlap_only"]
    )
    assert (
        rt["slow_peer_pull_blocks"]["network_aware"] * 4
        <= rt["slow_peer_pull_blocks"]["overlap_only"]
    )
    assert rt["ttft_p99_ratio"] < 1.0
