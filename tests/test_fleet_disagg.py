"""Fleet-harness disagg topology (ISSUE 17): the parity A/B, the
streaming-vs-legacy handoff gap, per-pool autoscaling, and the chaos
degradation contract — all on the virtual clock, through the production
`choose_decode_target` chooser and planner controller."""

from dynamo_tpu.fleet.harness import (
    ChaosEvent,
    FleetHarness,
    FleetSpec,
    disagg_tenants,
    run_disagg_ab,
)
from dynamo_tpu.planner.planner_core import SlaTargets


def _match_streams(a: dict, b: dict) -> int:
    """Assert byte-identity for every request that completed with the
    same length in both runs; return how many were compared."""
    compared = 0
    for rid, toks in a.items():
        other = b.get(rid)
        if toks and other and len(other) == len(toks):
            assert other == toks, f"stream {rid} diverged"
            compared += 1
    return compared


def test_disagg_ab_parity_ttft_and_byte_identity():
    """The headline acceptance (ISSUE 17): at EQUAL replica budget over
    the 4x diurnal swing, streaming disagg holds total latency within
    1.1x of aggregated, TTFT attainment at or above it, and every
    stream byte-identical — disagg only moves where tokens are
    computed."""
    r = run_disagg_ab(duration_s=90.0, seed=0)
    agg, dis = r["agg"], r["disagg"]
    # Equal budget by construction (both arms static at the same size).
    assert agg.replica_seconds == dis.replica_seconds
    assert agg.broken_streams == 0 and dis.broken_streams == 0
    assert agg.shed == 0 and dis.shed == 0
    # Total-latency parity: the streaming handoff hides the transfer.
    assert dis.e2e_p50_ms <= 1.1 * agg.e2e_p50_ms, (
        agg.summary(),
        dis.summary(),
    )
    # First-token attainment holds (long prefills left the decode batch).
    assert dis.attainment_ttft >= agg.attainment_ttft
    # The topology actually engaged: long prompts ran remote and every
    # handoff streamed.
    assert dis.remote_prefills > 100
    assert dis.handoffs_streamed == dis.remote_prefills
    assert dis.handoff_fallbacks == 0
    assert dis.handoff_blocks > 0
    assert agg.remote_prefills == 0
    compared = _match_streams(agg.streams, dis.streams)
    assert compared == agg.completed == dis.completed


def test_disagg_streaming_beats_legacy_pull():
    """The before/after of the whole PR: pull-after-prefill serializes
    the full KV transfer behind prefill and shows up in every stream's
    latency; the chunk-pipelined handoff leaves only the tail window in
    flight. Same fleet, same arrivals, byte-identical streams."""
    legacy = run_disagg_ab(duration_s=60.0, seed=1, streaming=False)
    stream = run_disagg_ab(duration_s=60.0, seed=1, streaming=True)
    leg, st, agg = legacy["disagg"], stream["disagg"], stream["agg"]
    assert leg.broken_streams == 0 and st.broken_streams == 0
    # Legacy is the measured liability; streaming is parity.
    assert st.e2e_p50_ms <= 1.1 * agg.e2e_p50_ms
    assert leg.e2e_p50_ms > 1.15 * agg.e2e_p50_ms, (
        "legacy pull no longer shows the serialization cost the "
        "streaming handoff exists to remove"
    )
    assert leg.e2e_p50_ms > st.e2e_p50_ms
    # Handoff mechanics identical apart from timing.
    assert leg.handoffs_streamed == st.handoffs_streamed
    assert leg.streams == st.streams, "handoff pacing changed bytes"


def test_disagg_sever_mid_handoff_bit_identical():
    """The degradation contract on the critical path: sever the
    prefill->decode links mid-run (every handoff in the window fails at
    a chunk boundary) — each affected request degrades to local
    recompute on its decode worker and completes bit-identically to the
    no-fault run."""
    base = run_disagg_ab(duration_s=60.0, seed=0)["disagg"]
    cut = run_disagg_ab(
        duration_s=60.0,
        seed=0,
        chaos_disagg=[
            # Workers 0-2 are the prefill pool (spawned first at
            # prefill_fraction=0.5 of 6).
            ChaosEvent(t=15.0, action="partition", worker=0, duration_s=15.0),
            ChaosEvent(t=15.0, action="partition", worker=1, duration_s=15.0),
            ChaosEvent(t=15.0, action="partition", worker=2, duration_s=15.0),
        ],
    )["disagg"]
    assert cut.handoff_fallbacks > 0, "sever window never hit a handoff"
    assert cut.failed_pulls >= cut.handoff_fallbacks
    assert cut.broken_streams == 0 and cut.shed == 0
    assert cut.streams == base.streams, (
        "sever mid-handoff changed client-visible bytes"
    )


def test_disagg_kill_mid_run_migrates_bit_identically():
    """Chaos kill of a prefill worker (mid-prompt work dies before any
    handoff) and of a decode worker (continuations die mid-stream):
    both degrade through the migration replay and every stream still
    matches the no-fault run."""
    base = run_disagg_ab(duration_s=60.0, seed=3)["disagg"]
    killed = run_disagg_ab(
        duration_s=60.0,
        seed=3,
        chaos_disagg=[
            ChaosEvent(t=20.0, action="kill", worker=0),   # prefill pool
            ChaosEvent(t=35.0, action="kill", worker=4),   # decode pool
        ],
    )["disagg"]
    assert killed.migrations >= 1, "kills hit empty workers — untested"
    assert killed.broken_streams == 0
    compared = _match_streams(base.streams, killed.streams)
    assert compared > 100


def test_disagg_planner_shifts_pool_ratio_live():
    """The planner scales the prefill and decode pools independently
    through the same controller the real fleet runs — the replica ratio
    tracks the diurnal swing instead of being frozen at deploy time."""
    spec = FleetSpec(
        tenants=disagg_tenants(scale=1.5, diurnal_period_s=90.0),
        duration_s=90.0,
        seed=0,
        planner_on=True,
        initial_replicas=6,
        min_replicas=2,
        max_replicas=12,
        disagg=True,
        prefill_fraction=0.5,
        scheduling="waves",
        max_num_seqs=8,
        decode_us_per_seq=500.0,
        pull_ms_per_block=4.0,
        disagg_chunk_blocks=8,
        sla=SlaTargets(ttft_s=0.35, itl_s=0.08),
        keep_streams=False,
    )
    h = FleetHarness(spec)
    report = h.run()
    assert report.broken_streams == 0
    prefill_sizes = {n for _, c, n in h.connector.calls if c == "prefill"}
    decode_sizes = {n for _, c, n in h.connector.calls if c == "decode"}
    # Both pools actuated, each through more than one size — the ratio
    # moved, it wasn't a fixed split scaled in lockstep.
    assert len(prefill_sizes) >= 2, h.connector.calls
    assert len(decode_sizes) >= 2, h.connector.calls
    ratios = {
        (np, nd)
        for (_, cp, np), (_, cd, nd) in zip(
            [x for x in h.connector.calls if x[1] == "prefill"],
            [x for x in h.connector.calls if x[1] == "decode"],
        )
    }
    assert len(ratios) >= 2, "prefill:decode ratio never shifted"
    roles = {w.role for w in h.workers}
    assert roles == {"prefill", "decode"}


def test_disagg_short_prompts_decode_locally():
    """Prompts at or under the remote-prefill threshold never leave the
    decode pool — and produce the same bytes as when everything runs
    remote (the threshold only moves where prefill happens)."""
    remote = run_disagg_ab(duration_s=30.0, seed=2)["disagg"]
    local = run_disagg_ab(
        duration_s=30.0, seed=2, max_local_prefill_tokens=100_000
    )["disagg"]
    assert remote.remote_prefills > 0
    assert local.remote_prefills == 0
    assert local.handoffs_streamed == 0
    assert local.broken_streams == 0
    compared = _match_streams(remote.streams, local.streams)
    assert compared > 0
