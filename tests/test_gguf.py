"""GGUF reader: metadata, tensor index + payloads, config, tokenizer.

Parity: reference `lib/llm/src/gguf/{content,gguf_metadata,
gguf_tokenizer}.rs`. The test synthesizes a spec-conformant GGUF v3 file
byte by byte — no llama.cpp artifacts needed.
"""

import struct

import numpy as np
import pytest

from dynamo_tpu.engine.gguf import (
    GGUFTokenizer,
    config_from_gguf,
    read_gguf,
)

_STR, _U32, _F32V, _ARR = 8, 4, 6, 9


def _s(text: str) -> bytes:
    b = text.encode()
    return struct.pack("<Q", len(b)) + b


def _kv_str(key, val):
    return _s(key) + struct.pack("<I", _STR) + _s(val)


def _kv_u32(key, val):
    return _s(key) + struct.pack("<I", _U32) + struct.pack("<I", val)


def _kv_f32(key, val):
    return _s(key) + struct.pack("<I", _F32V) + struct.pack("<f", val)


def _kv_str_array(key, vals):
    out = _s(key) + struct.pack("<I", _ARR) + struct.pack("<I", _STR)
    out += struct.pack("<Q", len(vals))
    for v in vals:
        out += _s(v)
    return out


@pytest.fixture
def gguf_path(tmp_path):
    tokens = ["<s>", "</s>", "▁hi", "▁there", "a", "b", "<0x21>"]
    meta = (
        _kv_str("general.architecture", "llama")
        + _kv_str("general.name", "tinygguf")
        + _kv_u32("llama.embedding_length", 64)
        + _kv_u32("llama.block_count", 2)
        + _kv_u32("llama.attention.head_count", 4)
        + _kv_u32("llama.attention.head_count_kv", 2)
        + _kv_u32("llama.feed_forward_length", 128)
        + _kv_f32("llama.rope.freq_base", 10000.0)
        + _kv_f32("llama.attention.layer_norm_rms_epsilon", 1e-5)
        + _kv_str_array("tokenizer.ggml.tokens", tokens)
        + _kv_u32("tokenizer.ggml.bos_token_id", 0)
        + _kv_u32("tokenizer.ggml.eos_token_id", 1)
    )
    w = np.arange(12, dtype=np.float32).reshape(3, 4)
    # GGUF dims are innermost-first: (4, 3) for a [3, 4] row-major array.
    tinfo = (
        _s("tok_embd.weight")
        + struct.pack("<I", 2)
        + struct.pack("<QQ", 4, 3)
        + struct.pack("<IQ", 0, 0)  # F32, offset 0
        + _s("blk.0.attn_q.weight")
        + struct.pack("<I", 1)
        + struct.pack("<Q", 8)
        + struct.pack("<IQ", 12, 4096)  # Q4_K, indexed but not loadable
    )
    header = struct.pack("<IIQQ", 0x46554747, 3, 2, 12) + meta + tinfo
    pad = (-len(header)) % 32
    path = tmp_path / "tiny.gguf"
    path.write_bytes(header + b"\0" * pad + w.tobytes())
    return path, tokens, w


def test_reads_metadata_tensors_and_payload(gguf_path):
    path, tokens, w = gguf_path
    g = read_gguf(path)
    assert g.version == 3
    assert g.metadata["general.name"] == "tinygguf"
    assert g.metadata["tokenizer.ggml.tokens"] == tokens
    assert g.tensors["tok_embd.weight"].shape == (3, 4)
    assert g.tensors["blk.0.attn_q.weight"].type_name == "Q4_K"
    np.testing.assert_array_equal(g.load_tensor("tok_embd.weight"), w)
    with pytest.raises(NotImplementedError):
        g.load_tensor("blk.0.attn_q.weight")


def test_config_from_gguf(gguf_path):
    path, tokens, _ = gguf_path
    cfg = config_from_gguf(read_gguf(path))
    assert cfg.hidden_size == 64
    assert cfg.num_layers == 2
    assert cfg.num_heads == 4 and cfg.num_kv_heads == 2
    assert cfg.head_dim == 16
    assert cfg.vocab_size == len(tokens)
    assert cfg.intermediate_size == 128


def test_gguf_tokenizer_roundtrip(gguf_path):
    path, _, _ = gguf_path
    tok = GGUFTokenizer.from_gguf(read_gguf(path))
    ids = tok.encode(" hi there")
    assert ids == [2, 3]
    assert tok.decode(ids) == " hi there"
    # Byte-token fallback + special-token skipping.
    assert tok.decode([0, 6, 1]) == "!"
    assert tok.decode([0, 6, 1], skip_special_tokens=False) != "!"


def test_rejects_non_gguf(tmp_path):
    bad = tmp_path / "bad.gguf"
    bad.write_bytes(b"NOPE" + b"\0" * 64)
    with pytest.raises(ValueError):
        read_gguf(bad)


def test_tokenizer_protocol_surface_and_utf8_bytes(gguf_path):
    """The GGUF tokenizer must satisfy the serving Tokenizer protocol
    (Decoder reads eos_token_id) and treat <0xXX> tokens as raw UTF-8
    BYTES, not code points."""
    path, _, _ = gguf_path
    tok = GGUFTokenizer.from_gguf(read_gguf(path))
    assert tok.eos_token_id == 1 and tok.bos_token_id == 0
    assert tok.vocab_size == 7

    # Multi-byte character round trip through byte tokens.
    euro_tokens = ["<s>", "</s>", "<0xE2>", "<0x82>", "<0xAC>"]
    t2 = GGUFTokenizer(
        tokens=euro_tokens,
        bos_id=0,
        eos_id=1,
        _index={t: i for i, t in enumerate(euro_tokens)},
        _max_token_len=max(len(t) for t in euro_tokens),
    )
    ids = t2.encode("€")
    assert ids == [2, 3, 4]
    assert t2.decode(ids) == "€"
