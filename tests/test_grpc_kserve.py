"""KServe gRPC frontend e2e against a mocker worker."""

import asyncio

import grpc
import pytest

from dynamo_tpu.backends.mocker import run_mocker
from dynamo_tpu.grpc import kserve_pb2 as pb
from dynamo_tpu.grpc.kserve_service import KserveGrpcService
from dynamo_tpu.llm.mocker import MockEngineArgs
from dynamo_tpu.llm.model_manager import ModelManager
from dynamo_tpu.runtime import DistributedRuntime
from dynamo_tpu.runtime.store import StoreServer

pytestmark = [pytest.mark.e2e]

SERVICE = "inference.GRPCInferenceService"


def _method(channel, name, req_cls, resp_cls):
    return channel.unary_unary(
        f"/{SERVICE}/{name}",
        request_serializer=req_cls.SerializeToString,
        response_deserializer=resp_cls.FromString,
    )


async def test_kserve_grpc_end_to_end():
    store = StoreServer()
    await store.start()
    worker_rt = await DistributedRuntime.create(store.address)
    front_rt = await DistributedRuntime.create(store.address)
    served = asyncio.Event()
    worker = asyncio.create_task(
        run_mocker(
            worker_rt, model_name="mock",
            engine_args=MockEngineArgs(speedup_ratio=200.0),
            served_event=served,
        )
    )
    await asyncio.wait_for(served.wait(), 10)

    manager = ModelManager(front_rt, router_mode="kv")
    await manager.start()
    for _ in range(100):
        if manager.list_models():
            break
        await asyncio.sleep(0.05)
    svc = KserveGrpcService(manager, host="127.0.0.1", port=0)
    await svc.start()

    try:
        async with grpc.aio.insecure_channel(f"127.0.0.1:{svc.port}") as ch:
            live = await _method(ch, "ServerLive", pb.ServerLiveRequest, pb.ServerLiveResponse)(
                pb.ServerLiveRequest()
            )
            assert live.live

            ready = await _method(ch, "ServerReady", pb.ServerReadyRequest, pb.ServerReadyResponse)(
                pb.ServerReadyRequest()
            )
            assert ready.ready

            mready = await _method(ch, "ModelReady", pb.ModelReadyRequest, pb.ModelReadyResponse)(
                pb.ModelReadyRequest(name="mock")
            )
            assert mready.ready

            req = pb.ModelInferRequest(model_name="mock", id="t1")
            tensor = req.inputs.add()
            tensor.name = "text_input"
            tensor.datatype = "BYTES"
            tensor.shape.append(1)
            tensor.contents.bytes_contents.append(b"hello kserve")
            req.parameters["max_tokens"].int64_param = 6
            infer = _method(ch, "ModelInfer", pb.ModelInferRequest, pb.ModelInferResponse)
            resp = await infer(req)
            assert resp.model_name == "mock"
            out = resp.outputs[0]
            assert out.name == "text_output"
            assert out.contents.bytes_contents[0] == b"abcdef"  # mocker text

            missing = _method(ch, "ModelReady", pb.ModelReadyRequest, pb.ModelReadyResponse)
            r = await missing(pb.ModelReadyRequest(name="nope"))
            assert not r.ready
    finally:
        await svc.stop()
        await manager.stop()
        for rt in (front_rt, worker_rt):
            rt.signal_shutdown()
        worker.cancel()
        for rt in (front_rt, worker_rt):
            try:
                await rt.shutdown()
            # dynalint: allow-broad-except(best-effort teardown; runtime may already be closed)
            except Exception:
                pass
        await store.stop()
