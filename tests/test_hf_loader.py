"""HF checkpoint loading parity: our forward must match transformers'
logits on the same tiny llama checkpoint."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp  # noqa: E402

from dynamo_tpu.engine.config import EngineConfig  # noqa: E402
from dynamo_tpu.engine.loader import load_hf_llama  # noqa: E402
from dynamo_tpu.engine.model import init_cache, prefill_step_impl  # noqa: E402


@pytest.fixture(scope="module")
def hf_checkpoint(tmp_path_factory):
    cfg = transformers.LlamaConfig(
        vocab_size=128,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(cfg)
    path = tmp_path_factory.mktemp("hf-tiny-llama")
    model.save_pretrained(path)
    return path, model


def test_loader_matches_transformers_logits(hf_checkpoint):
    path, hf_model = hf_checkpoint
    cfg, params = load_hf_llama(path, dtype=jnp.float32)
    assert cfg.num_layers == 2 and cfg.num_kv_heads == 2

    prompt = [3, 17, 42, 99, 7, 64, 23, 5]
    with torch.no_grad():
        want = hf_model(torch.tensor([prompt])).logits[0, -1].numpy()

    eng = EngineConfig(
        num_kv_blocks=16, block_size=8, max_num_seqs=2, max_model_len=64,
        prefill_buckets=(16, 32), decode_buckets=(2,),
    )
    k, v = init_cache(cfg, eng, dtype=jnp.float32)
    table = np.full(eng.max_blocks_per_seq, eng.garbage_block, np.int32)
    table[:2] = [0, 1]
    toks = np.zeros(16, np.int32)
    toks[: len(prompt)] = prompt
    got, _, _ = prefill_step_impl(
        params, jnp.asarray(toks), k, v, jnp.asarray(table),
        jnp.int32(len(prompt)), jnp.int32(0), cfg, eng, kv_span=16,
    )
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-3, atol=2e-3)
