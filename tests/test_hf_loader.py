"""HF checkpoint loading parity: our forward must match transformers'
logits on the same tiny llama checkpoint."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp  # noqa: E402

from dynamo_tpu.engine.config import EngineConfig  # noqa: E402
from dynamo_tpu.engine.loader import load_hf_llama  # noqa: E402
from dynamo_tpu.engine.model import init_cache  # noqa: E402
from tests.model_harness import prefill_chunk  # noqa: E402


@pytest.fixture(scope="module")
def hf_checkpoint(tmp_path_factory):
    cfg = transformers.LlamaConfig(
        vocab_size=128,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(cfg)
    path = tmp_path_factory.mktemp("hf-tiny-llama")
    model.save_pretrained(path)
    return path, model


def test_loader_matches_transformers_logits(hf_checkpoint):
    path, hf_model = hf_checkpoint
    cfg, params = load_hf_llama(path, dtype=jnp.float32)
    assert cfg.num_layers == 2 and cfg.num_kv_heads == 2

    prompt = [3, 17, 42, 99, 7, 64, 23, 5]
    with torch.no_grad():
        want = hf_model(torch.tensor([prompt])).logits[0, -1].numpy()

    eng = EngineConfig(
        num_kv_blocks=16, block_size=8, max_num_seqs=2, max_model_len=64,
        prefill_buckets=(16, 32), decode_buckets=(2,),
    )
    cache = init_cache(cfg, eng, dtype=jnp.float32)
    got, _ = prefill_chunk(params, cache, prompt, 0, [0, 1], cfg, eng, 16)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-3, atol=2e-3)


def test_loader_tp_blocked_layout_matches_tp1(hf_checkpoint):
    """load_hf_llama(tp=2) is a column permutation of tp=1 — same model."""
    from dynamo_tpu.engine.model import split_gu, split_qkv

    path, _ = hf_checkpoint
    cfg, p1 = load_hf_llama(path, dtype=jnp.float32, tp=1)
    _, p2 = load_hf_llama(path, dtype=jnp.float32, tp=2)
    x = np.random.RandomState(0).randn(4, cfg.hidden_size).astype(np.float32)
    for a, b in zip(
        split_qkv(jnp.asarray(x) @ p1["layers"]["wqkv"][0], cfg, 1),
        split_qkv(jnp.asarray(x) @ p2["layers"]["wqkv"][0], cfg, 2),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)
    g1, u1 = split_gu(jnp.asarray(x) @ p1["layers"]["wgu"][0], 1)
    g2, u2 = split_gu(jnp.asarray(x) @ p2["layers"]["wgu"][0], 2)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(u1), np.asarray(u2), rtol=1e-5, atol=1e-5)
