"""HF checkpoint loading parity: our forward must match transformers'
logits on the same tiny llama checkpoint."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp  # noqa: E402

from dynamo_tpu.engine.config import EngineConfig  # noqa: E402
from dynamo_tpu.engine.loader import load_hf_llama  # noqa: E402
from dynamo_tpu.engine.model import init_cache  # noqa: E402
from tests.model_harness import prefill_chunk  # noqa: E402


@pytest.fixture(scope="module")
def hf_checkpoint(tmp_path_factory):
    cfg = transformers.LlamaConfig(
        vocab_size=128,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(cfg)
    path = tmp_path_factory.mktemp("hf-tiny-llama")
    model.save_pretrained(path)
    return path, model


def test_loader_matches_transformers_logits(hf_checkpoint):
    path, hf_model = hf_checkpoint
    cfg, params = load_hf_llama(path, dtype=jnp.float32)
    assert cfg.num_layers == 2 and cfg.num_kv_heads == 2

    prompt = [3, 17, 42, 99, 7, 64, 23, 5]
    with torch.no_grad():
        want = hf_model(torch.tensor([prompt])).logits[0, -1].numpy()

    eng = EngineConfig(
        num_kv_blocks=16, block_size=8, max_num_seqs=2, max_model_len=64,
        prefill_buckets=(16, 32), decode_buckets=(2,),
    )
    cache = init_cache(cfg, eng, dtype=jnp.float32)
    got, _ = prefill_chunk(params, cache, prompt, 0, [0, 1], cfg, eng, 16)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-3, atol=2e-3)


@pytest.fixture(scope="module")
def qwen2_checkpoint(tmp_path_factory):
    cfg = transformers.Qwen2Config(
        vocab_size=128,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        rope_theta=10000.0,
        tie_word_embeddings=False,
        use_sliding_window=False,
    )
    torch.manual_seed(1)
    model = transformers.Qwen2ForCausalLM(cfg)
    path = tmp_path_factory.mktemp("hf-tiny-qwen2")
    model.save_pretrained(path)
    return path, model


def test_qwen2_loader_matches_transformers_logits(qwen2_checkpoint):
    """Qwen2 family: same llama body + qkv biases — the bias must ride
    the fused shard-blocked layout and land in dense_layer's qkv add."""
    path, hf_model = qwen2_checkpoint
    cfg, params = load_hf_llama(path, dtype=jnp.float32)
    assert cfg.attn_qkv_bias and "bqkv" in params["layers"]

    prompt = [3, 17, 42, 99, 7, 64, 23, 5]
    with torch.no_grad():
        want = hf_model(torch.tensor([prompt])).logits[0, -1].numpy()

    eng = EngineConfig(
        num_kv_blocks=16, block_size=8, max_num_seqs=2, max_model_len=64,
        prefill_buckets=(16, 32), decode_buckets=(2,),
    )
    cache = init_cache(cfg, eng, dtype=jnp.float32)
    got, _ = prefill_chunk(params, cache, prompt, 0, [0, 1], cfg, eng, 16)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-3, atol=2e-3)


def test_qwen2_tp_layout_same_model(qwen2_checkpoint):
    """tp=2-fused qwen2 params (weights AND biases) describe the same
    model: sharded engine output matches the tp=1 load exactly."""
    from dynamo_tpu.parallel.sharding import (
        cache_sharding,
        make_mesh,
        shard_params,
    )
    from tests.model_harness import prefill_chunk as chunk

    path, _ = qwen2_checkpoint
    cfg, p1 = load_hf_llama(path, dtype=jnp.float32, tp=1)
    _, p2 = load_hf_llama(path, dtype=jnp.float32, tp=2)
    eng = EngineConfig(
        num_kv_blocks=16, block_size=8, max_num_seqs=2, max_model_len=64,
        prefill_buckets=(16, 32), decode_buckets=(2,),
    )
    prompt = [5, 9, 100, 42, 77]
    want, _ = chunk(p1, init_cache(cfg, eng, dtype=jnp.float32), prompt, 0,
                    [0], cfg, eng, 16)
    import jax

    mesh = make_mesh(dp=1, tp=2)
    sp = shard_params(p2, cfg, mesh)
    cd = jax.device_put(
        init_cache(cfg, eng, dtype=jnp.float32), cache_sharding(mesh)
    )
    got, _ = chunk(sp, cd, prompt, 0, [0], cfg, eng, 16, mesh=mesh)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4
    )


def test_loader_host_side_int8_matches_device_quantize(hf_checkpoint):
    """load_hf_llama(quant='int8') quantizes host-side (the device never
    holds the bf16 footprint — the 8B-on-16GB mode); its values must
    match quantize_params applied after a plain load."""
    from dynamo_tpu.engine.model import quantize_params

    path, _ = hf_checkpoint
    cfg, p_host = load_hf_llama(path, dtype=jnp.float32, quant="int8")
    _, p_plain = load_hf_llama(path, dtype=jnp.float32)
    p_dev = quantize_params(p_plain)
    for k in ("wqkv", "wo", "wgu", "w_down"):
        np.testing.assert_array_equal(
            np.asarray(p_host["layers"][k]["w"]),
            np.asarray(p_dev["layers"][k]["w"]),
        )
        np.testing.assert_allclose(
            np.asarray(p_host["layers"][k]["scale"]),
            np.asarray(p_dev["layers"][k]["scale"]), rtol=1e-6,
        )
    np.testing.assert_array_equal(
        np.asarray(p_host["lm_head"]["w"]), np.asarray(p_dev["lm_head"]["w"])
    )


def test_loader_tp_blocked_layout_matches_tp1(hf_checkpoint):
    """load_hf_llama(tp=2) is a column permutation of tp=1 — same model."""
    from dynamo_tpu.engine.model import split_gu, split_qkv

    path, _ = hf_checkpoint
    cfg, p1 = load_hf_llama(path, dtype=jnp.float32, tp=1)
    _, p2 = load_hf_llama(path, dtype=jnp.float32, tp=2)
    x = np.random.RandomState(0).randn(4, cfg.hidden_size).astype(np.float32)
    for a, b in zip(
        split_qkv(jnp.asarray(x) @ p1["layers"]["wqkv"][0], cfg, 1),
        split_qkv(jnp.asarray(x) @ p2["layers"]["wqkv"][0], cfg, 2),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)
    g1, u1 = split_gu(jnp.asarray(x) @ p1["layers"]["wgu"][0], 1)
    g2, u2 = split_gu(jnp.asarray(x) @ p2["layers"]["wgu"][0], 2)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(u1), np.asarray(u2), rtol=1e-5, atol=1e-5)
