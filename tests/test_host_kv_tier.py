"""Host KV tier (G2): offload on HBM eviction, onboard on prefix hit,
deterministic output across the round trip.

Parity: reference KVBM offload tier (`block_manager/offload.rs`) and its
determinism tests (`tests/kvbm/test_determinism.py`).
"""

import numpy as np
import pytest

from dynamo_tpu.engine import EngineCore, tiny_engine, tiny_model
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from tests.test_engine_core import _req, run_to_completion

CFG = tiny_model()


def make_core(**kw) -> EngineCore:
    return EngineCore(CFG, tiny_engine(**kw), seed=0)


def _fill_with_noise(core, n_requests=6, tag=1000):
    """Run distinct requests to push earlier blocks out of HBM."""
    rng = np.random.RandomState(tag)
    seqs = [
        core.add_request(
            _req(list(rng.randint(1, 300, size=40)), f"noise-{tag}-{i}", max_tokens=4)
        )
        for i in range(n_requests)
    ]
    run_to_completion(core, seqs)


def test_offload_and_onboard_roundtrip_is_deterministic():
    # Ground truth without any memory pressure.
    base = make_core()
    prompt = list(range(7, 7 + 40))
    ref_seq = base.add_request(_req(prompt, "ref", max_tokens=6))
    ref, _ = run_to_completion(base, [ref_seq])

    # Tiny HBM pool + host tier: noise evicts the prompt's blocks to host.
    core = make_core(num_kv_blocks=24, host_kv_blocks=64, max_model_len=128)
    s1 = core.add_request(_req(prompt, "a", max_tokens=6))
    run_to_completion(core, [s1])
    _fill_with_noise(core, n_requests=6)
    core.offload.flush()  # offload is async; land in-flight transfers
    assert core.host_pool.stats.offloads > 0, "nothing was offloaded to host"

    # The prompt's blocks must now be (at least partly) host-resident.
    s2 = core.add_request(_req(prompt, "b", max_tokens=6))
    d2, _ = run_to_completion(core, [s2])
    assert core.host_pool.stats.onboards > 0, "no host blocks onboarded"
    assert s2.num_cached_tokens > 0
    assert d2["b"] == ref["ref"], "output changed across offload/onboard"


def test_host_pool_lru_eviction_emits_removed():
    removed: list[int] = []
    core = EngineCore(
        CFG,
        tiny_engine(num_kv_blocks=24, host_kv_blocks=4, max_model_len=128),
        seed=0,
        on_removed=lambda hs: removed.extend(hs),
    )
    # Lots of distinct content: device evicts to host; tiny host pool
    # evicts onward, emitting `removed` (the worker truly forgot those).
    _fill_with_noise(core, n_requests=8, tag=1)
    _fill_with_noise(core, n_requests=8, tag=2)
    core.offload.flush()
    assert core.host_pool.stats.evictions > 0
    assert len(removed) >= core.host_pool.stats.evictions


def test_host_tier_disabled_by_default():
    core = make_core()
    assert core.host_pool is None


def test_disk_tier_roundtrip_is_deterministic(tmp_path):
    """G3: blocks demoted device->host->disk onboard back with identical
    greedy output (parity: reference tests/kvbm/test_determinism.py:489,
    block_manager/storage/disk.rs)."""
    base = make_core()
    prompt = list(range(11, 11 + 40))
    ref_seq = base.add_request(_req(prompt, "ref", max_tokens=6))
    ref, _ = run_to_completion(base, [ref_seq])

    # Tiny HBM pool AND tiny host pool: noise pushes the prompt's blocks
    # all the way to disk.
    core = make_core(
        num_kv_blocks=24,
        host_kv_blocks=4,
        disk_kv_dir=str(tmp_path / "g3"),
        disk_kv_blocks=256,
        max_model_len=128,
    )
    s1 = core.add_request(_req(prompt, "a", max_tokens=6))
    run_to_completion(core, [s1])
    _fill_with_noise(core, n_requests=8)
    _fill_with_noise(core, n_requests=8, tag=2000)
    core.offload.flush()
    assert core.disk_pool.stats.offloads > 0, "nothing reached the disk tier"

    s2 = core.add_request(_req(prompt, "b", max_tokens=6))
    d2, _ = run_to_completion(core, [s2])
    assert core.disk_pool.stats.onboards > 0, "no disk blocks onboarded"
    assert s2.num_cached_tokens > 0
    assert d2["b"] == ref["ref"], "output changed across disk offload/onboard"


def test_disk_tier_eviction_emits_removed(tmp_path):
    removed: list[int] = []
    core = EngineCore(
        CFG,
        tiny_engine(
            num_kv_blocks=24,
            host_kv_blocks=4,
            disk_kv_dir=str(tmp_path / "g3"),
            disk_kv_blocks=4,
            max_model_len=128,
        ),
        seed=0,
        on_removed=lambda hs: removed.extend(hs),
    )
    for tag in (1, 2, 3):
        _fill_with_noise(core, n_requests=8, tag=tag)
    core.offload.flush()
    assert core.disk_pool.stats.evictions > 0
    assert len(removed) >= core.disk_pool.stats.evictions
    # Host evictions demoted (did not emit removal): the worker forgot
    # only what fell off the END of the tier chain.
    assert core.host_pool.stats.evictions >= core.disk_pool.stats.offloads


def test_onboard_roundtrip_preserves_bytes_and_event_accounting():
    """The ISSUE 5 satellite contract: evict -> host tier ->
    _onboard_from_host must hand back the EXACT page bytes, and the
    router-facing events must fire exactly once across the round trip —
    stored once at the original commit (demotion to host is not removal,
    onboarding is not a re-store), removed never (the host pool is big
    enough to hold everything)."""
    import jax.numpy as jnp

    stored: list[int] = []
    removed: list[int] = []
    core = EngineCore(
        CFG,
        tiny_engine(num_kv_blocks=24, host_kv_blocks=64, max_model_len=128),
        seed=0,
        on_stored=lambda hs, parent: stored.extend(hs),
        on_removed=lambda hs: removed.extend(hs),
    )
    prompt = list(range(7, 7 + 40))
    s1 = core.add_request(_req(prompt, "a", max_tokens=4))
    ref, _ = run_to_completion(core, [s1])
    bs = core.engine.block_size
    cap = (len(prompt) - 1) // bs  # the onboardable prefix (admission cap)
    prefix_hashes = s1.prompt_hashes[:cap]
    assert [stored.count(h) for h in prefix_hashes] == [1] * cap
    # Snapshot the committed prefix pages while still device-resident.
    byte0 = {}
    for h in prefix_hashes:
        bid = core.allocator._by_hash[h].block_id
        byte0[h] = np.asarray(
            core._slice_page(core.cache, jnp.int32(bid))
        ).tobytes()

    _fill_with_noise(core, n_requests=6)
    core.offload.flush()
    evicted = [h for h in prefix_hashes if h in core.host_pool]
    assert evicted, "noise did not push the prefix to the host tier"
    for h in evicted:
        assert core.host_pool._blocks[h].kv.tobytes() == byte0[h], (
            "host-tier page bytes diverged from the device original"
        )
    # Demotion to host is NOT removal: the block is still onboardable.
    assert not set(prefix_hashes) & set(removed)

    s2 = core.add_request(_req(prompt, "b", max_tokens=4))
    d2, _ = run_to_completion(core, [s2])
    assert core.host_pool.stats.onboards > 0, "no host blocks onboarded"
    assert s2.num_cached_tokens >= cap * bs
    assert d2["b"] == ref["a"], "output changed across the round trip"
    # Back on device with identical bytes.
    for h in evicted:
        bid = core.allocator._by_hash[h].block_id
        assert np.asarray(
            core._slice_page(core.cache, jnp.int32(bid))
        ).tobytes() == byte0[h], "onboarded page bytes diverged"
    # Exactly-once events across the whole round trip: onboarding
    # registers with emit=False, so no duplicate stored; nothing removed.
    for h in prefix_hashes:
        assert stored.count(h) == 1, f"stored re-emitted for {h:#x}"
        assert removed.count(h) == 0, f"removed emitted for live block {h:#x}"


def test_host_pool_removal_events_fire_exactly_once():
    """Host-pool LRU evictions emit `removed` exactly once per hash —
    a double removal would poison the router's radix view."""
    from collections import Counter

    removed: list[int] = []
    core = EngineCore(
        CFG,
        tiny_engine(num_kv_blocks=24, host_kv_blocks=4, max_model_len=128),
        seed=0,
        on_removed=lambda hs: removed.extend(hs),
    )
    _fill_with_noise(core, n_requests=8, tag=11)
    _fill_with_noise(core, n_requests=8, tag=12)
    core.offload.flush()
    assert core.host_pool.stats.evictions > 0
    dupes = {h: c for h, c in Counter(removed).items() if c > 1}
    assert not dupes, f"removed emitted more than once: {dupes}"


def test_offload_engine_preserves_bytes_across_tiers(tmp_path):
    """Direct pipeline unit: pages submitted through the async offload
    worker land in host/disk tiers byte-identical, with parent links
    intact, and fetch() pops them back unchanged."""
    from dynamo_tpu.engine.host_cache import HostKvPool
    from dynamo_tpu.engine.offload import DiskKvPool, OffloadEngine

    host = HostKvPool(2)
    disk = DiskKvPool(tmp_path / "g3", 8)
    eng = OffloadEngine(host, disk)
    rng = np.random.RandomState(0)
    pages = {h: rng.randn(2, 8, 4, 16).astype(np.float32) for h in (101, 102, 103)}
    parent = None
    want_parent = {}
    for h, page in pages.items():
        eng.submit(h, parent, page.copy())
        want_parent[h] = parent
        parent = h
    eng.flush()
    # 3 blocks through a 2-block host pool: the oldest demoted to disk.
    assert len(host) == 2 and len(disk) == 1
    for h, page in pages.items():
        got = eng.fetch(h)
        assert got is not None, f"block {h} lost in the tiers"
        p, kv = got
        assert p == want_parent[h]
        assert np.asarray(kv).tobytes() == page.tobytes()
    eng.close()


def test_disk_put_survives_crash_mid_write(tmp_path, monkeypatch):
    """ISSUE 8 satellite: DiskKvPool.put writes via tmp file +
    os.replace, so a crash mid-write can never leave a torn block at the
    final path that a later peek()/pop() would onboard as corrupt KV.
    Simulated partial write: np.save dumps half the bytes, then dies."""
    from dynamo_tpu.engine import offload as offload_mod
    from dynamo_tpu.engine.offload import DiskKvPool

    disk = DiskKvPool(tmp_path / "g3", 8)
    page = np.arange(2 * 8 * 4 * 16, dtype=np.float32).reshape(2, 8, 4, 16)

    real_save = offload_mod.np.save

    def torn_save(f, arr):
        # Write a believable partial .npy (header + some data), then die
        # the way ENOSPC / SIGKILL would.
        import io

        buf = io.BytesIO()
        real_save(buf, arr)
        f.write(buf.getvalue()[: buf.getbuffer().nbytes // 2])
        raise OSError("simulated crash mid-write")

    monkeypatch.setattr(offload_mod.np, "save", torn_save)
    with pytest.raises(OSError):
        disk.put(0xBAD, None, page)
    monkeypatch.setattr(offload_mod.np, "save", real_save)

    # Nothing torn is visible: not indexed, not readable, no final file,
    # and the tmp file was cleaned up.
    assert 0xBAD not in disk
    assert disk.peek(0xBAD) is None and disk.pop(0xBAD) is None
    assert not disk._path(0xBAD).exists()
    assert not list((tmp_path / "g3").glob("*.tmp"))

    # The pool still works after the failed write, and a retry of the
    # SAME hash lands the full bytes.
    disk.put(0xBAD, None, page)
    assert disk.peek(0xBAD).tobytes() == page.tobytes()
    got = disk.pop(0xBAD)
    assert got is not None and got[1].tobytes() == page.tobytes()


def test_offload_does_not_block_step():
    """Evictions must not run device->host copies inside step(): with the
    transfer worker stalled, steps that trigger evictions still complete
    (the old synchronous path would deadlock/stall here)."""
    import threading
    import time as _time

    core = make_core(num_kv_blocks=24, host_kv_blocks=64, max_model_len=128)
    # Stall the worker: occupy the queue with a sentinel the worker
    # blocks on (a threading.Event disguised as a device page).
    gate = threading.Event()

    class SlowPage:
        def __array__(self, dtype=None):
            gate.wait(timeout=30)
            import numpy as _np

            return _np.zeros(1, dtype=_np.float32)

    core.offload.submit(-1, None, SlowPage())
    # These runs evict plenty of blocks; all their transfers queue behind
    # the stalled one. Steps must still finish promptly.
    t0 = _time.monotonic()
    _fill_with_noise(core, n_requests=8, tag=77)
    _fill_with_noise(core, n_requests=8, tag=78)
    elapsed = _time.monotonic() - t0
    assert core.offload._q.qsize() >= 0  # transfers queued, engine done
    gate.set()
    core.offload.flush()
    assert elapsed < 25, f"steps stalled behind offload transfers ({elapsed:.1f}s)"
