"""Host KV tier (G2): offload on HBM eviction, onboard on prefix hit,
deterministic output across the round trip.

Parity: reference KVBM offload tier (`block_manager/offload.rs`) and its
determinism tests (`tests/kvbm/test_determinism.py`).
"""

import numpy as np

from dynamo_tpu.engine import EngineCore, tiny_engine, tiny_model
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from tests.test_engine_core import _req, run_to_completion

CFG = tiny_model()


def make_core(**kw) -> EngineCore:
    return EngineCore(CFG, tiny_engine(**kw), seed=0)


def _fill_with_noise(core, n_requests=6, tag=1000):
    """Run distinct requests to push earlier blocks out of HBM."""
    rng = np.random.RandomState(tag)
    seqs = [
        core.add_request(
            _req(list(rng.randint(1, 300, size=40)), f"noise-{tag}-{i}", max_tokens=4)
        )
        for i in range(n_requests)
    ]
    run_to_completion(core, seqs)


def test_offload_and_onboard_roundtrip_is_deterministic():
    # Ground truth without any memory pressure.
    base = make_core()
    prompt = list(range(7, 7 + 40))
    ref_seq = base.add_request(_req(prompt, "ref", max_tokens=6))
    ref, _ = run_to_completion(base, [ref_seq])

    # Tiny HBM pool + host tier: noise evicts the prompt's blocks to host.
    core = make_core(num_kv_blocks=24, host_kv_blocks=64, max_model_len=128)
    s1 = core.add_request(_req(prompt, "a", max_tokens=6))
    run_to_completion(core, [s1])
    _fill_with_noise(core, n_requests=6)
    assert core.host_pool.stats.offloads > 0, "nothing was offloaded to host"

    # The prompt's blocks must now be (at least partly) host-resident.
    s2 = core.add_request(_req(prompt, "b", max_tokens=6))
    d2, _ = run_to_completion(core, [s2])
    assert core.host_pool.stats.onboards > 0, "no host blocks onboarded"
    assert s2.num_cached_tokens > 0
    assert d2["b"] == ref["ref"], "output changed across offload/onboard"


def test_host_pool_lru_eviction_emits_removed():
    removed: list[int] = []
    core = EngineCore(
        CFG,
        tiny_engine(num_kv_blocks=24, host_kv_blocks=4, max_model_len=128),
        seed=0,
        on_removed=lambda hs: removed.extend(hs),
    )
    # Lots of distinct content: device evicts to host; tiny host pool
    # evicts onward, emitting `removed` (the worker truly forgot those).
    _fill_with_noise(core, n_requests=8, tag=1)
    _fill_with_noise(core, n_requests=8, tag=2)
    assert core.host_pool.stats.evictions > 0
    assert len(removed) >= core.host_pool.stats.evictions


def test_host_tier_disabled_by_default():
    core = make_core()
    assert core.host_pool is None
