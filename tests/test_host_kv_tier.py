"""Host KV tier (G2): offload on HBM eviction, onboard on prefix hit,
deterministic output across the round trip.

Parity: reference KVBM offload tier (`block_manager/offload.rs`) and its
determinism tests (`tests/kvbm/test_determinism.py`).
"""

import numpy as np

from dynamo_tpu.engine import EngineCore, tiny_engine, tiny_model
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from tests.test_engine_core import _req, run_to_completion

CFG = tiny_model()


def make_core(**kw) -> EngineCore:
    return EngineCore(CFG, tiny_engine(**kw), seed=0)


def _fill_with_noise(core, n_requests=6, tag=1000):
    """Run distinct requests to push earlier blocks out of HBM."""
    rng = np.random.RandomState(tag)
    seqs = [
        core.add_request(
            _req(list(rng.randint(1, 300, size=40)), f"noise-{tag}-{i}", max_tokens=4)
        )
        for i in range(n_requests)
    ]
    run_to_completion(core, seqs)


def test_offload_and_onboard_roundtrip_is_deterministic():
    # Ground truth without any memory pressure.
    base = make_core()
    prompt = list(range(7, 7 + 40))
    ref_seq = base.add_request(_req(prompt, "ref", max_tokens=6))
    ref, _ = run_to_completion(base, [ref_seq])

    # Tiny HBM pool + host tier: noise evicts the prompt's blocks to host.
    core = make_core(num_kv_blocks=24, host_kv_blocks=64, max_model_len=128)
    s1 = core.add_request(_req(prompt, "a", max_tokens=6))
    run_to_completion(core, [s1])
    _fill_with_noise(core, n_requests=6)
    core.offload.flush()  # offload is async; land in-flight transfers
    assert core.host_pool.stats.offloads > 0, "nothing was offloaded to host"

    # The prompt's blocks must now be (at least partly) host-resident.
    s2 = core.add_request(_req(prompt, "b", max_tokens=6))
    d2, _ = run_to_completion(core, [s2])
    assert core.host_pool.stats.onboards > 0, "no host blocks onboarded"
    assert s2.num_cached_tokens > 0
    assert d2["b"] == ref["ref"], "output changed across offload/onboard"


def test_host_pool_lru_eviction_emits_removed():
    removed: list[int] = []
    core = EngineCore(
        CFG,
        tiny_engine(num_kv_blocks=24, host_kv_blocks=4, max_model_len=128),
        seed=0,
        on_removed=lambda hs: removed.extend(hs),
    )
    # Lots of distinct content: device evicts to host; tiny host pool
    # evicts onward, emitting `removed` (the worker truly forgot those).
    _fill_with_noise(core, n_requests=8, tag=1)
    _fill_with_noise(core, n_requests=8, tag=2)
    core.offload.flush()
    assert core.host_pool.stats.evictions > 0
    assert len(removed) >= core.host_pool.stats.evictions


def test_host_tier_disabled_by_default():
    core = make_core()
    assert core.host_pool is None


def test_disk_tier_roundtrip_is_deterministic(tmp_path):
    """G3: blocks demoted device->host->disk onboard back with identical
    greedy output (parity: reference tests/kvbm/test_determinism.py:489,
    block_manager/storage/disk.rs)."""
    base = make_core()
    prompt = list(range(11, 11 + 40))
    ref_seq = base.add_request(_req(prompt, "ref", max_tokens=6))
    ref, _ = run_to_completion(base, [ref_seq])

    # Tiny HBM pool AND tiny host pool: noise pushes the prompt's blocks
    # all the way to disk.
    core = make_core(
        num_kv_blocks=24,
        host_kv_blocks=4,
        disk_kv_dir=str(tmp_path / "g3"),
        disk_kv_blocks=256,
        max_model_len=128,
    )
    s1 = core.add_request(_req(prompt, "a", max_tokens=6))
    run_to_completion(core, [s1])
    _fill_with_noise(core, n_requests=8)
    _fill_with_noise(core, n_requests=8, tag=2000)
    core.offload.flush()
    assert core.disk_pool.stats.offloads > 0, "nothing reached the disk tier"

    s2 = core.add_request(_req(prompt, "b", max_tokens=6))
    d2, _ = run_to_completion(core, [s2])
    assert core.disk_pool.stats.onboards > 0, "no disk blocks onboarded"
    assert s2.num_cached_tokens > 0
    assert d2["b"] == ref["ref"], "output changed across disk offload/onboard"


def test_disk_tier_eviction_emits_removed(tmp_path):
    removed: list[int] = []
    core = EngineCore(
        CFG,
        tiny_engine(
            num_kv_blocks=24,
            host_kv_blocks=4,
            disk_kv_dir=str(tmp_path / "g3"),
            disk_kv_blocks=4,
            max_model_len=128,
        ),
        seed=0,
        on_removed=lambda hs: removed.extend(hs),
    )
    for tag in (1, 2, 3):
        _fill_with_noise(core, n_requests=8, tag=tag)
    core.offload.flush()
    assert core.disk_pool.stats.evictions > 0
    assert len(removed) >= core.disk_pool.stats.evictions
    # Host evictions demoted (did not emit removal): the worker forgot
    # only what fell off the END of the tier chain.
    assert core.host_pool.stats.evictions >= core.disk_pool.stats.offloads


def test_offload_does_not_block_step():
    """Evictions must not run device->host copies inside step(): with the
    transfer worker stalled, steps that trigger evictions still complete
    (the old synchronous path would deadlock/stall here)."""
    import threading
    import time as _time

    core = make_core(num_kv_blocks=24, host_kv_blocks=64, max_model_len=128)
    # Stall the worker: occupy the queue with a sentinel the worker
    # blocks on (a threading.Event disguised as a device page).
    gate = threading.Event()

    class SlowPage:
        def __array__(self, dtype=None):
            gate.wait(timeout=30)
            import numpy as _np

            return _np.zeros(1, dtype=_np.float32)

    core.offload.submit(-1, None, SlowPage())
    # These runs evict plenty of blocks; all their transfers queue behind
    # the stalled one. Steps must still finish promptly.
    t0 = _time.monotonic()
    _fill_with_noise(core, n_requests=8, tag=77)
    _fill_with_noise(core, n_requests=8, tag=78)
    elapsed = _time.monotonic() - t0
    assert core.offload._q.qsize() >= 0  # transfers queued, engine done
    gate.set()
    core.offload.flush()
    assert elapsed < 25, f"steps stalled behind offload transfers ({elapsed:.1f}s)"
