"""Cluster-wide prefix KV pool (ISSUE 11).

Unit surface: tier-tagged event wire compat, the tier-composing
GlobalKvIndex (a worker stays routable while ANY tier holds a block),
the bounded KV event publisher (visible drops + anti-entropy resync),
and the indexer→publisher resync request round trip over a real store.

Engine surface: a tiny jax EngineCore with host+disk tiers wired
tier-aware — the composed index never loses the worker's prefix while
the worker can still serve it, across demotion and onboarding.

Fleet surface: two real mocker workers behind the real frontend router —
the peer pull serves a rerouted request's prefill, chaos (sever / stall
/ dead peer) degrades every pull to local recompute with BIT-IDENTICAL
streams and populated fallback/breaker counters, and a graceful drain
retracts the worker's published inventory immediately (not at lease
expiry).
"""

import asyncio
import os
from contextlib import suppress

import pytest

from dynamo_tpu.llm.kv_pool import GlobalKvIndex, PeerPullStats
from dynamo_tpu.llm.kv_router.protocols import (
    KvCacheEvent,
    RouterEvent,
    kv_events_subject,
    kv_resync_subject,
)
from dynamo_tpu.llm.kv_router.publisher import KvEventPublisher

pytestmark = [pytest.mark.integration, pytest.mark.pre_merge]


def ev(worker, eid, op, hashes=(), parent=None, tier="device"):
    return RouterEvent(
        worker, eid, KvCacheEvent(op=op, block_hashes=tuple(hashes),
                                  parent_hash=parent, tier=tier)
    )


# ---------------------------------------------------------------------------
# Wire compat
# ---------------------------------------------------------------------------


def test_tier_rides_the_wire_and_legacy_events_decode_device():
    e = ev(7, 1, "stored", [11, 12], parent=None, tier="disk")
    back = RouterEvent.from_wire(e.to_wire())
    assert back.event.tier == "disk"
    assert back.event.block_hashes == (11, 12)
    # Device-tier events travel untagged: byte-identical to the pre-tier
    # wire format, so old consumers parse new workers and vice versa.
    legacy = ev(7, 2, "stored", [13])
    assert b"disk" not in legacy.to_wire() and b"t" not in legacy.to_wire()[:1]
    assert RouterEvent.from_wire(legacy.to_wire()).event.tier == "device"


# ---------------------------------------------------------------------------
# GlobalKvIndex composition
# ---------------------------------------------------------------------------


def test_index_composes_tiers_worker_survives_demotion():
    idx = GlobalKvIndex()
    idx.apply_event(ev(1, 1, "stored", [10], None))
    idx.apply_event(ev(1, 2, "stored", [20], 10))
    assert idx.find_matches([10, 20]) == {1: 2}
    # Demotion: stored(host) then removed(device) — the worker never
    # stops matching (it can still serve the block from host).
    idx.apply_event(ev(1, 3, "stored", [10], None, tier="host"))
    idx.apply_event(ev(1, 4, "removed", [10], tier="device"))
    assert idx.find_matches([10, 20]) == {1: 2}
    assert idx.holders(10) == {1: {"host"}}
    # Host→disk demotion keeps it matched too.
    idx.apply_event(ev(1, 5, "stored", [10], None, tier="disk"))
    idx.apply_event(ev(1, 6, "removed", [10], tier="host"))
    assert idx.find_matches([10, 20]) == {1: 2}
    # The LAST tier letting go retracts the worker: the prefix chain is
    # broken at depth 1, so nothing matches (block 20 is still held —
    # truthfully in the ledger — but unreachable as a prefix).
    idx.apply_event(ev(1, 7, "removed", [10], tier="disk"))
    assert idx.find_matches([10, 20]) == {}
    assert idx.holders(10) == {}
    idx.apply_event(ev(1, 8, "removed", [20], tier="device"))
    assert idx.num_blocks(1) == 0


def test_index_host_only_inventory_still_matches():
    # A resync snapshot can legitimately publish a block that lives ONLY
    # in an offload tier — it is still servable (peer pull onboards it).
    idx = GlobalKvIndex()
    idx.apply_event(ev(3, 1, "stored", [10], None, tier="host"))
    assert idx.find_matches([10]) == {3: 1}


def test_index_cleared_and_remove_worker_retire_everything():
    idx = GlobalKvIndex()
    for w in (1, 2):
        idx.apply_event(ev(w, 1, "stored", [10], None))
        idx.apply_event(ev(w, 2, "stored", [10], None, tier="host"))
    idx.apply_event(ev(1, 3, "cleared"))
    assert idx.find_matches([10]) == {2: 1}
    assert idx.num_blocks(1) == 0
    idx.remove_worker(2)
    assert idx.find_matches([10]) == {}
    assert idx.stats()["index_blocks"] == 0


def test_index_gap_detection_requests_resync():
    gaps: list[int] = []
    idx = GlobalKvIndex(on_gap=gaps.append)
    idx.apply_event(ev(5, 1, "stored", [10], None))
    idx.apply_event(ev(5, 2, "stored", [20], 10))
    idx.apply_event(ev(5, 2, "stored", [20], 10))  # duplicate: ignored
    assert gaps == [] and idx.gaps_detected == 0
    idx.apply_event(ev(5, 9, "stored", [30], 20))  # ids 3..8 lost
    assert gaps == [5] and idx.gaps_detected == 1
    # The gapped event itself still applies (best effort until resync).
    assert idx.find_matches([10, 20, 30]) == {5: 3}


def test_index_dump_round_trips_tiers():
    idx = GlobalKvIndex()
    idx.apply_event(ev(4, 1, "stored", [10, 20], None))
    idx.apply_event(ev(4, 2, "stored", [10], None, tier="host"))
    idx.apply_event(ev(4, 3, "removed", [10], tier="device"))
    fresh = GlobalKvIndex()
    for e in idx.dump_as_events(4):
        assert e.event_id == 0, "bootstrap events must be unsequenced"
        fresh.apply_event(e)
    assert fresh.find_matches([10, 20]) == idx.find_matches([10, 20]) == {4: 2}
    assert fresh.holders(10) == idx.holders(10) == {4: {"host"}}
    # The dump must NOT poison the replica's live-id dedup: the worker's
    # next real events (low ids — lower than the dump's entry count in
    # the old numbering) still apply, including removals.
    fresh.apply_event(ev(4, 4, "removed", [10], tier="host"))
    assert fresh.find_matches([10, 20]) == {}
    fresh.apply_event(ev(4, 5, "stored", [30], None))
    assert fresh.find_matches([30]) == {4: 1}


# ---------------------------------------------------------------------------
# Bounded publisher + anti-entropy
# ---------------------------------------------------------------------------


class FakeStore:
    def __init__(self):
        self.published: list[tuple[str, bytes]] = []

    async def publish(self, subject: str, payload: bytes) -> None:
        self.published.append((subject, payload))

    def events(self):
        return [RouterEvent.from_wire(p) for _s, p in self.published]


async def test_publisher_orders_tags_and_accounts():
    store = FakeStore()
    pub = KvEventPublisher(store, "ns", "c", worker_id=9)
    pub.stored_nowait([1], None)
    pub.stored_nowait([1], None, "host")
    pub.removed_nowait([1], "device")
    assert await pub.flush()
    evs = store.events()
    assert [(e.event.op, e.event.tier) for e in evs] == [
        ("stored", "device"), ("stored", "host"), ("removed", "device"),
    ]
    assert [e.event_id for e in evs] == [1, 2, 3]
    st = pub.stats()
    assert st["events_published"] == 3 and st["events_dropped"] == 0
    assert st["published_blocks"] == 1  # net: host copy is the survivor
    assert st["published_host_blocks"] == 1


async def test_publisher_overflow_drops_visibly_and_resyncs():
    store = FakeStore()
    pub = KvEventPublisher(store, "ns", "c", worker_id=9, buffer=2)
    inventory = [("device", 100, None), ("host", 200, 100)]
    pub.inventory_source = lambda: inventory
    # Enqueue a burst with the drain task never scheduled yet (no await
    # between calls): the buffer holds 2, the rest drop visibly.
    for i in range(6):
        pub.stored_nowait([i + 1], None)
    assert pub.events_dropped_total > 0
    assert await pub.flush()
    assert pub.resyncs_total == 1
    evs = store.events()
    # The resync supersedes the buffered backlog: cleared, then the full
    # inventory with tier tags, and nothing stale after it.
    assert evs[0].event.op == "cleared"
    assert [(e.event.op, e.event.tier, e.event.block_hashes)
            for e in evs[1:]] == [
        ("stored", "device", (100,)), ("stored", "host", (200,)),
    ]
    # The composed result is exactly the inventory.
    idx = GlobalKvIndex()
    for e in evs:
        idx.apply_event(e)
    assert idx.find_matches([100, 200]) == {9: 2}
    assert pub.stats()["published_blocks"] == 2


async def test_publisher_resync_batches_chain_runs():
    """A contiguous same-tier chain resyncs as ONE multi-hash event, not
    one store round trip per block."""
    store = FakeStore()
    pub = KvEventPublisher(store, "ns", "c", worker_id=9, buffer=1)
    pub.inventory_source = lambda: [
        ("device", 1, None), ("device", 2, 1), ("device", 3, 2),
        ("host", 4, 3), ("host", 9, None),
    ]
    pub.stored_nowait([50], None)
    pub.stored_nowait([51], None)  # overflow -> resync
    assert await pub.flush()
    evs = store.events()
    assert evs[0].event.op == "cleared"
    assert [(e.event.tier, e.event.block_hashes, e.event.parent_hash)
            for e in evs[1:]] == [
        ("device", (1, 2, 3), None), ("host", (4,), 3), ("host", (9,), None),
    ]


async def test_resync_request_round_trip_over_store():
    """An indexer that sees an event-id gap publishes a resync request;
    the worker's publisher answers with cleared + full inventory and the
    index converges — the anti-entropy loop end to end."""
    from dynamo_tpu.llm.kv_router.indexer import KvIndexer
    from dynamo_tpu.runtime.store import StoreServer
    from dynamo_tpu.runtime.store.client import StoreClient

    store = StoreServer()
    await store.start()
    pub_client = await StoreClient.open(store.address)
    idx_client = await StoreClient.open(store.address)
    try:
        subject = kv_events_subject("ns", "c")
        pub = KvEventPublisher(pub_client, "ns", "c", worker_id=3)
        pub.inventory_source = lambda: [("device", 100, None), ("disk", 200, 100)]
        await pub.start()
        indexer = KvIndexer(idx_client, subject,
                            resync_subject=kv_resync_subject("ns", "c"))
        await indexer.start()

        pub.stored_nowait([100], None)
        await pub.flush()
        # Manufacture a gap: events 2..4 vanish (as if dropped upstream).
        pub._event_id += 3
        pub.stored_nowait([999], 100)
        await pub.flush()

        async def until(cond, timeout=10.0):
            for _ in range(int(timeout / 0.05)):
                if cond():
                    return True
                await asyncio.sleep(0.05)
            return False

        # Gap detected -> resync requested -> inventory re-published ->
        # the index converges on the snapshot (999 was superseded).
        assert await until(lambda: pub.resyncs_total >= 1), "no resync ran"
        assert await until(
            lambda: indexer.find_matches([100, 200]) == {3: 2}
            and indexer.find_matches([999]) == {}
        ), f"index never converged: {indexer.find_matches([100, 200])}"
        assert indexer.tree.gaps_detected >= 1
        await indexer.stop()
        await pub.stop()
    finally:
        for c in (pub_client, idx_client):
            with suppress(ConnectionError, OSError):
                await c.close()
        await store.stop()


# ---------------------------------------------------------------------------
# Engine tier events (tiny jax core, host+disk tiers)
# ---------------------------------------------------------------------------


def test_engine_tier_events_keep_composed_index_consistent(tmp_path):
    """Wire a tiny EngineCore tier-aware and replay its event stream into
    a GlobalKvIndex: across device eviction → host → disk demotion and
    onboarding, the composed index scores the worker for the prompt
    exactly while the worker can serve it — never a transient loss."""
    import numpy as np

    from dynamo_tpu.engine import EngineCore, tiny_engine, tiny_model
    from dynamo_tpu.tokens import compute_seq_hashes
    from tests.test_engine_core import _req, run_to_completion

    events: list[RouterEvent] = []
    eid = [0]

    def emit(op, hashes, parent, tier):
        eid[0] += 1
        events.append(ev(1, eid[0], op, hashes, parent, tier))

    core = EngineCore(
        tiny_model(),
        tiny_engine(
            num_kv_blocks=24, host_kv_blocks=8,
            disk_kv_dir=str(tmp_path / "disk"), disk_kv_blocks=64,
            max_model_len=128,
        ),
        seed=0,
        on_stored=lambda hs, p: emit("stored", hs, p, "device"),
        on_removed=lambda hs: emit("removed", hs, None, "device"),
        on_tier_stored=lambda hs, p, tier: emit("stored", hs, p, tier),
        on_tier_removed=lambda hs, tier: emit("removed", hs, None, tier),
    )
    prompt = list(range(7, 7 + 40))
    hashes = compute_seq_hashes(prompt, core.engine.block_size)
    s1 = core.add_request(_req(prompt, "a", max_tokens=4))
    ref, _ = run_to_completion(core, [s1])

    rng = np.random.RandomState(0)
    for i in range(8):
        seqs = [core.add_request(
            _req(list(rng.randint(1, 300, size=40)), f"n{i}", max_tokens=4))]
        run_to_completion(core, seqs)
    core.offload.flush()
    assert core.host_pool.stats.offloads > 0

    idx = GlobalKvIndex()
    for e in events:
        idx.apply_event(e)
    n_prompt = len([h for h in hashes if h in dict.fromkeys(hashes)])
    got = idx.find_matches(hashes)
    # The worker still serves the whole prompt prefix (device or tiers) —
    # and the composed index agrees.
    assert got.get(1, 0) == len(hashes), (got, len(hashes), n_prompt)
    host_or_disk = [
        h for h in hashes if "host" in idx.holders(h).get(1, set())
        or "disk" in idx.holders(h).get(1, set())
    ]
    assert host_or_disk, "nothing demoted to the offload tiers"

    # Onboard: rerunning the prompt promotes tiers back to device; the
    # index must still match and the output must be unchanged.
    s2 = core.add_request(_req(prompt, "b", max_tokens=4))
    d2, _ = run_to_completion(core, [s2])
    assert d2["b"] == ref["a"]
    idx2 = GlobalKvIndex()
    for e in events:
        idx2.apply_event(e)
    assert idx2.find_matches(hashes).get(1, 0) == len(hashes)

    # The resync snapshot composes to the same worker-level answer.
    snap = core.kv_inventory()
    idx3 = GlobalKvIndex()
    fid = 0
    for tier, h, parent in snap:
        fid += 1
        idx3.apply_event(ev(1, fid, "stored", [h], parent, tier))
    assert idx3.find_matches(hashes).get(1, 0) == len(hashes)


# ---------------------------------------------------------------------------
# Mocker fleet: peer pull + chaos degradation + drain retraction
# ---------------------------------------------------------------------------


class MockPoolFleet:
    """Two run_mocker workers (full worker wiring: kv_fetch endpoint,
    peer pull, publisher) + the real KV frontend router."""

    def __init__(self, n: int = 2, **args_kw):
        from dynamo_tpu.llm.mocker import MockEngineArgs

        self.n = n
        self.args = MockEngineArgs(
            num_kv_blocks=512, block_size=8, speedup_ratio=50.0,
            kv_pull_us_per_block=5.0, **args_kw,
        )

    async def __aenter__(self) -> "MockPoolFleet":
        from dynamo_tpu.backends.mocker import run_mocker
        from dynamo_tpu.frontend.main import run_frontend
        from dynamo_tpu.runtime import DistributedRuntime
        from dynamo_tpu.runtime.store import StoreServer

        self.store = StoreServer()
        await self.store.start()
        self.runtimes: list[DistributedRuntime] = []
        self.worker_ids: list[int] = []
        self.engines: list = []
        self.tasks: list[asyncio.Task] = []
        for _ in range(self.n):
            rt = await DistributedRuntime.create(self.store.address)
            served = asyncio.Event()
            self.tasks.append(asyncio.create_task(run_mocker(
                rt, model_name="mock", engine_args=self.args,
                served_event=served, engine_out=self.engines,
            )))
            await asyncio.wait_for(served.wait(), 15)
            self.runtimes.append(rt)
            self.worker_ids.append(rt.primary_lease_id)
        front_rt = await DistributedRuntime.create(self.store.address)
        self.front_rt = front_rt
        ready = asyncio.Event()
        services: list = []
        self.tasks.append(asyncio.create_task(run_frontend(
            front_rt, http_host="127.0.0.1", http_port=0,
            router_mode="kv", ready_event=ready, service_out=services,
        )))
        await asyncio.wait_for(ready.wait(), 15)
        self.service = services[0]
        for _ in range(200):
            served_model = self.service.manager.get("mock")
            if served_model is not None and served_model.push_router is not None:
                self.push = served_model.push_router
                break
            await asyncio.sleep(0.05)
        else:
            raise TimeoutError("model never appeared")
        return self

    async def __aexit__(self, *exc) -> None:
        from dynamo_tpu.runtime import chaos

        chaos.uninstall()
        for rt in [self.front_rt] + self.runtimes:
            rt.signal_shutdown()
        await asyncio.sleep(0.05)
        for t in self.tasks:
            t.cancel()
        for rt in [self.front_rt] + self.runtimes:
            with suppress(Exception):  # dynalint: allow-broad-except(best-effort teardown; runtime may already be closed)
                await rt.shutdown()
        await self.store.stop()

    async def route(self, prompt, rid, *, pinned=None, exclude=None,
                    max_tokens=6):
        from dynamo_tpu.llm.protocols.common import (
            PreprocessedRequest, SamplingOptions, StopConditions,
        )

        pre = PreprocessedRequest(
            model="mock", token_ids=list(prompt), request_id=rid,
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=max_tokens),
        )
        kw = {}
        if pinned is not None:
            kw["router_overrides"] = {"backend_instance_id": pinned}
        if exclude is not None:
            kw["exclude"] = exclude
        toks = []
        async for out in self.push.generate(
            pre.to_wire(), rid, list(prompt), **kw
        ):
            toks.extend(out.get("token_ids") or [])
        self.push.router.free(rid)
        return toks


def _pool_gauges(metrics_text: str) -> dict:
    out = {}
    for line in metrics_text.splitlines():
        if line.startswith("dynamo_kv_pool_") or line.startswith("dynamo_kv_events_"):
            name = line.split("{")[0].split(" ")[0]
            out[name] = float(line.rsplit(None, 1)[-1])
    return out


PROMPT = list(range(1, 90))  # 11 complete 8-token blocks


async def test_mocker_peer_pull_serves_rerouted_prefill():
    async with MockPoolFleet() as f:
        a = f.worker_ids[0]
        want = await f.route(PROMPT, "seed", pinned=a)
        assert len(want) == 6
        got = await f.route(PROMPT, "reroute", exclude={a})
        assert got == want, "peer-pulled stream diverged"
        st = f.engines[1].peer_stats
        assert st.pulls_attempted == 1 and st.pulls_succeeded == 1
        assert st.pulls_fallback == 0
        assert st.blocks_pulled == 11  # the prompt's complete blocks
        assert st.bytes_pulled > 0 and st.last_pull_ms >= 0.0
        # The pull registered the prefix on the second worker: the next
        # pinned run there is a pure prefix-cache hit (no new pull).
        b = f.worker_ids[1]
        got2 = await f.route(PROMPT, "warm", pinned=b)
        assert got2 == want
        assert st.pulls_attempted == 1


async def test_mocker_peer_pull_sever_falls_back_bit_identical():
    from dynamo_tpu.runtime import chaos
    from dynamo_tpu.runtime.chaos import ChaosPlan, ChaosRule

    async with MockPoolFleet() as f:
        a = f.worker_ids[0]
        want = await f.route(PROMPT, "seed", pinned=a)
        chaos.install(ChaosPlan(rules=[
            ChaosRule(point="kv_transfer.pull", action="sever", match=str(a)),
        ]))
        got = await f.route(PROMPT, "reroute", exclude={a})
        assert got == want, "sever mid-pull broke the stream"
        st = f.engines[1].peer_stats
        assert st.pulls_fallback == 1 and st.pulls_succeeded == 0
        assert st.blocks_pulled == 0


async def test_mocker_peer_pull_stall_bounded_by_frame_deadline():
    """Frames from the peer stop arriving mid-pull (dropped at the
    dataplane): the per-frame deadline converts the stall into a local
    recompute — the request completes bit-identically, well inside the
    stall budget a wedged pull would have burned."""
    import time as _time

    from dynamo_tpu.runtime import chaos
    from dynamo_tpu.runtime.chaos import ChaosPlan, ChaosRule

    os.environ["DYN_KV_POOL_FRAME_TIMEOUT_S"] = "0.4"
    try:
        async with MockPoolFleet() as f:
            a = f.worker_ids[0]
            a_addr = f.runtimes[0].ingress.address
            want = await f.route(PROMPT, "seed", pinned=a)
            # Drop every response frame from A's ingress: the kv_fetch
            # stream opens and then goes silent — the stall shape.
            chaos.install(ChaosPlan(rules=[
                ChaosRule(point="dataplane.recv", action="drop", match=a_addr),
            ]))
            t0 = _time.monotonic()
            got = await f.route(PROMPT, "reroute", exclude={a})
            elapsed = _time.monotonic() - t0
            assert got == want, "stalled pull broke the stream"
            assert elapsed < 5.0, (
                f"fallback took {elapsed:.1f}s — the frame deadline did "
                "not bound the stall"
            )
            assert f.engines[1].peer_stats.pulls_fallback == 1
    finally:
        os.environ.pop("DYN_KV_POOL_FRAME_TIMEOUT_S", None)


async def test_mocker_peer_pull_dead_peer_falls_back():
    """The hinted peer is gone (ingress down, lease still live so the
    hint still points at it): the dial fails, the pull falls back, the
    stream is served by local recompute bit-identically."""
    async with MockPoolFleet() as f:
        a = f.worker_ids[0]
        want = await f.route(PROMPT, "seed", pinned=a)
        await f.runtimes[0].ingress.stop()
        got = await f.route(PROMPT, "reroute", exclude={a})
        assert got == want, "dead-peer pull broke the stream"
        assert f.engines[1].peer_stats.pulls_fallback == 1


async def test_mocker_drain_retracts_published_inventory():
    """Graceful drain publishes the worker-clear: an event-layer consumer
    (KvIndexer with no instance watch) drops the worker's blocks the
    moment the drain lands — NOT at lease expiry."""
    from dynamo_tpu.llm.kv_router.indexer import KvIndexer
    from dynamo_tpu.runtime.store.client import StoreClient
    from dynamo_tpu.tokens import compute_seq_hashes

    async with MockPoolFleet(n=1) as f:
        a = f.worker_ids[0]
        idx_client = await StoreClient.open(f.store.address)
        indexer = KvIndexer(idx_client, kv_events_subject("dynamo", "backend"))
        await indexer.start()
        try:
            await f.route(PROMPT, "seed", pinned=a)
            hashes = compute_seq_hashes(PROMPT, 8)
            for _ in range(100):
                if indexer.find_matches(hashes).get(a):
                    break
                await asyncio.sleep(0.05)
            else:
                raise AssertionError("worker inventory never indexed")
            assert await f.runtimes[0].drain(timeout=2.0)
            for _ in range(100):
                if indexer.tree.num_blocks(a) == 0:
                    break
                await asyncio.sleep(0.05)
            assert indexer.tree.num_blocks(a) == 0, (
                "drain left the worker's inventory in the index"
            )
        finally:
            await indexer.stop()
            with suppress(ConnectionError, OSError):
                await idx_client.close()
