"""Quantized KV cache (ISSUE 8): per-block int8 pages + scale metadata.

The three invariants this file pins:

1. **Quality guard** — int8 KV vs bf16 KV on the model harness: greedy
   next-token agreement (teacher-forced, so one flip cannot cascade) and
   a max-logit-error bound. Bounds measured at 1.0 / 0.064 on the tiny
   preset and pinned with margin.
2. **Bit-stability** — the int8 bytes + scales a block was given at
   write time are IDENTICAL at every place the block ever lives: device
   pages, host tier, disk tier, back on device after onboarding, and on
   a peer after a kv transfer. Quantize once, never re-quantize.
3. **Fail-fast dtype fencing** — a mixed-dtype peer pull (int8 producer,
   bf16 consumer or vice versa) raises instead of silently casting or
   re-quantizing.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.engine import EngineCore, tiny_engine, tiny_model
from dynamo_tpu.engine.kv_quant import (
    dequantize_kv,
    kv_byte_ratio,
    kv_page_bytes,
    pack_kv_page,
    quantize_kv,
    unpack_kv_page,
)
from dynamo_tpu.tokens import compute_seq_hashes
from tests.test_engine_core import _req, run_to_completion
from tests.test_host_kv_tier import _fill_with_noise

CFG = tiny_model()

# Quality-guard bounds (measured on the tiny preset: teacher-forced
# agreement 1.0, max logit delta 0.064 — pinned with ~4x margin; a
# regression past these means the quantizer, the scale layout, or the
# dequant path broke, not noise).
GREEDY_MATCH_FLOOR = 0.98
MAX_LOGIT_ERR = 0.25


def make_core(kv_dtype="int8", **kw) -> EngineCore:
    return EngineCore(CFG, tiny_engine(kv_dtype=kv_dtype, **kw), seed=0)


# -- unit: quantizer + packed representation --------------------------------

def test_quantize_dequantize_error_bound():
    rng = np.random.RandomState(0)
    kvn = jnp.asarray(rng.randn(17, 4, 16).astype(np.float32) * 3.0)
    q, sc = quantize_kv(kvn)
    assert q.dtype == jnp.int8 and sc.shape == (17, 4)
    deq = dequantize_kv(q, sc)
    # Symmetric int8: error per element <= scale/2 = amax/254.
    bound = np.abs(np.asarray(kvn)).max(axis=-1, keepdims=True) / 254.0 + 1e-6
    assert (np.abs(np.asarray(deq) - np.asarray(kvn)) <= bound).all()
    # Zero rows stay exactly zero (scale floor, no NaN).
    qz, scz = quantize_kv(jnp.zeros((3, 4, 16)))
    assert np.asarray(qz).any() == False  # noqa: E712
    assert np.isfinite(np.asarray(scz)).all()


def test_pack_unpack_roundtrip_and_size_validation():
    rng = np.random.RandomState(1)
    L, bs, n_kv, d = 2, 8, 2, 16
    kv = rng.randint(-127, 128, size=(L, bs, 2 * n_kv, d)).astype(np.int8)
    sc = np.abs(rng.randn(L, bs, 2 * n_kv)).astype(np.float32)
    buf = pack_kv_page(kv, sc)
    assert buf.dtype == np.uint8 and buf.ndim == 1
    kv2, sc2 = unpack_kv_page(buf, L, bs, n_kv, d)
    assert kv2.tobytes() == kv.tobytes()
    assert sc2.tobytes() == sc.tobytes()
    # Bytes round trip too (the wire carries bytes, not arrays).
    kv3, sc3 = unpack_kv_page(buf.tobytes(), L, bs, n_kv, d)
    assert kv3.tobytes() == kv.tobytes() and sc3.tobytes() == sc.tobytes()
    with pytest.raises(ValueError, match="does not match"):
        unpack_kv_page(buf[:-1], L, bs, n_kv, d)


def test_capacity_ratio_at_fixed_budget():
    """The headline capacity claim: >= 1.8x resident blocks at a fixed
    HBM budget for llama3-8b geometry (the primary bench shape)."""
    bf16 = kv_page_bytes(32, 32, 8, 128, "bf16")
    int8 = kv_page_bytes(32, 32, 8, 128, "int8")
    budget = 8 << 30
    assert (budget // int8) / (budget // bf16) >= 1.8
    assert abs(kv_byte_ratio("int8", 128) - int8 / bf16) < 1e-9
    assert kv_byte_ratio("bf16") == 1.0


def test_bf16_default_layout_untouched():
    """kv_dtype defaults to bf16 and keeps plain per-layer arrays — the
    classic path must be byte-for-byte the pre-quantization layout."""
    core = EngineCore(CFG, tiny_engine(), seed=0)
    assert core.engine.kv_dtype == "bf16"
    assert not core.engine.kv_quantized
    assert isinstance(core.cache, tuple)
    assert not isinstance(core.cache[0], dict)
    q = make_core()
    assert isinstance(q.cache[0], dict)
    assert q.cache[0]["kv"].dtype == jnp.int8
    assert q.cache[0]["scale"].dtype == jnp.float32
    assert q.cache[0]["scale"].shape == q.cache[0]["kv"].shape[:-1]


def test_unknown_kv_dtype_rejected():
    with pytest.raises(ValueError, match="kv_dtype"):
        EngineCore(CFG, tiny_engine(kv_dtype="fp8"), seed=0)


# -- quality guard (the pinned greedy-match / logit-error bound) ------------

def test_quality_guard_greedy_match_and_logit_error():
    """Teacher-forced comparison so a single early flip cannot cascade:
    both caches consume the bf16 path's greedy tokens; at every position
    the int8 cache must pick the same argmax and stay inside the logit
    error bound."""
    from dynamo_tpu.engine.model import init_cache, init_params
    from tests.model_harness import prefill_chunk

    eng_bf = tiny_engine(max_model_len=256)
    eng_q = tiny_engine(max_model_len=256, kv_dtype="int8")
    params = init_params(jax.random.PRNGKey(0), CFG)
    total = match = 0
    max_err = 0.0
    for t in range(3):
        prompt = list(np.random.RandomState(t).randint(1, 300, size=40))
        ids = list(range(12))
        c_bf, c_q = init_cache(CFG, eng_bf), init_cache(CFG, eng_q)
        l_bf, c_bf = prefill_chunk(params, c_bf, prompt, 0, ids, CFG, eng_bf, 64)
        l_q, c_q = prefill_chunk(params, c_q, prompt, 0, ids, CFG, eng_q, 64)
        pos = len(prompt)
        for _ in range(16):
            a, b = int(np.argmax(l_bf)), int(np.argmax(l_q))
            total += 1
            match += a == b
            max_err = max(
                max_err,
                float(np.max(np.abs(np.asarray(l_bf) - np.asarray(l_q)))),
            )
            l_bf, c_bf = prefill_chunk(params, c_bf, [a], pos, ids, CFG, eng_bf, 32)
            l_q, c_q = prefill_chunk(params, c_q, [a], pos, ids, CFG, eng_q, 32)
            pos += 1
    assert match / total >= GREEDY_MATCH_FLOOR, (
        f"greedy agreement {match / total:.3f} under the pinned floor"
    )
    assert max_err <= MAX_LOGIT_ERR, (
        f"max logit error {max_err:.4f} over the pinned bound"
    )


def test_int8_megastep_stream_matches_k1():
    """The megastep invariant holds WITHIN the int8 dtype: k=8 and k=1
    produce bit-identical streams (quantized decode writes are inside
    the scanned body)."""
    prompt = list(range(7, 7 + 40))
    a = make_core(megastep_k=1)
    d1, _ = run_to_completion(a, [a.add_request(_req(prompt, "x", max_tokens=12))])
    b = make_core(megastep_k=8)
    d8, _ = run_to_completion(b, [b.add_request(_req(prompt, "x", max_tokens=12))])
    assert d1["x"] == d8["x"]
    assert b.exec_stats["megastep_dispatches"] >= 1


# -- bit-stability across every tier and transfer ---------------------------

def test_int8_bytes_stable_device_host_disk_onboard_peer(tmp_path):
    """THE round-trip satellite: quantized block bytes (int8 payload +
    scales, packed) are identical at every hop — device pages -> host
    tier -> disk tier -> onboarded back to device -> pulled by a peer
    over the kv-transfer bytes path. Quantize exactly once."""
    prompt = list(range(7, 7 + 40))
    base = make_core()
    ref, _ = run_to_completion(
        base, [base.add_request(_req(prompt, "ref", max_tokens=6))]
    )

    core = make_core(
        num_kv_blocks=24, host_kv_blocks=4,
        disk_kv_dir=str(tmp_path / "g3"), disk_kv_blocks=256,
        max_model_len=128,
    )
    s1 = core.add_request(_req(prompt, "a", max_tokens=6))
    run_to_completion(core, [s1])
    bs = core.engine.block_size
    cap = (len(prompt) - 1) // bs
    prefix_hashes = s1.prompt_hashes[:cap]
    # Hop 0: canonical bytes while device-resident.
    w0 = core.read_cached_pages(prefix_hashes)
    assert len(w0) == cap
    geom = core._page_geometry()
    for buf in w0:
        unpack_kv_page(buf, *geom)  # parses at the local geometry

    # Hop 1+2: evict through host into disk.
    _fill_with_noise(core, n_requests=8)
    _fill_with_noise(core, n_requests=8, tag=2000)
    core.offload.flush()
    in_host = [h for h in prefix_hashes if h in core.host_pool]
    in_disk = [h for h in prefix_hashes if h in core.disk_pool]
    assert in_host or in_disk, "noise did not push the prefix off-device"
    for i, h in enumerate(prefix_hashes):
        if h in core.host_pool:
            assert core.host_pool._blocks[h].kv.tobytes() == w0[i], (
                "host-tier bytes diverged from the device write"
            )
        if h in core.disk_pool:
            assert core.disk_pool.peek(h).tobytes() == w0[i], (
                "disk-tier bytes diverged from the device write"
            )

    # Hop 3: onboard back to device (admission prefix hit).
    s2 = core.add_request(_req(prompt, "b", max_tokens=6))
    d2, _ = run_to_completion(core, [s2])
    assert core.host_pool.stats.onboards + core.disk_pool.stats.onboards > 0
    assert s2.num_cached_tokens > 0
    assert d2["b"] == ref["ref"], "output changed across the tier round trip"
    w1 = core.read_cached_pages(prefix_hashes)
    assert w1 == w0, "onboarded device bytes diverged from the original"

    # Hop 4: peer pull over the kv-transfer bytes path.
    peer = make_core()
    blocks = []
    parent = None
    for h, buf in zip(prefix_hashes, w1):
        blocks.append({
            "hash": h, "parent": parent,
            "shape": [CFG.num_layers, bs, 2 * CFG.num_kv_heads, CFG.head_dim],
            "dtype": "int8",
            "layout": {"kind": "combined_kv_page", "block_size": bs,
                       "kv_dtype": "int8"},
            "kv": buf,
        })
        parent = h
    res = peer.import_blocks(blocks)
    assert res.imported == cap and res.dropped == 0
    w2 = peer.read_cached_pages(prefix_hashes)
    assert w2 == w0, "peer-imported bytes diverged from the original"
    # And the peer serves the prefix: same greedy output, prefix cached.
    s3 = peer.add_request(_req(prompt, "c", max_tokens=6))
    d3, _ = run_to_completion(peer, [s3])
    assert s3.num_cached_tokens >= cap * bs
    assert d3["c"] == ref["ref"]


def test_int8_disagg_hold_and_direct_import_byte_stable():
    """The disagg path proper: a held prefill's pages export as packed
    int8 bytes and a co-located core direct-imports them bit-identically
    (ONE device program, no host staging)."""
    a = make_core()
    prompt = list(range(3, 3 + 40))
    pre = _req(prompt, "hold", max_tokens=2)
    pre.kv_transfer_params = {"do_remote_decode": True}
    run_to_completion(a, [a.add_request(pre)])
    descs = a.export_descriptors("hold")
    assert descs and descs[0]["dtype"] == "int8"
    assert descs[0]["layout"]["kv_dtype"] == "int8"
    pages = a.read_held_pages("hold", 0, 32)
    hashes = [d["hash"] for d in descs]

    b = make_core()
    res = b.import_blocks_direct(a, "hold")
    assert res.imported == len(descs)
    assert b.read_cached_pages(hashes) == pages, (
        "direct-imported pages diverged from the staged bytes"
    )
    a.release_held("hold")


def test_mixed_dtype_transfer_fails_fast():
    """An int8 producer feeding a bf16 consumer (or vice versa) must
    fail with a pointed error — silently casting would re-quantize or
    serve garbage scales."""
    a = make_core()
    prompt = list(range(5, 5 + 40))
    pre = _req(prompt, "hold", max_tokens=2)
    pre.kv_transfer_params = {"do_remote_decode": True}
    run_to_completion(a, [a.add_request(pre)])
    descs = a.export_descriptors("hold")
    pages = a.read_held_pages("hold", 0, 32)
    blocks = [dict(d, kv=kv) for d, kv in zip(descs, pages)]

    bf = EngineCore(CFG, tiny_engine(), seed=1)
    with pytest.raises(ValueError, match="dtype mismatch"):
        bf.import_blocks(blocks)
    with pytest.raises(ValueError, match="dtype mismatch"):
        bf.import_blocks_direct(a, "hold")

    # And the mirror image: bf16 pages into an int8 consumer.
    b2 = EngineCore(CFG, tiny_engine(), seed=2)
    pre2 = _req(prompt, "hold2", max_tokens=2)
    pre2.kv_transfer_params = {"do_remote_decode": True}
    run_to_completion(b2, [b2.add_request(pre2)])
    descs2 = b2.export_descriptors("hold2")
    pages2 = b2.read_held_pages("hold2", 0, 32)
    q = make_core()
    with pytest.raises(ValueError, match="dtype mismatch"):
        q.import_blocks([dict(d, kv=kv) for d, kv in zip(descs2, pages2)])


# -- int8 first-party decode kernel (interpret mode: CPU-runnable) ----------

def test_paged_attention_int8_pallas_matches_quantized_reference():
    """The extended decode kernel: int8 page DMA + in-VMEM dequant must
    match the dequant-on-gather reference bit-for-close (f32 math both
    sides). Interpret mode keeps it tier-1/CPU-runnable."""
    from dynamo_tpu.ops.paged_attention import (
        paged_attention_pallas,
        paged_attention_reference,
    )

    rng = jax.random.PRNGKey(7)
    B, n_q, n_kv, d, bs, max_blocks = 4, 8, 2, 16, 8, 6
    total = (max_blocks * B + 1) * bs
    ks = jax.random.split(rng, 4)
    q = jax.random.normal(ks[0], (B, n_q, d), jnp.float32)
    k_f = jax.random.normal(ks[1], (n_kv, total, d), jnp.float32)
    v_f = jax.random.normal(ks[2], (n_kv, total, d), jnp.float32)
    k_i8, k_sc = quantize_kv(k_f)
    v_i8, v_sc = quantize_kv(v_f)
    tables = np.arange(B * max_blocks, dtype=np.int32).reshape(B, max_blocks)
    seq_lens = np.array([5, 17, 48, 1], np.int32)

    want = paged_attention_reference(
        q, k_i8, v_i8, jnp.asarray(tables), jnp.asarray(seq_lens),
        block_size=bs, k_scale=k_sc, v_scale=v_sc,
    )
    got = paged_attention_pallas(
        q, k_i8, v_i8, jnp.asarray(tables), jnp.asarray(seq_lens),
        block_size=bs, k_scale=k_sc, v_scale=v_sc, interpret=True,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)
    # And the quantized attention is close to the full-precision one.
    exact = paged_attention_reference(
        q, k_f, v_f, jnp.asarray(tables), jnp.asarray(seq_lens), block_size=bs
    )
    assert float(np.max(np.abs(np.asarray(got) - np.asarray(exact)))) < 0.15


def test_metrics_report_int8_capacity():
    core = make_core()
    st = core.kv_cache_stats()
    assert st["kv_dtype"] == "int8" and st["kv_dtype_int8"] == 1
    assert st["capacity_blocks"] == core.engine.num_kv_blocks
    bf = EngineCore(CFG, tiny_engine(), seed=0)
    st_bf = bf.kv_cache_stats()
    assert st_bf["kv_dtype_int8"] == 0
    assert st["bytes_per_block"] < st_bf["bytes_per_block"]
