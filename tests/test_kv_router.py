"""KV router: radix indexer, cost scheduler, active sequences, event flow."""

import pytest

from dynamo_tpu.llm.kv_router import (
    ActiveSequences,
    DefaultWorkerSelector,
    KvCacheEvent,
    KvIndexer,
    RadixTree,
    RouterConfig,
    RouterEvent,
    softmax_sample,
)
from dynamo_tpu.llm.kv_router.indexer import ApproxKvIndexer
from dynamo_tpu.llm.kv_router.protocols import kv_events_subject
from dynamo_tpu.llm.kv_router.publisher import KvEventPublisher
from dynamo_tpu.runtime.store import StoreClient, StoreServer
from dynamo_tpu.tokens import compute_seq_hashes

pytestmark = [pytest.mark.unit, pytest.mark.pre_merge]


def stored(worker, event_id, hashes, parent=None):
    return RouterEvent(worker, event_id, KvCacheEvent("stored", tuple(hashes), parent))


def removed(worker, event_id, hashes):
    return RouterEvent(worker, event_id, KvCacheEvent("removed", tuple(hashes)))


def test_peer_prefix_tie_breaks_by_lowest_worker_id():
    """ISSUE 8 satellite: two peers with EQUAL overlap must resolve to
    the same peer every time — dict insertion order (KV-event arrival
    order) must not pick the hint, or routing traces and chaos replays
    stop reproducing."""
    from dynamo_tpu.llm.kv_router.router import best_peer_hint

    assert best_peer_hint({7: 5, 3: 5}) == (3, 5)
    assert best_peer_hint({3: 5, 7: 5}) == (3, 5)  # insertion order flipped
    # Higher overlap still wins regardless of id.
    assert best_peer_hint({3: 5, 7: 9}) == (7, 9)
    assert best_peer_hint({7: 9, 3: 5}) == (7, 9)
    # Three-way tie: lowest id, any insertion order.
    for order in ({5: 2, 1: 2, 9: 2}, {9: 2, 5: 2, 1: 2}, {1: 2, 9: 2, 5: 2}):
        assert best_peer_hint(order) == (1, 2)


def test_radix_matches_contiguous_prefix():
    t = RadixTree()
    h = compute_seq_hashes(list(range(128)), 32)  # 4 blocks
    t.apply_event(stored(1, 1, h[:3]))
    t.apply_event(stored(2, 1, h[:1]))
    scores = t.find_matches(h)
    assert scores == {1: 3, 2: 1}


def test_radix_removed_blocks_shrink_overlap():
    t = RadixTree()
    h = compute_seq_hashes(list(range(96)), 32)
    t.apply_event(stored(1, 1, h))
    t.apply_event(removed(1, 2, h[2:]))
    assert t.find_matches(h) == {1: 2}
    assert t.num_blocks(1) == 2


def test_radix_worker_removal_and_prune():
    t = RadixTree()
    h = compute_seq_hashes(list(range(64)), 32)
    t.apply_event(stored(1, 1, h))
    t.apply_event(stored(2, 1, h[:1]))
    t.remove_worker(1)
    assert t.find_matches(h) == {2: 1}
    assert t.num_blocks() == 1  # second block pruned entirely


def test_radix_duplicate_event_ignored():
    t = RadixTree()
    h = compute_seq_hashes(list(range(32)), 32)
    t.apply_event(stored(1, 5, h))
    t.apply_event(removed(1, 5, h))  # same event id → replay, dropped
    assert t.find_matches(h) == {1: 1}


def test_radix_divergent_suffixes():
    t = RadixTree()
    a = compute_seq_hashes([1] * 64, 32)
    b = compute_seq_hashes([1] * 32 + [2] * 32, 32)
    assert a[0] == b[0]
    t.apply_event(stored(1, 1, a))
    t.apply_event(stored(2, 1, b))
    assert t.find_matches(a) == {1: 2, 2: 1}
    assert t.find_matches(b) == {2: 2, 1: 1}


def test_softmax_sample_temperature_zero_is_argmin():
    costs = {10: 5.0, 20: 1.0, 30: 9.0}
    assert softmax_sample(costs, 0.0) == 20


def test_softmax_sample_prefers_low_cost():
    import random

    rng = random.Random(0)
    costs = {1: 0.0, 2: 100.0}
    picks = [softmax_sample(costs, 0.5, rng) for _ in range(200)]
    assert picks.count(1) > 150


def test_selector_prefers_overlap_and_low_load():
    active = ActiveSequences(block_size=32)
    sel = DefaultWorkerSelector()
    cfg = RouterConfig(overlap_weight=1.0, temperature=0.0, block_size=32)
    # Worker 1 has 3 of 4 blocks cached; both idle → pick 1.
    r = sel.select_worker([1, 2], {1: 3}, 128, active, cfg)
    assert r.worker_id == 1
    assert r.overlap_blocks == 3
    assert r.required_prefill_tokens == 128 - 96
    # Now pile load on worker 1; worker 2 (no overlap, idle) should win.
    for i in range(50):
        active.add_request(f"r{i}", 1, 1024, 0)
    r2 = sel.select_worker([1, 2], {1: 3}, 128, active, cfg)
    assert r2.worker_id == 2


def test_active_sequences_lifecycle():
    a = ActiveSequences(block_size=32)
    a.add_request("r1", 7, prompt_tokens=100, overlap_blocks=2)
    assert a.prefill_tokens(7) == 100 - 64
    assert a.decode_blocks(7) == 4  # ceil(100/32)
    a.mark_prefill_done("r1")
    assert a.prefill_tokens(7) == 0
    a.add_decode_block("r1")
    assert a.decode_blocks(7) == 5
    a.free("r1")
    assert a.decode_blocks(7) == 0
    assert a.active_requests() == 0


def test_active_sequences_worker_death_orphans():
    a = ActiveSequences()
    a.add_request("r1", 1, 10, 0)
    a.add_request("r2", 1, 10, 0)
    a.add_request("r3", 2, 10, 0)
    orphans = a.remove_worker(1)
    assert sorted(orphans) == ["r1", "r2"]
    assert a.active_requests() == 1


def test_approx_indexer_ttl():
    idx = ApproxKvIndexer(ttl_s=1000.0)
    h = compute_seq_hashes(list(range(64)), 32)
    idx.process_routing_decision(5, h)
    assert idx.find_matches(h) == {5: 2}
    idx.remove_worker(5)
    assert idx.find_matches(h) == {}


@pytest.mark.integration
async def test_event_publisher_to_indexer_roundtrip():
    """Worker publishes KV events → router's indexer sees the overlap
    (parity: bindings publisher→indexer round-trip test)."""
    import asyncio

    async with StoreServer() as server:
        async with await StoreClient.open(server.address) as worker_store:
            async with await StoreClient.open(server.address) as router_store:
                indexer = KvIndexer(router_store, kv_events_subject("ns", "backend"))
                await indexer.start()
                pub = KvEventPublisher(worker_store, "ns", "backend", worker_id=42)
                h = compute_seq_hashes(list(range(96)), 32)
                await pub.stored(h[:1], parent_hash=None)
                await pub.stored(h[1:], parent_hash=h[0])
                for _ in range(100):
                    if indexer.find_matches(h).get(42) == 3:
                        break
                    await asyncio.sleep(0.01)
                assert indexer.find_matches(h) == {42: 3}
                await pub.removed(h[1:])
                for _ in range(100):
                    if indexer.find_matches(h).get(42) == 1:
                        break
                    await asyncio.sleep(0.01)
                assert indexer.find_matches(h) == {42: 1}
                await indexer.stop()


@pytest.mark.integration
async def test_two_router_replica_sync_converges():
    """Two routers serving the same component converge on the same
    overlap scores (radix bootstrap + shared events) and consistent load
    counts (active-sequence deltas) — parity: reference
    ActiveSequencesMultiWorker + dump_tree_as_events
    (sequence.rs:225, indexer.rs:445)."""
    import asyncio
    import dataclasses

    from dynamo_tpu.llm.kv_router.protocols import RouterConfig
    from dynamo_tpu.llm.kv_router.router import KvRouter

    cfg = RouterConfig(replica_sync=True, block_size=32)

    async def wait_for(cond, n=200):
        for _ in range(n):
            if cond():
                return True
            await asyncio.sleep(0.01)
        return cond()

    async with StoreServer() as server:
        async with await StoreClient.open(server.address) as worker_store:
            async with await StoreClient.open(server.address) as store_a:
                async with await StoreClient.open(server.address) as store_b:
                    ra = KvRouter(store_a, "ns", "backend", dataclasses.replace(cfg))
                    await ra.start()

                    # Worker 7 stores three blocks; router A routes two
                    # requests BEFORE router B exists.
                    pub = KvEventPublisher(worker_store, "ns", "backend", worker_id=7)
                    tokens = list(range(96))
                    h = compute_seq_hashes(tokens, 32)
                    await pub.stored(h, parent_hash=None)
                    await wait_for(lambda: ra.indexer.find_matches(h).get(7) == 3)

                    r1 = ra.find_best_match("req-1", tokens, [7])
                    ra.mark_prefill_done("req-1")
                    ra.find_best_match("req-2", list(range(200, 264)), [7])

                    # Late joiner: bootstrap must deliver radix + load.
                    rb = KvRouter(store_b, "ns", "backend", dataclasses.replace(cfg))
                    await rb.start()
                    assert rb.indexer.find_matches(h) == ra.indexer.find_matches(h)
                    assert rb.active.decode_blocks(7) == ra.active.decode_blocks(7)
                    assert rb.active.prefill_tokens(7) == ra.active.prefill_tokens(7)
                    assert rb.active.active_requests() == 2

                    # Live deltas flow both ways.
                    rb.find_best_match("req-3", list(range(300, 364)), [7])
                    assert await wait_for(
                        lambda: ra.active.decode_blocks(7) == rb.active.decode_blocks(7)
                    )
                    ra.free("req-2")
                    assert await wait_for(
                        lambda: rb.active.active_requests() == 2
                    )
                    assert ra.active.prefill_tokens(7) == rb.active.prefill_tokens(7)

                    # Overlap scoring identical on both replicas.
                    assert r1.overlap_blocks == 3
                    sel_a = ra.find_best_match("req-4", tokens, [7])
                    sel_b = rb.find_best_match("req-5", tokens, [7])
                    assert sel_a.overlap_blocks == sel_b.overlap_blocks == 3

                    await ra.stop()
                    await rb.stop()


async def test_second_generation_bootstrap_keeps_radix():
    """A replica whose radix knowledge came ONLY from bootstrap must still
    serve a full dump to the next late joiner: bootstrap events must feed
    known_workers exactly like live events (advisor r4)."""
    import asyncio
    import dataclasses

    from dynamo_tpu.llm.kv_router.protocols import RouterConfig
    from dynamo_tpu.llm.kv_router.router import KvRouter

    cfg = RouterConfig(replica_sync=True, block_size=32)

    async def wait_for(cond, n=200):
        for _ in range(n):
            if cond():
                return True
            await asyncio.sleep(0.01)
        return cond()

    async with StoreServer() as server:
        async with await StoreClient.open(server.address) as worker_store:
            async with await StoreClient.open(server.address) as store_a:
                async with await StoreClient.open(server.address) as store_b:
                    async with await StoreClient.open(server.address) as store_c:
                        ra = KvRouter(store_a, "ns", "backend", dataclasses.replace(cfg))
                        await ra.start()
                        pub = KvEventPublisher(worker_store, "ns", "backend", worker_id=9)
                        tokens = list(range(64))
                        h = compute_seq_hashes(tokens, 32)
                        await pub.stored(h, parent_hash=None)
                        await wait_for(lambda: ra.indexer.find_matches(h).get(9) == 2)

                        # Generation 2: learns the radix only via bootstrap.
                        rb = KvRouter(store_b, "ns", "backend", dataclasses.replace(cfg))
                        await rb.start()
                        assert rb.indexer.find_matches(h).get(9) == 2
                        assert 9 in rb.known_workers()
                        await ra.stop()  # original replica gone

                        # Generation 3: only rb can answer the bootstrap.
                        rc = KvRouter(store_c, "ns", "backend", dataclasses.replace(cfg))
                        await rc.start()
                        assert rc.indexer.find_matches(h).get(9) == 2
                        await rb.stop()
                        await rc.stop()


def test_processed_endpoints_snapshot():
    """MetricsAggregator aggregates the fleet's ForwardPassMetrics into a
    ProcessedEndpoints view (reference metrics_aggregator.rs +
    scoring.rs:93)."""
    from dynamo_tpu.llm.kv_router.protocols import (
        ForwardPassMetrics,
        KvStats,
        WorkerStats,
    )
    from dynamo_tpu.llm.kv_router.publisher import MetricsAggregator

    agg = MetricsAggregator.__new__(MetricsAggregator)
    agg.latest = {}
    agg.latest[1] = ForwardPassMetrics(
        worker=WorkerStats(request_active_slots=2, request_total_slots=8,
                           num_requests_waiting=1),
        kv=KvStats(kv_active_blocks=90, kv_total_blocks=100,
                   gpu_cache_usage_perc=0.9),
        worker_id=1,
    )
    agg.latest[2] = ForwardPassMetrics(
        worker=WorkerStats(request_active_slots=1, request_total_slots=8,
                           num_requests_waiting=0),
        kv=KvStats(kv_active_blocks=10, kv_total_blocks=100,
                   gpu_cache_usage_perc=0.1),
        worker_id=2,
    )
    snap = agg.snapshot()
    assert snap.worker_ids == [1, 2]
    assert snap.avg_kv_usage == pytest.approx(0.5)
    assert snap.max_kv_usage == pytest.approx(0.9)
    assert snap.total_slots == 16 and snap.active_slots == 3
    assert snap.requests_waiting == 1
    # Busy policy lives in WorkerMonitor (single implementation).
    from dynamo_tpu.runtime.worker_monitor import WorkerMonitor

    mon = WorkerMonitor.__new__(WorkerMonitor)
    mon.aggregator = agg
    mon.busy_threshold = 0.85
    mon.busy = set()
    mon.on_busy_change = lambda w, b: None
    for m in agg.latest.values():
        mon._on_metrics(m)
    assert mon.busy == {1}
    assert mon.eligible([1, 2]) == [2]
    mon.remove_worker(1)
    assert mon.busy == set()


@pytest.mark.integration
async def test_busy_worker_excluded_from_routing():
    """Busy-aware routing: a worker above busy_threshold KV usage loses
    traffic while an alternative exists; all-busy falls back to the full
    set (reference worker_monitor busy marking)."""
    import dataclasses

    from dynamo_tpu.llm.kv_router.protocols import (
        ForwardPassMetrics,
        KvStats,
        RouterConfig,
        WorkerStats,
    )
    from dynamo_tpu.llm.kv_router.publisher import MetricsAggregator
    from dynamo_tpu.llm.kv_router.router import KvPushRouter, KvRouter

    def fpm(usage):
        return ForwardPassMetrics(
            worker=WorkerStats(0, 8, 0), kv=KvStats(0, 100, usage)
        )

    class FakeClient:
        def __init__(self):
            self.on_instance_removed = []
            self.sent = []

        def instance_ids(self):
            return [1, 2]

        async def direct(self, worker_id, payload, headers=None):
            self.sent.append(worker_id)

            async def stream():
                yield {"token_ids": [1], "finish_reason": "stop"}

            return stream()

    cfg = RouterConfig(use_kv_events=False, busy_threshold=0.9, block_size=32)
    router = KvRouter.__new__(KvRouter)
    from dynamo_tpu.llm.kv_router.indexer import ApproxKvIndexer
    from dynamo_tpu.llm.kv_router.scheduler import DefaultWorkerSelector
    from dynamo_tpu.llm.kv_router.sequence import ActiveSequences

    router.config = cfg
    router.active = ActiveSequences(block_size=32)
    router.selector = DefaultWorkerSelector()
    router.indexer = ApproxKvIndexer()
    router.sync = None

    from dynamo_tpu.runtime.worker_monitor import WorkerMonitor

    mon = WorkerMonitor.__new__(WorkerMonitor)
    mon.aggregator = MetricsAggregator.__new__(MetricsAggregator)
    mon.aggregator.latest = {}
    mon.busy_threshold = 0.9
    mon.busy = set()
    mon.on_busy_change = lambda w, b: None
    for w, usage in ((1, 0.95), (2, 0.2)):
        m = fpm(usage)
        m.worker_id = w
        mon.aggregator.latest[w] = m
        mon._on_metrics(m)
    client = FakeClient()
    push = KvPushRouter(client, router, monitor=mon)

    async def one(rid):
        async for _ in push.generate({"token_ids": [5] * 40}, rid, [5] * 40):
            pass

    for i in range(4):
        await one(f"r{i}")
    assert set(client.sent) == {2}, "busy worker 1 still got traffic"

    # All busy -> full set again: every request still routes (the
    # fallback must not raise or starve).
    m2 = fpm(0.99)
    m2.worker_id = 2
    mon.aggregator.latest[2] = m2
    mon._on_metrics(m2)
    assert mon.busy == {1, 2}
    client.sent.clear()
    for i in range(6):
        await one(f"s{i}")
    assert len(client.sent) == 6
    assert set(client.sent) <= {1, 2}
