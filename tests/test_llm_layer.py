"""LLM layer: detokenizer/stop engine, preprocessor, model cards, discovery."""

import asyncio

import pytest

from dynamo_tpu.llm.detokenizer import Decoder, IncrementalDetokenizer, StopStringChecker
from dynamo_tpu.llm.model_card import ModelDeploymentCard, ModelRuntimeConfig
from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
from dynamo_tpu.llm.protocols.common import LLMEngineOutput
from dynamo_tpu.llm.protocols.openai import ChatCompletionRequest, ChatMessage
from dynamo_tpu.llm.tokenizer import ByteTokenizer
from dynamo_tpu.runtime.store import StoreServer, StoreClient

pytestmark = [pytest.mark.unit, pytest.mark.pre_merge]


def test_incremental_detok_multibyte():
    tok = ByteTokenizer()
    text = "héllo ☃ wörld"
    ids = tok.encode(text)
    detok = IncrementalDetokenizer(tok)
    out = "".join(detok.step(i) for i in ids)
    assert out == text  # every byte boundary handled


def test_stop_string_jail_across_chunks():
    c = StopStringChecker(["</s>"])
    emit1, hit1 = c.step("hello <")
    assert (emit1, hit1) == ("hello ", False)  # '<' jailed
    emit2, hit2 = c.step("/s")
    assert (emit2, hit2) == ("", False)
    emit3, hit3 = c.step("> trailing")
    assert (emit3, hit3) == ("", True)  # stop hit, nothing after emitted


def test_stop_string_false_alarm_released():
    c = StopStringChecker(["STOP"])
    assert c.step("abcST") == ("abc", False)
    assert c.step("xyz") == ("STxyz", False)  # jail released on mismatch


def test_decoder_stop_token_hidden():
    tok = ByteTokenizer()
    d = Decoder(tok, stop_token_ids=[65])  # 'A'
    s = d.step(ord("h"))
    assert s.text == "h" and s.finish_reason is None
    s = d.step(65)
    assert s.text == "" and s.finish_reason == "stop"


def test_decoder_eos_and_max_tokens():
    tok = ByteTokenizer()
    d = Decoder(tok, max_tokens=3)
    assert d.step(ord("a")).finish_reason is None
    assert d.step(tok.eos_token_id).finish_reason == "eos"

    d2 = Decoder(tok, max_tokens=2)
    assert d2.step(ord("a")).finish_reason is None
    assert d2.step(ord("b")).finish_reason == "length"


def test_decoder_min_tokens_suppresses_eos():
    tok = ByteTokenizer()
    d = Decoder(tok, min_tokens=2, max_tokens=10)
    assert d.step(tok.eos_token_id).finish_reason is None  # too early
    assert d.step(tok.eos_token_id).finish_reason is None  # still == min
    assert d.step(tok.eos_token_id).finish_reason == "eos"


def test_preprocess_chat_and_budget():
    mdc = ModelDeploymentCard(name="m", tokenizer="byte", context_length=100)
    pre = OpenAIPreprocessor(mdc)
    req = ChatCompletionRequest(
        model="m",
        messages=[ChatMessage(role="user", content="hi")],
        max_tokens=5000,
        temperature=0.5,
        stop="END",
    )
    p = pre.preprocess_chat(req)
    assert p.token_ids, "prompt must tokenize"
    assert p.sampling.temperature == 0.5
    assert p.stop.stop == ["END"]
    assert p.stop.max_tokens == 100 - len(p.token_ids)  # clamped to context


async def _collect(gen):
    return [x async for x in gen]


def test_postprocess_chat_stream():
    mdc = ModelDeploymentCard(name="m", tokenizer="byte", context_length=1000)
    pre = OpenAIPreprocessor(mdc)
    req = ChatCompletionRequest(
        model="m", messages=[ChatMessage(role="user", content="hi")], max_tokens=50
    )
    p = pre.preprocess_chat(req)

    async def engine():
        tok = ByteTokenizer()
        yield LLMEngineOutput(token_ids=tok.encode("hel"))
        yield LLMEngineOutput(token_ids=tok.encode("lo"))
        yield LLMEngineOutput(token_ids=[tok.eos_token_id], finish_reason="eos")

    chunks = asyncio.run(
        _collect(pre.postprocess_chat_stream(p, engine(), include_usage=True))
    )
    text = "".join(c.choices[0].delta.content or "" for c in chunks)
    assert text == "hello"
    assert chunks[0].choices[0].delta.role == "assistant"
    assert chunks[-1].choices[0].finish_reason == "stop"
    assert chunks[-1].usage.completion_tokens == 6


def test_mdc_roundtrip_and_checksum():
    mdc = ModelDeploymentCard(
        name="llama", context_length=4096, kv_block_size=16,
        runtime_config=ModelRuntimeConfig(total_kv_blocks=1024),
    )
    again = ModelDeploymentCard.from_wire(mdc.to_wire())
    assert again == mdc
    assert again.checksum() == mdc.checksum()
    mdc2 = ModelDeploymentCard(name="llama", context_length=8192)
    assert mdc2.checksum() != mdc.checksum()


@pytest.mark.integration
async def test_model_discovery_flow():
    from dynamo_tpu.llm.discovery import ModelWatcher, register_llm
    from dynamo_tpu.runtime import DistributedRuntime

    async with StoreServer() as server:
        worker = await DistributedRuntime.create(server.address)
        front = await DistributedRuntime.create(server.address)
        try:
            added: list = []
            removed: list = []
            watcher = ModelWatcher(front.store)

            async def on_add(entry, mdc):
                added.append((entry.name, mdc.context_length))

            async def on_rm(name):
                removed.append(name)

            watcher.on_model_added.append(on_add)
            watcher.on_model_removed.append(on_rm)
            await watcher.start()

            ep = worker.namespace("ns").component("backend").endpoint("generate")

            async def handler(req, ctx):
                yield {}

            await ep.serve(handler)
            await register_llm(ep, ModelDeploymentCard(name="tiny", context_length=2048))

            for _ in range(100):
                if added:
                    break
                await asyncio.sleep(0.02)
            assert added == [("tiny", 2048)]

            await worker.shutdown()  # lease drops → model removed
            for _ in range(100):
                if removed:
                    break
                await asyncio.sleep(0.02)
            assert removed == ["tiny"]
        finally:
            await watcher.stop()
            await front.shutdown()
